"""ref: examples/hello_c.c"""
import ompi_tpu
comm = ompi_tpu.init()
print(f"Hello, world, I am {comm.rank} of {comm.size}", flush=True)
ompi_tpu.finalize()
