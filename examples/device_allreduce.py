"""Device-collective smoke program for the hybrid launch model.

Run (one app-shell process owning every rank as a chip-driving
thread — the deployment that makes coll/tpu reachable from mpirun):

    python -m ompi_tpu.tools.mpirun -np 8 --ranks-per-proc all \
        examples/device_allreduce.py

Each rank allreduces / reduce-scatters a device-resident array via
XLA mesh collectives, then rank 0 prints the coll/tpu offload pvar —
which must be > 0, proving the collectives ran as compiled HLO over
the mesh instead of the host-staged p2p fallback.
"""
import numpy as np

import ompi_tpu
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size

import jax
import jax.numpy as jnp

x = jax.device_put(jnp.full((size * 4,), float(rank + 1), jnp.float32),
                   comm.device)
r = comm.allreduce_arr(x, mpi_op.SUM)
rs = comm.reduce_scatter_arr(x, mpi_op.SUM)
expect = sum(range(1, size + 1))
assert float(np.asarray(r)[0]) == expect, (rank, np.asarray(r)[0])
assert float(np.asarray(rs)[0]) == expect

# sub-communicator: even/odd split still offloads on its sub-mesh
sub = comm.split(rank % 2)
sr = sub.allreduce_arr(x, mpi_op.MAX)
assert float(np.asarray(sr)[0]) == float(size - 2 + (rank % 2) + 1)

offloaded = 0
for pv in registry.all_pvars():
    if pv.full_name == "coll_tpu_offloaded_collectives":
        offloaded = pv.read()
# one atomic write per line: every rank is a thread of ONE app-shell
# process, and print()'s separate text/newline writes interleave
# across ranks on the shared stdout
import sys
if rank == 0:
    sys.stdout.write(f"coll_tpu_offloaded_collectives={offloaded}\n")
    sys.stdout.flush()
    assert offloaded > 0, "device collectives were not offloaded!"
sys.stdout.write(f"rank {rank} ok\n")
sys.stdout.flush()
ompi_tpu.finalize()
