"""OpenSHMEM atomics demo: every PE fetch-increments a counter on
PE 0 (ticket lock pattern) and adds into a symmetric accumulator.

Run: python -m ompi_tpu.tools.mpirun -np 4 examples/shmem_atomics.py
"""
import numpy as np

from ompi_tpu import shmem

shmem.init()
me, n = shmem.my_pe(), shmem.n_pes()
counter = shmem.malloc(1, np.int64)
acc = shmem.malloc(1, np.int64)
# self-puts, not .local stores: a device heap has no writable host
# alias, so local initialization goes through the data plane too
shmem.p(counter, 0, 0, me)
shmem.p(acc, 0, 0, me)
shmem.barrier_all()

ticket = shmem.atomic_fetch_inc(counter, 0, 0)  # unique 0..n-1
shmem.atomic_add(acc, 0, me + 1, 0)
shmem.barrier_all()

if me == 0:
    assert counter.local[0] == n, counter.local
    assert acc.local[0] == sum(range(1, n + 1)), acc.local
    print(f"shmem atomics ok: {n} tickets, acc={int(acc.local[0])}",
          flush=True)
# every PE got a distinct ticket
all_t = shmem.malloc(n, np.int64)
mine = shmem.malloc(1, np.int64)
shmem.p(mine, 0, ticket, me)
shmem.barrier_all()  # complete the self-put before the collective
shmem.collect(all_t, mine)
assert sorted(all_t.local.tolist()) == list(range(n))
shmem.finalize()
