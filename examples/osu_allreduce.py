"""OSU-style allreduce micro-benchmark over the launched job
(ref: the external OSU suite SURVEY.md §4 delegates to)."""
import sys
import time
import numpy as np
import ompi_tpu
from ompi_tpu.op import op

comm = ompi_tpu.init()
sizes = [4, 1024, 64 * 1024, 1024 * 1024]
if len(sys.argv) > 1:
    sizes = [int(s) for s in sys.argv[1].split(",")]
for nbytes in sizes:
    n = max(1, nbytes // 4)
    x = np.full(n, comm.rank + 1.0, dtype=np.float32)
    r = np.empty_like(x)
    comm.Allreduce(x, r, op.SUM)
    iters = 20 if nbytes <= 64 * 1024 else 5
    comm.Barrier()
    t0 = time.perf_counter()
    for _ in range(iters):
        comm.Allreduce(x, r, op.SUM)
    dt = (time.perf_counter() - t0) / iters
    assert abs(r[0] - sum(range(1, comm.size + 1))) < 1e-3
    if comm.rank == 0:
        print(f"{n * 4:>10} bytes  {dt * 1e6:10.1f} us", flush=True)
ompi_tpu.finalize()
