"""Ring attention over the device mesh — the long-context /
sequence-parallel flagship (SURVEY §5 long-context row; the
scaling-book recipe: shard the sequence, rotate KV blocks around the
ring with ppermute, accumulate attention online).

Each rank owns one sequence shard (Q_i, K_i, V_i).  The KV block
rotates size times via ``comm.ppermute_arr`` (the mesh-neighbor
primitive XLA lowers to an ICI CollectivePermute); partial attention
accumulates with the online-softmax (log-sum-exp) rule, so the result
is EXACT full attention over the whole sequence while no rank ever
materializes more than one remote block.

Run on the virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \\
        python examples/ring_attention.py
"""

from __future__ import annotations

import numpy as np


def ring_attention_step(q, k, v, acc, m, l):
    """One block: online-softmax accumulation of attention(q, k, v)
    into (acc, m, l) — numerator, running max, running denominator."""
    import jax.numpy as jnp

    s = q @ k.T / np.sqrt(q.shape[-1])          # (sq, skv)
    m_new = jnp.maximum(m, s.max(axis=-1))       # (sq,)
    p = jnp.exp(s - m_new[:, None])
    scale = jnp.exp(m - m_new)
    l_new = l * scale + p.sum(axis=-1)
    acc_new = acc * scale[:, None] + p @ v
    return acc_new, m_new, l_new


def ring_attention(comm, q, k, v):
    """Exact attention over the comm-wide sequence; each rank returns
    its own sequence shard of the output."""
    import jax.numpy as jnp

    size, rank = comm.size, comm.rank
    acc = jnp.zeros_like(q)
    m = jnp.full((q.shape[0],), -jnp.inf, q.dtype)
    l = jnp.zeros((q.shape[0],), q.dtype)
    # ring: block b seen at step t is the one owned by (rank + t)
    perm = [((r + 1) % size, r) for r in range(size)]  # src -> dst
    for _ in range(size):
        acc, m, l = ring_attention_step(q, k, v, acc, m, l)
        k = comm.ppermute_arr(k, perm)
        v = comm.ppermute_arr(v, perm)
    return acc / l[:, None]


def reference_attention(q_full, k_full, v_full):
    s = q_full @ k_full.T / np.sqrt(q_full.shape[-1])
    p = np.exp(s - s.max(axis=-1, keepdims=True))
    p /= p.sum(axis=-1, keepdims=True)
    return p @ v_full


def main() -> None:
    from ompi_tpu.testing import run_ranks

    nranks, sq, d = 4, 8, 16
    rng = np.random.default_rng(0)
    Q = rng.standard_normal((nranks * sq, d)).astype(np.float32)
    K = rng.standard_normal((nranks * sq, d)).astype(np.float32)
    V = rng.standard_normal((nranks * sq, d)).astype(np.float32)
    want = reference_attention(Q, K, V)

    def fn(comm):
        import jax.numpy as jnp

        r = comm.rank
        q = jnp.asarray(Q[r * sq:(r + 1) * sq])
        k = jnp.asarray(K[r * sq:(r + 1) * sq])
        v = jnp.asarray(V[r * sq:(r + 1) * sq])
        out = ring_attention(comm, q, k, v)
        np.testing.assert_allclose(
            np.asarray(out), want[r * sq:(r + 1) * sq],
            rtol=2e-4, atol=2e-5)
        return True

    assert all(run_ranks(nranks, fn, devices=True))
    print(f"ring attention OK: {nranks} ranks x {sq} tokens, "
          f"exact vs full attention")


if __name__ == "__main__":
    main()
