"""2-D Jacobi halo exchange on a cartesian process grid — the classic
topo + neighbor-collective workload (ref: the halo/CP pattern in
SURVEY.md §2.8; run under our mpirun):

    python -m ompi_tpu.tools.mpirun -np 4 examples/halo_stencil.py
"""

import numpy as np

import ompi_tpu
from ompi_tpu.topo import dims_create


def main() -> None:
    world = ompi_tpu.init()
    dims = dims_create(world.size, 2)
    cart = world.Create_cart(dims, periods=[True, True])
    if cart is None:
        ompi_tpu.finalize()
        return

    n = 8  # local tile edge
    tile = np.full((n, n), float(cart.rank), dtype=np.float64)

    # neighbor_alltoall: per dim, (source-dir block, dest-dir block)
    sbuf = np.stack([
        tile[0],        # north edge → row-source neighbor
        tile[-1],       # south edge → row-dest neighbor
        tile[:, 0],     # west edge
        tile[:, -1],    # east edge
    ]).ravel()
    rbuf = np.zeros_like(sbuf)
    cart.Neighbor_alltoall(sbuf, rbuf)
    halo = rbuf.reshape(4, n)

    interior = tile[1:-1, 1:-1]
    north, south, west, east = halo
    mean_halo = (north.sum() + south.sum() + west.sum() + east.sum()) / (4 * n)

    # device halo: when this rank owns a chip (hybrid launch), the
    # same shift runs device-to-device through the btl/tpu shim —
    # sendrecv_arr places the edge on the neighbor's chip directly
    if cart.state.device is not None:
        import jax.numpy as jnp
        left, right = cart.Shift(1, 1)
        dev_edge = jnp.asarray(tile[:, -1])
        dev_halo = cart.sendrecv_arr(dev_edge, right, left, tag=11)
        assert float(dev_halo[0]) == float(left), "device halo mismatch"

    print(f"rank {cart.rank} coords {cart.Get_coords()} "
          f"halo-mean {mean_halo:.2f} interior-mean {interior.mean():.2f}",
          flush=True)
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
