"""Every-pair p2p check (ref: examples/connectivity_c.c)."""
import numpy as np
import ompi_tpu

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
for peer in range(size):
    if peer == rank:
        continue
    me = np.array([rank], dtype=np.int32)
    other = np.zeros(1, dtype=np.int32)
    comm.Sendrecv(me, peer, 7, other, peer, 7)
    assert other[0] == peer, (rank, peer, other)
comm.Barrier()
if rank == 0:
    print(f"Connectivity test on {size} processes PASSED", flush=True)
ompi_tpu.finalize()
