"""Worker side of the Comm_spawn demo (see spawn_parent.py)."""
import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
parent = ompi_tpu.get_parent()
assert parent is not None, "worker must be spawned"

mine = np.array([100.0 + comm.rank], dtype=np.float64)
got = np.empty(1, dtype=np.float64)
parent.Allreduce(mine, got, mpi_op.SUM)
# we receive the parents' reduction
nparents = parent.remote_size
assert got[0] == sum(range(1, nparents + 1)), got

merged = parent.merge(high=True)
total = np.empty(1, dtype=np.float64)
merged.Allreduce(mine, total, mpi_op.SUM)
print(f"worker {comm.rank}: merged rank {merged.rank}/{merged.size}",
      flush=True)
ompi_tpu.finalize()
