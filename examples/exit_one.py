"""Failure-propagation probe: rank 1 exits abnormally WITHOUT calling
abort; every other rank blocks in a collective.  The launcher's
errmgr policy must kill the job (ref: orte/test/mpi/bad_exit.c)."""
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
if comm.rank == 1:
    sys.exit(7)
buf = np.zeros(1, dtype=np.int64)
comm.Allreduce(buf, buf.copy(), op=mpi_op.SUM)  # hangs: rank 1 never joins
print("should not reach here", flush=True)
