"""4-rank token ring (ref: examples/ring_c.c — the BASELINE PR1
program).  Run: python -m ompi_tpu.tools.mpirun -np 4 examples/ring.py
"""
import numpy as np
import ompi_tpu

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
token = np.array([0], dtype=np.int32)
if rank == 0:
    token[0] = 10
    print(f"Process 0 sending {token[0]} to 1, tag 201 ({size} processes)")
    comm.Send(token, dest=1, tag=201)
    comm.Recv(token, source=size - 1, tag=201)
    print(f"Process 0 received token {token[0]} from {size - 1}")
else:
    comm.Recv(token, source=rank - 1, tag=201)
    token -= 1
    comm.Send(token, dest=(rank + 1) % size, tag=201)
print(f"Process {rank} done", flush=True)
ompi_tpu.finalize()
