"""Abort propagation (ref: orte/test/mpi/abort.c)."""
import sys
import ompi_tpu

comm = ompi_tpu.init()
if comm.rank == 1:
    comm.abort(42)
# other ranks wait in a collective that can never complete
comm.Barrier()
print("should not reach here", flush=True)
