"""MPI_Comm_spawn demo: parents spawn 2 workers, allreduce across
the bridge (intercomm semantics: each side receives the OTHER side's
reduction), then everyone merges into one intracomm
(ref: orte/test/mpi/loop_spawn.c family)."""
import os

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "spawn_worker.py")
inter = comm.spawn(worker, maxprocs=2)
assert inter.remote_size == 2

mine = np.array([float(comm.rank + 1)], dtype=np.float64)
got = np.empty(1, dtype=np.float64)
inter.Allreduce(mine, got, mpi_op.SUM)
# workers contribute 100 + their world rank each
assert got[0] == sum(100.0 + r for r in range(2)), got

merged = inter.merge(high=False)
total = np.empty(1, dtype=np.float64)
merged.Allreduce(mine, total, mpi_op.SUM)
print(f"parent {comm.rank}: merged size {merged.size} "
      f"total {total[0]}", flush=True)
ompi_tpu.finalize()
