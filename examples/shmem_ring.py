"""OpenSHMEM-style ring: each PE puts a token into its right
neighbor's symmetric flag and waits on its own (the oshmem
ring_oshmem.c analog).

Run: python -m ompi_tpu.tools.mpirun -np 4 examples/shmem_ring.py
"""
import numpy as np

from ompi_tpu import shmem

shmem.init()
me, n = shmem.my_pe(), shmem.n_pes()
flag = shmem.malloc(1, np.int64)
# self-put, not a .local store: a device heap has no writable host
# alias, so local initialization goes through the data plane too
shmem.p(flag, 0, -1, me)
shmem.barrier_all()

if me == 0:
    shmem.p(flag, 0, 42, (me + 1) % n)  # inject the token
shmem.wait_until(flag, 0, "ge", 0)
token = int(flag.local[0])
if me != 0:
    shmem.p(flag, 0, token + 1, (me + 1) % n)
shmem.barrier_all()
if me == 0:
    assert token == 42 + n - 1, token  # full circle incremented n-1 times
    print(f"shmem ring complete: PE 0 ended with {token}", flush=True)
shmem.finalize()
