"""Hierarchical allreduce across simulated (or real) multi-chip nodes.

The multi-node device-collective pattern: split COMM_WORLD by node
(COMM_TYPE_SHARED), device-allreduce *within* each node over the XLA
mesh (coll/tpu), then combine the per-node partials *across* nodes on
the node leaders over the DCN/tcp plane, and broadcast back.  This is
the coll/ml hierarchical idea re-shaped for TPU pods: ICI inside the
node, host network between nodes.

Run:  python -m ompi_tpu.tools.mpirun -np 4 --simulate-nodes 2x2 \
          --ranks-per-proc all examples/hier_allreduce.py
"""
import numpy as np

import ompi_tpu
from ompi_tpu.comm.communicator import COMM_TYPE_SHARED
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size

import jax
import jax.numpy as jnp

node = comm.split_type(COMM_TYPE_SHARED)
leaders = comm.split(0 if node.rank == 0 else 1)

x = jax.device_put(jnp.full((size * 2,), float(rank + 1), jnp.float32),
                   comm.device)

# 1. intra-node device allreduce (XLA mesh collective over local chips)
partial = node.allreduce_arr(x, mpi_op.SUM)

# 2. inter-node allreduce of the partials on node leaders (host plane)
buf = np.asarray(partial)
if node.rank == 0:
    total = np.empty_like(buf)
    leaders.Allreduce(buf, total, op=mpi_op.SUM)
else:
    total = buf

# 3. intra-node bcast of the result
out = np.empty_like(total)
node.Bcast(total if node.rank == 0 else out, root=0)
result = total if node.rank == 0 else out

expect = sum(range(1, size + 1))
assert float(result[0]) == expect, (rank, result[0], expect)

offloaded = 0
for pv in registry.all_pvars():
    if pv.full_name == "coll_tpu_offloaded_collectives":
        offloaded = pv.read()
print(f"rank {rank}: hierarchical allreduce ok "
      f"(device-offloaded={offloaded})", flush=True)
assert offloaded > 0, "intra-node collective was not device-offloaded"
ompi_tpu.finalize()
