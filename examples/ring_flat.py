"""The reference's examples/ring_c.c, written against the flat MPI_*
surface (ompi_tpu.mpi) — token passed around a ring 10 times:

    python -m ompi_tpu.tools.mpirun -np 4 examples/ring_flat.py
"""

import numpy as np

from ompi_tpu import mpi as MPI


def main() -> None:
    MPI.MPI_Init()
    comm = MPI.MPI_COMM_WORLD()
    rank = MPI.MPI_Comm_rank(comm)
    size = MPI.MPI_Comm_size(comm)
    next_r, prev_r = (rank + 1) % size, (rank - 1) % size

    message = np.array([10], dtype=np.int32)
    if rank == 0:
        print(f"Process 0 sending {int(message[0])} to {next_r}, "
              f"tag 201 ({size} processes in ring)", flush=True)
        MPI.MPI_Send(message, 1, MPI.MPI_INT, next_r, 201, comm)

    while True:
        MPI.MPI_Recv(message, 1, MPI.MPI_INT, prev_r, 201, comm)
        if rank == 0:
            message[0] -= 1
            print(f"Process 0 decremented value: {int(message[0])}",
                  flush=True)
        if message[0] == 0 and rank != 0:
            MPI.MPI_Send(message, 1, MPI.MPI_INT, next_r, 201, comm)
            break
        MPI.MPI_Send(message, 1, MPI.MPI_INT, next_r, 201, comm)
        if message[0] == 0:
            break

    if rank == 0:
        MPI.MPI_Recv(message, 1, MPI.MPI_INT, prev_r, 201, comm)
    print(f"Process {rank} exiting", flush=True)
    MPI.MPI_Finalize()


if __name__ == "__main__":
    main()
