"""One-sided RMA smoke test under mpirun: fence put ring, exclusive-
lock atomic counter, fetch_and_op (ref: MPI-3 RMA examples)."""

import numpy as np

import ompi_tpu
from ompi_tpu import osc
from ompi_tpu.op import op as mpi_op


def main() -> None:
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size

    # fence epoch: put rank id to right neighbor
    mem = np.full(2, -1, dtype=np.int64)
    win = osc.create(comm, mem)
    win.fence()
    win.put(np.full(2, rank, dtype=np.int64), (rank + 1) % size)
    win.fence()
    assert (mem == (rank - 1 + size) % size).all(), "put ring mismatch"

    # passive target: atomic counter on rank 0
    ctr = np.zeros(1, dtype=np.int64)
    cwin = osc.create(comm, ctr)
    for _ in range(5):
        old = np.empty(1, dtype=np.int64)
        cwin.fetch_and_op(1, old, 0, op=mpi_op.SUM)
    comm.Barrier()
    if rank == 0:
        assert ctr[0] == 5 * size, f"counter {ctr[0]} != {5 * size}"
        print(f"rma_counter OK on {size} ranks")
    cwin.free()
    win.free()
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
