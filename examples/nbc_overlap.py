"""Nonblocking-collective smoke test: overlap Iallreduce/Ibcast/
Ibarrier with p2p traffic, verify results (run under mpirun)."""

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op
from ompi_tpu.pml.request import wait_all


def main() -> None:
    comm = ompi_tpu.init()
    rank, size = comm.rank, comm.size

    x = np.arange(1000, dtype=np.float64) + rank
    r = np.empty_like(x)
    req1 = comm.Iallreduce(x, r, mpi_op.SUM)

    b = np.full(8, 4242, dtype=np.int64) if rank == 0 \
        else np.zeros(8, dtype=np.int64)
    req2 = comm.Ibcast(b, root=0)

    # p2p ring token while the collectives are in flight
    peer = (rank + 1) % size
    src = (rank - 1 + size) % size
    sb = np.array([rank * 11], dtype=np.int64)
    rb = np.empty(1, dtype=np.int64)
    comm.Sendrecv(sb, peer, 7, rb, src, 7)

    req3 = comm.Ibarrier()
    wait_all([req1, req2, req3])

    exp = sum(np.arange(1000, dtype=np.float64) + k for k in range(size))
    assert np.allclose(r, exp), "Iallreduce mismatch"
    assert (b == 4242).all(), "Ibcast mismatch"
    assert rb[0] == src * 11, "Sendrecv mismatch"

    g = np.empty(size, dtype=np.int64) if rank == 0 else None
    comm.Igather(np.array([rank], dtype=np.int64), g, root=0).wait()
    if rank == 0:
        assert list(g) == list(range(size)), "Igather mismatch"
        print(f"nbc_overlap OK on {size} ranks")
    ompi_tpu.finalize()


if __name__ == "__main__":
    main()
