"""Self-healing respawn tests (ft/respawn + cr/buddy): a killed rank
is replaced IN-JOB under its original world rank, restores from a
partner's in-memory buddy checkpoint, and the job finishes at full
size byte-identical to a fault-free run."""

import os

import numpy as np
import pytest

from ompi_tpu import errhandler as eh
from ompi_tpu import ft_inject
from ompi_tpu.cr import buddy
from ompi_tpu.errhandler import MPIException
from ompi_tpu.ft import respawn, ulfm
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import mpirun_run, run_ranks

FT_CODES = (eh.ERR_PROC_FAILED, eh.ERR_PROC_FAILED_PENDING,
            eh.ERR_REVOKED)


@pytest.fixture
def buddy_degree_1():
    registry.set("cr_buddy_degree", "1")
    yield
    registry.set("cr_buddy_degree", "0")


def _step(i, acc, comm):
    x = np.full(4, (comm.rank + 1.0) * (i + 1))
    r = np.empty_like(x)
    comm.Allreduce(x, r, mpi_op.SUM)
    return acc + r


def _make_fn(iters=8, kill_at=None):
    """App loop with per-iteration buddy checkpoints.  ``kill_at``
    maps rank -> iteration at which the ORIGINAL incarnation dies
    (replacements never re-kill; distinct iterations keep failures
    sequential, the respawn contract)."""
    kill_at = kill_at or {}

    def fn(comm):
        state = comm.state
        was_joining = respawn.joining(state)
        if was_joining:
            comm = respawn.rejoin(comm)
            st = buddy.restore(comm)
            i, acc = int(st["i"]), np.asarray(st["acc"])
        else:
            i, acc = 0, np.zeros(4)
        did_kill = False
        while i < iters:
            try:
                buddy.checkpoint(comm, {"i": i, "acc": acc})
                if (not was_joining and not did_kill
                        and kill_at.get(comm.rank) == i):
                    did_kill = True
                    ulfm.kill_now(state)
                acc = _step(i, acc, comm)
                i += 1
            except MPIException as e:
                if e.code not in FT_CODES:
                    raise
                comm = respawn.rejoin(comm)
                st = buddy.restore(comm)
                i, acc = int(st["i"]), np.asarray(st["acc"])
        return acc.tobytes()
    return fn


# ---- the tentpole: kill -> respawn -> buddy restore -----------------

def test_respawn_byte_identical_full_size(buddy_degree_1):
    """4 ranks, rank 1 killed mid-run: under respawn the job completes
    at FULL world size with results byte-identical to a fault-free
    run — the replacement's state came from a partner's memory (there
    is no filesystem store in this test at all)."""
    clean = run_ranks(4, _make_fn(), timeout=60)
    faulty = run_ranks(4, _make_fn(kill_at={1: 5}), timeout=120,
                       respawn=True)
    assert faulty == clean
    assert all(r is not None for r in faulty)  # nobody missing


def test_respawn_chaos_victim_list(buddy_degree_1):
    """Repeated kills across a run, victims drawn from the
    ft_inject_victim_rank comma list (the satellite): each original
    incarnation dies at a distinct iteration, each death is recovered
    by a separate rejoin epoch, and the result still matches the
    fault-free run bit-for-bit."""
    registry.set("ft_inject_victim_rank", "1,3")
    try:
        victims = ft_inject.victim_ranks(4)
        assert victims == [1, 3]
        kill_at = {v: 2 + 3 * k for k, v in enumerate(victims)}
        clean = run_ranks(4, _make_fn(iters=10), timeout=60)
        faulty = run_ranks(4, _make_fn(iters=10, kill_at=kill_at),
                           timeout=180, respawn=True)
        assert faulty == clean
    finally:
        registry.set("ft_inject_victim_rank", "1")


def test_respawn_pvars_count_rejoins(buddy_degree_1):
    before = respawn._pv_rejoins.read()
    run_ranks(4, _make_fn(kill_at={2: 3}), timeout=120, respawn=True)
    # 3 survivors + 1 replacement each completed one rejoin
    assert respawn._pv_rejoins.read() - before >= 4
    assert respawn._pv_rejoin_us.read() > 0


# ---- cr/buddy on its own --------------------------------------------

def test_buddy_roundtrip_without_failure(buddy_degree_1):
    def fn(comm):
        s1 = buddy.checkpoint(comm, {"v": comm.rank * 1.0})
        s2 = buddy.checkpoint(comm, {"v": comm.rank + 100.0})
        assert (s1, s2) == (0, 1)
        st = buddy.restore(comm)
        return st["v"]

    assert run_ranks(3, fn) == [100.0, 101.0, 102.0]


def test_buddy_partner_placement(buddy_degree_1):
    """Copy k of rank r lives on (r+k) %% size — verify the held map
    directly."""
    def fn(comm):
        buddy.checkpoint(comm, {"r": comm.rank}, degree=2)
        held = sorted(k[0] for k in
                      comm.state.extra["cr_buddy"]["held"])
        want = sorted({(comm.rank - 1) % comm.size,
                       (comm.rank - 2) % comm.size})
        assert held == want, (held, want)
        return buddy.committed_seq(comm.state)

    assert run_ranks(4, fn) == [0, 0, 0, 0]


def test_buddy_degree_zero_is_noop():
    """cr_buddy_degree=0 (the default): checkpoint is a single int
    check — no quiesce, no pickle, no replica state, no traffic."""
    def fn(comm):
        assert buddy.checkpoint(comm, {"big": np.zeros(1 << 16)}) == -1
        assert "cr_buddy" not in comm.state.extra
        return True

    assert run_ranks(2, fn) == [True, True]


def test_buddy_restore_none_before_any_commit(buddy_degree_1):
    def fn(comm):
        return buddy.restore(comm)

    assert run_ranks(2, fn) == [None, None]


# ---- satellites ------------------------------------------------------

def test_victim_ranks_parsing():
    registry.set("ft_inject_victim_rank", "0, 2,3")
    try:
        assert ft_inject.victim_ranks() == [0, 2, 3]
        registry.set("ft_inject_plan", "rank_kill")
        assert ft_inject.rank_faults(2) == ["rank_kill"]
        assert ft_inject.rank_faults(1) == []
        assert ft_inject.rank_kill_victim() == 0  # compat: first victim
    finally:
        registry.set("ft_inject_plan", "")
        registry.set("ft_inject_victim_rank", "1")


def test_victim_ranks_random_is_seed_deterministic():
    registry.set("ft_inject_victim_rank", "random")
    try:
        a = ft_inject.victim_ranks(8)
        assert a == ft_inject.victim_ranks(8)  # same seed, same pick
        assert 0 <= a[0] < 8
        registry.set("ft_inject_seed", "12345")
        b = ft_inject.victim_ranks(8)
        assert b == ft_inject.victim_ranks(8)
    finally:
        registry.set("ft_inject_seed", "0")
        registry.set("ft_inject_victim_rank", "1")


def test_cr_keep_mca_default(tmp_path):
    """cr_keep (the --ckpt-keep satellite) sets the job-wide default
    for checkpoint(..., keep=): the store is pruned to the newest N
    complete snapshots without any per-call argument."""
    from ompi_tpu import cr
    root = str(tmp_path / "store")
    registry.set("cr_keep", "1")
    try:
        def fn(comm):
            for i in range(3):
                cr.checkpoint(comm, {"i": i}, store_dir=root)
            return True

        assert run_ranks(2, fn) == [True, True]
        done = [d for d in os.listdir(root)
                if os.path.isfile(os.path.join(root, d, "meta.json"))]
        assert len(done) == 1, sorted(os.listdir(root))
    finally:
        registry.set("cr_keep", "0")


def test_kv_purge_op():
    """The kvstore 'purge' op (ticket/note hygiene): prefix-delete of
    data keys AND counters, including put-once claim counters."""
    from ompi_tpu.runtime.kvstore import KVClient, KVServer
    os.environ.setdefault("TPUMPI_JOB_SECRET", "purge-test-secret")
    srv = KVServer(1)
    try:
        cli = KVClient(srv.addr)
        cli.put("ulfm:note:0", ["fail", 1])
        cli.put("ulfm:agree:5:d", True)
        cli.put("keepme", 7)
        cli.incr("ulfm:nseq")
        assert cli.put_once("ulfm:agree:5:c", 1)  # claim counter too
        n = cli.purge("ulfm:")
        assert n >= 2
        assert cli.get("keepme") == 7
        assert srv.data.get("ulfm:note:0") is None
        assert all(not k.startswith(("ulfm:", "claim:ulfm:"))
                   for k in srv.counters)
        # the claim counter is gone: put_once works again
        assert cli.put_once("ulfm:agree:5:c", 2)
        cli.close()
    finally:
        srv.close()


def test_purge_tickets_keeps_notes():
    """Epoch rollover purges consumed agreement tickets but KEEPS
    failure notes (a late watcher relies on the epoch filter, not on
    deletion); finalize's purge_store drops everything."""
    import threading
    from types import SimpleNamespace

    world = SimpleNamespace(shared={}, shared_lock=threading.Lock())
    state = SimpleNamespace(rte=SimpleNamespace(world=world, kv=None))
    world.shared[("agree", 7, "d")] = True
    world.shared[("shrink", 3, "c", 0)] = [1]
    world.shared[("respawn", 1, "d")] = {"failed": [1]}
    world.shared[("ulfm", "cid")] = 4097
    world.shared[("other", "app")] = "untouched"
    ulfm.purge_tickets(state)
    assert ("agree", 7, "d") not in world.shared
    assert ("shrink", 3, "c", 0) not in world.shared
    assert ("respawn", 1, "d") in world.shared  # live until finalize
    ulfm.purge_store(state)
    assert ("respawn", 1, "d") not in world.shared
    assert ("ulfm", "cid") not in world.shared
    assert world.shared == {("other", "app"): "untouched"}


def test_epoch_cid_banding():
    """After a recovery epoch, new cids come from the epoch's band
    (epoch * EPOCH_CID_STRIDE) so a replacement can never collide with
    a pre-failure cid it never saw."""
    from ompi_tpu.comm.communicator import EPOCH_CID_STRIDE

    def fn(comm):
        comm.state.respawn_epoch = 2
        sub = comm.dup()
        assert sub.cid >= 2 * EPOCH_CID_STRIDE
        return sub.cid

    cids = run_ranks(2, fn)
    assert cids[0] == cids[1]


def test_ulfm_unfail_allows_re_kill_detection():
    """unfail() clears the delivery dedup: a rank that was replaced
    and later dies AGAIN is detected a second time."""
    def fn(comm):
        u = comm.state.ulfm
        u.deliver(("fail", 1))
        assert u.poll() == 1
        assert 1 in u.failed
        u.unfail(1)
        assert 1 not in u.failed
        u.deliver(("fail", 1))
        assert u.poll() == 1  # seen-set was cleared: detected again
        return True

    assert run_ranks(1, fn) == [True]


def test_ingest_filters_recovered_epochs():
    """Epoch-tagged failure notes at or below the rank's recovery
    epoch are stale replays and must not re-kill a revived rank."""
    def fn(comm):
        u = comm.state.ulfm
        comm.state.respawn_epoch = 2
        u.deliver(("fail", 1, 1))   # epoch 1 <= 2: recovered, dropped
        u.deliver(("fail", 1, 2))   # epoch 2 <= 2: recovered, dropped
        assert u.poll() == 0
        assert 1 not in u.failed
        u.deliver(("fail", 1, 3))   # epoch 3 > 2: a NEW death
        assert u.poll() == 1
        assert 1 in u.failed
        u.deliver(("fail", comm.rank, 3))  # own rank: alive, dropped
        assert u.poll() == 0
        return True

    assert run_ranks(1, fn) == [True]


# ---- end-to-end over real processes ---------------------------------

@pytest.mark.slow
def test_mpirun_respawn_policy_process_ranks():
    """ft_inject kills rank 1; the 'respawn' errmgr policy relaunches
    it under the same world rank, the replacement restores from a
    buddy copy and the job EXITS 0 at FULL size with every rank
    reporting the same digest."""
    r = mpirun_run(
        4, "tests/_respawn_prog.py",
        mca=(("errmgr_base_policy", "respawn"),
             ("ft_inject_plan", "rank_kill"),
             ("ft_inject_victim_rank", "1"),
             ("ft_inject_after", "0.8"),
             ("cr_buddy_degree", "1")),
        timeout=240, job_timeout=180)
    out = r.stdout.decode()
    err = r.stderr.decode()
    assert r.returncode == 0, (r.returncode, out[-800:], err[-2000:])
    lines = [ln for ln in out.splitlines() if ln.startswith("rank=")]
    assert len(lines) == 4, out[-800:]          # FULL size at the end
    assert all("size=4" in ln for ln in lines), lines
    digests = {ln.split("digest=")[1].strip() for ln in lines}
    assert len(digests) == 1, lines             # byte-identical state
    assert sum("joined=1" in ln for ln in lines) == 1, lines
    assert "respawn policy" in err
