"""Connect/accept between two halves of one job through a named port
(run under mpirun by test_intercomm.py)."""
import numpy as np

import ompi_tpu
from ompi_tpu.comm import dpm
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
half = comm.size // 2
low = comm.rank < half
local = comm.split(0 if low else 1)
if low:
    inter = dpm.comm_accept(local, "ca-test-port")
else:
    inter = dpm.comm_connect(local, "ca-test-port")
s = np.array([1.0 if low else 2.0])
r = np.empty(1)
inter.Allreduce(s, r, mpi_op.SUM)
expect = 2.0 * (comm.size - half) if low else 1.0 * half
assert r[0] == expect, (comm.rank, r[0], expect)

# second rendezvous on the SAME port (r3 advisor regression: the
# first round's connect record must have been consumed, and the two
# bridge cids must differ — no stale-record pairing, no hash cids)
if low:
    inter2 = dpm.comm_accept(local, "ca-test-port")
else:
    inter2 = dpm.comm_connect(local, "ca-test-port")
assert inter2.cid != inter.cid
r2 = np.empty(1)
inter2.Allreduce(s, r2, mpi_op.SUM)
assert r2[0] == expect, (comm.rank, r2[0], expect)
print("ok", flush=True)
ompi_tpu.finalize()
