"""Regression tests for the transient-fault hardening satellites:
wire CRCs, Progress interrupt suppression/deferral + stale-fd repair,
KV client retry semantics, shmem lock-ticket retirement on timeout,
rendezvous-engine epoch reset, vprotocol ack-watermark refresh, and
HNP heartbeat liveness-by-silence."""

import os
import socket
import sys
import threading
import time
import types

import numpy as np
import pytest

from ompi_tpu.mca.params import registry


# ---- wire frame CRCs ------------------------------------------------

def test_wire_crc_header_corruption_detected():
    from ompi_tpu.btl import wire
    frame = bytearray(b"\x00" + bytes(range(120)))  # unknown code:
    crc = wire.frame_crc(frame)                     # span = 64
    wire.check_crc(frame, crc)  # pristine passes
    bad = bytearray(frame)
    bad[10] ^= 0xFF
    with pytest.raises(wire.CorruptFrame):
        wire.check_crc(bad, crc)


def test_wire_crc_covers_header_span_only():
    """The CRC protects the parsed header region (hdr_span); payload
    integrity past it is the datatype engine's concern.  A flip past
    the span must NOT trip the header check."""
    from ompi_tpu.btl import wire
    frame = bytearray(b"\x00" + bytes(range(120)))
    assert wire.hdr_span(frame) == 64
    crc = wire.frame_crc(frame)
    tail = bytearray(frame)
    tail[100] ^= 0xFF
    wire.check_crc(tail, crc)


def test_wire_hdr_span_short_frame():
    from ompi_tpu.btl import wire
    short = bytearray(b"\x00\x01\x02")
    assert wire.hdr_span(short) == 3
    wire.check_crc(short, wire.frame_crc(short))


# ---- Progress: interrupt suppression / deferral / stale fds ---------

def test_progress_suppressed_interrupt_discarded():
    from ompi_tpu.runtime.progress import Progress
    p = Progress()
    p.interrupt = RuntimeError("late ft interrupt")
    p.suppress_interrupts = True
    p.progress()  # must not raise
    assert p.interrupt is None


def test_progress_deferred_interrupt_held_then_raised():
    from ompi_tpu.runtime.progress import Progress
    p = Progress()
    p.interrupt = RuntimeError("recovery wanted")
    with p.deferred_interrupts():
        p.progress()  # held: checkpoint write in flight
        assert p.interrupt is not None
    with pytest.raises(RuntimeError, match="recovery wanted"):
        p.progress()
    assert p.interrupt is None


def test_progress_idle_fd_reregister_after_reuse():
    """A transport socket closed without unregistering (injected
    sever, test surgery) leaves a stale selector entry; a new socket
    reusing the fd number must still register cleanly."""
    from ompi_tpu.runtime.progress import Progress
    p = Progress()
    s1 = socket.socket()
    fd1 = s1.fileno()
    p.register_idle_fd(fd1, drain=lambda: None)
    s1.close()  # selector entry for fd1 is now stale
    s2 = socket.socket()
    try:
        if s2.fileno() != fd1:  # Linux reuses the lowest free fd
            pytest.skip("OS did not reuse the fd number")
        p.register_idle_fd(s2.fileno())  # must repair, not raise
        assert fd1 not in p._idle_drains  # stale drain hook dropped
    finally:
        s2.close()


# ---- KV client retry/backoff ----------------------------------------

class _FlakyKV:
    """Minimal KV server that kills the first ``fail_replies``
    connections right after reading a request (send consumed, reply
    lost) — the exact shape of a mid-op partition."""

    def __init__(self, fail_replies: int) -> None:
        from ompi_tpu.runtime.kvstore import _recv_msg, _send_msg
        self._recv, self._send = _recv_msg, _send_msg
        self.fail_replies = fail_replies
        self.requests: list = []
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.addr = "127.0.0.1:%d" % self.sock.getsockname()[1]
        self._stop = False
        threading.Thread(target=self._serve, daemon=True).start()

    def _serve(self) -> None:
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            try:
                while True:
                    msg = self._recv(conn)
                    if msg is None:
                        break
                    self.requests.append(msg)
                    if self.fail_replies > 0:
                        self.fail_replies -= 1
                        conn.close()  # reply lost
                        break
                    self._send(conn, {"ok": True, "value": msg})
            except OSError:
                pass

    def close(self) -> None:
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


@pytest.fixture
def fast_kv_retry():
    import ompi_tpu.runtime.kvstore  # noqa: F401  (registers the var)
    old = registry.get("rte_base_kv_retry_delay", 0.05)
    registry.set("rte_base_kv_retry_delay", "0.01")
    yield
    registry.set("rte_base_kv_retry_delay", str(old))


def test_kv_idempotent_op_retried_through_lost_reply(fast_kv_retry):
    from ompi_tpu.runtime.kvstore import KVClient
    srv = _FlakyKV(fail_replies=1)
    try:
        cli = KVClient(srv.addr)
        resp = cli._request({"op": "probe"}, idempotent=True)
        assert resp["ok"]
        # the op was SENT twice (first reply lost, retried)
        assert len(srv.requests) == 2
        cli.close()
    finally:
        srv.close()


def test_kv_nonidempotent_lost_reply_raises(fast_kv_retry):
    """A lost reply to a non-idempotent op (incr, fence, spawn) must
    surface, never silently resend: the server may already have
    applied it."""
    from ompi_tpu.runtime.kvstore import KVClient
    srv = _FlakyKV(fail_replies=1)
    try:
        cli = KVClient(srv.addr)
        with pytest.raises(ConnectionError):
            cli._request({"op": "probe"}, idempotent=False)
        assert len(srv.requests) == 1  # exactly once on the wire
        cli.close()
    finally:
        srv.close()


def test_kv_send_failure_always_retried(fast_kv_retry):
    """A severed socket discovered at SEND time is retryable for any
    op: the server never saw (a complete frame of) the request."""
    from ompi_tpu.runtime.kvstore import KVClient
    srv = _FlakyKV(fail_replies=0)
    try:
        cli = KVClient(srv.addr)
        cli._sock.close()  # partition before the op
        resp = cli._request({"op": "probe"}, idempotent=False)
        assert resp["ok"]
        cli.close()
    finally:
        srv.close()


# ---- shmem: set_lock timeout retires its ticket ---------------------

def test_set_lock_timeout_does_not_wedge_lock():
    from ompi_tpu import shmem
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        ctx = shmem.init(comm)
        try:
            lock = ctx.malloc(1, np.int64)
            ctx.barrier_all()
            if comm.rank == 0:
                ctx.set_lock(lock)
            comm.Barrier()
            if comm.rank == 1:
                # held by rank 0: time out, and the abandoned ticket
                # must be retired — else the lock wedges forever
                with pytest.raises(TimeoutError):
                    ctx.set_lock(lock, timeout=0.4)
            comm.Barrier()
            if comm.rank == 0:
                ctx.clear_lock(lock)
            comm.Barrier()
            # both ranks take and release it again, in rank order:
            # proves no ghost ticket is holding the queue
            for turn in range(comm.size):
                if comm.rank == turn:
                    ctx.set_lock(lock, timeout=10.0)
                    ctx.clear_lock(lock)
                comm.Barrier()
            return True
        finally:
            shmem.finalize()

    assert all(run_ranks(2, fn))


# ---- btl/tpu rendezvous engine epoch reset --------------------------

def test_rndv_engine_ft_reset_clears_tables():
    from ompi_tpu.btl.tpu import TpuRndvEngine
    state = types.SimpleNamespace(progress=types.SimpleNamespace(
        register=lambda *a, **k: None))
    eng = TpuRndvEngine(state)
    flat = np.arange(32, dtype=np.float32)
    x1 = eng.begin_send(flat)
    x2 = eng.begin_send(flat)
    eng._gc_tombstones.add(99)
    eng.staged_bytes = 4096
    eng._inflight.append(("req", 4096))
    eng.ft_reset()
    assert eng.pending == {} and eng._gc_tombstones == set()
    assert eng._inflight == [] and eng.staged_bytes == 0
    # the id space must stay MONOTONE across the epoch: a recycled
    # xid would let a stale pull address a new transfer
    x3 = eng.begin_send(flat)
    assert x3 > max(x1, x2, 99)


# ---- vprotocol: periodic ack-watermark refresh ----------------------

def test_vprotocol_ack_refresh_resends_watermark(tmp_path):
    """Every Nth ack tick bypasses the already-acked skip, so a
    watermark whose ack frame died on the wire is re-sent (acks are
    idempotent)."""
    from ompi_tpu import cr
    from ompi_tpu.pml.vprotocol import find
    from ompi_tpu.testing import run_ranks

    store = str(tmp_path / "store")
    registry.set("pml_vprotocol", "pessimist")
    registry.set("vprotocol_pessimist_ack_interval_s", "0.01")
    registry.set("vprotocol_pessimist_ack_refresh_ticks", "2")
    try:
        def fn(comm):
            v = find(comm.state.pml)
            assert v is not None
            if comm.rank == 0:
                comm.Send(np.arange(4, dtype=np.float64), 1, tag=3)
                comm.Barrier()
                comm.Barrier()
                return True
            got = np.empty(4)
            comm.Recv(got, 0, tag=3)
            # a local snapshot makes the consumed watermark durable —
            # only durable watermarks are ever acked
            cr.checkpoint_local(comm, {"ok": 1}, store_dir=store)
            cid = comm.cid
            key = next(k for k in v._durable if k[0] == cid)
            v._acked[key] = v._durable[key]  # pretend ack delivered
            comm.Barrier()
            sent = []
            orig = v._base._ep

            def spying_ep(gsrc):
                sent.append(gsrc)
                return orig(gsrc)

            v._base._ep = spying_ep
            deadline = time.monotonic() + 5.0
            while not sent and time.monotonic() < deadline:
                comm.state.progress.progress()
                time.sleep(0.005)
            v._base._ep = orig
            assert sent, "refresh tick never re-sent the watermark"
            comm.Barrier()
            return True

        assert all(run_ranks(2, fn))
    finally:
        registry.set("pml_vprotocol", "")
        registry.set("vprotocol_pessimist_ack_interval_s", "0.25")
        registry.set("vprotocol_pessimist_ack_refresh_ticks", "8")


# ---- HNP: liveness by silence (heartbeat budget) --------------------

class _Events:
    def __init__(self) -> None:
        self.seen: list = []
        self.got_lost = threading.Event()

    def activate(self, name, **info):
        self.seen.append((name, info))
        if name == "EV_DAEMON_LOST":
            self.got_lost.set()


def test_heartbeat_silence_declares_daemon_lost():
    """The acceptance gate: a daemon that stops beating is declared
    lost WITHOUT waiting for TCP death — its socket stays open the
    whole time."""
    from ompi_tpu.runtime import oob
    from ompi_tpu.runtime.kvstore import _send_msg
    from ompi_tpu.tools.plm import HNP

    old_iv = oob.heartbeat_interval_var.value
    old_budget = oob.heartbeat_budget_var.value
    old_secret = os.environ.pop("TPUMPI_JOB_SECRET", None)
    registry.set("oob_base_heartbeat_interval", "0.1")
    registry.set("oob_base_heartbeat_budget", "3")
    ev = _Events()
    hnp = None
    s = None
    try:
        hnp = HNP(maps=[], agent="ssh", python=sys.executable,
                  pythonpath="", events=ev)
        s = socket.create_connection(("127.0.0.1", hnp.port))
        _send_msg(s, {"op": "register", "node": 5, "name": "wedged",
                      "if_ip": "127.0.0.1", "secret": ""})
        # send nothing more; the socket stays OPEN (a wedged daemon,
        # not a dead one) — only the beat monitor can notice
        assert ev.got_lost.wait(5.0), ev.seen
        assert ("EV_DAEMON_LOST", {"node": 5}) in ev.seen
        assert 5 in hnp._beat_dead
    finally:
        if hnp is not None:
            hnp._stop = True
            hnp.listener.close()
        if s is not None:
            s.close()
        registry.set("oob_base_heartbeat_interval", str(old_iv))
        registry.set("oob_base_heartbeat_budget", str(old_budget))
        if old_secret is not None:
            os.environ["TPUMPI_JOB_SECRET"] = old_secret


def test_reconnect_grace_holds_daemon_lost():
    """A channel drop with reconnect_grace > 0 arms a timer instead
    of firing EV_DAEMON_LOST; a re-register inside the grace cancels
    it and the job never notices."""
    from ompi_tpu.runtime import oob
    from ompi_tpu.runtime.kvstore import _send_msg
    from ompi_tpu.tools.plm import HNP

    old_grace = oob.reconnect_grace_var.value
    old_secret = os.environ.pop("TPUMPI_JOB_SECRET", None)
    registry.set("oob_base_reconnect_grace", "1.5")
    ev = _Events()
    hnp = None
    try:
        hnp = HNP(maps=[], agent="ssh", python=sys.executable,
                  pythonpath="", events=ev)
        s1 = socket.create_connection(("127.0.0.1", hnp.port))
        _send_msg(s1, {"op": "register", "node": 3, "name": "n3",
                       "if_ip": "127.0.0.1", "secret": ""})
        deadline = time.monotonic() + 5.0
        while 3 not in hnp.channels and time.monotonic() < deadline:
            time.sleep(0.01)
        assert 3 in hnp.channels
        s1.close()  # transient drop
        deadline = time.monotonic() + 5.0
        while not hnp._grace_timers and time.monotonic() < deadline:
            time.sleep(0.01)
        assert 3 in hnp._grace_timers, "grace timer not armed"
        # reconnect within the grace
        s2 = socket.create_connection(("127.0.0.1", hnp.port))
        _send_msg(s2, {"op": "register", "node": 3, "name": "n3",
                       "if_ip": "127.0.0.1", "secret": "",
                       "reconnect": True})
        deadline = time.monotonic() + 5.0
        while hnp._grace_timers and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(1.8)  # past the original grace deadline
        assert not ev.got_lost.is_set(), ev.seen
        # reconnect=True must not double-announce the daemon
        assert ev.seen.count(("EV_DAEMON_UP", {"node": 3})) == 1
        s2.close()
    finally:
        if hnp is not None:
            hnp._stop = True
            hnp.listener.close()
        registry.set("oob_base_reconnect_grace", str(old_grace))
        if old_secret is not None:
            os.environ["TPUMPI_JOB_SECRET"] = old_secret


# ---- C/R bookmark vs transport duplicates ---------------------------

def test_cr_arrived_ignores_transport_duplicate_envelopes():
    """A reconnect-resent duplicate envelope is dropped by the pml
    sequence gate and must not inflate cr_arrived: quiesce balances
    sender sent against receiver arrived, so one phantom arrival
    stalls every later checkpoint (seen live under ft_inject sever —
    the old conn's buffered copy and the replayed copy both reached
    the pml)."""
    from ompi_tpu.pml.ob1 import MATCH
    from ompi_tpu.testing import run_ranks

    def fn(comm):
        sub = comm.dup()  # private cid: never pollute WORLD's seq space
        pml = sub.state.pml
        cid = sub.cid
        base = pml._next_seq.get((cid, 0), 0)
        before = pml.cr_arrived.get(0, 0)
        first = (MATCH, cid, 0, 5, base, 0, b"first")
        pml._handle(first)
        pml._handle(first)  # transport duplicate: dropped, uncounted
        assert pml.cr_arrived.get(0, 0) == before + 1
        # out-of-order copy parked, duplicated while parked, then the
        # gap fills: exactly three real messages counted overall
        ahead = (MATCH, cid, 0, 5, base + 2, 0, b"third")
        pml._handle(ahead)
        pml._handle(ahead)  # duplicate of a parked envelope
        pml._handle((MATCH, cid, 0, 5, base + 1, 0, b"second"))
        assert pml.cr_arrived.get(0, 0) == before + 3
        # exactly-once delivery: three distinct messages, no copies
        # (buffer order is dispatch order, not seq order — the parked
        # envelope drains from _advance_seq before the gap-filler)
        assert sorted(m.payload for m in pml._unexpected.get(cid, [])) \
            == [b"first", b"second", b"third"]
        pml._unexpected.get(cid, []).clear()  # consumed: keep finalize quiet

    run_ranks(1, fn)
