"""Checkpointing DVM session workload (run by test_fleet.py and the
fleet probe): a deterministic stepped allreduce accumulation that
checkpoints EVERY step to the filesystem tier and restores at start —
so a preempted run resumes where it stopped and its final digest is
byte-identical to an unpreempted run.

argv: tag store_dir steps [sleep_s]

Rank 0 prints ``DIGEST {tag} {sha256}`` and ``STEPS {tag} {resumed_at}``
so tests can assert both the value and that a resume actually happened.
"""
import hashlib
import sys
import time

import numpy as np

import ompi_tpu
from ompi_tpu.cr import ckpt
from ompi_tpu.op import op as mpi_op

tag = sys.argv[1]
store = sys.argv[2]
steps = int(sys.argv[3])
sleep_s = float(sys.argv[4]) if len(sys.argv) > 4 else 0.0

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size

snap = ckpt.restore(comm, store_dir=store)
if snap is None:
    start = 0
    vec = np.zeros(32, np.float64)
else:
    start = int(snap["step"])
    vec = np.asarray(snap["vec"], np.float64)

for step in range(start, steps):
    contrib = np.full(32, float((step + 1) * (rank + 1)), np.float64)
    r = np.empty_like(contrib)
    comm.Allreduce(contrib, r, mpi_op.SUM)
    vec = vec + r
    ckpt.checkpoint(comm, {"step": step + 1, "vec": vec},
                    store_dir=store, fs=True)
    if sleep_s:
        time.sleep(sleep_s)

ckpt.flush(comm)  # commit the last epoch before the digest
dig = hashlib.sha256(vec.tobytes()).hexdigest()
if rank == 0:
    print(f"STEPS {tag} {start}", flush=True)
    print(f"DIGEST {tag} {dig}", flush=True)
ompi_tpu.finalize()
