"""Severed-socket recovery driver (run under mpirun by test_bml):
rank 0 starts a large rendezvous send to rank 1, then hard-closes its
outbound tcp sockets; the transfer must still complete (tcp
reconnect + undrained-frame resend), byte-exact."""
import numpy as np

import ompi_tpu
from ompi_tpu.datatype import engine as dt

comm = ompi_tpu.init()
state = comm.state
n = 2 * 1024 * 1024  # well past the tcp eager limit: rendezvous
if comm.rank == 0:
    x = np.arange(n, dtype=np.float32)
    req = state.pml.isend(x, n, dt.FLOAT, 1, 7, comm)
    # sever every outbound tcp socket NOW — between the RNDV head and
    # the ACK-triggered FRAG stream
    for m in state.btls:
        if m.name == "tcp":
            for conn in m._out.values():
                conn.sock.close()
    req.wait()
else:
    y = np.empty(n, dtype=np.float32)
    comm.Recv(y, 0, tag=7)
    assert np.array_equal(y, np.arange(n, dtype=np.float32)), \
        "payload corrupted across the reconnect"
comm.Barrier()
if comm.rank == 0:
    print("sever ok", flush=True)
ompi_tpu.finalize()
