"""Cross-process device p2p driver (run under mpirun): the payload
host-stages through the wrapper's pickle exactly once."""
import numpy as np

import ompi_tpu

comm = ompi_tpu.init()
if comm.rank == 0:
    try:
        import jax.numpy as jnp
        x = jnp.arange(16.0)
    except Exception:
        x = np.arange(16.0)
    comm.send_arr(x, 1, tag=5)
else:
    got = comm.recv_arr(0, tag=5)
    assert float(np.asarray(got)[15]) == 15.0
comm.Barrier()
if comm.rank == 0:
    print("devp2p ok", flush=True)
ompi_tpu.finalize()
