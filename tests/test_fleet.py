"""Overload-robustness tests for the serving control plane (ISSUE 12):
priority admission + preemption must never fail a preempted job (it
resumes from checkpoint, byte-identical); per-session quotas degrade
then reject without poisoning the pool; deadline shedding rejects
infeasible work with a typed error at admission; live pool resize
grows/shrinks capacity under traffic with zero failed jobs; and the
FleetController closes the loop — all proven under ft_inject chaos
(dvm_disconnect, rank_kill) with ScopedPvar band-sum exactness held
across resize epochs."""

import os
import threading
import time

import pytest

from ompi_tpu.mca.params import registry

jax = pytest.importorskip("jax")

from ompi_tpu import obs as _obs  # noqa: E402
from ompi_tpu.tools.dvm import (DVMServer, DvmBusy,  # noqa: E402
                                DvmClient, DvmDeadline, DvmError,
                                _pv_preempts, _pv_resizes, _pv_sheds,
                                _send)

HERE = os.path.dirname(__file__)
PROG = os.path.join(HERE, "_dvm_session_prog.py")
SLOW_PROG = os.path.join(HERE, "_dvm_slow_prog.py")
CKPT_PROG = os.path.join(HERE, "_fleet_ckpt_prog.py")
HOST_PROG = os.path.join(HERE, "_fleet_host_prog.py")
BUDDY_PROG = os.path.join(HERE, "_fleet_buddy_prog.py")


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


def _pool(tmp_path, capacity):
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(capacity, devices=jax.devices(),
                    uri_file=uri).start()
    return srv, uri


def _digest(stdout, tag):
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "DIGEST" and parts[1] == tag:
            return parts[2]
    raise AssertionError(f"no DIGEST {tag} in: {stdout!r}")


def _resumed_at(stdout, tag):
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) == 3 and parts[0] == "STEPS" and parts[1] == tag:
            return int(parts[2])
    raise AssertionError(f"no STEPS {tag} in: {stdout!r}")


def _wait_for(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _assert_band_sums_exact():
    """global == sum(bands) for every ScopedPvar — attribution never
    leaks or double-counts, including across resize epochs."""
    for sp in _obs.scoped_items():
        g = sp.pvar.read()
        s = sum(sp.bands)
        assert g == s, f"{sp.pvar.full_name}: global {g} != Σbands {s}"


# -- satellite 1: queue timeout knob ----------------------------------------


def test_queue_timeout_then_retry(tmp_path):
    """dvm_queue_timeout_s bounds an untimed queued attach with a
    friendly DvmBusy naming the knob; a later retry (after capacity
    frees) succeeds — timeout-then-retry is a working pattern."""
    srv, uri = _pool(tmp_path, 2)
    saved = _set({"dvm_queue_timeout_s": 1.0})
    try:
        c1 = DvmClient(uri)
        s1 = c1.attach(2)["sid"]
        c2 = DvmClient(uri)
        t0 = time.monotonic()
        with pytest.raises(DvmBusy, match="dvm_queue_timeout_s"):
            c2.attach(2)  # no client timeout: the knob bounds it
        assert time.monotonic() - t0 < 20
        c1.detach(s1)
        r = c2.attach(2)  # retry now succeeds
        c2.detach(r["sid"])
        c1.close()
        c2.close()
    finally:
        _restore(saved)
        srv.stop()


# -- satellite 2: dead queued client swept ----------------------------------


def test_dead_queued_client_swept_and_successor_admitted(tmp_path):
    """A client that dies WHILE QUEUED must not hold its place in
    line: the heartbeat sweep abandons its waiter and the session
    queued behind it is admitted as soon as capacity frees."""
    srv, uri = _pool(tmp_path, 2)
    saved = _set({"dvm_heartbeat_s": 0.3})
    try:
        c1 = DvmClient(uri)
        s1 = c1.attach(2)["sid"]
        doomed = DvmClient(uri)
        # fire the attach without waiting for the reply, so we can
        # kill the connection while the waiter sits in the queue
        _send(doomed.sock, {"op": "attach", "np": 2, "wait": True})
        _wait_for(lambda: len(srv._waiters) == 1,
                  what="doomed attach queued")
        got = {}

        def behind():
            with DvmClient(uri) as c3:
                r = c3.attach(2, timeout=60)
                got.update(r)
                c3.detach(r["sid"])

        th = threading.Thread(target=behind)
        th.start()
        _wait_for(lambda: len(srv._waiters) == 2,
                  what="successor queued behind the doomed client")
        doomed.sock.close()  # dies in line
        _wait_for(lambda: len(srv._waiters) == 1, timeout=15,
                  what="heartbeat sweep of the dead waiter")
        c1.detach(s1)  # frees capacity -> the SUCCESSOR admits
        th.join(timeout=60)
        assert "sid" in got, got
        c1.close()
    finally:
        _restore(saved)
        srv.stop()


# -- tentpole: priority admission -------------------------------------------


def test_priority_orders_admission_queue(tmp_path):
    """A higher-priority attach queued later is admitted first (FIFO
    within a priority level, priority across levels)."""
    srv, uri = _pool(tmp_path, 2)
    c1 = DvmClient(uri)
    s1 = c1.attach(2)["sid"]
    order = []

    def waiter(prio, name):
        with DvmClient(uri) as c:
            r = c.attach(2, timeout=60, priority=prio)
            order.append(name)
            time.sleep(0.3)  # hold briefly so admissions serialize
            c.detach(r["sid"])

    lo = threading.Thread(target=waiter, args=(0, "lo"))
    lo.start()
    _wait_for(lambda: len(srv._waiters) == 1, what="low-prio queued")
    hi = threading.Thread(target=waiter, args=(5, "hi"))
    hi.start()
    _wait_for(lambda: len(srv._waiters) == 2, what="high-prio queued")
    with srv.lock:
        assert srv._waiters[0].priority == 5, \
            "priority attach did not sort ahead of the FIFO waiter"
    c1.detach(s1)
    hi.join(timeout=60)
    lo.join(timeout=60)
    assert order == ["hi", "lo"]
    c1.close()
    srv.stop()


# -- tentpole: preemption (running + idle victims) --------------------------


def test_preempt_running_session_resumes_byte_identical(tmp_path):
    """A high-priority attach preempts a running preemptible session:
    the victim checkpoints-resumes (STEPS shows a nonzero restart),
    its client sees ONE successful slower run whose digest is
    byte-identical to an unpreempted baseline — never a failed job."""
    srv, uri = _pool(tmp_path, 2)
    steps, sleep_s = 10, 0.2
    # unpreempted baseline in its own store
    store_a = str(tmp_path / "store_a")
    cb = DvmClient(uri)
    sb = cb.attach(2)["sid"]
    rb = cb.run(sb, CKPT_PROG, ["base", store_a, str(steps)],
                timeout=240)
    assert rb["code"] == 0, rb["stderr"][-2000:]
    base_dig = _digest(rb["stdout"], "base")
    cb.detach(sb)
    cb.close()

    p0 = _pv_preempts.read()
    store_v = str(tmp_path / "store_v")
    cv = DvmClient(uri)
    sv = cv.attach(2, preemptible=True)["sid"]
    res = {}

    def victim_run():
        res["r"] = cv.run(sv, CKPT_PROG,
                          ["vic", store_v, str(steps), str(sleep_s)],
                          timeout=240)

    th = threading.Thread(target=victim_run)
    th.start()
    time.sleep(1.0)  # the victim is mid-run, a few steps checkpointed
    hi = DvmClient(uri)
    rh = hi.attach(2, priority=5, timeout=120)
    # the preemptor got the victim's ranks and can run immediately
    rr = hi.run(rh["sid"], PROG, ["hi"], timeout=120)
    assert rr["code"] == 0, rr["stderr"][-2000:]
    hi.detach(rh["sid"])
    hi.close()
    th.join(timeout=240)
    r = res["r"]
    assert r["code"] == 0, r["stderr"][-2000:]  # never a failed job
    assert r.get("preempted", 0) >= 1
    assert _pv_preempts.read() >= p0 + 1
    assert _resumed_at(r["stdout"], "vic") > 0, \
        "victim restarted from scratch instead of its checkpoint"
    assert _digest(r["stdout"], "vic") == base_dig
    cv.detach(sv)
    cv.close()
    srv.stop()


def test_preempt_idle_session_parks_then_resumes_transparently(tmp_path):
    """An idle preemptible victim is parked immediately (its ranks
    reclaimed for the preemptor); its next run re-admits and re-brings
    it up behind the scenes."""
    srv, uri = _pool(tmp_path, 2)
    p0 = _pv_preempts.read()
    cv = DvmClient(uri)
    sv = cv.attach(2, preemptible=True)["sid"]
    r0 = cv.run(sv, PROG, ["idle"], timeout=120)
    assert r0["code"] == 0, r0["stderr"][-2000:]
    hi = DvmClient(uri)
    rh = hi.attach(2, priority=1, timeout=60)
    with srv.lock:
        assert srv.sessions[sv].parked, "idle victim was not parked"
        assert srv.active_ranks == 2
    assert _pv_preempts.read() == p0 + 1
    hi.detach(rh["sid"])
    hi.close()
    # next run on the parked session: transparent re-admission
    r1 = cv.run(sv, PROG, ["idle"], timeout=240)
    assert r1["code"] == 0, r1["stderr"][-2000:]
    assert r1.get("preempted", 0) == 1
    assert r1["stdout"] == r0["stdout"]
    cv.detach(sv)
    cv.close()
    srv.stop()


# -- tentpole: live resize under traffic + chaos (satellite 4) --------------


def test_resize_under_traffic_zero_failed_jobs(tmp_path):
    """Grow 4->8 and shrink 8->4 while sessions are actively running:
    zero failed jobs, byte-identical outputs, both epochs recorded,
    and ScopedPvar band sums stay exact across the resize epochs."""
    srv, uri = _pool(tmp_path, 4)
    c0 = DvmClient(uri)
    s0 = c0.attach(2)["sid"]
    baseline = c0.run(s0, PROG, ["rz"], timeout=120)
    assert baseline["code"] == 0, baseline["stderr"][-2000:]
    c0.detach(s0)
    c0.close()
    z0 = _pv_resizes.read()
    errors = []
    outs = []

    def worker(nruns):
        try:
            with DvmClient(uri) as c:
                sid = c.attach(2, timeout=120)["sid"]
                for _ in range(nruns):
                    r = c.run(sid, PROG, ["rz"], timeout=120)
                    assert r["code"] == 0, r["stderr"][-2000:]
                    outs.append(r["stdout"])
                c.detach(sid)
        except BaseException as e:  # noqa: BLE001
            errors.append(repr(e))

    t1 = threading.Thread(target=worker, args=(4,))
    t2 = threading.Thread(target=worker, args=(4,))
    t1.start()
    t2.start()
    time.sleep(0.3)  # traffic in flight
    admin = DvmClient(uri)
    gr = admin.resize(8)
    assert gr["was"] == 4 and gr["epoch"] == 1
    t3 = threading.Thread(target=worker, args=(2,))
    t3.start()  # uses the grown headroom
    time.sleep(0.3)
    sh = admin.resize(4)
    assert sh["was"] == 8 and sh["epoch"] == 2
    for t in (t1, t2, t3):
        t.join(timeout=240)
    assert not errors, errors
    assert len(outs) == 10 and all(o == baseline["stdout"]
                                   for o in outs), \
        "a run under resize diverged from the baseline"
    assert _pv_resizes.read() == z0 + 2
    st = admin.stats()
    assert st["capacity"] == 4 and st["epoch"] == 2
    admin.close()
    _assert_band_sums_exact()
    srv.stop()


def test_resize_with_client_disconnect_chaos(tmp_path):
    """ft_inject dvm_disconnect during the resize window: the doomed
    client's session unwinds, the pool resizes anyway, survivors stay
    byte-identical, and new sessions keep being admitted."""
    srv, uri = _pool(tmp_path, 4)
    cb = DvmClient(uri)
    sb = cb.attach(2)["sid"]
    base = cb.run(sb, PROG, ["sv"], timeout=120)
    assert base["code"] == 0, base["stderr"][-2000:]
    saved = _set({"ft_inject_plan": "dvm_disconnect:1",
                  "ft_inject_skip": 0})
    try:
        ca = DvmClient(uri)  # injector armed at construction
        sa = ca.attach(2)["sid"]
        with pytest.raises(DvmError, match="dvm_disconnect"):
            ca.run(sa, PROG, ["doomed"])
    finally:
        _restore(saved)
    admin = DvmClient(uri)
    admin.resize(8)
    r1 = cb.run(sb, PROG, ["sv"], timeout=120)
    assert r1["code"] == 0 and r1["stdout"] == base["stdout"]
    admin.resize(4)
    r2 = cb.run(sb, PROG, ["sv"], timeout=120)
    assert r2["code"] == 0 and r2["stdout"] == base["stdout"]
    # the orphaned session is reaped; the pool still admits
    _wait_for(lambda: len(srv.sessions) == 1, timeout=60,
              what="orphaned session reaped")
    with DvmClient(uri) as cn:
        rn = cn.attach(2, timeout=60)
        cn.detach(rn["sid"])
    _assert_band_sums_exact()
    cb.detach(sb)
    cb.close()
    admin.close()
    srv.stop()


def test_rank_kill_chaos_confined_to_victim_session(tmp_path):
    """ft_inject rank_kill inside one session of the pool: that run
    fails and the session dies, but a peer session's output stays
    byte-identical and the pool keeps admitting new sessions."""
    srv, uri = _pool(tmp_path, 4)
    cb = DvmClient(uri)
    sb = cb.attach(2)["sid"]
    base = cb.run(sb, PROG, ["pk"], timeout=120)
    assert base["code"] == 0, base["stderr"][-2000:]
    # arm the kill ONLY around the doomed session's bring-up (the
    # death timer arms at mpi_init); the peer attached before, the
    # post-mortem session attaches after the restore
    saved = _set({"ft_inject_plan": "rank_kill",
                  "ft_inject_skip": 0,
                  "ft_inject_victim_rank": "1",
                  "ft_inject_after": 0.3})
    try:
        ca = DvmClient(uri)
        sa = ca.attach(2)["sid"]
    finally:
        _restore(saved)
    store = str(tmp_path / "store_kill")
    ra = ca.run(sa, CKPT_PROG, ["doom", store, "20", "0.2"],
                timeout=240)
    assert ra["code"] != 0, "the armed rank_kill never fired"
    # the victim's RankKilled is published ULFM-style; this program
    # is not ULFM-aware, so its surviving peer dies session-confined
    # on the resulting ERR_PROC_FAILED naming the corpse
    assert "MPI_ERR_PROC_FAILED" in ra["stderr"]
    assert "rank_kill" in ra["stderr"]
    with pytest.raises(DvmError, match="dead"):
        ca.run(sa, PROG, ["again"])
    # the peer is untouched, byte for byte
    rb = cb.run(sb, PROG, ["pk"], timeout=120)
    assert rb["code"] == 0 and rb["stdout"] == base["stdout"]
    ca.detach(sa)  # releases the dead session's ranks
    with DvmClient(uri) as cn:
        rn = cn.attach(2, timeout=60)
        r = cn.run(rn["sid"], PROG, ["fresh"], timeout=120)
        assert r["code"] == 0, r["stderr"][-2000:]
        cn.detach(rn["sid"])
    ca.close()
    cb.detach(sb)
    cb.close()
    srv.stop()


# -- tentpole: deadline shedding --------------------------------------------


def test_deadline_shed_typed_reject_keeps_session_alive(tmp_path):
    """An infeasible deadline is shed at admission with a typed
    DvmDeadline in microseconds — and shedding a run must NOT poison
    the session: a feasible run right after succeeds."""
    srv, uri = _pool(tmp_path, 4)
    c = DvmClient(uri)
    sid = c.attach(2)["sid"]
    warm = c.run(sid, SLOW_PROG, timeout=120)  # seeds est_wall_us
    assert warm["code"] == 0, warm["stderr"][-2000:]
    assert srv.est_wall_us > 1_000_000  # the 1.5s sleep dominates
    h0 = _pv_sheds.read()
    with pytest.raises(DvmDeadline, match="shed at admission"):
        c.run(sid, SLOW_PROG, deadline_ms=100)
    assert _pv_sheds.read() == h0 + 1
    r = c.run(sid, PROG, ["ok"], deadline_ms=60_000, timeout=120)
    assert r["code"] == 0, r["stderr"][-2000:]
    c.detach(sid)
    c.close()
    srv.stop()


# -- tentpole: per-session quotas -------------------------------------------


def test_hbm_quota_degrades_then_rejects_without_poisoning_pool(
        tmp_path):
    """Over-budget HBM deposits: first breach degrades (evicts the
    offender's own cache band), continued breach fails THAT run with
    QuotaExceeded — the peer session and the pool keep working."""
    from ompi_tpu.serve import quota

    srv, uri = _pool(tmp_path, 4)
    hog = str(tmp_path / "_hog.py")
    with open(hog, "w") as f:
        f.write(
            "import numpy as np\n"
            "import ompi_tpu\n"
            "from ompi_tpu.op import op as mpi_op\n"
            "comm = ompi_tpu.init()\n"
            "for i in range(8):\n"
            "    x = np.full(4096, float(comm.rank + i), np.float64)\n"
            "    comm.allreduce_arr(x, mpi_op.SUM)\n"
            "ompi_tpu.finalize()\n")
    cb = DvmClient(uri)
    sb = cb.attach(2)["sid"]
    # each of the 8 iterations deposits 2 ranks x 32 KiB = 64 KiB;
    # a 100 KB budget breaches on the 4th deposit (degrade) and
    # rejects on the 5th
    saved = _set({"dvm_quota_hbm_bytes": 100_000})
    rej0 = quota.pv_rejects.read()
    try:
        ca = DvmClient(uri)
        sa = ca.attach(2)["sid"]
        ra = ca.run(sa, hog, timeout=120)
        assert ra["code"] != 0, "the quota never rejected"
        assert "quota" in ra["stderr"]
        assert quota.pv_rejects.read() > rej0
        assert quota.pv_hbm.read_band(sa) > 0  # attributed to the hog
        ca.close()
    finally:
        _restore(saved)
    rb = cb.run(sb, PROG, ["peer"], timeout=120)
    assert rb["code"] == 0, rb["stderr"][-2000:]
    cb.detach(sb)
    cb.close()
    _assert_band_sums_exact()
    srv.stop()


def test_cache_share_quota_evicts_own_entries():
    """dvm_quota_cache_share_pct caps one band's CompiledLRU share at
    insert time by evicting that band's own oldest entries — nobody
    else's."""
    import types

    from ompi_tpu.coll.device import compile_cache
    from ompi_tpu.runtime import state as statemod

    saved = _set({"dvm_quota_cache_share_pct": 5})
    fake = types.SimpleNamespace(cid_band=777)
    statemod.set_current(fake)
    ev0 = compile_cache.pv_band_evictions.read()
    cap = max(1, registry.get("coll_device_cache_max", 256))
    band_cap = max(1, cap * 5 // 100)
    try:
        for i in range(band_cap + 3):
            compile_cache.get(("fleet-test", 777, i), lambda: object())
        assert compile_cache.count_band(777) == band_cap
        assert compile_cache.pv_band_evictions.read() == ev0 + 3
    finally:
        statemod.set_current(None)
        compile_cache.drop_band(777)
        _restore(saved)
    assert compile_cache.count_band(777) == 0


# -- tentpole: FleetController closed loop (satellite 6 audit tie-in) -------


def test_controller_grows_under_backlog_and_shrinks_idle(tmp_path):
    """dvm_ctrl=1: queued attaches make the controller grow the pool
    (admitting the backlog with no manual resize), and a sustained
    idle pool shrinks back to its floor."""
    saved = _set({"dvm_ctrl": 1,
                  "dvm_ctrl_max_ranks": 4,
                  "ctrl_tick_interval_ms": 50,
                  "ctrl_grow_queue_depth": 1,
                  "ctrl_grow_step": 2,
                  "ctrl_shrink_idle_ticks": 2,
                  "dvm_heartbeat_s": 0.3})
    try:
        srv, uri = _pool(tmp_path, 2)  # floor 2, ceiling 4
        assert srv.ctrl is not None
        c1 = DvmClient(uri)
        s1 = c1.attach(2)["sid"]
        c2 = DvmClient(uri)
        r2 = c2.attach(2, timeout=60)  # backlog -> controller grows
        assert srv.capacity == 4
        m = c2.metrics(events=4)
        assert m["ctrl"]["ticks"] > 0
        assert m["ctrl"]["shed_margin_pct"] >= 100
        assert m["epoch"] >= 1
        assert registry._pvars["ctrl_loop_ticks"].read() > 0
        c2.detach(r2["sid"])
        c1.detach(s1)
        # idle now: the loop shrinks back to the floor
        _wait_for(lambda: srv.capacity == 2, timeout=30,
                  what="idle shrink back to the floor")
        c1.close()
        c2.close()
        srv.stop()
    finally:
        _restore(saved)


# -- ISSUE 16: host failure domains (DESIGN.md §21) -------------------------


def _pool2(tmp_path, capacity, hosts=2):
    """A multi-host pool: ranks band contiguously across `hosts`
    failure domains (rank's node_id = rank * hosts // np)."""
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(capacity, devices=jax.devices(), uri_file=uri,
                    hosts=hosts).start()
    return srv, uri


def _lines(stdout, kind, tag):
    out = []
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[0] == kind and parts[1] == tag:
            out.append(parts[2:])
    return out


def test_ring_offsets_prefers_off_host_partners():
    """satellite: buddy placement skips same-host partners whenever
    the topology allows, and degrades to the classic ring when it
    cannot (single host, or no host-safe offset exists)."""
    from ompi_tpu.cr.buddy import ring_offsets

    # 2 hosts x 2 ranks: offset 1 pairs within-host ranks (0<->1),
    # offset 2 is the unique host-safe choice
    assert ring_offsets([0, 0, 1, 1], 1) == [2]
    # degree past the host-safe supply falls back to plain offsets
    assert ring_offsets([0, 0, 1, 1], 3) == [2, 1, 3]
    # interleaved placement: every odd offset crosses hosts
    assert ring_offsets([0, 1, 0, 1], 1) == [1]
    # one host: the classic SCR partner ring
    assert ring_offsets([0, 0, 0, 0], 2) == [1, 2]
    # no offset is host-safe for an asymmetric band: plain ring
    assert ring_offsets([0, 0, 1], 1) == [1]
    assert ring_offsets([7], 1) == []


def test_two_host_attach_cross_host_fence_byte_identical(tmp_path):
    """One attach commands a world spanning both host domains: the
    init/finalize fences cross the DCN KV path, the proctable stamps
    each rank's failure domain, and the output matches a single-host
    run byte for byte."""
    (tmp_path / "one").mkdir(exist_ok=True)
    srv1, uri1 = _pool(tmp_path / "one", 4)
    c1 = DvmClient(uri1)
    s1 = c1.attach(4)["sid"]
    base = c1.run(s1, PROG, ["xh"], timeout=120)
    assert base["code"] == 0, base["stderr"][-2000:]
    c1.detach(s1)
    c1.close()
    srv1.stop()

    srv, uri = _pool2(tmp_path, 4)
    c = DvmClient(uri)
    r = c.attach(4)
    assert r["hosts"] == 2
    sid = r["sid"]
    out = c.run(sid, PROG, ["xh"], timeout=120)
    assert out["code"] == 0, out["stderr"][-2000:]
    assert out["stdout"] == base["stdout"], \
        "a DCN-spanning world diverged from the single-host run"
    st = c.stats()
    assert st["hosts"] == 2 and st["hosts_lost"] == 0
    # the gray-failure plane arms on multi-host pools: stats carries
    # its counters (all quiet here) alongside the liveness ones
    assert st["hosts_degraded"] == 0 and st["hosts_quarantined"] == 0
    # the proctable stamps which host's death takes each rank down
    import json
    with open(f"{uri}.proctable.json") as fh:
        table = json.load(fh)
    doms = sorted(ent["hdom"] for ent in table if "hdom" in ent)
    assert doms == [0, 0, 1, 1], table
    c.detach(sid)
    c.close()
    srv.stop()


def test_host_kill_shrink_arm_single_failure_set(tmp_path):
    """host_kill mid-collective under ULFM: every rank on the dead
    host lands in ONE atomic failure set, so each survivor shrinks
    exactly once and all survivors' digests are byte-identical after
    redoing the run on the shrunk world."""
    srv, uri = _pool2(tmp_path, 4)
    c = DvmClient(uri)
    sid = c.attach(4)["sid"]
    res = {}

    def run():
        res["r"] = c.run(sid, HOST_PROG, ["sa", "120"], timeout=240)

    th = threading.Thread(target=run)
    th.start()
    _wait_for(lambda: srv.sessions[sid].running, what="session running")
    time.sleep(0.6)  # mid-loop, well before step 120
    srv.kill_host(1)
    assert srv._host_dead[1] == 1
    assert srv.hosts_rehydrating == 1
    th.join(timeout=240)
    r = res["r"]
    assert r["code"] == 0, r["stderr"][-2000:]
    shrinks = _lines(r["stdout"], "SHRINKS", "sa")
    digs = _lines(r["stdout"], "DIGEST", "sa")
    # survivors = ranks 0,1 (host 0); victims 2,3 exited silently
    assert sorted(int(s[0]) for s in shrinks) == [0, 1], shrinks
    assert all(int(s[1]) == 1 for s in shrinks), \
        f"a survivor saw a torn failure set: {shrinks}"
    assert len(digs) == 2 and digs[0] == digs[1], digs
    # host-granularity respawn reports a real MTTR and refills the
    # fleet (the RPC path the operator and the probe both use)
    rr = c.respawn_host(1)
    assert rr["mttr_ms"] > 0
    assert srv.hosts_rehydrating == 0 and srv._host_dead[1] == 0
    st = c.stats()
    assert st["hosts_lost"] == 0  # live count back to full
    assert registry._pvars["fleet_hosts_lost"].read() >= 1  # lifetime
    c.detach(sid)
    c.close()
    _assert_band_sums_exact()
    srv.stop()


def test_host_kill_replay_arm_byte_identical(tmp_path):
    """host_kill against a session that is NOT ULFM-aware
    (mpi_ft_ulfm=0): the whole session parks, waits out the domain
    rehydration, and replays from its checkpoint — the client sees
    one successful slower run, digest byte-identical to an unkilled
    baseline, never a failed job."""
    saved = _set({"mpi_ft_ulfm": 0})
    try:
        srv, uri = _pool2(tmp_path, 4)
        steps, sleep_s = 12, 0.2
        store_a = str(tmp_path / "store_a")
        cb = DvmClient(uri)
        sb = cb.attach(2)["sid"]
        rb = cb.run(sb, CKPT_PROG, ["hbase", store_a, str(steps)],
                    timeout=240)
        assert rb["code"] == 0, rb["stderr"][-2000:]
        base_dig = _digest(rb["stdout"], "hbase")
        cb.detach(sb)
        cb.close()

        store_v = str(tmp_path / "store_v")
        cv = DvmClient(uri)
        sv = cv.attach(2)["sid"]
        res = {}

        def run():
            res["r"] = cv.run(sv, CKPT_PROG,
                              ["hvic", store_v, str(steps),
                               str(sleep_s)], timeout=240)

        th = threading.Thread(target=run)
        th.start()
        _wait_for(lambda: srv.sessions[sv].running,
                  what="victim running")
        time.sleep(0.8)  # a few steps checkpointed
        srv.kill_host(1)
        time.sleep(0.3)
        mttr = srv.respawn_host(1)
        assert mttr > 0
        th.join(timeout=240)
        r = res["r"]
        assert r["code"] == 0, r["stderr"][-2000:]  # zero failed jobs
        assert r.get("preempted", 0) >= 1
        assert _resumed_at(r["stdout"], "hvic") > 0, \
            "victim restarted from scratch instead of its checkpoint"
        assert _digest(r["stdout"], "hvic") == base_dig
        cv.detach(sv)
        cv.close()
        srv.stop()
    finally:
        _restore(saved)


def test_buddy_restore_from_off_host_partner(tmp_path):
    """satellite: on a 2-host pool the buddy ring places every
    replica off-host, so host 1's ranks restore their state from
    host 0 partners after losing their own copies."""
    srv, uri = _pool2(tmp_path, 4)
    c = DvmClient(uri)
    sid = c.attach(4)["sid"]
    r = c.run(sid, BUDDY_PROG, ["bd"], timeout=240)
    assert r["code"] == 0, r["stderr"][-2000:]
    oks = _lines(r["stdout"], "BUDDY", "bd")
    assert sorted(int(o[0]) for o in oks) == [0, 1, 2, 3], r["stdout"]
    c.detach(sid)
    c.close()
    srv.stop()


def test_ft_inject_host_kill_class(tmp_path):
    """satellite: the deterministic host_kill fault class severs the
    victim host at the armed op count — same lost-domain handling as
    heartbeat silence, no process needed."""
    saved = _set({"ft_inject_plan": "host_kill:3",
                  "ft_inject_skip": 0,
                  "ft_inject_victim_host": 1})
    try:
        srv, uri = _pool2(tmp_path, 4)  # injector armed in _setup
        assert srv._hkill is not None
        c = DvmClient(uri)
        c.stats()   # op 1
        c.stats()   # op 2
        c.stats()   # op 3 -> fires
        assert srv._host_dead[1] == 1
        assert srv.hosts_rehydrating == 1
        st = c.stats()
        assert st["hosts_lost"] == 1
        c.close()
        srv.stop()
    finally:
        _restore(saved)


def test_host_journal_federation_and_bounded_replay(tmp_path):
    """satellite: per-host write-ahead journals federate under one
    incarnation; completed-jobid replay memory stays bounded at 64
    across torn-tail recovery, compaction, and TWO successive
    incarnations."""
    import json
    srv, uri = _pool2(tmp_path, 4)
    c = DvmClient(uri)
    sid = c.attach(2)["sid"]  # sid 1 -> host 1's journal (1 % 2)
    r = c.run(sid, PROG, ["fj"], timeout=120)
    assert r["code"] == 0, r["stderr"][-2000:]
    h0_path = f"{uri}.journal.jsonl"
    h1_path = f"{uri}.journal.h1.jsonl"

    def _h1():
        with open(h1_path) as fh:
            return fh.read()

    # run/run_done append asynchronously; the heartbeat tick flushes
    _wait_for(lambda: '"run_done"' in _h1(), timeout=30,
              what="run_done flushed to the host journal")
    with open(h0_path) as fh:
        h0 = fh.read()
    h1 = _h1()
    # the session's records route to its OWNING host's journal
    assert '"attach"' not in h0
    assert '"attach"' in h1 and '"run_done"' in h1
    # both journals are stamped with the same fleet incarnation
    inc0 = json.loads(h0.splitlines()[0])["inc"]
    inc1 = json.loads(h1.splitlines()[0])["inc"]
    assert inc0 == inc1
    c.sock.close()  # vanish without detach: the session must replay
    srv.stop()      # deletes both journals

    # resurrect the fleet's journals with 80 extra completed jobs and
    # a torn tail on the HOST journal (the host died mid-append)
    fakes = "".join(
        json.dumps({"t": "run_done", "sid": sid,
                    "jobid": f"fake-{i}", "code": 0}) + "\n"
        for i in range(80))
    with open(h0_path, "w") as fh:
        fh.write(h0)
    with open(h1_path, "w") as fh:
        fh.write(h1 + fakes + '{"t": "run_done", "sid')  # torn tail
    srv2 = DVMServer(4, devices=jax.devices(), uri_file=uri,
                     hosts=2).start()
    assert srv2.rehydrated == 1
    sess = srv2.sessions[sid]
    assert sess.parked and len(sess.completed) <= 64, \
        f"replay memory unbounded: {len(sess.completed)}"
    # the compacted host journal carries the bound forward too
    with open(h1_path) as fh:
        compacted = fh.read()
    assert compacted.count('"run_done"') <= 64
    with open(h1_path) as fh:
        h1b = fh.read()
    with open(h0_path) as fh:
        h0b = fh.read()
    srv2.stop()

    # second incarnation: the bound holds again, no re-accretion
    with open(h0_path, "w") as fh:
        fh.write(h0b)
    with open(h1_path, "w") as fh:
        fh.write(h1b)
    srv3 = DVMServer(4, devices=jax.devices(), uri_file=uri,
                     hosts=2).start()
    assert srv3.rehydrated == 1
    assert len(srv3.sessions[sid].completed) <= 64
    srv3.stop()


def test_clean_halt_deletes_federated_journals(tmp_path):
    """A journal on disk always means a crash — the RPC halt path
    must delete the per-host federated journals along with the
    primary, or the next incarnation resurrects sessions nobody
    wants back."""
    srv, uri = _pool2(tmp_path, 4)
    c = DvmClient(uri)
    sid = c.attach(2)["sid"]  # sid 1 -> host 1's journal
    assert os.path.exists(f"{uri}.journal.jsonl")
    assert os.path.exists(f"{uri}.journal.h1.jsonl")
    c.halt()
    assert not os.path.exists(f"{uri}.journal.jsonl")
    assert not os.path.exists(f"{uri}.journal.h1.jsonl"), \
        "clean halt left a host journal behind"
    c.close()
    srv.stop()
    del sid


def test_controller_tick_is_audited_hot():
    """The controller's decision tick rides the progress sweep, so it
    must be declared to the hot-path audit — and pass it."""
    from ompi_tpu.tools.hotpath_audit import HOT_FUNCTIONS, audit
    assert "FleetController.tick" in HOT_FUNCTIONS[
        "ompi_tpu/serve/controller.py"]
    assert audit() == []
