"""Critical-path profiler (ISSUE 13 / docs/DESIGN.md §18): phase-span
sampling exactness, the gating-verdict rule on synthetic spans, a
4-rank injected-straggler world whose analysis must name the delayed
rank (and the rendezvous phase) as gating with >=90% of op wall time
attributed to named phases, embedded mpisync offsets in the dumps, the
flow-arrow-stitched Chrome trace, and the hotpath_audit declarations
for the new phase record points."""

import json
import os

import pytest

from ompi_tpu import trace
from ompi_tpu.mca.params import registry
from ompi_tpu.testing import run_ranks
from ompi_tpu.tools import critpath, traceview

# register the plan knob _PIPE_ON pins off before any registry.set
import ompi_tpu.coll.plan  # noqa: E402,F401

# segmented-ring pipeline knobs (the test_coll_pipeline PIPE_ON shape):
# small segments so a 16 KiB allreduce becomes several rendezvous
_PIPE_ON = {
    "coll_pipeline_enable": True,
    "coll_pipeline_min_bytes": 2048,
    "coll_seg_size": 4096,
    "coll_pipeline_rd_max_bytes": 0,
    "coll_hier_enable": False,
    # critpath attribution is over the PER-SEGMENT rendezvous phase
    # structure; the compiled-plan tier collapses it to one meet
    "coll_plan_enable": False,
}


@pytest.fixture(autouse=True)
def _clean():
    yield
    registry.set("trace_enable", "0")
    registry.set("trace_dump_path", "")
    registry.set("trace_phase_enable", "0")
    registry.set("trace_sample_spec", "")
    registry.set("trace_sample_auto", "1024")
    registry.set("trace_sample_max", "64")
    registry.set("coll_pipeline_enable", "0")
    registry.set("coll_pipeline_min_bytes", "1048576")
    registry.set("coll_seg_size", "1048576")
    registry.set("coll_pipeline_rd_max_bytes", "0")
    registry.set("coll_hier_enable", "0")
    registry.set("ft_inject_plan", "")
    registry.set("ft_inject_skip", "8")
    registry.set("ft_inject_delay_ms", "20")


# -- sampling exactness for the new category --------------------------------

def test_phase_sampling_exact():
    """The phase category obeys the same exactness invariant as every
    other sampled category: kept + sampled-out == seen, and the pvar
    accessors agree with the manual count."""
    registry.set("trace_sample_spec", "phase:4")
    registry.set("trace_sample_auto", "0")   # pin the period
    tr = trace.Tracer(0, capacity=4096)
    kept = 0
    for i in range(100):
        t0 = tr.start_sampled(trace.CAT_PHASE)
        if t0:
            tr.end(t0, trace.NAME_PH_DISPATCH, trace.CAT_PHASE, 1, i, 0)
            kept += 1
    assert kept == 25                      # exactly 1-in-4
    assert tr.cat_seen("phase") == 100
    assert tr.dropped_by_cat()["phase"] == 100 - kept
    assert tr.span_count("phase") == kept
    assert tr.sampling_rates()["phase"] == 4


def test_phase_totals_label_merge():
    """phase_totals folds span names into report labels (fused_pack
    and ph_pack are both 'pack')."""
    registry.set("trace_sample_auto", "0")
    tr = trace.Tracer(0, capacity=64)
    tr.phase = True
    for name in (trace.NAME_PH_PACK, trace.NAME_FUSED_PACK,
                 trace.NAME_PH_EXECUTE):
        t0 = tr.start_sampled(trace.CAT_PHASE)
        tr.end(t0, name, trace.CAT_PHASE, 1, 0, 0)
    tot = tr.phase_totals()
    assert set(tot) == {"pack", "execute"}
    assert tot["pack"] >= 0 and tot["execute"] >= 0


# -- the gating rule on synthetic spans -------------------------------------

def _sp(rank, ts, dur, name, cat, **args):
    return {"rank": rank, "ts": ts, "dur": dur, "name": name,
            "cat": cat, "ph": "X", "args": args}


def test_gating_verdict_skew_vs_phase():
    """A gate whose recorded phases are dwarfed by the arrival skew is
    arrival-gated ('rendezvous'); a gate with a contained phase at
    least as large as the skew is gated by THAT phase."""
    events = [
        # group A: rank 1 arrives 5000 us late, tiny execute span
        _sp(0, 0.0, 5100.0, "meet", "coll_dispatch", cid=1, seq=0),
        _sp(1, 5000.0, 100.0, "meet", "coll_dispatch", cid=1, seq=0),
        _sp(1, 5010.0, 40.0, "ph_execute", "phase", cid=1, seq=0),
        # group B: rank 1 arrives 10 us late but burns 80 us executing
        _sp(0, 9000.0, 100.0, "meet", "coll_dispatch", cid=1, seq=1),
        _sp(1, 9010.0, 90.0, "meet", "coll_dispatch", cid=1, seq=1),
        _sp(1, 9012.0, 80.0, "ph_execute", "phase", cid=1, seq=1),
    ]
    idx = critpath.phase_index(events)
    groups = critpath.group_ops(events)
    ga, skew_a = critpath._gate_of(groups[("coll_dispatch", "meet", 1, 0)])
    gb, skew_b = critpath._gate_of(groups[("coll_dispatch", "meet", 1, 1)])
    assert ga["rank"] == 1 and skew_a == 5000.0
    assert critpath.gating_verdict(ga, skew_a, idx) == "rendezvous"
    assert gb["rank"] == 1 and skew_b == 10.0
    assert critpath.gating_verdict(gb, skew_b, idx) == "execute"


def test_clipped_attribution_never_exceeds_op():
    """Phase time is clipped to the op window — a finish-wait overlap
    can never attribute more than 100% of an op span."""
    op = _sp(0, 100.0, 50.0, "meet", "coll_dispatch", cid=1, seq=0)
    phases = [
        _sp(0, 90.0, 40.0, "ph_dispatch", "phase", cid=1, seq=0),
        _sp(0, 120.0, 400.0, "ph_execute", "phase", cid=1, seq=0),
    ]
    assert critpath._clipped_phase_us(op, phases) <= op["dur"]


# -- the acceptance world: injected straggler named as gating ---------------

def _segring_world(tmp_path, victim=None):
    """One 4-rank segmented-ring world, phase-profiled at full
    fidelity, dumped to tmp_path; when ``victim`` is set that rank
    straggles 40 ms at every rendezvous deposit (ft_inject)."""
    registry.set("trace_enable", "1")
    registry.set("trace_dump_path", str(tmp_path))
    registry.set("trace_phase_enable", "1")
    registry.set("trace_sample_auto", "0")   # full fidelity
    for k, v in _PIPE_ON.items():
        registry.set(k, v)
    if victim is not None:
        registry.set("ft_inject_plan", "delay:1.0")
        registry.set("ft_inject_skip", "0")
        registry.set("ft_inject_delay_ms", "40")

    def fn(comm):
        import jax
        import jax.numpy as jnp
        from ompi_tpu.op.op import SUM
        if victim is not None and comm.rank != victim:
            # disarm the injector cache: only the victim straggles
            comm.state._coll_delay_inj = False
        x = jax.device_put(
            jnp.arange(4099, dtype=jnp.float32) + comm.rank,
            comm.device)
        for _ in range(3):
            x = comm.allreduce_arr(x, SUM)
        comm.Barrier()
        return float(x[0])

    res = run_ranks(4, fn, devices=True, timeout=240)
    assert len(set(res)) == 1              # the collectives agreed
    dumps = traceview.load_dumps([str(tmp_path / "trace-r*.json")])
    assert len(dumps) == 4
    offsets = traceview.embedded_offsets(dumps)
    assert len(offsets) == 4               # satellite: auto-embedded
    return dumps, offsets


def test_phase_coverage_on_clean_segring(tmp_path):
    """Acceptance: on a clean 4-rank segmented-ring run, >=90% of op
    wall time is attributed to named phases, and the dispatch-tax
    table has per-phase medians for the segring tier."""
    dumps, offsets = _segring_world(tmp_path)
    doc = critpath.analyze(dumps, offsets)
    assert doc["coverage"] >= 0.90, doc
    assert doc["multi_rank_ops"] > 0
    assert any("segring" in k for k in doc["tax"]), doc["tax"]


def test_injected_delay_names_gating_rank(tmp_path):
    """4-rank segmented-ring world with a deterministic ft_inject
    rendezvous delay on ONE rank: the critical-path analysis must name
    that rank as gating (arrival-gated: 'rendezvous') and stitch flow
    arrows into the Chrome trace."""
    victim = 2
    dumps, offsets = _segring_world(tmp_path, victim=victim)

    # judge only ops whose arrival skew clears scheduler noise: every
    # surviving stall should trace back to the injected straggler
    doc = critpath.analyze(dumps, offsets, min_skew_us=20000.0)
    gating = doc["gating"]
    assert gating, doc
    victim_gated = sum(v for k, v in gating.items()
                       if k.startswith(f"r{victim}:"))
    assert victim_gated > sum(gating.values()) / 2, gating
    top_key = next(iter(gating))
    assert top_key == f"r{victim}:rendezvous", gating
    # the injected 40 ms stall shows up as arrival skew
    assert doc["skew_us"]["max"] >= 20000.0, doc["skew_us"]

    # CLI smoke: --json output parses, -o writes flow arrows
    out = tmp_path / "stitched.json"
    rc = critpath.main([str(tmp_path / "trace-r*.json"),
                        "-o", str(out), "--json"])
    assert rc == 0
    stitched = json.loads(out.read_text())
    phs = {e.get("ph") for e in stitched["traceEvents"]}
    assert "s" in phs and "f" in phs       # perfetto flow arrows


# -- audit wiring -----------------------------------------------------------

def test_hotpath_audit_declares_phase_helpers():
    """The per-op phase record points are held to the zero-allocation
    budget by the same AST lint as the tracer itself."""
    from ompi_tpu.tools import hotpath_audit
    assert "_phase_fn" in hotpath_audit.HOT_FUNCTIONS[
        "ompi_tpu/coll/device.py"]
    assert "_ph_rdv_start" in hotpath_audit.HOT_FUNCTIONS[
        "ompi_tpu/coll/device.py"]
    assert "_pull_segment" in hotpath_audit.HOT_FUNCTIONS[
        "ompi_tpu/coll/pipeline.py"]
    assert hotpath_audit.audit() == []
