"""coll/sm analog: single-meeting collectives for thread-rank worlds
(ref: ompi/mca/coll/sm).  Results must match the p2p path
bit-for-bit, including rank-order folds for non-commutative ops."""

import numpy as np
import pytest

from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks


def test_allreduce_matches_p2p_bitwise():
    def fn(comm):
        x = (np.arange(8, dtype=np.float64) + comm.rank * 0.1)
        r_sm = np.empty_like(x)
        comm.Allreduce(x, r_sm, mpi_op.SUM)
        # p2p result via the tuned module directly.  Exact ORDER
        # equivalence is covered by the non-commutative test below;
        # here different fold trees (sm left fold vs p2p binomial)
        # may differ in float rounding, so compare numerically.
        from ompi_tpu.coll.tuned import TunedModule
        from ompi_tpu.datatype import engine as dt
        r_p2p = np.empty_like(x)
        TunedModule().allreduce(comm, x, r_p2p, 8, dt.DOUBLE,
                                mpi_op.SUM)
        np.testing.assert_allclose(r_sm, r_p2p, rtol=1e-12)
        return True

    assert all(run_ranks(4, fn))


def test_noncommutative_user_op_rank_order():
    def fn(comm):
        # left-fold of string-like concat encoded as base-10 digits:
        # (((r0 op r1) op r2) op r3) — order-sensitive
        def user(invec, inoutvec, _dt):
            inoutvec[:] = invec * 10 + inoutvec

        op = mpi_op.create(user, commute=False)
        x = np.array([comm.rank + 1], dtype=np.int64)
        r = np.empty_like(x)
        comm.Allreduce(x, r, op)
        # left fold: ((1*10+2)*10+3)*10+4 = 1234 for size 4
        want = 0
        for d in range(1, comm.size + 1):
            want = want * 10 + d
        # user op convention: invec is the LOWER-rank partial
        assert r[0] == want, (r[0], want)
        return True

    assert all(run_ranks(4, fn))


def test_bcast_and_root_buffer_reuse():
    def fn(comm):
        buf = np.full(16, float(comm.rank), np.float64)
        if comm.rank == 2:
            buf[:] = 7.25
        comm.Bcast(buf, root=2)
        # root may clobber its buffer immediately after returning
        if comm.rank == 2:
            buf[:] = -1.0
        comm.Barrier()
        if comm.rank != 2:
            assert (buf == 7.25).all()
        return True

    assert all(run_ranks(6, fn))


def test_reduce_only_root_receives():
    def fn(comm):
        x = np.full(4, comm.rank + 1.0)
        r = np.zeros(4) if comm.rank == 1 else None
        comm.Reduce(x, r, mpi_op.MAX, root=1)
        if comm.rank == 1:
            assert (r == comm.size).all()
        return True

    assert all(run_ranks(5, fn))


def test_allgather_and_alltoall():
    def fn(comm):
        n = comm.size
        mine = np.array([comm.rank * 10 + 1], np.int32)
        allg = np.empty(n, np.int32)
        comm.Allgather(mine, allg)
        assert list(allg) == [r * 10 + 1 for r in range(n)]

        sb = np.array([comm.rank * n + d for d in range(n)], np.int64)
        rb = np.empty_like(sb)
        comm.Alltoall(sb, rb)
        assert list(rb) == [s * n + comm.rank for s in range(n)]
        return True

    assert all(run_ranks(4, fn))


def test_minloc_pair_and_in_place():
    def fn(comm):
        from ompi_tpu.datatype import engine as dt
        pair = np.zeros(2, dtype=[("v", "f8"), ("i", "i8")])
        pair["v"] = [comm.rank + 0.5, 10 - comm.rank]
        pair["i"] = comm.rank
        out = np.empty_like(pair)
        comm.Allreduce((pair, 2, dt.DOUBLE_INT), (out, 2, dt.DOUBLE_INT),
                       mpi_op.MINLOC)
        assert out["i"][0] == 0          # min of rank+0.5 at rank 0
        assert out["i"][1] == comm.size - 1

        buf = np.full(3, comm.rank + 1.0)
        from ompi_tpu.coll.buffers import IN_PLACE
        comm.Allreduce(IN_PLACE, buf, mpi_op.SUM)
        assert (buf == sum(range(1, comm.size + 1))).all()
        return True

    assert all(run_ranks(3, fn))


def test_derived_datatype_goes_through_pack():
    def fn(comm):
        from ompi_tpu.datatype import engine as dt
        vec = dt.vector(3, 1, 2, dt.DOUBLE).commit()
        sb = np.arange(6, dtype=np.float64) + comm.rank
        rb = np.zeros(6, dtype=np.float64)
        comm.Allreduce((sb, 1, vec), (rb, 1, vec), mpi_op.SUM)
        n = comm.size
        base = sum(range(n))
        # strided elements reduced; gaps untouched
        assert rb[0] == 0 * n + base and rb[2] == 2 * n + base
        assert rb[1] == 0.0
        return True

    assert all(run_ranks(4, fn))


def test_sm_actually_selected_in_thread_world():
    def fn(comm):
        return comm.coll.providers.get("allreduce") == "sm"

    assert all(run_ranks(2, fn))
