"""Errhandler / attribute-keyval / Info machinery tests
(ref: ompi/errhandler/errhandler.h, ompi/attribute/attribute.c,
ompi/info/info.c)."""

import numpy as np
import pytest

from ompi_tpu import attrs, errhandler, mpi
from ompi_tpu.errhandler import (ERRORS_ARE_FATAL, ERRORS_RETURN,
                                 Errhandler, MPIException)
from ompi_tpu.info import Info, info_env
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks


# ---- error classes / dispatch --------------------------------------

def test_error_classify_and_string():
    assert errhandler.classify(ValueError("x (MPI_ERR_RANK)")) \
        == errhandler.ERR_RANK
    assert errhandler.classify(FileNotFoundError("f")) \
        == errhandler.ERR_NO_SUCH_FILE
    assert errhandler.classify(MPIException(errhandler.ERR_TRUNCATE)) \
        == errhandler.ERR_TRUNCATE
    assert errhandler.error_string(errhandler.ERR_RANK) == "MPI_ERR_RANK"


def test_errors_return_reraises():
    def fn(comm):
        assert comm.Get_errhandler() is ERRORS_RETURN
        with pytest.raises(ValueError):
            comm.Send(np.zeros(1), dest=99)  # invalid rank
        return True

    assert run_ranks(2, fn) == [True, True]


def test_user_handler_invoked_before_raise():
    def fn(comm):
        seen = []
        comm.Set_errhandler(Errhandler(
            lambda c, code: seen.append((c.name, code))))
        with pytest.raises(ValueError):
            comm.Send(np.zeros(1), dest=99)
        assert seen == [("MPI_COMM_WORLD", errhandler.ERR_RANK)]
        # dup carries the handler over
        d = comm.dup()
        assert d.Get_errhandler().fn is not None
        return True

    assert run_ranks(2, fn) == [True, True]


def test_errors_are_fatal_aborts():
    """FATAL routes through rte.abort → SystemExit in thread worlds."""
    def fn(comm):
        if comm.rank == 0:
            comm.Set_errhandler(ERRORS_ARE_FATAL)
            try:
                comm.Send(np.zeros(1), dest=99)
            except SystemExit:
                return "aborted"
            return "no-abort"
        return "peer"

    res = run_ranks(1, fn)
    assert res == ["aborted"]


def test_call_errhandler_explicit():
    def fn(comm):
        hits = []
        comm.Set_errhandler(Errhandler(lambda c, code: hits.append(code)))
        with pytest.raises(MPIException):
            comm.Call_errhandler(errhandler.ERR_IO)
        assert hits == [errhandler.ERR_IO]
        return True

    assert run_ranks(1, fn) == [True]


# ---- attributes -----------------------------------------------------

def test_predefined_world_attrs():
    def fn(comm):
        flag, tag_ub = comm.Get_attr(attrs.TAG_UB)
        assert flag and tag_ub == 2**31 - 1
        flag, us = comm.Get_attr(attrs.UNIVERSE_SIZE)
        assert flag and us == comm.size
        return True

    assert run_ranks(2, fn) == [True, True]


def test_keyval_copy_delete_callbacks():
    def fn(comm):
        log = []
        kv = attrs.create_keyval(
            copy_fn=lambda obj, k, extra, v: v * 2,
            delete_fn=lambda obj, k, v, extra: log.append(("del", v)),
            extra_state="xs")
        comm.Set_attr(kv, 21)
        assert comm.Get_attr(kv) == (True, 21)
        d = comm.dup()
        assert d.Get_attr(kv) == (True, 42)  # copy callback ran
        # overwrite runs the delete callback on the old value
        comm.Set_attr(kv, 5)
        assert ("del", 21) in log
        d.free()  # delete_all on free
        assert ("del", 42) in log
        comm.Delete_attr(kv)
        assert ("del", 5) in log
        assert comm.Get_attr(kv) == (False, None)
        attrs.free_keyval(kv)
        return True

    assert run_ranks(2, fn) == [True, True]


def test_null_copy_fn_not_propagated():
    def fn(comm):
        kv = attrs.create_keyval()  # MPI_NULL_COPY_FN
        comm.Set_attr(kv, "private")
        d = comm.dup()
        assert d.Get_attr(kv) == (False, None)
        return True

    assert run_ranks(1, fn) == [True]


def test_invalid_keyval_rejected():
    def fn(comm):
        with pytest.raises(ValueError):
            comm.Set_attr(424242, 1)
        return True

    assert run_ranks(1, fn) == [True]


# ---- info -----------------------------------------------------------

def test_info_basic():
    inf = Info()
    inf.set("cb_buffer_size", "1048576")
    inf.set("striping_factor", "4")
    assert inf.get("cb_buffer_size") == (True, "1048576")
    assert inf.get("nope") == (False, None)
    assert inf.nkeys() == 2
    assert inf.nthkey(0) == "cb_buffer_size"
    d = inf.dup()
    inf.delete("striping_factor")
    assert inf.nkeys() == 1 and d.nkeys() == 2
    with pytest.raises(KeyError):
        inf.delete("striping_factor")


def test_info_limits():
    inf = Info()
    with pytest.raises(ValueError):
        inf.set("", "v")
    with pytest.raises(ValueError):
        inf.set("k" * 300, "v")


def test_info_env():
    inf = info_env()
    assert inf.get("thread_level")[0]
    assert inf.get("host")[0]


def test_comm_set_get_info():
    def fn(comm):
        inf = Info()
        inf.set("hint", "on")
        comm.Set_info(inf)
        got = comm.Get_info()
        assert got.get("hint") == (True, "on")
        d = comm.dup()
        assert d.Get_info().get("hint") == (True, "on")
        return True

    assert run_ranks(1, fn) == [True]


def test_info_threads_into_file_open(tmp_path):
    def fn(comm):
        from ompi_tpu.io import file as iomod
        inf = Info()
        inf.set("cb_buffer_size", "65536")
        f = iomod.open(comm, str(tmp_path / "t.bin"),
                       iomod.MODE_CREATE | iomod.MODE_RDWR, info=inf)
        assert f.info["cb_buffer_size"] == "65536"
        assert f.Get_errhandler() is ERRORS_RETURN
        f.close()
        return True

    assert run_ranks(2, fn) == [True, True]


# ---- flat bindings --------------------------------------------------

def test_flat_bindings_surface():
    assert mpi.MPI_Error_string(mpi.MPI_ERR_RANK) == "MPI_ERR_RANK"
    assert mpi.MPI_Error_class(mpi.MPI_ERR_IO) == mpi.MPI_ERR_IO
    inf = mpi.MPI_Info_create()
    mpi.MPI_Info_set(inf, "a", "b")
    assert mpi.MPI_Info_get(inf, "a") == (True, "b")
    assert mpi.MPI_Info_get_nkeys(inf) == 1
    kv = mpi.MPI_Comm_create_keyval()
    assert kv > 0
    mpi.MPI_Comm_free_keyval(kv)
    assert callable(mpi.PMPI_Info_set)  # PMPI aliases cover new names


# ---- r3 advisor regressions ----------------------------------------

def test_errhandler_inherited_by_split_create_group():
    """MPI: newly created communicators inherit the parent's error
    handler (not just dup)."""
    def fn(comm):
        h = Errhandler(lambda c, code: None)
        comm.Set_errhandler(h)
        from ompi_tpu.comm.communicator import Group
        sub = comm.split(0, comm.rank)
        assert sub.Get_errhandler() is h
        cg = comm.create_group(Group(list(range(comm.size))))
        assert cg.Get_errhandler() is h
        cr = comm.create(Group(list(range(comm.size))))
        assert cr.Get_errhandler() is h
        for c in (sub, cg, cr):
            c.free()
        return True

    assert run_ranks(2, fn) == [True, True]


def test_errhandler_inherited_by_intercomm_and_merge():
    def fn(comm):
        from ompi_tpu.comm.intercomm import intercomm_create
        h = Errhandler(lambda c, code: None)
        comm.Set_errhandler(h)
        low = comm.rank < 1
        local = comm.split(0 if low else 1)
        local.Set_errhandler(h)
        inter = intercomm_create(local, 0, comm, 1 if low else 0)
        assert inter.Get_errhandler() is h
        merged = inter.merge(high=not low)
        assert merged.Get_errhandler() is h
        return True

    assert run_ranks(2, fn) == [True, True]


def test_keyval_free_deferred_while_attached():
    """free_keyval while values are attached must defer: later dup
    still runs the copy callback; final delete runs the delete
    callback; the entry disappears only when the last value is gone."""
    events = []

    class Obj:
        def __init__(self):
            self.attrs = {}

    kv = attrs.create_keyval(
        copy_fn=lambda o, k, extra, v: v + 1,
        delete_fn=lambda o, k, v, extra: events.append(("del", v)))
    a = Obj()
    attrs.set_attr(a, kv, 10)
    attrs.free_keyval(kv)          # deferred: still attached to a
    b = Obj()
    attrs.copy_all(a, b)           # copy callback must still run
    assert b.attrs[kv] == 11
    # attaching NEW values through a freed keyval is erroneous
    with pytest.raises(ValueError):
        attrs.set_attr(Obj(), kv, 1)
    attrs.delete_all(a)
    attrs.delete_all(b)
    assert ("del", 10) in events and ("del", 11) in events
    # now fully released: the keyval is gone
    with pytest.raises(ValueError):
        attrs.set_attr(Obj(), kv, 1)


# ---- mpi_errhandler_world_default (PR 4) ---------------------------

def test_world_default_fatal_param():
    """--mca mpi_errhandler_world_default fatal restores the reference
    C default: the predefined comms come up FATAL, derived comms
    inherit it, and an error aborts via the rte (SystemExit in thread
    worlds)."""
    from ompi_tpu.mca.params import registry
    prior = registry.get("mpi_errhandler_world_default", "return")
    registry.set("mpi_errhandler_world_default", "fatal")
    try:
        def fn(comm):
            assert comm.Get_errhandler() is ERRORS_ARE_FATAL
            d = comm.dup()
            assert d.Get_errhandler() is ERRORS_ARE_FATAL
            try:
                comm.Send(np.zeros(1), dest=99)
            except SystemExit:
                return "aborted"
            return "no-abort"

        assert run_ranks(1, fn) == ["aborted"]
    finally:
        registry.set("mpi_errhandler_world_default", prior)


def test_handlerless_object_resolves_through_world():
    """An errhandler-less MPI object dispatches through COMM_WORLD's
    installed handler (OMPI_ERRHANDLER_INVOKE(NULL, ...) analog), not
    straight to the compiled-in default."""
    def fn(comm):
        hits = []
        comm.Set_errhandler(Errhandler(
            lambda obj, code: hits.append(code)))

        class Bare:  # e.g. a window/file before its handler is set
            state = comm.state

        with pytest.raises(MPIException):
            errhandler.dispatch(Bare(), MPIException(errhandler.ERR_IO))
        assert hits == [errhandler.ERR_IO]
        return True

    assert run_ranks(1, fn) == [True]


def test_keyval_free_unattached_is_immediate():
    class Obj:
        def __init__(self):
            self.attrs = {}

    kv = attrs.create_keyval()
    attrs.free_keyval(kv)
    with pytest.raises(ValueError):
        attrs.set_attr(Obj(), kv, 1)
