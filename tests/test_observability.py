"""Observability stack: PERUSE-analog request events, memchecker
buffer-validity checks, the MPIR-analog proctable + stack attach,
and mpisync clock offsets."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ompi_tpu import memchecker, peruse
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_peruse():
    yield
    peruse.unsubscribe_all()
    registry.set("opal_memchecker_enable", False)


def test_peruse_request_lifecycle_events():
    events = []
    for ev in peruse.EVENTS:
        peruse.subscribe(ev, lambda e, **kw: events.append((e, kw)))

    def fn(comm):
        x = np.array([comm.rank], np.int64)
        y = np.empty(1, np.int64)
        nxt = (comm.rank + 1) % comm.size
        prv = (comm.rank - 1) % comm.size
        rq = comm.Irecv(y, prv, tag=5)
        comm.Send(x, nxt, tag=5)
        rq.wait()

    run_ranks(2, fn)
    kinds = {e for e, _ in events}
    assert "req_activate" in kinds
    assert "req_complete" in kinds
    # both send and recv activations observed, with byte counts
    acts = [kw for e, kw in events if e == "req_activate"]
    assert {a["kind"] for a in acts} == {"send", "recv"}
    assert all(a["bytes"] == 8 for a in acts)
    # a message arriving before its recv is posted queues unexpected
    assert any(e == "req_match_unex" for e, _ in events) or \
        any(e == "req_match" for e, _ in events)


def test_peruse_disabled_costs_nothing():
    assert not peruse.enabled
    fired = []
    peruse.subscribe("req_complete", lambda e, **kw: fired.append(1))
    peruse.unsubscribe_all()
    assert not peruse.enabled


def test_memchecker_poisons_recv_buffer():
    registry.set("opal_memchecker_enable", True)

    def fn(comm):
        if comm.rank == 0:
            y = np.zeros(4, np.uint8)
            rq = comm.Irecv(y, 1, tag=9)
            # posted but unmatched: buffer must hold the poison
            # pattern, not stale zeros
            poisoned = bytes(y) == bytes([memchecker.POISON] * 4)
            comm.Send(np.zeros(1, np.uint8), 1, tag=8)  # release peer
            rq.wait()
            assert bytes(y) == b"\x07\x07\x07\x07"
            return poisoned
        comm.Recv(np.empty(1, np.uint8), 0, tag=8)
        comm.Send(np.full(4, 7, np.uint8), 0, tag=9)
        return True

    assert all(run_ranks(2, fn))


def test_memchecker_catches_modified_send_buffer():
    registry.set("opal_memchecker_enable", True)
    big = 1024 * 1024  # above inproc eager limit: rendezvous

    def fn(comm):
        if comm.rank == 0:
            x = np.zeros(big, np.uint8)
            rq = comm.state.pml.isend(
                x, big, _u8(), 1, 11, comm)
            x[0] = 99  # illegal: buffer owned by an active request
            try:
                while not rq.complete:
                    comm.state.progress.progress()
                return False  # memchecker should have raised
            except RuntimeError as e:
                return "modified" in str(e)
        y = np.empty(big, np.uint8)
        comm.Recv(y, 0, tag=11)
        return True

    def _u8():
        from ompi_tpu.datatype import engine as dt
        return dt.BYTE

    assert all(run_ranks(2, fn))


def test_proctable_and_stack_attach():
    """mpirun publishes the MPIR-analog proctable; attach --stacks
    makes a hung rank dump its threads."""
    import tempfile
    import textwrap
    import time

    with tempfile.TemporaryDirectory() as d:
        prog = os.path.join(d, "hang.py")
        with open(prog, "w") as f:
            f.write(textwrap.dedent("""
                import os, sys, time
                import ompi_tpu
                comm = ompi_tpu.init()
                print("SESSION", os.environ["TPUMPI_SESSION_DIR"],
                      flush=True)
                time.sleep(30)
                ompi_tpu.finalize()
            """))
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
             "--timeout", "25", prog],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        try:
            session = None
            for _ in range(200):
                line = p.stdout.readline()
                if line.startswith("SESSION"):
                    session = line.split()[1]
                    break
            assert session, "ranks never reported their session dir"
            table_path = os.path.join(session, "proctable.json")
            for _ in range(100):
                if os.path.exists(table_path):
                    break
                time.sleep(0.05)
            table = json.load(open(table_path))
            assert len(table) == 2
            assert all("pid" in e and "tag" in e for e in table)
            # attach --stacks: every rank dumps its stacks to stderr
            r = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.attach",
                 session, "--stacks"],
                capture_output=True, text=True, timeout=30, env=env,
                cwd=REPO)
            assert r.returncode == 0, r.stderr
            assert "signalled 2/2" in r.stdout
        finally:
            p.terminate()
            out, err = p.communicate(timeout=30)
        # the SIGUSR1 faulthandler wrote tracebacks into job stderr
        assert "Traceback" in err or "Current thread" in err, err


def test_mpisync_reports_offsets():
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "3",
         "--timeout", "90",
         os.path.join(REPO, "ompi_tpu", "tools", "mpisync.py"),
         "--rounds", "10"],
        capture_output=True, text=True, timeout=150,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO)
    assert r.returncode == 0, r.stderr
    last = r.stdout.strip().splitlines()[-1]
    data = json.loads(last)
    assert len(data["offsets_us"]) == 3
    assert data["rtts_us"][1] > 0 and data["rtts_us"][2] > 0
    # same-host clocks: offsets bounded by a loose sanity envelope
    assert all(abs(o) < 5e6 for o in data["offsets_us"])


@pytest.mark.skipif(sys.platform != "linux",
                    reason="pstat scrapes Linux /proc")
def test_pstat_snapshot_and_pvars():
    """opal/mca/pstat analog: /proc stats + live MPI_T pvars."""
    from ompi_tpu.runtime import pstat

    st = pstat.snapshot()
    assert st, "Linux /proc scrape failed"
    assert st["rss_mb"] > 0 and st["threads"] >= 1
    assert st["utime_s"] >= 0

    def fn(comm):
        pv = next(p for p in registry.all_pvars()
                  if p.full_name == f"opal_pstat_rss_mb_r{comm.rank}")
        return pv.read() > 0

    assert all(run_ranks(2, fn))


def test_notifier_file_sink(tmp_path):
    """orte/mca/notifier analog: events route to configured sinks;
    default is off."""
    from ompi_tpu.runtime import notifier

    log = tmp_path / "events.log"
    registry.set("orte_notifier_sinks", f"file:{log}")
    try:
        notifier.notify("error", "job-x", "rank 3 exploded")
        notifier.notify("bogus-severity", "job-x", "still logged")
    finally:
        registry.set("orte_notifier_sinks", "")
    lines = log.read_text().splitlines()
    assert len(lines) == 2
    assert "error job=job-x rank 3 exploded" in lines[0]
    assert "notice" in lines[1]  # unknown severity mapped to notice
    # default (empty) sinks: no-op, never raises
    notifier.notify("error", "job-x", "dropped")
