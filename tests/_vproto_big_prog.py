"""Replay of a logged payload LARGER than the shm ring (ADVICE r4):
rank 0 sends 12 MiB (> the 8 MiB default btl_shm_ring_size), then
gratuitously replays its whole log.  Pre-fix, replay pushed one raw
MATCH frame and Ring.push raised 'frame can never fit'; now the
payload rides position-addressed MSEG segments and the receiver
drops the assembled duplicate (consumed sequence number)."""
import numpy as np

import ompi_tpu
from ompi_tpu.pml.vprotocol import find

comm = ompi_tpu.init()
v = find(comm.state.pml)
assert v is not None, "launch with --mca pml_vprotocol pessimist"
N = 12 * 1024 * 1024 // 8
if comm.rank == 0:
    comm.Send(np.arange(N, dtype=np.float64), dest=1, tag=5)
    comm.Barrier()
    assert v.replay() >= 1
    comm.Barrier()
    print("vproto big ok", flush=True)
else:
    got = np.empty(N)
    comm.Recv(got, source=0, tag=5)
    assert got[0] == 0.0 and got[-1] == N - 1
    comm.Barrier()   # sender replays now
    comm.Barrier()   # sender done replaying
    comm.state.progress.progress()
    # the assembled duplicate must have been dropped, not re-matched
    assert comm.Iprobe(source=0, tag=5) in (False, None), \
        "duplicate redelivery of replayed large message"
ompi_tpu.finalize()
