"""Checkpoint/restart tests (ref: the reference C/R stack — crs +
crcp/bkmrk + snapc/full + sstore + orte-checkpoint/restart; SURVEY §5
checkpoint row).  End-to-end: kill a job mid-iteration, restart from
the store, identical results."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from ompi_tpu import cr
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- sstore analog: layout, atomicity ------------------------------

def test_store_latest_complete_and_pruning(tmp_path):
    st = cr.Store(str(tmp_path))
    assert st.latest_complete() is None
    for seq in range(3):
        st.write_rank(seq, 0, {"payload": seq})
        st.mark_complete(seq, {"nprocs": 1, "seq": seq})
    # an incomplete newest dir (no meta.json) must be ignored
    st.write_rank(3, 0, {"payload": 3})
    assert st.latest_complete() == 2
    assert st.read_rank(2, 0)["payload"] == 2
    st.prune(keep=1)
    assert st.latest_complete() == 2
    assert not os.path.exists(st.seq_path(0))
    assert not os.path.exists(st.seq_path(1))
    # the incomplete dir is never pruned (it may be mid-write)
    assert os.path.exists(st.seq_path(3))


def test_store_rank_write_is_atomic(tmp_path):
    st = cr.Store(str(tmp_path))
    st.write_rank(0, 0, {"payload": 1})
    # no temp droppings
    assert all(not f.startswith(".")
               for f in os.listdir(st.seq_path(0)))


# ---- quiesce + snapshot-carried messages ---------------------------

def test_quiesce_carries_unreceived_eager(tmp_path):
    d = str(tmp_path)

    def fn(comm):
        if comm.rank == 0:
            comm.Send(np.array([42.0]), dest=1, tag=5)
        seq = cr.checkpoint(comm, {"step": 7}, store_dir=d)
        assert seq == 0
        os.environ[cr.ENV_RESTART] = "1"
        try:
            got = cr.restore(comm, store_dir=d)
        finally:
            os.environ.pop(cr.ENV_RESTART, None)
        assert got == {"step": 7}
        if comm.rank == 1:
            r = np.empty(1)
            comm.Recv(r, source=0, tag=5)
            assert r[0] == 42.0
        comm.Barrier()
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_device_array_payload_roundtrip(tmp_path):
    d = str(tmp_path)

    def fn(comm):
        import jax.numpy as jnp
        x = jnp.arange(8.0) * (comm.rank + 1)
        cr.checkpoint(comm, {"x": x, "nested": [x, (1, x)]},
                      store_dir=d)
        os.environ[cr.ENV_RESTART] = "1"
        try:
            got = cr.restore(comm, store_dir=d)
        finally:
            os.environ.pop(cr.ENV_RESTART, None)
        import jax
        assert isinstance(got["x"], jax.Array)
        assert np.allclose(np.asarray(got["x"]),
                           np.arange(8.0) * (comm.rank + 1))
        assert np.allclose(np.asarray(got["nested"][1][1]),
                           np.arange(8.0) * (comm.rank + 1))
        return True

    assert run_ranks(2, fn, devices=True) == [True, True]


def test_restore_topology_mismatch_raises(tmp_path):
    d = str(tmp_path)

    def write(comm):
        cr.checkpoint(comm, {"a": 1}, store_dir=d)
        return True

    assert run_ranks(2, write) == [True, True]
    # doctor the metadata to claim a different world size
    st = cr.Store(d)
    seq = st.latest_complete()
    meta = st.read_meta(seq)
    meta["nprocs"] = 5
    st.mark_complete(seq, meta)

    def read(comm):
        os.environ[cr.ENV_RESTART] = "1"
        try:
            with pytest.raises(RuntimeError, match="topology mismatch"):
                cr.restore(comm, store_dir=d)
        finally:
            os.environ.pop(cr.ENV_RESTART, None)
        return True

    assert run_ranks(2, read) == [True, True]


def test_shmem_heap_snapshot(tmp_path):
    d = str(tmp_path)

    def fn(comm):
        from ompi_tpu.shmem import ShmemCtx
        ctx = ShmemCtx(comm, heap_size=4096)
        arr = ctx.malloc((8,), np.float64)
        arr.local[:] = comm.rank + 0.5
        ctx.barrier_all()
        cr.checkpoint(comm, None, store_dir=d, shmem_ctx=ctx)
        arr.local[:] = -1.0  # clobber, then restore
        os.environ[cr.ENV_RESTART] = "1"
        try:
            cr.restore(comm, store_dir=d, shmem_ctx=ctx)
        finally:
            os.environ.pop(cr.ENV_RESTART, None)
        assert np.all(arr.local == comm.rank + 0.5)
        ctx.finalize()
        return True

    assert run_ranks(2, fn) == [True, True]


# ---- end-to-end: crash mid-job, restart, identical results ---------

def _run(cmd, env=None, timeout=240):
    full_env = dict(os.environ)
    full_env["PYTHONPATH"] = REPO + os.pathsep + \
        full_env.get("PYTHONPATH", "")
    if env:
        full_env.update(env)
    return subprocess.run(cmd, capture_output=True, env=full_env,
                          timeout=timeout)


def test_checkpoint_kill_restart_under_mpirun(tmp_path):
    prog = os.path.join(REPO, "tests", "_ckpt_prog.py")
    store = str(tmp_path / "store")
    # 1) uninterrupted reference run (its own store)
    ref = _run([sys.executable, "-m", "ompi_tpu.tools.mpirun",
                "-np", "4", "--ckpt-dir", str(tmp_path / "ref"), prog])
    assert ref.returncode == 0, ref.stderr.decode()
    ref_line = [ln for ln in ref.stdout.decode().splitlines()
                if ln.startswith("final ")][0]

    # 2) crashing run: rank 2 dies after the step-5 checkpoint
    r1 = _run([sys.executable, "-m", "ompi_tpu.tools.mpirun",
               "-np", "4", "--ckpt-dir", store, prog],
              env={"CKPT_CRASH_AT": "5"})
    assert r1.returncode != 0
    assert cr.Store(store).latest_complete() is not None

    # 3) restart via the orte-restart analog: resumes and completes
    r2 = _run([sys.executable, "-m", "ompi_tpu.tools.restart", store])
    assert r2.returncode == 0, r2.stderr.decode()
    line = [ln for ln in r2.stdout.decode().splitlines()
            if ln.startswith("final ")][0]
    assert "resumed=True" in line
    # identical final state to the uninterrupted run
    assert line.replace("resumed=True", "resumed=False") == ref_line
    # job.json recorded the launch for the restart tool
    job = json.load(open(os.path.join(store, "job.json")))
    assert job["np"] == 4


def test_errmgr_restart_policy_auto_recovers(tmp_path):
    """Elastic-recovery slice (VERDICT r3 #6): with the MCA-selected
    errmgr restart policy, a SIGKILL'd rank mid-run leads to an
    automatic relaunch from the latest complete snapshot and the job
    completes with the uninterrupted run's results."""
    prog = os.path.join(REPO, "tests", "_ckpt_prog.py")
    store = str(tmp_path / "store")
    ref = _run([sys.executable, "-m", "ompi_tpu.tools.mpirun",
                "-np", "4", "--ckpt-dir", str(tmp_path / "ref"), prog])
    assert ref.returncode == 0, ref.stderr.decode()
    ref_line = [ln for ln in ref.stdout.decode().splitlines()
                if ln.startswith("final ")][0]

    r = _run([sys.executable, "-m", "ompi_tpu.tools.mpirun",
              "-np", "4", "--ckpt-dir", store, "--verbose", "state",
              "--mca", "errmgr_base_policy", "restart", prog],
             env={"CKPT_CRASH_AT": "5"})
    err = r.stderr.decode()
    assert r.returncode == 0, err[-2000:]
    assert "DRAINING -> RESTARTING" in err
    line = [ln for ln in r.stdout.decode().splitlines()
            if ln.startswith("final ")][0]
    assert "resumed=True" in line
    assert line.replace("resumed=True", "resumed=False") == ref_line


def test_store_compression_roundtrip_and_back_compat(tmp_path):
    """Images gzip by default (format marker), shrink compressible
    payloads, and pre-compression raw images still read."""
    import pickle as _pickle

    from ompi_tpu.mca.params import registry

    store = cr.Store(str(tmp_path))
    blob = {"payload": np.zeros(64 * 1024, dtype=np.float64),
            "pml_msgs": []}
    store.write_rank(1, 0, blob)
    path = os.path.join(store.seq_path(1), "rank_0.ckpt")
    size_gz = os.path.getsize(path)
    got = store.read_rank(1, 0)
    assert np.array_equal(got["payload"], blob["payload"])
    # compressible payload shrinks by a lot
    assert size_gz < 64 * 1024 * 8 / 4, size_gz

    # raw (pre-marker) image: written uncompressed, still readable
    registry.set("cr_base_compress", False)
    try:
        store.write_rank(2, 0, blob)
        assert os.path.getsize(
            os.path.join(store.seq_path(2), "rank_0.ckpt")) > 64 * 1024
        got = store.read_rank(2, 0)
        assert np.array_equal(got["payload"], blob["payload"])
    finally:
        registry.set("cr_base_compress", True)

    # hand-written legacy raw file (no marker)
    with open(os.path.join(store.seq_path(1), "rank_9.ckpt"),
              "wb") as f:
        _pickle.dump(blob, f)
    got = store.read_rank(1, 9)
    assert np.array_equal(got["payload"], blob["payload"])


def test_migrate_moves_ranks_to_other_nodes(tmp_path):
    """orte-migrate analog (VERDICT r4 missing #4): kill a simulated
    multi-node job mid-run, restart with a rank MOVED to a different
    node via ompi_tpu.tools.migrate; the job resumes from the latest
    snapshot on the new placement and produces the identical final
    state (ref: orte/tools/orte-migrate/orte-migrate.c:1)."""
    prog = os.path.join(REPO, "tests", "_ckpt_prog.py")
    store = str(tmp_path / "store")
    # crashing run on 3 simulated nodes (byslot: rank 2 on sim2)
    r1 = _run([sys.executable, "-m", "ompi_tpu.tools.mpirun",
               "-np", "3", "--simulate-nodes", "3x1",
               "--ranks-per-proc", "1",
               "--ckpt-dir", store, prog],
              env={"CKPT_CRASH_AT": "4"})
    assert r1.returncode != 0
    assert cr.Store(store).latest_complete() is not None

    # migrate rank 2 off its node onto sim0
    r2 = _run([sys.executable, "-m", "ompi_tpu.tools.migrate",
               store, "--move", "2=sim0"])
    out = r2.stdout.decode()
    assert r2.returncode == 0, out[-800:] + r2.stderr.decode()[-2000:]
    line = [ln for ln in out.splitlines() if ln.startswith("final ")][0]
    assert "resumed=True" in line
    # the moved rank really runs on its new node
    assert "rank 2 on node sim0" in out, out[-1200:]
    # placement independence: identical result to an uninterrupted run
    ref = _run([sys.executable, "-m", "ompi_tpu.tools.mpirun",
                "-np", "3", "--ranks-per-proc", "1",
                "--ckpt-dir", str(tmp_path / "ref"), prog])
    ref_line = [ln for ln in ref.stdout.decode().splitlines()
                if ln.startswith("final ")][0]
    assert line.replace("resumed=True", "resumed=False") == ref_line
