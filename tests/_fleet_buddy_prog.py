"""Off-host buddy-restore workload (run by test_fleet.py): on a
2-host DVM pool, every rank buddy-checkpoints with degree 1 and
asserts the failure-domain-aware ring actually placed its replica on
the OTHER host.  Host 1's ranks then drop their own copies — the
in-process stand-in for "host 1 died and its replacements came back
empty" — and the collective restore must serve them from the
off-host partners that survived.

argv: tag

Every rank prints ``BUDDY {tag} {rank} OK`` after verifying the
restored payload; the test asserts one line per rank and exit 0.
"""
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.cr import buddy

tag = sys.argv[1]

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size

nodes = buddy._rank_nodes(comm)
assert len(set(nodes)) > 1, (
    f"pool did not band ranks across hosts: {nodes}")
my_node = nodes[rank]

payload = {"rank": rank,
           "vec": np.full(16, float(rank + 1), np.float64)}
seq = buddy.checkpoint(comm, payload, degree=1)
assert seq >= 0, "buddy checkpoint did not commit"

bs = comm.state.extra["cr_buddy"]
# placement proof: every copy this rank holds belongs to an OFF-host
# owner — one dead host can never take a rank and its replica together
for owner, s in bs["held"]:
    assert nodes[owner] != my_node, (
        f"rank {rank} (host {my_node}) holds rank {owner}'s copy but "
        f"they share a host — placement is not domain-aware")

# host 1 dies: its ranks lose their own in-memory state
if my_node == 1:
    bs["self"].clear()

out = buddy.restore(comm)
assert out is not None, "restore found nothing committed"
assert int(out["rank"]) == rank
assert np.array_equal(np.asarray(out["vec"]),
                      np.full(16, float(rank + 1), np.float64))

# one atomic write: rank-threads share the session stdout buffer and
# print()'s separate text/newline writes interleave across ranks
sys.stdout.write(f"BUDDY {tag} {rank} OK\n")
sys.stdout.flush()
ompi_tpu.finalize()
