"""OpenSHMEM-analog layer tests (ref: oshmem §2.7 — memheap symmetric
allocation, spml put/get, atomic, scoll; examples ring_oshmem.c)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ompi_tpu import shmem
from ompi_tpu.testing import run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def shmem_ranks(n, fn, devices=False):
    """Thread-rank harness with a per-thread shmem ctx.  devices=True
    gives each rank a jax device, so osc selection mints the
    device-heap ctx (ctx.device True)."""
    def wrapped(comm):
        ctx = shmem.init(comm)
        try:
            return fn(ctx, comm)
        finally:
            shmem.finalize()

    return run_ranks(n, wrapped, devices=devices)


# ---- memheap --------------------------------------------------------

def test_symmetric_offsets_and_views():
    def fn(ctx, comm):
        a = ctx.malloc(16, np.float64)
        b = ctx.malloc((4, 4), np.int32)
        assert a.local.shape == (16,) and b.local.shape == (4, 4)
        # symmetry: identical offsets on every PE
        return (a.offset, b.offset)

    res = shmem_ranks(4, fn)
    assert len(set(res)) == 1


def test_malloc_free_reuse_and_exhaustion():
    def fn(ctx, comm):
        a = ctx.malloc(1024, np.uint8)
        off_a = a.offset
        ctx.free(a)
        b = ctx.malloc(512, np.uint8)
        assert b.offset == off_a  # first-fit reuses the hole
        with pytest.raises(MemoryError):
            ctx.malloc(ctx.heap_size * 2, np.uint8)
        return True

    assert shmem_ranks(1, fn) == [True]


# ---- put/get/p/g ----------------------------------------------------

def test_put_get_roundtrip():
    def fn(ctx, comm):
        me, n = comm.rank, comm.size
        x = ctx.malloc(8, np.int64)
        x.local[:] = -1
        ctx.barrier_all()
        right = (me + 1) % n
        ctx.put(x, np.full(8, me, dtype=np.int64), right)
        ctx.barrier_all()
        left = (me - 1) % n
        assert (x.local == left).all()
        got = ctx.get(x, right)  # read my right neighbor's memory
        assert (got == me).all()
        return True

    assert shmem_ranks(4, fn) == [True] * 4


def test_p_g_single_element():
    def fn(ctx, comm):
        x = ctx.malloc(4, np.float64)
        x.local[:] = 0
        ctx.barrier_all()
        ctx.p(x, 2, 3.5, (comm.rank + 1) % comm.size)
        ctx.barrier_all()
        assert x.local[2] == 3.5
        assert ctx.g(x, 2, (comm.rank + 1) % comm.size) == 3.5
        return True

    assert shmem_ranks(3, fn) == [True] * 3


def test_wait_until():
    def fn(ctx, comm):
        flag = ctx.malloc(1, np.int64)
        flag.local[0] = 0
        ctx.barrier_all()
        if comm.rank == 0:
            for peer in range(1, comm.size):
                ctx.p(flag, 0, 7, peer)
            ctx.quiet()
        else:
            ctx.wait_until(flag, 0, "eq", 7)
        ctx.barrier_all()
        return True

    assert shmem_ranks(3, fn) == [True] * 3


# ---- atomics --------------------------------------------------------

def test_atomics_counter_and_cas():
    def fn(ctx, comm):
        me, n = comm.rank, comm.size
        ctr = ctx.malloc(1, np.int64)
        ctr.local[0] = 0
        ctx.barrier_all()
        t = ctx.atomic_fetch_inc(ctr, 0, 0)
        ctx.barrier_all()
        if me == 0:
            assert ctr.local[0] == n
        ctx.barrier_all()
        # cas: exactly one PE wins the 100 -> me race
        tgt = ctx.malloc(1, np.int64)
        tgt.local[0] = 100
        ctx.barrier_all()
        old = ctx.atomic_compare_swap(tgt, 0, 100, me + 1000, 0)
        wins = ctx.malloc(n, np.int64)
        mine = ctx.malloc(1, np.int64)
        mine.local[0] = 1 if old == 100 else 0
        ctx.collect(wins, mine)
        assert wins.local.sum() == 1
        # swap returns previous value
        sw = ctx.malloc(1, np.int64)
        sw.local[0] = 5
        ctx.barrier_all()
        if me == 0:
            prev = ctx.atomic_swap(sw, 0, 9, 0)
            assert prev == 5 and sw.local[0] == 9
        return int(t)

    res = shmem_ranks(4, fn)
    assert sorted(res) == list(range(4))  # distinct tickets


# ---- collectives ----------------------------------------------------

def test_scoll_broadcast_collect_reduce():
    def fn(ctx, comm):
        me, n = comm.rank, comm.size
        src = ctx.malloc(2, np.float64)
        dst = ctx.malloc(2, np.float64)
        src.local[:] = me + 1
        ctx.broadcast(dst, src, root=1)
        assert (dst.local == 2.0).all()
        allv = ctx.malloc(2 * n, np.float64)
        ctx.collect(allv, src)
        assert allv.local[::2].tolist() == [r + 1 for r in range(n)]
        total = ctx.malloc(2, np.float64)
        ctx.sum_to_all(total, src)
        assert (total.local == sum(range(1, n + 1))).all()
        mx = ctx.malloc(2, np.float64)
        ctx.max_to_all(mx, src)
        assert (mx.local == n).all()
        return True

    assert shmem_ranks(3, fn) == [True] * 3


# ---- process-rank examples (the VERDICT gate: thread AND process) ---

def _mpirun(np_, prog):
    from ompi_tpu.testing import mpirun_run
    # generous timeouts: these run late in the suite on a loaded
    # 1-core CI box where process launch + window setup can crawl
    return mpirun_run(np_, os.path.join("examples", prog),
                      timeout=480, job_timeout=420)


def test_shmem_ring_example_procs():
    r = _mpirun(4, "shmem_ring.py")
    assert r.returncode == 0, r.stderr.decode()
    assert "PE 0 ended with 45" in r.stdout.decode()


def test_shmem_atomics_example_procs():
    r = _mpirun(4, "shmem_atomics.py")
    assert r.returncode == 0, r.stderr.decode()
    assert "4 tickets, acc=10" in r.stdout.decode()


# ---- memheap framework (buddy + firstfit components) ----------------

def test_buddy_allocator_split_coalesce():
    from ompi_tpu.shmem.memheap import Buddy

    b = Buddy(1 << 16)
    a1 = b.malloc(1000)   # order 10
    a2 = b.malloc(1000)
    a3 = b.malloc(100)    # order 7
    assert len({a1, a2, a3}) == 3
    # buddies coalesce back: after freeing everything a full-heap
    # allocation succeeds again
    b.free(a2)
    b.free(a1)
    b.free(a3)
    big = b.malloc((1 << 16) - 1)
    assert big == 0
    b.free(big)
    # determinism: a replayed sequence yields identical offsets
    c = Buddy(1 << 16)
    assert [c.malloc(1000), c.malloc(1000), c.malloc(100)] == \
        [a1, a2, a3]


def test_buddy_nonpow2_heap_covered_by_top_blocks():
    from ompi_tpu.shmem.memheap import Buddy

    size = (1 << 16) + (1 << 12) + 64
    b = Buddy(size)
    total = 0
    seen = set()
    while True:
        try:
            off = b.malloc(64)
        except MemoryError:
            break
        assert off + 64 <= size
        assert off not in seen
        seen.add(off)
        total += 64
    assert total == size  # every byte reachable, none past the end


def test_memheap_component_selection():
    from ompi_tpu.mca.params import registry
    from ompi_tpu.shmem import memheap

    assert memheap.select(1 << 12).name == "buddy"
    registry.set("shmem_memheap_allocator", "firstfit")
    try:
        assert memheap.select(1 << 12).name == "firstfit"
    finally:
        registry.set("shmem_memheap_allocator", "buddy")


def test_allocator_checkpoint_state_roundtrip():
    from ompi_tpu.shmem import memheap

    b = memheap.select(1 << 14)
    keep = b.malloc(500)
    tmp = b.malloc(700)
    b.free(tmp)
    st = b.state()
    r = memheap.restore(st, 1 << 14)
    # restored allocator continues identically to the original
    assert r.malloc(300) == b.malloc(300)
    r.free(keep)
    b.free(keep)
    assert r.state() == b.state()


# ---- scoll-over-coll reuse ------------------------------------------

def test_scoll_rides_the_comm_coll_stack():
    """The scoll/mpi module must delegate to comm.coll: the count of
    comm-level collective calls grows with each shmem collective
    (scoll-over-coll reuse, ref: oshmem/mca/scoll/mpi)."""
    def fn(ctx, comm):
        assert ctx.scoll.name == "mpi"
        calls = []
        orig = comm.Allreduce

        def counted(*a, **k):
            calls.append(1)
            return orig(*a, **k)

        comm.Allreduce = counted
        try:
            s = ctx.malloc(4, np.int64)
            d = ctx.malloc(4, np.int64)
            s.local[:] = comm.rank
            ctx.barrier_all()
            ctx.sum_to_all(d, s)
            assert (d.local == sum(range(comm.size))).all()
            assert len(calls) == 1  # rode Allreduce, not a side path
        finally:
            comm.Allreduce = orig
        # and the comm's merged vtable is the provider underneath
        assert "allreduce" in comm.coll.providers
        return True

    assert shmem_ranks(3, fn) == [True] * 3


# ---- r5 API tail: iput/iget, locks, shmem_ptr -----------------------

def test_iput_iget_strided_roundtrip():
    """shmem_iput/iget (ref: oshmem/shmem/c/shmem_iput.c): strided
    local stream -> strided remote placement and back."""
    def fn(ctx, comm):
        right = (comm.rank + 1) % comm.size
        dst = ctx.malloc(8, np.int64)
        dst.local[:] = -1
        ctx.barrier_all()
        # every 2nd source element to every 2nd remote index
        src = np.arange(8, dtype=np.int64) + 10 * comm.rank
        ctx.iput(dst, src, tst=2, sst=2, nelems=4, pe=right)
        ctx.barrier_all()
        left = (comm.rank - 1) % comm.size
        exp = np.full(8, -1, dtype=np.int64)
        exp[::2] = (np.arange(8) + 10 * left)[::2]
        assert (dst.local == exp).all(), (comm.rank, dst.local, exp)
        # iget the even indices back from my right neighbor
        got = np.full(8, -7, dtype=np.int64)
        ctx.iget(got, dst, tst=2, sst=2, nelems=4, pe=right)
        exp2 = np.full(8, -7, dtype=np.int64)
        exp2[::2] = (np.arange(8) + 10 * comm.rank)[::2]
        assert (got == exp2).all(), (comm.rank, got, exp2)
        ctx.barrier_all()
        return True

    assert all(shmem_ranks(4, fn))


def test_lock_mutual_exclusion_threads():
    """Ticket-lock fairness + mutual exclusion, thread ranks: lost
    updates from a non-atomic read-modify-write are exactly what a
    broken lock produces."""
    ITERS = 10

    def fn(ctx, comm):
        lock = ctx.malloc(1, np.int64)
        counter = ctx.malloc(1, np.int64)
        ctx.barrier_all()
        for _ in range(ITERS):
            ctx.set_lock(lock)
            v = int(ctx.g(counter, 0, 0))
            ctx.p(counter, 0, v + 1, 0)
            ctx.win.flush(0)
            ctx.clear_lock(lock)
        ctx.barrier_all()
        total = int(ctx.g(counter, 0, 0))
        assert total == comm.size * ITERS, total
        return True

    assert all(shmem_ranks(4, fn))


def test_test_lock_semantics():
    def fn(ctx, comm):
        lock = ctx.malloc(1, np.int64)
        ctx.barrier_all()
        if comm.rank == 0:
            assert ctx.test_lock(lock) is True     # free -> acquired
        comm.Barrier()
        if comm.rank == 1:
            assert ctx.test_lock(lock) is False    # held -> refused
        comm.Barrier()
        if comm.rank == 0:
            ctx.clear_lock(lock)
        comm.Barrier()
        if comm.rank == 1:
            assert ctx.test_lock(lock) is True     # free again
            ctx.clear_lock(lock)
        ctx.barrier_all()
        return True

    assert all(shmem_ranks(2, fn))


def test_lock_mutual_exclusion_procs():
    """The contended-mpirun form VERDICT r4 #6 asks for: process
    ranks over the osc/pml stack."""
    from ompi_tpu.testing import mpirun_run
    prog = os.path.join(REPO, "tests", "_shmem_lock_prog.py")
    # 3 ranks: the 1-core CI box serializes every osc fetch through
    # the scheduler.  One retry, and ONLY for the timeout/wedge mode
    # (the contended-spin schedule is bimodal on this box: ~10 s
    # typical, occasionally wedged into the job timeout) — a
    # lost-update correctness failure must fail immediately, never
    # be retried away.  The deterministic mutual-exclusion proof is
    # the thread-rank twin above.
    r = None
    for attempt in (1, 2):
        try:
            r = mpirun_run(3, prog, timeout=240, job_timeout=180)
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"lock proc test attempt {attempt}: outer timeout\n")
            continue
        if b"shmem lock ok: 24" in r.stdout:
            break
        timed_out = r.returncode == 124 or \
            b"exceeded --timeout" in r.stderr
        sys.stderr.write(
            f"lock proc test attempt {attempt} "
            f"{'timed out' if timed_out else 'FAILED'}:\n"
            f"{r.stdout.decode()[-500:]}\n"
            f"{r.stderr.decode()[-1000:]}\n")
        if not timed_out:
            break  # correctness failure: no retry
    assert r is not None and b"shmem lock ok: 24" in r.stdout, \
        (r.stdout.decode()[-800:] + r.stderr.decode()[-2000:]
         if r is not None else "both attempts hit the outer timeout")


def test_shmem_ptr():
    """Thread-rank PEs share an address space: ptr() is a REAL view
    of the peer's heap (stores are visible to the peer); process
    ranks get None (tested via the lock prog running under mpirun —
    here the thread side)."""
    def fn(ctx, comm):
        x = ctx.malloc(4, np.int64)
        x.local[:] = comm.rank
        ctx.barrier_all()
        peer = (comm.rank + 1) % comm.size
        view = ctx.ptr(x, peer)
        assert view is not None and (view == peer).all()
        # direct store, visible to the owner after a barrier
        view[comm.rank % 4] = 100 + comm.rank
        ctx.barrier_all()
        left = (comm.rank - 1) % comm.size
        assert x.local[left % 4] == 100 + left, x.local
        ctx.barrier_all()
        return True

    assert all(shmem_ranks(4, fn))


# ---- promoted examples: byte-identity across osc components ---------
# (ISSUE 14) The SAME workload — API-only, no .local stores — must
# return identical bytes whether the symmetric heap is the pt2pt
# window's numpy segment or the device component's HBM shard.

def _ring_workload(ctx, comm):
    """examples/shmem_ring.py: a token injected by PE 0 circles the
    ring via shmem_p + wait_until, incremented at every hop."""
    me, n = comm.rank, comm.size
    flag = ctx.malloc(1, np.int64)
    ctx._write_sym(flag, np.full(1, -1, np.int64))
    ctx.barrier_all()
    if me == 0:
        ctx.p(flag, 0, 42, (me + 1) % n)
    ctx.wait_until(flag, 0, "ge", 0)
    token = int(flag.local[0])
    if me != 0:
        ctx.p(flag, 0, token + 1, (me + 1) % n)
    ctx.barrier_all()
    if me == 0:
        assert token == 42 + n - 1, token
    return {"device": ctx.device, "token": token,
            "final": np.asarray(flag.local).tobytes()}


def _atomics_workload(ctx, comm):
    """examples/shmem_atomics.py: fetch-inc ticketing + atomic
    accumulator on PE 0, distinct tickets proven via fcollect."""
    me, n = comm.rank, comm.size
    counter = ctx.malloc(1, np.int64)
    acc = ctx.malloc(1, np.int64)
    ctx._write_sym(counter, np.zeros(1, np.int64))
    ctx._write_sym(acc, np.zeros(1, np.int64))
    ctx.barrier_all()
    ticket = int(ctx.atomic_fetch_inc(counter, 0, 0))
    ctx.atomic_add(acc, 0, me + 1, 0)
    ctx.barrier_all()
    all_t = ctx.malloc(n, np.int64)
    mine = ctx.malloc(1, np.int64)
    ctx._write_sym(mine, np.full(1, ticket, np.int64))
    ctx.barrier_all()
    ctx.collect(all_t, mine)
    tickets = sorted(np.asarray(all_t.local).tolist())
    assert tickets == list(range(n)), tickets
    return {"device": ctx.device,
            "counter": int(ctx.g(counter, 0, 0)),
            "acc": int(ctx.g(acc, 0, 0)),
            "tickets": np.asarray(tickets, np.int64).tobytes()}


@pytest.mark.parametrize(
    "workload", [_ring_workload, _atomics_workload],
    ids=["shmem_ring", "shmem_atomics"])
def test_promoted_examples_byte_identical(workload):
    n = 4
    host = shmem_ranks(n, workload)
    dev = shmem_ranks(n, workload, devices=True)
    assert all(not r["device"] for r in host)
    assert all(r["device"] for r in dev)
    for r in range(n):
        for k in host[r]:
            if k != "device":
                assert host[r][k] == dev[r][k], (r, k)


def test_device_heap_local_readonly_and_ptr_none():
    """A device heap has no live host alias: SymArray.local is a
    read-only snapshot and ptr() refuses to hand out peer views."""
    def fn(ctx, comm):
        assert ctx.device and ctx.heap is None
        x = ctx.malloc(4, np.int32)
        ctx._write_sym(x, np.arange(4, dtype=np.int32))
        loc = x.local
        assert not loc.flags.writeable
        with pytest.raises(ValueError):
            loc[0] = 9
        assert (ctx.ptr(x, comm.rank) == np.arange(4)).all()
        peer = (comm.rank + 1) % comm.size
        assert ctx.ptr(x, peer) is None
        ctx.barrier_all()
        return True

    assert all(shmem_ranks(2, fn, devices=True))


def test_scoll_on_device_heap():
    """PE collectives stage through the ctx accessors, so they work
    when the symmetric blocks live in HBM."""
    def fn(ctx, comm):
        me, n = comm.rank, comm.size
        src = ctx.malloc(3, np.int32)
        dst = ctx.malloc(3, np.int32)
        ctx._write_sym(src, np.full(3, me + 1, np.int32))
        ctx.barrier_all()
        ctx.sum_to_all(dst, src)
        assert (dst.local == n * (n + 1) // 2).all(), dst.local
        b = ctx.malloc(4, np.int32)
        if me == 1:
            ctx._write_sym(b, np.arange(4, dtype=np.int32) * 7)
        ctx.barrier_all()
        ctx.broadcast(b, b, root=1)
        assert (b.local == np.arange(4, dtype=np.int32) * 7).all()
        ctx.barrier_all()
        return True

    assert all(shmem_ranks(4, fn, devices=True))


def test_ring_byte_identity_across_shrink():
    """Survivors of a ULFM shrink epoch rebuild a device-heap ctx on
    the shrunken comm and the promoted ring workload is
    byte-identical to a fresh world of the survivor size."""
    import time

    from ompi_tpu import errhandler as eh
    from ompi_tpu.errhandler import MPIException
    from ompi_tpu.ft import ulfm

    codes = (eh.ERR_PROC_FAILED, eh.ERR_PROC_FAILED_PENDING,
             eh.ERR_REVOKED)

    def chaos(comm):
        comm.Barrier()
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        work = comm
        while work is comm:
            try:
                work.Barrier()
                time.sleep(0.05)
            except MPIException as e:
                assert e.code in codes, e.code
                work = work.shrink(name="survivors")
        ctx = shmem.ShmemCtx(work)
        out = _ring_workload(ctx, work)
        ctx.finalize()
        return out

    def fresh(comm):
        ctx = shmem.ShmemCtx(comm)
        out = _ring_workload(ctx, comm)
        ctx.finalize()
        return out

    got = run_ranks(4, chaos, devices=True, allow_failures=True,
                    timeout=180.0)
    ref = run_ranks(3, fresh, devices=True)
    assert got[0] is None
    for i in range(1, 4):
        assert got[i] == ref[i - 1], i
