"""coll/seg: shared-segment collectives between same-node process
ranks (coll/sm re-design for processes; native C hot path +
interoperable Python protocol)."""

import os
import subprocess
import sys

import pytest

from ompi_tpu.testing import mpirun_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROG = os.path.join(REPO, "tests", "_collseg_prog.py")


def _run(np_, *args, mca=()):
    r = mpirun_run(np_, PROG, *args, mca=mca, timeout=240,
                   job_timeout=200)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"collseg ok" in r.stdout
    assert b"collseg chunked ok" in r.stdout
    return r


def test_collseg_native_all_ops_8_ranks():
    _run(8)


def test_collseg_native_non_power_of_two():
    _run(5)


def test_collseg_python_protocol_fallback():
    """The Python protocol path (native disabled in-process) must
    produce identical results through the same segment layout."""
    _run(4, "--python-path")


def test_collseg_two_ranks():
    _run(2)


@pytest.mark.parametrize("rem", [0, 1, -1],
                         ids=["exact", "plus1", "piece-minus1"])
def test_collseg_chunked_tail_matrix(rem):
    """Tail-segment audit (DESIGN.md §12 satellite): chunked
    allreduce/bcast counts with count % piece in {0, 1, piece-1}
    across int8/float16/float32/float64, on a non-power-of-two comm —
    the ragged remainder must round-trip exactly and the P-divisible
    head must still take the split rs+ag rounds."""
    prog = os.path.join(REPO, "tests", "_collseg_tails_prog.py")
    r = mpirun_run(5, prog, str(rem),
                   mca=(("coll_seg_slot_bytes", "16384"),),
                   timeout=240, job_timeout=200)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"collseg tails ok" in r.stdout, \
        r.stdout.decode()[-500:] + r.stderr.decode()[-1500:]


def test_native_path_engages_under_mpirun():
    """The C segment hot path must actually serve mpirun process
    ranks — asserted via the coll_seg_native_ops pvar (a silent
    Python fallback would invalidate every small-message latency
    claim; ref: ompi/mca/coll/sm/coll_sm_module.c:102)."""
    prog = os.path.join(REPO, "tests", "_seg_pvar_prog.py")
    r = mpirun_run(4, prog, timeout=200, job_timeout=150)
    out = r.stdout.decode()
    assert out.count("seg pvar ok") == 4, \
        out[-1000:] + r.stderr.decode()[-1500:]
