"""Respawn chaos program (run via mpirun by test_respawn.py): one rank
is killed mid-loop by ft_inject ``rank_kill``; under the ``respawn``
errmgr policy mpirun relaunches it under the SAME world rank at a
bumped recovery epoch, survivors + the replacement run the rejoin
protocol (ft/respawn) and everyone rolls back to the newest buddy
checkpoint (cr/buddy) — the job finishes at FULL size with results
byte-identical to a fault-free run, and the replacement's state comes
from a partner rank's memory, never the filesystem store."""
import time

import numpy as np

import ompi_tpu
from ompi_tpu.cr import buddy
from ompi_tpu.errhandler import MPIException
from ompi_tpu.ft import respawn
from ompi_tpu.op import op as mpi_op

ITERS = 40


def _load(st):
    if st is None:  # died before the first commit: start over
        return 0, np.zeros(8)
    return int(st["i"]), np.asarray(st["acc"])


comm = ompi_tpu.init()
was_joining = respawn.joining(comm.state)
if was_joining:
    comm = respawn.rejoin(comm)
    i, acc = _load(buddy.restore(comm))
else:
    i, acc = 0, np.zeros(8)
rejoins = 0
while i < ITERS:
    try:
        buddy.checkpoint(comm, {"i": i, "acc": acc})
        x = np.full(8, (comm.rank + 1.0) * (i + 1))
        r = np.empty_like(x)
        comm.Allreduce(x, r, mpi_op.SUM)
        acc = acc + r
        i += 1
        time.sleep(0.05)
    except MPIException as e:
        assert e.code in (75, 76, 77), e.code
        comm = respawn.rejoin(comm)
        i, acc = _load(buddy.restore(comm))
        rejoins += 1
print(f"rank={comm.rank} size={comm.size} joined={int(was_joining)} "
      f"rejoins={rejoins} digest={acc.tobytes().hex()[:24]}",
      flush=True)
ompi_tpu.finalize()
