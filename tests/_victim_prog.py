"""Failure-injection target: rank 1 prints its pid and sleeps (the
test SIGKILLs it) while every other rank blocks in a collective."""
import os
import time

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
if comm.rank == 1:
    print(f"victim pid {os.getpid()}", flush=True)
    time.sleep(120)
buf = np.zeros(1)
comm.Allreduce(buf, buf.copy(), mpi_op.SUM)
print("should not get here", flush=True)
