"""MPI_T surface, pml/monitoring interposition, info tool (ref:
ompi/mpi/tool, ompi/mca/pml/monitoring + test/monitoring/)."""

import numpy as np
import pytest

import ompi_tpu.mpit as mpit
from ompi_tpu.mca.params import registry
from ompi_tpu.testing import run_ranks


@pytest.fixture
def mpit_session():
    mpit.init_thread()
    yield
    mpit.finalize()


def test_mpit_requires_init():
    with pytest.raises(mpit.MpitError):
        mpit.cvar_get_num()


def test_cvar_enumeration_and_handles(mpit_session):
    n = mpit.cvar_get_num()
    assert n > 0
    info = mpit.cvar_get_info(0)
    assert {"name", "help", "type", "level", "scope"} <= set(info)
    idx = mpit.cvar_get_index(info["name"])
    assert idx == 0
    with pytest.raises(mpit.MpitError):
        mpit.cvar_get_info(n + 1000)
    with pytest.raises(mpit.MpitError):
        mpit.cvar_get_index("no_such_variable_xyz")


def test_cvar_write_roundtrip(mpit_session):
    registry.register("mpitest", "demo", "knob", 7, int, help="test knob")
    h = mpit.cvar_handle_alloc("mpitest_demo_knob")
    assert mpit.cvar_read(h) == 7
    mpit.cvar_write(h, 13)
    assert mpit.cvar_read(h) == 13
    assert registry.get("mpitest_demo_knob") == 13


def test_categories_cover_frameworks(mpit_session):
    import ompi_tpu.coll  # ensure frameworks registered  # noqa: F401
    n = mpit.category_get_num()
    names = [mpit.category_get_info(i)["name"] for i in range(n)]
    assert "coll" in names and "pml" in names


def test_monitoring_counts_traffic():
    registry.set("pml_monitoring_enable", True)
    try:
        def fn(comm):
            x = np.arange(64, dtype=np.float64)
            r = np.empty_like(x)
            if comm.rank == 0:
                comm.Send(x, dest=1, tag=5)
            elif comm.rank == 1:
                comm.Recv(r, source=0, tag=5)
            comm.Barrier()
            return comm.state.pml.matrix_rows()

        rows = run_ranks(2, fn)
        # rank0 sent one user message of 512 bytes to peer 1
        assert rows[0]["sent_msgs"][1] == 1
        assert rows[0]["sent_bytes"][1] == 512
        # barrier traffic is internal (tag < 0) → filtered
        assert rows[0]["sent_filtered_msgs"][1] >= 1
        # rank1 received the user payload
        assert rows[1]["recv_bytes"][0] >= 512
        # user and internal streams kept separate
        assert rows[0]["sent_msgs"][0] == 0
    finally:
        registry.set("pml_monitoring_enable", False)


def test_monitoring_pvar_session_delta():
    registry.set("pml_monitoring_enable", True)
    try:
        def fn(comm):
            mpit.init_thread()
            s = mpit.pvar_session_create()
            h = mpit.pvar_handle_alloc(s, "pml_monitoring_messages_size")
            base = mpit.pvar_read(h)
            mpit.pvar_reset(h)
            if comm.rank == 0:
                comm.Send(np.zeros(32, dtype=np.float64), dest=1, tag=0)
            else:
                r = np.empty(32, dtype=np.float64)
                comm.Recv(r, source=0, tag=0)
            delta = mpit.pvar_read(h)
            mpit.finalize()
            return (base, delta)

        res = run_ranks(2, fn)
        base0, delta0 = res[0]
        assert delta0[1] == 256      # bytes to peer 1 since reset
        _, delta1 = res[1]
        assert delta1 == [0, 0]      # rank1 sent nothing
    finally:
        registry.set("pml_monitoring_enable", False)


def test_monitoring_dump(tmp_path):
    registry.set("pml_monitoring_enable", True)
    try:
        def fn(comm):
            if comm.rank == 0:
                comm.Send(np.zeros(8, dtype=np.int64), dest=1, tag=0)
            else:
                comm.Recv(np.empty(8, dtype=np.int64), source=0, tag=0)
            path = str(tmp_path / f"prof.{comm.rank}")
            comm.state.pml.dump(path)
            return path

        paths = run_ranks(2, fn)
        lines = open(paths[0]).read().strip().splitlines()
        assert lines == ["0 1 1 64"]
    finally:
        registry.set("pml_monitoring_enable", False)


def test_monitoring_disabled_no_wrap():
    def fn(comm):
        return hasattr(comm.state.pml, "matrix_rows")

    assert run_ranks(2, fn) == [False, False]


def test_pvar_stop_freezes_value(mpit_session):
    registry.set("pml_monitoring_enable", True)
    try:
        def fn(comm):
            s = mpit.pvar_session_create()
            h = mpit.pvar_handle_alloc(s, "pml_monitoring_messages_count")
            if comm.rank == 0:
                comm.Send(np.zeros(4, dtype=np.int64), dest=1, tag=0)
                mpit.pvar_stop(h)
                frozen = mpit.pvar_read(h)
                comm.Send(np.zeros(4, dtype=np.int64), dest=1, tag=0)
                still = mpit.pvar_read(h)
                mpit.pvar_start(h)
                live = mpit.pvar_read(h)
                return (frozen, still, live)
            comm.Recv(np.empty(4, dtype=np.int64), source=0, tag=0)
            comm.Recv(np.empty(4, dtype=np.int64), source=0, tag=0)
            return None

        frozen, still, live = run_ranks(2, fn)[0]
        assert frozen[1] == 1 and still[1] == 1   # frozen at stop
        assert live[1] == 2                        # live again
    finally:
        registry.set("pml_monitoring_enable", False)


def test_cvar_index_stable_across_new_registrations(mpit_session):
    idx = mpit.cvar_get_index("pml_monitoring_enable")
    # an alphabetically-earlier registration must NOT shift indices
    registry.register("aaa", "zzz", "newvar", 1, int)
    assert mpit.cvar_get_index("pml_monitoring_enable") == idx
    assert mpit.cvar_get_info(idx)["name"] == "pml_monitoring_enable"


def test_monitoring_anytag_irecv_counts_as_user():
    registry.set("pml_monitoring_enable", True)
    try:
        def fn(comm):
            if comm.rank == 0:
                comm.Send(np.zeros(16, dtype=np.float64), dest=1, tag=9)
                return None
            r = np.empty(16, dtype=np.float64)
            comm.Irecv(r, source=0).wait()   # default tag = ANY_TAG
            return comm.state.pml.matrix_rows()

        rows = run_ranks(2, fn)[1]
        assert rows["recv_bytes"][0] == 128   # user, not filtered
    finally:
        registry.set("pml_monitoring_enable", False)


def test_neighbor_buffer_divisibility_error():
    def fn(comm):
        cart = comm.Create_cart([3], periods=[True])
        try:
            cart.Neighbor_allgather(np.zeros(1), np.zeros(5))
            return "no-error"
        except ValueError:
            return "ok"

    assert run_ranks(3, fn) == ["ok"] * 3


def test_cart_coords_invalid_rank_raises():
    def fn(comm):
        cart = comm.Create_cart([2, 2])
        try:
            cart.Get_coords(7)
            return "no-error"
        except ValueError:
            return "ok"

    assert run_ranks(4, fn) == ["ok"] * 4


def test_info_tool_output(capsys):
    from ompi_tpu.tools import info
    assert info.main([]) == 0
    out = capsys.readouterr().out
    assert "Components:" in out and "coll" in out
    assert info.main(["--param", "all", "all", "--parsable"]) == 0
    out = capsys.readouterr().out
    assert "mca:" in out and ":param:" in out and ":source:" in out


def test_pvar_counts_fast_and_slow_send_paths():
    """The ob1 bytes_sent pvar must count BOTH convertor paths: the
    contiguous fast path (ContigConvertor) and the stack-machine slow
    path (strided buffer) — the r3 fast path must not bypass
    accounting (VERDICT r3 weak #3)."""
    from ompi_tpu.datatype.convertor import ContigConvertor
    from ompi_tpu.datatype.convertor import make_convertor
    from ompi_tpu.datatype import engine as dtmod

    # path sanity: contiguous dtype -> fast path, vector dtype -> slow
    vec = dtmod.vector(8, 1, 2, dtmod.DOUBLE).commit()
    flat = np.arange(16, dtype=np.float64)
    assert isinstance(make_convertor(dtmod.DOUBLE, 16, flat),
                      ContigConvertor)
    assert not isinstance(make_convertor(vec, 1, flat),
                          ContigConvertor)

    def fn(comm):
        pv = comm.state.pml.pvar_sent
        got = {}
        if comm.rank == 0:
            base = pv.read()
            comm.Send(flat, dest=1, tag=7)              # fast path
            got["fast"] = pv.read() - base
            base = pv.read()
            comm.Send((flat, 1, vec), dest=1, tag=9)    # slow path
            got["slow"] = pv.read() - base
        else:
            r = np.empty(16, dtype=np.float64)
            comm.Recv(r, source=0, tag=7)
            r8 = np.empty(8, dtype=np.float64)
            comm.Recv(r8, source=0, tag=9)
            got["strided_recv_ok"] = bool((r8 == flat[::2]).all())
        return got

    res = run_ranks(2, fn)
    assert res[0]["fast"] == 16 * 8
    assert res[0]["slow"] == 8 * 8  # vector packs 8 doubles
    assert res[1]["strided_recv_ok"]
