"""Uncoordinated checkpoint e2e (vprotocol/pessimist): rank 0 SENDS
then checkpoints immediately — no quiesce, the message may still be
in flight; rank 1 checkpoints BEFORE receiving.  A crash after the
snapshots and a restart must replay the in-flight message from rank
0's sender log so rank 1's receive completes correctly."""
import os

import numpy as np

import ompi_tpu
from ompi_tpu import cr
from ompi_tpu.op import op as mpi_op

crash = os.environ.get("VPROTO_CRASH") == "1"
comm = ompi_tpu.init()

state = cr.restore_local(comm)
if state is None:
    state = {"phase": 0}
    # warm the channel with one exchanged value
    x = np.full(4, comm.rank + 1.0)
    r = np.empty(4)
    comm.Allreduce(x, r, mpi_op.SUM)
    if comm.rank == 0:
        comm.Send(np.arange(8.0), dest=1, tag=11)
        # send IN FLIGHT: snapshot without quiesce or drain
        state["phase"] = 1
        cr.checkpoint_local(comm, state)
    else:
        state["phase"] = 1
        cr.checkpoint_local(comm, state)  # BEFORE receiving tag 11
    if crash and comm.rank == 1:
        os._exit(17)

if state["phase"] == 1:
    if comm.rank == 1:
        got = np.empty(8)
        comm.Recv(got, source=0, tag=11)
        assert (got == np.arange(8.0)).all(), got
    comm.Barrier()
    if comm.rank == 0:
        print("vproto ok", flush=True)
ompi_tpu.finalize()
