"""Iterating job with per-step checkpoints (run under mpirun by
test_cr.py).  CKPT_CRASH_AT=k makes rank 2 die hard right after the
step-k checkpoint; a restart resumes from that snapshot and must
produce the same final answer as an uninterrupted run."""
import os

import numpy as np

import ompi_tpu
from ompi_tpu import cr
from ompi_tpu.op import op as mpi_op

STEPS = 8
crash_at = int(os.environ.get("CKPT_CRASH_AT", "-1"))

comm = ompi_tpu.init()
state = cr.restore(comm)
resumed = state is not None
if state is None:
    state = {"step": 0, "acc": np.zeros(4)}

while state["step"] < STEPS:
    contrib = np.full(4, float(comm.rank + 1) * (state["step"] + 1))
    r = np.empty(4)
    comm.Allreduce(contrib, r, mpi_op.SUM)
    state["acc"] = state["acc"] + r
    state["step"] += 1
    cr.checkpoint(comm, state, keep=2)
    if state["step"] == crash_at and comm.rank == 2:
        os._exit(17)  # hard mid-job death (no finalize, no cleanup)

node = os.environ.get("TPUMPI_NODE_NAME", "local")
print(f"rank {comm.rank} on node {node}", flush=True)
if comm.rank == 0:
    print(f"final step={state['step']} resumed={resumed} "
          f"acc={state['acc'].tolist()}", flush=True)
ompi_tpu.finalize()
