"""Multi-node launch tests: ras/rmaps mapping, simulated-node
daemons, tree launch through a local ssh-agent shim, IOF relay and
failure propagation (ref: the reference's multi-node-on-one-machine
strategies — ras/simulator fake allocations + oversubscribed local
rsh launch, SURVEY §4)."""

import os
import subprocess
import sys

import pytest

from ompi_tpu.runtime import ras, rmaps
from ompi_tpu.tools.plm import build_tree

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOCALSSH = f"{sys.executable} -m ompi_tpu.tools.localssh"


def mpirun(np, prog, *extra, timeout=240):
    from ompi_tpu.testing import mpirun_run
    return mpirun_run(np, os.path.join("examples", prog),
                      extra=extra, timeout=timeout, job_timeout=0)


# ---- ras: allocation parsing ---------------------------------------

def test_parse_hosts_slots():
    nodes = ras.parse_hosts("a,b:4,localhost:2")
    assert [n.name for n in nodes] == ["a", "b", "localhost"]
    assert [n.slots for n in nodes] == [1, 4, 2]
    assert nodes[2].local and not nodes[0].local


def test_parse_hostfile(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("# cluster\nn0 slots=2\nn1 slots=3  # tail\n\nn2\n")
    nodes = ras.parse_hostfile(str(hf))
    assert [(n.name, n.slots) for n in nodes] == [
        ("n0", 2), ("n1", 3), ("n2", 1)]


def test_parse_simulate():
    nodes = ras.parse_simulate("4x2")
    assert len(nodes) == 4 and all(n.slots == 2 and n.simulated
                                   for n in nodes)
    assert ras.parse_simulate("3")[0].sim_devices == 1
    with pytest.raises(ValueError):
        ras.parse_simulate("0x2")


def test_allocate_sources_exclusive():
    with pytest.raises(ValueError):
        ras.allocate("a,b", None, "2x2", 4)
    default = ras.allocate(None, None, None, 6)
    assert len(default) == 1 and default[0].local \
        and default[0].slots == 6


# ---- rmaps: mapping policies ---------------------------------------

def _nodes(*slots):
    return [ras.Node(name=f"n{i}", slots=s, node_id=i)
            for i, s in enumerate(slots)]


def test_map_byslot_fills_nodes():
    maps = rmaps.map_ranks(_nodes(2, 2), 3)
    assert maps[0].ranks == [0, 1] and maps[1].ranks == [2]


def test_map_bynode_round_robin():
    maps = rmaps.map_ranks(_nodes(2, 2), 4, policy="bynode")
    assert maps[0].ranks == [0, 2] and maps[1].ranks == [1, 3]


def test_map_oversubscribe_gate():
    with pytest.raises(ValueError):
        rmaps.map_ranks(_nodes(1, 1), 4)
    maps = rmaps.map_ranks(_nodes(1, 1), 4, oversubscribe=True)
    assert sorted(maps[0].ranks + maps[1].ranks) == [0, 1, 2, 3]


def test_map_hybrid_shells():
    maps = rmaps.map_ranks(_nodes(4, 2), 6, rpp=4)
    assert [(p.rank_base, p.nlocal) for p in maps[0].procs] == [(0, 4)]
    assert [(p.rank_base, p.nlocal) for p in maps[1].procs] == [(4, 2)]
    with pytest.raises(ValueError):
        rmaps.map_ranks(_nodes(2, 2), 4, rpp=2, policy="bynode")


def test_map_hybrid_oversubscribed_contiguous():
    """Oversubscribed byslot keeps per-node contiguity (slot-
    proportional shares), so hybrid shells still map."""
    maps = rmaps.map_ranks(_nodes(2, 2), 6, rpp=6, oversubscribe=True)
    assert maps[0].ranks == [0, 1, 2] and maps[1].ranks == [3, 4, 5]
    assert [(p.rank_base, p.nlocal) for p in maps[0].procs] == [(0, 3)]
    # slot-proportional with largest-remainder: slots (3,1), np=6 →
    # floors (4,1), one remainder unit to the larger-remainder node
    maps = rmaps.map_ranks(_nodes(3, 1), 6, oversubscribe=True)
    assert maps[0].ranks == [0, 1, 2, 3, 4] and maps[1].ranks == [5]


def test_explicit_single_node_enforces_slots():
    """--hosts localhost:2 must enforce the slot count even though
    the allocation is one local node (PLM path, not the implicit
    direct path)."""
    r = mpirun(4, "ring.py", "--hosts", "localhost:2")
    assert r.returncode == 2
    assert "not enough slots" in r.stderr.decode()


def test_launch_tree_covers_all_nodes_once():
    nodes = [ras.Node(name=f"n{i}", slots=1, node_id=i)
             for i in range(13)]
    for radix in (1, 2, 3, 32):
        roots = build_tree(nodes, radix)
        seen = []

        def walk(e):
            seen.append(e["node"])
            for c in e["subtree"]:
                walk(c)

        for r in roots:
            walk(r)
        assert sorted(seen) == list(range(13)), radix
        if radix == 2:
            assert len(roots) == 2  # HNP fan-out respects the radix


# ---- end-to-end: simulated nodes + localssh tree launch ------------

def test_sim_nodes_ring():
    r = mpirun(4, "ring.py", "--simulate-nodes", "2x2", "--tag-output")
    assert r.returncode == 0, r.stderr.decode()
    out = r.stdout.decode()
    assert "received token 7 from 3" in out
    assert "[sim1:" in out  # IOF relays through the remote daemon


def test_sim_nodes_connectivity():
    r = mpirun(4, "connectivity.py", "--simulate-nodes", "4x1")
    assert r.returncode == 0, r.stderr.decode()
    assert "PASSED" in r.stdout.decode()


def test_sim_nodes_bynode_mapping_runs():
    r = mpirun(4, "connectivity.py", "--simulate-nodes", "2x2",
               "--map-by", "bynode")
    assert r.returncode == 0, r.stderr.decode()
    assert "PASSED" in r.stdout.decode()


def test_sim_nodes_hybrid_device_collective():
    """The VERDICT r1 #3 gate: a device collective in a multi-node
    job — intra-node XLA mesh allreduce + inter-node host combine."""
    r = mpirun(4, "hier_allreduce.py", "--simulate-nodes", "2x2",
               "--ranks-per-proc", "all")
    assert r.returncode == 0, r.stderr.decode()
    assert r.stdout.decode().count("hierarchical allreduce ok") == 4
    assert "device-offloaded=0" not in r.stdout.decode()


def test_hosts_localssh_tree_launch():
    """--hosts with an ssh-style agent (shimmed local), tree radix 1
    so the second daemon is launched BY the first (plm tree spawn)."""
    r = mpirun(4, "ring.py", "--hosts", "A:2,B:2",
               "--launch-agent", LOCALSSH, "--tree-radix", "1")
    assert r.returncode == 0, r.stderr.decode()
    assert "received token 7 from 3" in r.stdout.decode()


def test_sim_nodes_abort_propagates():
    r = mpirun(4, "abort_test.py", "--simulate-nodes", "2x2")
    assert r.returncode == 42, (r.returncode, r.stderr.decode())
    assert "MPI_Abort" in r.stderr.decode()


def test_sim_nodes_nonzero_exit_kills_job():
    r = mpirun(3, "exit_one.py", "--simulate-nodes", "3x1",
               timeout=120)
    assert r.returncode == 7, (r.returncode, r.stderr.decode())
    assert "terminating job" in r.stderr.decode()


def test_kv_proxy_aggregates_connections():
    """The per-node KV proxy (grpcomm analog) collapses per-rank KV
    traffic: the central server sees O(daemons) connections, not
    O(ranks) — with 8 ranks on 2 simulated nodes, at most 2 upstream
    channels per daemon (ops + fence) instead of 8 rank sockets."""
    import re
    r = mpirun(8, "hello.py", "--simulate-nodes", "2x4",
               "--devices", "none", "--verbose", "kv")
    assert r.returncode == 0, r.stderr.decode()
    err = r.stderr.decode()
    m = re.search(r"kv server served (\d+) connections", err)
    assert m, err
    served = int(m.group(1))
    assert served <= 4, f"expected O(daemons) connections, saw {served}"
    assert b"Hello" in r.stdout


def test_preload_stages_program_to_nodes(tmp_path):
    """filem/raw analog: --preload ships the program bytes in the
    launch message; daemons run the staged copy from their session
    dir (no shared-filesystem assumption)."""
    prog = tmp_path / "myprog.py"
    prog.write_text(
        "import os\n"
        "import ompi_tpu\n"
        "comm = ompi_tpu.init()\n"
        "print('RAN', comm.rank, os.path.abspath(__file__), flush=True)\n"
        "ompi_tpu.finalize()\n")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--simulate-nodes", "2x1", "--devices", "none", "--preload",
         "--timeout", "120", str(prog)],
        capture_output=True, timeout=180,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO)
    assert r.returncode == 0, r.stderr.decode()
    out = r.stdout.decode()
    ran = [ln for ln in out.splitlines() if ln.startswith("RAN")]
    assert len(ran) == 2
    # the executed file is the STAGED copy in a session dir, not the
    # original path
    for ln in ran:
        path = ln.split()[-1]
        assert str(prog) != path
        assert os.path.basename(path) == "staged_myprog.py"
