"""Cross-rank span tracing (ompi_tpu/trace + tools/traceview):
disabled-cost contract, enabled-path allocation guard, ring
wraparound accounting, sampling exactness + adaptive backoff,
clock-corrected multi-rank merge, histogram pvars, the extended
PERUSE coll/nbc events, the pml/monitoring finalize dump, pstat pvar
idempotency across repeated worlds, and the hotpath_audit AST lint
that holds the hot functions to the zero-allocation budget."""

import gc
import json
import os
import time
import tracemalloc

import numpy as np
import pytest

from ompi_tpu import peruse, trace
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks
from ompi_tpu.tools import traceview


@pytest.fixture(autouse=True)
def _clean_trace():
    yield
    registry.set("trace_enable", "0")
    registry.set("trace_dump_path", "")
    registry.set("trace_buffer_events", "8192")
    registry.set("trace_sample_spec", "")
    registry.set("trace_sample_auto", "1024")
    registry.set("trace_sample_max", "64")
    registry.set("pml_monitoring_enable", "0")
    registry.set("pml_monitoring_dump_path", "")
    peruse.unsubscribe_all()


def _traffic(comm):
    """A little of everything: p2p, blocking colls, an nbc."""
    sbuf = np.ones(4, np.float32) * (comm.rank + 1)
    rbuf = np.zeros(4, np.float32)
    nxt = (comm.rank + 1) % comm.size
    prv = (comm.rank - 1) % comm.size
    rq = comm.Irecv(rbuf, prv, tag=3)
    comm.Send(sbuf, nxt, tag=3)
    rq.wait()
    comm.Allreduce(sbuf, rbuf, mpi_op.SUM)
    comm.Barrier()
    r = comm.Ibarrier()
    r.wait()


# -- the cost contract ------------------------------------------------------

def test_trace_disabled_costs_nothing():
    """trace_enable off (default): every layer's cached tracer slot is
    None — the single-attribute-check contract, asserted structurally
    the way test_peruse_disabled_costs_nothing asserts the flag."""
    assert not trace.enable_var.value

    def fn(comm):
        assert comm.state.tracer is None
        assert comm.state.progress.tracer is None
        # ob1 caches the tracer at selection time (unwrap monitoring/
        # vprotocol interpositions if any)
        pml = comm.state.pml
        while not hasattr(pml, "_tracer"):
            pml = pml._pml
        assert pml._tracer is None
        assert trace.current_tracer() is None
        _traffic(comm)
        return comm.state.tracer is None

    assert all(run_ranks(2, fn))
    assert trace.global_tracer() is None


def test_enabled_hot_path_retains_no_objects():
    """The recording hot path allocates NOTHING that survives the
    call: ring columns are preallocated typed arrays, ids are interned
    ints, timestamps are transient PyLongs.  Measured with tracemalloc
    over thousands of start_sampled/end pairs (skip branch, keep
    branch, adaptation, and ring wraparound all exercised) — the net
    retained memory must stay within a few stray counter ints, i.e.
    far under one byte per span."""
    tr = trace.Tracer(0, capacity=256)
    # warm: cross the wraparound boundary and the first adaptation
    # thresholds so every code path has already run once
    for _ in range(2048):
        t0 = tr.start_sampled(trace.CAT_COLL_DISPATCH)
        if t0:
            tr.end(t0, trace.NAME_MEET, trace.CAT_COLL_DISPATCH, 1, 2, 3)
    gc.collect()
    tracemalloc.start()
    base = tracemalloc.get_traced_memory()[0]
    for _ in range(4000):
        t0 = tr.start_sampled(trace.CAT_COLL_DISPATCH)
        if t0:
            tr.end(t0, trace.NAME_MEET, trace.CAT_COLL_DISPATCH, 1, 2, 3)
    gc.collect()
    grown = tracemalloc.get_traced_memory()[0] - base
    tracemalloc.stop()
    assert grown < 4096, f"hot path retained {grown} bytes over 4000 spans"


def test_wall_anchor_read_once():
    """time.time is read ONCE at Tracer construction; recording and
    snapshot decoding run entirely on perf_counter_ns + the stored
    anchor.  Proven by making the wall clock explode after init."""
    tr = trace.Tracer(0, capacity=8)
    real_time = time.time

    def boom():
        raise AssertionError("wall clock read on the hot path")

    time.time = boom
    try:
        t0 = tr.start()
        tr.end(t0, trace.NAME_MEET, trace.CAT_COLL, 7, 1, 64)
        t1 = tr.start_sampled(trace.CAT_COLL)
        tr.end(t1, trace.NAME_MEET, trace.CAT_COLL, 7, 2, 64)
        evs = tr.snapshot()
    finally:
        time.time = real_time
    assert len(evs) == 2
    # timestamps decode affinely off the single anchor, in order
    assert evs[0]["ts"] <= evs[1]["ts"]
    assert abs(evs[0]["ts"] - tr.anchor_wall) < 5.0


def test_ring_wraparound_counts_drops():
    tr = trace.Tracer(0, capacity=8)
    for i in range(20):
        tr.instant(f"ev{i}", "test", i=i)
    kept = tr.snapshot()
    assert len(kept) == 8
    # oldest-first unroll of the newest 8
    assert [e["args"]["i"] for e in kept] == list(range(12, 20))
    assert tr.recorded == 20
    assert tr.dropped == 12


def test_span_records_duration_and_histogram():
    tr = trace.Tracer(0, capacity=64)
    t0 = tr.start()
    # hot API: interned ids + integer arg columns; the p2p match-id
    # string is synthesized at snapshot time, never on the hot path
    tr.end(t0, trace.NAME_SEND, trace.CAT_P2P, 0, 0, 1, 1, 16)
    (ev,) = tr.snapshot()
    assert ev["ph"] == "X" and ev["cat"] == "p2p"
    assert ev["dur"] >= 0
    assert ev["args"]["mid"] == "0:0:1:1"
    assert ev["args"]["bytes"] == 16
    assert tr.hist_total(trace.HIST_P2P_COMPLETE) == 1
    # cold compat path: string keys + a real kwargs dict, seconds out
    t0 = tr.start()
    dur_s = tr.end_slow(t0, "reconnect", "oob", node="n0")
    assert dur_s >= 0.0
    ev = tr.snapshot()[-1]
    assert ev["name"] == "reconnect" and ev["args"] == {"node": "n0"}
    # bucketing: 3 us -> bucket 2 ([2,4) us), 0 us -> bucket 0
    tr.hist_add(trace.HIST_COLL_DISPATCH, 3e-6)
    assert tr.hists[trace.HIST_COLL_DISPATCH][2] == 1
    tr.hist_add(trace.HIST_COLL_DISPATCH, 0.0)
    assert tr.hists[trace.HIST_COLL_DISPATCH][0] == 1
    # far overflow lands in the last bucket, never raises
    tr.hist_add(trace.HIST_COLL_DISPATCH, 3600.0)
    assert tr.hists[trace.HIST_COLL_DISPATCH][trace.N_BUCKETS - 1] == 1


# -- sampling: exact counters, adaptive backoff -----------------------------

def test_sampled_counters_exact():
    """1-in-N sampling never loses count: kept + sampled-out always
    equals seen, per category, and the pvar-facing accessors agree."""
    registry.set("trace_sample_spec", "p2p:4")
    registry.set("trace_sample_auto", "0")   # pin the period
    tr = trace.Tracer(0, capacity=4096)
    kept = 0
    for i in range(100):
        t0 = tr.start_sampled(trace.CAT_P2P)
        if t0:
            tr.end(t0, trace.NAME_SEND, trace.CAT_P2P, 0, 0, 1, i, 8)
            kept += 1
    assert kept == 25                      # exactly 1-in-4
    assert tr.recorded == 100              # seen, kept or not
    assert tr.cat_seen("p2p") == 100
    assert tr.dropped == 75
    assert tr.dropped_by_cat()["p2p"] == 75
    assert tr.sampling_rates()["p2p"] == 4
    assert tr.span_count("p2p") == kept
    # histograms count KEPT spans only: totals equal ring span counts
    assert tr.hist_total(trace.HIST_P2P_COMPLETE) == kept


def test_adaptive_sampling_backs_off_on_seen():
    """The period doubles every trace_sample_auto SEEN events (kept +
    skipped) up to trace_sample_max; quiet categories never leave full
    fidelity, and the exact counters still balance."""
    registry.set("trace_sample_auto", "8")
    registry.set("trace_sample_max", "16")
    tr = trace.Tracer(0, capacity=4096)
    kept = 0
    for i in range(200):
        t0 = tr.start_sampled(trace.CAT_COLL)
        if t0:
            tr.end(t0, trace.NAME_MEET, trace.CAT_COLL, 1, i, 0)
            kept += 1
    rates = tr.sampling_rates()
    assert rates["coll"] == 16             # reached the cap...
    assert rates["p2p"] == 1               # ...quiet cat untouched
    assert kept < 60                       # geometric backoff bit
    assert tr.cat_seen("coll") == 200
    assert tr.span_count("coll") == kept
    assert tr.dropped_by_cat()["coll"] == 200 - kept


def test_sampling_pvars_and_dump_sections(tmp_path):
    """The sampling/drop accounting is visible everywhere a consumer
    looks: MPI_T pvars in-job, the per-rank dump's sampling /
    dropped_by_cat / anchor sections, and the traceview summary."""
    registry.set("trace_enable", "1")
    registry.set("trace_dump_path", str(tmp_path))
    registry.set("trace_sample_spec", "coll:8")

    def fn(comm):
        for _ in range(32):
            comm.Barrier()
        tr = comm.state.tracer
        from ompi_tpu import mpit
        mpit.init_thread()
        try:
            sess = mpit.pvar_session_create()
            rates = mpit.pvar_read(
                mpit.pvar_handle_alloc(sess, "trace_sampling_rate"))
            dropped = mpit.pvar_read(
                mpit.pvar_handle_alloc(sess, "trace_dropped_coll"))
        finally:
            mpit.finalize()
        assert rates["coll"] == 8
        assert dropped > 0
        assert dropped == tr.dropped_by_cat()["coll"]
        # exactness through the pvar surface: kept + dropped == seen
        assert tr.span_count("coll") + dropped == tr.cat_seen("coll")
        return True

    assert all(run_ranks(2, fn))
    doc = json.loads((tmp_path / "trace-r0.json").read_text())
    assert doc["sampling"]["coll"] == 8
    assert doc["dropped_by_cat"]["coll"] > 0
    assert doc["anchor"]["wall_s"] > 0 and doc["anchor"]["perf_ns"] > 0
    dumps = traceview.load_dumps([str(tmp_path / "*.json")])
    text = traceview.summary(dumps, [0.0, 0.0])
    assert "dropped by category" in text
    assert "sampling 1-in-N" in text and "coll:8" in text


# -- the traced world -------------------------------------------------------

def test_traced_world_spans_and_correlation(tmp_path):
    registry.set("trace_enable", "1")
    registry.set("trace_dump_path", str(tmp_path))

    def fn(comm):
        _traffic(comm)
        tr = comm.state.tracer
        return {"rank": comm.rank,
                "p2p": tr.span_count("p2p"),
                "coll": tr.span_count("coll"),
                "nbc": tr.span_count("nbc"),
                "events": tr.snapshot()}

    res = run_ranks(4, fn)
    for r in res:
        assert r["p2p"] >= 2      # the ring send + recv at least
        assert r["coll"] >= 2     # allreduce + barrier entry spans
        assert r["nbc"] == 1      # the ibarrier schedule
    # p2p correlation: every receiver's mid appears as some sender's
    # mid (the ob1 match id is constructed identically on both sides)
    mids = [set(e["args"]["mid"] for e in r["events"]
                if e["cat"] == "p2p" and e["name"] == name)
            for name in ("send", "recv") for r in res]
    sends, recvs = set().union(*mids[:4]), set().union(*mids[4:])
    assert recvs <= sends
    # collective correlation: every rank logged allreduce under the
    # same (cid, seq)
    ar = [next(e for e in r["events"] if e["name"] == "allreduce")
          for r in res]
    assert len({(e["args"]["cid"], e["args"]["seq"]) for e in ar}) == 1
    # finalize dumped one file per rank
    assert sorted(os.listdir(tmp_path)) == [
        f"trace-r{r}.json" for r in range(4)]


def test_histogram_pvars_match_span_counts():
    registry.set("trace_enable", "1")
    registry.set("trace_buffer_events", "65536")

    def fn(comm):
        _traffic(comm)
        _traffic(comm)
        tr = comm.state.tracer
        assert tr.dropped == 0
        # the histograms that mirror ring categories agree with the
        # span counts — same instrumentation points feed both
        assert tr.hist_total(trace.HIST_P2P_COMPLETE) == \
            tr.span_count("p2p")
        assert tr.hist_total(trace.HIST_COLL_DISPATCH) == \
            tr.span_count("coll_dispatch")
        # ...and the MPI_T pvar surface reads THIS rank's histograms
        from ompi_tpu import mpit
        mpit.init_thread()
        try:
            sess = mpit.pvar_session_create()
            ph = mpit.pvar_handle_alloc(sess, "trace_hist_p2p_complete")
            assert sum(mpit.pvar_read(ph)) == tr.span_count("p2p")
            ph = mpit.pvar_handle_alloc(sess, "trace_events_recorded")
            assert mpit.pvar_read(ph) == tr.recorded
        finally:
            mpit.finalize()
        # sweep latency is itself sampled 1-in-16: enough explicit
        # sweeps guarantee at least one lands on the timed stride
        for _ in range(33):
            comm.state.progress.progress()
        assert tr.hist_total(trace.HIST_PROGRESS_TICK) > 0
        return True

    assert all(run_ranks(2, fn))


# -- the cross-rank merge ---------------------------------------------------

def test_traceview_merges_clock_corrected(tmp_path):
    registry.set("trace_enable", "1")
    registry.set("trace_dump_path", str(tmp_path))
    run_ranks(4, _traffic)
    dumps = traceview.load_dumps([str(tmp_path / "*.json")])
    assert [d["rank"] for d in dumps] == [0, 1, 2, 3]

    # synthetic mpisync offsets (us): rank r's clock = rank0's + off
    offsets = [0.0, 1000.0, -500.0, 250.0]
    events = traceview.corrected_events(dumps, offsets)
    assert events
    # correction math: a rank's corrected timestamps are its raw
    # timestamps minus its offset (then a common rebase) — verify on
    # rank 1 against a manual recompute
    raw1 = sorted(e["ts"] for d in dumps if d["rank"] == 1
                  for e in d["events"])
    base = min(e["ts"] - offsets[d["rank"]] / 1e6
               for d in dumps for e in d["events"])
    got1 = sorted(e["ts"] for e in events if e["rank"] == 1)
    want1 = sorted((t - offsets[1] / 1e6 - base) * 1e6 for t in raw1)
    assert got1 == pytest.approx(want1, abs=1.0)
    # per-rank monotonic after correction (each rank's ring is
    # recorded in time order; correction shifts a rank uniformly)
    for r in range(4):
        ts = [e["ts"] for e in events if e["rank"] == r]
        assert ts == sorted(ts)

    doc = traceview.chrome_trace(dumps, offsets)
    # valid Chrome trace-event JSON: serializable, required keys
    json.loads(json.dumps(doc))
    evs = doc["traceEvents"]
    assert {e["ph"] for e in evs} <= {"M", "X", "i"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert xs and all(
        {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e) for e in xs)
    assert {e["pid"] for e in xs} == {0, 1, 2, 3}
    assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in xs)
    # the text summary runs end to end
    text = traceview.summary(dumps, offsets, top=3)
    assert "slowest" in text and "straggler" in text


def test_traceview_cli(tmp_path):
    registry.set("trace_enable", "1")
    registry.set("trace_dump_path", str(tmp_path))
    run_ranks(4, _traffic)
    sync = tmp_path / "sync.json"
    sync.write_text(json.dumps(
        {"offsets_us": [0.0, 40.0, -15.0, 5.0], "rtts_us": []}))
    out = tmp_path / "merged.json"
    rc = traceview.main([str(tmp_path / "trace-r*.json"),
                         "--sync", str(sync), "-o", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert len([e for e in doc["traceEvents"] if e["ph"] == "X"]) > 0
    assert doc["otherData"]["ranks"]["0"]["dropped"] == 0


# -- the hot-path budget lint -----------------------------------------------

def test_hotpath_audit_clean():
    """Tier-1 gate: every declared hot function passes the AST lint —
    no container displays, no f-strings, no banned builtins, no
    time.time.  A refactor that sneaks an allocation back onto the
    recording path fails HERE, not in a perf probe three PRs later."""
    from ompi_tpu.tools import hotpath_audit
    assert hotpath_audit.audit() == []


def test_hotpath_audit_detects_regressions():
    from ompi_tpu.tools import hotpath_audit
    bad = (
        "import time\n"
        "class Tracer:\n"
        "    def end(self):\n"
        "        x = (1, 2)\n"
        "        y = [3]\n"
        "        d = {'a': 1}\n"
        "        s = f'{x}'\n"
        "        z = dict(a=1)\n"
        "        return time.time()\n"
    )
    got = hotpath_audit.audit_source(bad, ("Tracer.end",), "fake.py")
    text = "\n".join(got)
    for what in ("tuple allocation", "list allocation",
                 "dict allocation", "f-string", "call to dict()",
                 "time.time reference"):
        assert what in text, f"lint missed: {what}"
    # a Store-context unpack target is NOT an allocation
    ok = "def f(pair):\n    a, b = pair\n    return a\n"
    assert hotpath_audit.audit_source(ok, ("f",), "fake.py") == []
    # a renamed/missing hot function is itself a violation (the audit
    # must never silently stop covering a function)
    missing = hotpath_audit.audit_source(
        "def g():\n    pass\n", ("f",), "fake.py")
    assert missing and "not found" in missing[0]


# -- shared PERUSE instrumentation points -----------------------------------

def test_peruse_coll_and_nbc_events():
    events = []
    for ev in ("coll_begin", "coll_end", "nbc_activate",
               "nbc_complete"):
        peruse.subscribe(ev, lambda e, **kw: events.append((e, kw)))

    def fn(comm):
        x = np.ones(4, np.float32)
        r = np.zeros(4, np.float32)
        comm.Allreduce(x, r, mpi_op.SUM)
        rq = comm.Ibarrier()
        rq.wait()

    run_ranks(2, fn)
    kinds = [e for e, _ in events]
    assert "coll_begin" in kinds and "coll_end" in kinds
    assert "nbc_activate" in kinds and "nbc_complete" in kinds
    # begin/end pair under the same correlation key
    begins = [(kw["cid"], kw["seq"]) for e, kw in events
              if e == "coll_begin"]
    ends = [(kw["cid"], kw["seq"]) for e, kw in events
            if e == "coll_end"]
    assert sorted(begins) == sorted(ends)
    assert all(kw["coll"] for _, kw in events)


def test_peruse_events_fire_without_tracer():
    """The shared hooks serve PERUSE alone: trace off, subscribe on."""
    assert not trace.enable_var.value
    seen = []
    peruse.subscribe("coll_begin", lambda e, **kw: seen.append(kw))

    def fn(comm):
        comm.Barrier()
        assert comm.state.tracer is None

    run_ranks(2, fn)
    assert seen and all("seq" in kw for kw in seen)


# -- pml/monitoring finalize dump -------------------------------------------

def test_monitoring_finalize_dump_and_matrices(tmp_path):
    registry.set("pml_monitoring_enable", "1")
    prefix = str(tmp_path / "traffic")
    registry.set("pml_monitoring_dump_path", prefix)

    def fn(comm):
        buf = np.ones(8, np.float32)
        r = np.zeros(8, np.float32)
        if comm.rank == 0:
            comm.Send(buf, 1, tag=5)
            comm.Send(buf, 1, tag=6)
            comm.Recv(r, 1, tag=7)
        else:
            comm.Recv(r, 0, tag=5)
            comm.Recv(r, 0, tag=6)
            comm.Send(buf, 0, tag=7)
        comm.Barrier()

    run_ranks(2, fn)
    # per-rank .prof files (profile2mat.pl input format)
    for rank in (0, 1):
        lines = open(f"{prefix}.{rank}.prof").read().splitlines()
        assert all(len(ln.split()) == 4 for ln in lines)
    # rank 0 aggregated the matrices after the fence
    msg = [[float(v) for v in ln.split()]
           for ln in open(f"{prefix}_msg.mat").read().splitlines()]
    size = [[float(v) for v in ln.split()]
            for ln in open(f"{prefix}_size.mat").read().splitlines()]
    avg = [[float(v) for v in ln.split()]
           for ln in open(f"{prefix}_avg.mat").read().splitlines()]
    assert msg[0][1] == 2 and msg[1][0] == 1
    assert size[0][1] == 64 and size[1][0] == 32
    assert avg[0][1] == 32 and avg[0][0] == 0


def test_monitoring_dump_disabled_writes_nothing(tmp_path):
    registry.set("pml_monitoring_dump_path", str(tmp_path / "t"))
    # monitoring NOT enabled: the dump path alone must not interpose
    run_ranks(2, lambda comm: comm.Barrier())
    assert os.listdir(tmp_path) == []


# -- pstat pvar idempotency -------------------------------------------------

def test_pstat_pvars_idempotent_across_worlds():
    from ompi_tpu.mca.params import registry as reg

    def fn(comm):
        comm.Barrier()

    run_ranks(2, fn)
    names = [p.full_name for p in reg.all_pvars()
             if p.full_name.startswith("opal_pstat_")]
    count0 = len(names)
    assert len(set(names)) == count0  # no duplicates ever
    for _ in range(3):
        run_ranks(2, fn)
    names = [p.full_name for p in reg.all_pvars()
             if p.full_name.startswith("opal_pstat_")]
    assert len(names) == count0
    assert len(set(names)) == count0
