"""SDC chaos workload (run by test_integrity.py against a multi-host
DVM pool): a stepped device allreduce whose analytic result is known
on every rank, so each step self-verifies.  With device_sdc armed on
the victim rank and the integrity plane sampling every op, every flip
must be detected at the rendezvous, the op retried from pristine
sources, and every rank's result stays byte-exact — the prog prints
``SDC {tag} {rank} ok`` only when all steps matched.

argv: tag steps
"""
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

tag = sys.argv[1]
steps = int(sys.argv[2])

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
expect = float(size * (size + 1) // 2)
ok = True
for _step in range(steps):
    if comm.state.device is not None:
        import jax.numpy as jnp
        x = jnp.full((32,), float(rank + 1), jnp.float32)
        got = np.asarray(comm.allreduce_arr(x, mpi_op.SUM))
    else:
        x = np.full(32, rank + 1.0, np.float32)
        got = np.empty_like(x)
        comm.Allreduce(x, got, mpi_op.SUM)
    if not np.array_equal(got, np.full(32, expect, np.float32)):
        ok = False
# one atomic write: rank-threads share the session stdout buffer
sys.stdout.write(f"SDC {tag} {rank} {'ok' if ok else 'bad'}\n")
sys.stdout.flush()
ompi_tpu.finalize()
