"""DVM session workload (run against a resident pool session by
test_dvm.py): a deterministic mix of fused nonblocking device
collectives plus one blocking allreduce, digested to a single line
printed by rank 0 only — so a run's stdout is byte-comparable across
sequential and concurrent sessions.  The tag argv proves per-session
argv isolation inside the multiplexed pool."""
import hashlib
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
rank, size = comm.rank, comm.size
pieces = []
# optional argv[2]: repeat the collective mix (reps=1 keeps digests
# byte-identical for every existing caller; the reqtrace probe uses
# larger reps so a run's wall amortizes fixed RPC/rounding overhead)
reps = int(sys.argv[2]) if len(sys.argv) > 2 else 1
for _ in range(reps):
    if comm.state.device is not None:
        import jax.numpy as jnp
        a = jnp.arange(32, dtype=jnp.int32) * (rank + 1)
        b = (jnp.ones((16,), jnp.float32) * (rank + 1)).at[0].set(-rank)
        c = jnp.full((7,), rank * 3 + 1, jnp.int32)
        reqs = [comm.iallreduce_arr(a, mpi_op.SUM),
                comm.iallreduce_arr(b, mpi_op.MAX),
                comm.ibcast_arr(c, 1 % size)]
        for q in reqs:
            q.wait()
        pieces += [np.asarray(q.result).tobytes() for q in reqs]
        d = comm.allreduce_arr(
            jnp.full((64,), rank + 1.0, jnp.float32), mpi_op.SUM)
        pieces.append(np.asarray(d).tobytes())
    else:
        x = np.full(16, rank + 1.0, np.float32)
        r = np.empty_like(x)
        comm.Allreduce(x, r, mpi_op.SUM)
        pieces.append(r.tobytes())
tag = sys.argv[1] if len(sys.argv) > 1 else "t"
dig = hashlib.sha256(b"".join(pieces)).hexdigest()
if rank == 0:
    print(f"DIGEST {tag} {dig}", flush=True)
ompi_tpu.finalize()
