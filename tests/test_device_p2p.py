"""Device-buffer p2p (btl/tpu shim): D2D placement between
co-resident rank-thread devices, by-reference delivery, host-staged
fallback across processes, and the halo pattern."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ompi_tpu.testing import run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_send_recv_arr_roundtrip_on_devices():
    import jax

    def fn(comm):
        import jax.numpy as jnp
        x = jnp.full((64,), float(comm.rank + 1))
        nxt = (comm.rank + 1) % comm.size
        prv = (comm.rank - 1) % comm.size
        got = comm.sendrecv_arr(x, nxt, prv, tag=4)
        # result lives on MY device and carries the neighbor's value
        assert got.device == comm.state.device
        assert float(got[0]) == float(prv + 1)
        return True

    assert all(run_ranks(4, fn, devices=True))


def test_send_arr_lands_on_peer_device_no_host_bounce():
    """The sender PLACES the array on the receiver's chip: what
    arrives is already resident there (device_put at send time), and
    within a process the payload travels by reference."""
    import jax

    def fn(comm):
        import jax.numpy as jnp
        if comm.rank == 0:
            comm.send_arr(jnp.arange(8.0), 1, tag=9)
        elif comm.rank == 1:
            # peek at the raw payload before recv_arr converts
            msg = comm.state.pml.recv_obj(0, 9, comm)
            from ompi_tpu.btl.tpu import DeviceArrayPayload
            assert isinstance(msg.payload, DeviceArrayPayload)
            arr = msg.payload.arr
            assert arr.device == comm.state.device  # D2D, pre-placed
            assert float(np.asarray(arr)[3]) == 3.0
        comm.Barrier()
        return True

    assert all(run_ranks(2, fn, devices=True))


def test_matching_interleaves_with_byte_messages():
    def fn(comm):
        import jax.numpy as jnp
        if comm.rank == 0:
            comm.Send(np.array([7], np.int64), 1, tag=1)
            comm.send_arr(jnp.ones(4), 1, tag=1)
            comm.Send(np.array([8], np.int64), 1, tag=1)
        else:
            y = np.empty(1, np.int64)
            comm.Recv(y, 0, tag=1)
            assert y[0] == 7
            arr = comm.recv_arr(0, tag=1)
            assert float(arr[0]) == 1.0
            comm.Recv(y, 0, tag=1)
            assert y[0] == 8
        comm.Barrier()
        return True

    assert all(run_ranks(2, fn, devices=True))


def test_host_staged_across_processes():
    """Across a process boundary the wrapper pickles to numpy —
    exactly one host staging, correctness preserved."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "90",
         os.path.join(REPO, "tests", "_devp2p_prog.py")],
        capture_output=True, timeout=150,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO)
    assert r.returncode == 0, r.stderr.decode()
    assert b"devp2p ok" in r.stdout


def test_halo_exchange_uses_device_path():
    """The halo pattern on devices: cart shifts via sendrecv_arr."""
    import jax

    def fn(comm):
        import jax.numpy as jnp
        cart = comm.Create_cart([2, 2], periods=[True, True])
        left, right = cart.Shift(1, 1)
        tile = jnp.full((4,), float(cart.rank))
        halo = cart.sendrecv_arr(tile, right, left, tag=2)
        assert float(halo[0]) == float(left)
        return True

    assert all(run_ranks(4, fn, devices=True))


def test_chunked_transfer_bounded_staging():
    """>chunk-sized arrays stream via the pull rendezvous: correct
    content and host staging bounded at a few chunks (ref:
    pml_ob1_sendreq.c:404-453 pipelined schedule)."""
    from ompi_tpu.testing import mpirun_run
    r = mpirun_run(2, os.path.join("tests", "_devp2p_big_prog.py"),
                   timeout=300, job_timeout=250)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"devp2p-big ok" in r.stdout


def test_chunked_256mib_across_simulated_nodes():
    """The VERDICT r3 #5 gate: a 256 MiB device send crosses a
    simulated two-node job (tcp transport) with bounded staging."""
    from ompi_tpu.testing import mpirun_run
    r = mpirun_run(2, os.path.join("tests", "_devp2p_big_prog.py"),
                   "--mb", "256",
                   extra=("--simulate-nodes", "2"),
                   timeout=400, job_timeout=350)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert b"devp2p-big ok" in r.stdout


def test_chunked_header_checkpoint_roundtrip():
    """A not-yet-received chunked transfer survives capture/restore:
    the receiver snapshots the header, the sender snapshots the
    parked data, and the pull completes after reinjection."""
    import numpy as np
    from ompi_tpu.btl import tpu as tpumod
    from ompi_tpu.mca.params import registry

    def fn(comm):
        if comm.rank == 0:
            eng = tpumod._engine(comm.state)
            flat = np.arange(5000, dtype=np.float64)
            xid = eng.begin_send(flat)
            cap = eng.cr_capture()
            assert len(cap) == 1 and cap[0][0] == xid
            eng.pending.clear()
            eng.cr_restore(cap)
            assert xid in eng.pending
            # fresh ids never collide with restored ones
            assert eng.begin_send(flat) > xid
            eng.pending.clear()
        else:
            # receiver-side: a captured xferhdr reinjects intact
            pml = comm.state.pml
            hdr = tpumod._XferHdr(7, (10, 500), "float64", 40000,
                                  registry.get("btl_tpu_chunk_bytes"))
            from ompi_tpu.pml.ob1 import MATCH_OBJ, UnexpectedMsg
            pml._unexpected.setdefault(comm.cid, []).append(
                UnexpectedMsg(MATCH_OBJ, comm.cid, 0, 4, 0,
                              len(hdr), None, hdr))
            msgs = pml.cr_capture()
            kinds = [m[4] for m in msgs]
            assert "xferhdr" in kinds, kinds
            pml._unexpected[comm.cid].clear()
            pml.cr_restore(msgs)
            m = pml._unexpected[comm.cid][0]
            assert isinstance(m.payload, tpumod._XferHdr)
            assert m.payload.shape == (10, 500)
            pml._unexpected[comm.cid].clear()
        comm.Barrier()
        return True

    assert all(run_ranks(2, fn))
