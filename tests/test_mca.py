"""MCA registry tests: variable precedence, component selection.

Models the reference's variable-system behavior
(opal/mca/base/mca_base_var.c): defaults < files < env < override.
"""

import os

import pytest

from ompi_tpu.mca import base as mca_base
from ompi_tpu.mca import params


def test_var_default_and_env(monkeypatch):
    var = params.registry.register("tst", "comp", "alpha", 7, int, help="x")
    assert var.value == 7
    assert var.source == params.SOURCE_DEFAULT

    monkeypatch.setenv(params.ENV_PREFIX + "tst_comp_alpha", "42")
    params.registry.refresh()
    assert params.registry.get("tst_comp_alpha") == 42

    params.registry.set("tst_comp_alpha", 9)
    assert params.registry.get("tst_comp_alpha") == 9  # override beats env
    monkeypatch.delenv(params.ENV_PREFIX + "tst_comp_alpha")


def test_var_size_suffixes():
    var = params.registry.register("tst", "comp", "eager", "64k", int)
    assert var.value == 65536


def test_var_bool_coercion(monkeypatch):
    monkeypatch.setenv(params.ENV_PREFIX + "tst_comp_flag", "yes")
    var = params.registry.register("tst", "comp", "flag", False, bool)
    assert var.value is True


def test_param_file(tmp_path, monkeypatch):
    f = tmp_path / "params.conf"
    f.write_text("# comment\ntst_comp_beta = 13\n")
    monkeypatch.setenv(params.PARAM_FILE_ENV, str(f))
    params.registry.refresh()
    var = params.registry.register("tst", "comp", "beta", 1, int)
    assert var.value == 13
    assert var.source == params.SOURCE_FILE


def test_pvar_counter():
    pv = params.registry.register_pvar("tst", "comp", "msgs", var_class="counter")
    pv.add(3)
    pv.add(2)
    assert pv.read() == 5


class _Comp(mca_base.Component):
    def __init__(self, name, priority, usable=True):
        super().__init__()
        self.name = name
        self.priority = priority
        self.usable = usable

    def query(self):
        if not self.usable:
            return None
        return (self.priority, f"module-{self.name}")


def test_framework_select_one_priority():
    fw = mca_base.Framework("test", "tfw1")
    fw.add_component(_Comp("lo", 10))
    fw.add_component(_Comp("hi", 50))
    fw.add_component(_Comp("broken", 99, usable=False))
    comp, payload = fw.select_one()
    assert comp.name == "hi"
    assert payload == "module-hi"


def test_framework_user_exclusion():
    fw = mca_base.Framework("test", "tfw2")
    fw.add_component(_Comp("a", 10))
    fw.add_component(_Comp("b", 50))
    params.registry.register("tfw2", "", "", "", str)
    params.registry.set("tfw2", "^b")
    try:
        comp, _ = fw.select_one()
        assert comp.name == "a"
    finally:
        params.registry.set("tfw2", "")


def test_framework_select_all_sorted():
    fw = mca_base.Framework("test", "tfw3")
    fw.add_component(_Comp("a", 10))
    fw.add_component(_Comp("b", 50))
    allc = fw.select_all()
    assert [c.name for _, c, _ in allc] == ["b", "a"]


def test_parse_mca_args():
    rest = params.parse_mca_args(
        ["prog", "--mca", "tst_comp_gamma", "5", "arg1"])
    assert rest == ["prog", "arg1"]
    var = params.registry.register("tst", "comp", "gamma", 0, int)
    assert var.value == 5


def test_schizo_accepts_ompi_mca_env(monkeypatch):
    """schizo/ompi analog: OMPI_MCA_* spellings resolve; the native
    TPUMPI_MCA_* prefix wins when both are set."""
    from ompi_tpu.mca.params import registry

    v = registry.register("test", "schizo", "knob", 1, int)
    monkeypatch.setenv("OMPI_MCA_test_schizo_knob", "5")
    registry.refresh()
    assert registry.get("test_schizo_knob") == 5
    monkeypatch.setenv("TPUMPI_MCA_test_schizo_knob", "9")
    registry.refresh()
    assert registry.get("test_schizo_knob") == 9
    monkeypatch.delenv("OMPI_MCA_test_schizo_knob")
    monkeypatch.delenv("TPUMPI_MCA_test_schizo_knob")
    registry.refresh()
    assert registry.get("test_schizo_knob") == 1


def test_installdirs_fields_env_override_and_expand(monkeypatch):
    """installdirs analog (opal/mca/installdirs): package-derived
    defaults, TPUMPI_* env overrides, ${field} expansion."""
    from ompi_tpu.runtime import installdirs

    dirs = installdirs.all_dirs()
    assert os.path.isdir(dirs["prefix"])
    assert os.path.isdir(dirs["libdir"])
    monkeypatch.setenv("TPUMPI_SYSCONFDIR", "/tmp/etc-override")
    assert installdirs.get("sysconfdir") == "/tmp/etc-override"
    assert installdirs.expand("${sysconfdir}/x.conf") == \
        "/tmp/etc-override/x.conf"
    with pytest.raises(KeyError):
        installdirs.get("no_such_dir")


def test_info_tool_reports_installdirs(capsys):
    from ompi_tpu.tools import info
    assert info.main(["--parsable"]) == 0
    out = capsys.readouterr().out
    assert "installdirs:prefix:" in out


def test_installdirs_override_referencing_other_field(monkeypatch):
    from ompi_tpu.runtime import installdirs

    monkeypatch.setenv("TPUMPI_DATADIR", "${prefix}/share")
    got = installdirs.get("datadir")
    assert "${" not in got
    assert got == installdirs.get("prefix") + "/share"
