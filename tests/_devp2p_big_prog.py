"""Chunked cross-process device-array rendezvous: a large array must
stream in bounded chunks (peak host staging <= a few chunks), never
as one giant pickled frame (ref: pml_ob1_sendreq.c:404-453 pipelined
schedule)."""
import sys

import numpy as np

import ompi_tpu
import ompi_tpu.btl.tpu  # register btl_tpu_* params
from ompi_tpu.mca.params import registry

comm = ompi_tpu.init()
MB = 1024 * 1024
chunk = registry.get("btl_tpu_chunk_bytes")
n_mb = int(sys.argv[sys.argv.index("--mb") + 1]) if "--mb" in sys.argv else 48
n = n_mb * MB // 4  # float32 elements; >> chunk (4 MiB)

if comm.rank == 0:
    x = np.arange(n, dtype=np.float32).reshape(4, -1)
    comm.send_arr(x, 1, tag=3)
    # service pulls until the transfer drains
    eng = comm.state._tpu_rndv
    import time
    deadline = time.monotonic() + 120
    while (eng.pending or eng._inflight) and time.monotonic() < deadline:
        comm.state.progress.progress()
        comm.state.progress.idle_tick()
    assert not eng.pending, "transfer never drained"
    comm.Barrier()
    staged = eng.max_staged_bytes
    depth = registry.get("btl_tpu_pipeline_depth")
    bound = (depth + 2) * chunk
    assert staged <= bound, (staged, bound)
    print(f"devp2p-big ok staged={staged} bound={bound}", flush=True)
else:
    got = comm.recv_arr(0, tag=3)
    a = np.asarray(got)
    assert a.shape == (4, n // 4)
    flat = a.reshape(-1)
    assert flat[0] == 0.0 and flat[-1] == float(n - 1)
    step = max(1, n // 997)
    idx = np.arange(0, n, step)
    assert (flat[idx] == idx.astype(np.float32)).all()
    comm.Barrier()
ompi_tpu.finalize()
