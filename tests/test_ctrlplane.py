"""Control-plane fault-tolerance tests (docs/DESIGN.md §20): the
replicated KV store must survive a primary kill with ranks PARKED in
a fence (the standby completes the fence from replicated arrivals —
never re-creates it), the kv_kill/dvm_kill chaos classes must be
deterministic and off by default, and the Supervisor's respawn loop
must heal the fault plan so a killed child comes back clean."""

import os
import sys
import threading
import time

from ompi_tpu.mca.params import registry
from ompi_tpu.runtime.kvstore import KVClient, KVServer, _kv_pvars


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


def _pvar(suffix):
    for p in _kv_pvars():
        if p.full_name.endswith(suffix):
            return p
    raise AssertionError(f"no kv pvar ending with {suffix}")


def test_kill_injectors_disabled_by_default():
    """Empty plan = no injector objects: a production KVServer or DVM
    never pays for chaos plumbing."""
    from ompi_tpu import ft_inject
    assert not ft_inject.enabled()
    assert ft_inject.kv_kill_injector() is None
    assert ft_inject.dvm_kill_injector() is None


def test_kill_injector_fires_exactly_once_at_count():
    """The armed op count is deterministic (no RNG): False until op
    N, True AT op N, False forever after — a chaos run replays
    bit-for-bit."""
    from ompi_tpu.ft_inject import KillInjector
    ki = KillInjector("kv", 5)
    assert [ki.op() for _ in range(10)] == \
        [False] * 4 + [True] + [False] * 5
    # rates below 1 (a bare class name got the default rate) arm the
    # mid-run default instead of dying on the first op
    assert KillInjector("dvm", 0.02).after_ops == 64


def test_kv_primary_kill_mid_fence_failover():
    """The acceptance scenario: three clients parked in an n=4 fence
    when the primary dies.  The promoted standby holds the fence's
    replicated arrivals, the straggler lands on the standby, and ALL
    FOUR complete — plus data and counters written before the kill
    survive it."""
    srv = KVServer(4, replicas=1)
    clients = [KVClient(srv.uri) for _ in range(4)]
    clients[0].put("pre/kill", "v1")
    clients[0].incr("pre/ctr")
    failovers0 = _pvar("failovers").read()
    done = [False] * 4
    errs = []
    release = threading.Event()

    def worker(i):
        try:
            if i == 3:
                release.wait(30)
            clients[i].fence("chaos", n=4)
            done[i] = True
        except Exception as e:  # noqa: BLE001
            errs.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)    # workers 0-2 are parked inside the fence
    srv.crash()        # hard primary death, nothing flushed politely
    release.set()      # the straggler arrives — at the STANDBY
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    assert all(done), done
    assert clients[0].get("pre/kill", timeout=10) == "v1"
    # incr returns the PRE-increment value: exactly one incr happened
    # before the kill, so the replicated counter must read 1 now
    assert clients[1].incr("pre/ctr") == 1
    assert _pvar("failovers").read() > failovers0
    for c in clients:
        c.close()
    srv.close()


def test_kv_kill_class_crashes_primary_at_op_count():
    """The MCA-armed path end to end: ft_inject_plan=kv_kill:N makes
    the primary hard-crash serving its Nth op; the client's failover
    absorbs it mid-stream and every op lands."""
    saved = _set({"ft_inject_plan": "kv_kill:10"})
    try:
        srv = KVServer(2, replicas=1)
        assert srv._kill is not None, \
            "replicated server must arm the planned kv_kill"
        c = KVClient(srv.uri)
        for k in range(30):    # death at op 10, failover, keep going
            c.put(f"a/{k}", k)
        assert c.get("a/29", timeout=10) == 29
        assert c.get("a/5", timeout=10) == 5  # pre-kill data survived
        c.close()
        srv.close()
    finally:
        _restore(saved)


def test_kv_kill_not_armed_without_replica():
    """kv_kill on a replicas=0 server would kill the only copy — the
    class only arms when there is a standby to fail over to."""
    saved = _set({"ft_inject_plan": "kv_kill:10"})
    try:
        srv = KVServer(1, replicas=0)
        assert srv._kill is None
        srv.close()
    finally:
        _restore(saved)


def test_supervisor_respawns_and_heals_fault_plan(tmp_path):
    """Kill once, then heal: the first child sees the armed chaos env
    and dies; the respawn runs under respawn_env with the plan
    cleared and exits 0, which ends the loop."""
    from ompi_tpu.tools.dvm import Supervisor
    marker = str(tmp_path / "runs.txt")
    prog = ("import os,sys\n"
            f"open({marker!r},'a').write("
            "os.environ.get('PROBE_CHAOS','-')+'\\n')\n"
            "sys.exit(7 if os.environ.get('PROBE_CHAOS') else 0)\n")
    env = dict(os.environ)
    env["PROBE_CHAOS"] = "1"
    heal = dict(os.environ)
    heal.pop("PROBE_CHAOS", None)
    sup = Supervisor([sys.executable, "-c", prog], env=env,
                     respawn_env=heal)
    rc = sup.run_forever()
    assert rc == 0
    assert sup.restarts == 1
    with open(marker) as f:
        assert f.read().split() == ["1", "-"]


def test_controller_holds_shrink_while_rehydrated_sessions_parked():
    """A freshly rehydrated pool has zero active ranks and an empty
    queue — exactly what the controller's idle-shrink predicate
    matches.  Shrinking there would yank capacity from under sessions
    whose clients are mid-reconnect; the rehydrated_parked count must
    inhibit the shrink until every one resumes or detaches."""
    from ompi_tpu.serve.controller import FleetController

    class _Stub:
        capacity = 8
        active_ranks = 0
        _waiters = ()
        est_wall_us = 0
        rehydrated_parked = 2

    srv = _Stub()
    fc = FleetController(srv, floor=2, ceil=8)
    fc.shrink_ticks = 2
    now = 0
    for _ in range(10):
        now += fc.interval_ns + 1
        fc.tick(now)
    assert fc.want_capacity == 0, \
        "controller shrank a pool still holding rehydrated sessions"
    srv.rehydrated_parked = 0   # every session resumed or detached
    for _ in range(10):
        now += fc.interval_ns + 1
        fc.tick(now)
    assert fc.want_capacity == fc.floor
