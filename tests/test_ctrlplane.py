"""Control-plane fault-tolerance tests (docs/DESIGN.md §20): the
replicated KV store must survive a primary kill with ranks PARKED in
a fence (the standby completes the fence from replicated arrivals —
never re-creates it), the kv_kill/dvm_kill chaos classes must be
deterministic and off by default, and the Supervisor's respawn loop
must heal the fault plan so a killed child comes back clean."""

import os
import sys
import threading
import time

from ompi_tpu.mca.params import registry
from ompi_tpu.runtime.kvstore import KVClient, KVServer, _kv_pvars


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


def _pvar(suffix):
    for p in _kv_pvars():
        if p.full_name.endswith(suffix):
            return p
    raise AssertionError(f"no kv pvar ending with {suffix}")


def test_kill_injectors_disabled_by_default():
    """Empty plan = no injector objects: a production KVServer or DVM
    never pays for chaos plumbing."""
    from ompi_tpu import ft_inject
    assert not ft_inject.enabled()
    assert ft_inject.kv_kill_injector() is None
    assert ft_inject.dvm_kill_injector() is None


def test_kill_injector_fires_exactly_once_at_count():
    """The armed op count is deterministic (no RNG): False until op
    N, True AT op N, False forever after — a chaos run replays
    bit-for-bit."""
    from ompi_tpu.ft_inject import KillInjector
    ki = KillInjector("kv", 5)
    assert [ki.op() for _ in range(10)] == \
        [False] * 4 + [True] + [False] * 5
    # rates below 1 (a bare class name got the default rate) arm the
    # mid-run default instead of dying on the first op
    assert KillInjector("dvm", 0.02).after_ops == 64


def test_kv_primary_kill_mid_fence_failover():
    """The acceptance scenario: three clients parked in an n=4 fence
    when the primary dies.  The promoted standby holds the fence's
    replicated arrivals, the straggler lands on the standby, and ALL
    FOUR complete — plus data and counters written before the kill
    survive it."""
    srv = KVServer(4, replicas=1)
    clients = [KVClient(srv.uri) for _ in range(4)]
    clients[0].put("pre/kill", "v1")
    clients[0].incr("pre/ctr")
    failovers0 = _pvar("failovers").read()
    done = [False] * 4
    errs = []
    release = threading.Event()

    def worker(i):
        try:
            if i == 3:
                release.wait(30)
            clients[i].fence("chaos", n=4)
            done[i] = True
        except Exception as e:  # noqa: BLE001
            errs.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)    # workers 0-2 are parked inside the fence
    srv.crash()        # hard primary death, nothing flushed politely
    release.set()      # the straggler arrives — at the STANDBY
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    assert all(done), done
    assert clients[0].get("pre/kill", timeout=10) == "v1"
    # incr returns the PRE-increment value: exactly one incr happened
    # before the kill, so the replicated counter must read 1 now
    assert clients[1].incr("pre/ctr") == 1
    assert _pvar("failovers").read() > failovers0
    for c in clients:
        c.close()
    srv.close()


def test_kv_kill_class_crashes_primary_at_op_count():
    """The MCA-armed path end to end: ft_inject_plan=kv_kill:N makes
    the primary hard-crash serving its Nth op; the client's failover
    absorbs it mid-stream and every op lands."""
    saved = _set({"ft_inject_plan": "kv_kill:10"})
    try:
        srv = KVServer(2, replicas=1)
        assert srv._kill is not None, \
            "replicated server must arm the planned kv_kill"
        c = KVClient(srv.uri)
        for k in range(30):    # death at op 10, failover, keep going
            c.put(f"a/{k}", k)
        assert c.get("a/29", timeout=10) == 29
        assert c.get("a/5", timeout=10) == 5  # pre-kill data survived
        c.close()
        srv.close()
    finally:
        _restore(saved)


def test_kv_kill_not_armed_without_replica():
    """kv_kill on a replicas=0 server would kill the only copy — the
    class only arms when there is a standby to fail over to."""
    saved = _set({"ft_inject_plan": "kv_kill:10"})
    try:
        srv = KVServer(1, replicas=0)
        assert srv._kill is None
        srv.close()
    finally:
        _restore(saved)


def test_supervisor_respawns_and_heals_fault_plan(tmp_path):
    """Kill once, then heal: the first child sees the armed chaos env
    and dies; the respawn runs under respawn_env with the plan
    cleared and exits 0, which ends the loop."""
    from ompi_tpu.tools.dvm import Supervisor
    marker = str(tmp_path / "runs.txt")
    prog = ("import os,sys\n"
            f"open({marker!r},'a').write("
            "os.environ.get('PROBE_CHAOS','-')+'\\n')\n"
            "sys.exit(7 if os.environ.get('PROBE_CHAOS') else 0)\n")
    env = dict(os.environ)
    env["PROBE_CHAOS"] = "1"
    heal = dict(os.environ)
    heal.pop("PROBE_CHAOS", None)
    sup = Supervisor([sys.executable, "-c", prog], env=env,
                     respawn_env=heal)
    rc = sup.run_forever()
    assert rc == 0
    assert sup.restarts == 1
    with open(marker) as f:
        assert f.read().split() == ["1", "-"]


# -- ISSUE 16: host failure domains (docs/DESIGN.md §21) ---------------------


def test_kv_standby_placed_with_host_anti_affinity():
    """The standby's failure domain: co-resident on a one-host fleet
    (the in-process default), anti-affine when the caller names the
    off-host domain, and pinned by rte_base_kv_standby_host."""
    srv = KVServer(2, replicas=1)
    assert srv.standby.host_id == srv.host_id  # single host: co-res
    srv.close()
    srv = KVServer(2, replicas=1, host_id=0, standby_host=1)
    assert srv.host_id == 0 and srv.standby.host_id == 1
    srv.close()
    saved = _set({"rte_base_kv_standby_host": 3})
    try:
        srv = KVServer(2, replicas=1, host_id=0, standby_host=1)
        assert srv.standby.host_id == 3  # the knob pins placement
        srv.close()
    finally:
        _restore(saved)


def test_kv_host_crash_mid_fence_completes_on_off_host_standby():
    """The §21 acceptance scenario: a fence in flight when the
    primary's HOST dies completes on the anti-affine standby — the
    arrivals were already replicated across the DCN."""
    srv = KVServer(4, replicas=1, host_id=0, standby_host=1)
    clients = [KVClient(srv.uri) for _ in range(4)]
    clients[0].put("pre/host", "v1")
    done = [False] * 4
    errs = []
    release = threading.Event()

    def worker(i):
        try:
            if i == 3:
                release.wait(30)
            clients[i].fence("hostchaos", n=4)
            done[i] = True
        except Exception as e:  # noqa: BLE001
            errs.append((i, repr(e)))

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)            # workers 0-2 parked inside the fence
    assert srv.crash_host(0)   # host 0 dies: primary goes with it
    release.set()              # straggler lands on the standby
    for t in threads:
        t.join(timeout=60)
    assert not errs, errs
    assert all(done), done
    assert clients[0].get("pre/host", timeout=10) == "v1"
    for c in clients:
        c.close()
    srv.close()


def test_kv_host_crash_of_standby_degrades_replication_only():
    """Losing the STANDBY's host degrades replication but never the
    service: the primary keeps answering."""
    srv = KVServer(2, replicas=1, host_id=0, standby_host=1)
    c = KVClient(srv.uri)
    c.put("k", "v")
    assert srv.crash_host(1)
    assert srv.repl_degraded
    assert c.get("k", timeout=10) == "v"
    c.put("k2", "v2")
    assert c.get("k2", timeout=10) == "v2"
    c.close()
    srv.close()


def test_kv_client_names_anti_affinity_when_all_endpoints_share_host():
    """A standby placed WITHOUT anti-affinity dies with its primary on
    a host kill; the client's endpoint rotation must then fail with an
    error that names the misplacement and the knob — not rotate
    forever on a bare connect error."""
    import pytest
    saved = _set({"rte_base_kv_retry_max": 1,
                  "rte_base_kv_retry_delay": 0.01})
    try:
        srv = KVServer(2, replicas=1, host_id=0, standby_host=0)
        c = KVClient(srv.uri)
        c.put("k", "v")
        assert srv.crash_host(0)  # takes BOTH endpoints
        with pytest.raises(ConnectionError,
                           match="rte_base_kv_standby_host"):
            c.get("k", timeout=10)
        c.close()
        srv.close()
    finally:
        _restore(saved)


def test_controller_holds_shrink_while_hosts_rehydrating():
    """A lost host domain mid-rehydration parks its sessions at zero
    active ranks — the idle-shrink predicate's trap.  The
    hosts_rehydrating count must inhibit the shrink until the
    replacement host rejoins."""
    from ompi_tpu.serve.controller import FleetController

    class _Stub:
        capacity = 8
        active_ranks = 0
        _waiters = ()
        est_wall_us = 0
        rehydrated_parked = 0
        hosts_rehydrating = 1

    srv = _Stub()
    fc = FleetController(srv, floor=2, ceil=8)
    fc.shrink_ticks = 2
    now = 0
    for _ in range(10):
        now += fc.interval_ns + 1
        fc.tick(now)
    assert fc.want_capacity == 0, \
        "controller shrank a pool mid host-rehydration"
    srv.hosts_rehydrating = 0   # the replacement host rejoined
    for _ in range(10):
        now += fc.interval_ns + 1
        fc.tick(now)
    assert fc.want_capacity == fc.floor


def test_controller_auto_respawns_dead_hosts_when_opted_in():
    """ctrl_host_respawn=1 turns the controller into the cluster
    scheduler stand-in: its apply sweep replaces dead domains.  The
    default (0) leaves them to the operator so MTTR stays measurable."""
    from ompi_tpu.serve.controller import FleetController

    class _Stub:
        capacity = 8
        active_ranks = 0
        _waiters = ()
        est_wall_us = 0
        hosts = 2
        _host_dead = [0, 1]

        def __init__(self):
            self.respawned = []

        def respawn_host(self, h):
            self.respawned.append(h)
            self._host_dead[h] = 0
            return 1.0

    srv = _Stub()
    fc = FleetController(srv, floor=2, ceil=8)
    fc.apply()                      # default: hands off
    assert srv.respawned == []
    saved = _set({"ctrl_host_respawn": 1})
    try:
        fc.apply()
        assert srv.respawned == [1]
        fc.apply()                  # idempotent once healed
        assert srv.respawned == [1]
    finally:
        _restore(saved)


def test_controller_holds_shrink_while_rehydrated_sessions_parked():
    """A freshly rehydrated pool has zero active ranks and an empty
    queue — exactly what the controller's idle-shrink predicate
    matches.  Shrinking there would yank capacity from under sessions
    whose clients are mid-reconnect; the rehydrated_parked count must
    inhibit the shrink until every one resumes or detaches."""
    from ompi_tpu.serve.controller import FleetController

    class _Stub:
        capacity = 8
        active_ranks = 0
        _waiters = ()
        est_wall_us = 0
        rehydrated_parked = 2

    srv = _Stub()
    fc = FleetController(srv, floor=2, ceil=8)
    fc.shrink_ticks = 2
    now = 0
    for _ in range(10):
        now += fc.interval_ns + 1
        fc.tick(now)
    assert fc.want_capacity == 0, \
        "controller shrank a pool still holding rehydrated sessions"
    srv.rehydrated_parked = 0   # every session resumed or detached
    for _ in range(10):
        now += fc.interval_ns + 1
        fc.tick(now)
    assert fc.want_capacity == fc.floor
