"""pml/vprotocol pessimist: sender-based message logging for
uncoordinated checkpoints (ref: ompi/mca/vprotocol/pessimist;
VERDICT r3 missing #2)."""

import os

import numpy as np
import pytest

from ompi_tpu.mca.params import registry
from ompi_tpu.testing import mpirun_run, run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def pessimist():
    registry.set("pml_vprotocol", "pessimist")
    yield
    registry.set("pml_vprotocol", "")


def test_log_and_replay_redelivers_in_flight(pessimist):
    """The core protocol, in-process: a message consumed into the
    unexpected queue at the cut is dropped from the snapshot and
    exactly redelivered by the sender-log replay."""
    from ompi_tpu.pml.vprotocol import find

    def fn(comm):
        v = find(comm.state.pml)
        assert v is not None
        base = v._base
        if comm.rank == 0:
            comm.Send(np.arange(4, dtype=np.float64), dest=1, tag=7)
            comm.Barrier()
            # peer simulated restart: replay everything logged
            comm.Barrier()
            v.replay()
            comm.Barrier()
            return True
        # rank 1: let the message land in the unexpected queue
        while not base._unexpected.get(comm.cid):
            comm.state.progress.progress()
        comm.Barrier()
        want = base.cr_capture_lenient()
        assert len(want) >= 1
        # simulate the restart cut: drop the unconsumed message,
        # keep the counters, arm replay_want
        vlog = v.cr_capture_vlog()
        base._unexpected[comm.cid].clear()
        v.cr_restore_vlog(vlog)
        base._replay_want = {tuple(w) for w in want}
        comm.Barrier()  # sender replays now
        got = np.empty(4)
        comm.Recv(got, source=0, tag=7)
        assert (got == np.arange(4.0)).all(), got
        assert not base._replay_want
        comm.Barrier()
        return True

    assert all(run_ranks(2, fn))


def test_duplicate_replay_is_dropped(pessimist):
    """Replaying the whole log twice must deliver once: consumed
    sequence numbers are dropped, not re-matched."""
    from ompi_tpu.pml.vprotocol import find

    def fn(comm):
        v = find(comm.state.pml)
        if comm.rank == 0:
            comm.Send(np.full(2, 5.0), dest=1, tag=3)
            comm.Barrier()
            v.replay()   # gratuitous full replay
            v.replay()
            comm.Barrier()
        else:
            got = np.empty(2)
            comm.Recv(got, source=0, tag=3)
            comm.Barrier()
            comm.Barrier()
            # the replays must not create matchable duplicates
            comm.state.progress.progress()
            from ompi_tpu.pml.request import ANY_TAG
            assert comm.Iprobe(source=0, tag=3) in (False, None), \
                "duplicate redelivery"
        return True

    assert all(run_ranks(2, fn))


def test_replay_segments_large_payloads(pessimist):
    """A logged payload larger than the btl's eager limit replays as
    MSEG segments (multi-segment: 8 MiB > inproc max_send_size) and
    is reassembled + redelivered exactly (ADVICE r4: a raw MATCH
    bigger than the transport frame limit can never be pushed)."""
    from ompi_tpu.pml.vprotocol import find

    N = 1024 * 1024  # 8 MiB float64 > inproc 4 MiB max_send_size
    def fn(comm):
        v = find(comm.state.pml)
        base = v._base
        data = np.arange(N, dtype=np.float64)
        if comm.rank == 0:
            # Isend: the RNDV is never ACKed (rank 1 drops it to
            # simulate the restart cut), so a blocking Send could
            # not complete — the request is abandoned like a real
            # restart abandons the pre-crash pml
            comm.Isend(data, dest=1, tag=9)
            comm.Barrier()
            comm.Barrier()
            v.replay()
            comm.Barrier()
            return True
        # rank 1: let the RNDV land unmatched, then simulate the
        # uncoordinated-restart cut (drop unconsumed, arm wants)
        while not base._unexpected.get(comm.cid):
            comm.state.progress.progress()
        comm.Barrier()
        want = base.cr_capture_lenient()
        base._unexpected[comm.cid].clear()
        base._replay_want = {tuple(w) for w in want}
        comm.Barrier()  # sender replays now
        got = np.empty(N)
        comm.Recv(got, source=0, tag=9)
        assert got[0] == 0.0 and got[-1] == N - 1 and \
            got[N // 2] == N // 2, "reassembly corrupted payload"
        assert not base._mseg, "leaked partial reassembly"
        comm.Barrier()
        return True

    assert all(run_ranks(2, fn))


def test_replay_larger_than_shm_ring():
    """mpirun process ranks over the shm btl: replay of a payload
    larger than the ring must segment instead of raising (the
    ADVICE r4 crash scenario, end-to-end)."""
    prog = os.path.join(REPO, "tests", "_vproto_big_prog.py")
    r = mpirun_run(2, prog, mca=(("pml_vprotocol", "pessimist"),),
                   timeout=200, job_timeout=150)
    assert b"vproto big ok" in r.stdout, \
        r.stdout.decode()[-1000:] + r.stderr.decode()[-2000:]


def test_coordinated_checkpoint_gc_clears_log(pessimist, tmp_path):
    from ompi_tpu import cr
    from ompi_tpu.pml.vprotocol import find

    def fn(comm):
        v = find(comm.state.pml)
        x = np.full(4, comm.rank + 1.0)
        r = np.empty(4)
        from ompi_tpu.op import op as mpi_op
        comm.Allreduce(x, r, mpi_op.SUM)
        assert v.log_bytes >= 0
        cr.checkpoint(comm, {"x": 1}, store_dir=str(tmp_path))
        assert v.log_bytes == 0 and not v.log
        return True

    assert all(run_ranks(2, fn))


def test_receiver_ack_gc_log_plateaus(pessimist, tmp_path):
    """Soak: stream 3x the sender-log cap with periodic LOCAL
    snapshots on the receiver.  Receiver acks (snapshot-durable
    watermarks) trim the sender log in steady state: no MemoryError,
    and the log plateaus under the cap (VERDICT r4 weak #6 / next #8;
    ref: vprotocol_pessimist_sender_based.c GC protocol)."""
    import time as _time

    from ompi_tpu import cr
    from ompi_tpu.pml.vprotocol import find

    registry.set("vprotocol_pessimist_log_max_mb", "2")
    registry.set("vprotocol_pessimist_ack_interval_s", "0.02")
    store = str(tmp_path / "store")
    try:
        CHUNK = 16384       # 128 KiB float64
        TOTAL = 48          # 6 MB total traffic > 2 MB cap

        def fn(comm):
            v = find(comm.state.pml)
            data = np.zeros(CHUNK, np.float64)
            if comm.rank == 0:
                peak = 0
                for i in range(TOTAL):
                    # flow control: wait for receiver acks to trim
                    # the log before exceeding ~75% of the cap —
                    # without GC this wait never resolves
                    deadline = _time.monotonic() + 60
                    while v.log_bytes + data.nbytes > (3 << 19):
                        comm.state.progress.progress()
                        _time.sleep(0.002)
                        assert _time.monotonic() < deadline, \
                            "sender log never trimmed (GC dead)"
                    comm.Send(data, dest=1, tag=5)
                    peak = max(peak, v.log_bytes)
                comm.Barrier()
                assert peak <= (2 << 20), f"log exceeded cap: {peak}"
                return peak
            buf = np.empty(CHUNK)
            for i in range(TOTAL):
                comm.Recv(buf, source=0, tag=5)
                if i % 4 == 3:
                    cr.checkpoint_local(comm, {"i": i},
                                        store_dir=store)
            comm.Barrier()
            return 0

        res = run_ranks(2, fn)
        assert res[0] > 0  # traffic actually flowed through the log
    finally:
        registry.set("vprotocol_pessimist_log_max_mb", "256")
        registry.set("vprotocol_pessimist_ack_interval_s", "0.25")


def test_uncoordinated_checkpoint_restart_e2e(tmp_path):
    """mpirun e2e: snapshot with a message IN FLIGHT (no quiesce),
    crash, restart — the sender log replays it and the job completes
    (the capability the r3 C/R stack lacked: every checkpoint needed
    a global drain)."""
    prog = os.path.join(REPO, "tests", "_vproto_prog.py")
    store = str(tmp_path / "store")
    mca = (("pml_vprotocol", "pessimist"),)

    r1 = mpirun_run(2, prog, mca=mca,
                    extra=("--ckpt-dir", store),
                    timeout=200, job_timeout=150)
    # rank 1 died after its snapshot
    import subprocess
    env = {**os.environ, "VPROTO_CRASH": "1"}
    del r1
    import sys
    r1 = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "150", "--ckpt-dir", store,
         "--mca", "pml_vprotocol", "pessimist", prog],
        capture_output=True, timeout=200,
        env={**env, "PYTHONPATH": REPO + os.pathsep
             + env.get("PYTHONPATH", ""), "JAX_PLATFORMS": "cpu"},
        cwd=REPO)
    assert r1.returncode != 0  # crashed as scripted

    r2 = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "150", "--restart", store,
         "--mca", "pml_vprotocol", "pessimist", prog],
        capture_output=True, timeout=200,
        env={**os.environ, "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", ""),
             "JAX_PLATFORMS": "cpu"},
        cwd=REPO)
    assert r2.returncode == 0, r2.stderr.decode()[-2000:]
    assert b"vproto ok" in r2.stdout
