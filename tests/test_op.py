"""Reduction op table tests (ref: ompi/mca/op/base/op_base_functions.c
loops; MAXLOC/MINLOC pair semantics from the MPI standard)."""

import numpy as np
import pytest

from ompi_tpu.datatype import engine as dt
from ompi_tpu.op import op as mpi_op


def test_sum_prod_max_min():
    a = np.array([1, 5, 3], dtype=np.int32)
    b = np.array([4, 2, 3], dtype=np.int32)
    np.testing.assert_array_equal(mpi_op.SUM.reduce(a, b), [5, 7, 6])
    np.testing.assert_array_equal(mpi_op.PROD.reduce(a, b), [4, 10, 9])
    np.testing.assert_array_equal(mpi_op.MAX.reduce(a, b), [4, 5, 3])
    np.testing.assert_array_equal(mpi_op.MIN.reduce(a, b), [1, 2, 3])


def test_logical_vs_bitwise():
    a = np.array([2, 0, 1], dtype=np.int32)
    b = np.array([1, 1, 0], dtype=np.int32)
    np.testing.assert_array_equal(mpi_op.LAND.reduce(a, b), [1, 0, 0])
    np.testing.assert_array_equal(mpi_op.BAND.reduce(a, b), [0, 0, 0])
    np.testing.assert_array_equal(mpi_op.LOR.reduce(a, b), [1, 1, 1])
    np.testing.assert_array_equal(mpi_op.LXOR.reduce(a, b), [0, 1, 1])
    np.testing.assert_array_equal(mpi_op.BXOR.reduce(a, b), [3, 1, 1])


def test_validity():
    assert mpi_op.SUM.valid_for(np.dtype(np.float32))
    assert not mpi_op.BAND.valid_for(np.dtype(np.float32))
    assert mpi_op.BAND.valid_for(np.dtype(np.int16))
    assert mpi_op.SUM.valid_for(np.dtype(np.complex64))
    assert not mpi_op.MAX.valid_for(np.dtype(np.complex64))
    assert mpi_op.MAXLOC.valid_for(dt.FLOAT_INT.base)
    assert not mpi_op.SUM.valid_for(dt.FLOAT_INT.base)


def test_maxloc_minloc_ties():
    a = np.zeros(3, dtype=dt.DOUBLE_INT.base)
    b = np.zeros(3, dtype=dt.DOUBLE_INT.base)
    a["v"] = [1.0, 5.0, 2.0]
    a["i"] = [0, 0, 2]
    b["v"] = [3.0, 5.0, 2.0]
    b["i"] = [1, 1, 0]
    r = mpi_op.MAXLOC.reduce(a, b)
    np.testing.assert_array_equal(r["v"], [3.0, 5.0, 2.0])
    np.testing.assert_array_equal(r["i"], [1, 0, 0])  # ties → min index
    r = mpi_op.MINLOC.reduce(a, b)
    np.testing.assert_array_equal(r["v"], [1.0, 5.0, 2.0])
    np.testing.assert_array_equal(r["i"], [0, 0, 0])


def test_user_op():
    def fn(invec, inoutvec, _dt):
        inoutvec += 2 * invec

    op = mpi_op.create(fn, commute=True)
    a = np.array([1, 2], dtype=np.int64)
    b = np.array([10, 20], dtype=np.int64)
    np.testing.assert_array_equal(op.reduce(a, b), [12, 24])
    assert op.is_user and op.commute


def test_replace_noop():
    a = np.array([1.0], dtype=np.float64)
    b = np.array([2.0], dtype=np.float64)
    assert mpi_op.REPLACE.reduce(a, b)[0] == 1.0
    assert mpi_op.NO_OP.reduce(a, b)[0] == 2.0


def test_jax_binary_forms():
    import jax.numpy as jnp

    f = mpi_op.jax_binary(mpi_op.SUM)
    assert float(f(jnp.float32(2), jnp.float32(3))) == 5.0
    f = mpi_op.jax_binary(mpi_op.MAX)
    assert float(f(jnp.float32(2), jnp.float32(3))) == 3.0
    assert mpi_op.jax_binary(mpi_op.MAXLOC) is None


def test_valid_for_matches_reduce():
    """valid_for must agree with what reduce accepts."""
    pair = dt.DOUBLE_INT.base
    flt = np.dtype(np.float32)
    assert not mpi_op.MAXLOC.valid_for(flt)
    assert mpi_op.REPLACE.valid_for(pair)
    assert mpi_op.NO_OP.valid_for(pair)
    a = np.zeros(2, dtype=pair)
    b = np.ones(2, dtype=pair)
    np.testing.assert_array_equal(mpi_op.REPLACE.reduce(a, b), a)
    np.testing.assert_array_equal(mpi_op.NO_OP.reduce(a, b), b)
