"""Distributed-lock mutual exclusion under mpirun PROCESS ranks with
contention: every PE increments a shared counter on PE 0 inside the
lock via a non-atomic read-modify-write.  Lost updates are exactly
what a broken lock produces (ref: oshmem/shmem/c/shmem_lock.c)."""
import numpy as np

import ompi_tpu
from ompi_tpu import shmem

comm = ompi_tpu.init()
ctx = shmem.init(comm)
ITERS = 8
lock = ctx.malloc(1, np.int64)
counter = ctx.malloc(1, np.int64)
ctx.barrier_all()
if comm.size > 1:
    # process ranks: peer heaps are NOT addressable -> shmem_ptr NULL
    assert ctx.ptr(counter, (comm.rank + 1) % comm.size) is None
for _ in range(ITERS):
    ctx.set_lock(lock)
    v = int(ctx.g(counter, 0, 0))        # read
    ctx.p(counter, 0, v + 1, 0)          # modify-write (NOT atomic)
    ctx.win.flush(0)
    ctx.clear_lock(lock)
ctx.barrier_all()
if comm.rank == 0:
    total = int(counter.local[0])
    expect = comm.size * ITERS
    assert total == expect, f"lost updates: {total} != {expect}"
    print(f"shmem lock ok: {total}", flush=True)
shmem.finalize()
ompi_tpu.finalize()
