"""Datatype engine + convertor tests.

Modeled on the reference's datatype suite (test/datatype/ddt_test.c,
ddt_raw.c, position.c, unpack_ooo.c, external32.c): pack/unpack round
trips checked against independent numpy slicing, partial/pipelined
packing, repositioning, out-of-order unpack, external32 byte order.
"""

import numpy as np
import pytest

from ompi_tpu.datatype import engine as dt
from ompi_tpu.datatype.convertor import Convertor, pack, unpack


def roundtrip(datatype, count, src):
    """pack from src, unpack into zeroed clone, return the clone."""
    data = pack(datatype, count, src)
    assert len(data) == datatype.size * count
    dst = np.zeros_like(src)
    consumed = unpack(datatype, count, dst, data)
    assert consumed == len(data)
    return dst, data


def test_predefined_sizes():
    assert dt.INT.size == 4
    assert dt.DOUBLE.size == 8
    assert dt.FLOAT_INT.size == 8
    assert dt.INT.extent == 4
    assert dt.INT.is_contiguous


def test_contiguous_roundtrip():
    t = dt.contiguous(10, dt.INT).commit()
    assert t.size == 40 and t.extent == 40 and t.is_contiguous
    src = np.arange(10, dtype=np.int32)
    dst, data = roundtrip(t, 1, src)
    np.testing.assert_array_equal(dst, src)
    assert data == src.tobytes()


def test_vector_pack_matches_slicing():
    # 4 blocks of 3 ints, stride 5 ints
    t = dt.vector(4, 3, 5, dt.INT).commit()
    assert t.size == 4 * 3 * 4
    src = np.arange(50, dtype=np.int32)
    data = pack(t, 1, src)
    expected = np.concatenate([src[i * 5:i * 5 + 3] for i in range(4)])
    np.testing.assert_array_equal(np.frombuffer(data, np.int32), expected)
    # unpack scatters back to the same offsets
    dst = np.zeros(50, dtype=np.int32)
    unpack(t, 1, dst, data)
    ref = np.zeros(50, dtype=np.int32)
    for i in range(4):
        ref[i * 5:i * 5 + 3] = src[i * 5:i * 5 + 3]
    np.testing.assert_array_equal(dst, ref)


def test_vector_multiple_count():
    t = dt.vector(3, 2, 4, dt.FLOAT).commit()
    # extent of the vector: (count-1)*stride + blocklen = 2*4+2 = 10 floats
    assert t.extent == 10 * 4
    src = np.arange(40, dtype=np.float32)
    data = pack(t, 2, src)
    exp = []
    for e in range(2):
        for b in range(3):
            off = e * 10 + b * 4
            exp.append(src[off:off + 2])
    np.testing.assert_array_equal(np.frombuffer(data, np.float32),
                                  np.concatenate(exp))


def test_hvector_negative_stride():
    t = dt.hvector(3, 2, -16, dt.INT).commit()
    src = np.arange(20, dtype=np.int32)
    # MPI buffer pointer sits at element 8; blocks at bytes 0,-16,-32
    conv = Convertor(t, 1, src, offset=8 * 4)
    data = conv.pack()
    exp = np.concatenate([src[8:10], src[4:6], src[0:2]])
    np.testing.assert_array_equal(np.frombuffer(data, np.int32), exp)


def test_indexed():
    t = dt.indexed([2, 1, 3], [0, 4, 7], dt.DOUBLE).commit()
    assert t.size == 6 * 8
    src = np.arange(12, dtype=np.float64)
    data = pack(t, 1, src)
    exp = np.concatenate([src[0:2], src[4:5], src[7:10]])
    np.testing.assert_array_equal(np.frombuffer(data, np.float64), exp)


def test_struct_mixed_types():
    # { int a[2]; double b; } with natural alignment
    t = dt.struct([2, 1], [0, 8], [dt.INT, dt.DOUBLE]).commit()
    assert t.size == 16
    assert t.extent == 16  # aligned to 8
    raw = bytearray(32)
    np.frombuffer(raw, np.int32)[0:2] = [7, 9]
    np.frombuffer(raw, np.float64)[1] = 3.5
    np.frombuffer(raw, np.int32)[4:6] = [1, 2]
    np.frombuffer(raw, np.float64)[3] = -1.25
    data = pack(t, 2, np.frombuffer(raw, np.uint8))
    ints = np.frombuffer(data[0:8], np.int32)
    d0 = np.frombuffer(data[8:16], np.float64)[0]
    np.testing.assert_array_equal(ints, [7, 9])
    assert d0 == 3.5
    ints2 = np.frombuffer(data[16:24], np.int32)
    d1 = np.frombuffer(data[24:32], np.float64)[0]
    np.testing.assert_array_equal(ints2, [1, 2])
    assert d1 == -1.25


def test_struct_alignment_padding():
    # { char c; double d; } → extent 16 with epsilon padding
    t = dt.struct([1, 1], [0, 8], [dt.CHAR, dt.DOUBLE]).commit()
    assert t.size == 9
    assert t.extent == 16


def test_subarray_2d():
    # 6x8 array, take rows 1..3, cols 2..5 (C order)
    t = dt.subarray([6, 8], [3, 4], [1, 2], dt.ORDER_C, dt.INT).commit()
    assert t.size == 12 * 4
    assert t.extent == 48 * 4
    src = np.arange(48, dtype=np.int32).reshape(6, 8)
    data = pack(t, 1, src)
    np.testing.assert_array_equal(
        np.frombuffer(data, np.int32).reshape(3, 4), src[1:4, 2:6])


def test_subarray_3d_fortran():
    sizes, subs, starts = [4, 5, 6], [2, 3, 2], [1, 1, 3]
    t = dt.subarray(sizes, subs, starts, dt.ORDER_FORTRAN, dt.FLOAT).commit()
    src = np.arange(120, dtype=np.float32).reshape(6, 5, 4)  # F order => C rev
    data = pack(t, 1, src)
    # Fortran (i,j,k) sizes 4,5,6 == C array [6][5][4] indexed [k][j][i]
    exp = src[3:5, 1:4, 1:3]
    np.testing.assert_array_equal(
        np.frombuffer(data, np.float32), exp.ravel())


def test_darray_block():
    t = dt.darray(4, 1, [8, 8], [dt.DISTRIBUTE_BLOCK] * 2,
                  [dt.DISTRIBUTE_DFLT_DARG] * 2, [2, 2], dt.ORDER_C,
                  dt.INT).commit()
    src = np.arange(64, dtype=np.int32).reshape(8, 8)
    data = pack(t, 1, src)
    # rank 1 of a 2x2 grid in C order → block row 0, col 1
    np.testing.assert_array_equal(
        np.frombuffer(data, np.int32).reshape(4, 4), src[0:4, 4:8])


def test_resized_extent():
    t = dt.resized(dt.INT, 0, 16).commit()
    assert t.extent == 16 and t.size == 4
    src = np.arange(16, dtype=np.int32)
    data = pack(t, 4, src)
    np.testing.assert_array_equal(np.frombuffer(data, np.int32),
                                  src[[0, 4, 8, 12]])


def test_partial_pack_resume():
    """Pipelined rendezvous-style chunked packing."""
    t = dt.vector(8, 3, 5, dt.INT).commit()
    src = np.arange(64, dtype=np.int32)
    whole = pack(t, 1, src)
    conv = Convertor(t, 1, src)
    chunks = []
    while not conv.done:
        chunks.append(conv.pack(max_bytes=7))  # awkward odd chunk size
    assert b"".join(chunks) == whole


def test_partial_unpack_resume():
    t = dt.vector(8, 3, 5, dt.INT).commit()
    src = np.arange(64, dtype=np.int32)
    whole = pack(t, 1, src)
    dst = np.zeros(64, dtype=np.int32)
    conv = Convertor(t, 1, dst)
    off = 0
    for sz in (5, 11, 1, 40, 1000):
        conv.unpack(whole[off:off + sz])
        off += sz
        if off >= len(whole):
            break
    ref = np.zeros(64, dtype=np.int32)
    for i in range(8):
        ref[i * 5:i * 5 + 3] = src[i * 5:i * 5 + 3]
    np.testing.assert_array_equal(dst, ref)


def test_out_of_order_unpack():
    """unpack_ooo.c analog: segments arrive out of order, repositioned."""
    t = dt.vector(6, 4, 7, dt.DOUBLE).commit()
    src = np.arange(50, dtype=np.float64)
    whole = pack(t, 1, src)
    dst = np.zeros(50, dtype=np.float64)
    segs = [(40, 60), (0, 40), (100, len(whole)), (60, 100)]
    for lo, hi in segs:
        conv = Convertor(t, 1, dst)
        conv.set_position(lo)
        conv.unpack(whole[lo:hi])
    ref = np.zeros(50, dtype=np.float64)
    for i in range(6):
        ref[i * 7:i * 7 + 4] = src[i * 7:i * 7 + 4]
    np.testing.assert_array_equal(dst, ref)


def test_position_pack_from_middle():
    t = dt.contiguous(100, dt.INT).commit()
    src = np.arange(100, dtype=np.int32)
    conv = Convertor(t, 1, src)
    conv.set_position(40)
    data = conv.pack(max_bytes=20)
    np.testing.assert_array_equal(np.frombuffer(data, np.int32),
                                  src[10:15])


def test_external32_byteorder():
    t = dt.contiguous(4, dt.INT).commit()
    src = np.array([1, 2, 3, 4], dtype=np.int32)
    data = pack(t, 1, src, external32=True)
    np.testing.assert_array_equal(
        np.frombuffer(data, np.dtype(np.int32).newbyteorder(">")), src)
    dst = np.zeros(4, dtype=np.int32)
    unpack(t, 1, dst, data, external32=True)
    np.testing.assert_array_equal(dst, src)


def test_external32_derived():
    t = dt.vector(3, 2, 4, dt.DOUBLE).commit()
    src = np.arange(12, dtype=np.float64)
    data = pack(t, 1, src, external32=True)
    exp = np.concatenate([src[0:2], src[4:6], src[8:10]])
    np.testing.assert_array_equal(
        np.frombuffer(data, np.dtype(np.float64).newbyteorder(">")), exp)


def test_checksum():
    t = dt.contiguous(16, dt.INT).commit()
    src = np.arange(16, dtype=np.int32)
    c1 = Convertor(t, 1, src, checksum=True)
    c1.pack()
    dst = np.zeros(16, dtype=np.int32)
    c2 = Convertor(t, 1, dst, checksum=True)
    c2.unpack(src.tobytes())
    assert c1.crc == c2.crc != 0


def test_nested_vector_of_struct():
    s = dt.struct([1, 1], [0, 4], [dt.INT, dt.FLOAT]).commit()
    t = dt.vector(3, 2, 3, s).commit()
    assert t.size == 6 * 8
    raw = np.zeros(9 * 8, dtype=np.uint8)
    for i in range(9):
        raw.view(np.int32)[i * 2] = i
        raw.view(np.float32)[i * 2 + 1] = i + 0.5
    data = pack(t, 1, raw)
    got_i = np.frombuffer(data, np.int32)[0::2]
    got_f = np.frombuffer(data, np.float32)[1::2]
    exp_idx = [0, 1, 3, 4, 6, 7]
    np.testing.assert_array_equal(got_i, exp_idx)
    np.testing.assert_array_equal(got_f, np.array(exp_idx, np.float32) + 0.5)


def test_get_envelope_contents():
    t = dt.vector(4, 3, 5, dt.INT)
    ni, na, nd, comb = t.get_envelope()
    assert comb == "VECTOR" and ni == 3 and nd == 1
    comb, ints, addrs, dts = t.get_contents()
    assert ints == [4, 3, 5] and dts[0] is dt.INT


def test_lb_ub_markers():
    t = dt.struct([1, 1, 1], [-4, 0, 12],
                  [dt.LB_MARKER, dt.INT, dt.UB_MARKER]).commit()
    assert t.lb == -4 and t.ub == 12 and t.extent == 16


def test_from_numpy_dtype():
    assert dt.from_numpy_dtype(np.float32) is dt.FLOAT
    assert dt.from_numpy_dtype(np.int32) is dt.INT
    assert dt.from_numpy_dtype("float64") is dt.DOUBLE


def test_pair_type_roundtrip():
    src = np.zeros(4, dtype=dt.FLOAT_INT.base)
    src["v"] = [1.5, -2.0, 3.25, 0.0]
    src["i"] = [10, 20, 30, 40]
    dst, _ = roundtrip(dt.FLOAT_INT, 4, src)
    np.testing.assert_array_equal(dst, src)


def test_buffer_too_short_raises():
    """as_strided has no bounds checks; the convertor must."""
    t = dt.vector(4, 3, 5, dt.INT).commit()  # spans 18 ints = 72 bytes
    short = np.arange(16, dtype=np.int32)    # only 64 bytes
    with pytest.raises(IndexError):
        pack(t, 1, short)
    with pytest.raises(IndexError):
        unpack(t, 1, short, b"\0" * t.size)


def test_darray_fortran_rowmajor_rank_decomp():
    """MPI-3.1 4.1.4: rank->coords is row-major regardless of order."""
    # 2x3 grid, rank 1 => coords [0,1] (row-major), NOT [1,0]
    t = dt.darray(6, 1, [4, 6], [dt.DISTRIBUTE_BLOCK] * 2,
                  [dt.DISTRIBUTE_DFLT_DARG] * 2, [2, 3], dt.ORDER_FORTRAN,
                  dt.INT).commit()
    src = np.arange(24, dtype=np.int32).reshape(6, 4)  # F-order [4][6]
    data = pack(t, 1, src)
    # Fortran gsizes [4,6]: dim0 blocks of 2 over p=2, dim1 blocks of 2
    # over p=3; coords [0,1] -> rows 0:2 (F dim0), cols 2:4 (F dim1)
    exp = src[2:4, 0:2]  # C view: dim order reversed
    np.testing.assert_array_equal(
        np.frombuffer(data, np.int32), exp.ravel())


def test_partial_pack_is_chunk_local():
    """Pipelined chunking must not rematerialize the whole run."""
    import time
    t = dt.contiguous(4 << 20, dt.BYTE).commit()
    src = np.zeros(4 << 20, dtype=np.uint8)
    conv = Convertor(t, 1, src)
    t0 = time.perf_counter()
    n = 0
    while not conv.done:
        conv.pack(max_bytes=64 << 10)
        n += 1
    el = time.perf_counter() - t0
    assert n == 64
    assert el < 1.0  # O(N^2) behavior would take far longer


# -- on-device packing (datatype/device.py; SURVEY §2.9.1 north star) --

def test_device_pack_vector_matches_host_convertor():
    import jax.numpy as jnp
    from ompi_tpu.datatype import convertor as cv
    from ompi_tpu.datatype import engine as dt
    from ompi_tpu.datatype.device import (device_pack, device_unpack,
                                          is_device_packable)

    vec = dt.vector(5, 2, 3, dt.FLOAT).commit()
    assert is_device_packable(vec, 2)
    buf = np.arange(40, dtype=np.float32)
    host = np.frombuffer(cv.pack(vec, 2, buf), dtype=np.float32)
    dev = np.asarray(device_pack(vec, 2, jnp.asarray(buf)))
    assert np.array_equal(host, dev)
    # unpack scatters back to the same slots
    out = np.asarray(device_unpack(vec, 2, jnp.asarray(dev),
                                   jnp.zeros(40, jnp.float32)))
    ref = np.zeros(40, dtype=np.float32)
    cv.unpack(vec, 2, ref, host.tobytes())
    assert np.array_equal(out, ref)


def test_device_pack_rejects_mixed_structs():
    from ompi_tpu.datatype import engine as dt
    from ompi_tpu.datatype.device import is_device_packable

    st = dt.struct([1, 1], [0, 8], [dt.INT, dt.DOUBLE]).commit()
    assert not is_device_packable(st, 1)


def test_device_pack_indexed_and_contiguous():
    import jax.numpy as jnp
    from ompi_tpu.datatype import convertor as cv
    from ompi_tpu.datatype import engine as dt
    from ompi_tpu.datatype.device import device_pack

    idxed = dt.indexed([2, 3], [7, 0], dt.INT).commit()
    buf = np.arange(16, dtype=np.int32)
    host = np.frombuffer(cv.pack(idxed, 1, buf), dtype=np.int32)
    dev = np.asarray(device_pack(idxed, 1, jnp.asarray(buf)))
    assert np.array_equal(host, dev)
