"""Failure-path hardening tests (VERDICT r1 #8): a dead rank must
fail the whole job in seconds with a diagnostic, never hang peers;
control-plane timeouts are registry-tunable
(ref: orte/mca/errmgr/default_hnp kill-on-proc-death policy)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ompi_tpu.testing import mpirun_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VICTIM = os.path.join(REPO, "tests", "_victim_prog.py")


def _launch(np_, *extra):
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun",
           "-np", str(np_), "--timeout", "60", *extra, VICTIM]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _pid_from(stream) -> int:
    deadline = time.monotonic() + 30
    line = ""
    while time.monotonic() < deadline:
        line = stream.readline()
        if "victim pid" in line:
            return int(line.split()[-1])
    raise AssertionError(f"victim never reported its pid: {line!r}")


def test_sigkill_mid_collective_fails_job_fast():
    """SIGKILL one rank while peers sit in Allreduce: the errmgr
    must kill the job within seconds, exit nonzero, and say why."""
    p = _launch(3)
    victim = _pid_from(p.stdout)
    os.kill(victim, signal.SIGKILL)
    t0 = time.monotonic()
    out, err = p.communicate(timeout=30)
    elapsed = time.monotonic() - t0
    assert p.returncode != 0
    assert elapsed < 10, f"took {elapsed}s to react"
    assert "exited with status -9" in err
    assert "should not get here" not in out


def test_sigkill_under_simulated_nodes():
    """Same policy through the multi-node daemon path."""
    p = _launch(3, "--simulate-nodes", "3x1", "--devices", "none")
    victim = _pid_from(p.stdout)
    os.kill(victim, signal.SIGKILL)
    out, err = p.communicate(timeout=30)
    assert p.returncode != 0
    assert "terminating job" in err
    assert "should not get here" not in out


def test_modex_timeout_tunable():
    """A rank waiting for a never-published modex key fails after the
    registry-tuned timeout instead of the 30s default."""
    r = mpirun_run(
        2, os.path.join("tests", "_modex_timeout_prog.py"),
        mca=(("rte_base_modex_timeout", "2"),), timeout=60)
    assert r.returncode == 3, (r.returncode, r.stderr.decode())


def test_rendezvous_stall_raises():
    """A device-collective rendezvous with an absent peer raises a
    stall diagnostic after the tuned timeout (thread-rank world)."""
    from ompi_tpu.coll.device import Rendezvous
    from ompi_tpu.mca.params import registry

    registry.set("coll_device_rendezvous_poll", 0.05)
    registry.set("coll_device_rendezvous_timeout", 0.5)
    try:
        rv = Rendezvous(2)  # second member never arrives
        with pytest.raises(RuntimeError, match="stalled"):
            rv.run(0, object(), lambda slots: slots)
    finally:
        registry.set("coll_device_rendezvous_poll", 0.25)
        registry.set("coll_device_rendezvous_timeout", 300.0)


def _find_daemon_pid(mpirun_pid: int, node_name: str):
    """The tpud daemon process for ``node_name`` among mpirun's
    children (simulated nodes are direct subprocesses)."""
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode("latin-1").split("\0")
            with open(f"/proc/{pid}/stat") as f:
                ppid = int(f.read().split()[3])
        except (OSError, ValueError, IndexError):
            continue
        if ppid == mpirun_pid and "ompi_tpu.tools.tpud" in cmd \
                and node_name in cmd:
            return int(pid)
    return None


def test_daemon_loss_live_recovery(tmp_path):
    """VERDICT r4 missing #1 / next #3: SIGKILL a DAEMON (not a rank)
    mid-job under --simulate-nodes with the recover errmgr policy.
    The job must finish with correct results WITHOUT a full relaunch:
    the dead node's ranks are re-routed onto a survivor at a bumped
    epoch and every rank rolls back to the latest snapshot
    (ref: orte/mca/routed/radix/routed_radix.c:58,
    orte/mca/rmaps/resilient/rmaps_resilient.c:76+)."""
    prog = os.path.join(REPO, "tests", "_ft_prog.py")
    store = str(tmp_path / "store")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "3",
         "--simulate-nodes", "3x1", "--ranks-per-proc", "1",
         "--ckpt-dir", store, "--timeout", "240",
         "--verbose", "state",
         "--mca", "errmgr_base_policy", "recover", prog],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
        cwd=REPO)
    try:
        # wait until a few checkpointed steps exist, then kill sim1's
        # daemon (which kills its rank via PDEATHSIG)
        deadline = time.monotonic() + 120
        seen = b""
        while b"ft: step 3 done" not in seen:
            line = proc.stdout.readline()
            assert line or proc.poll() is None, seen.decode()[-500:]
            seen += line
            assert time.monotonic() < deadline, seen.decode()[-800:]
        dpid = _find_daemon_pid(proc.pid, "sim1")
        assert dpid is not None, "sim1 daemon not found"
        os.kill(dpid, signal.SIGKILL)

        out, err = proc.communicate(timeout=200)
        out = seen + out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    text = out.decode()
    errt = err.decode()
    assert proc.returncode == 0, text[-1200:] + errt[-2500:]
    # the re-route happened and was announced; NOT a whole-job restart
    assert "recovering in place: re-routing ranks [1]" in errt, errt
    assert "RECOVERING (re-route epoch" in errt, errt
    assert "relaunching from snapshot" not in errt, errt
    # a survivor actually went through the epoch reset
    assert "recovering (epoch 1)" in text or \
        "recovering after transport error (epoch 1)" in text, text
    # rank 1 now lives on a surviving node (sim0 or sim2), not sim1
    import re
    m = re.search(r"rank 1 on node (\w+)", text)
    assert m and m.group(1) != "sim1", text
    # correct final answer: identical to an uninterrupted run
    ref = mpirun_run(3, prog, timeout=240, job_timeout=200,
                     extra=("--ckpt-dir", str(tmp_path / "ref")))
    ref_line = [ln for ln in ref.stdout.decode().splitlines()
                if ln.startswith("final ")][0]
    line = [ln for ln in text.splitlines()
            if ln.startswith("final ")][0]
    assert line == ref_line, (line, ref_line)
