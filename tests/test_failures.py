"""Failure-path hardening tests (VERDICT r1 #8): a dead rank must
fail the whole job in seconds with a diagnostic, never hang peers;
control-plane timeouts are registry-tunable
(ref: orte/mca/errmgr/default_hnp kill-on-proc-death policy)."""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from ompi_tpu.testing import mpirun_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VICTIM = os.path.join(REPO, "tests", "_victim_prog.py")


def _launch(np_, *extra):
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun",
           "-np", str(np_), "--timeout", "60", *extra, VICTIM]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


def _pid_from(stream) -> int:
    deadline = time.monotonic() + 30
    line = ""
    while time.monotonic() < deadline:
        line = stream.readline()
        if "victim pid" in line:
            return int(line.split()[-1])
    raise AssertionError(f"victim never reported its pid: {line!r}")


def test_sigkill_mid_collective_fails_job_fast():
    """SIGKILL one rank while peers sit in Allreduce: the errmgr
    must kill the job within seconds, exit nonzero, and say why."""
    p = _launch(3)
    victim = _pid_from(p.stdout)
    os.kill(victim, signal.SIGKILL)
    t0 = time.monotonic()
    out, err = p.communicate(timeout=30)
    elapsed = time.monotonic() - t0
    assert p.returncode != 0
    assert elapsed < 10, f"took {elapsed}s to react"
    assert "exited with status -9" in err
    assert "should not get here" not in out


def test_sigkill_under_simulated_nodes():
    """Same policy through the multi-node daemon path."""
    p = _launch(3, "--simulate-nodes", "3x1", "--devices", "none")
    victim = _pid_from(p.stdout)
    os.kill(victim, signal.SIGKILL)
    out, err = p.communicate(timeout=30)
    assert p.returncode != 0
    assert "terminating job" in err
    assert "should not get here" not in out


def test_modex_timeout_tunable():
    """A rank waiting for a never-published modex key fails after the
    registry-tuned timeout instead of the 30s default."""
    r = mpirun_run(
        2, os.path.join("tests", "_modex_timeout_prog.py"),
        mca=(("rte_base_modex_timeout", "2"),), timeout=60)
    assert r.returncode == 3, (r.returncode, r.stderr.decode())


def test_rendezvous_stall_raises():
    """A device-collective rendezvous with an absent peer raises a
    stall diagnostic after the tuned timeout (thread-rank world)."""
    from ompi_tpu.coll.device import Rendezvous
    from ompi_tpu.mca.params import registry

    registry.set("coll_device_rendezvous_poll", 0.05)
    registry.set("coll_device_rendezvous_timeout", 0.5)
    try:
        rv = Rendezvous(2)  # second member never arrives
        with pytest.raises(RuntimeError, match="stalled"):
            rv.run(0, object(), lambda slots: slots)
    finally:
        registry.set("coll_device_rendezvous_poll", 0.25)
        registry.set("coll_device_rendezvous_timeout", 300.0)
