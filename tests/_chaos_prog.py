"""Chaos workload (run under mpirun by test_chaos.py with a fault
plan armed): deterministic p2p + collectives + checkpoint whose
result digest must be byte-identical to an uninjected run.  Any
undetected frame corruption, lost message, or duplicated delivery
changes the digest; any unabsorbed fault hangs or kills the job."""
import hashlib
import os

import numpy as np

import ompi_tpu
from ompi_tpu import cr
from ompi_tpu.datatype import engine as dt
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
state = comm.state
rank, size = comm.rank, comm.size

digest = hashlib.sha256()

# -- p2p ring, rendezvous-sized (past the 64 KiB tcp eager limit) ----
n = 256 * 1024
rng = np.random.default_rng(1234 + rank)
mine = rng.standard_normal(n).astype(np.float32)
got = np.empty(n, dtype=np.float32)
right, left = (rank + 1) % size, (rank - 1) % size
sreq = state.pml.isend(mine, n, dt.FLOAT, right, 11, comm)
comm.Recv(got, left, tag=11)
sreq.wait()
want = np.random.default_rng(1234 + left).standard_normal(n) \
    .astype(np.float32)
assert np.array_equal(got, want), "p2p payload corrupted"
digest.update(got.tobytes())

# -- eager-sized p2p burst (many small frames: drop/dup/reorder food) -
for i in range(16):
    small = np.full(64, float(rank * 100 + i), dtype=np.float64)
    out = np.empty(64, dtype=np.float64)
    sreq = state.pml.isend(small, 64, dt.DOUBLE, right, 20 + i, comm)
    comm.Recv(out, left, tag=20 + i)
    sreq.wait()
    assert out[0] == float(left * 100 + i), "eager burst corrupted"
    digest.update(out.tobytes())

# -- collectives ------------------------------------------------------
contrib = np.arange(1024, dtype=np.float64) * (rank + 1)
summed = np.empty(1024, dtype=np.float64)
comm.Allreduce(contrib, summed, mpi_op.SUM)
expect = np.arange(1024, dtype=np.float64) * (size * (size + 1) / 2)
assert np.allclose(summed, expect), "allreduce wrong"
digest.update(summed.tobytes())

blob = np.full(4096, 7.5, dtype=np.float32) if rank == 0 \
    else np.empty(4096, dtype=np.float32)
comm.Bcast(blob, 0)
assert float(blob[0]) == 7.5 and float(blob[-1]) == 7.5, "bcast wrong"
digest.update(blob.tobytes())

# -- checkpoint under injection (quiesce + stable snapshot) ----------
if os.environ.get(cr.ENV_DIR):
    seq = cr.checkpoint(comm, {"digest": digest.hexdigest(),
                               "rank": rank})
    digest.update(str(int(seq)).encode())

comm.Barrier()
print(f"chaos digest {rank} {digest.hexdigest()}", flush=True)
ompi_tpu.finalize()
