"""Live daemon-loss recovery job (driven by test_failures.py).

Iterates checkpointed allreduce steps.  When the test SIGKILLs a node
daemon mid-run, the launcher's recover policy re-routes the dead
node's ranks onto a survivor at a bumped epoch; surviving ranks catch
JobRecovery out of whatever collective they were parked in, perform
the epoch reset, and every rank reloads the latest snapshot.  The
final answer must equal an uninterrupted run's."""
import os
import time

import numpy as np

import ompi_tpu
from ompi_tpu import cr
from ompi_tpu.op import op as mpi_op
from ompi_tpu.runtime import ft

comm = ompi_tpu.init()
STEPS = 10
PACE = float(os.environ.get("FT_PACE_S", "0.25"))


def _dbg(msg):
    if os.environ.get("FT_DEBUG"):
        import sys
        print(f"[prog r{comm.rank}] {msg}", file=sys.stderr,
              flush=True)


def load():
    _dbg("cr.restore enter")
    s = cr.restore(comm)
    _dbg(f"cr.restore done (have={s is not None})")
    if s is None:
        return {"step": 0, "acc": np.zeros(4)}
    return s


state = load()
recoveries = 0
while state["step"] < STEPS:
    try:
        contrib = np.full(4, float(comm.rank + 1) * (state["step"] + 1))
        r = np.empty(4)
        comm.Allreduce(contrib, r, mpi_op.SUM)
        state["acc"] = state["acc"] + r
        state["step"] += 1
        cr.checkpoint(comm, state, keep=3)
        if comm.rank == 0:
            print(f"ft: step {state['step']} done", flush=True)
        time.sleep(PACE)  # a window for the test to kill a daemon
    except ft.JobRecovery as e:
        recoveries += 1
        print(f"rank {comm.rank}: recovering (epoch {e.epoch})",
              flush=True)
        ft.recover(comm, e)
        _dbg("recover returned; loading")
        state = load()
        _dbg(f"resuming at step {state['step']}")
    except Exception:  # noqa: BLE001 — transport error racing the
        #                epoch announcement (a dead peer's connection
        #                can fail a send first)
        if os.environ.get("FT_DEBUG"):
            import sys
            import traceback
            print(f"rank {comm.rank}: transport-path error:\n"
                  f"{traceback.format_exc()}",
                  file=sys.stderr, flush=True)
        exc = ft.wait_pending(comm, timeout=30.0)
        recoveries += 1
        print(f"rank {comm.rank}: recovering after transport error "
              f"(epoch {exc.epoch})", flush=True)
        ft.recover(comm, exc)
        state = load()

node = os.environ.get("TPUMPI_NODE_NAME", "local")
print(f"rank {comm.rank} on node {node} recoveries={recoveries}",
      flush=True)
if comm.rank == 0:
    print(f"final step={state['step']} acc={state['acc'].tolist()}",
          flush=True)
ompi_tpu.finalize()
