"""Asserts the coll/seg NATIVE C path engages for mpirun process
ranks (VERDICT r4 weak #3: optimizing a path that silently fell
back to Python would be noise).  Printed counters are asserted
by test_coll_seg.py."""
import numpy as np
import ompi_tpu
from ompi_tpu.op import op as mpi_op
from ompi_tpu.mca.params import registry

comm = ompi_tpu.init()
x = np.full(4, comm.rank + 1.0, dtype=np.float32)
r = np.empty_like(x)
for _ in range(20):
    comm.Allreduce(x, r, mpi_op.SUM)
nat = registry._pvars["coll_seg_native_ops"].read()
py = registry._pvars["coll_seg_python_ops"].read()
assert nat >= 20, f"native path did not engage: native={nat} python={py}"
print(f"seg pvar ok rank {comm.rank}: native={nat} python={py}", flush=True)
ompi_tpu.finalize()
