"""Waits on a modex key nobody publishes: must fail after the
registry-tuned rte_base_modex_timeout, not the built-in default."""
import time

import ompi_tpu
from ompi_tpu.runtime import state as statemod

comm = ompi_tpu.init()
t0 = time.monotonic()
try:
    statemod.current().rte.modex_get((comm.rank + 1) % comm.size,
                                     "never-published-key")
except (TimeoutError, Exception) as e:  # noqa: BLE001
    dt = time.monotonic() - t0
    assert dt < 15, f"timeout not tuned down: {dt}s"
    raise SystemExit(3)
print("should not get here", flush=True)
