"""Gray-failure plane tests (DESIGN.md §24): the health plane must
see a slow-but-alive host that no liveness grace will ever catch —
score it from signals the stack already emits, walk the hysteresis
ladder one rung per sustained streak (never a false trip on a crisp
host), stop placing on it when degraded, drain-and-migrate when
quarantined (never a failed job), and recover one rung per clean
streak.  The chaos matrix combines host_slow with rank_kill on the
OTHER host: ULFM shrink completes byte-identically while the slow
host stays degraded-not-dead."""

import json
import os
import threading
import time

import pytest

from ompi_tpu.mca.params import registry

jax = pytest.importorskip("jax")

# knob registration happens at import: an unregistered knob reads back
# None from the registry, which _restore would then "restore" as a
# None override and crash the coercion
import ompi_tpu.ft_inject  # noqa: E402,F401
import ompi_tpu.runtime.oob  # noqa: E402,F401
from ompi_tpu.obs import health as _health  # noqa: E402
from ompi_tpu.obs.health import (DEGRADED, HEALTHY,  # noqa: E402
                                 QUARANTINED, HealthPlane,
                                 HostBeatEstimator, node_degraded)
from ompi_tpu.tools.dvm import DVMServer, DvmClient  # noqa: E402

HERE = os.path.dirname(__file__)
PROG = os.path.join(HERE, "_dvm_session_prog.py")
HOST_PROG = os.path.join(HERE, "_fleet_host_prog.py")

MS = 1_000_000  # ns per ms


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


def _pv(name):
    return registry._pvars[name].read()


def _pool2(tmp_path, capacity):
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(capacity, devices=jax.devices(), uri_file=uri,
                    hosts=2).start()
    return srv, uri


def _wait_for(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _lines(stdout, kind, tag):
    out = []
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[0] == kind and parts[1] == tag:
            out.append(parts[2:])
    return out


def _reset_health(srv):
    """Leave no process-global residue (degraded-mask bits, the
    fleet_host_health level gauge) for later tests in this process."""
    hp = srv.health
    if hp is None:
        return
    for h in range(hp.hosts):
        hp.reset_host(h)
    hp.collect()


class _Beater(threading.Thread):
    """An in-process stand-in for a tpud host agent: registers on the
    pool port and beats at a test-controlled pace — exact slow-beat
    control without subprocess scheduler noise."""

    def __init__(self, uri, host, interval_s):
        super().__init__(daemon=True)
        self.uri = uri
        self.host = host
        self.interval_s = interval_s
        self._halt = threading.Event()

    def halt(self):
        self._halt.set()

    def run(self):
        c = DvmClient(self.uri)
        try:
            c._rpc({"op": "host_register", "host": self.host,
                    "pid": os.getpid()})
            while not self._halt.wait(self.interval_s):
                c._rpc({"op": "host_beat", "host": self.host})
        except Exception:
            pass  # server stopping tears the socket under us
        finally:
            try:
                c.sock.close()
            except Exception:
                pass


# -- tentpole: the audited hot tick -----------------------------------------


def test_health_tick_is_hotpath_audited():
    """HealthPlane.tick is DECLARED hot (so a refactor that starts
    allocating on the heartbeat sweep fails tier-1) and currently
    passes the audit."""
    from ompi_tpu.tools import hotpath_audit
    funcs = hotpath_audit.HOT_FUNCTIONS.get("ompi_tpu/obs/health.py")
    assert funcs and "HealthPlane.tick" in funcs
    assert hotpath_audit.audit() == []


# -- tentpole: hysteresis state machine (synthetic time, no pool) ------------


def test_hysteresis_ladder_one_rung_per_streak():
    """A slow host escalates healthy -> degraded -> quarantined one
    rung per trip streak and recovers one rung per clear streak; the
    crisp host beside it never trips (zero false positives)."""
    saved = _set({"health_enable": 1, "health_tick_ms": 1,
                  "health_trip_ticks": 2, "health_clear_ticks": 2,
                  "health_degrade_score": 40,
                  "health_quarantine_score": 75})
    base_lvl = _pv("fleet_host_health")
    try:
        expect = 100 * MS
        hp = HealthPlane(2, expect_beat_ns=expect,
                         floor_grace_ns=1000 * MS)
        t0 = 1000 * MS  # nonzero epoch: last_ns == 0 means never-beaten
        hp.note_beat(0, t0)
        hp.note_beat(1, t0)
        states = []
        t = t0
        for i in range(1, 9):  # host 1 beats once per 800ms
            t = t0 + i * expect
            hp.note_beat(0, t)
            if i % 8 == 0:
                hp.note_beat(1, t)
            hp.tick(t + 1)
            states.append(hp.state[1])
            if hp.pending[1]:
                assert hp.collect() == [1]
        # overdue rule scored host 1 before its slow beat ever arrived
        # (at t=400ms since=4x expect), then the ladder walked
        # 0 -> 1 -> 2 with trip_ticks=2 per rung — never skipping one
        assert states[-1] == QUARANTINED
        for a, b in zip(states, states[1:]):
            assert b - a <= 1, f"ladder skipped a rung: {states}"
        assert DEGRADED in states, states
        assert not hp.placement_ok(1) and hp.placement_ok(0)
        assert node_degraded(1) and not node_degraded(0)
        assert _pv("fleet_host_health") == base_lvl + 1
        assert hp.snapshot()[1]["state"] == "quarantined"

        # crisp host 0: no trips, ever
        assert hp.state[0] == HEALTHY and hp.score[0] == 0

        # recovery: crisp beats drain the EWMA, one rung per clear
        # streak back to healthy
        down = []
        for i in range(1, 16):
            t += expect
            hp.note_beat(0, t)
            hp.note_beat(1, t)
            hp.tick(t + 1)
            down.append(hp.state[1])
            if hp.pending[1]:
                assert hp.collect() == [1]
        assert down[-1] == HEALTHY, down
        for a, b in zip(down, down[1:]):
            assert a - b <= 1, f"recovery skipped a rung: {down}"
        assert DEGRADED in down, down
        hp.collect()
        assert not node_degraded(1)
        assert _pv("fleet_host_health") == base_lvl
    finally:
        _restore(saved)
        _health.set_degraded_mask(0)


def test_overdue_beat_scores_before_arrival():
    """Detection must not wait for a 10x-slowed beat to arrive: once
    a beat is 3x late the gap itself replaces the EWMA.  A host that
    NEVER beat belongs to the liveness plane and is skipped."""
    saved = _set({"health_enable": 1, "health_tick_ms": 1})
    try:
        expect = 100 * MS
        hp = HealthPlane(2, expect_beat_ns=expect,
                         floor_grace_ns=1000 * MS)
        t = 0
        for i in range(5):  # crisp EWMA for host 0; host 1 never beats
            t = i * expect
            hp.note_beat(0, t)
        hp.tick(t + 1)
        assert hp.score[0] == 0
        hp.tick(t + 9 * expect)  # silence: 9x overdue, no new beat
        assert hp.score[0] == 100
        assert hp.score[1] == 0 and hp.up_streak[1] == 0
    finally:
        _restore(saved)


# -- satellite: adaptive host-liveness grace ---------------------------------


def test_adaptive_grace_floor_and_widening():
    """A crisp host sits exactly at the static floor; a jittery-but-
    alive host widens its own grace past it (so the liveness plane
    stops declaring it dead); the consumer's beat pacing multiplier
    is honored."""
    saved = _set({"health_grace_jitter_k": 4})
    try:
        floor = 1000 * MS
        est = HostBeatEstimator(2, floor_ns=floor, mult=6)
        t0, t1 = 0, 0
        for _ in range(10):  # host 0: metronome 100ms beats
            t0 += 100 * MS
            est.note(0, t0)
        assert est.grace_ns(0) == floor
        for i in range(10):  # host 1: alternating 50ms / 450ms
            t1 += (50 if i % 2 == 0 else 450) * MS
            est.note(1, t1)
        assert est.grace_ns(1) > floor
        assert est.grace_ns(99) == floor  # out-of-range: static floor

        # mult mirrors the consumer's pacing (tpud beats at grace/6,
        # the HNP daemon at its own budget): 12 * 100ms clears a 1s
        # floor where 6 * 100ms sat on it
        est12 = HostBeatEstimator(1, floor_ns=floor, mult=12)
        t = 0
        for _ in range(10):
            t += 100 * MS
            est12.note(0, t)
        assert est12.grace_ns(0) > floor
    finally:
        _restore(saved)


# -- satellite: doctor straggler verdict -------------------------------------


def test_doctor_straggler_verdict():
    """A stalled session with no absent rank but ranks resident on a
    host the health plane scores sick gets the STRAGGLER verdict —
    naming the host, its score, and the resident ranks — instead of
    the absent-rank hunt."""
    from ompi_tpu.tools.doctor import verdict
    doc = {"sid": 3, "np": 4, "ns": "s3", "run_ms": 900,
           "est_ms": 100, "factor_pct": 300, "mttd_ms": 12,
           "placement": [0, 0, 1, 1],
           "host_health": [
               {"host": 0, "state": "healthy", "score": 0,
                "signals": [], "excluded": False},
               {"host": 1, "state": "degraded", "score": 62,
                "signals": ["beat_slow", "rdv_skew"],
                "excluded": False}]}
    text = "\n".join(verdict(doc))
    assert "VERDICT: straggler" in text
    assert "host 1 is degraded" in text and "score 62" in text
    assert "[2,3]" in text and "beat_slow" in text

    # same capture, healthy fleet: no straggler story to tell
    doc["host_health"][1]["state"] = "healthy"
    text = "\n".join(verdict(doc))
    assert "straggler" not in text
    assert "local compute" in text

    # an EXCLUDED (dead) host is the liveness plane's case, not a
    # gray-failure one
    doc["host_health"][1]["state"] = "quarantined"
    doc["host_health"][1]["excluded"] = True
    text = "\n".join(verdict(doc))
    assert "straggler" not in text


# -- satellite: whole-host evacuation via migrate ----------------------------


def test_migrate_evacuate_plans_whole_host(tmp_path):
    """--evacuate NODE computes the per-rank moves itself: every rank
    of the sick node lands round-robin on the remaining allocation;
    a prior migration's rankfile is the effective placement, so a
    second evacuation of the now-empty node is an error, not a
    silent no-op."""
    from ompi_tpu.tools.migrate import plan_evacuation
    store = tmp_path / "store"
    store.mkdir()
    (store / "job.json").write_text(json.dumps(
        {"np": 4, "simulate": "2x2", "rpp": 1, "prog": "app.py",
         "args": [], "map_by": "byslot"}))
    cmd, rankfile, moves = plan_evacuation(str(store), "sim1")
    assert moves == {2: "sim0", 3: "sim0"}
    assert "rank 2=sim0" in rankfile and "rank 3=sim0" in rankfile
    assert "--restart" in cmd and "--oversubscribe" in cmd

    with pytest.raises(ValueError, match="unknown node"):
        plan_evacuation(str(store), "nosuch")

    (store / "migrate.rankfile").write_text(rankfile)
    with pytest.raises(ValueError, match="no rank currently placed"):
        plan_evacuation(str(store), "sim1")


# -- mitigation ladder on a live pool ----------------------------------------


def test_quarantine_drains_and_replaces_placement(tmp_path):
    """A quarantined host drains its residents through the preemption
    machinery (park, not kill — the host is alive) and the next
    bring-up bands the session over healthy hosts only; new attaches
    avoid the quarantined domain too.  The host is never declared
    dead and nothing fails."""
    srv, uri = _pool2(tmp_path, 4)
    base_q = _pv("fleet_quarantines")
    base_m = _pv("fleet_migrations")
    c = DvmClient(uri)
    try:
        sid = c.attach(4)["sid"]
        r = c.run(sid, PROG, ["gq"], timeout=120)
        assert r["code"] == 0, r["stderr"][-2000:]
        sess = srv.sessions[sid]
        assert sess.placement is None  # all healthy: static banding

        hp = srv.health
        hp.state[1] = QUARANTINED
        hp.pending[1] = 1
        srv._health_applied[1] = DEGRADED
        srv._health_collect()
        assert _pv("fleet_quarantines") == base_q + 1
        assert _pv("fleet_migrations") == base_m + 1
        assert sess.parked  # idle resident: parked directly

        r2 = c.run(sid, PROG, ["gq"], timeout=120)
        assert r2["code"] == 0, r2["stderr"][-2000:]  # never a failed job
        assert sess.placement == [0, 0, 0, 0]
        assert r2["stdout"] == r["stdout"]  # placement is identity-free

        c2 = DvmClient(uri)
        # np-4 session holds all capacity; nothing else fits — check
        # the planner directly for a fresh admission
        assert srv._plan_placement(2) == [0, 0]
        c2.sock.close()
        assert srv._host_dead[1] == 0  # quarantined, never dead
        rows = c.metrics()["host_health"]
        assert rows[1]["state"] == "quarantined"
        c.detach(sid)
    finally:
        c.sock.close()
        _reset_health(srv)
        srv.stop()


def test_stats_and_metrics_expose_health(tmp_path):
    """Per-host health rows ride the metrics RPC (top's column, the
    doctor capture); stats carries the degraded/quarantined counts.
    A single-host pool has no gray-failure plane to report."""
    srv, uri = _pool2(tmp_path, 2)
    c = DvmClient(uri)
    try:
        st = c.stats()
        assert st["hosts_degraded"] == 0
        assert st["hosts_quarantined"] == 0
        m = c.metrics()
        rows = m["host_health"]
        assert len(rows) == 2
        for row in rows:
            assert row["state"] == "healthy" and row["score"] == 0
            assert row["grace_ms"] > 0
    finally:
        c.sock.close()
        srv.stop()

    uri1 = str(tmp_path / "one.uri")
    srv1 = DVMServer(2, devices=jax.devices(), uri_file=uri1).start()
    c1 = DvmClient(uri1)
    try:
        assert c1.metrics()["host_health"] is None
        assert c1.stats()["hosts_degraded"] == 0
    finally:
        c1.sock.close()
        srv1.stop()


def test_dead_host_excluded_from_health_plane(tmp_path):
    """Death stays the liveness plane's case: a killed host leaves
    the scoring sweep (excluded, state reset) so the gray-failure
    plane never quarantines a corpse, and a respawned host rejoins
    healthy with fresh estimates."""
    srv, uri = _pool2(tmp_path, 4)
    try:
        hp = srv.health
        srv.kill_host(1)
        assert hp.excluded[1] == 1 and hp.state[1] == HEALTHY
        assert not hp.placement_ok(1)
        mttr = srv.respawn_host(1)
        assert mttr > 0
        assert hp.excluded[1] == 0 and hp.state[1] == HEALTHY
        assert hp.placement_ok(1)
    finally:
        _reset_health(srv)
        srv.stop()


# -- satellite: chaos matrix — host_slow x rank_kill -------------------------


def test_chaos_matrix_host_slow_and_rank_kill(tmp_path):
    """The gray failure and a hard failure at once: host 1 runs slow
    (host_slow — beats delayed, residents crawling) while rank_kill
    takes rank 1 on the HEALTHY host.  ULFM shrink must complete with
    one consistent failure set and byte-identical survivor digests;
    the slow host ends DEGRADED — never dead, never quarantined
    (score can't reach the pinned threshold), zero failed jobs."""
    saved = _set({
        "dvm_heartbeat_s": 0.2,
        "oob_host_grace_s": 0.1,
        "health_tick_ms": 150,
        "health_trip_ticks": 1,
        "health_clear_ticks": 64,       # hold degraded for the run
        "health_quarantine_score": 101,  # unreachable: score caps at 100
        "ft_inject_plan": "host_slow,rank_kill",
        "ft_inject_skip": 0,
        "ft_inject_victim_host": 1,
        "ft_inject_victim_rank": "1",
        "ft_inject_after": 0.3,
        "ft_inject_delay_ms": 5,
    })
    base_q = _pv("fleet_quarantines")
    srv, uri = _pool2(tmp_path, 4)
    beaters = [_Beater(uri, 0, 0.08), _Beater(uri, 1, 0.6)]
    for b in beaters:
        b.start()
    c = DvmClient(uri)
    try:
        sid = c.attach(4)["sid"]
        r = c.run(sid, HOST_PROG, ["gm", "40"], timeout=240)
        assert r["code"] == 0, r["stderr"][-2000:]  # zero failed jobs
        shrinks = _lines(r["stdout"], "SHRINKS", "gm")
        digs = _lines(r["stdout"], "DIGEST", "gm")
        # survivors = 0 (host 0) and 2,3 (the SLOW host — slow ranks
        # still finish); victim rank 1 exited silently
        assert sorted(int(s[0]) for s in shrinks) == [0, 2, 3], shrinks
        assert all(int(s[1]) == 1 for s in shrinks), \
            f"a survivor saw a torn failure set: {shrinks}"
        assert len(digs) == 3 and len({d[0] for d in digs}) == 1, digs

        # the slow host is degraded-not-dead: the health plane saw it
        # (beats 3x slower than host 0's) while the adaptive grace
        # kept the liveness plane quiet
        _wait_for(lambda: srv._health_applied[1] >= DEGRADED,
                  timeout=20, what="host 1 degraded")
        assert srv._host_dead[1] == 0, "slow host declared DEAD"
        assert srv.health.state[1] == DEGRADED
        assert _pv("fleet_quarantines") == base_q  # degraded only
        # and the healthy host never tripped anything
        assert srv.health.state[0] == HEALTHY
        assert srv._health_applied[0] == 0
        st = c.stats()
        assert st["hosts_degraded"] >= 1 and st["hosts_lost"] == 0
        c.detach(sid)
    finally:
        for b in beaters:
            b.halt()
        c.sock.close()
        for b in beaters:
            b.join(timeout=5)
        _reset_health(srv)
        srv.stop()
        _restore(saved)
