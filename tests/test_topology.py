"""Locality layer: topology detection (hwloc analog), binding (rtc
analog), NIC scoring (if/reachable analog)."""

import os

import pytest

from ompi_tpu.runtime import reachable, topology


def test_detect_reports_real_cpus():
    t = topology.detect()
    assert t.ncpus >= 1
    assert t.ncores >= 1
    assert t.nnuma >= 1
    assert len(t.core_groups()) == t.ncores
    # every cpu appears in exactly one core group
    flat = [c for g in t.core_groups() for c in g]
    assert sorted(flat) == sorted(c.cpu for c in t.cpus)
    assert "core" in t.summary()


def test_numa_cpu_maps_consistent():
    t = topology.detect()
    for nid in t.numa_nodes:
        assert t.cpus_of_numa(nid)


def test_bind_core_applies_affinity():
    t = topology.detect()
    before = os.sched_getaffinity(0)
    try:
        applied = topology.apply_binding(0, "core")
        assert applied is not None
        assert set(applied) == os.sched_getaffinity(0)
        assert set(applied) <= {c.cpu for c in t.cpus}
    finally:
        os.sched_setaffinity(0, before)


def test_bind_none_is_noop():
    assert topology.apply_binding(0, "none") is None


def test_bind_unknown_raises():
    with pytest.raises(ValueError):
        topology.apply_binding(0, "sockets")


def test_device_order_snakes_torus():
    class D:
        def __init__(self, id, coords):
            self.id = id
            self.coords = coords

    # 2x2 torus: snake order keeps consecutive devices adjacent
    devs = [D(0, (0, 0)), D(1, (1, 1)), D(2, (0, 1)), D(3, (1, 0))]
    ordered = topology.device_order_for_locality(devs)
    coords = [d.coords for d in ordered]
    assert coords == [(0, 0), (0, 1), (1, 1), (1, 0)]
    for a, b in zip(coords, coords[1:]):
        assert sum(abs(x - y) for x, y in zip(a, b)) == 1  # 1 ICI hop


def test_interfaces_enumerate_with_masks():
    ifs = reachable.interfaces()
    assert ifs
    lo = [i for i in ifs if i.loopback]
    assert lo and lo[0].ip == "127.0.0.1"
    for i in ifs:
        assert i.network is not None


def test_weighted_scoring_prefers_same_network():
    eth = reachable.Interface("eth0", "10.0.0.2", "255.255.255.0",
                              True, 10000, 1500)
    same_net = reachable.score_pair(eth, "10.0.0.7")
    same_kind = reachable.score_pair(eth, "192.168.9.9")
    other = reachable.score_pair(eth, "8.8.8.8")
    assert same_net > same_kind > other > 0
    down = reachable.Interface("eth1", "10.0.0.3", "255.255.255.0",
                               False, 10000, 1500)
    assert reachable.score_pair(down, "10.0.0.7") == 0
    lo = reachable.Interface("lo", "127.0.0.1", "255.0.0.0", True,
                             -1, 65536)
    assert reachable.score_pair(lo, "10.0.0.7") == 0  # lo never routes


def test_pick_remote_addr_scores_matrix():
    # loopback is reachable (same host); an unroutable peer net still
    # picks the best candidate
    assert reachable.pick_remote_addr(["127.0.0.1"]) == "127.0.0.1"
    got = reachable.pick_remote_addr(["127.0.0.1", "10.1.2.3"])
    assert got is not None
