"""Slow DVM session workload: parks long enough for test_dvm.py to
race a halt against it — the drain must let this run finish."""
import time

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
time.sleep(1.5)
x = np.full(8, comm.rank + 1.0, np.float32)
r = np.empty_like(x)
comm.Allreduce(x, r, mpi_op.SUM)
assert abs(float(r[0]) - sum(range(1, comm.size + 1))) < 1e-3
if comm.rank == 0:
    print("DONE", flush=True)
ompi_tpu.finalize()
