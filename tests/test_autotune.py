"""coll/autotune: the online loop folding the coll_dispatch /
coll_segment trace histograms back into the calibrate profile
(DESIGN.md §13).

The round-trip gate: a skewed histogram MOVES seg_crossover_bytes,
the per-comm _pipeline_pick caches re-resolve at a collective-seq
window boundary through the put-once shared snapshot, the formerly
fused payload routes to the segmented tier — and the result stays
byte-identical across the repick (the repo's segmented-tier
discipline: algorithm changes must be invisible in the bytes)."""

import types

import numpy as np
import pytest

from ompi_tpu import trace
from ompi_tpu.coll import autotune, calibrate
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

# register every knob the snapshots below touch before snapshotting
import ompi_tpu.coll.fusion    # noqa: E402,F401
import ompi_tpu.coll.pipeline  # noqa: E402,F401

KNOBS = (
    "coll_autotune_enable", "coll_autotune_interval_ops",
    "coll_autotune_ewma", "coll_autotune_min_samples",
    "coll_autotune_window_ops", "coll_autotune_persist",
    "coll_autotune_fusion",
    "coll_tuned_use_measured_rules", "coll_tuned_profile_path",
    "coll_pipeline_enable", "coll_hier_enable",
    "coll_device_fusion_max_ops", "trace_enable",
)


@pytest.fixture(autouse=True)
def _clean():
    saved = {k: registry.get(k) for k in KNOBS}
    yield
    for k, v in saved.items():
        registry.set(k, v)
    autotune.reset()
    calibrate.reset_cache()


def _seed_profile(tmp_path, name="prof.json", **over):
    """Point the process at a crafted profile: crossovers parked at
    1 GiB so nothing routes segmented until a fold moves them."""
    path = str(tmp_path / name)
    registry.set("coll_tuned_profile_path", path)
    calibrate.reset_cache()
    prof = {
        "host": "test", "backend": "crafted", "source": "crafted",
        "host_alpha_us": 5.0, "host_gbs": 10.0, "dispatch_us": 200.0,
        "seg_bytes": 1 << 20,
        "seg_crossover_bytes": {"allreduce": 1 << 30, "bcast": 1 << 30,
                                "alltoall": 1 << 30},
        "hier_min_bytes": 1 << 30,
    }
    prof.update(over)
    calibrate.save_profile(prof, path)
    return path


def _fake_state(tr):
    """Registration target for unit-level folds: a tracer to read and
    no shared world (the fold skips the purge loop for it)."""
    return types.SimpleNamespace(
        tracer=tr, rte=types.SimpleNamespace(world=None), comms={})


# -- the round trip ---------------------------------------------------------

def test_fold_moves_crossover_and_repicks_byte_identical(tmp_path):
    """Skewed histograms (slow whole-op dispatch, fast per-segment
    meets) pull seg_crossover_bytes from 1 GiB down to 256 KiB =
    2 * seg_bytes * (seg_med/disp_med) — the 640 KB allreduce that ran
    fused before the fold runs segmented after the window boundary,
    byte-for-byte identical.  Without the skew (ratio 1) the candidate
    would be 2 MiB and the payload would stay fused: the histogram
    CONTENT, not just the fold, drives the move."""
    _seed_profile(tmp_path)
    registry.set("coll_tuned_use_measured_rules", "1")
    registry.set("coll_autotune_enable", "1")
    registry.set("coll_autotune_interval_ops", "1000000000")  # manual fold
    registry.set("coll_autotune_ewma", "1.0")
    registry.set("coll_autotune_min_samples", "8")
    registry.set("coll_autotune_window_ops", "4")
    registry.set("coll_autotune_fusion", "0")
    registry.set("coll_pipeline_enable", "1")
    registry.set("coll_hier_enable", "0")
    registry.set("trace_enable", "1")
    autotune.reset()

    from ompi_tpu.coll import pipeline

    def fn(comm):
        x = jax.device_put(
            (jnp.arange(160000, dtype=jnp.float32) % 11) + comm.rank,
            comm.device)  # 640 KB, exact-representable values
        ops0 = pipeline.pv_ops.read()
        pre = np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()
        pre2 = np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()
        ops_pre = pipeline.pv_ops.read() - ops0
        tr = comm.state.tracer
        assert tr is not None      # autotune implies a tracer
        tuner = autotune.active()
        assert tuner is not None
        if comm.rank == 0:
            # the skew: whole-op dispatch ~768 us (bucket 10), per-
            # segment meet ~96 us (bucket 7) -> ratio exactly 1/8
            tr.hists[trace.HIST_COLL_DISPATCH][10] += 200
            tr.hists[trace.HIST_COLL_SEGMENT][7] += 200
        comm.Barrier()
        if comm.rank == 0:
            assert tuner.fold() is True
        comm.Barrier()
        prof = calibrate.get_profile(create=False)
        assert prof["seg_crossover_bytes"]["allreduce"] == 262144
        # cross a window boundary so the purged picks re-resolve
        # against the folded profile (pre-fold snapshots are put-once
        # per window and must not leak forward)
        for _ in range(2 * tuner.window_ops()):
            comm.Barrier()
        ops1 = pipeline.pv_ops.read()
        post = np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()
        ops_post = pipeline.pv_ops.read() - ops1
        # put-once snapshot: every re-ask in one window is the same
        # object, so members can never see different thresholds
        win = comm._coll_seq // tuner.window_ops()
        tbl = tuner.thresholds_for(comm, win)
        assert tbl is not None and tuner.thresholds_for(comm, win) is tbl
        # ...and the pvar surface reports the applied fold
        from ompi_tpu import mpit
        mpit.init_thread()
        try:
            sess = mpit.pvar_session_create()
            folds = mpit.pvar_read(
                mpit.pvar_handle_alloc(sess, "coll_autotune_folds"))
            cx = mpit.pvar_read(mpit.pvar_handle_alloc(
                sess, "coll_autotune_seg_crossover_allreduce"))
        finally:
            mpit.finalize()
        assert folds == 1 and cx == 262144
        return pre, pre2, post, ops_pre, ops_post

    res = run_ranks(4, fn, devices=True)
    assert len({pre for pre, _, _, _, _ in res}) == 1  # ranks agree
    for pre, pre2, post, ops_pre, ops_post in res:
        assert ops_pre == 0       # fused while crossover sat at 1 GiB
        assert ops_post > 0       # segmented after the fold + window
        assert pre == pre2 == post  # the repick is invisible in bytes


# -- fold mechanics (no world) ----------------------------------------------

def test_fold_accumulates_below_min_samples(tmp_path):
    """An under-threshold window must not advance the histogram
    baselines: samples keep accumulating until min_samples is met in
    one delta, and an immediate refold with nothing new is a no-op."""
    _seed_profile(tmp_path)
    registry.set("coll_tuned_use_measured_rules", "1")
    registry.set("coll_autotune_min_samples", "32")
    registry.set("coll_autotune_ewma", "1.0")
    registry.set("coll_autotune_fusion", "0")
    tr = trace.Tracer(0, capacity=64)
    tuner = autotune.Autotuner()
    tuner.register(_fake_state(tr))
    tr.hists[trace.HIST_COLL_DISPATCH][8] += 16
    assert tuner.fold() is False           # 16 < 32: accumulate
    assert tuner.folds == 0
    tr.hists[trace.HIST_COLL_DISPATCH][8] += 16
    assert tuner.fold() is True            # both windows counted
    assert tuner.folds == 1
    prof = calibrate.get_profile(create=False)
    assert prof["autotune"]["samples"] == 32
    assert tuner.fold() is False           # baselines advanced: no news


def test_fusion_retune_clamped(tmp_path):
    """The fusion flush threshold tracks dispatch_us/host_alpha_us but
    never escapes [4, 256] — a wild histogram cannot configure the
    batcher into pathology."""
    _seed_profile(tmp_path, host_alpha_us=0.5)
    registry.set("coll_tuned_use_measured_rules", "1")
    registry.set("coll_autotune_min_samples", "1")
    registry.set("coll_autotune_ewma", "1.0")
    registry.set("coll_autotune_fusion", "1")
    tr = trace.Tracer(0, capacity=64)
    tuner = autotune.Autotuner()
    tuner.register(_fake_state(tr))
    tr.hists[trace.HIST_COLL_DISPATCH][15] += 10   # ~24.6 ms dispatch
    assert tuner.fold() is True
    assert int(registry.get("coll_device_fusion_max_ops")) == 256
    # cheap dispatch vs expensive host constant: floor clamp
    _seed_profile(tmp_path, name="prof2.json", host_alpha_us=4000.0)
    tr2 = trace.Tracer(0, capacity=64)
    tuner2 = autotune.Autotuner()
    tuner2.register(_fake_state(tr2))
    tr2.hists[trace.HIST_COLL_DISPATCH][1] += 10   # ~1.5 us dispatch
    assert tuner2.fold() is True
    assert int(registry.get("coll_device_fusion_max_ops")) == 4
