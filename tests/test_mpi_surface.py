"""Surface-completion tests: Pack/Unpack, idup/create_group,
Sendrecv_replace, CYCLIC darray, v-variant i-collectives, alltoallw,
dynamic + shared windows, dist_graph_create, generalized requests,
handle conversion (VERDICT r1 #9)."""

import numpy as np
import pytest

from ompi_tpu import mpi
from ompi_tpu.datatype import engine as dt
from ompi_tpu.datatype.convertor import Convertor
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks


# ---- pack/unpack ----------------------------------------------------

def test_pack_unpack_roundtrip():
    src = np.arange(10, dtype=np.float64)
    out = np.zeros(200, dtype=np.uint8)
    pos = mpi.MPI_Pack(src, 10, mpi.MPI_DOUBLE, out, 200, 0)
    assert pos == 80 == mpi.MPI_Pack_size(10, mpi.MPI_DOUBLE)
    # append a second typed block
    ints = np.array([7, 8, 9], dtype=np.int32)
    pos2 = mpi.MPI_Pack(ints, 3, mpi.MPI_INT32_T, out, 200, pos)
    d_out = np.zeros(10, dtype=np.float64)
    i_out = np.zeros(3, dtype=np.int32)
    p = mpi.MPI_Unpack(out, 200, 0, d_out, 10, mpi.MPI_DOUBLE)
    p = mpi.MPI_Unpack(out, 200, p, i_out, 3, mpi.MPI_INT32_T)
    assert p == pos2
    assert (d_out == src).all() and (i_out == ints).all()


def test_pack_overflow_rejected():
    src = np.arange(8, dtype=np.float64)
    out = np.zeros(16, dtype=np.uint8)
    with pytest.raises(mpi.MPIException):
        mpi.MPI_Pack(src, 8, mpi.MPI_DOUBLE, out, 16, 0)


def test_pack_derived_type():
    vec = dt.vector(3, 1, 2, dt.INT32_T)  # every other int
    src = np.arange(6, dtype=np.int32)
    out = np.zeros(64, dtype=np.uint8)
    pos = mpi.MPI_Pack(src, 1, vec, out, 64, 0)
    assert pos == 12
    back = np.zeros(6, dtype=np.int32)
    mpi.MPI_Unpack(out, 64, 0, back, 1, vec)
    assert back[::2].tolist() == [0, 2, 4]


def test_pack_external32_big_endian():
    src = np.array([1], dtype=np.int32)
    out = np.zeros(4, dtype=np.uint8)
    mpi.MPI_Pack_external("external32", src, 1, mpi.MPI_INT32_T,
                          out, 4, 0)
    assert out.tolist() == [0, 0, 0, 1]  # big-endian on the wire
    back = np.zeros(1, dtype=np.int32)
    mpi.MPI_Unpack_external("external32", out, 4, 0, back, 1,
                            mpi.MPI_INT32_T)
    assert back[0] == 1


# ---- darray CYCLIC --------------------------------------------------

def test_darray_cyclic():
    a = np.arange(10, dtype=np.int32)
    t0 = dt.darray(2, 0, [10], [dt.DISTRIBUTE_CYCLIC], [2], [2],
                   dt.ORDER_C, dt.INT32_T)
    got = np.frombuffer(Convertor(t0, 1, a).pack(), dtype=np.int32)
    assert got.tolist() == [0, 1, 4, 5, 8, 9]
    # the four ranks of a 2x2 cyclic(1) grid tile 4x4 exactly once
    g = np.arange(16, dtype=np.int32)
    allidx = []
    for r in range(4):
        tr = dt.darray(4, r, [4, 4], [dt.DISTRIBUTE_CYCLIC] * 2,
                       [dt.DISTRIBUTE_DFLT_DARG] * 2, [2, 2],
                       dt.ORDER_C, dt.INT32_T)
        allidx += np.frombuffer(Convertor(tr, 1, g).pack(),
                                dtype=np.int32).tolist()
    assert sorted(allidx) == list(range(16))


# ---- communicator extras --------------------------------------------

def test_idup_and_create_group():
    def fn(comm):
        d, req = comm.idup()
        req.wait()
        assert d.size == comm.size and d.cid != comm.cid
        # create_group: only even ranks participate
        from ompi_tpu.comm.communicator import Group
        evens = Group([g for i, g in enumerate(comm.group)
                       if i % 2 == 0])
        if comm.rank % 2 == 0:
            sub = comm.create_group(evens, tag=3)
            assert sub.size == (comm.size + 1) // 2
            r = np.empty(1)
            sub.Allreduce(np.array([1.0]), r, mpi_op.SUM)
            assert r[0] == sub.size
        # odd ranks do NOT call create_group at all
        return True

    assert run_ranks(5, fn) == [True] * 5


def test_sendrecv_replace_ring():
    def fn(comm):
        buf = np.array([float(comm.rank)])
        comm.Sendrecv_replace(buf, (comm.rank + 1) % comm.size, 5,
                              (comm.rank - 1) % comm.size, 5)
        assert buf[0] == float((comm.rank - 1) % comm.size)
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_cart_reorder_by_device_order():
    """reorder=True orders cart ranks by device id (treematch analog
    with the mesh as the distance metric)."""
    import jax

    ndev = len(jax.devices())

    def fn(comm):
        cart = comm.Create_cart([comm.size], [True], reorder=True)
        # reordered cart rank should follow device-id order
        my_dev = comm.device.id if comm.device else None
        return (cart.rank, my_dev)

    if ndev < 4:
        pytest.skip("needs >= 4 devices")
    res = run_ranks(4, fn, devices=True)
    by_dev = sorted(range(4), key=lambda r: res[r][1])
    assert [res[r][0] for r in by_dev] == [0, 1, 2, 3]


# ---- v-variant i-collectives + alltoallw ----------------------------

def test_igatherv_iscatterv():
    def fn(comm):
        n = comm.size
        rcounts = [i + 1 for i in range(n)]
        displs = [sum(rcounts[:i]) for i in range(n)]
        sarr = np.full(comm.rank + 1, float(comm.rank), dtype=np.float64)
        if comm.rank == 0:
            rbuf = np.zeros(sum(rcounts), dtype=np.float64)
            req = comm.Igatherv(sarr, rbuf, rcounts, displs, root=0)
            req.wait()
            for r in range(n):
                seg = rbuf[displs[r]:displs[r] + rcounts[r]]
                assert (seg == float(r)).all()
        else:
            comm.Igatherv(sarr, None, rcounts, displs, root=0).wait()
        # iscatterv back
        rbuf2 = np.zeros(comm.rank + 1, dtype=np.float64)
        if comm.rank == 0:
            sbuf = np.concatenate([np.full(i + 1, 10.0 + i)
                                   for i in range(n)])
            comm.Iscatterv(sbuf, rcounts, displs, rbuf2, root=0).wait()
        else:
            comm.Iscatterv(None, rcounts, displs, rbuf2, root=0).wait()
        assert (rbuf2 == 10.0 + comm.rank).all()
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_alltoallw_mixed_types():
    def fn(comm):
        n = comm.size
        # one float64 to each peer, addressed by byte displacements
        sbuf = np.array([comm.rank * 10.0 + p for p in range(n)])
        rbuf = np.zeros(n, dtype=np.float64)
        counts = [1] * n
        sdispls = [8 * p for p in range(n)]
        rdispls = [8 * p for p in range(n)]
        types = [mpi.MPI_DOUBLE] * n
        mpi.MPI_Alltoallw(sbuf, counts, sdispls, types, rbuf, counts,
                          rdispls, types, comm)
        assert rbuf.tolist() == [p * 10.0 + comm.rank for p in range(n)]
        return True

    assert run_ranks(3, fn) == [True] * 3


# ---- windows: dynamic + shared --------------------------------------

def test_dynamic_window_attach_put():
    def fn(comm):
        from ompi_tpu.osc import window as oscmod
        win = oscmod.create_dynamic(comm)
        region = np.zeros(4, dtype=np.int64)
        win.attach(region)
        addr = mpi.MPI_Get_address(region)
        addrs = np.zeros(comm.size, dtype=np.int64)
        comm.Allgather(np.array([addr], dtype=np.int64), addrs)
        win.lock_all()
        right = (comm.rank + 1) % comm.size
        win.put(np.array([comm.rank + 1], dtype=np.int64), right,
                disp=int(addrs[right]))
        win.flush_all()
        comm.Barrier()
        left = (comm.rank - 1) % comm.size
        assert region[0] == left + 1, (comm.rank, region)
        win.unlock_all()
        win.detach(region)
        win.free()
        return True

    assert run_ranks(3, fn) == [True] * 3


def test_shared_window_direct_store():
    def fn(comm):
        from ompi_tpu.osc import window as oscmod
        win = oscmod.allocate_shared(comm, 8)
        mine = win.memory.view(np.int64)
        mine[0] = comm.rank + 100
        comm.Barrier()
        # direct load of a PEER's segment, no RMA call at all
        n, du, peer_seg = oscmod.shared_query(
            win, (comm.rank + 1) % comm.size)
        assert n == 8
        assert peer_seg.view(np.int64)[0] == \
            (comm.rank + 1) % comm.size + 100
        comm.Barrier()
        win.free()
        return True

    assert run_ranks(3, fn) == [True] * 3


# ---- dist_graph_create general form ---------------------------------

def test_dist_graph_create_general():
    def fn(comm):
        from ompi_tpu.topo.topo import dist_graph_create
        # rank 0 declares the whole ring; everyone else declares none
        if comm.rank == 0:
            sources = list(range(comm.size))
            degrees = [1] * comm.size
            dests = [(s + 1) % comm.size for s in range(comm.size)]
        else:
            sources, degrees, dests = [], [], []
        g = dist_graph_create(comm, sources, degrees, dests)
        assert g.topo.out_neighbors(g.rank) == \
            [(comm.rank + 1) % comm.size]
        assert g.topo.in_neighbors(g.rank) == \
            [(comm.rank - 1) % comm.size]
        return True

    assert run_ranks(4, fn) == [True] * 4


# ---- requests + misc -------------------------------------------------

def test_grequest_lifecycle():
    def fn(comm):
        log = []
        req = mpi.MPI_Grequest_start(
            query_fn=lambda extra, st: log.append(("q", extra)),
            free_fn=lambda extra: log.append(("f", extra)),
            extra_state="xs")
        assert not req.complete
        mpi.MPI_Grequest_complete(req)
        assert req.complete and ("q", "xs") in log
        req.free()
        assert ("f", "xs") in log
        return True

    assert run_ranks(1, fn) == [True]


def test_testany_testsome_and_get_status():
    def fn(comm):
        from ompi_tpu.pml.request import test_any, test_some
        if comm.rank == 0:
            reqs = [comm.Irecv(np.zeros(1), 1, t) for t in (1, 2)]
            assert test_any([]) == (-1, None)
            comm.Send(np.zeros(0), 1, 9)  # release peer
            while True:
                done = test_some(reqs)
                if len(done) == 2:
                    break
                comm.state.progress.progress()
            flag, st = mpi.MPI_Request_get_status(reqs[0])
            assert flag and st.tag == 1
        else:
            comm.Recv(np.zeros(0), 0, 9)
            comm.Send(np.ones(1), 0, 1)
            comm.Send(np.ones(1), 0, 2)
        return True

    assert run_ranks(2, fn) == [True, True]


def test_reduce_local_and_op_bindings():
    a = np.array([1.0, 5.0])
    b = np.array([4.0, 2.0])
    mpi.MPI_Reduce_local(a, b, 2, mpi.MPI_DOUBLE, mpi.MPI_MAX)
    assert b.tolist() == [4.0, 5.0]
    myop = mpi.MPI_Op_create(lambda x, y: x + y * 2, commute=False)
    assert not mpi.MPI_Op_commutative(myop)


def test_error_class_registry():
    c = mpi.MPI_Add_error_class()
    assert c > mpi.MPI_ERR_LASTCODE
    mpi.MPI_Add_error_string(c, "my custom failure")
    assert mpi.MPI_Error_string(c) == "my custom failure"
    code = mpi.MPI_Add_error_code(c)
    assert code > c


def test_handle_conversion_roundtrip():
    inf = mpi.MPI_Info_create()
    h = mpi.MPI_Info_c2f(inf)
    assert mpi.MPI_Info_f2c(h) is inf
    assert mpi.MPI_Info_c2f(inf) == h  # stable
    with pytest.raises(ValueError):
        mpi.MPI_Comm_f2c(999999)


def test_f90_and_match_size():
    assert mpi.MPI_Type_match_size(mpi.MPI_TYPECLASS_REAL, 8) \
        is mpi.MPI_DOUBLE
    assert mpi.MPI_Type_create_f90_real(6, 30) is mpi.MPI_FLOAT
    assert mpi.MPI_Type_create_f90_integer(15) is mpi.MPI_INT64_T


def test_get_elements_partial():
    from ompi_tpu.pml.request import Status
    st = Status()
    # 2xINT32 pair type, received 6 bytes = 1 full element + 2 bytes
    pair = dt.contiguous(2, dt.INT32_T)
    st.count = 10
    assert mpi.MPI_Get_elements(st, pair) == 2  # 8 full + 2 trailing
    st.count = 16
    assert mpi.MPI_Get_elements(st, pair) == 4


def test_type_envelope_contents():
    v = dt.vector(3, 2, 4, dt.INT32_T)
    comb, ints, addrs, dts = mpi.MPI_Type_get_envelope(v)
    assert comb == "VECTOR"
    assert mpi.MPI_Type_get_envelope(dt.INT32_T)[0] == \
        mpi.MPI_COMBINER_NAMED
    with pytest.raises(ValueError):
        mpi.MPI_Type_get_contents(dt.INT32_T)


def test_version_and_misc():
    assert mpi.MPI_Get_version() == (3, 1)
    assert "ompi_tpu" in mpi.MPI_Get_library_version()
    assert mpi.MPI_Wtick() > 0
    assert mpi.MPI_Aint_add(100, 8) == 108
    mem = mpi.MPI_Alloc_mem(64)
    assert mem.nbytes == 64
    mpi.MPI_Free_mem(mem)


def test_ialltoallw_and_ineighbor_alltoallw():
    """Surface tail (VERDICT r3 #8): the nonblocking w-variants."""
    def fn(comm):
        n = comm.size
        sbuf = np.array([comm.rank * 10.0 + p for p in range(n)])
        rbuf = np.zeros(n, dtype=np.float64)
        counts = [1] * n
        displs = [8 * p for p in range(n)]
        types = [mpi.MPI_DOUBLE] * n
        req = mpi.MPI_Ialltoallw(sbuf, counts, displs, types, rbuf,
                                 counts, displs, types, comm)
        req.wait()
        assert rbuf.tolist() == [p * 10.0 + comm.rank
                                 for p in range(n)]

        # ring cart: one double to each of left/right
        cart = comm.Create_cart([n], periods=[True])
        nbrs = cart.topo.in_neighbors(cart.rank)
        k = len(nbrs)
        s2 = np.array([cart.rank + 100.0 * i for i in range(k)])
        r2 = np.zeros(k, dtype=np.float64)
        cnt = [1] * k
        dsp = [8 * i for i in range(k)]
        tps = [mpi.MPI_DOUBLE] * k
        req = mpi.MPI_Ineighbor_alltoallw(s2, cnt, dsp, tps, r2, cnt,
                                          dsp, tps, cart)
        req.wait()
        # each neighbor sent us the slot addressed to us in its
        # out-neighbor order
        for i, src in enumerate(nbrs):
            their_out = cart.topo.out_neighbors(src)
            j = their_out.index(cart.rank)
            assert r2[i] == src + 100.0 * j, (r2, i, src, j)
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_register_datarep_roundtrip():
    """MPI_Register_datarep: user representation applied on the file
    byte path; duplicate names rejected."""
    import os
    import tempfile

    def fn(comm):
        from ompi_tpu.io import file as iof

        def enc(raw, dt, count, extra):
            return bytes(b ^ extra for b in raw)

        name = f"xor_rep_{comm.state.rank}"
        mpi.MPI_Register_datarep(name, read_conversion_fn=enc,
                                 write_conversion_fn=enc,
                                 extra_state=0x5A)
        try:
            mpi.MPI_Register_datarep(name)
            return False  # duplicate must raise
        except ValueError:
            pass
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "f.dat")
            self_comm = comm.Split(comm.rank)  # per-rank file
            fh = iof.open(self_comm, path,
                          iof.MODE_CREATE | iof.MODE_RDWR)
            fh.set_view(0, datarep=name)
            x = np.arange(8, dtype=np.float64)
            fh.write_at(0, x)
            got = np.zeros_like(x)
            fh.read_at(0, got)
            assert (got == x).all()
            # on disk the bytes are the CONVERTED representation
            disk = np.fromfile(path, dtype=np.uint8)
            plain = x.view(np.uint8)
            assert not np.array_equal(disk[:64], plain)
            assert np.array_equal(disk[:64] ^ 0x5A, plain)
            fh.close()
        return True

    assert run_ranks(2, fn) == [True] * 2
