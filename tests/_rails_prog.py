"""Multi-rail striping exerciser: a rendezvous-sized transfer over
btl self,tcp with btl_tcp_rails>1 must land FRAG segments on more
than one rail (pvar-counted) and arrive intact."""
import numpy as np

import ompi_tpu
from ompi_tpu.mca.params import registry

comm = ompi_tpu.init()
N = 4 * 1024 * 1024 // 8  # 4 MiB of float64 >> eager limit
if comm.rank == 0:
    x = np.arange(N, dtype=np.float64)
    comm.Send(x, dest=1, tag=5)
    comm.Barrier()
    counts = []
    for pv in registry.all_pvars():
        if pv.full_name.startswith("btl_tcp_rail") and \
                pv.full_name.endswith("_frags_r0"):
            counts.append((pv.full_name, pv.read()))
    counts.sort()
    used = sum(1 for _, c in counts if c and c > 0)
    print(f"rails used={used} counts={counts}", flush=True)
else:
    got = np.empty(N, dtype=np.float64)
    comm.Recv(got, source=0, tag=5)
    assert got[0] == 0.0 and got[-1] == float(N - 1)
    step = max(1, N // 997)
    idx = np.arange(0, N, step)
    assert (got[idx] == idx.astype(np.float64)).all()
    comm.Barrier()
ompi_tpu.finalize()
