"""MPI-IO: views, individual/shared/ordered/collective IO (ref:
ompi/mca/io/ompio + fcoll/two_phase; test spirit of ROMIO's
coll_test/atomicity programs)."""

import os

import numpy as np
import pytest

from ompi_tpu import io as mpiio
from ompi_tpu.datatype import engine as dt
from ompi_tpu.io.view import FileView
from ompi_tpu.testing import run_ranks

RW = mpiio.MODE_CREATE | mpiio.MODE_RDWR


# -- FileView mapping (pure) ------------------------------------------------

def test_view_default_is_byte_stream():
    v = FileView()
    assert v.map_bytes(10, 4) == [(10, 4)]


def test_view_disp_and_etype_units():
    v = FileView(disp=100, etype=dt.DOUBLE)
    assert v.map_bytes(2, 24) == [(100 + 16, 24)]


def test_view_strided_filetype():
    # filetype: 1 double taken, 1 skipped (double resized to extent 16
    # — the MPI idiom for interleaved views)
    ft = dt.resized(dt.DOUBLE, 0, 16)
    v = FileView(disp=0, etype=dt.DOUBLE, filetype=ft)
    assert v.tile_bytes == 8 and v.tile_extent == 16
    # element i lands at byte 16*i
    assert v.map_bytes(0, 8) == [(0, 8)]
    assert v.map_bytes(1, 8) == [(16, 8)]
    assert v.map_bytes(0, 24) == [(0, 8), (16, 8), (32, 8)]


def test_view_block_cyclic():
    # 2 doubles mine, 4 doubles extent (2-rank interleave)
    ft = dt.resized(dt.contiguous(2, dt.DOUBLE), 0, 32)
    v0 = FileView(0, dt.DOUBLE, ft)
    v1 = FileView(16, dt.DOUBLE, ft)
    assert v0.map_bytes(0, 32) == [(0, 16), (32, 16)]
    assert v1.map_bytes(0, 32) == [(16, 16), (48, 16)]
    # mid-tile start
    assert v0.map_bytes(1, 16) == [(8, 8), (32, 8)]


def test_view_rejects_bad_etype_multiple():
    with pytest.raises(ValueError):
        FileView(0, dt.DOUBLE, dt.contiguous(3, dt.INT32_T))


# -- individual IO ----------------------------------------------------------

def test_write_read_at(tmp_path):
    path = str(tmp_path / "wr.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        n = 16
        data = np.arange(n, dtype=np.float64) + comm.rank * 100
        f.write_at(comm.rank * n * 8, data)
        f.sync()
        comm.Barrier()
        peer = (comm.rank + 1) % comm.size
        got = np.empty(n, dtype=np.float64)
        f.read_at(peer * n * 8, got)
        f.close()
        return got

    res = run_ranks(3, fn)
    for rank, got in enumerate(res):
        peer = (rank + 1) % 3
        np.testing.assert_allclose(got,
                                   np.arange(16, dtype=np.float64)
                                   + peer * 100)


def test_individual_pointer_seek_tell(tmp_path):
    path = str(tmp_path / "seek.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.set_view(0, dt.DOUBLE)   # positions in doubles now
        if comm.rank == 0:
            f.write(np.array([1.0, 2.0, 3.0]))
            assert f.get_position() == 3
            f.seek(1)
            out = np.zeros(2)
            f.read(out)
            assert f.get_position() == 3
            f.seek(-1, mpiio.SEEK_CUR)
            assert f.get_position() == 2
            f.seek(0, mpiio.SEEK_END)
            end = f.get_position()
            f.close()
            return (list(out), end)
        f.close()
        return None

    out, end = run_ranks(2, fn)[0]
    assert out == [2.0, 3.0] and end == 3


def test_eof_read_zero_fills(tmp_path):
    path = str(tmp_path / "eof.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        if comm.rank == 0:
            f.write_at(0, np.array([7.0]))
        f.sync()
        comm.Barrier()
        out = np.full(4, -1.0)
        f.read_at(0, out)
        f.close()
        return list(out)

    for r in run_ranks(2, fn):
        assert r == [7.0, 0.0, 0.0, 0.0]


def test_file_size_ops_and_delete(tmp_path):
    path = str(tmp_path / "size.bin")

    def fn(comm):
        f = mpiio.open(comm, path,
                       RW | mpiio.MODE_DELETE_ON_CLOSE)
        if comm.rank == 0:
            f.set_size(1024)
        f.sync()
        comm.Barrier()
        s = f.get_size()
        f.close()
        return s

    assert run_ranks(2, fn) == [1024, 1024]
    assert not os.path.exists(path)


def test_collective_open_failure_raises_everywhere(tmp_path):
    path = str(tmp_path / "nonexistent" / "x.bin")

    def fn(comm):
        try:
            mpiio.open(comm, path, mpiio.MODE_RDONLY)
            return "no-error"
        except OSError:
            return "ok"

    assert run_ranks(2, fn) == ["ok", "ok"]


def test_iwrite_iread_requests(tmp_path):
    path = str(tmp_path / "nb.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        if comm.rank == 0:
            f.iwrite_at(0, np.arange(8, dtype=np.int64)).wait()
        f.sync()
        comm.Barrier()
        out = np.zeros(8, dtype=np.int64)
        st = f.iread_at(0, out).wait()
        f.close()
        return (list(out), st.count)

    for out, count in run_ranks(2, fn):
        assert out == list(range(8)) and count == 64


def test_write_all_wronly_no_rmw_crash(tmp_path):
    # WRONLY + collective write with holes must not pread (EBADF)
    path = str(tmp_path / "wronly.bin")

    def fn(comm):
        f = mpiio.open(comm, path,
                       mpiio.MODE_CREATE | mpiio.MODE_WRONLY)
        f.set_view(0, dt.DOUBLE)
        data = np.full(4, comm.rank + 1.0)
        f.write_at_all(comm.rank * 8, data)   # hole at [4,8)
        f.close()
        return "ok"

    assert run_ranks(2, fn) == ["ok", "ok"]
    raw = np.fromfile(path, dtype=np.float64)
    assert list(raw[:4]) == [1.0] * 4 and list(raw[8:12]) == [2.0] * 4


def test_append_mode_starts_at_eof(tmp_path):
    path = str(tmp_path / "append.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.set_view(0, dt.DOUBLE)
        if comm.rank == 0:
            f.write_at(0, np.full(4, 1.0))
        f.close()
        f = mpiio.open(comm, path,
                       mpiio.MODE_RDWR | mpiio.MODE_APPEND)
        f.set_view(0, dt.DOUBLE)
        f.seek(0, mpiio.SEEK_END)  # view reset pos; append-like seek
        start = 4
        if comm.rank == 0:
            f.write_at(start, np.full(2, 2.0))  # explicit offset works
        f.sync()
        comm.Barrier()
        out = np.zeros(6)
        f.read_at(0, out)
        f.close()
        return list(out)

    res = run_ranks(2, fn)
    assert res[0] == [1.0] * 4 + [2.0] * 2


def test_read_count_reports_actual_at_eof(tmp_path):
    path = str(tmp_path / "count.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        if comm.rank == 0:
            f.write_at(0, np.full(1, 3.0))  # 8 bytes in file
        f.sync()
        comm.Barrier()
        out = np.zeros(4)
        st = f.read_at(0, out)
        f.close()
        return st.count

    assert run_ranks(2, fn) == [8, 8]   # not the padded 32


def test_seek_invalid_leaves_position(tmp_path):
    path = str(tmp_path / "seekerr.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.seek(2)
        try:
            f.seek(-5, mpiio.SEEK_CUR)
            out = "no-error"
        except ValueError:
            out = f.get_position()
        f.close()
        return out

    assert run_ranks(2, fn) == [2, 2]


# -- views over real files --------------------------------------------------

def test_interleaved_views_write_then_read_whole(tmp_path):
    path = str(tmp_path / "interleave.bin")
    n_each = 4  # doubles per rank per tile

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        ft = dt.resized(dt.contiguous(n_each, dt.DOUBLE), 0,
                        n_each * comm.size * 8)
        f.set_view(comm.rank * n_each * 8, dt.DOUBLE, ft)
        data = np.full(2 * n_each, comm.rank * 1.0)  # two tiles worth
        f.write(data)
        f.sync()
        comm.Barrier()
        # read back raw (fresh view) on rank 0
        f.set_view(0, dt.DOUBLE)
        whole = np.zeros(2 * n_each * comm.size)
        f.read_at(0, whole)
        f.close()
        return list(whole)

    res = run_ranks(3, fn)
    expect = []
    for tile in range(2):
        for rank in range(3):
            expect += [float(rank)] * n_each
    assert res[0] == expect


# -- shared / ordered -------------------------------------------------------

def test_write_shared_disjoint_records(tmp_path):
    path = str(tmp_path / "shared.bin")
    rec = 8

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        data = np.full(rec, comm.rank * 1.0)
        for _ in range(2):
            f.write_shared(data)
        f.sync()
        comm.Barrier()
        out = np.full(rec * 2 * comm.size, -1.0)
        f.read_at(0, out)
        pos = f.get_position_shared()
        f.close()
        return (list(out), pos)

    res = run_ranks(3, fn)
    out, pos = res[0]
    # every record is a contiguous run of one rank's value; all present
    recs = [tuple(out[i * rec:(i + 1) * rec]) for i in range(6)]
    assert all(len(set(r)) == 1 for r in recs)
    vals = sorted(r[0] for r in recs)
    assert vals == [0.0, 0.0, 1.0, 1.0, 2.0, 2.0]
    # positions are etype units (bytes, the default view): 6 records
    # of 8 doubles = 384
    assert pos == 6 * rec * 8
    assert res[1][1] == pos and res[2][1] == pos


def test_write_ordered_rank_order(tmp_path):
    path = str(tmp_path / "ordered.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.set_view(0, dt.DOUBLE)
        data = np.full(comm.rank + 1, comm.rank * 1.0)  # varying sizes
        f.write_ordered(data)
        f.sync()
        comm.Barrier()
        out = np.full(6, -1.0)
        f.read_at(0, out)
        f.close()
        return list(out)

    res = run_ranks(3, fn)
    assert res[0] == [0.0, 1.0, 1.0, 2.0, 2.0, 2.0]


def test_seek_shared_resets(tmp_path):
    path = str(tmp_path / "seeksh.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.write_shared(np.zeros(4, dtype=np.uint8))
        comm.Barrier()
        f.seek_shared(0)
        p = f.get_position_shared()
        f.close()
        return p

    assert run_ranks(2, fn) == [0, 0]


# -- collective two-phase ---------------------------------------------------

@pytest.mark.parametrize("naggs", [0, 1, 2])
def test_write_at_all_contiguous_blocks(tmp_path, naggs):
    from ompi_tpu.mca.params import registry
    path = str(tmp_path / f"wall{naggs}.bin")
    registry.set("io_fcoll_num_aggregators", naggs)
    try:
        def fn(comm):
            f = mpiio.open(comm, path, RW)
            n = 32
            data = np.arange(n, dtype=np.float64) + comm.rank * 1000
            f.write_at_all(comm.rank * n * 8, data)
            f.sync()
            comm.Barrier()
            out = np.zeros(n * comm.size, dtype=np.float64)
            f.read_at(0, out)
            f.close()
            return out

        res = run_ranks(3, fn)
        expect = np.concatenate(
            [np.arange(32, dtype=np.float64) + r * 1000 for r in range(3)])
        np.testing.assert_allclose(res[0], expect)
    finally:
        registry.set("io_fcoll_num_aggregators", 0)


def test_write_at_all_interleaved_views(tmp_path):
    path = str(tmp_path / "wallview.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        ft = dt.resized(dt.contiguous(2, dt.DOUBLE), 0,
                        2 * comm.size * 8)
        f.set_view(comm.rank * 16, dt.DOUBLE, ft)
        data = np.full(6, comm.rank * 1.0)  # 3 tiles of 2
        f.write_at_all(0, data)
        f.sync()
        comm.Barrier()
        f.set_view(0, dt.DOUBLE)
        whole = np.zeros(6 * comm.size)
        f.read_at(0, whole)
        f.close()
        return list(whole)

    res = run_ranks(4, fn)
    expect = []
    for tile in range(3):
        for rank in range(4):
            expect += [float(rank)] * 2
    assert res[0] == expect


def test_read_at_all_roundtrip(tmp_path):
    path = str(tmp_path / "rall.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        n = 16
        if comm.rank == 0:
            allv = np.arange(n * comm.size, dtype=np.float64)
            f.write_at(0, allv)
        f.sync()
        comm.Barrier()
        mine = np.zeros(n, dtype=np.float64)
        f.read_at_all(comm.rank * n * 8, mine)
        f.close()
        return mine

    res = run_ranks(4, fn)
    for rank, got in enumerate(res):
        np.testing.assert_allclose(
            got, np.arange(16, dtype=np.float64) + rank * 16)


def test_write_all_gap_preserves_existing(tmp_path):
    # ranks write disjoint NON-adjacent blocks; the hole between them
    # must keep its prior contents (read-modify-write correctness)
    path = str(tmp_path / "gap.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.set_view(0, dt.DOUBLE)   # positions in doubles
        if comm.rank == 0:
            f.write_at(0, np.full(64, 9.0))   # pre-existing content
        f.sync()
        comm.Barrier()
        data = np.full(8, comm.rank + 1.0)
        # rank 0 → [0,8), rank 1 → [24,32): hole at [8,24)
        f.write_at_all(comm.rank * 24, data)
        f.sync()
        comm.Barrier()
        out = np.zeros(32)
        f.read_at(0, out)
        f.close()
        return list(out)

    res = run_ranks(2, fn)
    out = res[0]
    assert out[:8] == [1.0] * 8
    assert out[8:24] == [9.0] * 16      # hole untouched
    assert out[24:32] == [2.0] * 8


def test_read_all_sparse_views(tmp_path):
    path = str(tmp_path / "rsparse.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        if comm.rank == 0:
            f.write_at(0, np.arange(32, dtype=np.float64))
        f.sync()
        comm.Barrier()
        ft = dt.resized(dt.DOUBLE, 0, comm.size * 8)
        f.set_view(comm.rank * 8, dt.DOUBLE, ft)
        mine = np.zeros(32 // comm.size)
        f.read_at_all(0, mine)
        f.close()
        return list(mine)

    res = run_ranks(4, fn)
    for rank, got in enumerate(res):
        assert got == [float(rank + 4 * i) for i in range(8)]


def test_view_resized_smaller_extent_than_true_ub():
    # data at [8,16) with extent 8: legal resized type whose extent is
    # below its true_ub — tiles stride by 8 and interleave cleanly
    # (advisor round-1 finding: the stride must be extent, not true_ub)
    ft = dt.resized(dt.indexed_block(1, [1], dt.DOUBLE), 0, 8)
    v = FileView(0, dt.DOUBLE, ft)
    assert v.tile_extent == 8
    assert v.map_bytes(0, 8) == [(8, 8)]
    assert v.map_bytes(1, 8) == [(16, 8)]


def test_view_rejects_truly_overlapping_tiles():
    # data at [0,16) but extent 8: tile 1's data starts at 8, inside
    # tile 0's data — a genuine overlap, MPI_ERR_TYPE
    with pytest.raises(ValueError):
        FileView(0, dt.DOUBLE, dt.resized(dt.contiguous(2, dt.DOUBLE),
                                          0, 8))


def test_write_all_sparse_far_apart_offsets(tmp_path):
    # 1 double at offset 0 and 1 double 256 GiB away: aggregation must
    # allocate covered intervals only — a regression back to
    # partition-span allocation would try a ~128 GiB bytearray per
    # aggregator and die, so the distance itself pins the behavior
    path = str(tmp_path / "sparse_far.bin")
    FAR = 1 << 38

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.set_view(0, dt.DOUBLE)
        f.write_at_all((comm.rank * FAR) // 8, np.full(1, comm.rank + 1.0))
        f.sync()
        comm.Barrier()
        out0 = np.zeros(1)
        out1 = np.zeros(1)
        f.read_at(0, out0)
        f.read_at(FAR // 8, out1)
        f.close()
        return (out0[0], out1[0])

    res = run_ranks(2, fn)
    assert res[0] == (1.0, 2.0)
    assert res[1] == (1.0, 2.0)


def test_read_all_true_eof_counts(tmp_path):
    # collective read past EOF must report the true byte count, like
    # the individual path (advisor round-1 finding)
    path = str(tmp_path / "eofcnt.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        f.set_view(0, dt.DOUBLE)
        if comm.rank == 0:
            f.write_at(0, np.arange(6, dtype=np.float64))  # 48 bytes
        f.sync()
        comm.Barrier()
        mine = np.zeros(4, dtype=np.float64)
        # rank 0 reads [0,32) fully; rank 1 reads [32,64) but EOF=48
        st = f.read_at_all(comm.rank * 4, mine)
        f.close()
        return st.count

    res = run_ranks(2, fn)
    assert res[0] == 32
    assert res[1] == 16


def test_view_legal_interleaved_tiles():
    # data at [0,4)+[12,16) with extent 8: tile k's bytes fold to
    # distinct residues mod 8, so consecutive tiles interleave without
    # overlap — must be accepted, and map_bytes must walk it correctly
    ft = dt.resized(dt.indexed_block(1, [0, 3], dt.INT32_T), 0, 8)
    v = FileView(0, dt.INT32_T, ft)
    assert v.map_bytes(0, 16) == [(0, 4), (12, 4), (8, 4), (20, 4)]


# -- sharedfp info hint ------------------------------------------------------

def test_sharedfp_hint_disables_shared_pointers(tmp_path):
    """info {'sharedfp': 'false'} skips the shared-pointer window
    entirely (no dup'd comm, no per-sweep AM polling — the checkpoint
    engine's open mode); explicit-offset and collective I/O still
    work, shared-fp operations raise."""
    path = str(tmp_path / "nosp.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW, info={"sharedfp": "false"})
        assert f._sp_win is None and f._sp_comm is None
        data = np.full(4, float(comm.rank), dtype=np.float64)
        f.write_at(comm.rank * 32, data)
        f.sync()
        comm.Barrier()
        back = np.zeros(4, dtype=np.float64)
        f.read_at_all(comm.rank * 32, back)
        np.testing.assert_array_equal(back, data)
        with pytest.raises(RuntimeError, match="sharedfp"):
            f.write_shared(data)
        with pytest.raises(RuntimeError, match="sharedfp"):
            f.get_position_shared()
        f.close()
        return True

    assert run_ranks(2, fn) == [True, True]


def test_sharedfp_default_still_enabled(tmp_path):
    """Without the hint the shared pointer works as before."""
    path = str(tmp_path / "sp.bin")

    def fn(comm):
        f = mpiio.open(comm, path, RW)
        assert f._sp_win is not None
        one = np.full(2, float(comm.rank + 1), dtype=np.float64)
        f.write_shared(one)
        comm.Barrier()
        # default view: BYTE etype, so 2 doubles x 2 ranks = 32 bytes
        assert f.get_position_shared() == 32
        f.close()
        return True

    assert run_ranks(2, fn) == [True, True]
