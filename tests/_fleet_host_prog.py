"""Host-kill shrink-arm workload (run by test_fleet.py and the fleet
probe): a deterministic stepped allreduce on a multi-host DVM pool
whose host 1 is killed mid-loop.  Every rank resident on the dead
host is published as failed in ONE atomic domain record, so the ULFM
survivors observe a single consistent failure set: each survivor
shrinks exactly once, resets, and redoes the whole accumulation on
the shrunk world — making every survivor's digest byte-identical no
matter which step the kill interrupted.

Ranks on the dead host exit 0 the moment they see themselves in the
failure set (a killed host's ranks do not get to finalize; in the
in-process harness the thread stands in for the vanished process).

argv: tag steps [kill_rank:kill_step]

The optional third argument makes the death fully deterministic:
world rank ``kill_rank`` calls ``ulfm.kill_now`` at the top of step
``kill_step`` of its FIRST incarnation — a step-boundary kill with no
wall-clock timer in the loop, for tests that compose this workload
with other fault classes and must not race the victim's init window
(the timer-armed mid-op variant stays covered by the ``rank_kill``
chaos matrices).

Every survivor prints ``SHRINKS {tag} {rank} {n}`` and
``DIGEST {tag} {sha256}``; the test asserts n == 1 everywhere and all
digests identical.
"""
import hashlib
import sys
import time

import numpy as np

import ompi_tpu
from ompi_tpu.errhandler import MPIException
from ompi_tpu.op import op as mpi_op

tag = sys.argv[1]
steps = int(sys.argv[2])
kill_rank, kill_step = (-1, -1)
if len(sys.argv) > 3:
    kill_rank, kill_step = (int(x) for x in sys.argv[3].split(":"))

comm = ompi_tpu.init()
me = comm.rank
work = comm
vec = np.zeros(32, np.float64)
shrinks = 0
step = 0
def _i_am_dead():
    # a rank never ingests its OWN failure into ulfm.failed; the
    # host-kill path marks the victim incarnations with the same
    # ulfm_dead flag ft_inject rank_kill uses
    return getattr(comm.state, "ulfm_dead", False)


while step < steps:
    if _i_am_dead():
        # my host is the one that died: vanish without finalize
        # (ulfm_fence drops failed ranks from the quorum).  Checked
        # BEFORE each op — a dead rank must never meet survivors that
        # already shrank around it.
        sys.exit(0)
    if me == kill_rank and step == kill_step and shrinks == 0:
        # deterministic step-boundary death (first incarnation only):
        # RankKilled propagates out of runpy and the pool runner
        # publishes it exactly like the timer-armed rank_kill path
        from ompi_tpu.ft import ulfm as _ulfm
        _ulfm.kill_now(comm.state)
    contrib = np.full(32, float((step + 1) * (work.rank + 1)),
                      np.float64)
    r = np.empty_like(contrib)
    try:
        work.Allreduce(contrib, r, mpi_op.SUM)
    except MPIException as e:
        assert e.code in (75, 76, 77), e.code
        if _i_am_dead():
            sys.exit(0)
        # survivors: one shrink, then redo the run from step 0 on the
        # shrunk world — survivors may disagree on whether the
        # interrupted step completed, so partial sums are discarded
        # rather than reconciled
        work = work.shrink(name="survivors")
        shrinks += 1
        vec = np.zeros(32, np.float64)
        step = 0
        continue
    except Exception:  # noqa: BLE001
        # backstop for the publish/op race: a dead rank that slipped
        # into one more op against a world the survivors are already
        # reshaping dies HERE, not as a job failure
        if _i_am_dead():
            sys.exit(0)
        raise
    vec = vec + r
    step += 1
    time.sleep(0.02)

dig = hashlib.sha256(vec.tobytes()).hexdigest()
# one atomic write per line: rank-threads share the session stdout
# buffer and print()'s separate text/newline writes interleave
sys.stdout.write(f"SHRINKS {tag} {me} {shrinks}\n")
sys.stdout.write(f"DIGEST {tag} {dig}\n")
sys.stdout.flush()
ompi_tpu.finalize()
