"""Topologies + neighbor collectives (ref: ompi/mca/topo,
ompi/mpi/c/neighbor_*.c; test style after orte/test/mpi topology
programs)."""

import numpy as np
import pytest

from ompi_tpu.pml.request import PROC_NULL
from ompi_tpu.testing import run_ranks
from ompi_tpu.topo import (CART, DIST_GRAPH, GRAPH, UNDEFINED_TOPO,
                           CartTopo, dims_create)


# -- dims_create (pure) -----------------------------------------------------

@pytest.mark.parametrize("n,nd,exp", [
    (6, 2, [3, 2]),
    (7, 2, [7, 1]),
    (8, 3, [2, 2, 2]),
    (12, 2, [4, 3]),
    (16, 2, [4, 4]),
    (60, 3, [5, 4, 3]),
    (1, 2, [1, 1]),
])
def test_dims_create(n, nd, exp):
    assert dims_create(n, nd) == exp


def test_dims_create_fixed():
    assert dims_create(12, 2, [0, 4]) == [3, 4]
    assert dims_create(12, 3, [2, 0, 3]) == [2, 2, 3]
    with pytest.raises(ValueError):
        dims_create(10, 2, [4, 0])


# -- CartTopo math (pure) ---------------------------------------------------

def test_cart_coords_roundtrip():
    t = CartTopo([3, 4], [True, False], 0)
    for r in range(12):
        assert t.coords_to_rank(t.rank_to_coords(r)) == r
    # row-major: rank = c0*4 + c1
    assert t.rank_to_coords(7) == [1, 3]
    assert t.coords_to_rank([2, 1]) == 9


def test_cart_shift_periodic_vs_edge():
    t = CartTopo([4], [False], 0)
    assert t.shift(0, 1, 0) == (PROC_NULL, 1)
    assert t.shift(0, 1, 3) == (2, PROC_NULL)
    tp = CartTopo([4], [True], 0)
    assert tp.shift(0, 1, 0) == (3, 1)
    assert tp.shift(0, 1, 3) == (2, 0)
    assert tp.shift(0, 2, 1) == (3, 3)


def test_cart_neighbors_order():
    # 2x2 periodic: per dim, source then dest of +1 shift
    t = CartTopo([2, 2], [True, True], 0)
    assert t.neighbors(0) == [2, 2, 1, 1]


# -- communicator-attached topologies --------------------------------------

def test_cart_create_and_queries():
    def fn(comm):
        cart = comm.Create_cart([2, 2], periods=[True, False])
        assert cart.Topo_test() == CART
        dims, periods, coords = cart.Get_topo()
        assert dims == [2, 2] and periods == [True, False]
        assert cart.Get_cart_rank(coords) == cart.rank
        src, dst = cart.Shift(0, 1)
        return (cart.rank, coords, src, dst)

    for rank, coords, src, dst in run_ranks(4, fn):
        assert coords == [rank // 2, rank % 2]
        assert src == (rank + 2) % 4 and dst == (rank + 2) % 4


def test_cart_create_excess_ranks_get_null():
    def fn(comm):
        cart = comm.Create_cart([2], periods=[True])
        return None if cart is None else cart.size

    res = run_ranks(3, fn)
    assert res.count(None) == 1 and res.count(2) == 2


def test_cart_sub_splits_grid():
    def fn(comm):
        cart = comm.Create_cart([2, 3])
        row = cart.Sub([False, True])   # keep dim 1 → rows of 3
        col = cart.Sub([True, False])   # keep dim 0 → cols of 2
        return (cart.rank, row.size, row.rank, col.size, col.rank)

    for rank, rsize, rrank, csize, crank in run_ranks(6, fn):
        assert rsize == 3 and rrank == rank % 3
        assert csize == 2 and crank == rank // 3


def test_topo_test_undefined_without_topo():
    def fn(comm):
        return comm.Topo_test()

    assert run_ranks(2, fn) == [UNDEFINED_TOPO] * 2


# -- neighbor collectives ---------------------------------------------------

def test_neighbor_allgather_ring():
    def fn(comm):
        cart = comm.Create_cart([4], periods=[True])
        s = np.array([cart.rank * 10], dtype=np.int64)
        r = np.zeros(2, dtype=np.int64)
        cart.Neighbor_allgather(s, r)
        return list(r)

    for rank, r in enumerate(run_ranks(4, fn)):
        assert r == [((rank - 1) % 4) * 10, ((rank + 1) % 4) * 10]


def test_neighbor_allgather_nonperiodic_edges():
    def fn(comm):
        cart = comm.Create_cart([3], periods=[False])
        s = np.array([cart.rank + 1], dtype=np.int64)
        r = np.full(2, -1, dtype=np.int64)
        cart.Neighbor_allgather(s, r)
        return list(r)

    res = run_ranks(3, fn)
    assert res[0] == [-1, 2]      # no left neighbor: block untouched
    assert res[1] == [1, 3]
    assert res[2] == [2, -1]


def test_neighbor_alltoall_ring_directional():
    def fn(comm):
        cart = comm.Create_cart([4], periods=[True])
        # block 0 → source-direction neighbor, block 1 → dest-direction
        s = np.array([cart.rank * 100, cart.rank * 100 + 1],
                     dtype=np.int64)
        r = np.zeros(2, dtype=np.int64)
        cart.Neighbor_alltoall(s, r)
        return list(r)

    # my block 0 (from left neighbor) is what left sent in ITS block 1?
    # MPI defines: exchange block 2d with source-neighbor, 2d+1 with
    # dest-neighbor.  left neighbor exchanges its block 1 with... its
    # dest (me)?  No: each pair (me,left) exchange my block0 ↔ its
    # block... its dest-direction block is block 1 → lands in my
    # block 0.
    for rank, r in enumerate(run_ranks(4, fn)):
        left, right = (rank - 1) % 4, (rank + 1) % 4
        assert r == [left * 100 + 1, right * 100]


def test_neighbor_alltoall_two_rank_periodic_duplicate_neighbors():
    # both directions hit the same peer: ordering must disambiguate
    def fn(comm):
        cart = comm.Create_cart([2], periods=[True])
        s = np.array([cart.rank * 10, cart.rank * 10 + 1], dtype=np.int64)
        r = np.zeros(2, dtype=np.int64)
        cart.Neighbor_alltoall(s, r)
        return list(r)

    res = run_ranks(2, fn)
    # per MPI as-if code: block k exchanged with neighbor k, in order;
    # rank0's block0 ↔ rank1's block0, block1 ↔ block1
    assert res[0] == [10, 11]
    assert res[1] == [0, 1]


def test_neighbor_alltoall_2d_grid():
    def fn(comm):
        cart = comm.Create_cart([2, 2], periods=[True, True])
        nbrs = cart.topo.neighbors(cart.rank)
        s = np.array([cart.rank * 10 + j for j in range(4)],
                     dtype=np.int64)
        r = np.zeros(4, dtype=np.int64)
        cart.Neighbor_alltoall(s, r)
        return (nbrs, list(r))

    res = run_ranks(4, fn)
    for rank, (nbrs, r) in enumerate(res):
        for i, src in enumerate(nbrs):
            # src exchanged ITS block at the position where I appear
            # in its neighbor list matching this edge; by the pairwise
            # exchange rule block i ↔ block i when grids align
            src_nbrs = res[src][0]
            # find which of src's blocks landed here: pairing is by
            # per-(pair) message order; with 2x2 periodic each dim
            # pairs distinct peers, so block i comes from src block i
            assert r[i] == src * 10 + i


def test_neighbor_allgatherv():
    def fn(comm):
        cart = comm.Create_cart([3], periods=[True])
        s = np.full(cart.rank + 1, cart.rank, dtype=np.int64)
        left, right = (cart.rank - 1) % 3, (cart.rank + 1) % 3
        rcounts = [left + 1, right + 1]
        displs = [0, left + 1]
        r = np.full(sum(rcounts), -1, dtype=np.int64)
        cart.Neighbor_allgatherv(s, r, rcounts, displs)
        return (list(r), rcounts)

    for rank, (r, rc) in enumerate(run_ranks(3, fn)):
        left, right = (rank - 1) % 3, (rank + 1) % 3
        assert r[:rc[0]] == [left] * (left + 1)
        assert r[rc[0]:] == [right] * (right + 1)


def test_neighbor_alltoallv_dist_graph():
    def fn(comm):
        # chain 0→1→2 (directional): rank r sends to r+1, recvs from r-1
        srcs = [comm.rank - 1] if comm.rank > 0 else []
        dsts = [comm.rank + 1] if comm.rank < comm.size - 1 else []
        g = comm.Create_dist_graph_adjacent(srcs, dsts)
        assert g.Topo_test() == DIST_GRAPH
        sbuf = np.full(3, comm.rank * 7, dtype=np.int64)
        rbuf = np.full(3, -1, dtype=np.int64)
        g.Neighbor_alltoallv(sbuf, [3] * len(dsts), [0] * len(dsts),
                             rbuf, [3] * len(srcs), [0] * len(srcs))
        return list(rbuf)

    res = run_ranks(3, fn)
    assert res[0] == [-1, -1, -1]
    assert res[1] == [0, 0, 0]
    assert res[2] == [7, 7, 7]


def test_graph_create_neighbors():
    def fn(comm):
        # square: 0-1, 1-2, 2-3, 3-0
        index = [2, 4, 6, 8]
        edges = [1, 3, 0, 2, 1, 3, 0, 2]
        g = comm.Create_graph(index, edges)
        assert g.Topo_test() == GRAPH
        s = np.array([g.rank], dtype=np.int64)
        r = np.full(2, -1, dtype=np.int64)
        g.Neighbor_allgather(s, r)
        return list(r)

    res = run_ranks(4, fn)
    for rank, r in enumerate(res):
        assert r == [(rank - 1) % 4, (rank + 1) % 4] or \
               sorted(r) == sorted([(rank - 1) % 4, (rank + 1) % 4])


def test_ineighbor_allgather_overlap():
    def fn(comm):
        cart = comm.Create_cart([4], periods=[True])
        s1 = np.array([cart.rank], dtype=np.int64)
        s2 = np.array([cart.rank * 1000], dtype=np.int64)
        r1 = np.zeros(2, dtype=np.int64)
        r2 = np.zeros(2, dtype=np.int64)
        q1 = cart.Ineighbor_allgather(s1, r1)
        q2 = cart.Ineighbor_allgather(s2, r2)
        q2.wait()
        q1.wait()
        return (list(r1), list(r2))

    for rank, (r1, r2) in enumerate(run_ranks(4, fn)):
        left, right = (rank - 1) % 4, (rank + 1) % 4
        assert r1 == [left, right]
        assert r2 == [left * 1000, right * 1000]


def test_ineighbor_alltoall():
    def fn(comm):
        cart = comm.Create_cart([3], periods=[True])
        s = np.array([cart.rank * 10, cart.rank * 10 + 1], dtype=np.int64)
        r = np.zeros(2, dtype=np.int64)
        cart.Ineighbor_alltoall(s, r).wait()
        return list(r)

    for rank, r in enumerate(run_ranks(3, fn)):
        left, right = (rank - 1) % 3, (rank + 1) % 3
        assert r == [left * 10 + 1, right * 10]


# -- review regressions -----------------------------------------------------

def test_neighbor_allgather_derived_datatype():
    from ompi_tpu.datatype import engine as dt

    def fn(comm):
        cart = comm.Create_cart([3], periods=[True])
        pair = dt.contiguous(2, dt.DOUBLE)
        s = np.array([cart.rank * 1.0, cart.rank + 0.5])
        r = np.full(4, -1.0)
        # 1 element of contiguous(2, DOUBLE) per neighbor
        cart.Neighbor_allgather((s, 1, pair), (r, 2, pair))
        return list(r)

    for rank, r in enumerate(run_ranks(3, fn)):
        left, right = (rank - 1) % 3, (rank + 1) % 3
        assert r == [left * 1.0, left + 0.5, right * 1.0, right + 0.5]


def test_dup_carries_topology():
    def fn(comm):
        cart = comm.Create_cart([2, 2])
        d = cart.dup()
        return (d.Topo_test(), d.Get_coords())

    for rank, (kind, coords) in enumerate(run_ranks(4, fn)):
        assert kind == CART and coords == [rank // 2, rank % 2]


def test_topo_guards():
    def fn(comm):
        try:
            comm.Get_coords()
            return "no-error"
        except ValueError as e:
            pass
        try:
            comm.Neighbor_allgather(np.zeros(1), np.zeros(2))
            return "no-error"
        except ValueError:
            return "ok"

    assert run_ranks(2, fn) == ["ok", "ok"]


def test_cart_create_bad_periods_length():
    def fn(comm):
        try:
            comm.Create_cart([2, 2], periods=[True])
            return "no-error"
        except ValueError:
            return "ok"

    assert run_ranks(4, fn) == ["ok"] * 4


# -- device path ------------------------------------------------------------

def test_shift_arr_ring_on_devices():
    import jax.numpy as jnp

    def fn(comm):
        cart = comm.Create_cart([comm.size], periods=[True])
        x = jnp.full((4,), float(cart.rank))
        y = cart.shift_arr(x, 0, 1)
        return np.asarray(y)

    res = run_ranks(4, fn, devices=True)
    for rank, y in enumerate(res):
        np.testing.assert_allclose(y, np.full(4, (rank - 1) % 4))


def test_shift_arr_nonperiodic_edge_zeros():
    import jax.numpy as jnp

    def fn(comm):
        cart = comm.Create_cart([comm.size], periods=[False])
        x = jnp.full((2,), float(cart.rank + 1))
        y = cart.shift_arr(x, 0, 1)
        return np.asarray(y)

    res = run_ranks(4, fn, devices=True)
    np.testing.assert_allclose(res[0], np.zeros(2))
    for rank in range(1, 4):
        np.testing.assert_allclose(res[rank], np.full(2, rank))
