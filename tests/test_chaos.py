"""Chaos harness: run the deterministic workload in _chaos_prog.py
under every armed fault class and require either byte-identical
results (digest equality against an uninjected reference run) or a
bounded-time clean abort.  The injector is seed-driven
(ft_inject_seed), so any failure here replays bit-for-bit."""

import os
import re

import pytest

from ompi_tpu.testing import mpirun_run

SEED = "7"
PROG = os.path.join("tests", "_chaos_prog.py")


def _digests(out: bytes):
    """{rank: hexdigest} from the prog's 'chaos digest R H' lines."""
    return {int(m.group(1)): m.group(2) for m in re.finditer(
        rb"chaos digest (\d+) ([0-9a-f]{64})", out)}


def _chaos_run(plan, tmp_path, np_=2, rate="0.05", extra=(),
               mca_extra=()):
    env_dir = str(tmp_path / f"ckpt-{plan or 'ref'}")
    os.makedirs(env_dir, exist_ok=True)
    old = os.environ.get("TPUMPI_CKPT_DIR")
    os.environ["TPUMPI_CKPT_DIR"] = env_dir
    try:
        mca = [("btl", "self,tcp")]
        if plan:
            mca += [("ft_inject_plan", plan),
                    ("ft_inject_seed", SEED),
                    ("ft_inject_rate", rate)]
        mca += list(mca_extra)
        r = mpirun_run(np_, PROG, mca=mca, extra=extra,
                       timeout=240, job_timeout=150)
    finally:
        if old is None:
            os.environ.pop("TPUMPI_CKPT_DIR", None)
        else:
            os.environ["TPUMPI_CKPT_DIR"] = old
    return r


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Digest of the workload with NO faults armed — ground truth."""
    r = _chaos_run("", tmp_path_factory.mktemp("ref"))
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    d = _digests(r.stdout)
    assert set(d) == {0, 1}, r.stdout.decode()[-500:]
    return d


@pytest.mark.parametrize("plan", ["drop", "delay", "dup", "reorder",
                                  "corrupt", "corrupt_payload",
                                  "sever"])
def test_btl_fault_class_byte_identical(plan, reference, tmp_path):
    """Each frame-level fault class, alone, at the fixed seed: the
    reliable sublayer must absorb it and the digest must match the
    clean run exactly."""
    r = _chaos_run(plan, tmp_path)
    assert r.returncode == 0, \
        f"{plan}: rc={r.returncode}\n{r.stderr.decode()[-2000:]}"
    assert _digests(r.stdout) == reference, \
        f"{plan}: digest mismatch\n{r.stdout.decode()[-500:]}"


def test_btl_fault_cocktail_byte_identical(reference, tmp_path):
    """All frame-level classes at once — the worst storm the plan
    syntax can express — still byte-identical."""
    r = _chaos_run("drop:0.03,delay:0.03,dup:0.03,reorder:0.03,"
                   "corrupt:0.03,sever:0.01", tmp_path, rate="0.03")
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert _digests(r.stdout) == reference, r.stdout.decode()[-500:]


def test_kv_partition_job_survives(reference, tmp_path):
    """kv_partition severs the client↔server socket before KV ops;
    the retry/backoff path must reconnect and the job completes with
    the reference digest."""
    r = _chaos_run("kv_partition:0.2", tmp_path)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    assert _digests(r.stdout) == reference, r.stdout.decode()[-500:]


@pytest.mark.slow
def test_oob_sever_daemon_reconnects(tmp_path):
    """Injected daemon↔HNP channel drop on the victim node: the HNP
    holds EV_DAEMON_LOST for the reconnect grace, the daemon's
    backoff reconnect re-registers (reconnect=True, so no duplicate
    EV_DAEMON_UP) and the job completes normally."""
    r = _chaos_run("oob_sever", tmp_path, np_=4,
                   extra=("--simulate-nodes", "2x2"),
                   mca_extra=(("oob_base_reconnect_grace", "5.0"),))
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    d = _digests(r.stdout)
    assert set(d) == {0, 1, 2, 3}, r.stdout.decode()[-800:]


@pytest.mark.slow
def test_daemon_kill_terminates_job(tmp_path):
    """daemon_kill hard-exits the victim node's daemon mid-job: the
    errmgr must declare the node lost and tear the job down in
    bounded time — never a hang."""
    r = _chaos_run("daemon_kill", tmp_path, np_=4,
                   extra=("--simulate-nodes", "2x2"),
                   mca_extra=(("oob_base_heartbeat_interval", "0.5"),
                              ("oob_base_heartbeat_budget", "4")))
    assert r.returncode != 0, "job must not report success"
    err = r.stderr.decode()
    assert "lost" in err, err[-2000:]


def test_injector_disabled_by_default():
    """Empty plan = framework fully passive: no injector objects are
    built, so production paths never pay for chaos plumbing."""
    from ompi_tpu import ft_inject
    assert not ft_inject.enabled()
    assert ft_inject.btl_injector(0) is None
    assert ft_inject.kv_injector(0) is None
    assert ft_inject.node_faults(1) == []


def test_injector_deterministic_replay():
    """Same (seed, scope, rank) → identical fault sequence; different
    rank → (almost surely) a different one."""
    from ompi_tpu import ft_inject
    plan = {"drop": 0.2, "dup": 0.2}
    a = ft_inject.BtlInjector("btl", 0, plan)
    b = ft_inject.BtlInjector("btl", 0, plan)
    c = ft_inject.BtlInjector("btl", 1, plan)
    sa = [a.pick(0, 1) for _ in range(200)]
    sb = [b.pick(0, 1) for _ in range(200)]
    sc = [c.pick(0, 1) for _ in range(200)]
    assert sa == sb
    assert sa != sc
    assert any(x is not None for x in sa)  # skip=8 passed, faults fire
