"""Native C++ data plane tests: shm ring atomics, strided pack,
Python<->native interop (the reference's test/asm + test/class
lock-free coverage, SURVEY.md §4)."""

import ctypes
import os
import threading

import numpy as np
import pytest

from ompi_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native lib not built")


def _mk_ring(tmp_path, cap=1 << 16):
    from ompi_tpu.mca.params import registry
    registry.set("btl_shm_ring_size", cap)
    from ompi_tpu.btl.shm import Ring
    registry.refresh()
    r = Ring(str(tmp_path / "ring.buf"), create=True)
    registry.set("btl_shm_ring_size", 8 * 1024 * 1024)
    return r


def test_ring_roundtrip(tmp_path):
    r = _mk_ring(tmp_path)
    assert r._lib is not None
    frames = [b"hello", b"", b"x" * 1000, os.urandom(4096)]
    for f in frames:
        assert r.push(f)
    for f in frames:
        assert r.pop() == f
    assert r.pop() is None


def test_ring_wraparound(tmp_path):
    r = _mk_ring(tmp_path, cap=1 << 12)
    payload = os.urandom(1000)
    for _ in range(50):  # force wrap many times
        assert r.push(payload)
        assert r.pop() == payload


def test_ring_backpressure(tmp_path):
    r = _mk_ring(tmp_path, cap=1 << 12)
    big = b"y" * 3000
    assert r.push(big)
    assert not r.push(big)  # full
    assert r.pop() == big
    assert r.push(big)      # space released


def test_ring_python_native_interop(tmp_path):
    """Native producer, Python consumer and vice versa."""
    r = _mk_ring(tmp_path)
    lib_saved = r._lib
    msg = os.urandom(513)
    # native push, python pop
    assert r.push_native(msg)
    r._lib = None
    assert r.pop() == msg
    # python push, native pop
    assert r.push(msg + b"2")
    r._lib = lib_saved
    assert r.pop_native() == msg + b"2"


def test_ring_threaded_stress(tmp_path):
    """SPSC stress across threads (cross-process covered by the
    launcher integration tests)."""
    r = _mk_ring(tmp_path, cap=1 << 14)
    N = 2000
    out = []

    def producer():
        i = 0
        while i < N:
            if r.push(i.to_bytes(4, "big") + bytes([i % 251] * (i % 97))):
                i += 1

    def consumer():
        while len(out) < N:
            f = r.pop()
            if f is not None:
                out.append(f)

    tp = threading.Thread(target=producer)
    tc = threading.Thread(target=consumer)
    tp.start(); tc.start()
    tp.join(30); tc.join(30)
    assert len(out) == N
    for i, f in enumerate(out):
        assert int.from_bytes(f[:4], "big") == i
        assert f[4:] == bytes([i % 251] * (i % 97))


def test_pack_strided_matches_numpy():
    lib = native.load()
    src = np.arange(1000, dtype=np.uint8)
    dst = np.zeros(300, dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.tpumpi_pack_strided(
        src.ctypes.data_as(u8p), dst.ctypes.data_as(u8p), 30, 100, 10)
    exp = np.concatenate([src[i * 100:i * 100 + 30] for i in range(10)])
    np.testing.assert_array_equal(dst, exp)
    back = np.zeros(1000, dtype=np.uint8)
    lib.tpumpi_unpack_strided(
        back.ctypes.data_as(u8p), dst.ctypes.data_as(u8p), 30, 100, 10)
    ref = np.zeros(1000, dtype=np.uint8)
    for i in range(10):
        ref[i * 100:i * 100 + 30] = src[i * 100:i * 100 + 30]
    np.testing.assert_array_equal(back, ref)
