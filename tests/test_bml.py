"""bml multiplexing + failover (ref: ompi/mca/bml/r2 per-proc btl
arrays; pml/bfo failover idea; tcp transport-level reconnect)."""

import os
import subprocess
import sys

import pytest

from ompi_tpu.btl.base import BtlError, Endpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _FakeBtl:
    def __init__(self, name, exclusivity, fail_after=None):
        self.name = name
        self.exclusivity = exclusivity
        self.fail_after = fail_after
        self.sent = []

    def send(self, peer, frag):
        if self.fail_after is not None \
                and len(self.sent) >= self.fail_after:
            raise BtlError(f"{self.name} died")
        self.sent.append(frag)


def test_endpoint_prefers_exclusivity_order():
    a = _FakeBtl("fast", 100)
    b = _FakeBtl("slow", 10)
    ep = Endpoint(3, [a, b])
    ep.send(("M", 1))
    assert a.sent and not b.sent
    assert ep.btl is a


def test_endpoint_fails_over_and_retries_the_frag():
    a = _FakeBtl("dies", 100, fail_after=2)
    b = _FakeBtl("backup", 10)
    ep = Endpoint(3, [a, b])
    for i in range(5):
        ep.send(("F", i))
    # first two frags on the primary, the failed third RETRIED on the
    # backup, all later traffic stays failed-over
    assert [f[1] for f in a.sent] == [0, 1]
    assert [f[1] for f in b.sent] == [2, 3, 4]
    assert ep.btl is b


def test_endpoint_exhausted_raises():
    a = _FakeBtl("dies", 100, fail_after=0)
    ep = Endpoint(3, [a])
    with pytest.raises(BtlError):
        ep.send(("M",))


def test_tcp_severed_mid_rendezvous_recovers():
    """Sever the sender's tcp socket between the RNDV head and the
    FRAG stream: the transport reconnects and resends its undrained
    frames; duplicate segments are absorbed by positioned writes."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--mca", "btl", "self,tcp", "--timeout", "90",
         os.path.join(REPO, "tests", "_sever_prog.py")],
        capture_output=True, timeout=150,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO)
    assert r.returncode == 0, r.stderr.decode()
    assert b"sever ok" in r.stdout


def test_multirail_striping_tcp():
    """bml/r2 multi-rail (VERDICT r3 missing #4): with
    btl_tcp_rails=3, rendezvous FRAG segments round-robin across the
    rails (>=2 rails carry frags) and the transfer is intact; the
    envelope stream stays ordered on rail 0."""
    import os

    from ompi_tpu.testing import mpirun_run
    r = mpirun_run(2, os.path.join("tests", "_rails_prog.py"),
                   mca=(("btl", "self,tcp"), ("btl_tcp_rails", "3"),
                        ("btl_tcp_max_send_size", "131072")),
                   timeout=200, job_timeout=150)
    assert r.returncode == 0, r.stderr.decode()[-1500:]
    out = r.stdout.decode()
    line = [ln for ln in out.splitlines() if ln.startswith("rails used=")]
    assert line, out
    used = int(line[0].split("=")[1].split()[0])
    assert used >= 2, line


def test_single_rail_default_unchanged():
    import os

    from ompi_tpu.testing import mpirun_run
    r = mpirun_run(2, os.path.join("tests", "_rails_prog.py"),
                   mca=(("btl", "self,tcp"),),
                   timeout=200, job_timeout=150)
    assert r.returncode == 0, r.stderr.decode()[-1500:]
    assert b"rails used=1" in r.stdout
