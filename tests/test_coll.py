"""Collective tests: every algorithm vs numpy reference across
comm sizes (incl. non-power-of-2), IN_PLACE, derived datatypes,
non-commutative ops (badcoll.c / bcast_loop.c spirit).
"""

import numpy as np
import pytest

from ompi_tpu.coll import base as alg
from ompi_tpu.coll.buffers import IN_PLACE
from ompi_tpu.datatype import engine as dt
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

SIZES = [1, 2, 3, 5, 8]


@pytest.mark.parametrize("n", SIZES)
def test_allreduce_sum(n):
    def fn(comm):
        x = (np.arange(17, dtype=np.float64) + comm.rank)
        r = np.empty_like(x)
        comm.Allreduce(x, r, mpi_op.SUM)
        return r

    res = run_ranks(n, fn)
    exp = sum((np.arange(17, dtype=np.float64) + k) for k in range(n))
    for r in res:
        np.testing.assert_allclose(r, exp)


@pytest.mark.parametrize("opname,npop", [
    ("MAX", np.maximum), ("MIN", np.minimum), ("PROD", np.multiply)])
def test_allreduce_ops(opname, npop):
    n = 4

    def fn(comm):
        x = np.array([comm.rank + 1, 5 - comm.rank], dtype=np.int32)
        r = np.empty_like(x)
        comm.Allreduce(x, r, getattr(mpi_op, opname))
        return r

    res = run_ranks(n, fn)
    vals = [np.array([k + 1, 5 - k], dtype=np.int32) for k in range(n)]
    exp = vals[0]
    for v in vals[1:]:
        exp = npop(exp, v)
    for r in res:
        np.testing.assert_array_equal(r, exp)


def test_allreduce_in_place():
    def fn(comm):
        x = np.full(9, comm.rank + 1.0, dtype=np.float32)
        comm.Allreduce(IN_PLACE, x, mpi_op.SUM)
        return x

    res = run_ranks(4, fn)
    for r in res:
        np.testing.assert_allclose(r, np.full(9, 10.0))


def test_allreduce_maxloc():
    def fn(comm):
        x = np.zeros(3, dtype=dt.DOUBLE_INT.base)
        x["v"] = [comm.rank, -comm.rank, comm.rank * (-1) ** comm.rank]
        x["i"] = comm.rank
        r = np.zeros_like(x)
        comm.Allreduce((x, 3, dt.DOUBLE_INT), (r, 3, dt.DOUBLE_INT),
                       mpi_op.MAXLOC)
        return r

    n = 5
    res = run_ranks(n, fn)
    for r in res:
        assert r["v"][0] == n - 1 and r["i"][0] == n - 1
        assert r["v"][1] == 0 and r["i"][1] == 0
        assert r["v"][2] == 4 and r["i"][2] == 4


@pytest.mark.parametrize("n", SIZES)
def test_bcast(n):
    def fn(comm):
        buf = np.arange(33, dtype=np.int64) if comm.rank == 2 % n \
            else np.zeros(33, dtype=np.int64)
        comm.Bcast(buf, root=2 % n)
        return buf

    res = run_ranks(n, fn)
    for r in res:
        np.testing.assert_array_equal(r, np.arange(33))


def test_bcast_pipeline_large():
    def fn(comm):
        buf = (np.arange(600_000, dtype=np.float32) if comm.rank == 0
               else np.zeros(600_000, dtype=np.float32))
        comm.Bcast(buf, root=0)  # tuned picks pipeline above 256 KiB
        return buf[::100_000].copy()

    res = run_ranks(4, fn)
    for r in res:
        np.testing.assert_array_equal(
            r, np.arange(600_000, dtype=np.float32)[::100_000])


@pytest.mark.parametrize("n", SIZES)
def test_reduce(n):
    def fn(comm):
        x = np.arange(5, dtype=np.int64) * (comm.rank + 1)
        r = np.zeros(5, np.int64) if comm.rank == 0 else None
        comm.Reduce(x, r, mpi_op.SUM, root=0)
        return r

    res = run_ranks(n, fn)
    exp = np.arange(5, dtype=np.int64) * sum(range(1, n + 1))
    np.testing.assert_array_equal(res[0], exp)
    assert all(r is None for r in res[1:])


def test_reduce_noncommutative_user_op_ordering():
    """Non-commutative op must fold in rank order."""
    def fold(invec, inoutvec, _dt):
        # "concatenate digits": a*10 + b — order sensitive
        inoutvec[:] = invec * 10 + inoutvec

    op = mpi_op.create(fold, commute=False)

    def fn(comm):
        x = np.array([comm.rank + 1], dtype=np.int64)
        r = np.zeros(1, np.int64) if comm.rank == 0 else None
        comm.Reduce(x, r, op, root=0)
        return None if r is None else int(r[0])

    res = run_ranks(4, fn)
    # rank order fold: ((1*10+2)*10+3)*10+4 = 1234
    assert res[0] == 1234


@pytest.mark.parametrize("n", SIZES)
def test_allgather(n):
    def fn(comm):
        mine = np.array([comm.rank * 7, comm.rank], dtype=np.int32)
        out = np.zeros(2 * n, dtype=np.int32)
        comm.Allgather(mine, out)
        return out

    res = run_ranks(n, fn)
    exp = np.concatenate([[k * 7, k] for k in range(n)]).astype(np.int32)
    for r in res:
        np.testing.assert_array_equal(r, exp)


def test_allgather_algorithms_direct():
    for algo in (alg.allgather_ring, alg.allgather_bruck,
                 alg.allgather_linear):
        def fn(comm, algo=algo):
            mine = np.array([comm.rank], dtype=np.int64)
            out = np.zeros(comm.size, dtype=np.int64)
            algo(comm, mine, out)
            return out

        for n in (2, 3, 5, 8):
            res = run_ranks(n, fn)
            for r in res:
                np.testing.assert_array_equal(r, np.arange(n))


def test_allgather_recursivedoubling_pow2():
    def fn(comm):
        mine = np.array([comm.rank, comm.rank + 10], dtype=np.int64)
        out = np.zeros(2 * comm.size, dtype=np.int64)
        alg.allgather_recursivedoubling(comm, mine, out)
        return out

    for n in (2, 4, 8):
        res = run_ranks(n, fn)
        exp = np.concatenate([[k, k + 10] for k in range(n)])
        for r in res:
            np.testing.assert_array_equal(r, exp)


def test_allgatherv():
    def fn(comm):
        cnt = comm.rank + 1
        mine = np.full(cnt, comm.rank, dtype=np.int32)
        counts = [k + 1 for k in range(comm.size)]
        displs = np.cumsum([0] + counts[:-1]).tolist()
        out = np.zeros(sum(counts), dtype=np.int32)
        comm.Allgatherv(mine, out, counts, displs)
        return out

    n = 4
    res = run_ranks(n, fn)
    exp = np.concatenate([np.full(k + 1, k) for k in range(n)]).astype(np.int32)
    for r in res:
        np.testing.assert_array_equal(r, exp)


@pytest.mark.parametrize("n", SIZES)
def test_gather_scatter(n):
    def fn(comm):
        mine = np.array([comm.rank ** 2], dtype=np.int64)
        gathered = np.zeros(n, dtype=np.int64) if comm.rank == 0 else None
        comm.Gather(mine, gathered, root=0)
        back = np.zeros(1, dtype=np.int64)
        sbuf = (gathered + 100) if comm.rank == 0 else None
        comm.Scatter(sbuf, back, root=0)
        return int(back[0])

    res = run_ranks(n, fn)
    assert res == [k ** 2 + 100 for k in range(n)]


def test_gather_binomial_direct():
    def fn(comm):
        mine = np.array([comm.rank * 3], dtype=np.int64)
        out = np.zeros(comm.size, dtype=np.int64) if comm.rank == 1 else None
        alg.gather_binomial(comm, mine, out, root=1)
        return out

    for n in (2, 3, 5, 8):
        res = run_ranks(n, fn)
        np.testing.assert_array_equal(res[1], np.arange(n) * 3)


def test_gatherv_scatterv():
    def fn(comm):
        n = comm.size
        counts = [2 * (k + 1) for k in range(n)]
        displs = np.cumsum([0] + counts[:-1]).tolist()
        mine = np.full(counts[comm.rank], comm.rank, dtype=np.float64)
        rbuf = np.zeros(sum(counts)) if comm.rank == 0 else None
        comm.Gatherv(mine, rbuf, counts, displs, root=0)
        out = np.zeros(counts[comm.rank])
        comm.Scatterv(rbuf, counts, displs, out, root=0)
        return out

    n = 3
    res = run_ranks(n, fn)
    for k, r in enumerate(res):
        np.testing.assert_array_equal(r, np.full(2 * (k + 1), k))


@pytest.mark.parametrize("n", SIZES)
def test_alltoall(n):
    def fn(comm):
        sbuf = np.array([comm.rank * 100 + d for d in range(n)],
                        dtype=np.int32)
        rbuf = np.zeros(n, dtype=np.int32)
        comm.Alltoall(sbuf, rbuf)
        return rbuf

    res = run_ranks(n, fn)
    for k, r in enumerate(res):
        np.testing.assert_array_equal(
            r, np.array([s * 100 + k for s in range(n)], dtype=np.int32))


def test_alltoall_algorithms_direct():
    for algo in (alg.alltoall_linear, alg.alltoall_pairwise,
                 alg.alltoall_bruck):
        def fn(comm, algo=algo):
            n = comm.size
            sbuf = np.array([comm.rank * 100 + d for d in range(n)],
                            dtype=np.int64)
            rbuf = np.zeros(n, dtype=np.int64)
            algo(comm, sbuf, rbuf)
            return rbuf

        for n in (2, 3, 5, 8):
            res = run_ranks(n, fn)
            for k, r in enumerate(res):
                np.testing.assert_array_equal(
                    r, [s * 100 + k for s in range(n)])


def test_alltoallv():
    def fn(comm):
        n = comm.size
        scounts = [(comm.rank + d) % n + 1 for d in range(n)]
        sdispls = np.cumsum([0] + scounts[:-1]).tolist()
        sbuf = np.concatenate(
            [np.full(scounts[d], comm.rank * 10 + d, np.int64)
             for d in range(n)])
        rcounts = [(s + comm.rank) % n + 1 for s in range(n)]
        rdispls = np.cumsum([0] + rcounts[:-1]).tolist()
        rbuf = np.zeros(sum(rcounts), dtype=np.int64)
        comm.Alltoallv(sbuf, scounts, sdispls, rbuf, rcounts, rdispls)
        for s in range(n):
            seg = rbuf[rdispls[s]:rdispls[s] + rcounts[s]]
            np.testing.assert_array_equal(
                seg, np.full(rcounts[s], s * 10 + comm.rank))
        return True

    assert all(run_ranks(4, fn))


@pytest.mark.parametrize("n", SIZES)
def test_reduce_scatter_block(n):
    def fn(comm):
        sbuf = np.arange(3 * n, dtype=np.float64) + comm.rank
        rbuf = np.zeros(3, dtype=np.float64)
        comm.Reduce_scatter_block(sbuf, rbuf, mpi_op.SUM)
        return rbuf

    res = run_ranks(n, fn)
    total = sum((np.arange(3 * n, dtype=np.float64) + k) for k in range(n))
    for k, r in enumerate(res):
        np.testing.assert_allclose(r, total[3 * k:3 * (k + 1)])


def test_reduce_scatter_varcounts_max_derived():
    """BASELINE config 5: Reduce_scatter MPI_MAX on MPI_DOUBLE with a
    derived (vector) view of the send buffer."""
    def fn(comm):
        n = comm.size
        counts = [k + 1 for k in range(n)]
        total = sum(counts)
        # send buffer: every other double, via a resized datatype
        # (extent 16 = one double + one gap)
        stride = 2
        raw = np.zeros(total * stride, dtype=np.float64)
        raw[::stride] = np.arange(total) * (comm.rank + 1)
        vt = dt.resized(dt.DOUBLE, 0, 16).commit()
        rbuf = np.zeros(counts[comm.rank], dtype=np.float64)
        comm.Reduce_scatter((raw, total, vt), rbuf, counts, mpi_op.MAX)
        return rbuf

    n = 4
    res = run_ranks(n, fn)
    counts = [1, 2, 3, 4]
    offs = np.cumsum([0] + counts)
    expect_full = np.arange(10) * n  # max over ranks = *(n)
    for k, r in enumerate(res):
        np.testing.assert_allclose(r, expect_full[offs[k]:offs[k + 1]])


@pytest.mark.parametrize("n", SIZES)
def test_scan_exscan(n):
    def fn(comm):
        x = np.array([comm.rank + 1], dtype=np.int64)
        s = np.zeros(1, np.int64)
        e = np.zeros(1, np.int64)
        comm.Scan(x, s, mpi_op.SUM)
        comm.Exscan(x, e, mpi_op.SUM)
        return int(s[0]), int(e[0])

    res = run_ranks(n, fn)
    for k, (s, e) in enumerate(res):
        assert s == sum(range(1, k + 2))
        if k > 0:
            assert e == sum(range(1, k + 1))


@pytest.mark.parametrize("n", SIZES)
def test_barrier_algorithms(n):
    import time

    def fn(comm):
        marks = []
        for bar in (alg.barrier_linear, alg.barrier_bruck,
                    alg.barrier_doublering):
            if comm.rank == 0:
                time.sleep(0.01)
            bar(comm)
            marks.append(time.monotonic())
        return marks

    run_ranks(n, fn)  # completion without deadlock is the assertion


def test_collective_derived_datatype_bcast():
    """Bcast a subarray region."""
    def fn(comm):
        grid = np.zeros((4, 4), dtype=np.int32)
        if comm.rank == 0:
            grid[1:3, 1:3] = [[1, 2], [3, 4]]
        sub = dt.subarray([4, 4], [2, 2], [1, 1], dt.ORDER_C, dt.INT).commit()
        comm.Bcast((grid, 1, sub), root=0)
        return grid

    res = run_ranks(3, fn)
    for r in res:
        np.testing.assert_array_equal(r[1:3, 1:3], [[1, 2], [3, 4]])
        assert r.sum() == 10


def test_concurrent_collectives_on_split_comms():
    """Different sub-communicators run collectives concurrently."""
    def fn(comm):
        sub = comm.split(comm.rank % 2)
        x = np.array([comm.rank], dtype=np.int64)
        r = np.zeros(1, np.int64)
        sub.Allreduce(x, r, mpi_op.SUM)
        return int(r[0])

    res = run_ranks(6, fn)
    assert res == [0 + 2 + 4, 1 + 3 + 5] * 3


def test_scatter_in_place_root():
    """Root uses MPI_IN_PLACE; non-roots must still receive."""
    def fn(comm):
        n = comm.size
        if comm.rank == 0:
            sbuf = np.arange(2 * n, dtype=np.int64)
            comm.coll.scatter(comm, sbuf, 2, dt.INT64_T, IN_PLACE, 2,
                              dt.INT64_T, 0)
            return sbuf[:2].tolist()
        out = np.zeros(2, np.int64)
        comm.Scatter(None, out, root=0)
        return out.tolist()

    res = run_ranks(4, fn, timeout=20)
    assert res == [[0, 1], [2, 3], [4, 5], [6, 7]]


def test_allreduce_noncommutative_consistent():
    """Tuned must route non-commutative ops to the ordered fold."""
    def fold(invec, inoutvec, _dt):
        inoutvec[:] = invec * 10 + inoutvec

    op = mpi_op.create(fold, commute=False)

    def fn(comm):
        x = np.array([comm.rank + 1], dtype=np.int64)
        r = np.zeros(1, np.int64)
        comm.Allreduce(x, r, op)
        return int(r[0])

    res = run_ranks(4, fn)
    assert res == [1234, 1234, 1234, 1234]


def test_reduce_scatter_noncommutative():
    def fold(invec, inoutvec, _dt):
        inoutvec[:] = invec * 10 + inoutvec

    op = mpi_op.create(fold, commute=False)

    def fn(comm):
        sbuf = np.full(comm.size, comm.rank + 1, dtype=np.int64)
        rbuf = np.zeros(1, np.int64)
        comm.Reduce_scatter_block(sbuf, rbuf, op)
        return int(rbuf[0])

    res = run_ranks(4, fn)
    assert res == [1234, 1234, 1234, 1234]


def test_allreduce_recdbl_noncommutative_direct():
    """MPI ops must be associative; commutativity is the flag.  An
    associative non-commutative op (2x2 matmul) must fold in rank
    order under recursive doubling's operand-ordering rule."""
    def matmul_fold(invec, inoutvec, _dt):
        a = invec.reshape(2, 2)
        b = inoutvec.reshape(2, 2)
        inoutvec[:] = (a @ b).reshape(-1)

    op = mpi_op.create(matmul_fold, commute=False)

    def mat(k):
        return np.array([[k + 1, 2], [1, k]], dtype=np.int64)

    def fn(comm):
        x = mat(comm.rank).reshape(-1)
        r = np.zeros(4, np.int64)
        alg.allreduce_recursivedoubling(comm, x, r, op)
        return r.reshape(2, 2)

    for n in (2, 4, 8):
        res = run_ranks(n, fn)
        exp = mat(0)
        for k in range(1, n):
            exp = exp @ mat(k)
        for r in res:
            np.testing.assert_array_equal(r, exp)


def test_coll_vtable_hasattr():
    def fn(comm):
        assert hasattr(comm.coll, "allreduce")
        assert getattr(comm.coll, "alltoallw", None) is None or True
        assert not hasattr(comm.coll, "no_such_coll_fn")
        return True

    assert all(run_ranks(2, fn))
