"""Persistent requests, buffered sends, flat MPI_* surface (ref:
ompi/mpi/c/send_init.c, bsend.c, buffer_attach.c; PMPI aliasing
init.c:35-37)."""

import numpy as np
import pytest

import ompi_tpu
from ompi_tpu.testing import run_ranks


def test_persistent_ping_loop():
    def fn(comm):
        n = 16
        out = []
        if comm.rank == 0:
            buf = np.zeros(n, dtype=np.float64)
            req = comm.Send_init(buf, dest=1, tag=7)
            for it in range(5):
                buf[:] = it  # refresh payload between starts
                req.start()
                req.wait()
        else:
            buf = np.empty(n, dtype=np.float64)
            req = comm.Recv_init(buf, source=0, tag=7)
            for it in range(5):
                req.start()
                st = req.wait()
                assert st.source == 0 and st.tag == 7
                out.append(buf.copy())
        return out

    res = run_ranks(2, fn)
    for it, arr in enumerate(res[1]):
        np.testing.assert_allclose(arr, np.full(16, float(it)))


def test_persistent_startall_and_inactive_wait():
    def fn(comm):
        right = (comm.rank + 1) % comm.size
        left = (comm.rank - 1) % comm.size
        s = np.array([comm.rank * 1.0])
        r = np.zeros(1)
        sreq = comm.Send_init(s, dest=right, tag=2)
        rreq = comm.Recv_init(r, source=left, tag=2)
        # wait on an inactive persistent request returns immediately
        rreq.wait()
        from ompi_tpu.pml.persistent import start_all
        for _ in range(3):
            start_all([rreq, sreq])
            rreq.wait()
            sreq.wait()
        return float(r[0])

    res = run_ranks(3, fn)
    assert res == [2.0, 0.0, 1.0]


def test_persistent_double_start_raises():
    def fn(comm):
        if comm.rank == 0:
            r = np.zeros(1)
            req = comm.Recv_init(r, source=1, tag=0)
            req.start()
            try:
                req.start()
                out = "no-error"
            except RuntimeError:
                out = "ok"
            req.wait()  # the peer's send satisfies the first start
            comm.Send(np.zeros(1), dest=1, tag=1)  # release peer
            return out
        comm.Send(np.zeros(1), dest=0, tag=0)
        comm.Recv(np.zeros(1), source=0, tag=1)
        return None

    assert run_ranks(2, fn)[0] == "ok"


def test_bsend_user_buffer_reusable():
    def fn(comm):
        if comm.rank == 0:
            ompi_tpu.attach_buffer(1 << 16)
            buf = np.arange(32, dtype=np.float64)
            comm.Bsend(buf, dest=1, tag=0)
            buf[:] = -1  # clobber immediately: receiver must see copy
            comm.Bsend(buf * 0 + 5, dest=1, tag=1)
            size = ompi_tpu.detach_buffer()
            assert size == 1 << 16
            return None
        r1 = np.empty(32, dtype=np.float64)
        r2 = np.empty(32, dtype=np.float64)
        comm.Recv(r1, source=0, tag=0)
        comm.Recv(r2, source=0, tag=1)
        return (r1, r2)

    r1, r2 = run_ranks(2, fn)[1]
    np.testing.assert_allclose(r1, np.arange(32, dtype=np.float64))
    np.testing.assert_allclose(r2, np.full(32, 5.0))


def test_bsend_without_buffer_raises():
    def fn(comm):
        try:
            comm.Bsend(np.zeros(4), dest=(comm.rank + 1) % 2, tag=0)
            return "no-error"
        except RuntimeError:
            return "ok"

    assert run_ranks(2, fn) == ["ok", "ok"]


def test_bsend_exhaustion_raises():
    def fn(comm):
        if comm.rank == 0:
            ompi_tpu.attach_buffer(256)
            try:
                # 512B payload can't fit a 256B buffer
                comm.Bsend(np.zeros(64, dtype=np.float64), dest=1, tag=0)
                out = "no-error"
            except RuntimeError:
                out = "ok"
            comm.Send(np.zeros(1), dest=1, tag=9)
            ompi_tpu.detach_buffer()
            return out
        comm.Recv(np.zeros(1), source=0, tag=9)
        return None

    assert run_ranks(2, fn)[0] == "ok"


def test_bsend_init_persistent():
    def fn(comm):
        if comm.rank == 0:
            ompi_tpu.attach_buffer(1 << 14)
            buf = np.zeros(8, dtype=np.int64)
            req = comm.Bsend_init(buf, dest=1, tag=3)
            for it in range(3):
                buf[:] = it * 10
                req.start()
                req.wait()
            ompi_tpu.detach_buffer()
            return None
        got = []
        r = np.empty(8, dtype=np.int64)
        for _ in range(3):
            comm.Recv(r, source=0, tag=3)
            got.append(int(r[0]))
        return got

    assert run_ranks(2, fn)[1] == [0, 10, 20]


def test_rsend_behaves_as_send():
    def fn(comm):
        if comm.rank == 0:
            comm.Rsend(np.full(4, 9.0), dest=1, tag=0)
            comm.Irsend(np.full(4, 8.0), dest=1, tag=1).wait()
            return None
        a = np.empty(4)
        b = np.empty(4)
        comm.Recv(a, source=0, tag=0)
        comm.Recv(b, source=0, tag=1)
        return (float(a[0]), float(b[0]))

    assert run_ranks(2, fn)[1] == (9.0, 8.0)


def test_persistent_with_waitany_testall():
    from ompi_tpu.pml.request import test_all, wait_any

    def fn(comm):
        if comm.rank == 0:
            s = np.array([42.0])
            req = comm.Send_init(s, dest=1, tag=0)
            req.start()
            i = wait_any([req])            # must observe completion
            assert i == 0 and test_all([req])
            return "ok"
        r = np.zeros(1)
        rq = comm.Recv_init(r, source=0, tag=0)
        rq.start()
        assert wait_any([rq]) == 0
        return float(r[0])

    res = run_ranks(2, fn)
    assert res == ["ok", 42.0]


def test_bsend_failed_send_releases_reservation():
    def fn(comm):
        ompi_tpu.attach_buffer(600)
        try:
            try:
                comm.Bsend(np.zeros(32, dtype=np.float64), dest=99, tag=0)
            except Exception:
                pass
            # the 256B+overhead reservation must have been released:
            # a legal send of the same size fits a 600B buffer only
            # if the failed one didn't leak
            comm.Bsend(np.zeros(32, dtype=np.float64),
                       dest=(comm.rank + 1) % 2, tag=1)
            comm.Recv(np.empty(32, dtype=np.float64),
                      source=(comm.rank - 1) % 2, tag=1)
            return "ok"
        finally:
            ompi_tpu.detach_buffer()

    assert run_ranks(2, fn) == ["ok", "ok"]


# -- flat MPI_* surface -----------------------------------------------------

def test_flat_mpi_ring():
    from ompi_tpu import mpi as MPI

    def fn(comm):
        rank = MPI.MPI_Comm_rank(comm)
        size = MPI.MPI_Comm_size(comm)
        token = np.array([rank * 1.0])
        if rank == 0:
            MPI.MPI_Send(token, 1, MPI.MPI_DOUBLE, 1 % size, 0, comm)
            st = MPI.MPI_Recv(token, 1, MPI.MPI_DOUBLE,
                              (size - 1) % size, 0, comm)
            return (float(token[0]), st.source)
        MPI.MPI_Recv(token, 1, MPI.MPI_DOUBLE, rank - 1, 0, comm)
        token[0] += 1
        MPI.MPI_Send(token, 1, MPI.MPI_DOUBLE, (rank + 1) % size, 0, comm)
        return float(token[0])

    res = run_ranks(4, fn)
    assert res[0] == (3.0, 3)
    assert res[1:] == [1.0, 2.0, 3.0]


def test_flat_mpi_collectives_and_pmpi():
    from ompi_tpu import mpi as MPI

    # PMPI aliases exist and are the same callables
    assert MPI.PMPI_Allreduce is MPI.MPI_Allreduce
    assert MPI.PMPI_Send is MPI.MPI_Send

    def fn(comm):
        x = np.full(8, comm.rank + 1.0)
        r = np.empty(8)
        MPI.MPI_Allreduce(x, r, 8, MPI.MPI_DOUBLE, MPI.MPI_SUM, comm)
        dims = MPI.MPI_Dims_create(comm.size, 2)
        cart = MPI.MPI_Cart_create(comm, 2, dims, [True, True])
        coords = MPI.MPI_Cart_coords(cart, cart.rank)
        return (float(r[0]), tuple(dims), tuple(coords))

    res = run_ranks(4, fn)
    for rank, (total, dims, coords) in enumerate(res):
        assert total == 1 + 2 + 3 + 4
        assert dims == (2, 2)
        assert coords == (rank // 2, rank % 2)


def test_flat_mpi_win():
    from ompi_tpu import mpi as MPI

    def fn(comm):
        mem = np.zeros(4, dtype=np.int64)
        win = MPI.MPI_Win_create(mem, comm=comm)
        MPI.MPI_Win_fence(0, win)
        if comm.rank == 0:
            val = np.array([77], dtype=np.int64)
            MPI.MPI_Put(val, 1, MPI.MPI_INT64_T, 1, 2, 1,
                        MPI.MPI_INT64_T, win)
        MPI.MPI_Win_fence(0, win)
        out = int(mem[2])
        win.free()
        return out

    res = run_ranks(2, fn)
    assert res[1] == 77
