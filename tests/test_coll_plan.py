"""Compiled collective plans (coll/plan, DESIGN.md §22): byte
identity against the fused path across algorithms / dtypes / ragged
tails, exactly ONE rendezvous per op, cache lifetime across ULFM
epochs and autotone-style purges, and the shared staging utility the
pack bypass rides."""

import numpy as np
import pytest

from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

# register pipeline + plan knobs before any _set() snapshot
import ompi_tpu.coll.pipeline  # noqa: E402,F401
import ompi_tpu.coll.plan  # noqa: E402,F401


def _put(comm, a):
    return jax.device_put(a, comm.device)


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


# everything >= 2 KiB routes through the plan path with a 4 KiB
# calibrated segment: multi-segment programs, ragged tails, sub-
# segment pow2 quantization all exercised at test-sized arrays
PLAN_ON = {"coll_pipeline_enable": True, "coll_pipeline_min_bytes": 2048,
           "coll_seg_size": 4096, "coll_pipeline_rd_max_bytes": 0,
           "coll_hier_enable": False, "coll_plan_enable": True}
FUSED = {"coll_pipeline_enable": False, "coll_hier_enable": False}


def _reduce_ops(comm):
    """Allreduce over counts leaving count % seg in {0, +1, -1}
    territory, dtypes int8/f16/f32/f64, ops SUM/MAX/PROD — all values
    exact at any fold order.  Returns concatenated result bytes."""
    r = comm.rank
    out = []
    for n in (4096, 4097, 4095):
        x = _put(comm, (jnp.arange(n, dtype=jnp.float32) % 11) + r)
        out.append(np.asarray(comm.allreduce_arr(x, mpi_op.SUM))
                   .tobytes())
        xi = _put(comm, ((jnp.arange(n) % 17) * (r + 1))
                  .astype(jnp.int32))
        out.append(np.asarray(comm.allreduce_arr(xi, mpi_op.MAX))
                   .tobytes())
    x8 = _put(comm, ((jnp.arange(4097) % 3) + (r % 2)).astype(jnp.int8))
    out.append(np.asarray(comm.allreduce_arr(x8, mpi_op.SUM)).tobytes())
    xh = _put(comm, (jnp.arange(3072) % 7).astype(jnp.float16) + r)
    out.append(np.asarray(comm.allreduce_arr(xh, mpi_op.MAX)).tobytes())
    xd = _put(comm, ((jnp.arange(4099) % 5) + 1).astype(jnp.float64))
    out.append(np.asarray(comm.allreduce_arr(xd, mpi_op.PROD))
               .tobytes())
    return b"".join(out)


def _run_vs_fused(fn, n=4, plan_knobs=None, **kw):
    saved = _set(dict(PLAN_ON, **(plan_knobs or {})))
    try:
        plan = run_ranks(n, fn, **kw)
    finally:
        _restore(saved)
    saved = _set(FUSED)
    try:
        fused = run_ranks(n, fn, **kw)
    finally:
        _restore(saved)
    return plan, fused


# ---------------------------------------------------------------------------
# byte identity + the one-rendezvous contract
# ---------------------------------------------------------------------------

def test_plan_mesh_byte_identical_mixed_dtypes():
    """Plan-path mesh allreduce (segring pick): bytes equal to fused
    across dtypes and ragged tails, every rank agreeing, and the plan
    pvars actually moving."""
    def fn(comm):
        from ompi_tpu.coll import plan
        b0, h0 = plan.pv_builds.read(), plan.pv_hits.read()
        out = _reduce_ops(comm)
        again = _reduce_ops(comm)  # second pass: every geometry hits
        comm.Barrier()
        return (out, again,
                plan.pv_builds.read() - b0, plan.pv_hits.read() - h0)

    plan_res, fused = _run_vs_fused(fn, 4, devices=True)
    assert len({b for b, *_ in plan_res}) == 1
    for (pb, pb2, dbuilds, dhits), (fb, _, _, fh) in zip(plan_res,
                                                         fused):
        assert pb == fb
        assert pb2 == pb                  # deterministic on repeat
        assert dbuilds > 0 and dhits > 0  # plan tier engaged + reused
        assert fh == 0                    # fused run untouched


def test_plan_segrd_and_hop_explicit_byte_identical():
    """The recursive-doubling pick and the hop-explicit (native off)
    lowering of both algs: still byte-identical to fused."""
    def fn(comm):
        return _reduce_ops(comm)

    for knobs in ({"coll_pipeline_rd_max_bytes": 1 << 30},
                  {"coll_plan_native_reduce": False},
                  {"coll_pipeline_rd_max_bytes": 1 << 30,
                   "coll_plan_native_reduce": False}):
        plan_res, fused = _run_vs_fused(fn, 4, plan_knobs=knobs,
                                        devices=True)
        assert plan_res == fused


def test_plan_one_rendezvous_per_op():
    """THE structural claim: on the plan path an N-segment collective
    is ONE meet — no per-segment seg_meet spans, one plan_exec span
    per op, and meet-span count == op count."""
    def fn(comm):
        ops = 0
        for n in (4096, 4097, 6144):  # multi-segment sizes
            x = _put(comm, (jnp.arange(n, dtype=jnp.float32) % 11)
                     + comm.rank)
            comm.allreduce_arr(x, mpi_op.SUM)
            ops += 1
        tr = comm.state.tracer
        names = [e["name"] for e in tr.snapshot() if e["ph"] == "X"]
        return (ops, names.count("meet"), names.count("seg_meet"),
                names.count("plan_exec"))

    saved = _set(dict(PLAN_ON, trace_enable="1", trace_dump_path=""))
    try:
        res = run_ranks(4, fn, devices=True)
    finally:
        _restore(saved)
    for ops, meets, seg_meets, plan_execs in res:
        assert meets == ops == plan_execs == 3
        assert seg_meets == 0
    # the plan_exec spans land in the coll_segment histogram, so the
    # autotune fold keeps a per-op latency pulse on the plan path
    def hist_fn(comm):
        from ompi_tpu import trace
        x = _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11))
        comm.allreduce_arr(x, mpi_op.SUM)
        tr = comm.state.tracer
        return tr.hist_total(trace.HIST_COLL_SEGMENT)

    saved = _set(dict(PLAN_ON, trace_enable="1", trace_dump_path=""))
    try:
        res = run_ranks(4, hist_fn, devices=True)
    finally:
        _restore(saved)
    assert all(n >= 1 for n in res)


def test_plan_hbm_byte_identical():
    """Plan path over the intra-chip (one shared device) module:
    stacked whole-payload kernel, one rendezvous, fused-identical."""
    import jax as _jax
    _one_dev = lambda r: _jax.devices()[0]  # noqa: E731

    def fn(comm):
        from ompi_tpu.coll import plan
        b0 = plan.pv_builds.read()
        out = _reduce_ops(comm)
        comm.Barrier()
        return out, plan.pv_builds.read() - b0

    saved = _set(PLAN_ON)
    try:
        plan_res = run_ranks(4, fn, device_map=_one_dev)
    finally:
        _restore(saved)
    saved = _set(FUSED)
    try:
        fused = run_ranks(4, fn, device_map=_one_dev)
    finally:
        _restore(saved)
    for (pb, dbuilds), (fb, _) in zip(plan_res, fused):
        assert pb == fb
        assert dbuilds > 0


# ---------------------------------------------------------------------------
# chaos: delay faults and epoch boundaries
# ---------------------------------------------------------------------------

def test_plan_under_delay_faults():
    """ft_inject 'delay' at the (single) rendezvous: straggler arrival
    order through the plan path changes nothing."""
    def fn(comm):
        return _reduce_ops(comm)

    saved = _set(PLAN_ON)
    try:
        clean = run_ranks(4, fn, devices=True)
        chaos = _set({"ft_inject_plan": "delay", "ft_inject_seed": 7,
                      "ft_inject_rate": 0.5, "ft_inject_delay_ms": 5,
                      "ft_inject_skip": 0})
        try:
            chaotic = run_ranks(4, fn, devices=True)
        finally:
            _restore(chaos)
    finally:
        _restore(saved)
    assert clean == chaotic
    assert len({b for b, *_ in clean}) >= 1


def test_plan_across_shrink_epoch():
    """A rank dies mid-job: the shrink epoch must purge the resolved
    plan cache AND evict the old mesh's plan executables from the
    compile cache — then the shrunk world recomputes fresh, byte-
    identical to a never-failed world of the survivor size."""
    import time
    from ompi_tpu.coll.device import compile_cache
    from ompi_tpu.ft import ulfm

    def survivor(comm):
        old_dev_key = tuple(
            d.id for d in comm.mesh().devices.reshape(-1))
        _ = np.asarray(comm.allreduce_arr(
            _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank), mpi_op.SUM))  # old-epoch plan op
        assert "_coll_plans" in comm.__dict__
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        new = comm.shrink()
        assert "_coll_plans" not in comm.__dict__  # epoch hygiene
        stale = [k for k in list(compile_cache._d)
                 if isinstance(k, tuple) and k
                 and isinstance(k[0], str) and k[0].startswith("plan_")
                 and old_dev_key in k]
        assert not stale  # no stale-mesh executables survive
        x = _put(new, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + new.rank)
        return np.asarray(new.allreduce_arr(x, mpi_op.SUM)).tobytes()

    def fresh(comm):
        x = _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank)
        return np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()

    saved = _set(PLAN_ON)
    try:
        got = run_ranks(4, survivor, devices=True, allow_failures=True)
        ref = run_ranks(3, fresh, devices=True)
    finally:
        _restore(saved)
    assert got[0] is None
    assert got[1] == got[2] == got[3] == ref[0]


def test_plan_across_respawn_epoch():
    """Kill + in-job respawn between plan-path collectives: the
    replacement's epoch sees no stale plans and the completed job's
    bytes match a fault-free run exactly."""
    from ompi_tpu import errhandler as eh
    from ompi_tpu.cr import buddy
    from ompi_tpu.errhandler import MPIException
    from ompi_tpu.ft import respawn, ulfm

    ft_codes = (eh.ERR_PROC_FAILED, eh.ERR_PROC_FAILED_PENDING,
                eh.ERR_REVOKED)

    def make_fn(kill_at=None, iters=3):
        kill_at = kill_at or {}

        def fn(comm):
            state = comm.state
            was_joining = respawn.joining(state)
            if was_joining:
                comm = respawn.rejoin(comm)
                st = buddy.restore(comm)
                i, acc = int(st["i"]), np.asarray(st["acc"])
            else:
                i, acc = 0, np.zeros(4099, np.float32)
            did_kill = False
            base = (jnp.arange(4099, dtype=jnp.float32) % 11)
            while i < iters:
                try:
                    buddy.checkpoint(comm, {"i": i, "acc": acc})
                    if (not was_joining and not did_kill
                            and kill_at.get(comm.rank) == i):
                        did_kill = True
                        ulfm.kill_now(state)
                    x = _put(comm, base * (i + 1) + comm.rank)
                    acc = np.asarray(
                        comm.allreduce_arr(x, mpi_op.SUM))
                    i += 1
                except MPIException as e:
                    if e.code not in ft_codes:
                        raise
                    comm = respawn.rejoin(comm)
                    assert "_coll_plans" not in comm.__dict__
                    st = buddy.restore(comm)
                    i, acc = int(st["i"]), np.asarray(st["acc"])
            return acc.tobytes()
        return fn

    saved = _set(PLAN_ON)
    registry.set("cr_buddy_degree", "1")
    try:
        clean = run_ranks(4, make_fn(), devices=True, timeout=120)
        faulty = run_ranks(4, make_fn(kill_at={1: 1}), devices=True,
                           timeout=180, respawn=True)
    finally:
        registry.set("cr_buddy_degree", "0")
        _restore(saved)
    assert faulty == clean
    assert all(r is not None for r in faulty)


# ---------------------------------------------------------------------------
# cache bounds, pvars, staging
# ---------------------------------------------------------------------------

def test_plan_cache_lru_and_compile_stability():
    """Plan resolution is once per geometry (hits climb, builds flat
    on repeats), the per-comm LRU obeys coll_plan_cache_max, and a
    repeated identical world compiles ZERO new executables."""
    from ompi_tpu.coll import plan
    from ompi_tpu.coll.device import compile_cache

    def fn(comm):
        for _rep in range(3):
            for n in (2048, 4096, 6000):
                x = _put(comm, jnp.ones((n,), jnp.float32))
                comm.allreduce_arr(x, mpi_op.SUM)
        comm.Barrier()
        return len(comm.__dict__["_coll_plans"])

    saved = _set(PLAN_ON)
    try:
        run_ranks(4, fn, devices=True)  # warm: compile the programs
        builds0 = compile_cache.builds
        # thread-ranks share the process: read the process-wide pvars
        # here, where no rank is mid-flight
        b0, h0 = plan.pv_builds.read(), plan.pv_hits.read()
        res = run_ranks(4, fn, devices=True)
        assert compile_cache.builds == builds0  # zero new executables
        # 3 geometries x 4 ranks resolve fresh per-comm plans; every
        # repeat after the first hits
        assert plan.pv_builds.read() - b0 == 3 * 4
        assert plan.pv_hits.read() - h0 == 6 * 4
        assert res == [3] * 4
    finally:
        _restore(saved)

    # LRU bound: more geometries than the cap leaves <= cap entries
    def fn_lru(comm):
        for n in (2048, 4096, 6000, 8192, 10240):
            x = _put(comm, jnp.ones((n,), jnp.float32))
            comm.allreduce_arr(x, mpi_op.SUM)
        return len(comm.__dict__["_coll_plans"])

    saved = _set(dict(PLAN_ON, coll_plan_cache_max=2))
    try:
        res = run_ranks(4, fn_lru, devices=True)
    finally:
        _restore(saved)
    assert all(n <= 2 for n in res)


def test_plan_live_purge_rebuilds():
    """SELECTION_CACHE_KEYS includes _coll_plans: a live purge (what
    an autotune fold does when the calibrated segment moves) drops the
    resolved plans and the next op rebuilds rank-locally — same
    bytes."""
    from ompi_tpu.ft import ulfm

    def fn(comm):
        x = _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank)
        a = np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()
        assert "_coll_plans" in comm.__dict__
        ulfm.purge_comm_caches(comm, ulfm.SELECTION_CACHE_KEYS)
        assert "_coll_plans" not in comm.__dict__
        b = np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()
        return a == b

    saved = _set(PLAN_ON)
    try:
        res = run_ranks(4, fn, devices=True)
    finally:
        _restore(saved)
    assert all(res)


def test_staging_shared_utility():
    """The hoisted runtime/staging module: alignment guarantee, the
    probe's cached verdict, MirrorPool take/park reuse and bound, and
    osc/device actually riding the shared names."""
    from ompi_tpu.runtime import staging

    buf = staging.aligned_empty(1024)
    assert buf.ctypes.data % staging.STAGE_ALIGN == 0
    assert buf.nbytes == 1024

    v1 = staging.runtime_zero_copy()
    assert isinstance(v1, bool)
    assert staging.runtime_zero_copy() is v1  # cached

    pool = staging.MirrorPool(max_buffers=2)
    a = pool.take(256)
    assert a.ctypes.data % staging.STAGE_ALIGN == 0
    pool.park(a)
    b = pool.take(256)
    assert b.ctypes.data == a.ctypes.data  # reused, no fresh pages
    pool.park(b)
    pool.park(staging.aligned_empty(256))
    pool.park(staging.aligned_empty(256))  # beyond the bound: dropped
    assert len(pool._free) == 2
    pool.park(None)  # tolerated no-op
    assert len(pool._free) == 2
    small = pool.take(4096)  # nothing parked is big enough
    assert small.nbytes == 4096

    # osc/device is re-pointed at the shared discipline
    from ompi_tpu.osc import device as osc_device
    assert osc_device._aligned_empty is staging.aligned_empty
    assert osc_device._runtime_zero_copy is staging.runtime_zero_copy
    assert osc_device._STAGE_ALIGN == staging.STAGE_ALIGN
