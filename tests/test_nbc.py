"""Nonblocking collectives (coll/nbc): schedule-based i-collectives
vs numpy references, overlap of multiple in-flight instances, and
flush-on-completion for strided buffers (ref: libnbc test spirit —
ompi/mca/coll/libnbc progressed schedules)."""

import numpy as np
import pytest

from ompi_tpu.coll.buffers import IN_PLACE
from ompi_tpu.op import op as mpi_op
from ompi_tpu.pml.request import wait_all
from ompi_tpu.testing import run_ranks

SIZES = [1, 2, 3, 5, 8]


@pytest.mark.parametrize("n", SIZES)
def test_iallreduce(n):
    def fn(comm):
        x = np.arange(33, dtype=np.float64) + comm.rank
        r = np.empty_like(x)
        comm.Iallreduce(x, r, mpi_op.SUM).wait()
        return r

    exp = sum(np.arange(33, dtype=np.float64) + k for k in range(n))
    for r in run_ranks(n, fn):
        np.testing.assert_allclose(r, exp)


@pytest.mark.parametrize("n", SIZES)
def test_ibcast(n):
    def fn(comm):
        x = np.arange(16, dtype=np.int64) * 3 if comm.rank == 1 % n \
            else np.zeros(16, dtype=np.int64)
        comm.Ibcast(x, root=1 % n).wait()
        return x

    for r in run_ranks(n, fn):
        np.testing.assert_array_equal(r, np.arange(16, dtype=np.int64) * 3)


@pytest.mark.parametrize("n", SIZES)
def test_ireduce(n):
    def fn(comm):
        x = np.full(7, comm.rank + 1, dtype=np.int32)
        if comm.rank == 0:
            r = np.empty_like(x)
            comm.Ireduce(x, r, mpi_op.PROD, root=0).wait()
            return r
        comm.Ireduce(x, None, mpi_op.PROD, root=0).wait()
        return None

    res = run_ranks(n, fn)
    exp = np.full(7, np.prod(np.arange(1, n + 1)), dtype=np.int32)
    np.testing.assert_array_equal(res[0], exp)


def test_ireduce_noncommutative():
    n = 5

    def fn(comm):
        x = np.array([comm.rank], dtype=np.int64)
        def user(inv, inout, _dt):
            inout[:] = 10 * inv + inout
        op = mpi_op.create(user, commute=False)
        if comm.rank == 0:
            r = np.empty_like(x)
            comm.Ireduce(x, r, op, root=0).wait()
            return r
        comm.Ireduce(x, None, op, root=0).wait()
        return None

    res = run_ranks(n, fn)
    # canonical order: ((((0*10+1)*10+2)*10+3)*10+4 = 1234
    np.testing.assert_array_equal(res[0], np.array([1234]))


@pytest.mark.parametrize("n", SIZES)
def test_ibarrier(n):
    def fn(comm):
        req = comm.Ibarrier()
        req.wait()
        return comm.rank

    assert run_ranks(n, fn) == list(range(n))


@pytest.mark.parametrize("n", SIZES)
def test_iallgather(n):
    def fn(comm):
        x = np.array([comm.rank, comm.rank * 10], dtype=np.int64)
        r = np.empty(2 * comm.size, dtype=np.int64)
        comm.Iallgather(x, r).wait()
        return r

    exp = np.concatenate([[k, 10 * k] for k in range(n)])
    for r in run_ranks(n, fn):
        np.testing.assert_array_equal(r, exp)


def test_iallgatherv():
    n = 4

    def fn(comm):
        cnt = comm.rank + 1
        x = np.full(cnt, comm.rank, dtype=np.int64)
        rcounts = [k + 1 for k in range(comm.size)]
        displs = np.concatenate([[0], np.cumsum(rcounts)[:-1]]).tolist()
        r = np.empty(sum(rcounts), dtype=np.int64)
        comm.Iallgatherv(x, r, rcounts, displs).wait()
        return r

    exp = np.concatenate([np.full(k + 1, k, dtype=np.int64)
                          for k in range(n)])
    for r in run_ranks(n, fn):
        np.testing.assert_array_equal(r, exp)


@pytest.mark.parametrize("n", SIZES)
def test_igather_iscatter(n):
    def fn(comm):
        x = np.array([comm.rank * 2 + 1], dtype=np.int64)
        g = np.empty(comm.size, dtype=np.int64) if comm.rank == 0 else None
        comm.Igather(x, g, root=0).wait()
        s = np.empty(1, dtype=np.int64)
        src = g * 3 if comm.rank == 0 else None
        comm.Iscatter(src, s, root=0).wait()
        return s

    for k, r in enumerate(run_ranks(n, fn)):
        np.testing.assert_array_equal(r, np.array([(2 * k + 1) * 3]))


def test_iscatter_in_place():
    """Root receives IN_PLACE: keeps its own block, only sends."""
    n = 4

    def fn(comm):
        if comm.rank == 0:
            src = np.arange(comm.size, dtype=np.int64) * 5
            comm.Iscatter(src, IN_PLACE, root=0).wait()
            return src[0]
        r = np.empty(1, dtype=np.int64)
        comm.Iscatter(None, r, root=0).wait()
        return int(r[0])

    assert run_ranks(n, fn) == [0, 5, 10, 15]


@pytest.mark.parametrize("n", SIZES)
def test_ialltoall(n):
    def fn(comm):
        sz = comm.size
        x = (np.arange(sz, dtype=np.int64) + 100 * comm.rank)
        r = np.empty(sz, dtype=np.int64)
        comm.Ialltoall(x, r).wait()
        return r

    for k, r in enumerate(run_ranks(n, fn)):
        exp = np.array([k + 100 * j for j in range(n)], dtype=np.int64)
        np.testing.assert_array_equal(r, exp)


def test_ialltoallv():
    n = 3

    def fn(comm):
        sz = comm.size
        scounts = [(comm.rank + j) % sz + 1 for j in range(sz)]
        sdispls = np.concatenate([[0], np.cumsum(scounts)[:-1]]).tolist()
        sbuf = np.concatenate(
            [np.full(scounts[j], 10 * comm.rank + j, dtype=np.int64)
             for j in range(sz)])
        rcounts = [(j + comm.rank) % sz + 1 for j in range(sz)]
        rdispls = np.concatenate([[0], np.cumsum(rcounts)[:-1]]).tolist()
        rbuf = np.empty(sum(rcounts), dtype=np.int64)
        comm.Ialltoallv(sbuf, scounts, sdispls, rbuf, rcounts,
                        rdispls).wait()
        return rbuf

    res = run_ranks(n, fn)
    for k in range(n):
        exp = np.concatenate(
            [np.full((j + k) % n + 1, 10 * j + k, dtype=np.int64)
             for j in range(n)])
        np.testing.assert_array_equal(res[k], exp)


def test_ialltoallv_in_place():
    n = 3

    def fn(comm):
        sz = comm.size
        counts = [1] * sz
        displs = list(range(sz))
        buf = np.array([100 * comm.rank + j for j in range(sz)],
                       dtype=np.int64)
        comm.Ialltoallv(IN_PLACE, None, None, buf, counts, displs).wait()
        return buf

    res = run_ranks(n, fn)
    for k in range(n):
        exp = np.array([100 * j + k for j in range(n)], dtype=np.int64)
        np.testing.assert_array_equal(res[k], exp)


@pytest.mark.parametrize("n", SIZES)
def test_ireduce_scatter_block(n):
    def fn(comm):
        sz = comm.size
        x = np.arange(2 * sz, dtype=np.float64) + comm.rank
        r = np.empty(2, dtype=np.float64)
        comm.Ireduce_scatter_block(x, r, mpi_op.SUM).wait()
        return r

    full = sum(np.arange(2 * n, dtype=np.float64) + k for k in range(n))
    for k, r in enumerate(run_ranks(n, fn)):
        np.testing.assert_allclose(r, full[2 * k: 2 * k + 2])


def test_ireduce_scatter_varying():
    n = 4

    def fn(comm):
        rcounts = [1, 2, 3, 4][: comm.size]
        x = np.arange(sum(rcounts), dtype=np.int64) * (comm.rank + 1)
        r = np.empty(rcounts[comm.rank], dtype=np.int64)
        comm.Ireduce_scatter(x, r, rcounts, mpi_op.SUM).wait()
        return r

    rcounts = [1, 2, 3, 4]
    full = sum(np.arange(10, dtype=np.int64) * (k + 1) for k in range(n))
    displs = [0, 1, 3, 6]
    for k, r in enumerate(run_ranks(n, fn)):
        np.testing.assert_array_equal(
            r, full[displs[k]: displs[k] + rcounts[k]])


@pytest.mark.parametrize("n", SIZES)
def test_iscan_iexscan(n):
    def fn(comm):
        x = np.array([comm.rank + 1], dtype=np.int64)
        s = np.empty(1, dtype=np.int64)
        comm.Iscan(x, s, mpi_op.SUM).wait()
        e = np.full(1, -1, dtype=np.int64)
        comm.Iexscan(x, e, mpi_op.SUM).wait()
        return int(s[0]), int(e[0])

    for k, (s, e) in enumerate(run_ranks(n, fn)):
        assert s == sum(range(1, k + 2))
        if k > 0:
            assert e == sum(range(1, k + 1))


def test_overlapping_instances():
    """Several nonblocking collectives in flight on one comm at once —
    per-instance tags must keep them from cross-matching."""
    n = 4

    def fn(comm):
        xs = [np.full(5, comm.rank + i, dtype=np.int64) for i in range(6)]
        rs = [np.empty_like(x) for x in xs]
        reqs = [comm.Iallreduce(x, r, mpi_op.SUM)
                for x, r in zip(xs, rs)]
        b = comm.Ibarrier()
        wait_all(reqs + [b])
        return rs

    for rs in run_ranks(n, fn):
        for i, r in enumerate(rs):
            np.testing.assert_array_equal(
                r, np.full(5, sum(k + i for k in range(n))))


def test_overlap_with_p2p():
    """p2p traffic interleaved with a pending nonblocking collective."""
    n = 3

    def fn(comm):
        x = np.full(4, comm.rank, dtype=np.int64)
        r = np.empty_like(x)
        req = comm.Iallreduce(x, r, mpi_op.SUM)
        peer = (comm.rank + 1) % comm.size
        src = (comm.rank - 1 + comm.size) % comm.size
        sb = np.array([comm.rank * 7], dtype=np.int64)
        rb = np.empty(1, dtype=np.int64)
        comm.Sendrecv(sb, peer, 5, rb, src, 5)
        req.wait()
        return r, int(rb[0])

    res = run_ranks(n, fn)
    for k, (r, v) in enumerate(res):
        np.testing.assert_array_equal(r, np.full(4, sum(range(n))))
        assert v == ((k - 1 + n) % n) * 7


def test_strided_buffer_flush():
    """Copied-out (non-contiguous) buffers must be written back when
    the schedule completes, not at post time."""
    n = 2

    def fn(comm):
        big = np.zeros((8, 2), dtype=np.float64)
        col = big[:, 0]  # strided view → convertor copy path
        x = np.arange(8, dtype=np.float64) + comm.rank
        comm.Iallreduce(x, col, mpi_op.SUM).wait()
        return big.copy()

    for big in run_ranks(n, fn):
        np.testing.assert_allclose(
            big[:, 0], 2 * np.arange(8, dtype=np.float64) + 1)
        np.testing.assert_allclose(big[:, 1], 0)
