"""ULFM rank-failure mitigation tests (ompi_tpu/ft/ulfm): detect ->
ERR_PROC_FAILED -> revoke / agree / shrink, survivor-mesh rebuild
(ref: the MPI-4 FT proposal MPIX_Comm_revoke/shrink/agree)."""

import time

import numpy as np
import pytest

from ompi_tpu import errhandler as eh
from ompi_tpu.errhandler import MPIException
from ompi_tpu.ft import ulfm
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import mpirun_run, run_ranks

PF = eh.ERR_PROC_FAILED
PFP = eh.ERR_PROC_FAILED_PENDING
RV = eh.ERR_REVOKED


# ---- detect + report ------------------------------------------------

def test_parked_recv_raises_proc_failed():
    """A receive parked on a peer that dies completes with
    ERR_PROC_FAILED instead of hanging (the tentpole's report leg)."""
    def fn(comm):
        if comm.rank == 1:
            time.sleep(0.2)
            ulfm.kill_now(comm.state)
        buf = np.zeros(4)
        with pytest.raises(MPIException) as ei:
            comm.Recv(buf, source=1, tag=7)
        return ei.value.code

    r = run_ranks(3, fn, allow_failures=True)
    assert r == [PF, None, PF]
    assert ulfm._pv_failures.read() >= 1


def test_detection_latency_bound():
    """arm_rank_kill (the ft_inject rank_kill path) fires out of the
    victim's blocking wait; survivors learn of the death and drain
    within a small multiple of the kill delay — never a fence/recv
    timeout."""
    def fn(comm):
        if comm.rank == 1:
            ulfm.arm_rank_kill(comm.state, 0.25)
            buf = np.zeros(4)
            comm.Recv(buf, source=0, tag=99)  # parked until the kill
            return "victim survived"
        t0 = time.monotonic()
        buf = np.zeros(4)
        with pytest.raises(MPIException) as ei:
            comm.Recv(buf, source=1, tag=42)
        return (ei.value.code, time.monotonic() - t0)

    r = run_ranks(2, fn, allow_failures=True)
    assert r[1] is None  # the victim died, it did not "survive"
    code, dt = r[0]
    assert code == PF
    assert 0.2 <= dt < 10.0, dt


def test_parked_allreduce_raises_proc_failed():
    """Survivors parked inside a blocking collective drain with an
    ULFM error when a member dies mid-operation.  Rank 0 (every
    algorithm's root / chain head) is the victim, so no survivor can
    complete without noticing."""
    def fn(comm):
        if comm.rank == 0:
            time.sleep(0.25)
            ulfm.kill_now(comm.state)
        x = np.full(32, comm.rank + 1.0)
        r = np.empty_like(x)
        with pytest.raises(MPIException) as ei:
            comm.Allreduce(x, r, mpi_op.SUM)
        return ei.value.code

    r = run_ranks(4, fn, allow_failures=True)
    assert r[0] is None
    assert all(c in (PF, PFP, RV) for c in r[1:])


def test_send_to_failed_peer_raises_at_entry():
    """Once a failure is known, NEW ops naming the dead peer fail fast
    at post time (isend/irecv entry check), not at wait time."""
    def fn(comm):
        if comm.rank == 1:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)  # let the failure record arrive + ingest
        comm.state.ulfm.poll()
        with pytest.raises(MPIException) as ei:
            comm.Send(np.zeros(4), dest=1, tag=3)
        return ei.value.code

    r = run_ranks(3, fn, allow_failures=True)
    assert r == [PF, None, PF]


def test_anysource_pending_then_ack():
    """ANY_SOURCE with an unacknowledged failure raises
    ERR_PROC_FAILED_PENDING; after Comm.ack_failed() ANY_SOURCE works
    again and matches a live sender (MPIX_Comm_failure_ack)."""
    def fn(comm):
        if comm.rank == 1:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        comm.state.ulfm.poll()
        if comm.rank == 2:
            comm.Send(np.full(4, 7.0), dest=0, tag=5)
            return "sent"
        # rank 0: pending until the failure is acknowledged
        buf = np.zeros(4)
        with pytest.raises(MPIException) as ei:
            comm.Recv(buf, source=-1, tag=5)
        assert ei.value.code == PFP
        assert comm.ack_failed() == 1
        comm.Recv(buf, source=-1, tag=5)
        return float(buf[0])

    r = run_ranks(3, fn, allow_failures=True)
    assert r == [7.0, None, "sent"]


def test_get_failed_and_epoch():
    def fn(comm):
        if comm.rank == 2:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        u = comm.state.ulfm
        u.poll()
        assert u.epoch >= 1
        return comm.get_failed()

    r = run_ranks(3, fn, allow_failures=True)
    assert r == [[2], [2], None]


# ---- revoke ---------------------------------------------------------

def test_revoke_drains_all_ranks():
    """Comm.revoke poisons the communicator job-wide: every parked op
    drains with ERR_REVOKED, later ops fail at entry, and the parent
    communicator is untouched."""
    def fn(comm):
        sub = comm.dup(name="revokee")
        if comm.rank == 0:
            time.sleep(0.25)
            sub.revoke()
            code = RV
        else:
            buf = np.zeros(4)
            with pytest.raises(MPIException) as ei:
                sub.Recv(buf, source=0, tag=1)  # parked, then drained
            code = ei.value.code
        assert sub.is_revoked()
        # new ops on the revoked comm fail fast at entry
        with pytest.raises(MPIException) as ei2:
            sub.Send(np.zeros(2), dest=(comm.rank + 1) % comm.size)
        assert ei2.value.code == RV
        comm.Barrier()  # the parent communicator still works
        return code

    r = run_ranks(4, fn, allow_failures=True)
    assert r == [RV] * 4
    assert ulfm._pv_revokes.read() >= 1


# ---- agree ----------------------------------------------------------

def test_agree_healthy():
    def fn(comm):
        a = comm.agree(comm.rank != 2)  # one False poisons the AND
        b = comm.agree(True)
        return (a, b)

    assert run_ranks(4, fn) == [(False, True)] * 4


@pytest.mark.parametrize("phase", ["pre_contrib", "post_contrib",
                                   "pre_decision", "post_decision"])
def test_agree_identical_under_kill(phase):
    """The acceptance-critical property: every survivor returns the
    SAME flag no matter at which protocol phase a member dies.  The
    victim is rank 0 — the initial leader — so leader-death promotion
    is exercised, not just contributor loss."""
    def fn(comm):
        u = comm.state.ulfm
        if comm.rank == 0:
            def hook(p):
                if p == phase:
                    raise ulfm.RankKilled(f"killed at {p}")
            u._agree_test_hook = hook
        return comm.agree(comm.rank != 2)

    r = run_ranks(4, fn, allow_failures=True)
    assert r[0] is None, f"victim must die at {phase}"
    assert [x for x in r[1:]] == [False] * 3, (phase, r)


# ---- shrink ---------------------------------------------------------

def test_shrink_host_path():
    """shrink returns a survivor communicator every member agrees on:
    dense new ranks, same cid everywhere, errhandler inherited, and
    host-path collectives work on it."""
    def fn(comm):
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        new = comm.shrink(name="survivors")
        assert new.errhandler is comm.errhandler
        x = np.full(16, new.rank + 1.0)
        r = np.empty_like(x)
        new.Allreduce(x, r, mpi_op.SUM)
        return (new.size, new.rank, new.cid, float(r[0]))

    r = run_ranks(4, fn, allow_failures=True)
    assert r[0] is None
    live = [x for x in r if x is not None]
    assert [(s, rk) for s, rk, _, _ in live] == [(3, 0), (3, 1), (3, 2)]
    assert len({cid for _, _, cid, _ in live}) == 1  # agreed cid
    assert all(v == 6.0 for _, _, _, v in live)


def test_shrink_device_allreduce_byte_identical():
    """The chaos-demo acceptance check, thread-world edition: a device
    allreduce on the shrunk 3-rank communicator is byte-identical to
    the same allreduce on a fresh 3-rank world."""
    def survivor_bytes(comm):
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        new = comm.shrink()
        x = np.arange(8.0) * (new.rank + 1)
        return np.asarray(new.allreduce_arr(x, mpi_op.SUM)).tobytes()

    def fresh_bytes(comm):
        x = np.arange(8.0) * (comm.rank + 1)
        return np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()

    got = run_ranks(4, survivor_bytes, devices=True,
                    allow_failures=True)
    ref = run_ranks(3, fresh_bytes, devices=True)
    assert got[0] is None
    assert got[1] == got[2] == got[3] == ref[0] == ref[1] == ref[2]


def test_shrink_invalidates_compiled_cache():
    """Executables compiled against the dead mesh shape are dropped
    from the bounded CompiledLRU (they could never be hit again)."""
    from ompi_tpu.coll import device

    def fn(comm):
        x = np.arange(8.0)
        comm.allreduce_arr(x, mpi_op.SUM)  # compile on the 4-mesh
        mesh = comm.__dict__.get("_mesh")
        dev_key = (tuple(d.id for d in mesh.devices.reshape(-1))
                   if mesh is not None else None)
        time.sleep(0.2)  # everyone clear of the collective first
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        comm.shrink()
        if dev_key is None:
            return 0
        with device.compile_cache._lock:
            return sum(1 for k in device.compile_cache._d
                       if dev_key in k)

    r = run_ranks(4, fn, devices=True, allow_failures=True)
    assert all(x == 0 for x in r[1:]), r  # no stale-mesh entries


def test_compiled_lru_drop_mesh_unit():
    from ompi_tpu.coll.device import CompiledLRU
    c = CompiledLRU()
    old, new = (0, 1, 2, 3), (1, 2, 3)
    c.get(("allreduce", old, "f32"), lambda: (lambda: None))
    c.get(("bcast", old, "f32"), lambda: (lambda: None))
    c.get(("allreduce", new, "f32"), lambda: (lambda: None))
    assert c.drop_mesh(old) == 2
    assert len(c) == 1 and c.drop_mesh(old) == 0


# ---- chaos demo -----------------------------------------------------

def test_chaos_demo_threadworld():
    """The ISSUE's acceptance demo: a 4-rank job loses rank 0 mid-loop,
    survivors catch the failure, shrink, and COMPLETE the remaining
    iterations on 3 — with the final device allreduce byte-identical
    to a fresh 3-rank world's."""
    steps = 30

    def chaos(comm):
        work = comm
        out = None
        step = 0
        while step < steps:
            if comm.rank == 0 and step == 5:
                ulfm.kill_now(comm.state)  # dies mid-loop
            try:
                x = np.arange(8.0) * (work.rank + 1)
                out = np.asarray(work.allreduce_arr(x, mpi_op.SUM))
                step += 1
                time.sleep(0.02)
            except MPIException as e:
                assert e.code in (PF, PFP, RV), e.code
                work = work.shrink(name="survivors")
        return (work.size, out.tobytes())

    def fresh(comm):
        x = np.arange(8.0) * (comm.rank + 1)
        return np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()

    got = run_ranks(4, chaos, devices=True, allow_failures=True,
                    timeout=180.0)
    ref = run_ranks(3, fresh, devices=True)
    assert got[0] is None
    assert all(x == (3, ref[0]) for x in got[1:]), got


@pytest.mark.slow
def test_mpirun_ulfm_policy_process_ranks(tmp_path):
    """End-to-end over real processes: ft_inject kills rank 1, the
    'ulfm' errmgr policy publishes the failure instead of tearing the
    job down, survivors shrink and the job EXITS 0 on 3 ranks."""
    r = mpirun_run(
        4, "tests/_ulfm_prog.py",
        mca=(("errmgr_base_policy", "ulfm"),
             ("ft_inject_plan", "rank_kill"),
             ("ft_inject_victim_rank", "1"),
             ("ft_inject_after", "0.8")),
        timeout=180, job_timeout=120)
    out = r.stdout.decode()
    assert r.returncode == 0, (r.returncode, out[-500:],
                               r.stderr.decode()[-2000:])
    lines = [ln for ln in out.splitlines() if ln.startswith("rank=")]
    assert len(lines) == 3, out[-800:]
    assert all("size=3" in ln and "sum=6.0" in ln for ln in lines), lines
    assert "ulfm policy" in r.stderr.decode()


# ---- knobs / zero-cost-when-off -------------------------------------

def test_ulfm_disabled_is_absent():
    """mpi_ft_ulfm=0: no UlfmState is attached (hot paths see None —
    the zero-cost contract) and the mitigation API refuses."""
    registry.set("mpi_ft_ulfm", "0")
    try:
        def fn(comm):
            assert comm.state.ulfm is None
            with pytest.raises(RuntimeError, match="ULFM is disabled"):
                comm.agree(True)
            with pytest.raises(RuntimeError, match="ULFM is disabled"):
                comm.shrink()
            return True

        assert run_ranks(2, fn) == [True, True]
    finally:
        registry.set("mpi_ft_ulfm", "1")


def test_ft_inject_rank_faults_gating():
    from ompi_tpu import ft_inject
    assert ft_inject.rank_faults(0) == []  # plan empty: fully passive
    registry.set("ft_inject_plan", "rank_kill")
    registry.set("ft_inject_victim_rank", "2")
    try:
        assert ft_inject.rank_faults(2) == ["rank_kill"]
        assert ft_inject.rank_faults(0) == []
        assert ft_inject.rank_kill_victim() == 2
    finally:
        registry.set("ft_inject_plan", "")
        registry.set("ft_inject_victim_rank", "1")
