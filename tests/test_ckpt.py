"""Tiered checkpoint engine tests (cr/ckpt + cr/shard): the async
collective-I/O filesystem tier under buddy, two-phase commit, the CRC
restore ladder, io fault injection, and multi-kill chaos where a rank
AND all its buddy partners die in one window."""

import json
import os
import zlib

import numpy as np
import pytest

from ompi_tpu import errhandler as eh
from ompi_tpu.cr import buddy, ckpt
from ompi_tpu.cr import shard as shard_mod
from ompi_tpu.errhandler import MPIException
from ompi_tpu.ft import respawn, ulfm
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks
from ompi_tpu.tools import hotpath_audit

FT_CODES = (eh.ERR_PROC_FAILED, eh.ERR_PROC_FAILED_PENDING,
            eh.ERR_REVOKED)


@pytest.fixture
def store(tmp_path):
    return str(tmp_path / "ckpt")


@pytest.fixture
def buddy_degree_1():
    registry.set("cr_buddy_degree", "1")
    yield
    registry.set("cr_buddy_degree", "0")


@pytest.fixture
def inject_now():
    """Arm ft_inject with no warm-up so the first roll already fires;
    tests set the plan themselves and it is always cleared."""
    registry.set("ft_inject_skip", "0")
    yield
    registry.set("ft_inject_plan", "")
    registry.set("ft_inject_skip", "8")


# ---- shard serializer (the format both tiers share) ------------------

def test_shard_roundtrip_mixed_pytree():
    import jax.numpy as jnp
    payload = {
        "step": 7,
        "w": jnp.arange(32.0).reshape(4, 8),
        "opt": [np.arange(10, dtype=np.int32), ("adam", 0.9)],
        "note": "hello",
    }
    out = shard_mod.loads(shard_mod.dumps(payload), None)
    assert out["step"] == 7 and out["note"] == "hello"
    assert out["opt"][1] == ("adam", 0.9)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(payload["w"]))
    np.testing.assert_array_equal(out["opt"][0], payload["opt"][0])
    # kinds survive: jax leaves come back as jax, numpy as numpy
    assert not isinstance(out["w"], np.ndarray)
    assert isinstance(out["opt"][0], np.ndarray)


def test_shard_numpy_snapshot_at_plan_time():
    a = np.arange(8.0)
    p = shard_mod.plan({"a": a})
    a[:] = -1.0  # mutate AFTER plan: the snapshot must not tear
    shard_mod.drain(p.shards[0])
    got = np.frombuffer(p.shards[0].host.tobytes(), dtype=a.dtype)
    np.testing.assert_array_equal(got, np.arange(8.0))


def test_shard_loads_detects_corruption():
    blob = bytearray(shard_mod.dumps({"w": np.arange(64.0)}))
    blob[-3] ^= 0xFF  # flip a byte inside the shard region
    with pytest.raises(ValueError, match="CRC mismatch"):
        shard_mod.loads(bytes(blob), None)


# ---- filesystem tier roundtrips --------------------------------------

def _payload(rank, i):
    return {"i": i, "w": np.arange(256.0) * (i + 1) + rank,
            "tag": f"r{rank}"}


def test_fs_roundtrip_async(store):
    """Async mode: checkpoint enqueues, drain happens on progress
    ticks, flush commits; restore replays the epoch byte-exact."""
    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, 3), store_dir=store)
        e = ckpt.flush(comm)
        assert e == 0
        out = ckpt.restore(comm, store_dir=store)
        ref = _payload(comm.rank, 3)
        assert out["i"] == 3 and out["tag"] == ref["tag"]
        np.testing.assert_array_equal(out["w"], ref["w"])
        return True
    assert run_ranks(4, fn) == [True] * 4
    man = json.load(open(os.path.join(store, "ep_000000",
                                      "manifest.json")))
    assert man["nprocs"] == 4 and len(man["ranks"]) == 4


def test_fs_roundtrip_sync_mode(store):
    """cr_drain_depth 0: the epoch is written inside the checkpoint
    call through one fcoll collective write and committed before
    return — no flush needed."""
    registry.set("cr_drain_depth", "0")
    try:
        def fn(comm):
            _, e = ckpt.checkpoint(comm, _payload(comm.rank, 5),
                                   store_dir=store)
            assert e == 0
            assert ckpt.pending_epoch(comm.state) == -1
            out = ckpt.restore(comm, store_dir=store)
            np.testing.assert_array_equal(out["w"],
                                          _payload(comm.rank, 5)["w"])
            return True
        assert run_ranks(4, fn) == [True] * 4
    finally:
        registry.set("cr_drain_depth", "2")


def test_fs_interval_and_deferred_commit(store):
    """cr_fs_interval 2: epochs land on every other call; each
    begin folds the previous epoch's commit in."""
    registry.set("cr_fs_interval", "2")
    try:
        def fn(comm):
            epochs = []
            for i in range(4):
                _, e = ckpt.checkpoint(comm, _payload(comm.rank, i),
                                       store_dir=store)
                epochs.append(e)
            ckpt.flush(comm)
            return epochs
        out = run_ranks(4, fn)
        assert out == [[0, -1, 1, -1]] * 4
    finally:
        registry.set("cr_fs_interval", "1")
    # only calls 0 and 2 produced epochs
    assert sorted(os.listdir(store)) == ["ep_000000", "ep_000001"]


def test_commit_record_published_put_once(store):
    """Phase 2 of the commit publishes a put-once record in the ULFM
    KV plane, observable without touching the filesystem."""
    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, 0), store_dir=store)
        ckpt.flush(comm)
        rec = ulfm._store(comm.state).try_get(("cr_ckpt", "commit", 0))
        return rec is not None and rec["epoch"] == 0
    assert run_ranks(4, fn) == [True] * 4


def test_restore_ladder_empty_returns_none(store):
    """No buddy replica, no committed epoch: restore returns None and
    the caller escalates to job restart."""
    def fn(comm):
        return ckpt.restore(comm, store_dir=store)
    assert run_ranks(2, fn) == [None, None]


# ---- io fault injection ----------------------------------------------

def test_io_stall_delays_but_commits(store, inject_now):
    """io_stall holds writes delay_ms each; the epoch still commits
    and restores clean — stalls cost time, never integrity."""
    registry.set("ft_inject_plan", "io_stall:1.0")
    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, 1), store_dir=store)
        ckpt.flush(comm)
        out = ckpt.restore(comm, store_dir=store)
        np.testing.assert_array_equal(out["w"],
                                      _payload(comm.rank, 1)["w"])
        return True
    assert run_ranks(2, fn) == [True] * 2


def test_io_partial_crc_falls_back_to_previous_epoch(store,
                                                     inject_now):
    """A truncated shard write (io_partial) leaves a COMMITTED but
    corrupt epoch; restore detects the CRC mismatch and falls back to
    the previous committed epoch — never a torn one."""
    fb0 = ckpt._pv_crc_fb.read()

    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, 0), store_dir=store)
        ckpt.flush(comm)
        registry.set("ft_inject_plan", "io_partial:1.0")
        try:
            ckpt.checkpoint(comm, _payload(comm.rank, 1),
                            store_dir=store)
            ckpt.flush(comm)
        finally:
            registry.set("ft_inject_plan", "")
        out = ckpt.restore(comm, store_dir=store)
        assert out["i"] == 0, "restored a corrupt epoch"
        np.testing.assert_array_equal(out["w"],
                                      _payload(comm.rank, 0)["w"])
        return True

    assert run_ranks(4, fn) == [True] * 4
    assert ckpt._pv_crc_fb.read() > fb0
    # both epochs committed (manifest present); epoch 1 is just corrupt
    assert sorted(os.listdir(store)) == ["ep_000000", "ep_000001"]


def test_io_enospc_aborts_epoch_collectively(store, inject_now):
    """ENOSPC on any rank aborts the epoch on EVERY rank (agreed at
    commit), leaves no manifest, and the previous epoch restores."""
    ab0 = ckpt._pv_aborted.read()

    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, 0), store_dir=store)
        ckpt.flush(comm)
        registry.set("ft_inject_plan", "io_enospc:1.0")
        try:
            ckpt.checkpoint(comm, _payload(comm.rank, 1),
                            store_dir=store)
            with pytest.raises(OSError, match="aborted"):
                ckpt.flush(comm)
        finally:
            registry.set("ft_inject_plan", "")
        out = ckpt.restore(comm, store_dir=store)
        assert out["i"] == 0
        return True

    assert run_ranks(4, fn) == [True] * 4
    assert ckpt._pv_aborted.read() > ab0
    assert not os.path.exists(os.path.join(store, "ep_000001",
                                           "manifest.json"))


def test_io_partial_sync_mode(store, inject_now):
    """The injection point also covers the fcoll collective-write
    path (cr_drain_depth 0): corruption is zeroed tail bytes there,
    caught by the same manifest CRCs at restore."""
    registry.set("cr_drain_depth", "0")
    try:
        def fn(comm):
            ckpt.checkpoint(comm, _payload(comm.rank, 0),
                            store_dir=store)
            registry.set("ft_inject_plan", "io_partial:1.0")
            try:
                ckpt.checkpoint(comm, _payload(comm.rank, 1),
                                store_dir=store)
            finally:
                registry.set("ft_inject_plan", "")
            out = ckpt.restore(comm, store_dir=store)
            return out["i"]
        assert run_ranks(2, fn) == [0, 0]
    finally:
        registry.set("cr_drain_depth", "2")


# ---- the tentpole scenario: multi-kill chaos -------------------------

def _make_fn(root, iters=8, kill_at=None):
    """App loop with per-iteration tiered checkpoints; kill_at maps
    rank -> iteration at which the ORIGINAL incarnation dies (same
    iteration on several ranks = one correlated multi-kill window)."""
    kill_at = kill_at or {}

    def _step(i, acc, comm):
        x = np.full(4, (comm.rank + 1.0) * (i + 1))
        r = np.empty_like(x)
        comm.Allreduce(x, r, mpi_op.SUM)
        return acc + r

    def fn(comm):
        state = comm.state
        was_joining = respawn.joining(state)
        recover = was_joining  # rejoin+restore before the first step
        i, acc = 0, np.zeros(4)
        did_kill = False
        while i < iters:
            try:
                if recover:
                    # recovery runs INSIDE the try: a peer dying while
                    # this rank is mid-rejoin/restore lands back in the
                    # handler and recovery restarts against the newer
                    # failure set instead of escaping the loop
                    comm = respawn.rejoin(comm)
                    st = ckpt.restore(comm, store_dir=root)
                    i, acc = int(st["i"]), np.asarray(st["acc"])
                    recover = False
                ckpt.checkpoint(comm, {"i": i, "acc": acc},
                                store_dir=root)
                if (not was_joining and not did_kill
                        and kill_at.get(comm.rank) == i):
                    did_kill = True
                    ulfm.kill_now(state)
                acc = _step(i, acc, comm)
                i += 1
            except MPIException as e:
                if e.code not in FT_CODES:
                    raise
                if (not was_joining and not did_kill
                        and kill_at.get(comm.rank) == i):
                    # a partner's death interrupted this rank before
                    # its own scheduled kill fired: die anyway, so the
                    # multi-kill stays correlated (one window) instead
                    # of degrading to two sequential single kills
                    did_kill = True
                    ulfm.kill_now(state)
                recover = True
        return acc.tobytes()
    return fn


def test_multikill_rank_and_buddy_falls_to_fs(store, buddy_degree_1):
    """8 ranks, degree 1: rank 1 AND its only partner (rank 2) die in
    one window — every buddy copy of rank 1's state is gone.  The
    ladder degrades to the filesystem tier and the job finishes
    byte-identical to a fault-free run, with the tier hit visible in
    the cr_ckpt pvars."""
    clean = run_ranks(8, _make_fn(store), timeout=120)
    import shutil
    shutil.rmtree(store, ignore_errors=True)
    fs0 = ckpt._pv_rest_fs.read()
    faulty = run_ranks(8, _make_fn(store, kill_at={1: 5, 2: 5}),
                       timeout=180, respawn=True)
    assert faulty == clean
    assert ckpt._pv_rest_fs.read() > fs0


def test_single_kill_stays_on_buddy_fast_path(store, buddy_degree_1):
    """One dead rank with a live partner never touches the filesystem
    tier at restore: the buddy rung of the ladder serves it (the 4.4ms
    MTTR path from ISSUE 4/5 is preserved, not bypassed)."""
    clean = run_ranks(4, _make_fn(store), timeout=120)
    import shutil
    shutil.rmtree(store, ignore_errors=True)
    fs0 = ckpt._pv_rest_fs.read()
    bd0 = ckpt._pv_rest_buddy.read()
    faulty = run_ranks(4, _make_fn(store, kill_at={1: 5}),
                       timeout=120, respawn=True)
    assert faulty == clean
    assert ckpt._pv_rest_buddy.read() > bd0
    assert ckpt._pv_rest_fs.read() == fs0


@pytest.mark.slow
def test_multikill_16_ranks_two_pairs(store, buddy_degree_1):
    """16 ranks, TWO correlated pairs (each a rank + its partner) dead
    in the same window: one batched rejoin epoch, filesystem restores,
    byte-identical finish."""
    clean = run_ranks(16, _make_fn(store), timeout=240)
    import shutil
    shutil.rmtree(store, ignore_errors=True)
    fs0 = ckpt._pv_rest_fs.read()
    faulty = run_ranks(
        16, _make_fn(store, kill_at={1: 5, 2: 5, 9: 5, 10: 5}),
        timeout=300, respawn=True)
    assert faulty == clean
    assert ckpt._pv_rest_fs.read() > fs0


@pytest.mark.slow
def test_large_state_async_roundtrip(store):
    """Multi-megabyte mixed jax/numpy state through the async drain:
    many shards, several drain ticks, byte-exact restore."""
    import jax.numpy as jnp

    def fn(comm):
        payload = {
            "w": [jnp.arange(65536.0) + comm.rank for _ in range(8)],
            "m": np.random.default_rng(comm.rank).normal(
                size=(512, 512)),
        }
        ckpt.checkpoint(comm, payload, store_dir=store)
        ckpt.flush(comm)
        out = ckpt.restore(comm, store_dir=store)
        for a, b in zip(out["w"], payload["w"]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(out["m"], payload["m"])
        return True
    assert run_ranks(4, fn, timeout=240) == [True] * 4


# ---- retention + observability ---------------------------------------

def test_cr_keep_uniform_across_tiers(store):
    """One retention knob (cr_keep) governs both tiers: buddy seqs
    prune to max(KEEP_SEQS, cr_keep); fs epochs prune to max(2,
    cr_keep) after each commit."""
    registry.set("cr_keep", "3")
    try:
        assert buddy._keep_seqs() == 3
        assert ckpt.keep_epochs() == 3

        def fn(comm):
            for i in range(6):
                ckpt.checkpoint(comm, _payload(comm.rank, i),
                                store_dir=store)
            ckpt.flush(comm)
            return True
        assert run_ranks(2, fn) == [True] * 2
    finally:
        registry.set("cr_keep", "0")
    assert sorted(os.listdir(store)) == [
        "ep_000003", "ep_000004", "ep_000005"]
    # cr_keep 0: fs keeps all, buddy falls back to its RAM-bounded
    # KEEP_SEQS default
    assert ckpt.keep_epochs() == 0
    assert buddy._keep_seqs() == buddy.KEEP_SEQS
    # the fallback epoch always survives: floor of 2
    registry.set("cr_keep", "1")
    try:
        assert ckpt.keep_epochs() == 2
        assert buddy._keep_seqs() == buddy.KEEP_SEQS
    finally:
        registry.set("cr_keep", "0")


def test_ckpt_pvars_count_work(store):
    """The cr_ckpt_* pvars move with the work: epochs, shards, bytes,
    drain ticks, and the stall high-watermark."""
    pvs = (ckpt._pv_epochs, ckpt._pv_shards, ckpt._pv_bytes,
           ckpt._pv_ticks)
    base = [p.read() for p in pvs]

    def fn(comm):
        ckpt.checkpoint(comm, _payload(comm.rank, 0), store_dir=store)
        ckpt.flush(comm)
        return True
    assert run_ranks(2, fn) == [True] * 2
    for p, v in zip(pvs, base):
        assert p.read() > v, p.name
    assert ckpt._pv_stall.read() > 0


def test_hotpath_audit_stays_green():
    """Engine.tick and Progress.progress are declared hot functions;
    the AST audit over every hot function must stay empty."""
    assert hotpath_audit.audit() == []
