"""dfs: remote read-only file access over the control plane
(orte/mca/dfs/app analog; VERDICT r3 missing #5)."""

import os

import pytest

from ompi_tpu.testing import mpirun_run

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def datafile(tmp_path):
    p = tmp_path / "input.bin"
    p.write_bytes(bytes(i % 256 for i in range(3000)))
    return str(p)


def test_dfs_local_posix_route(datafile):
    from ompi_tpu.runtime import dfs
    with dfs.open(datafile) as f:
        assert f.size() == 3000
        assert f.read(5) == bytes(range(5))
        f.seek(-4, dfs.SEEK_END)
        assert len(f.read()) == 4


def test_dfs_through_kv_single_host(datafile):
    r = mpirun_run(2, os.path.join("tests", "_dfs_prog.py"), datafile,
                   timeout=180, job_timeout=150)
    assert r.returncode == 0, r.stderr.decode()[-1500:]
    assert b"dfs ok" in r.stdout


def test_dfs_forwarded_through_node_proxy(datafile):
    """Simulated multi-node: ranks sit behind per-node daemons whose
    KV proxies must forward the hnp-host dfs requests upstream."""
    r = mpirun_run(4, os.path.join("tests", "_dfs_prog.py"), datafile,
                   extra=("--simulate-nodes", "2x2"),
                   timeout=240, job_timeout=200)
    assert r.returncode == 0, r.stderr.decode()[-1500:]
    assert b"dfs ok" in r.stdout
