"""P2P engine tests: matching, protocols, wildcards, ordering.

Models the reference's p2p coverage (orte/test/mpi/hello.c,
crisscross.c; matching subtleties ref pml_ob1_recvfrag.c:510-558).
"""

import numpy as np
import pytest

from ompi_tpu.datatype import engine as dt
from ompi_tpu.op import op as mpi_op
from ompi_tpu.pml.request import ANY_SOURCE, ANY_TAG, ERR_TRUNCATE
from ompi_tpu.testing import run_ranks


def test_ring_token():
    def ring(comm):
        token = np.array([0], dtype=np.int64)
        if comm.rank == 0:
            token[0] = 42
            comm.Send(token, dest=1)
            comm.Recv(token, source=comm.size - 1)
        else:
            comm.Recv(token, source=comm.rank - 1)
            token += 1
            comm.Send(token, dest=(comm.rank + 1) % comm.size)
        return int(token[0])

    res = run_ranks(4, ring)
    assert res[0] == 42 + 3


def test_eager_and_rendezvous_sizes():
    """Cross the eager/rndv protocol boundary (512 KiB inproc)."""
    sizes = [0, 1, 1024, 512 * 1024, 512 * 1024 + 1, 3 * 1024 * 1024]

    def fn(comm):
        out = []
        for i, n in enumerate(sizes):
            if comm.rank == 0:
                data = np.arange(n, dtype=np.uint8)
                comm.Send(data, dest=1, tag=i)
            else:
                buf = np.zeros(n, dtype=np.uint8)
                st = comm.Recv(buf, source=0, tag=i)
                assert st.count == n
                np.testing.assert_array_equal(
                    buf, np.arange(n, dtype=np.uint8))
                out.append(n)
        return out

    res = run_ranks(2, fn)
    assert res[1] == sizes


def test_any_source_any_tag():
    def fn(comm):
        if comm.rank == 0:
            seen = set()
            buf = np.zeros(1, dtype=np.int32)
            for _ in range(comm.size - 1):
                st = comm.Recv(buf, source=ANY_SOURCE, tag=ANY_TAG)
                assert st.source == buf[0]
                assert st.tag == 10 + buf[0]
                seen.add(int(buf[0]))
            return seen
        comm.Send(np.array([comm.rank], np.int32), dest=0,
                  tag=10 + comm.rank)
        return None

    res = run_ranks(5, fn)
    assert res[0] == {1, 2, 3, 4}


def test_message_ordering_same_peer():
    """MPI guarantees FIFO per (src, comm); mixed tags must not
    reorder same-tag messages."""
    N = 50

    def fn(comm):
        if comm.rank == 0:
            for i in range(N):
                comm.Send(np.array([i], np.int32), dest=1, tag=5)
        else:
            for i in range(N):
                buf = np.zeros(1, np.int32)
                comm.Recv(buf, source=0, tag=5)
                assert buf[0] == i
        return True

    assert all(run_ranks(2, fn))


def test_unexpected_before_post():
    """Sender fires before receiver posts; message must buffer."""
    import time

    def fn(comm):
        if comm.rank == 0:
            comm.Send(np.array([7.5], np.float64), dest=1, tag=3)
        else:
            time.sleep(0.05)  # let it land in the unexpected queue
            buf = np.zeros(1, np.float64)
            comm.Recv(buf, source=0, tag=3)
            assert buf[0] == 7.5
        return True

    assert all(run_ranks(2, fn))


def test_ssend_blocks_until_matched():
    import time

    def fn(comm):
        if comm.rank == 0:
            t0 = time.monotonic()
            comm.Ssend(np.zeros(4, np.int32), dest=1)
            elapsed = time.monotonic() - t0
            assert elapsed > 0.04, f"Ssend returned in {elapsed}s"
        else:
            time.sleep(0.06)
            comm.Recv(np.zeros(4, np.int32), source=0)
        return True

    assert all(run_ranks(2, fn))


def test_probe_and_mprobe():
    def fn(comm):
        if comm.rank == 0:
            comm.Send(np.arange(10, dtype=np.int32), dest=1, tag=9)
            comm.Send(np.arange(3, dtype=np.int32), dest=1, tag=11)
        else:
            st = comm.Probe(source=0, tag=9)
            assert st.count == 40 and st.tag == 9
            msg = comm.Mprobe(source=0, tag=11)
            buf = np.zeros(3, np.int32)
            comm.Mrecv(buf, msg)
            np.testing.assert_array_equal(buf, [0, 1, 2])
            buf10 = np.zeros(10, np.int32)
            comm.Recv(buf10, source=0, tag=9)
            np.testing.assert_array_equal(buf10, np.arange(10))
        return True

    assert all(run_ranks(2, fn))


def test_truncation_error_flagged():
    def fn(comm):
        if comm.rank == 0:
            comm.Send(np.arange(10, dtype=np.int32), dest=1, tag=0)
        else:
            buf = np.zeros(4, np.int32)
            st = comm.Recv(buf, source=0, tag=0)
            assert st.error == ERR_TRUNCATE
            np.testing.assert_array_equal(buf, [0, 1, 2, 3])
        return True

    assert all(run_ranks(2, fn))


def test_sendrecv_exchange():
    def fn(comm):
        me = np.array([comm.rank], np.int32)
        other = np.zeros(1, np.int32)
        peer = (comm.rank + 1) % comm.size
        prev = (comm.rank - 1) % comm.size
        comm.Sendrecv(me, peer, 1, other, prev, 1)
        return int(other[0])

    res = run_ranks(4, fn)
    assert res == [3, 0, 1, 2]


def test_derived_datatype_p2p():
    """Send a matrix column (vector datatype) to a contiguous recv."""
    def fn(comm):
        if comm.rank == 0:
            grid = np.arange(36, dtype=np.float64).reshape(6, 6)
            col = dt.vector(6, 1, 6, dt.DOUBLE).commit()
            comm.Send((grid, 1, col), dest=1, tag=2)
        else:
            buf = np.zeros(6, np.float64)
            comm.Recv(buf, source=0, tag=2)
            np.testing.assert_array_equal(buf, [0, 6, 12, 18, 24, 30])
        return True

    assert all(run_ranks(2, fn))


def test_rendezvous_derived_large():
    """Large strided send crossing the rndv path with pipelining."""
    def fn(comm):
        rows, cols = 1200, 1024
        if comm.rank == 0:
            m = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
            col_t = dt.vector(rows, 8, cols, dt.FLOAT).commit()
            comm.Send((m, 1, col_t), dest=1, tag=0)
        else:
            buf = np.zeros(rows * 8, np.float32)
            comm.Recv(buf, source=0, tag=0)
            m = np.arange(rows * cols, dtype=np.float32).reshape(rows, cols)
            np.testing.assert_array_equal(buf.reshape(rows, 8), m[:, :8])
        return True

    assert all(run_ranks(2, fn))


def test_isend_irecv_waitall():
    from ompi_tpu.pml.request import wait_all

    def fn(comm):
        peer = 1 - comm.rank
        sends = [comm.Isend(np.full(4, i, np.int32), dest=peer, tag=i)
                 for i in range(8)]
        bufs = [np.zeros(4, np.int32) for _ in range(8)]
        recvs = [comm.Irecv(bufs[i], source=peer, tag=i) for i in range(8)]
        wait_all(sends + recvs)
        for i, b in enumerate(bufs):
            np.testing.assert_array_equal(b, np.full(4, i))
        return True

    assert all(run_ranks(2, fn))


def test_cancel_unmatched_recv():
    def fn(comm):
        buf = np.zeros(1, np.int32)
        req = comm.Irecv(buf, source=0, tag=999)
        if comm.rank == 1:
            ok = comm.state.pml.cancel_recv(req)
            assert ok
            st = req.wait()
            assert st.cancelled
        else:
            req2 = comm.Irecv(buf, source=1, tag=999)
            comm.state.pml.cancel_recv(req2)
            comm.state.pml.cancel_recv(req)
        return True

    assert all(run_ranks(2, fn))


def test_send_to_self():
    def fn(comm):
        if comm.rank == 0:
            req = comm.Isend(np.array([5], np.int32), dest=0, tag=1)
            buf = np.zeros(1, np.int32)
            comm.Recv(buf, source=0, tag=1)
            req.wait()
            assert buf[0] == 5
        return True

    assert all(run_ranks(2, fn))


def test_crisscross_stress():
    """Every pair exchanges (connectivity_c.c analog)."""
    def fn(comm):
        reqs = []
        bufs = {}
        for peer in range(comm.size):
            if peer == comm.rank:
                continue
            bufs[peer] = np.zeros(16, np.int64)
            reqs.append(comm.Irecv(bufs[peer], source=peer, tag=4))
        for peer in range(comm.size):
            if peer == comm.rank:
                continue
            reqs.append(comm.Isend(
                np.full(16, comm.rank * 1000 + peer, np.int64),
                dest=peer, tag=4))
        for r in reqs:
            r.wait()
        for peer, b in bufs.items():
            assert b[0] == peer * 1000 + comm.rank
        return True

    assert all(run_ranks(6, fn))


def test_public_cancel_completes_request():
    """MPI_Cancel on an unmatched recv must complete the request."""
    def fn(comm):
        buf = np.zeros(1, np.int32)
        req = comm.Irecv(buf, source=1 - comm.rank, tag=321)
        req.cancel()
        st = req.wait(timeout=5)
        assert st.cancelled
        return True

    assert all(run_ranks(2, fn))
