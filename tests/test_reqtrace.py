"""Request-scoped distributed tracing + the hang doctor
(docs/DESIGN.md §23): trace-id mint/parse round-trips, the per-rank
req_mark window ring and its span attribution, the traceview --job
waterfall reduction (synthetic and CLI), the doctor verdict reducer
over capture documents, byte-identity of a traced+watchdog-armed run
vs an untraced one, watchdog false-positive suppression (below the
stall factor: zero captures; above: exactly one per job), the attach
--events dropped-count note, per-session scoped-histogram prometheus
series, and the hotpath-audit coverage of the two new hot functions."""

import json
import os
import threading

import pytest

from ompi_tpu import obs, trace
from ompi_tpu.mca.params import registry
from ompi_tpu.obs import reqtrace
from ompi_tpu.testing import run_ranks
from ompi_tpu.tools import doctor, traceview

HERE = os.path.dirname(__file__)
PROG = os.path.join(HERE, "_dvm_session_prog.py")
SLOW_PROG = os.path.join(HERE, "_dvm_slow_prog.py")
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clean_reqtrace():
    yield
    registry.set("obs_reqtrace_enable", "0")
    registry.set("obs_watchdog_ms", "0")
    registry.set("obs_watchdog_factor", "4")
    registry.set("trace_enable", "0")
    registry.set("ft_inject_plan", "")


# -- trace-context mint/parse ------------------------------------------------

def test_mint_parse_fmt_roundtrip():
    seen = set()
    for _ in range(1000):
        tid, span = reqtrace.mint()
        assert 0 < tid < 1 << 63
        assert tid not in seen
        seen.add(tid)
        assert span >= 1
    t = next(iter(seen))
    assert reqtrace.parse(reqtrace.fmt(t)) == t
    assert reqtrace.fmt(t).startswith("0x")
    assert reqtrace.parse(str(t)) == t          # decimal form
    with pytest.raises(ValueError):
        reqtrace.parse("not-a-tid")


def test_mint_disabled_by_default():
    assert not reqtrace.enabled()
    registry.set("obs_reqtrace_enable", "1")
    assert reqtrace.enabled()


def test_next_span_monotonic():
    a = reqtrace.next_span()
    b = reqtrace.next_span()
    assert b > a


# -- req_mark ring on the Tracer ---------------------------------------------

def test_req_mark_windows_bracket_spans():
    registry.set("trace_enable", "1")
    import numpy as np
    from ompi_tpu.op import op as mpi_op

    def fn(comm):
        tr = comm.state.tracer
        assert tr is not None
        sbuf = np.ones(8, np.float32)
        rbuf = np.zeros(8, np.float32)
        tr.req_mark(0x51)
        comm.Allreduce(sbuf, rbuf, mpi_op.SUM)
        tr.req_mark(0)
        comm.Barrier()
        wins = tr.req_windows()
        dump = {"rank": comm.rank, "events": tr.snapshot(),
                "req_windows": wins}
        return wins, dump

    out = run_ranks(2, fn)
    for wins, dump in out:
        tags = [w["tag"] for w in wins]
        assert tags == [0x51, 0]
        ts = [w["ts"] for w in wins]
        assert ts == sorted(ts)
        # the window attributes this rank's coll spans to the request
        phases = traceview.request_phases([dump], 0x51)
        assert phases.get(dump["rank"], {}).get("coll", 0) > 0


def test_req_mark_ring_bounded():
    registry.set("trace_enable", "1")

    def fn(comm):
        tr = comm.state.tracer
        for n in range(trace.REQ_MARKS + 7):
            tr.req_mark(n + 1)
        return tr.req_windows()

    wins = run_ranks(1, fn)[0]
    assert len(wins) == trace.REQ_MARKS
    # oldest marks rotated out: the survivors are the newest REQ_MARKS
    assert wins[0]["tag"] == 8
    assert wins[-1]["tag"] == trace.REQ_MARKS + 7


# -- the traceview --job waterfall reduction ---------------------------------

def _flight_dump(tid=0x7, sid=3):
    evs = [
        {"name": "req_attach", "cat": "flight", "ph": "i", "ts": 100.0,
         "rank": -1, "args": {"sid": sid, "tid": tid,
                              "queued_us": 2000}},
        {"name": "req_run", "cat": "flight", "ph": "i", "ts": 100.1,
         "rank": -1, "args": {"sid": sid, "tid": tid, "span": 2,
                              "wall_ms": 50}},
        {"name": "req_park", "cat": "flight", "ph": "i", "ts": 100.2,
         "rank": -1, "args": {"sid": sid, "tid": tid}},
        {"name": "req_resume", "cat": "flight", "ph": "i", "ts": 100.3,
         "rank": -1, "args": {"sid": sid, "tid": tid, "us": 1500}},
        {"name": "req_drain", "cat": "flight", "ph": "i", "ts": 100.35,
         "rank": -1, "args": {"band": sid, "epoch": 1, "us": 800}},
        {"name": "req_run", "cat": "flight", "ph": "i", "ts": 100.4,
         "rank": -1, "args": {"sid": sid, "tid": tid, "span": 3,
                              "wall_ms": 30}},
    ]
    return {"rank": -1, "flight": True, "recorded": len(evs),
            "dropped": 0, "events": evs}


def test_job_report_synthetic_waterfall():
    fdump = _flight_dump()
    rdump = {"rank": 0, "events": [
        {"name": "allreduce", "cat": "coll", "ph": "X", "ts": 100.12,
         "dur": 0.004, "args": {}}],
        "req_windows": [{"tag": 0x7, "ts": 100.11},
                        {"tag": 0, "ts": 100.16}]}
    lines, info = traceview.job_report([fdump, rdump], [], 0x7)
    assert info["queued_us"] == 2000
    assert info["runs"] == 2 and info["run_us"] == 80000
    assert info["parks"] == 1 and info["resume_us"] == 1500
    assert info["drain_us"] == 800
    # drain stalls overlap run wall: reported, never summed
    assert info["total_us"] == 2000 + 80000 + 1500
    text = "\n".join(lines)
    assert "run #1" in text and "run #2" in text
    assert "drain" in text and "overlap" in text
    assert "span sum" in text
    # the rank's in-request span attribution rode along
    assert info["phases"].get(0, {}).get("coll", 0) == 4000
    assert "cat=" not in "" and any("in-request span" in ln
                                    for ln in lines)
    # an unknown job yields the hint line and empty info
    lines2, info2 = traceview.job_report([fdump], [], 0x999)
    assert not info2 and lines2


def test_traceview_job_cli(tmp_path, capsys):
    p = str(tmp_path / "flight.events.json")
    with open(p, "w") as fh:
        json.dump(_flight_dump(), fh)
    assert traceview.main([p, "--job", "0x7"]) == 0
    out = capsys.readouterr().out
    assert "span sum" in out and "queue" in out
    assert traceview.main([p, "--job", "0x999"]) == 1
    assert traceview.main([p, "--job", "zzz"]) == 2


# -- doctor verdict reducer --------------------------------------------------

def _capture_doc(sid=5, tid=0x9):
    return {
        "sid": sid, "tid": tid, "span": 2, "ns": f"s{sid}", "np": 4,
        "run_ms": 900, "est_ms": 100, "factor_pct": 200,
        "mttd_ms": 12.5, "aborted": None,
        "stacks": {f"dvm-s{sid}-r0": ["  File x, line 1, in wait\n"]},
        "rendezvous": [{"cid": 1, "gen": 3, "size": 4, "count": 3,
                        "arrived": [0, 1, 3], "absent": [2],
                        "pending_gens": [], "group": [4, 5, 6, 7]}],
        "fences": {"f1": {"arrived_weight": 2, "waiters": 1,
                          "arrivals": {"4": 1, "5": 1}}},
        "events": [{"name": "wd_stall", "cat": "flight", "ph": "i",
                    "ts": 1.0, "rank": -1,
                    "args": {"sid": sid, "tid": tid}}],
    }


def test_doctor_verdict_names_absent_rank():
    lines = doctor.verdict(_capture_doc())
    text = "\n".join(lines)
    # slot 2 of group [4,5,6,7] is GLOBAL rank 6 — the verdict names
    # world ranks, not comm-local slots
    assert "ABSENT ranks [6]" in text
    assert "waiting ranks [4,5,7]" in text
    assert "cid=1" in text and "gen=3" in text
    assert "0x9" in text and "s5" in text
    assert "900ms" in text and "rendezvous" in text
    # fences ride along as supporting evidence when rdvs exist
    assert "fence f1" in text and "VERDICT: in-flight KV" not in text


def test_doctor_verdict_fence_and_local_fallbacks():
    doc = _capture_doc()
    doc["rendezvous"] = []
    text = "\n".join(doctor.verdict(doc))
    assert "VERDICT: in-flight KV fence(s)" in text
    doc["fences"] = {}
    text = "\n".join(doctor.verdict(doc))
    assert "slow inside local compute" in text


def test_doctor_load_captures_and_cli(tmp_path, capsys):
    uri = str(tmp_path / "pool.uri")
    cap = f"{uri}.doctor.s5.json"
    with open(cap, "w") as fh:
        json.dump(_capture_doc(), fh)
    # a direct capture path and a uri glob both resolve
    assert doctor.load_captures(cap)[0]["sid"] == 5
    assert doctor.load_captures(uri)[0]["sid"] == 5
    assert doctor.main([uri]) == 0
    out = capsys.readouterr().out
    assert "ABSENT ranks [6]" in out and "flight recorder" in out
    assert doctor.main([uri, "--job", "0x9", "--stacks"]) == 0
    out = capsys.readouterr().out
    assert "dvm-s5-r0" in out
    # a tid mismatch filters everything out -> exit 1 with the hint
    assert doctor.main([uri, "--job", "0x8"]) == 1
    assert "obs_watchdog_ms" in capsys.readouterr().err


# -- live pool: byte identity + watchdog -------------------------------------

def _pool_run(tmp_path, name, tag):
    jax = pytest.importorskip("jax")
    from ompi_tpu.tools.dvm import DVMServer, DvmClient
    uri = str(tmp_path / f"{name}.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    try:
        with DvmClient(uri) as c:
            sid = c.attach(2)["sid"]
            r = c.run(sid, PROG, [tag], timeout=120)
            c.detach(sid)
        return r
    finally:
        srv.stop()


def test_traced_watchdog_run_byte_identical(tmp_path):
    """Tier-1 identity gate: request tagging + an armed watchdog must
    never perturb job output — same prog, same DIGEST line."""
    plain = _pool_run(tmp_path, "plain", "bi")
    assert plain["code"] == 0, plain.get("stderr", "")[-2000:]
    registry.set("obs_reqtrace_enable", "1")
    registry.set("obs_watchdog_ms", "100")
    traced = _pool_run(tmp_path, "traced", "bi")
    assert traced["code"] == 0, traced.get("stderr", "")[-2000:]
    assert traced["stdout"] == plain["stdout"]
    assert "DIGEST bi " in plain["stdout"]


def test_watchdog_suppression_and_single_capture(tmp_path):
    """Below the stall factor: ZERO doctor events.  Above: exactly one
    capture per job (the wd_fired latch), with the capture persisted
    next to the uri file and carrying the request tid."""
    jax = pytest.importorskip("jax")
    from ompi_tpu.tools.dvm import DVMServer, DvmClient
    registry.set("obs_reqtrace_enable", "1")
    registry.set("obs_watchdog_ms", "100")     # tick every 50 ms
    uri = str(tmp_path / "wd.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    try:
        with DvmClient(uri) as c:
            resp = c.attach(2)
            sid, tid = resp["sid"], resp["tid"]
            # sharpen the estimator to a deterministic 100 ms
            assert c.run(sid, PROG, ["warm"],
                         timeout=120)["code"] == 0
            srv.est_wall_us = 100_000
            # slow-but-below-threshold: 1.5 s sleep vs a 200 s limit
            registry.set("obs_watchdog_factor", "2000")
            assert c.run(sid, SLOW_PROG, [],
                         timeout=120)["code"] == 0
            assert srv.doctor_reports == []
            # above threshold (200 ms limit): exactly ONE capture
            srv.est_wall_us = 100_000
            registry.set("obs_watchdog_factor", "2")
            assert c.run(sid, SLOW_PROG, [],
                         timeout=120)["code"] == 0
            assert len(srv.doctor_reports) == 1
            doc = srv.doctor_reports[0]
            assert doc["sid"] == sid and doc["tid"] == tid
            assert doc["mttd_ms"] >= 0
            assert doc["stacks"]
            # nothing rendezvous-blocked during a sleep: the verdict
            # falls through to local compute
            assert "slow inside local compute" in \
                "\n".join(doctor.verdict(doc))
            cap = f"{uri}.doctor.s{sid}.json"
            assert os.path.isfile(cap)
            assert doctor.load_captures(cap)[0]["sid"] == sid
            # the wd_stall flight event fired exactly once
            names = [e["name"] for e in obs.recorder().snapshot(256)]
            assert names.count("wd_stall") == 1
            c.detach(sid)
    finally:
        srv.stop()


def test_watchdog_off_by_default(tmp_path):
    import time as _time
    jax = pytest.importorskip("jax")
    from ompi_tpu.tools.dvm import DVMServer
    # drain any watchdog thread a prior test's halted pool left in
    # its last 50 ms sleep
    for _ in range(40):
        if not any(t.name == "dvm-watchdog"
                   for t in threading.enumerate()):
            break
        _time.sleep(0.05)
    uri = str(tmp_path / "off.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    try:
        assert not any(t.name == "dvm-watchdog"
                       for t in threading.enumerate())
    finally:
        srv.stop()


# -- operator surfaces -------------------------------------------------------

def test_attach_events_dropped_note(tmp_path, capsys):
    """A compacted ring is never a silent short tail: the note says
    how many events are gone and why."""
    from ompi_tpu.tools import attach
    uri = str(tmp_path / "gone.uri")     # no pool at this uri
    with open(f"{uri}.events.json", "w") as fh:
        json.dump({"rank": -1, "flight": True, "recorded": 100,
                   "dropped": 60,
                   "events": [{"name": "dvm_run", "cat": "flight",
                               "ph": "i", "ts": 1.0, "rank": -1,
                               "args": {}}] * 40}, fh)
    assert attach.main([uri, "--events", "8"]) == 0
    out = capsys.readouterr().out
    assert "60 older event(s) of 100 recorded were dropped" in out
    assert "obs_events_ring" in out


def test_prometheus_scoped_hist_series():
    """Per-session SLI histograms export as labeled percentile gauges
    in the 0.0.4 text format: one family, session + q labels."""
    sh = obs.scoped_hist("dvm_sli_test_qwait_us")
    sh.add_us(100, band=7)
    sh.add_us(200, band=7)
    m = obs.local_metrics(events=0)
    text = obs.prometheus_text(m)
    assert "# TYPE ompi_tpu_dvm_sli_test_qwait_us gauge" in text
    assert 'ompi_tpu_dvm_sli_test_qwait_us{q="p99"}' in text
    assert ('ompi_tpu_dvm_sli_test_qwait_us{session="7",q="p99"}'
            in text)
    # 0.0.4: every non-comment line is "name{labels} value"
    for ln in text.strip().splitlines():
        assert ln.startswith("#") or " " in ln


def test_hotpath_audit_covers_reqtrace_and_watchdog():
    from ompi_tpu.tools import hotpath_audit
    assert "Tracer.req_mark" in hotpath_audit.HOT_FUNCTIONS[
        "ompi_tpu/trace/__init__.py"]
    assert "DVMServer._watchdog_tick" in hotpath_audit.HOT_FUNCTIONS[
        "ompi_tpu/tools/dvm.py"]
    assert hotpath_audit.audit() == []
