"""MPI_Comm_join driver (run under mpirun by test_intercomm): rank 0
listens on a localhost socket, rank 1 dials it; both join over the
connected fd, sendrecv across the resulting 1-1 intercomm, and
verify."""
import socket

import numpy as np

import ompi_tpu
from ompi_tpu import mpi
from ompi_tpu.datatype import engine as dt

comm = ompi_tpu.init()
state = comm.state
if comm.rank == 0:
    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    state.rte.modex_put("join_port", lst.getsockname()[1])
    conn, _ = lst.accept()
else:
    port = state.rte.modex_get(0, "join_port")
    conn = socket.create_connection(("127.0.0.1", int(port)))

inter = mpi.MPI_Comm_join(conn.fileno())
assert inter.size == 1 and inter.remote_size == 1
pml = state.pml
x = np.array([comm.rank], dtype=np.int64)
y = np.empty(1, dtype=np.int64)
s = pml.isend(x, 1, dt.INT64_T, 0, -62, inter)
pml.recv(y, 1, dt.INT64_T, 0, -62, inter)
s.wait()
assert int(y[0]) == 1 - comm.rank, y
conn.close()
if comm.rank == 0:
    print("join ok", flush=True)
ompi_tpu.finalize()
