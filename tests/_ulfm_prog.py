"""ULFM chaos program (run via mpirun by test_ulfm.py): one rank is
killed mid-loop by ft_inject ``rank_kill``; under the ``ulfm`` errmgr
policy the survivors see ERR_PROC_FAILED, shrink, and finish the job
on the remaining ranks — forward recovery, no restart."""
import time

import numpy as np

import ompi_tpu
from ompi_tpu.errhandler import MPIException
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
work = comm
r = np.empty(64, dtype=np.float64)
shrunk = 0
for step in range(120):
    try:
        buf = np.full(64, work.rank + 1.0, dtype=np.float64)
        work.Allreduce(buf, r, mpi_op.SUM)
    except MPIException as e:
        assert e.code in (75, 76, 77), e.code
        work = work.shrink(name="survivors")
        shrunk += 1
        continue
    time.sleep(0.02)
print(f"rank={work.rank} size={work.size} shrunk={shrunk} "
      f"sum={float(r[0])}", flush=True)
ompi_tpu.finalize()
