"""Device-memory one-sided RMA tests (osc/device, ISSUE 14): the
promoted rma_counter / halo_stencil examples as byte-identity checks
between the pt2pt and device components, framework selection, segment
chunking, typed-atomic dtype routing, and epoch hygiene across ULFM
death and shrink (kernels/selection purged, blocked sync raises)."""

import time

import numpy as np
import pytest

from ompi_tpu import errhandler as eh
from ompi_tpu import osc
from ompi_tpu.errhandler import MPIException
from ompi_tpu.ft import ulfm
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

PF = eh.ERR_PROC_FAILED
PFP = eh.ERR_PROC_FAILED_PENDING
RV = eh.ERR_REVOKED


# ---- promoted example workloads (component-agnostic) ----------------
# osc.allocate routes through osc_select: a mesh-capable comm
# (devices=True) mints the device window, a host comm the pt2pt one —
# the SAME workload bytes must come back either way.

def _counter_workload(comm):
    """examples/rma_counter.py: fence put ring, passive atomic
    counter, fetch_and_op ticketing, compare_and_swap."""
    rank, size = comm.rank, comm.size
    out = {}

    ring = osc.allocate(comm, 16, disp_unit=8, name="ring")
    out["component"] = type(ring).__name__
    ring.fence()
    ring.put(np.full(2, rank, dtype=np.int64), (rank + 1) % size)
    ring.fence()
    out["ring"] = np.asarray(ring.memory).tobytes()

    # passive target: int64 counter on rank 0 (the 8-byte dtype takes
    # the device component's host-fallback atomic path)
    ctr = osc.allocate(comm, 8, disp_unit=8, name="ctr")
    tickets = []
    for _ in range(5):
        old = np.empty(1, dtype=np.int64)
        ctr.lock(0, osc.LOCK_SHARED)
        ctr.fetch_and_op(1, old, 0, op=mpi_op.SUM)
        ctr.unlock(0)
        tickets.append(int(old[0]))
    assert sorted(set(tickets)) == tickets  # monotone per origin
    comm.Barrier()
    got = np.empty(1, dtype=np.int64)
    ctr.lock(0, osc.LOCK_SHARED)
    ctr.get(got, 0)
    ctr.unlock(0)
    assert int(got[0]) == 5 * size
    out["counter"] = got.tobytes()

    # compare_and_swap election on an int32 slot (device-jitted dtype)
    slot = osc.allocate(comm, 4, disp_unit=4, name="cas")
    oldv = np.empty(1, dtype=np.int32)
    slot.lock(0, osc.LOCK_SHARED)
    slot.compare_and_swap(np.int32(0), np.int32(rank + 1), oldv, 0)
    slot.unlock(0)
    comm.Barrier()
    winner = np.empty(1, dtype=np.int32)
    slot.lock(0, osc.LOCK_SHARED)
    slot.get(winner, 0)
    slot.unlock(0)
    assert 1 <= int(winner[0]) <= size
    out["cas_winner_is_set"] = bool(winner[0] != 0)

    slot.free()
    ctr.free()
    ring.free()
    return out


def _halo_workload(comm):
    """examples/halo_stencil.py, RMA-flavored: each rank PUTS its
    tile edges into the neighbors' windows (west slot / east slot)
    instead of exchanging them with neighbor collectives."""
    rank, size = comm.rank, comm.size
    n = 32
    win = osc.allocate(comm, 2 * n * 4, disp_unit=4, name="halo")
    tile = (np.arange(n, dtype=np.float32) + 1) * (rank + 1)
    win.fence()
    win.put(tile, (rank + 1) % size, disp=0)       # right's west slot
    win.put(tile * 2, (rank - 1) % size, disp=n)   # left's east slot
    win.fence()
    halo = np.asarray(win.memory).tobytes()
    # one relaxation step off the received halos
    mem = np.frombuffer(halo, dtype=np.float32)
    west, east = mem[:n], mem[n:]
    new = (tile + west + east) / 3.0
    win.free()
    return {"component": type(win).__name__, "halo": halo,
            "tile": new.tobytes()}


def _expected_halo(rank, size):
    n = 32
    base = np.arange(n, dtype=np.float32) + 1
    west = base * ((rank - 1) % size + 1)
    east = base * 2 * ((rank + 1) % size + 1)
    return np.concatenate([west, east]).tobytes()


@pytest.mark.parametrize("workload", [_counter_workload, _halo_workload],
                         ids=["rma_counter", "halo_stencil"])
def test_promoted_examples_byte_identical(workload):
    n = 4
    host = run_ranks(n, workload, devices=False)
    dev = run_ranks(n, workload, devices=True)
    assert all(r["component"] == "Window" for r in host)
    assert all(r["component"] == "DeviceWindow" for r in dev)
    for r in range(n):
        for k in host[r]:
            if k == "component":
                continue
            assert host[r][k] == dev[r][k], (r, k)
    if workload is _halo_workload:
        for r in range(n):
            assert dev[r]["halo"] == _expected_halo(r, n)


# ---- framework selection --------------------------------------------

def test_osc_select_device_vs_pt2pt():
    """Win_create commits to the mesh only for device-committed
    buffers; --mca osc pt2pt overrides the verdict."""
    def fn(comm):
        import jax.numpy as jnp
        host_win = osc.create(comm, np.zeros(8, dtype=np.int64))
        dev_win = osc.create(comm, jnp.zeros(8, jnp.int32))
        kinds = (type(host_win).__name__, type(dev_win).__name__)
        host_win.free()
        dev_win.free()
        registry.set("osc", "pt2pt")
        comm.__dict__.pop("_osc_pick", None)
        try:
            forced = osc.allocate(comm, 64, name="forced")
            forced_kind = type(forced).__name__
            forced.free()
        finally:
            registry.set("osc", "")
            comm.__dict__.pop("_osc_pick", None)
        return kinds + (forced_kind,)

    res = run_ranks(2, fn, devices=True)
    assert all(r == ("Window", "DeviceWindow", "Window") for r in res)


def test_no_mesh_falls_back_to_pt2pt():
    def fn(comm):
        win = osc.allocate(comm, 32)
        kind = type(win).__name__
        win.free()
        return kind

    assert run_ranks(2, fn, devices=False) == ["Window", "Window"]


# ---- data plane -----------------------------------------------------

def test_large_transfers_chunked_by_segment():
    """Kernel mode: transfers larger than the calibrated segment are
    split into bucket kernels; bytes land exactly (including
    unaligned spans)."""
    def fn(comm):
        registry.set("osc_device_dma", "0")
        registry.set("osc_device_seg_bytes", "4096")
        try:
            win = osc.allocate(comm, 1 << 16, name="big")
            rng = np.random.default_rng(100 + comm.rank)
            blob = rng.integers(0, 256, 40001, dtype=np.uint8)
            win.fence()
            win.put(blob, (comm.rank + 1) % comm.size, disp=13)
            win.fence()
            back = np.empty(40001, dtype=np.uint8)
            win.get(back, comm.rank, disp=13)
            left = (comm.rank - 1) % comm.size
            exp = np.random.default_rng(100 + left).integers(
                0, 256, 40001, dtype=np.uint8)
            ok = bool(np.array_equal(back, exp))
            win.fence()
            win.free()
            return ok
        finally:
            registry.set("osc_device_seg_bytes", "0")
            registry.set("osc_device_dma", "1")

    assert all(run_ranks(4, fn, devices=True))


def test_dma_and_kernel_lowerings_byte_identical():
    """The default direct-DMA lowering and the whole-mesh ppermute
    kernel lowering must produce identical window bytes for the same
    op sequence — puts at odd offsets, zero-copy wholesale puts,
    accumulate, CAS and get_accumulate."""
    def run(comm, tag):
        rank, size = comm.rank, comm.size
        win = osc.allocate(comm, 256, disp_unit=1, name=f"eq-{tag}")
        win.fence()
        # odd-offset partial put
        win.put(np.arange(7, dtype=np.uint8) + rank,
                (rank + 1) % size, disp=3)
        win.fence()
        # wholesale put (DMA mode's zero-copy borrow path when the
        # buffer happens to be aligned); snapshot before the Barrier
        # so no rank reads a window a peer already rewrote this epoch
        snap = np.asarray(win.memory).view(np.uint8)[3:10].copy()
        comm.Barrier()
        whole = np.full(256, rank + 10, dtype=np.uint8)
        whole[3:10] = snap
        win.put(whole, (rank + 2) % size)
        win.fence()
        # typed ops
        win.accumulate(np.full(4, rank + 1, dtype=np.int32), 0,
                       disp=16, op=mpi_op.SUM)
        win.fence()
        old = np.empty(1, dtype=np.int32)
        win.lock(0, osc.LOCK_SHARED)
        if rank == 1:  # single origin: the winner must be
            win.compare_and_swap(np.int32(0), np.int32(rank + 1),
                                 old, 0, disp=32)  # deterministic
        res = np.empty(4, dtype=np.int32)
        win.get_accumulate(np.full(4, 2, dtype=np.int32), res, 0,
                           disp=16, op=mpi_op.NO_OP)
        win.unlock(0)
        win.fence()
        mem = np.asarray(win.memory).tobytes()
        win.free()
        return {"mem": mem, "res": res.tobytes()}

    # the registry is process-global and ranks are threads: flipping
    # the var inside the rank fn would let an early-finishing rank
    # switch its peers' lowering mid-sequence — set it once per run,
    # from the parent, around run_ranks
    via_dma = run_ranks(4, lambda c: run(c, "dma"), devices=True)
    registry.set("osc_device_dma", "0")
    try:
        via_krn = run_ranks(4, lambda c: run(c, "krn"), devices=True)
    finally:
        registry.set("osc_device_dma", "1")
    for r in range(4):
        assert via_dma[r]["mem"] == via_krn[r]["mem"], r
        assert via_dma[r]["res"] == via_krn[r]["res"], r


def test_accumulate_dtype_routing():
    """int32/float32 accumulate runs the jitted kernel; int64/float64
    take the host fallback — results identical either way."""
    def fn(comm):
        rank, size = comm.rank, comm.size
        out = {}
        for dt, tag in ((np.int32, "i4"), (np.float32, "f4"),
                        (np.int64, "i8"), (np.float64, "f8")):
            win = osc.allocate(comm, 8 * np.dtype(dt).itemsize,
                               disp_unit=np.dtype(dt).itemsize,
                               name=f"acc-{tag}")
            win.fence()
            win.accumulate(np.full(8, rank + 1, dtype=dt), 0,
                           op=mpi_op.SUM)
            win.fence()
            if rank == 0:
                out[tag] = np.asarray(win.memory).tobytes()
            # MPI_REPLACE and MPI_NO_OP through get_accumulate
            res = np.empty(8, dtype=dt)
            win.fence()
            win.get_accumulate(np.full(8, 99, dtype=dt), res, 0,
                               op=mpi_op.NO_OP)
            win.fence()
            total = size * (size + 1) // 2
            assert np.all(res == np.asarray(total, dtype=dt)), (tag, res)
            win.free()
        return out

    res = run_ranks(4, fn, devices=True)
    total = 4 * 5 // 2
    for tag, dt in (("i4", np.int32), ("f4", np.float32),
                    ("i8", np.int64), ("f8", np.float64)):
        assert res[0][tag] == np.full(8, total, dtype=dt).tobytes()


def test_bucket_keys_bounded():
    """Kernel mode: a size sweep must not mint one kernel per size —
    bucket widths are pow2-quantized, so distinct put-kernel keys
    stay logarithmic."""
    def fn(comm):
        from ompi_tpu.coll import device as cdev
        registry.set("osc_device_dma", "0")
        try:
            win = osc.allocate(comm, 1 << 14, name="sweep")
            win.fence()
            for nb in range(1, 200, 7):
                win.put(np.full(nb, comm.rank, dtype=np.uint8),
                        (comm.rank + 1) % comm.size)
            win.fence()
            with cdev.compile_cache._lock:
                keys = sum(1 for k in cdev.compile_cache._d
                           if k[0] == "osc_pput" and k[1] == win._dev_key
                           and k[2] == win._cap)
            win.free()
            return keys
        finally:
            registry.set("osc_device_dma", "1")

    res = run_ranks(2, fn, devices=True)
    # sizes 1..199 collapse onto ONE 256-byte bucket per (origin,
    # target) pair — 2 pairs in this 2-rank sweep
    assert all(k <= 2 for k in res), res


# ---- epoch hygiene (ULFM) -------------------------------------------

def test_fence_raises_after_peer_death():
    """A fence on a comm with a dead rank must raise, not hang."""
    def fn(comm):
        win = osc.allocate(comm, 64, name="chaos-fence")
        win.fence()
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        try:
            for _ in range(100):
                win.fence()
                time.sleep(0.02)
            return "no-raise"
        except MPIException as e:
            assert e.code in (PF, PFP, RV), e.code
            win.abandon()
            return "raised"

    r = run_ranks(4, fn, devices=True, allow_failures=True)
    assert r[0] is None and all(x == "raised" for x in r[1:]), r


def test_lock_raises_after_peer_death():
    """A passive-target lock of a dead rank completes with
    ERR_PROC_FAILED instead of spinning forever."""
    def fn(comm):
        win = osc.allocate(comm, 64, name="chaos-lock")
        win.fence()
        if comm.rank == 1:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        try:
            for _ in range(100):
                win.lock(1, osc.LOCK_EXCLUSIVE)
                win.put(np.zeros(4, dtype=np.uint8), 1)
                win.unlock(1)
                time.sleep(0.02)
            return "no-raise"
        except MPIException as e:
            assert e.code in (PF, PFP, RV), e.code
            win.abandon()
            return "raised"

    r = run_ranks(3, fn, devices=True, allow_failures=True)
    assert r[1] is None and all(
        x == "raised" for i, x in enumerate(r) if i != 1), r


def test_shrink_purges_rma_kernels_and_selection():
    """ULFM shrink drops the dead mesh's compiled RMA kernels from
    the CompiledLRU, re-decides osc selection (_osc_pick) and purges
    the window shard tables of the revoked comm."""
    from ompi_tpu.coll import device as cdev

    def fn(comm):
        win = osc.allocate(comm, 256, name="purge")
        win.fence()
        win.put(np.arange(8, dtype=np.uint8), (comm.rank + 1) % comm.size)
        win.fence()
        dev_key = win._dev_key
        time.sleep(0.2)
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        comm.shrink()
        with cdev.compile_cache._lock:
            stale = sum(1 for k in cdev.compile_cache._d if dev_key in k)
        pick_purged = "_osc_pick" not in comm.__dict__
        world = comm.state.rte.world
        with world.shared_lock:
            tabs = sum(1 for k in world.shared
                       if isinstance(k, tuple) and k
                       and k[0] == "osc_devwin" and k[1] == comm.cid)
        return (stale, pick_purged, tabs)

    r = run_ranks(4, fn, devices=True, allow_failures=True)
    assert all(x == (0, True, 0) for x in r[1:]), r


def test_counter_byte_identity_across_shrink():
    """The acceptance demo: survivors shrink after a death and the
    promoted counter workload on the shrunken device comm is
    byte-identical to a fresh world of the survivor size."""
    def chaos(comm):
        comm.Barrier()
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        work = comm
        while work is comm:
            try:
                work.Barrier()
                time.sleep(0.05)
            except MPIException as e:
                assert e.code in (PF, PFP, RV), e.code
                work = work.shrink(name="survivors")
        return _counter_workload(work)

    got = run_ranks(4, chaos, devices=True, allow_failures=True,
                    timeout=180.0)
    ref = run_ranks(3, _counter_workload, devices=True)
    assert got[0] is None
    for i in range(1, 4):
        assert got[i] == ref[i - 1], i
