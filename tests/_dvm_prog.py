"""DVM warm-pool probe (run via mpirun --dvm by test_launcher.py):
times program start -> first completed device collective.  In a warm
pool, imports, the jax runtime, and the compiled collective are all
cache hits, so the second job's time collapses."""
import time

t0 = time.perf_counter()
import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
if comm.state.device is not None:
    import jax
    import jax.numpy as jnp
    x = jax.device_put(jnp.full((1024,), comm.rank + 1.0, jnp.float32),
                       comm.state.device)
    r = comm.allreduce_arr(x, mpi_op.SUM)
    got = float(np.asarray(r)[0])
else:
    x = np.full(1024, comm.rank + 1.0, dtype=np.float32)
    r = np.empty_like(x)
    comm.Allreduce(x, r, mpi_op.SUM)
    got = float(r[0])
expect = sum(range(1, comm.size + 1))
assert abs(got - expect) < 1e-3, (got, expect)
if comm.rank == 0:
    print(f"first_coll_s={time.perf_counter() - t0:.4f}", flush=True)
ompi_tpu.finalize()
