"""Hybrid-launch integration tests: mpirun --ranks-per-proc spawns
per-host app shells (ompi_tpu.tools.hostrun) whose rank-threads drive
devices, making coll/tpu reachable from a real launch (VERDICT r1 #2;
ref: selection must work on real jobs, coll_base_comm_select.c:51-58).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mpirun(np, prog, *args, rpp="all", devices=None, timeout=150,
           extra=()):
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", str(np),
           "--ranks-per-proc", str(rpp)]
    if devices:
        cmd += ["--devices", devices]
    cmd += list(extra)
    cmd += [os.path.join(REPO, "examples", prog), *args]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    return subprocess.run(cmd, capture_output=True, timeout=timeout,
                          env=env, cwd=REPO)


def test_device_collectives_offloaded_under_mpirun():
    """The north-star gate: a real mpirun job reports
    coll_tpu_offloaded_collectives > 0."""
    r = mpirun(8, "device_allreduce.py")
    assert r.returncode == 0, (r.stdout.decode(), r.stderr.decode())
    out = r.stdout.decode()
    line = [ln for ln in out.splitlines()
            if ln.startswith("coll_tpu_offloaded_collectives=")]
    assert line, out
    assert int(line[0].split("=")[1]) > 0
    for k in range(8):
        assert f"rank {k} ok" in out


def test_hybrid_two_shells_ring():
    """Two app shells (simulated hosts): cross-process p2p via tcp,
    in-process via the inproc btl."""
    r = mpirun(4, "ring.py", rpp=2, devices="none")
    assert r.returncode == 0, r.stderr.decode()
    assert "received token 7 from 3" in r.stdout.decode()


def test_hybrid_two_shells_connectivity():
    r = mpirun(4, "connectivity.py", rpp=2, devices="none")
    assert r.returncode == 0, r.stderr.decode()
    assert "PASSED" in r.stdout.decode()


def test_hybrid_rank_failure_kills_job():
    """A rank-thread failing is the thread analog of a rank process
    dying: the shell reports it to the launcher, whose errmgr policy
    terminates the job (nonzero) instead of hanging peers."""
    import tempfile
    import textwrap

    with tempfile.TemporaryDirectory() as d:
        prog = os.path.join(d, "fail_one.py")
        with open(prog, "w") as f:
            f.write(textwrap.dedent("""
                import ompi_tpu
                from ompi_tpu.op import op as mpi_op
                comm = ompi_tpu.init()
                if comm.rank == 1:
                    raise RuntimeError("boom on rank 1")
                import numpy as np
                x = np.zeros(1, np.int32)
                r = np.zeros(1, np.int32)
                comm.Allreduce(x, r, mpi_op.SUM)
                ompi_tpu.finalize()
            """))
        cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "4",
               "--ranks-per-proc", "all", "--devices", "none",
               "--timeout", "60", prog]
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        r = subprocess.run(cmd, capture_output=True, timeout=120,
                           env=env, cwd=REPO)
        assert r.returncode != 0
        assert r.returncode != 124, "job hung until --timeout"
        assert "boom on rank 1" in r.stderr.decode()
