"""Device collective tests: coll/tpu (XLA mesh) on the 8-device
virtual CPU mesh, coll/hbm (co-located ranks, one chip), and the
host-staged fallback.  This is the north-star path (BASELINE.json):
MPI collectives on device-resident buffers lowered to
psum/psum_scatter/all_gather/all_to_all/ppermute.
"""

import numpy as np
import pytest

from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _put(comm, a):
    return jax.device_put(a, comm.device)


# ---------------------------------------------------------------------------
# coll/tpu: one rank per device
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [2, 4, 8])
def test_tpu_allreduce_sum(n):
    def fn(comm):
        assert comm.coll.providers["allreduce_arr"] == "tpu"
        x = _put(comm, jnp.arange(32, dtype=jnp.float32) + comm.rank)
        r = comm.allreduce_arr(x, mpi_op.SUM)
        return np.asarray(r)

    res = run_ranks(n, fn, devices=True)
    exp = sum(np.arange(32, dtype=np.float32) + k for k in range(n))
    for r in res:
        np.testing.assert_allclose(r, exp)


@pytest.mark.parametrize("opname", ["MAX", "MIN", "PROD", "BXOR"])
def test_tpu_allreduce_ops(opname):
    n = 4
    op = getattr(mpi_op, opname)
    dtype = jnp.int32 if not op.float_ok else jnp.float32

    def fn(comm):
        x = _put(comm, jnp.array([comm.rank + 1, 7 - comm.rank],
                                 dtype=dtype))
        return np.asarray(comm.allreduce_arr(x, op))

    res = run_ranks(n, fn, devices=True)
    vals = [np.array([k + 1, 7 - k]) for k in range(n)]
    npop = {"MAX": np.maximum, "MIN": np.minimum,
            "PROD": np.multiply, "BXOR": np.bitwise_xor}[opname]
    exp = vals[0]
    for v in vals[1:]:
        exp = npop(exp, v)
    for r in res:
        np.testing.assert_array_equal(r, exp)


def test_tpu_bcast():
    def fn(comm):
        val = comm.rank * 100.0 if comm.rank == 3 else 0.0
        x = _put(comm, jnp.full((8,), val, dtype=jnp.float32))
        return float(np.asarray(comm.bcast_arr(x, root=3))[0])

    res = run_ranks(8, fn, devices=True)
    assert res == [300.0] * 8


def test_tpu_reduce_scatter():
    n = 4

    def fn(comm):
        x = _put(comm, jnp.arange(n * 3, dtype=jnp.float32) * (comm.rank + 1))
        return np.asarray(comm.reduce_scatter_arr(x, mpi_op.SUM))

    res = run_ranks(n, fn, devices=True)
    total = np.arange(n * 3, dtype=np.float32) * sum(range(1, n + 1))
    for k, r in enumerate(res):
        np.testing.assert_allclose(r, total[3 * k:3 * (k + 1)])


def test_tpu_allgather_alltoall():
    n = 8

    def fn(comm):
        ag = comm.allgather_arr(_put(comm, jnp.array([comm.rank * 2],
                                                     jnp.int32)))
        a2a = comm.alltoall_arr(_put(
            comm, jnp.arange(n, dtype=jnp.int32) + comm.rank * 10))
        return np.asarray(ag).tolist(), np.asarray(a2a).tolist()

    res = run_ranks(n, fn, devices=True)
    for k, (ag, a2a) in enumerate(res):
        assert ag == [2 * i for i in range(n)]
        assert a2a == [i * 10 + k for i in range(n)]


def test_tpu_ppermute_ring():
    """The ring-attention primitive: shift along the mesh axis."""
    n = 8

    def fn(comm):
        x = _put(comm, jnp.array([comm.rank], jnp.int32))
        fwd = comm.ppermute_arr(
            x, [(i, (i + 1) % n) for i in range(n)])
        return int(np.asarray(fwd)[0])

    res = run_ranks(n, fn, devices=True)
    assert res == [(k - 1) % n for k in range(n)]


def test_tpu_subcomm_mesh():
    """Split comm maps onto a sub-mesh; collectives stay on-device."""
    def fn(comm):
        sub = comm.split(comm.rank % 2)
        assert sub.coll.providers["allreduce_arr"] == "tpu"
        x = _put(comm, jnp.array([float(comm.rank)], jnp.float32))
        r = sub.allreduce_arr(x, mpi_op.SUM)
        return float(np.asarray(r)[0])

    res = run_ranks(8, fn, devices=True)
    assert res == [12.0, 16.0] * 4  # 0+2+4+6, 1+3+5+7


def test_tpu_unsupported_op_falls_back():
    """MAXLOC (pair type) is not XLA-lowered; falls back through the
    host path and still returns correct results."""
    def fn(comm):
        x = _put(comm, jnp.full((4,), float(comm.rank), jnp.float32))
        # user op → host fallback
        fold = mpi_op.create(
            lambda a, b, _: np.copyto(b, np.maximum(a, b)), commute=True)
        r = comm.allreduce_arr(x, fold)
        return float(np.asarray(r)[0])

    res = run_ranks(4, fn, devices=True)
    assert res == [3.0] * 4


def test_tpu_bf16():
    """bf16 allreduce — the MXU-native dtype."""
    def fn(comm):
        x = _put(comm, jnp.full((16,), comm.rank + 1, dtype=jnp.bfloat16))
        r = comm.allreduce_arr(x, mpi_op.SUM)
        return float(np.asarray(r, dtype=np.float32)[0])

    res = run_ranks(4, fn, devices=True)
    assert res == [10.0] * 4


# ---------------------------------------------------------------------------
# coll/hbm: all ranks co-located on one device
# ---------------------------------------------------------------------------

def _one_dev(rank):
    return jax.devices()[0]


def test_hbm_selected_and_allreduce():
    def fn(comm):
        assert comm.coll.providers["allreduce_arr"] == "hbm"
        x = _put(comm, jnp.arange(8, dtype=jnp.float32) * (comm.rank + 1))
        r = comm.allreduce_arr(x, mpi_op.SUM)
        return np.asarray(r)

    res = run_ranks(4, fn, device_map=_one_dev)
    exp = np.arange(8, dtype=np.float32) * 10
    for r in res:
        np.testing.assert_allclose(r, exp)


def test_hbm_alltoall_allgather_bcast():
    n = 4

    def fn(comm):
        a2a = comm.alltoall_arr(_put(
            comm, jnp.arange(n, dtype=jnp.int32) + comm.rank * 10))
        ag = comm.allgather_arr(_put(comm, jnp.array([comm.rank],
                                                     jnp.int32)))
        b = comm.bcast_arr(_put(comm, jnp.array(
            [comm.rank * 5.0], jnp.float32)), root=2)
        rs = comm.reduce_scatter_arr(_put(
            comm, jnp.ones(n * 2, jnp.float32)), mpi_op.SUM)
        return (np.asarray(a2a).tolist(), np.asarray(ag).tolist(),
                float(np.asarray(b)[0]), np.asarray(rs).tolist())

    res = run_ranks(n, fn, device_map=_one_dev)
    for k, (a2a, ag, b, rs) in enumerate(res):
        assert a2a == [i * 10 + k for i in range(n)]
        assert ag == list(range(n))
        assert b == 10.0
        assert rs == [float(n)] * 2


def test_hbm_ppermute():
    n = 4

    def fn(comm):
        x = _put(comm, jnp.array([comm.rank], jnp.int32))
        y = comm.ppermute_arr(x, [(i, (i + 1) % n) for i in range(n)])
        return int(np.asarray(y)[0])

    res = run_ranks(n, fn, device_map=_one_dev)
    assert res == [(k - 1) % n for k in range(n)]


# ---------------------------------------------------------------------------
# host-staged fallback (no devices assigned)
# ---------------------------------------------------------------------------

def test_arr_host_fallback():
    def fn(comm):
        assert comm.coll.providers["allreduce_arr"] == "arr_host"
        x = jnp.full((4,), float(comm.rank + 1))
        r = comm.allreduce_arr(x, mpi_op.SUM)
        return float(np.asarray(r)[0])

    res = run_ranks(3, fn)  # no devices => host staging
    assert res == [6.0] * 3


def test_tpu_numpy_input_falls_back():
    """numpy buffers through the _arr surface still work."""
    def fn(comm):
        x = np.full(4, comm.rank + 1.0)
        r = comm.allreduce_arr(x, mpi_op.SUM)
        return float(np.asarray(r)[0])

    res = run_ranks(4, fn, devices=True)
    assert res == [10.0] * 4


# ---------------------------------------------------------------------------
# review-finding regressions
# ---------------------------------------------------------------------------

def test_tpu_scalar_allreduce():
    """0-d arrays (a loss value) must work on the device path."""
    def fn(comm):
        x = jax.device_put(jnp.float32(comm.rank + 1.0), comm.device)
        r = comm.allreduce_arr(x, mpi_op.SUM)
        assert np.asarray(r).shape == ()
        return float(r)

    res = run_ranks(4, fn, devices=True)
    assert res == [10.0] * 4


def test_hbm_alltoall_2d():
    """Multi-dimensional alltoall through the stacked path."""
    n = 4

    def fn(comm):
        x = _put(comm, jnp.arange(n * 3, dtype=jnp.float32).reshape(n, 3)
                 + comm.rank * 100)
        r = comm.alltoall_arr(x)
        return np.asarray(r)

    res = run_ranks(n, fn, device_map=_one_dev)
    for k, r in enumerate(res):
        assert r.shape == (n, 3)
        for src in range(n):
            np.testing.assert_allclose(
                r[src], np.arange(n * 3, dtype=np.float32).reshape(n, 3)[k]
                + src * 100)


def test_arr_shapes_consistent_across_providers():
    """allgather/alltoall/reduce_scatter must return identical shapes
    whether served by tpu, hbm, or the host fallback."""
    def fn(comm):
        x = _put(comm, jnp.ones((comm.size * 2, 3), jnp.float32))
        ag = comm.allgather_arr(x)
        a2a = comm.alltoall_arr(x)
        rs = comm.reduce_scatter_arr(x, mpi_op.SUM)
        return (comm.coll.providers["allgather_arr"],
                np.asarray(ag).shape, np.asarray(a2a).shape,
                np.asarray(rs).shape)

    n = 4
    tpu_res = run_ranks(n, fn, devices=True)
    hbm_res = run_ranks(n, fn, device_map=_one_dev)
    host_res = run_ranks(n, fn)
    shapes = {r[1:] for r in tpu_res + hbm_res + host_res}
    assert len(shapes) == 1, shapes
    assert {r[0] for r in tpu_res} == {"tpu"}
    assert {r[0] for r in host_res} == {"arr_host"}


def test_mixed_residency_no_deadlock():
    """One rank passes numpy, the rest jax arrays — eligibility must
    not diverge (the device path moves stray buffers)."""
    def fn(comm):
        if comm.rank == 0:
            x = np.full(8, 1.0, dtype=np.float32)  # forgot device_put
        else:
            x = _put(comm, jnp.full((8,), 1.0, jnp.float32))
        r = comm.allreduce_arr(x, mpi_op.SUM)
        return float(np.asarray(r)[0])

    res = run_ranks(4, fn, devices=True, timeout=60)
    assert res == [4.0] * 4


def test_hbm_peer_abort_unblocks_rendezvous():
    """A rank dying before the rendezvous must not hang the others."""
    def fn(comm):
        if comm.rank == 1:
            raise ValueError("dead rank")
        x = _put(comm, jnp.ones((4,), jnp.float32))
        comm.allreduce_arr(x, mpi_op.SUM)
        return True

    with pytest.raises(Exception, match="dead rank|aborted"):
        run_ranks(3, fn, device_map=_one_dev, timeout=30)


def test_comm_free_drops_rendezvous():
    def fn(comm):
        sub = comm.dup()
        x = _put(comm, jnp.ones((4,), jnp.float32))
        sub.allreduce_arr(x, mpi_op.SUM)
        key = ("coll_rv", sub.cid, tuple(sub.group))
        world = comm.state.rte.world
        comm.Barrier()
        had = key in world.shared
        comm.Barrier()
        sub.Free()
        comm.Barrier()
        return had, key in world.shared

    res = run_ranks(2, fn, devices=True)
    assert res[0][0] is True and res[0][1] is False


def test_ring_attention_example_exact():
    """The long-context flagship: ring attention via ppermute_arr is
    EXACT full attention over the comm-wide sequence (online-softmax
    accumulation while KV blocks rotate the mesh ring)."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "ring_attention_example",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples",
            "ring_attention.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod.main()


# ---------------------------------------------------------------------------
# bounded compiled-collective cache (CompiledLRU)
# ---------------------------------------------------------------------------

def test_compile_cache_hit_skips_recompilation():
    """Repeating a (kind, mesh, shape, dtype, op) must reuse the
    compiled executable — asserted via the build trace counter, never
    timing."""
    from ompi_tpu.coll.device import compile_cache
    from ompi_tpu.mca.params import registry

    pv_hits = registry.register_pvar("coll", "device", "cache_hits")

    def fn(comm):
        x = _put(comm, jnp.arange(128, dtype=jnp.float32) + comm.rank)
        return np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).sum()

    run_ranks(4, fn, devices=True)  # warm: compiles at most once
    builds0, hits0 = compile_cache.builds, pv_hits.read()
    run_ranks(4, fn, devices=True)  # identical world + shape: all hits
    assert compile_cache.builds == builds0
    assert pv_hits.read() > hits0


def test_compile_cache_lru_bound_under_shape_churn():
    """coll_device_cache_max is enforced: a churn of distinct shapes
    evicts LRU entries instead of growing without bound, and the
    eviction pvar moves."""
    from ompi_tpu.coll.device import compile_cache
    from ompi_tpu.mca.params import registry

    pv_evict = registry.register_pvar("coll", "device",
                                      "cache_evictions")
    old = registry.get("coll_device_cache_max")
    registry.set("coll_device_cache_max", 4)
    try:
        def fn(comm):
            tot = 0.0
            for n in range(1, 11):  # 10 distinct shapes
                x = _put(comm, jnp.ones((8 * n,), jnp.float32))
                tot += float(np.asarray(
                    comm.allreduce_arr(x, mpi_op.SUM))[0])
            return tot

        e0 = pv_evict.read()
        res = run_ranks(2, fn, devices=True)
        assert res == [20.0, 20.0]
        assert len(compile_cache) <= 4
        assert pv_evict.read() > e0
    finally:
        registry.set("coll_device_cache_max", old)


def test_compile_cache_fusion_signature_keys():
    """Fused batches key the cache on their full fusion signature:
    two different batch compositions are distinct fused entries (plus
    the per-rank pack helpers), and replaying the same compositions
    compiles nothing new."""
    from ompi_tpu.coll.device import compile_cache

    def fn(comm):
        q1 = comm.iallreduce_arr(jnp.arange(4, dtype=jnp.int32),
                                 mpi_op.SUM)
        comm.flush_arr()
        q2 = comm.iallreduce_arr(jnp.arange(4, dtype=jnp.int32),
                                 mpi_op.SUM)
        q3 = comm.ibcast_arr(jnp.ones((2,), jnp.float32), 0)
        comm.flush_arr()
        return q1.complete and q2.complete and q3.complete

    def fused_keys():
        return {k for k in compile_cache._d if k[0] == "fused"}

    k0 = fused_keys()
    assert all(run_ranks(2, fn, devices=True))
    assert len(fused_keys() - k0) == 2  # one fused exe per signature
    b1 = compile_cache.builds
    assert all(run_ranks(2, fn, devices=True))
    assert compile_cache.builds == b1  # warm replay: all cache hits
