"""Tail-segment audit exerciser for the chunked segment collectives:
element counts straddling the piece size (count % piece in
{0, 1, piece-1}) across odd dtypes must stream through the segment
correctly — the ragged remainder takes the every-rank-folds round,
the P-divisible head must still split as reduce_scatter+allgather.

argv[1]: 0 (exact multiple), 1 (one extra element), -1 (piece-1
extra).  Run with a small coll_seg_slot_bytes so several pieces fit
in seconds.
"""
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
P, me = comm.size, comm.rank
assert comm.coll.providers.get("allreduce") == "seg", \
    comm.coll.providers

slot = registry.get("coll_seg_slot_bytes")
rem_arg = int(sys.argv[1])

for dt in (np.int8, np.float16, np.float32, np.float64):
    itemsize = np.dtype(dt).itemsize
    per = (slot // itemsize) // P * P
    rem = {0: 0, 1: 1, -1: per - 1}[rem_arg]
    n = per * 2 + rem  # two full pieces + the tail under test
    # exact-representable values at every dtype (fp16 sums stay tiny,
    # int8 sums stay far from wraparound for P <= 8)
    base = (np.arange(n) % 5).astype(dt)
    x = base + np.dtype(dt).type(me % 2)
    r = np.empty_like(x)
    comm.Allreduce(x, r, mpi_op.SUM)
    expect = base.astype(np.int64) * P + sum(r_ % 2 for r_ in range(P))
    assert (r.astype(np.int64) == expect).all(), \
        (dt, n, np.nonzero(r.astype(np.int64) != expect)[0][:5])

    # MAX exercises the non-SUM fold on the same tail geometry
    xm = base + np.dtype(dt).type(me)
    rm = np.empty_like(xm)
    comm.Allreduce(xm, rm, mpi_op.MAX)
    expect_m = base.astype(np.int64) + (P - 1)
    assert (rm.astype(np.int64) == expect_m).all(), (dt, n)

    # chunked bcast has its own piece size (no P rounding): same
    # count offsets against it
    perb = slot // itemsize
    nb = perb * 2 + {0: 0, 1: 1, -1: perb - 1}[rem_arg]
    bb = (np.arange(nb) % 7).astype(dt) if me == 0 \
        else np.zeros(nb, dt)
    comm.Bcast(bb, root=0)
    assert (bb.astype(np.int64) == np.arange(nb) % 7).all(), (dt, nb)

comm.Barrier()
if me == 0:
    print("collseg tails ok", flush=True)
ompi_tpu.finalize()
