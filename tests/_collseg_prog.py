"""coll/seg exerciser under mpirun: every segment collective, both
the native C path and the Python protocol fallback, must agree with
reference results (ref: the coll/sm test pattern — same-node process
ranks meeting in a shared segment)."""
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.coll.buffers import IN_PLACE
from ompi_tpu.datatype import engine as dt
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
P, me = comm.size, comm.rank

# the segment must actually be selected for these comms
assert comm.coll.providers.get("allreduce") == "seg", \
    comm.coll.providers

force_python = "--python-path" in sys.argv
if force_python:
    # disable the native fast path: the Python protocol must produce
    # identical results (and interoperate with the same segment)
    import ompi_tpu.coll.seg as segmod
    segmod.SegCollModule._native_run = \
        lambda self, *a, **k: False

# allreduce SUM f32
x = np.full(8, me + 1.0, np.float32)
r = np.empty_like(x)
comm.Allreduce(x, r, mpi_op.SUM)
assert (r == sum(range(1, P + 1))).all(), r

# allreduce MAX i64
xi = np.arange(4, dtype=np.int64) + me
ri = np.empty_like(xi)
comm.Allreduce(xi, ri, mpi_op.MAX)
assert (ri == np.arange(4) + P - 1).all(), ri

# allreduce BAND u32 (int-only op)
xb = np.full(4, 0xFF ^ (1 << me), np.uint32)
rb = np.empty_like(xb)
comm.Allreduce(xb, rb, mpi_op.BAND)
expect = 0xFF
for p in range(P):
    expect &= 0xFF ^ (1 << p)
assert (rb == expect).all(), rb

# IN_PLACE allreduce
buf = np.full(4, float(me), np.float64)
comm.Allreduce(IN_PLACE, buf, mpi_op.SUM)
assert (buf == sum(range(P))).all(), buf

# bcast
broot = min(2, P - 1)
b = np.arange(16.0, dtype=np.float64) if me == broot else np.zeros(16)
comm.Bcast(b, root=broot)
assert (b == np.arange(16.0)).all(), b

# reduce to a non-zero root
rr = np.empty(8, np.float32) if me == 1 else np.empty(8, np.float32)
rroot = 1 % P
comm.Reduce(x, rr, mpi_op.SUM, root=rroot)
if me == rroot:
    assert (rr == sum(range(1, P + 1))).all(), rr

# allgather
g = np.empty(P * 2, np.float32)
comm.Allgather(np.full(2, float(me), np.float32), g)
assert (g.reshape(P, 2) == np.arange(P)[:, None]).all(), g

# alltoall
sa = np.arange(P * 2, dtype=np.float32) + 100 * me
ra = np.empty_like(sa)
comm.Alltoall(sa, ra)
for p in range(P):
    assert (ra[p * 2:(p + 1) * 2] ==
            np.arange(me * 2, me * 2 + 2) + 100 * p).all(), ra

# reduce_scatter_block
srs = np.arange(P * 3, dtype=np.float64) + me
rrs = np.empty(3, np.float64)
comm.Reduce_scatter_block(srs, rrs, mpi_op.SUM)
base = np.arange(me * 3, me * 3 + 3) * P + sum(range(P))
assert (rrs == base).all(), (rrs, base)

# barrier ordering smoke: many barriers back-to-back (generation +
# bank reuse churn)
for _ in range(50):
    comm.Barrier()

# a payload bigger than the slot on a collective WITHOUT a chunked
# path (alltoall) must fall back to the p2p stack and still be right
from ompi_tpu.mca.params import registry as _reg0
slot_b = _reg0.get("coll_seg_slot_bytes") or (8 << 20)
n_over = ((slot_b // 4) + P) // P * P  # per-rank rows exceed the slot
sa2 = (np.arange(n_over, dtype=np.float32) + 1000.0 * me)
ra2 = np.empty_like(sa2)
comm.Alltoall(sa2, ra2)
blk = n_over // P
for p in range(P):
    expect = np.arange(me * blk, (me + 1) * blk,
                       dtype=np.float32) + 1000.0 * p
    assert (ra2[p * blk:(p + 1) * blk] == expect).all(), p
# oversize allreduce takes the chunked segment path (checked below)

comm.Barrier()
if me == 0:
    print("collseg ok", flush=True)

# chunked large payloads: allreduce + bcast > slot stream through the
# segment in pieces
from ompi_tpu.mca.params import registry as _reg
big_n = (_reg.get("coll_seg_slot_bytes") or (8 << 20)) // 4 * 3
bigx = np.arange(big_n, dtype=np.float32) * 0 + (me + 1)
bigr = np.empty_like(bigx)
comm.Allreduce(bigx, bigr, mpi_op.SUM)
assert (bigr == sum(range(1, P + 1))).all()
bb = np.arange(big_n, dtype=np.float32) if me == 0 \
    else np.full(big_n, -1.0, np.float32)
comm.Bcast(bb, root=0)
assert (bb == np.arange(big_n, dtype=np.float32)).all()

comm.Barrier()
if me == 0:
    print("collseg chunked ok", flush=True)
ompi_tpu.finalize()
