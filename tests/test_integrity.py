"""Silent-data-corruption plane tests (DESIGN.md §25): a chip that
computes wrong answers while passing every heartbeat must be caught by
the sampled algebraic cross-check on the rendezvous path, attributed
to the corrupting rank by the bisection round, convicted into the §24
health plane (immediate quarantine — never a failed job), and the
poisoned op retried from pristine sources byte-identically.  The
chaos matrix composes device_sdc with host_slow and rank_kill on a
2-host pool; satellites cover the wire payload digest above CRC and
the buddy-tier CRC restore fallback."""

import os
import time
import types

import numpy as np
import pytest

from ompi_tpu.mca.params import registry

jax = pytest.importorskip("jax")

# knob registration happens at import: an unregistered knob reads back
# None from the registry, which _restore would then "restore" as a
# None override and crash the coercion
import ompi_tpu.ft_inject  # noqa: E402,F401
import ompi_tpu.cr.buddy  # noqa: E402,F401
import ompi_tpu.cr.ckpt  # noqa: E402,F401
from ompi_tpu.obs import integrity as ig  # noqa: E402
from ompi_tpu.obs.health import (HEALTHY, QUARANTINED,  # noqa: E402
                                 HealthPlane)
from ompi_tpu.op import op as mpi_op  # noqa: E402
from ompi_tpu.testing import run_ranks  # noqa: E402
from ompi_tpu.tools.dvm import DVMServer, DvmClient  # noqa: E402

HERE = os.path.dirname(__file__)
SDC_PROG = os.path.join(HERE, "_sdc_prog.py")
HOST_PROG = os.path.join(HERE, "_fleet_host_prog.py")


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)
    ig.refresh()


def _pv(name):
    return registry._pvars[name].read()


def _lines(stdout, kind, tag):
    out = []
    for line in stdout.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[0] == kind and parts[1] == tag:
            out.append(parts[2:])
    return out


ARM = {
    "integrity_enable": 1,
    "integrity_sample": 1,
    "integrity_sample_auto": 0,
}

INJECT = dict(ARM, **{
    "ft_inject_plan": "device_sdc:1",
    "ft_inject_victim_rank": "1",
    "ft_inject_sdc_period": 1,
})


# -- tentpole: digest algebra ------------------------------------------------


def test_digest_modular_int_exactness():
    """Int SUM digests are exact mod 2^width: a uint8 reduction that
    overflows on-device still matches the python-int fold of per-rank
    claims under the width mask — overflow is never a false
    positive."""
    a = np.array([200], np.uint8)
    b = np.array([100], np.uint8)
    da = ig.digest(a, ig.F_INTSUM)
    db = ig.digest(b, ig.F_INTSUM)
    # device result wraps: 300 mod 256 = 44
    out = np.array([44], np.uint8)
    dout = ig.digest(out, ig.F_INTSUM)
    assert ig._eq(ig.F_INTSUM, da + db, dout, 1, 0.0)
    # and a genuinely wrong result is NOT masked by the wrap
    bad = np.array([45], np.uint8)
    assert not ig._eq(ig.F_INTSUM, da + db,
                      ig.digest(bad, ig.F_INTSUM), 1, 0.0)


def test_digest_float_tolerance_band():
    """Float SUM digests compare within the relative band (device
    reassociation rounds differently from the float64 host fold);
    MAX/MIN are exact; non-finite digests fail open (NaN poisoning is
    a model problem, not chip corruption)."""
    assert ig._eq(ig.F_FSUM, 1.0, 1.0 + 5e-5, 4, 1e-4)
    assert not ig._eq(ig.F_FSUM, 1.0, 1.001, 4, 1e-4)
    assert ig._eq(ig.F_MAX, 9.0, 9.0, 4, 1e-4)
    assert not ig._eq(ig.F_MAX, 9.0, 9.0 + 1e-9, 4, 1e-4)
    assert ig._eq(ig.F_FSUM, float("nan"), 1.0, 4, 1e-4)
    assert ig._eq(ig.F_FSUM, float("inf"), 1.0, 4, 1e-4)


def test_digest_kinds_and_empty():
    x = np.array([3, 9, 2], np.int32)
    assert ig.digest(x, ig.F_MAX) == 9
    assert ig.digest(x, ig.F_MIN) == 2
    assert ig.digest(np.empty(0, np.float32), ig.F_FSUM) == 0.0
    assert ig.digest(np.empty(0, np.int32), ig.F_INTSUM) == 0
    # int digests view bytes as unsigned — negative ints digest too
    assert ig.digest(np.array([-1], np.int32), ig.F_INTSUM) \
        == 0xFFFFFFFF


def test_spec_gating():
    """spec() is None unarmed; armed, it classifies exactly the
    algebraically-checkable (kind, op, dtype) set — bool and exotic
    reduce ops are excluded rather than false-positived."""
    saved = _set(ARM)
    try:
        ig.set_armed(False)
        assert ig.spec("allreduce", "MPI_SUM",
                       np.zeros(2, np.float32)) is None
        ig.set_armed(True)
        f = np.zeros(2, np.float32)
        i = np.zeros(2, np.int32)
        assert ig.spec("allreduce", "MPI_SUM", f) \
            == ("allreduce", ig.F_FSUM, 4)
        assert ig.spec("allreduce", "MPI_MAX", i) \
            == ("allreduce", ig.F_MAX, 4)
        assert ig.spec("redscat", "MPI_MIN", i) \
            == ("redscat", ig.F_MIN, 4)
        assert ig.spec("allreduce", "MPI_PROD", f) is None
        assert ig.spec("allreduce", "MPI_SUM",
                       np.zeros(2, np.bool_)) is None
        assert ig.spec("gather", "", i) == ("gather", ig.F_INTSUM, 4)
        assert ig.spec("alltoall", "", f) \
            == ("alltoall", ig.F_FSUM, 4)
        assert ig.spec("bcast", "", f, root=2) \
            == ("bcast", ig.F_FSUM, 4, 2)
    finally:
        _restore(saved)


def test_sampler_adaptive_and_deterministic():
    """The per-comm countdown starts dense (period 1) and doubles
    toward the cap as clean checks bank — and two comms walking the
    same op sequence make identical decisions (the comm-consistency
    invariant the last-arriver execution model requires)."""
    saved_cap, saved_auto = ig._cap, ig._auto
    ig._cap, ig._auto = 8, 2
    try:
        c1, c2 = types.SimpleNamespace(), types.SimpleNamespace()
        s1 = [ig.sample(c1) for _ in range(100)]
        s2 = [ig.sample(c2) for _ in range(100)]
        assert s1 == s2
        assert s1[0] == 1  # fresh world: checked immediately
        # the period ramps: early ops sample denser than late ops
        assert sum(s1[:20]) > sum(s1[-20:])
        assert c1.__dict__["_ig_state"][1] == 8  # ramped to the cap
        # steady state at the cap: exactly 1-in-8 from here on
        tail = [ig.sample(c1) for _ in range(80)]
        assert sum(tail) == 10
    finally:
        ig._cap, ig._auto = saved_cap, saved_auto


# -- tentpole: detect / attribute / survive on the device path ---------------


def _conviction_ranks():
    return sorted({r["rank"] for r in ig.convicted_snapshot()})


def test_mesh_detect_convict_retry_across_op_kinds():
    """device_sdc flips rank 1's operand on every mesh collective;
    with 1-in-1 sampling every flip is detected at the rendezvous,
    bisection convicts exactly rank 1, and the retry-from-source makes
    every result analytically exact — never a failed job, never a
    wrong answer."""
    saved = _set(INJECT)
    ig.refresh()
    ig.reset()
    base_m = _pv("integrity_mismatches")
    base_c = _pv("integrity_convictions")
    base_r = _pv("integrity_retry_ops")

    def fn(comm):
        import jax.numpy as jnp
        rank, size = comm.rank, comm.size
        outs = []
        s = comm.allreduce_arr(
            jnp.full((32,), float(rank + 1), jnp.float32), mpi_op.SUM)
        outs.append(np.array_equal(
            np.asarray(s), np.full(32, 10.0, np.float32)))
        m = comm.allreduce_arr(
            jnp.full((8,), (rank + 1) * 100, jnp.int32), mpi_op.MAX)
        outs.append(np.array_equal(
            np.asarray(m), np.full(8, 400, np.int32)))
        # victim as root: the flip propagates unless caught
        b = comm.bcast_arr(
            jnp.full((16,), float(rank * 10 + 7), jnp.float32), root=1)
        outs.append(np.array_equal(
            np.asarray(b), np.full(16, 17.0, np.float32)))
        rs = comm.reduce_scatter_arr(
            jnp.full((size * 4,), float(rank + 1), jnp.float32),
            mpi_op.SUM)
        outs.append(np.array_equal(
            np.asarray(rs), np.full(4, 10.0, np.float32)))
        ag = comm.allgather_arr(jnp.full((2,), rank + 1, jnp.int32))
        outs.append(np.array_equal(
            np.asarray(ag).ravel(),
            np.repeat(np.arange(1, size + 1, dtype=np.int32), 2)))
        at = comm.alltoall_arr(jnp.full((size,), rank + 1, jnp.int32))
        outs.append(np.array_equal(
            np.asarray(at).ravel(),
            np.arange(1, size + 1, dtype=np.int32)))
        return all(outs)
    try:
        assert all(run_ranks(4, fn, devices=True))
        assert _conviction_ranks() == [1]
        assert _pv("integrity_mismatches") > base_m
        assert _pv("integrity_convictions") > base_c
        assert _pv("integrity_retry_ops") > base_r
    finally:
        ig.reset()
        _restore(saved)


def test_hbm_detect_convict_retry():
    """Same contract on the co-located (hbm) dispatcher: every rank on
    one chip, victim rank 1 flipping — detection, attribution to rank
    1, byte-exact retried results."""
    saved = _set(INJECT)
    ig.refresh()
    ig.reset()
    dev0 = jax.devices()[0]

    def fn(comm):
        import jax.numpy as jnp
        rank = comm.rank
        s = comm.allreduce_arr(
            jnp.full((16,), float(rank + 1), jnp.float32), mpi_op.SUM)
        b = comm.bcast_arr(
            jnp.full((8,), float(rank + 5), jnp.float32), root=1)
        return (np.array_equal(np.asarray(s),
                               np.full(16, 10.0, np.float32))
                and np.array_equal(np.asarray(b),
                                   np.full(8, 6.0, np.float32)))
    try:
        assert all(run_ranks(4, fn, device_map=lambda r: dev0))
        assert _conviction_ranks() == [1]
    finally:
        ig.reset()
        _restore(saved)


@pytest.mark.parametrize("mode", ["mesh", "hbm"])
def test_fused_batch_detect(mode):
    """The nonblocking fusion engine batches ops into ONE rendezvous;
    the fused check spec carries one entry per group/slot so a flip
    inside the batch is still detected and attributed to rank 1, and
    the whole batch retries from pristine sources."""
    saved = _set(INJECT)
    ig.refresh()
    ig.reset()
    dev0 = jax.devices()[0]

    def fn(comm):
        import jax.numpy as jnp
        rank, size = comm.rank, comm.size
        qs = [comm.iallreduce_arr(
                  jnp.full((16,), float(rank + 1), jnp.float32),
                  mpi_op.SUM),
              comm.iallreduce_arr(
                  jnp.full((4,), (rank + 1) * 10, jnp.int32),
                  mpi_op.MAX),
              comm.ibcast_arr(
                  jnp.full((8,), rank * 2 + 3, jnp.int32), 1 % size)]
        for q in qs:
            q.wait()
        return (np.array_equal(np.asarray(qs[0].result),
                               np.full(16, 10.0, np.float32))
                and np.array_equal(np.asarray(qs[1].result),
                                   np.full(4, 40, np.int32))
                and np.array_equal(np.asarray(qs[2].result),
                                   np.full(8, 5, np.int32)))
    try:
        if mode == "mesh":
            assert all(run_ranks(4, fn, devices=True))
        else:
            assert all(run_ranks(4, fn, device_map=lambda r: dev0))
        assert _conviction_ranks() == [1]
    finally:
        ig.reset()
        _restore(saved)


def test_clean_run_zero_false_positives():
    """Armed at 1-in-1 sampling with NO fault injected: a full op mix
    (float sums included — the reassociation-band case) must bank
    checks without a single mismatch."""
    saved = _set(ARM)
    ig.refresh()
    ig.reset()
    base_k = _pv("integrity_checks")
    base_m = _pv("integrity_mismatches")

    def fn(comm):
        import jax.numpy as jnp
        rank, size = comm.rank, comm.size
        comm.allreduce_arr(
            jnp.full((1024,), 0.1 * (rank + 1), jnp.float32),
            mpi_op.SUM)
        comm.allreduce_arr(
            jnp.full((16,), rank, jnp.int32), mpi_op.MIN)
        comm.bcast_arr(jnp.arange(32, dtype=jnp.float32), root=0)
        comm.allgather_arr(jnp.full((4,), rank + 1, jnp.float32))
        qs = [comm.iallreduce_arr(
                  jnp.full((8,), float(rank), jnp.float32),
                  mpi_op.SUM),
              comm.ibcast_arr(jnp.full((4,), 3, jnp.int32), 1 % size)]
        for q in qs:
            q.wait()
        return True
    try:
        assert all(run_ranks(4, fn, devices=True))
        assert _pv("integrity_checks") > base_k
        assert _pv("integrity_mismatches") == base_m
        assert ig.convicted_snapshot() == []
    finally:
        ig.reset()
        _restore(saved)


def test_bisect_convicts_executing_rank_on_compute_corruption():
    """When every deposited operand still matches its gate claim, the
    reduction itself was computed wrong — the executing chip (the
    last-arriver running this closure) is the culprit."""
    saved = _set(ARM)
    ig.refresh()
    ig.reset()
    comm = types.SimpleNamespace(rank=2, cid=0, _dev_seq=0,
                                 group=[0, 1, 2, 3])
    ck = ("allreduce", ig.F_INTSUM, 4)
    shards = []
    for r in range(4):
        a = np.full(4, r + 1, np.int32)
        shards.append(ig._Checked(a, a.copy(),
                                  ig.digest(a, ig.F_INTSUM), r))

    def bad_fn(parts):
        out = np.sum(np.stack([np.asarray(p) for p in parts]), axis=0,
                     dtype=np.int32)
        out[0] += 1  # the "chip" mis-computes the reduction
        return [out]
    base_r = _pv("integrity_retry_ops")
    try:
        ig._run_checked(comm, bad_fn, ck, shards)
        recs = ig.convicted_snapshot()
        assert len(recs) == 1
        assert recs[0]["rank"] == 2  # the executing rank, by fallback
        assert recs[0]["kind"] == "allreduce"
        assert _pv("integrity_retry_ops") == base_r + 1
    finally:
        ig.reset()
        _restore(saved)


def test_checker_defect_fails_open():
    """A defect inside the verifier must never take down the datapath
    (the plane's contract is 'never a failed job'): a ck whose claims
    blow up the comparison passes the op through untouched."""
    comm = types.SimpleNamespace(rank=0, cid=0, _dev_seq=0,
                                 group=[0, 1])
    a = np.full(4, 1, np.int32)
    shards = [ig._Checked(a, a.copy(), object(), 0),
              ig._Checked(a, a.copy(), object(), 1)]
    out = ig._run_checked(
        comm, lambda parts: [np.asarray(parts[0]) * 2],
        ("allreduce", ig.F_INTSUM, 4), shards)
    assert np.array_equal(out[0], a * 2)
    assert ig.convicted_snapshot() == []


# -- tentpole: the injector and flip shape -----------------------------------


def test_sdc_injector_deterministic():
    from ompi_tpu.ft_inject import SdcInjector, sdc_injector
    inj = SdcInjector(1, 3, 2)
    seq = [inj.should_flip() for _ in range(12)]
    # armed at op 3, then every 2nd op after
    assert seq == [False, False, True, False, True, False, True,
                   False, True, False, True, False]
    assert inj.flips == 5
    assert inj.last_flip_ns > 0
    one_shot = SdcInjector(1, 2, 0)
    assert [one_shot.should_flip() for _ in range(8)] \
        == [False, True] + [False] * 6
    assert sdc_injector(0, 4) is None  # plan empty: fully passive


def test_flip_targets_checked_carrier():
    """flip_value on a _Checked carrier retargets only the datapath
    binding: the pristine source and the gate claim survive — exactly
    the divergence _bisect attributes.  On an unwrapped value the flip
    mutates a COPY (device buffers are donated; the corruption must
    not write back into application arrays)."""
    a = np.full(9, 1.0, np.float32)
    c = ig._Checked(a, a.copy(), ig.digest(a, ig.F_FSUM), 0)
    ig.flip_value(c)
    assert not np.array_equal(np.asarray(c.v), a)  # datapath corrupted
    assert np.array_equal(c.src, a)                # source pristine
    assert ig._eq(ig.F_FSUM, c.d, ig.digest(c.src, ig.F_FSUM), 4, 0.0)
    raw = np.full(5, 7, np.int32)
    flipped = ig.flip_value(raw)
    assert not np.array_equal(flipped, raw)
    assert np.array_equal(raw, np.full(5, 7, np.int32))


# -- tentpole: conviction drives the health plane ----------------------------


def test_health_sdc_signal_is_decisive():
    """One conviction quarantines the host on the next tick — no
    hysteresis ladder, no hope of widening around a corrupting chip —
    and it works even on a host that never beat (the conviction proves
    the chip is alive; only dead/rehydrating hosts are excluded)."""
    hp = HealthPlane(2, 100 * 1_000_000, 50 * 1_000_000)
    assert hp.enabled
    hp.note_sdc(0)
    assert hp.sdc_n == 1
    hp.next_ns = 0
    hp.tick(time.monotonic_ns())
    assert hp.state[0] == QUARANTINED
    assert hp.score[0] == 100
    assert hp.state[1] == HEALTHY
    assert "sdc" in hp.tripped(0)
    assert "sdc" not in hp.tripped(1)
    rows = hp.snapshot()
    assert rows[0]["sdc"] == 1 and rows[1]["sdc"] == 0
    assert hp.collect() == [0]  # latched exactly once
    assert not hp.placement_ok(0)
    # excluded (dead) hosts stay the liveness plane's case
    hp.excluded[1] = 1
    hp.note_sdc(1)
    hp.next_ns = 0
    hp.tick(time.monotonic_ns())
    assert hp.state[1] == HEALTHY
    hp.excluded[1] = 0
    hp.reset_host(0)
    hp.reset_host(1)
    assert hp.sdc == [0, 0] and hp.sdc_n == 0
    assert hp.state[0] == HEALTHY


def test_doctor_sdc_verdict():
    from ompi_tpu.tools import doctor
    doc = {"sid": 1, "np": 4, "ns": 0,
           "sdc": [{"rank": 1, "host": 0, "cid": 0,
                    "kind": "allreduce"}]}
    text = "\n".join(doctor.verdict(doc))
    assert "SDC VERDICT" in text
    assert "CONVICTED: rank 1 on host 0" in text
    clean = "\n".join(doctor.verdict({"sid": 1, "np": 4, "ns": 0}))
    assert "SDC VERDICT" not in clean


def test_integrity_hot_functions_audited():
    """sample/fold are DECLARED hot (a refactor that starts allocating
    on the per-op countdown fails tier-1) and currently pass."""
    from ompi_tpu.tools import hotpath_audit
    assert "ompi_tpu/obs/integrity.py" in hotpath_audit.HOT_FUNCTIONS
    fns = hotpath_audit.HOT_FUNCTIONS["ompi_tpu/obs/integrity.py"]
    assert "sample" in fns and "fold" in fns
    assert hotpath_audit.audit() == []


# -- satellite: wire payload digest above CRC --------------------------------


def test_wire_payload_crc():
    """The payload digest covers exactly the bytes the header CRC does
    NOT: sender computes from (hdr, payload) before the gather, the
    receiver from the contiguous frame — identical digests; a flipped
    payload byte (which the header CRC can never see) fails it."""
    from ompi_tpu.btl import wire
    hdr, payload = wire.encode(("F", 11, 0, b"payload-bytes-here"))
    frame = hdr + payload
    crc = wire.payload_crc(hdr, payload)
    assert crc == wire.payload_crc(frame)
    wire.check_payload_crc(frame, crc)  # no raise
    bad = bytearray(frame)
    bad[len(hdr) + 4] ^= 0x10
    assert wire.frame_crc(bytes(bad)) == wire.frame_crc(frame)
    with pytest.raises(wire.CorruptFrame):
        wire.check_payload_crc(bytes(bad), crc)
    # pickle frames: the tail past the covered span is payload too
    phdr, ppay = wire.encode(("weird", list(range(100))))
    assert ppay is None
    wire.check_payload_crc(phdr, wire.payload_crc(phdr))


# -- satellite: buddy-tier CRC fallback on restore ---------------------------


def test_buddy_restore_crc_fallback_to_fs_epoch(tmp_path):
    """A corrupting host flips bits in parked buddy blobs too: restore
    CRC-verifies every replica, AGREES on the verdict (one corrupt
    rank sends the whole world down together — never a split across
    sequences), falls one ladder rung to the fs epoch, and re-seeds
    the buddy tier."""
    saved = _set({"cr_buddy_degree": 1,
                  "cr_fs_dir": str(tmp_path / "ckpt")})
    base_fb = _pv("cr_buddy_restore_crc_fallbacks")
    base_fs = _pv("cr_ckpt_restore_fs")

    def fn(comm):
        from ompi_tpu.cr import ckpt
        payload = {"arr": np.arange(64, dtype=np.float64) + comm.rank}
        bseq, epoch = ckpt.checkpoint(comm, payload, fs=True)
        assert bseq >= 0 and epoch >= 0
        comm.Barrier()
        if comm.rank == 1:  # flip a bit inside the parked blob
            bs = comm.state.extra["cr_buddy"]
            blob = bytearray(bs["self"][bseq])
            blob[len(blob) // 2] ^= 0x08
            bs["self"][bseq] = bytes(blob)
        out = ckpt.restore(comm)
        assert out is not None
        return bool(np.array_equal(
            out["arr"], np.arange(64, dtype=np.float64) + comm.rank))
    try:
        assert all(run_ranks(2, fn, devices=True))
        assert _pv("cr_buddy_restore_crc_fallbacks") == base_fb + 1
        assert _pv("cr_ckpt_restore_fs") == base_fs + 2
    finally:
        _restore(saved)


# -- satellite: chaos matrix — device_sdc x host_slow x rank_kill ------------


def test_chaos_matrix_sdc_host_slow_rank_kill(tmp_path):
    """The silent failure composed with the gray and the hard one on a
    2-host pool: run 1 arms device_sdc on rank 1 (host 0) while host 1
    crawls — every flip must be convicted against exactly that chip
    and every rank's analytic result stays exact; the pool's convict
    hook feeds the health plane, whose next tick quarantines host 0.
    Run 2 switches to host_slow + rank_kill: ULFM shrink completes
    byte-identically.  Zero failed jobs across the whole matrix."""
    saved = _set({
        "health_tick_ms": 600_000,  # ticks under test control only
        "integrity_enable": 1,
        "integrity_sample": 1,
        "integrity_sample_auto": 0,
        "ft_inject_plan": "device_sdc:3,host_slow",
        "ft_inject_skip": 0,
        "ft_inject_victim_rank": "1",
        "ft_inject_victim_host": 1,
        "ft_inject_sdc_period": 1,
        "ft_inject_after": 0.3,
        "ft_inject_delay_ms": 5,
    })
    ig.refresh()
    ig.reset()
    base_c = _pv("integrity_convictions")
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri,
                    hosts=2).start()
    # ticks stay under test control: next_ns starts at 0, so without
    # this the pool's FIRST heartbeat sweep would tick right after the
    # convictions land and quarantine host 0 mid-matrix (the designed
    # mitigation — but this test pins tick timing to assert the signal
    # itself, then drives the quarantine tick by hand)
    srv.health.next_ns = time.perf_counter_ns() + 3_600 * 1_000_000_000
    c = DvmClient(uri)
    try:
        sid = c.attach(4)["sid"]
        # run 1: every step self-verifies — detection + retry keep the
        # results exact even though rank 1 flips every op from op 3 on
        r = c.run(sid, SDC_PROG, ["cm", "6"], timeout=240)
        assert r["code"] == 0, r["stderr"][-2000:]
        rows = _lines(r["stdout"], "SDC", "cm")
        assert sorted(int(x[0]) for x in rows) == [0, 1, 2, 3], rows
        assert all(x[1] == "ok" for x in rows), rows
        # conviction pinned to the corrupting chip: rank 1, host 0
        recs = ig.convicted_snapshot()
        assert recs and {rec["rank"] for rec in recs} == {1}, recs
        assert {rec["host"] for rec in recs} == {0}, recs
        assert _pv("integrity_convictions") > base_c
        # the pool's hook fed the health plane; the next tick
        # quarantines host 0 outright
        hp = srv.health
        assert hp.sdc[0] > 0 and hp.sdc[1] == 0
        assert c.metrics()["sdc"], "metrics RPC must carry the rows"

        # run 2: the hard + gray composition on the same pool — a
        # FRESH session so the plan switch is seen at mpi_init.  The
        # kill is the prog's deterministic step-boundary kill_now
        # (rank 1 dies at step 5), not the timer-armed rank_kill
        # class: a wall-clock timer can land in the victim's init
        # window when the suite loads the box, and this test pins
        # WHICH faults compose, not WHEN they land (the timer race
        # is test_grayfail's chaos matrix)
        c.detach(sid)
        registry.set("ft_inject_plan", "host_slow")
        sid = c.attach(4)["sid"]
        r2 = c.run(sid, HOST_PROG, ["cm2", "30", "1:5"], timeout=240)
        assert r2["code"] == 0, r2["stderr"][-2000:]  # never a failed job
        shrinks = _lines(r2["stdout"], "SHRINKS", "cm2")
        digs = _lines(r2["stdout"], "DIGEST", "cm2")
        assert sorted(int(s[0]) for s in shrinks) == [0, 2, 3], shrinks
        assert all(int(s[1]) == 1 for s in shrinks), shrinks
        assert len(digs) == 3 and len({d[0] for d in digs}) == 1, digs

        hp.next_ns = 0
        hp.tick(time.monotonic_ns())
        assert hp.state[0] == QUARANTINED
        assert "sdc" in hp.tripped(0)
        assert srv._host_dead[0] == 0  # quarantined, never dead
        c.detach(sid)
    finally:
        c.sock.close()
        ig.reset()
        hp = srv.health
        if hp is not None:
            for h in range(hp.hosts):
                hp.reset_host(h)
            hp.collect()
        srv.stop()
        _restore(saved)
