"""One-sided RMA tests (osc): put/get/accumulate under fence,
passive-target lock/unlock atomic counters, PSCW neighbor exchange,
compare-and-swap, flush semantics (ref: ompi/mca/osc tests and
MPI-3 RMA examples)."""

import numpy as np
import pytest

from ompi_tpu import osc
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

SIZES = [2, 3, 4, 8]


@pytest.mark.parametrize("n", SIZES)
def test_put_fence(n):
    """Each rank puts its rank id into the right neighbor's window."""
    def fn(comm):
        mem = np.full(4, -1, dtype=np.int64)
        win = osc.create(comm, mem)
        win.fence()
        right = (comm.rank + 1) % comm.size
        win.put(np.full(4, comm.rank, dtype=np.int64), right)
        win.fence()
        out = mem.copy()
        win.free()
        return out

    res = run_ranks(n, fn)
    for k, r in enumerate(res):
        np.testing.assert_array_equal(r, np.full(4, (k - 1 + n) % n))


@pytest.mark.parametrize("n", SIZES)
def test_get_fence(n):
    def fn(comm):
        mem = np.arange(3, dtype=np.float64) * (comm.rank + 1)
        win = osc.create(comm, mem)
        win.fence()
        left = (comm.rank - 1 + comm.size) % comm.size
        out = np.empty(3, dtype=np.float64)
        win.get(out, left)
        win.fence()
        win.free()
        return out

    res = run_ranks(n, fn)
    for k, r in enumerate(res):
        left = (k - 1 + n) % n
        np.testing.assert_allclose(r, np.arange(3, dtype=np.float64)
                                   * (left + 1))


def test_put_disp_and_subrange(n=4):
    """Puts at different displacements land at the right offsets."""
    def fn(comm):
        mem = np.zeros(comm.size, dtype=np.int64)
        win = osc.create(comm, mem)  # disp_unit = 8 (itemsize)
        win.fence()
        for t in range(comm.size):
            win.put(np.array([comm.rank + 1], dtype=np.int64), t,
                    disp=comm.rank)
        win.fence()
        out = mem.copy()
        win.free()
        return out

    res = run_ranks(n, fn)
    exp = np.arange(1, n + 1, dtype=np.int64)
    for r in res:
        np.testing.assert_array_equal(r, exp)


@pytest.mark.parametrize("n", SIZES)
def test_accumulate_sum(n):
    """All ranks accumulate into rank 0 under one fence epoch —
    serial application in the AM handler makes this atomic."""
    def fn(comm):
        mem = np.zeros(5, dtype=np.int64)
        win = osc.create(comm, mem)
        win.fence()
        win.accumulate(np.arange(5, dtype=np.int64) + comm.rank, 0,
                       op=mpi_op.SUM)
        win.fence()
        out = mem.copy()
        win.free()
        return out

    res = run_ranks(n, fn)
    exp = sum(np.arange(5, dtype=np.int64) + k for k in range(n))
    np.testing.assert_array_equal(res[0], exp)


def test_lock_unlock_counter():
    """Passive-target atomic counter: every rank increments rank 0's
    counter under an exclusive lock; total must be exact."""
    n = 6
    incs = 10

    def fn(comm):
        mem = np.zeros(1, dtype=np.int64)
        win = osc.create(comm, mem)
        for _ in range(incs):
            win.lock(0, osc.LOCK_EXCLUSIVE)
            old = np.empty(1, dtype=np.int64)
            win.get(old, 0)
            win.put(old + 1, 0)
            win.unlock(0)
        # counter is complete only after everyone unlocked
        comm.Barrier()
        out = int(mem[0])
        win.free()
        return out

    res = run_ranks(n, fn)
    assert res[0] == n * incs


def test_fetch_and_op():
    """fetch_and_op is atomic without any user lock."""
    n = 5
    incs = 7

    def fn(comm):
        mem = np.zeros(1, dtype=np.int64)
        win = osc.create(comm, mem)
        olds = []
        for _ in range(incs):
            old = np.empty(1, dtype=np.int64)
            win.fetch_and_op(1, old, 0, op=mpi_op.SUM)
            olds.append(int(old[0]))
        comm.Barrier()
        out = int(mem[0])
        win.free()
        return out, olds

    res = run_ranks(n, fn)
    assert res[0][0] == n * incs
    # every fetched old value must be unique (atomicity proof)
    seen = [v for (_, olds) in res for v in olds]
    assert len(set(seen)) == n * incs


def test_compare_and_swap_election():
    """Only one rank wins CAS(-1 -> rank)."""
    n = 6

    def fn(comm):
        mem = np.full(1, -1, dtype=np.int64)
        win = osc.create(comm, mem)
        win.fence()
        old = np.empty(1, dtype=np.int64)
        win.compare_and_swap(-1, comm.rank, old, 0)
        win.fence()
        final = np.empty(1, dtype=np.int64)
        win.get(final, 0)
        win.fence()
        out = (int(old[0]), int(final[0]))
        win.free()
        return out

    res = run_ranks(n, fn)
    winners = [k for k, (old, _) in enumerate(res) if old == -1]
    assert len(winners) == 1
    assert all(final == winners[0] for (_, final) in res)


def test_get_accumulate():
    n = 4

    def fn(comm):
        mem = np.full(2, 100, dtype=np.int64)
        win = osc.create(comm, mem)
        win.fence()
        old = np.empty(2, dtype=np.int64)
        win.get_accumulate(np.full(2, 1, dtype=np.int64), old, 0,
                           op=mpi_op.SUM)
        win.fence()
        out = (old.copy(), mem.copy())
        win.free()
        return out

    res = run_ranks(n, fn)
    np.testing.assert_array_equal(res[0][1], np.full(2, 100 + n))
    olds = sorted(int(o[0]) for (o, _) in res)
    assert olds == [100 + k for k in range(n)]


def test_pscw():
    """Post/Start/Complete/Wait: even ranks expose, odd ranks write."""
    n = 4

    def fn(comm):
        mem = np.zeros(1, dtype=np.int64)
        win = osc.create(comm, mem)
        if comm.rank % 2 == 0:
            origin = comm.rank + 1
            if origin < comm.size:
                win.post([origin])
                win.wait()
            out = int(mem[0])
        else:
            target = comm.rank - 1
            win.start([target])
            win.put(np.array([comm.rank * 100], dtype=np.int64), target)
            win.complete()
            out = -1
        comm.Barrier()
        win.free()
        return out

    res = run_ranks(n, fn)
    assert res[0] == 100
    assert res[2] == 300


def test_flush_passive():
    """lock_all + put + flush makes the value visible mid-epoch."""
    n = 3

    def fn(comm):
        mem = np.zeros(1, dtype=np.int64)
        win = osc.create(comm, mem)
        if comm.rank == 1:
            win.lock(0, osc.LOCK_SHARED)
            win.put(np.array([42], dtype=np.int64), 0)
            win.flush(0)  # applied at target NOW
            got = np.empty(1, dtype=np.int64)
            win.get(got, 0)
            win.unlock(0)
            assert got[0] == 42
        comm.Barrier()
        out = int(mem[0])
        win.free()
        return out

    res = run_ranks(n, fn)
    assert res[0] == 42


def test_lock_shared_concurrent_readers():
    n = 5

    def fn(comm):
        mem = np.array([comm.rank * 3], dtype=np.int64)
        win = osc.create(comm, mem)
        comm.Barrier()
        vals = []
        for t in range(comm.size):
            win.lock(t, osc.LOCK_SHARED)
            v = np.empty(1, dtype=np.int64)
            win.get(v, t)
            win.unlock(t)
            vals.append(int(v[0]))
        win.free()
        return vals

    for vals in run_ranks(n, fn):
        assert vals == [k * 3 for k in range(n)]


def test_two_windows_independent():
    """Traffic on two windows over the same comm must not cross."""
    n = 3

    def fn(comm):
        m1 = np.zeros(2, dtype=np.int64)
        m2 = np.zeros(2, dtype=np.int64)
        w1 = osc.create(comm, m1)
        w2 = osc.create(comm, m2)
        w1.fence()
        w2.fence()
        right = (comm.rank + 1) % comm.size
        w1.put(np.full(2, 10 + comm.rank, dtype=np.int64), right)
        w2.put(np.full(2, 20 + comm.rank, dtype=np.int64), right)
        w1.fence()
        w2.fence()
        out = (m1.copy(), m2.copy())
        w1.free()
        w2.free()
        return out

    res = run_ranks(n, fn)
    for k, (a, b) in enumerate(res):
        left = (k - 1 + n) % n
        np.testing.assert_array_equal(a, np.full(2, 10 + left))
        np.testing.assert_array_equal(b, np.full(2, 20 + left))


def test_passive_then_fence_epoch():
    """fence counting must stay correct after a passive-target epoch
    (regression: unlock used to drop its ops from the fence counts)."""
    n = 3

    def fn(comm):
        mem = np.zeros(1, dtype=np.int64)
        win = osc.create(comm, mem)
        if comm.rank == 1:
            win.lock(0, osc.LOCK_EXCLUSIVE)
            win.put(np.array([7], dtype=np.int64), 0)
            win.unlock(0)
        comm.Barrier()
        win.fence()
        if comm.rank == 2:
            win.put(np.array([9], dtype=np.int64), 0)
        win.fence()
        out = int(mem[0])
        win.free()
        return out

    res = run_ranks(n, fn)
    assert res[0] == 9


def test_zero_count_put():
    """Zero-count RMA ops are legal no-ops and must not crash the
    target's progress loop."""
    n = 2

    def fn(comm):
        mem = np.full(2, 5, dtype=np.int64)
        win = osc.create(comm, mem)
        win.fence()
        win.put(np.empty(0, dtype=np.int64), (comm.rank + 1) % comm.size)
        win.fence()
        out = mem.copy()
        win.free()
        return out

    for r in run_ranks(n, fn):
        np.testing.assert_array_equal(r, np.full(2, 5))


def test_win_allocate_and_float():
    n = 2

    def fn(comm):
        win = osc.allocate(comm, 8 * 4)
        win.fence()
        if comm.rank == 0:
            win.put(np.linspace(0, 1, 4, dtype=np.float64), 1)
        win.fence()
        out = win.memory.view(np.float64).copy() if comm.rank == 1 else None
        win.free()
        return out

    res = run_ranks(n, fn)
    np.testing.assert_allclose(res[1], np.linspace(0, 1, 4))
