"""coll/pipeline: the segmented / pipelined / hierarchical
large-message device tier (DESIGN.md §12).

Byte-identity discipline: every segmented result is compared bytewise
against the fused single-dispatch path on the SAME world, using
exact-representable float values (small integers), so any reordering
bug — stripe bookkeeping, tail padding, pipeline depth — shows as a
hard byte diff, never a tolerance argument.  Fault and epoch tests
assert the same identity under ft_inject delay chaos and across ULFM
shrink + respawn epochs (segment state must not leak across epochs).
"""

import time

import numpy as np
import pytest

from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

# register the pipeline + plan knobs before any _set() snapshot, so
# saved values are real defaults (not the unregistered-knob None
# sentinel)
import ompi_tpu.coll.pipeline  # noqa: E402,F401
import ompi_tpu.coll.plan  # noqa: E402,F401


def _put(comm, a):
    return jax.device_put(a, comm.device)


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


# route everything >= 2 KiB through 4 KiB segments: several segments
# per op, tails included, in test-sized arrays.  The compiled-plan
# path is pinned OFF: this file is the per-segment pipelined tier's
# coverage (tests/test_coll_plan.py covers the plan path)
PIPE_ON = {"coll_pipeline_enable": True, "coll_pipeline_min_bytes": 2048,
           "coll_seg_size": 4096, "coll_pipeline_rd_max_bytes": 0,
           "coll_hier_enable": False, "coll_plan_enable": False}
PIPE_OFF = {"coll_pipeline_enable": False, "coll_hier_enable": False}


def _mixed_ops(comm):
    """The canonical segmented workload: allreduce/bcast/alltoall over
    sizes that leave tails (count % seg in {0, 1, seg-1} territory),
    exact-representable values.  Returns concatenated result bytes."""
    r = comm.rank
    P = comm.size
    out = []
    # 4099 floats = 16 KiB + tail; values exact at any fold order
    base = (jnp.arange(4099, dtype=jnp.float32) % 11).astype(jnp.float32)
    x = _put(comm, base + r)
    out.append(np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes())
    xi = _put(comm, (jnp.arange(3072, dtype=jnp.int32) % 17) * (r + 1))
    out.append(np.asarray(comm.allreduce_arr(xi, mpi_op.MAX)).tobytes())
    xb = _put(comm, jnp.full(2048 + 1, 0xFF ^ (1 << r), jnp.uint32))
    out.append(np.asarray(comm.allreduce_arr(xb, mpi_op.BAND)).tobytes())
    b = _put(comm, base * (r + 1))
    out.append(np.asarray(comm.bcast_arr(b, root=min(2, P - 1)))
               .tobytes())
    m = 1031 * P  # odd per-rank block size
    a = _put(comm, jnp.arange(m, dtype=jnp.int32) + 100000 * r)
    a2a = np.asarray(comm.alltoall_arr(a)).tobytes()
    # (rank-symmetric results, rank-specific alltoall rows)
    return b"".join(out), a2a


def _run_twice(fn, n=4, **kw):
    """fn under the segmented tier, then under the fused path."""
    saved = _set(PIPE_ON)
    try:
        seg = run_ranks(n, fn, **kw)
    finally:
        _restore(saved)
    saved = _set(PIPE_OFF)
    try:
        fused = run_ranks(n, fn, **kw)
    finally:
        _restore(saved)
    return seg, fused


# ---------------------------------------------------------------------------
# correctness: segmented vs fused, byte for byte (tier-1 fast gate)
# ---------------------------------------------------------------------------

def test_segmented_mesh_byte_identical():
    """The fast deterministic 4-rank gate: every segmented mesh
    algorithm returns the same bytes as the fused path, the tier
    actually engaged (pvars moved), and all ranks agree."""
    from ompi_tpu.coll import pipeline

    def fn(comm):
        ops0 = pipeline.pv_ops.read()
        segs0 = pipeline.pv_segments.read()
        common, a2a = _mixed_ops(comm)
        return common, a2a, pipeline.pv_ops.read() - ops0, \
            pipeline.pv_segments.read() - segs0

    seg, fused = _run_twice(fn, 4, devices=True)
    assert len({c for c, _, _, _ in seg}) == 1   # ranks byte-agree
    for (sc, sa, dops, dsegs), (fc, fa, fops, _) in zip(seg, fused):
        assert sc == fc and sa == fa             # tier is invisible
        assert dops >= 5                         # ...but engaged
        assert dsegs > dops                      # multiple segments/op
        assert fops == 0                         # fused run untouched


def test_segmented_mixed_dtypes():
    """Odd dtypes through the identity-padded tail: int8 (sum stays in
    range), float16, float64, int64 — bytewise equal to fused."""
    def fn(comm):
        r = comm.rank
        out = []
        x8 = _put(comm, (jnp.arange(4097) % 3).astype(jnp.int8)
                  + np.int8(r % 2))
        out.append(np.asarray(comm.allreduce_arr(x8, mpi_op.SUM))
                   .tobytes())
        h = _put(comm, ((jnp.arange(2050) % 8) + r).astype(jnp.float16))
        out.append(np.asarray(comm.allreduce_arr(h, mpi_op.MAX))
                   .tobytes())
        d = _put(comm, (jnp.arange(1025, dtype=jnp.float64) % 9) + r)
        out.append(np.asarray(comm.allreduce_arr(d, mpi_op.SUM))
                   .tobytes())
        i64 = _put(comm, (jnp.arange(1000, dtype=jnp.int64) % 13)
                   * (r + 1))
        out.append(np.asarray(comm.allreduce_arr(i64, mpi_op.PROD))
                   .tobytes())
        return b"".join(out)

    seg, fused = _run_twice(fn, 4, devices=True)
    assert seg == fused
    assert len(set(seg)) == 1


def test_segmented_hbm_byte_identical():
    """Co-located ranks (one shared device): the hbm segmentation path
    — per-segment stacked kernels — is bytewise the monolithic one."""
    def _one_dev(r):
        return jax.devices()[0]

    def fn(comm):
        r = comm.rank
        base = (jnp.arange(5003, dtype=jnp.float32) % 7)
        x = _put(comm, base + r)
        a = _put(comm, jnp.arange(1009 * comm.size, dtype=jnp.int32)
                 + 1000 * r)
        return (np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes(),
                np.asarray(comm.alltoall_arr(a)).tobytes())

    seg, fused = _run_twice(fn, 4, device_map=_one_dev)
    assert seg == fused
    # allreduce output is rank-symmetric; alltoall rows are per-rank
    assert len({ar for ar, _ in seg}) == 1


def test_recursive_doubling_window():
    """Power-of-two comm inside the rd window: segrd must be picked
    (not segring) and stay byte-identical across ranks and vs fused —
    the operand-order-swap discipline under test."""
    from ompi_tpu.coll import tuned

    def fn(comm):
        x = _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank)
        alg = tuned.device_algorithm(comm, "allreduce", int(x.nbytes))
        return np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes(), \
            alg

    saved = _set(dict(PIPE_ON, coll_pipeline_rd_max_bytes=1 << 30))
    try:
        seg = run_ranks(4, fn, devices=True)
    finally:
        _restore(saved)
    saved = _set(PIPE_OFF)
    try:
        fused = run_ranks(4, fn, devices=True)
    finally:
        _restore(saved)
    assert all(alg == "segrd" for _, alg in seg)
    assert len({b for b, _ in seg}) == 1
    assert [b for b, _ in seg] == [b for b, _ in fused]


def test_hierarchical_allreduce():
    """Forced 2x4 slices on 8 ranks: the hier tier engages (pvar) and
    the result is bitwise-consistent across every rank and equal to
    the fused reference."""
    from ompi_tpu.coll import pipeline

    def fn(comm):
        h0 = pipeline.pv_hier.read()
        base = (jnp.arange(3001, dtype=jnp.float32) % 9)
        x = _put(comm, base + comm.rank)
        out = np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()
        return out, pipeline.pv_hier.read() - h0

    saved = _set({"coll_pipeline_enable": True, "coll_hier_enable": True,
                  "coll_hier_slice_size": 4, "coll_hier_min_bytes": 1024,
                  "coll_pipeline_min_bytes": 2048,
                  "coll_seg_size": 4096})
    try:
        seg = run_ranks(8, fn, devices=True)
    finally:
        _restore(saved)
    saved = _set(PIPE_OFF)
    try:
        fused = run_ranks(8, fn, devices=True)
    finally:
        _restore(saved)
    assert len({b for b, _ in seg}) == 1
    assert all(d > 0 for _, d in seg)
    assert seg[0][0] == fused[0][0]


# ---------------------------------------------------------------------------
# chaos: delay faults and epoch boundaries
# ---------------------------------------------------------------------------

def test_segmented_under_delay_faults():
    """ft_inject 'delay' at the rendezvous choke point: arbitrary
    straggler arrival orders through the pipelined begin/finish
    schedule must not change a single byte."""
    def fn(comm):
        return _mixed_ops(comm)

    saved = _set(PIPE_ON)
    try:
        clean = run_ranks(4, fn, devices=True)
        chaos_knobs = _set({"ft_inject_plan": "delay",
                            "ft_inject_seed": 7, "ft_inject_rate": 0.5,
                            "ft_inject_delay_ms": 5, "ft_inject_skip": 0})
        try:
            chaotic = run_ranks(4, fn, devices=True)
        finally:
            _restore(chaos_knobs)
    finally:
        _restore(saved)
    assert clean == chaotic
    # cross-rank identity holds for the rank-symmetric ops (alltoall
    # rows are legitimately per-rank)
    assert len({common for common, _ in clean}) == 1


def test_segmented_across_shrink_epoch():
    """A rank dies mid-job: segmented collectives ran on the old
    epoch, the shrunk comm must route and compute freshly — results
    byte-identical to a never-failed world of the survivor size, and
    the old epoch's routing caches are gone from the parent comm."""
    from ompi_tpu.ft import ulfm

    def survivor(comm):
        _ = np.asarray(comm.allreduce_arr(
            _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank), mpi_op.SUM))  # old-epoch segmented op
        if comm.rank == 0:
            ulfm.kill_now(comm.state)
        time.sleep(0.3)
        new = comm.shrink()
        assert "_pipeline_pick" not in comm.__dict__  # epoch hygiene
        assert "_hier_plan" not in comm.__dict__
        x = _put(new, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + new.rank)
        return np.asarray(new.allreduce_arr(x, mpi_op.SUM)).tobytes()

    def fresh(comm):
        x = _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank)
        return np.asarray(comm.allreduce_arr(x, mpi_op.SUM)).tobytes()

    saved = _set(PIPE_ON)
    try:
        got = run_ranks(4, survivor, devices=True, allow_failures=True)
        ref = run_ranks(3, fresh, devices=True)
    finally:
        _restore(saved)
    assert got[0] is None
    assert got[1] == got[2] == got[3] == ref[0]


def test_segmented_across_respawn_epoch():
    """Kill + in-job respawn between segmented collectives: the
    replacement's epoch must not see stale segment/routing state, and
    the completed job's bytes match a fault-free run exactly."""
    from ompi_tpu import errhandler as eh
    from ompi_tpu.cr import buddy
    from ompi_tpu.errhandler import MPIException
    from ompi_tpu.ft import respawn, ulfm

    ft_codes = (eh.ERR_PROC_FAILED, eh.ERR_PROC_FAILED_PENDING,
                eh.ERR_REVOKED)

    def make_fn(kill_at=None, iters=4):
        kill_at = kill_at or {}

        def fn(comm):
            state = comm.state
            was_joining = respawn.joining(state)
            if was_joining:
                comm = respawn.rejoin(comm)
                st = buddy.restore(comm)
                i, acc = int(st["i"]), np.asarray(st["acc"])
            else:
                i, acc = 0, np.zeros(4099, np.float32)
            did_kill = False
            base = (jnp.arange(4099, dtype=jnp.float32) % 11)
            while i < iters:
                try:
                    buddy.checkpoint(comm, {"i": i, "acc": acc})
                    if (not was_joining and not did_kill
                            and kill_at.get(comm.rank) == i):
                        did_kill = True
                        ulfm.kill_now(state)
                    x = _put(comm, base * (i + 1) + comm.rank)
                    acc = np.asarray(
                        comm.allreduce_arr(x, mpi_op.SUM))
                    i += 1
                except MPIException as e:
                    if e.code not in ft_codes:
                        raise
                    comm = respawn.rejoin(comm)
                    st = buddy.restore(comm)
                    i, acc = int(st["i"]), np.asarray(st["acc"])
            return acc.tobytes()
        return fn

    saved = _set(PIPE_ON)
    registry.set("cr_buddy_degree", "1")
    try:
        # devices=True: the point is the SEGMENTED DEVICE tier across
        # the epoch (the rendezvous waits poll ulfm, so every survivor
        # detects the failure — the host p2p tree would leave a rank
        # waiting on a live peer that already left for rejoin)
        clean = run_ranks(4, make_fn(), devices=True, timeout=120)
        faulty = run_ranks(4, make_fn(kill_at={1: 2}), devices=True,
                           timeout=180, respawn=True)
    finally:
        registry.set("cr_buddy_degree", "0")
        _restore(saved)
    assert faulty == clean
    assert all(r is not None for r in faulty)


# ---------------------------------------------------------------------------
# cache bounds and observability
# ---------------------------------------------------------------------------

def test_seg_kernel_cache_not_blown_by_message_sizes():
    """The eviction-pressure satellite: a sweep of distinct message
    sizes all routes through ONE identity-padded segment shape, so the
    CompiledLRU gains ~one segmented entry, the hits pvar climbs, and
    eviction pressure stays flat."""
    from ompi_tpu.coll.device import compile_cache

    pv_hits = registry.register_pvar("coll", "device", "cache_hits")
    pv_evict = registry.register_pvar("coll", "device",
                                      "cache_evictions")

    def fn(comm):
        tot = 0.0
        for n in range(1, 13):  # 12 distinct message sizes, one dtype
            x = _put(comm, jnp.ones((513 * n + n % 3,), jnp.float32))
            tot += float(np.asarray(
                comm.allreduce_arr(x, mpi_op.SUM))[0])
        return tot

    saved = _set(PIPE_ON)
    try:
        run_ranks(4, fn, devices=True)  # warm: compile the seg kernel
        builds0, hits0, evict0 = (compile_cache.builds, pv_hits.read(),
                                  pv_evict.read())
        res = run_ranks(4, fn, devices=True)
        assert res == [4.0 * 12] * 4
        # identical world: zero new executables across 12 sizes
        assert compile_cache.builds == builds0
        assert pv_hits.read() > hits0
        assert pv_evict.read() == evict0
        # the segmented entries are keyed by segment shape, not
        # message size: at most a couple of seg keys exist for this
        # 4-device world (other tests' shrunk worlds may add theirs)
        seg_keys = [k for k in list(compile_cache._d)
                    if isinstance(k, tuple) and k
                    and k[0] == "segring" and len(k[1]) == 4]
        assert 0 < len(seg_keys) <= 2
    finally:
        _restore(saved)


def test_coll_segment_histogram_and_spans():
    """Per-segment meets feed the coll_segment trace category: spans
    carry (cid, seq, nbytes), the HIST_COLL_SEGMENT histogram counts
    them, and the MPI_T pvar surface exports it."""
    from ompi_tpu import trace

    def fn(comm):
        x = _put(comm, (jnp.arange(4099, dtype=jnp.float32) % 11)
                 + comm.rank)
        comm.allreduce_arr(x, mpi_op.SUM)
        tr = comm.state.tracer
        segs = [e for e in tr.snapshot() if e["cat"] == "coll_segment"]
        assert segs and all("cid" in e["args"] for e in segs)
        assert tr.hist_total(trace.HIST_COLL_SEGMENT) == len(segs)
        from ompi_tpu import mpit
        mpit.init_thread()
        try:
            sess = mpit.pvar_session_create()
            ph = mpit.pvar_handle_alloc(sess, "trace_hist_coll_segment")
            assert sum(mpit.pvar_read(ph)) == len(segs)
        finally:
            mpit.finalize()
        return len(segs)

    saved = _set(dict(PIPE_ON, trace_enable="1", trace_dump_path=""))
    try:
        res = run_ranks(4, fn, devices=True)
    finally:
        _restore(saved)
    assert all(n > 1 for n in res)  # several segments traced


# ---------------------------------------------------------------------------
# stress (excluded from the tier-1 fast gate)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_pipeline_stress_8rank():
    """8 ranks, deeper pipeline, repeated mixed segmented collectives
    with rotating sizes: byte-identical to the fused path and across
    ranks every iteration."""
    def fn(comm):
        common, a2a = [], []
        for it in range(6):
            n = 3001 + 997 * it
            base = (jnp.arange(n, dtype=jnp.float32) % 13)
            x = _put(comm, base + comm.rank * (it + 1))
            common.append(np.asarray(
                comm.allreduce_arr(x, mpi_op.SUM)).tobytes())
            a = _put(comm, jnp.arange(257 * comm.size, dtype=jnp.int64)
                     + 10**6 * comm.rank + it)
            a2a.append(np.asarray(comm.alltoall_arr(a)).tobytes())
            b = _put(comm, base * (comm.rank + it + 1))
            common.append(np.asarray(
                comm.bcast_arr(b, root=it % comm.size)).tobytes())
        return b"".join(common), b"".join(a2a)

    saved = _set(dict(PIPE_ON, coll_pipeline_depth=3))
    try:
        seg = run_ranks(8, fn, devices=True, timeout=600)
    finally:
        _restore(saved)
    saved = _set(PIPE_OFF)
    try:
        fused = run_ranks(8, fn, devices=True, timeout=600)
    finally:
        _restore(saved)
    # allreduce/bcast are rank-symmetric; alltoall rows are per-rank
    assert len({common for common, _ in seg}) == 1
    assert seg == fused
