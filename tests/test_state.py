"""Job state machine (runtime/statemachine — orte/mca/state analog):
transition sequencing, error-state policy, and the --verbose state
trace through a real mpirun launch."""

import os
import subprocess
import sys

import pytest

from ompi_tpu.runtime import statemachine as smx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_machine_runs_lifecycle_in_order():
    sm = smx.StateMachine("hnp")
    seen = []

    def step(next_state):
        def h(sm, info):
            seen.append(sm.state)
            if next_state is not None:
                sm.activate(next_state)
        return h

    sm.register_table({
        smx.ALLOCATE: step(smx.MAP),
        smx.MAP: step(smx.LAUNCH_APPS),
        smx.LAUNCH_APPS: step(smx.RUNNING),
        smx.RUNNING: step(smx.DRAINING),
        smx.DRAINING: step(smx.TERMINATED),
        smx.TERMINATED: step(None),
    })
    sm.activate(smx.ALLOCATE)
    assert sm.run() == 0
    assert seen == [smx.ALLOCATE, smx.MAP, smx.LAUNCH_APPS,
                    smx.RUNNING, smx.DRAINING, smx.TERMINATED]


def test_error_state_carries_exit_code():
    sm = smx.StateMachine("hnp")

    def on_fail(sm, info):
        sm.exit_code = info["code"]
        sm.activate(smx.TERMINATED)

    sm.register(smx.PROC_FAILED, on_fail)
    sm.register(smx.TERMINATED, lambda sm, info: None)
    sm.activate(smx.PROC_FAILED, code=7)
    assert sm.run() == 7


def test_events_do_not_change_state():
    sm = smx.StateMachine("hnp")
    hits = []
    sm.register("EV_PING", lambda sm, info: hits.append(sm.state))
    sm.register(smx.RUNNING, lambda sm, info: None)
    sm.register(smx.TERMINATED, lambda sm, info: None)
    sm.activate(smx.RUNNING)
    sm.activate("EV_PING")
    sm.activate(smx.TERMINATED)
    sm.run()
    # the EV_ handler observed RUNNING — events never rename the state
    assert hits == [smx.RUNNING]


def test_cross_thread_activation():
    import threading
    sm = smx.StateMachine("hnp")
    sm.register(smx.RUNNING, lambda sm, info: None)
    sm.register("EV_DONE",
                lambda sm, info: sm.activate(smx.TERMINATED))
    sm.register(smx.TERMINATED, lambda sm, info: None)
    sm.activate(smx.RUNNING)
    threading.Timer(0.05, lambda: sm.activate("EV_DONE")).start()
    assert sm.run() == 0


def test_verbose_state_trace_under_mpirun():
    """--verbose state prints every lifecycle transition (the VERDICT
    r2 requirement for the state-machine re-design)."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "60", "--verbose", "state",
         os.path.join(REPO, "examples", "hello.py")],
        capture_output=True, timeout=120)
    assert r.returncode == 0, r.stderr.decode()
    err = r.stderr.decode()
    for arrow in ("INIT -> ALLOCATE", "ALLOCATE -> MAP",
                  "MAP -> LAUNCH_APPS", "LAUNCH_APPS -> RUNNING",
                  "RUNNING -> DRAINING", "DRAINING -> TERMINATED"):
        assert arrow in err, err


def test_verbose_state_trace_multinode():
    """The PLM path walks the daemon states too."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "120", "--verbose", "state",
         "--simulate-nodes", "2x1", "--devices", "none",
         os.path.join(REPO, "examples", "hello.py")],
        capture_output=True, timeout=180)
    assert r.returncode == 0, r.stderr.decode()
    err = r.stderr.decode()
    for arrow in ("MAP -> LAUNCH_DAEMONS",
                  "LAUNCH_DAEMONS -> DAEMONS_REPORTED",
                  "DAEMONS_REPORTED -> LAUNCH_APPS",
                  "RUNNING -> DRAINING", "DRAINING -> TERMINATED"):
        assert arrow in err, err
