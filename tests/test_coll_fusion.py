"""Fusion/coalescing tests: the small-message device-collective fast
path (coll/fusion).  Interleaved nonblocking allreduce/bcast across
rank-threads must be byte-identical to the unfused blocking path —
with mixed dtypes/ops, under ft_inject delay faults, and through the
finalize-time flush.  Also covers the dispatcher drain satellite and
the measured-crossover selection plane (coll/calibrate).
"""

import json

import numpy as np
import pytest

from ompi_tpu.mca.params import registry
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _set(monkeypatch_vals):
    """registry.set with restore; returns a finalizer-style context."""
    saved = {k: registry.get(k) for k in monkeypatch_vals}
    for k, v in monkeypatch_vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


def _interleaved(comm):
    """The canonical fused batch: mixed kinds, ops, dtypes, a scalar.
    Returns (fused results, unfused references) as byte strings."""
    r = comm.rank
    a = jnp.arange(16, dtype=jnp.int32) * (r + 1)
    b = (jnp.ones((8,), jnp.float32) * (r + 1)).at[0].set(-r)
    c = jnp.full((5,), r * 3 + 1, jnp.int32)
    d = jnp.int32(r + 2)
    reqs = [comm.iallreduce_arr(a, mpi_op.SUM),
            comm.iallreduce_arr(b, mpi_op.MAX),
            comm.ibcast_arr(c, 1 % comm.size),
            comm.iallreduce_arr(d, mpi_op.PROD)]
    for q in reqs:
        q.wait()
    fused = [np.asarray(q.result).tobytes() for q in reqs]
    unfused = [np.asarray(comm.allreduce_arr(a, mpi_op.SUM)).tobytes(),
               np.asarray(comm.allreduce_arr(b, mpi_op.MAX)).tobytes(),
               np.asarray(comm.bcast_arr(c, 1 % comm.size)).tobytes(),
               np.asarray(comm.allreduce_arr(d, mpi_op.PROD)).tobytes()]
    return fused, unfused


@pytest.mark.parametrize("n", [2, 4, 8])
def test_fused_byte_identical_mesh(n):
    """Interleaved small iallreduce/ibcast (mixed dtypes/ops/scalar)
    fused into one dispatch == the unfused blocking path, byte for
    byte, on the multi-device mesh path."""
    def fn(comm):
        assert comm.coll.providers["iallreduce_arr"] == "nbc"
        assert comm.coll.providers["allreduce_arr"] == "tpu"
        return _interleaved(comm)

    for fused, unfused in run_ranks(n, fn, devices=True):
        assert fused == unfused


def test_fused_byte_identical_hbm():
    """Same batch on a single-chip comm (coll/hbm fused path)."""
    def fn(comm):
        assert comm.coll.providers["allreduce_arr"] == "hbm"
        return _interleaved(comm)

    dev0 = jax.devices()[0]
    for fused, unfused in run_ranks(3, fn, device_map=lambda r: dev0):
        assert fused == unfused


def test_fused_counts_one_batch():
    """A wait on the FIRST request flushes the whole pending batch as
    ONE fused dispatch; the pvars record batch vs per-collective
    counts."""
    pv_b = registry.register_pvar("coll", "device", "fused_batches")
    pv_c = registry.register_pvar("coll", "device", "fused_collectives")
    b0, c0 = pv_b.read(), pv_c.read()

    def fn(comm):
        qs = [comm.iallreduce_arr(
                  jnp.arange(4, dtype=jnp.int32) + k, mpi_op.SUM)
              for k in range(6)]
        qs[0].wait()  # flushes all six
        assert all(q.complete for q in qs)
        return [np.asarray(q.result).sum() for q in qs]

    run_ranks(4, fn, devices=True)
    assert pv_b.read() - b0 == 4       # one batch per rank-thread
    assert pv_c.read() - c0 == 24      # six collectives each


def test_fused_auto_flush_at_max_ops():
    saved = _set({"coll_device_fusion_max_ops": 3})
    try:
        def fn(comm):
            qs = [comm.iallreduce_arr(jnp.int32(k), mpi_op.SUM)
                  for k in range(3)]
            # the third enqueue crossed the bound: batch already ran
            assert all(q.complete for q in qs)
            return [int(np.asarray(q.result)) for q in qs]

        res = run_ranks(2, fn, devices=True)
        for vals in res:
            assert vals == [0, 2, 4]
    finally:
        _restore(saved)


def test_fusion_disabled_knob_runs_immediately():
    saved = _set({"coll_device_fusion": False})
    try:
        def fn(comm):
            q = comm.iallreduce_arr(jnp.arange(4, dtype=jnp.int32),
                                    mpi_op.SUM)
            assert q.complete  # immediate blocking execution
            return np.asarray(q.result).tolist()

        res = run_ranks(2, fn, devices=True)
        assert res[0] == [0, 2, 4, 6]
    finally:
        _restore(saved)


def test_large_payload_bypasses_fusion():
    """Above coll_device_fusion_threshold the op runs unfused
    immediately (bandwidth-dominated; coalescing buys nothing)."""
    def fn(comm):
        big = jnp.ones((65536 // 4 + 1,), jnp.float32)
        q = comm.iallreduce_arr(big, mpi_op.SUM)
        assert q.complete
        return float(np.asarray(q.result)[0])

    assert run_ranks(2, fn, devices=True) == [2.0, 2.0]


def test_fused_flush_at_finalize():
    """A batch enqueued and never waited on must flush at
    MPI_Finalize (the dispatcher-drain hook), not die with the rank."""
    reqs = {}

    def fn(comm):
        reqs[comm.rank] = comm.iallreduce_arr(
            jnp.arange(8, dtype=jnp.int32), mpi_op.SUM)
        return comm.rank

    run_ranks(4, fn, devices=True)
    exp = (np.arange(8, dtype=np.int32) * 4).tobytes()
    for r, q in reqs.items():
        assert q.complete, f"rank {r} not flushed at finalize"
        assert np.asarray(q.result).tobytes() == exp


def test_fused_under_delay_faults():
    """ft_inject 'delay' at the rendezvous choke point (seed-driven
    stragglers, the chaos-harness discipline of tests/test_chaos.py):
    arbitrary arrival orders must not change a single byte."""
    def fn(comm):
        return _interleaved(comm)

    clean = run_ranks(4, fn, devices=True)
    saved = _set({"ft_inject_plan": "delay", "ft_inject_seed": 7,
                  "ft_inject_rate": 0.5, "ft_inject_delay_ms": 5,
                  "ft_inject_skip": 0})
    try:
        chaotic = run_ranks(4, fn, devices=True)
    finally:
        _restore(saved)
    for (cf, cu), (df, du) in zip(clean, chaotic):
        assert cf == cu and df == du
        assert cf == df  # delay faults change nothing


def test_fused_batch_mismatch_is_clear_error():
    """Divergent batches across ranks (an SPMD bug) must raise a
    diagnosable error on every rank, never deadlock."""
    def fn(comm):
        if comm.rank == 0:
            comm.iallreduce_arr(jnp.int32(1), mpi_op.SUM)
        comm.iallreduce_arr(jnp.arange(4, dtype=jnp.int32), mpi_op.SUM)
        with pytest.raises(RuntimeError, match="batch mismatch|failed"):
            comm.flush_arr()
        return True

    assert run_ranks(2, fn, devices=True) == [True, True]


# ---------------------------------------------------------------------------
# dispatcher drain (satellite): flush at finalize, reject afterwards
# ---------------------------------------------------------------------------

def test_dispatcher_drains_rejects_and_revives():
    from ompi_tpu.coll import device as dmod

    saved = _set({"coll_device_dispatcher": True})
    try:
        res = run_ranks(2, lambda c: int(np.asarray(
            c.allreduce_arr(jnp.int32(1), mpi_op.SUM))), devices=True)
        assert res == [2, 2]
    finally:
        _restore(saved)
    d = dmod._dispatcher_singleton
    assert d is not None and d.closed  # last finalize drained it
    with pytest.raises(RuntimeError, match="closed"):
        d.submit(lambda: None)
    with pytest.raises(RuntimeError, match="finalize"):
        dmod._dispatcher()
    # a fresh world in the same process revives the plane
    res = run_ranks(2, lambda c: int(np.asarray(
        c.allreduce_arr(jnp.int32(3), mpi_op.SUM))), devices=True)
    assert res == [6, 6]


# ---------------------------------------------------------------------------
# measured crossover selection (coll/calibrate)
# ---------------------------------------------------------------------------

def _fake_profile(tmp_path, crossovers, alpha=5.0, gbs=10.0,
                  dispatch=600.0):
    prof = {"host": "test", "backend": "cpu", "source": "test",
            "host_alpha_us": alpha, "host_gbs": gbs,
            "dispatch_us": dispatch, "crossover_bytes": crossovers}
    p = tmp_path / "profile.json"
    p.write_text(json.dumps(prof))
    return str(p)


def test_measured_rules_off_by_default_and_static_fallback(tmp_path):
    from ompi_tpu.coll import calibrate

    path = _fake_profile(tmp_path, {"allreduce": 1 << 20})
    saved = _set({"coll_tuned_profile_path": path})
    calibrate.reset_cache()
    try:
        assert not calibrate.use_measured_rules()
        # rules off: thresholds stay static, no reroute
        assert calibrate.measured_threshold(
            "allreduce_small", 8, 10000) == 10000
    finally:
        _restore(saved)
        calibrate.reset_cache()


def test_measured_crossover_reroutes_device_path(tmp_path):
    """With measured rules on and a profile whose crossover is above
    the payload, the device module must host-stage the collective —
    visible as a frozen offload pvar (and unchanged results)."""
    from ompi_tpu.coll import calibrate

    pv = registry.register_pvar("coll", "tpu", "offloaded_collectives")
    path = _fake_profile(
        tmp_path, {"allreduce": 1 << 20, "bcast": 0, "alltoall": 0})
    saved = _set({"coll_tuned_profile_path": path,
                  "coll_tuned_use_measured_rules": True})
    calibrate.reset_cache()
    try:
        assert calibrate.crossover_bytes("allreduce", 4) == 1 << 20

        def fn(comm):
            x = jnp.arange(16, dtype=jnp.float32) + comm.rank
            return np.asarray(comm.allreduce_arr(x, mpi_op.SUM))

        n0 = pv.read()
        res = run_ranks(4, fn, devices=True)
        assert pv.read() == n0, "small allreduce was not rerouted"
        exp = sum(np.arange(16, dtype=np.float32) + k for k in range(4))
        np.testing.assert_allclose(res[0], exp)

        # bcast crossover is 0: stays on the device path
        def fb(comm):
            return np.asarray(comm.bcast_arr(
                jnp.arange(4, dtype=jnp.int32), 0))

        n1 = pv.read()
        run_ranks(4, fb, devices=True)
        assert pv.read() > n1
    finally:
        _restore(saved)
        calibrate.reset_cache()


def test_measured_thresholds_move_with_profile(tmp_path):
    """The alpha-beta ladder must actually consume the measured
    numbers: a high-alpha profile pushes the recursive-doubling
    cutoff above a low-alpha one."""
    from ompi_tpu.coll import calibrate

    saved = _set({"coll_tuned_use_measured_rules": True})
    try:
        p1 = _fake_profile(tmp_path, {}, alpha=1.0, gbs=5.0)
        registry.set("coll_tuned_profile_path", p1)
        calibrate.reset_cache()
        low = calibrate.measured_threshold("allreduce_small", 8, 10000)

        p2 = _fake_profile(tmp_path, {}, alpha=200.0, gbs=5.0)
        registry.set("coll_tuned_profile_path", p2)
        calibrate.reset_cache()
        high = calibrate.measured_threshold("allreduce_small", 8, 10000)
        assert high > low > 0
    finally:
        _restore(saved)
        calibrate.reset_cache()


@pytest.mark.slow
def test_calibration_probe_real():
    """The real one-shot probe: sane dispatch constant and host alpha,
    crossovers solved for every kind."""
    from ompi_tpu.coll import calibrate

    prof = calibrate.measure_profile()
    assert prof["host_alpha_us"] > 0
    assert prof["host_gbs"] > 0
    assert prof["dispatch_us"] is None or prof["dispatch_us"] > 0
    assert set(prof["crossover_bytes"]) == {"allreduce", "bcast",
                                            "alltoall"}
    for v in prof["crossover_bytes"].values():
        assert 0 <= v <= 4 << 20


@pytest.mark.slow
def test_fusion_stress_interleaved_shapes():
    """Many rounds of randomized (but rank-agreed) fused batches:
    shapes/ops vary per round, every round byte-identical to the
    unfused path."""
    import random

    rng = random.Random(11)
    rounds = []
    for _ in range(20):
        batch = []
        for _ in range(rng.randint(2, 6)):
            kind = rng.choice(["allreduce", "bcast"])
            shape = (rng.randint(1, 512),)
            dt = rng.choice(["int32", "float32"])
            op = rng.choice(["SUM", "MAX", "MIN"])
            batch.append((kind, shape, dt, op, rng.randint(0, 3)))
        rounds.append(batch)

    def fn(comm):
        out = []
        for batch in rounds:
            reqs, refs = [], []
            for kind, shape, dt, opname, root in batch:
                x = (jnp.arange(shape[0], dtype=dt) * (comm.rank + 1)
                     - comm.rank)
                if kind == "allreduce":
                    reqs.append(comm.iallreduce_arr(
                        x, getattr(mpi_op, opname)))
                    refs.append(lambda x=x, o=opname: comm.allreduce_arr(
                        x, getattr(mpi_op, o)))
                else:
                    reqs.append(comm.ibcast_arr(x, root))
                    refs.append(lambda x=x, r=root: comm.bcast_arr(x, r))
            comm.flush_arr()
            for q, ref in zip(reqs, refs):
                q.wait()
                out.append(np.asarray(q.result).tobytes()
                           == np.asarray(ref()).tobytes())
        return all(out)

    assert all(run_ranks(4, fn, devices=True))
