"""Repeated Comm_spawn rounds (loop_spawn.c analog, run under mpirun
by test_intercomm.py).  Each round spawns one worker, allreduces over
the merged comm, and frees it; the universe grows monotonically."""
import os
import sys

import numpy as np

import ompi_tpu
from ompi_tpu.op import op as mpi_op

comm = ompi_tpu.init()
parent = ompi_tpu.get_parent()

if parent is not None:  # worker role
    merged = parent.merge(high=True)
    r = np.empty(1)
    merged.Allreduce(np.array([1.0]), r, mpi_op.SUM)
    assert r[0] == merged.size
    ompi_tpu.finalize()
    sys.exit(0)

me = os.path.abspath(__file__)
for round_ in range(3):
    inter = comm.spawn(me, maxprocs=1)
    merged = inter.merge(high=False)
    r = np.empty(1)
    merged.Allreduce(np.array([1.0]), r, mpi_op.SUM)
    assert r[0] == comm.size + 1, (round_, r[0])
    merged.free()
    inter.free()
if comm.rank == 0:
    print("loop-spawn done 3 rounds", flush=True)
ompi_tpu.finalize()
