"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip sharding logic is validated on a virtual CPU mesh
(xla_force_host_platform_device_count) since only one real TPU chip is
reachable in CI.  This must run before jax initializes its backends;
the axon sitecustomize pins jax_platforms, so we override via
jax.config as well as the environment.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long multi-node / chaos scenarios excluded from the "
        "tier-1 fast gate (run with -m slow)")
