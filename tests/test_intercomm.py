"""Intercommunicator + dynamic process management tests
(ref: orte/test/mpi/intercomm_create.c, loop_spawn.c;
ompi/communicator/comm.c intercomm paths; ompi/dpm/dpm.c)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from ompi_tpu.comm.intercomm import ROOT, intercomm_create
from ompi_tpu.op import op as mpi_op
from ompi_tpu.pml.request import PROC_NULL
from ompi_tpu.testing import run_ranks

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mk_inter(comm, half):
    """Split world into [0,half) and [half,n); bridge leaders 0 and
    half over comm."""
    low = comm.rank < half
    local = comm.split(0 if low else 1)
    inter = intercomm_create(local, 0, comm, half if low else 0, tag=9)
    return inter, local, low


def test_create_sizes_and_groups():
    def fn(comm):
        inter, local, low = _mk_inter(comm, 2)
        assert inter.is_inter
        assert inter.size == local.size
        assert inter.remote_size == comm.size - local.size
        locals_ = inter.local_group_obj().ranks
        remotes = inter.remote_group_obj().ranks
        assert sorted(locals_ + remotes) == list(range(comm.size))
        return True

    assert run_ranks(5, fn) == [True] * 5


def test_p2p_across_bridge():
    def fn(comm):
        from ompi_tpu.datatype import engine as dt
        inter, local, low = _mk_inter(comm, 3)
        pml = comm.state.pml
        if local.rank < min(inter.size, inter.remote_size):
            x = np.array([comm.rank], dtype=np.int64)
            y = np.empty(1, dtype=np.int64)
            s = pml.isend(x, 1, dt.INT64_T, local.rank, -60, inter)
            pml.recv(y, 1, dt.INT64_T, local.rank, -60, inter)
            s.wait()
            expect = comm.rank + 3 if low else comm.rank - 3
            assert int(y[0]) == expect
        inter.Barrier()
        return True

    assert run_ranks(6, fn) == [True] * 6


def test_rooted_bcast_and_reduce():
    def fn(comm):
        inter, local, low = _mk_inter(comm, 2)
        # bcast: global rank 0 (low leader) -> high group
        buf = np.array([7.5 if comm.rank == 0 else 0.0])
        if low:
            inter.Bcast(buf, root=ROOT if comm.rank == 0 else PROC_NULL)
        else:
            inter.Bcast(buf, root=0)
            assert buf[0] == 7.5
        # reduce: high group's data lands at low leader
        s = np.array([float(comm.rank)])
        r = np.zeros(1)
        if low:
            inter.Reduce(s, r, mpi_op.SUM,
                         root=ROOT if comm.rank == 0 else PROC_NULL)
            if comm.rank == 0:
                assert r[0] == sum(range(2, comm.size))
        else:
            inter.Reduce(s, None, mpi_op.SUM, root=0)
        return True

    assert run_ranks(5, fn) == [True] * 5


def test_allreduce_exchanges_groups():
    def fn(comm):
        inter, local, low = _mk_inter(comm, 3)
        s = np.array([float(comm.rank + 1)])
        r = np.empty(1)
        inter.Allreduce(s, r, mpi_op.SUM)
        low_sum = sum(range(1, 4))
        high_sum = sum(range(4, comm.size + 1))
        assert r[0] == (high_sum if low else low_sum)
        return True

    assert run_ranks(6, fn) == [True] * 6


def test_allgather_and_alltoall():
    def fn(comm):
        inter, local, low = _mk_inter(comm, 2)
        rs = inter.remote_size
        s = np.array([float(comm.rank)], dtype=np.float64)
        r = np.empty(rs, dtype=np.float64)
        inter.Allgather(s, r)
        remote = inter.remote_group_obj().ranks
        assert list(r) == [float(g) for g in remote]
        # alltoall: block i goes to remote rank i
        sb = np.array([comm.rank * 10.0 + i for i in range(rs)])
        rb = np.empty(rs)
        inter.Alltoall(sb, rb)
        for i, g in enumerate(remote):
            assert rb[i] == g * 10.0 + local.rank
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_merge_orders_low_first():
    def fn(comm):
        inter, local, low = _mk_inter(comm, 2)
        merged = inter.merge(high=not low)
        assert merged.size == comm.size
        assert merged.rank == comm.rank
        r = np.empty(1)
        merged.Allreduce(np.array([1.0]), r, mpi_op.SUM)
        assert r[0] == comm.size
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_connect_accept_same_job():
    """Two halves of one job rendezvous through a named port (needs
    the launcher's KV server, so it runs under mpirun)."""
    prog = os.path.join(REPO, "tests", "_connect_accept_prog.py")
    r = _mpirun(4, prog)
    assert r.returncode == 0, r.stderr.decode()
    assert r.stdout.decode().count("ok") == 4


def _mpirun(np_, prog, *args, timeout=120):
    from ompi_tpu.testing import mpirun_run
    return mpirun_run(np_, prog, *args, timeout=timeout)


def test_spawn_under_mpirun():
    r = _mpirun(3, os.path.join(REPO, "examples", "spawn_parent.py"))
    assert r.returncode == 0, r.stderr.decode()
    out = r.stdout.decode()
    assert out.count("merged size 5") == 3
    assert "worker 1: merged rank 4/5" in out


def test_spawn_loop_under_mpirun():
    """Repeated spawns extend the universe each time
    (loop_spawn.c analog, small loop)."""
    r = _mpirun(2, os.path.join(REPO, "tests", "_loop_spawn_prog.py"))
    assert r.returncode == 0, r.stderr.decode()
    assert "loop-spawn done 3 rounds" in r.stdout.decode()


def test_icreate_wire_tag_block_isolated():
    """r3 advisor regression: the intercomm_create wire tag must live
    in a dedicated negative block — never colliding with the small
    internal tags, create_group's [-400,-1399], nbc's <=-2000, or
    non-negative user tag space, for ANY user tag."""
    from ompi_tpu.comm.intercomm import _icreate_wire_tag
    for tag in (0, 5, 7, 8, 17, 25, 26, 400, 999, 2**20):
        wt = _icreate_wire_tag(tag)
        assert -1999 <= wt <= -1500


def test_create_with_colliding_user_tags():
    """User tags that previously collided with internal protocol tags
    (5->TAG_GATHER, 7->TAG_SPLIT, 8->TAG_CID) must work."""
    def fn(comm):
        low = comm.rank < 2
        local = comm.split(0 if low else 1)
        for tag in (5, 7, 8, 30):
            inter = intercomm_create(local, 0, comm,
                                     2 if low else 0, tag=tag)
            assert inter.remote_size == comm.size - local.size
            inter.free()
        local.free()
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_intercomm_split_pairs_colors_across_sides():
    """MPI_Comm_split on an intercommunicator: same color on both
    sides pairs up; one-sided colors get COMM_NULL (MPI-3.1 §6.4.2,
    ref: ompi/mpi/c/comm_split.c inter branch)."""
    def fn(comm):
        inter, local, low = _mk_inter(comm, 3)
        # colors: local side {0: ranks 0,1; 1: rank 2};
        # remote side (3 ranks) {0: ranks 0,1; 7: rank 2}
        color = 0 if local.rank < 2 else (1 if low else 7)
        sub = inter.split(color, key=local.rank)
        if color == 0:
            assert sub is not None and sub.is_inter
            assert sub.size == 2 and sub.remote_size == 2
            # the pair comm works for p2p: exchange global ranks
            from ompi_tpu.datatype import engine as dt
            pml = comm.state.pml
            x = np.array([comm.rank], dtype=np.int64)
            y = np.empty(1, dtype=np.int64)
            s = pml.isend(x, 1, dt.INT64_T, sub.rank, -61, sub)
            pml.recv(y, 1, dt.INT64_T, sub.rank, -61, sub)
            s.wait()
            expect = comm.rank + 3 if low else comm.rank - 3
            assert int(y[0]) == expect
        else:
            # color 1 / 7 exist on one side only -> COMM_NULL
            assert sub is None
        return True

    assert run_ranks(6, fn) == [True] * 6


def test_intercomm_split_undefined_returns_null():
    def fn(comm):
        inter, local, low = _mk_inter(comm, 2)
        from ompi_tpu.comm.communicator import UNDEFINED
        if local.rank == 0:
            sub = inter.split(0, key=0)
            assert sub is not None
            assert sub.size == 1 and sub.remote_size == 1
        else:
            assert inter.split(UNDEFINED) is None
        return True

    assert run_ranks(4, fn) == [True] * 4


def test_comm_join_over_socket():
    """MPI_Comm_join builds a 1-1 intercomm from a raw connected
    socket (ref: ompi/mpi/c/comm_join.c)."""
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "90",
         os.path.join(REPO, "tests", "_join_prog.py")],
        capture_output=True, timeout=150,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO)
    assert r.returncode == 0, r.stderr.decode()
    assert b"join ok" in r.stdout
