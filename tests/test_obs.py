"""Observability stack (ompi_tpu/obs + the DVM metrics RPC;
docs/DESIGN.md §16): MPI_T index stability when the obs gauges
register, ScopedPvar attribution (global == sum of bands, proven both
as a unit and under four concurrent DVM sessions), flight-recorder
ring accounting + persistence + the traceview merge, idempotent
scrape registration across looped worlds, the attach --events and
ompi_tpu-top operator tools, the hotpath_audit coverage of the
scrape tick — plus the classic observability surface (merged from the
old test_observability.py): PERUSE-analog request events, memchecker
buffer-validity checks, the MPIR-analog proctable + stack attach,
mpisync clock offsets, pstat /proc pvars, and the notifier sinks."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from ompi_tpu import memchecker, mpit, obs, peruse, trace
from ompi_tpu.mca.params import registry
from ompi_tpu.testing import run_ranks
from ompi_tpu.tools import traceview

HERE = os.path.dirname(__file__)
PROG = os.path.join(HERE, "_dvm_session_prog.py")
REPO = os.path.dirname(HERE)


@pytest.fixture(autouse=True)
def _clean_obs():
    yield
    registry.set("obs_scrape_interval_ms", "100")
    registry.set("obs_events_ring", "256")
    registry.set("obs_prometheus", "1")
    registry.set("trace_enable", "0")
    registry.set("trace_dump_path", "")


@pytest.fixture
def pool(tmp_path):
    jax = pytest.importorskip("jax")
    from ompi_tpu.tools.dvm import DVMServer
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(8, devices=jax.devices(), uri_file=uri).start()
    yield srv, uri
    srv.stop()


# -- MPI_T surface stability ------------------------------------------------

def test_pvar_indices_stable_after_obs_registration():
    """MPI_T requires pvar indices never move once handed out.  The
    obs gauges append; re-registration is a no-op (pstat model)."""
    mpit.init_thread()
    try:
        obs.register_pvars()  # may already have run — idempotent
        names = [p.full_name
                 for p in registry.pvars_in_registration_order()]
        idx = {n: mpit.pvar_get_index(n) for n in names[:8]}
        obs.register_pvars()
        obs.register_pvars()
        names2 = [p.full_name
                  for p in registry.pvars_in_registration_order()]
        assert names2 == names, "re-registration moved or added pvars"
        assert len(set(names2)) == len(names2), "duplicate pvar names"
        for n, i in idx.items():
            assert mpit.pvar_get_index(n) == i
        # the gauges themselves exist and are readable through MPI_T
        for want in ("obs_p50_progress_tick", "obs_p99_serve_attach",
                     "obs_events_recorded", "obs_events_dropped",
                     "obs_scrapes"):
            i = mpit.pvar_get_index(want)
            info = mpit.pvar_get_info(i)
            assert info["name"] == want
    finally:
        mpit.finalize()


# -- ScopedPvar attribution -------------------------------------------------

def test_scoped_pvar_global_is_sum_of_bands():
    sp = obs.scoped_pvar("test", "obs", "unit_counter",
                         help="test counter")
    base = sp.read()
    base_bands = dict(sp.nonzero_bands())
    sp.add(3, band=1)
    sp.add(5, band=2)
    sp.add(2, band=0)                    # unattributed
    sp.add(7, band=obs.MAX_BANDS + 4)    # wraps into band 4
    assert sp.read() - base == 17
    assert sp.read_band(1) - base_bands.get(1, 0) == 3
    assert sp.read_band(2) - base_bands.get(2, 0) == 5
    assert sp.read_band(4) - base_bands.get(4, 0) == 7
    assert sp.read() == sum(sp.bands), \
        "global must equal the sum over all bands"
    # the factory is idempotent: same full name -> same wrapper AND
    # same underlying registry PVar (indices never move)
    again = obs.scoped_pvar("test", "obs", "unit_counter")
    assert again is sp
    assert again.pvar is sp.pvar


def test_scoped_snapshot_shape():
    sp = obs.scoped_pvar("test", "obs", "snap_counter")
    sp.add(4, band=9)
    snap = obs.scoped_snapshot()
    ent = snap[sp.full_name]
    assert ent["global"] == sum(int(v) for v in ent["bands"].values())
    assert ent["bands"]["9"] >= 4


# -- flight recorder --------------------------------------------------------

def test_flight_recorder_ring_bound_and_drops():
    rec = obs.FlightRecorder(16)
    for n in range(40):
        rec.record(obs.EV_CKPT_COMMIT, n)
    assert rec.recorded == 40
    assert rec.dropped == 24
    evs = rec.snapshot()
    assert len(evs) == 16
    # oldest-first, and only the newest 16 survive the wrap
    assert [e["args"]["epoch"] for e in evs] == list(range(24, 40))
    assert [e["args"]["epoch"] for e in rec.snapshot(last=4)] \
        == [36, 37, 38, 39]
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)


def test_flight_recorder_decodes_interned_strings():
    rec = obs.FlightRecorder(8)
    rec.record(obs.EV_FT_INJECT, obs.intern("rank_kill"),
               obs.intern("world"), rank=2)
    rec.record(obs.EV_ADMIT_REJECT, -1, obs.intern("busy"))
    evs = rec.snapshot()
    assert evs[0]["name"] == "ft_inject"
    assert evs[0]["args"] == {"cls": "rank_kill", "scope": "world"}
    assert evs[0]["rank"] == 2
    assert evs[1]["args"]["reason"] == "busy"


def test_recorder_persist_and_traceview_merge(tmp_path):
    """The persisted ring is a traceview-loadable dump: it merges with
    per-rank trace dumps onto one perfetto timeline (the flight lane
    is the daemon lane, rank -1)."""
    rec = obs.FlightRecorder(32)
    rec.record(obs.EV_DVM_ATTACH, 1, 4, 120)
    rec.record(obs.EV_ULFM_SHRINK, 7, 9, 3, 4500, rank=0)
    path = str(tmp_path / "ring.events.json")
    assert rec.persist(path) == path
    rank0 = {"rank": 0, "recorded": 1, "dropped": 0,
             "events": [{"name": "allreduce", "cat": "coll", "ph": "X",
                         "ts": rec.anchor_wall, "dur": 1e-4,
                         "args": {"cid": 0, "seq": 1}}]}
    d0 = str(tmp_path / "trace-r0.json")
    with open(d0, "w") as fh:
        json.dump(rank0, fh)
    dumps = traceview.load_dumps([d0, path])
    assert [d["rank"] for d in dumps] == [-1, 0]
    doc = traceview.chrome_trace(dumps, [])
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"dvm_attach", "ulfm_shrink", "allreduce"} <= names
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e.get("name") == "process_name"}
    assert "daemon" in procs          # the flight lane
    text = traceview.summary(dumps, [])
    assert "2 rank dump(s)" in text


def test_record_event_never_raises():
    obs.record_event(999, 1, 2, 3, 4)       # unknown type: still safe
    evs = obs.recorder().snapshot(last=1)
    assert evs and evs[0]["name"] == "999"


# -- scrape buffer ----------------------------------------------------------

class _FakeTracer:
    def __init__(self):
        self.hists = [[0] * trace.N_BUCKETS
                      for _ in trace.HIST_NAMES]
        self.anchor_wall = 0.0
        self.anchor_ns = 0


def test_scraper_snapshot_consistency():
    tr = _FakeTracer()
    tr.hists[1][6] = 10
    tr.hists[1][7] = 5
    import time as _time
    sc = obs.Scraper(tr, interval_ms=1)
    assert sc.read_hists() is None      # no refresh yet -> fall back
    now = _time.perf_counter_ns()
    assert sc.tick(now) == 1
    assert sc.tick(now) == 0            # interval-gated
    hists = sc.read_hists()
    assert hists is not None
    assert hists[1][6] == 10 and hists[1][7] == 5
    assert sc.ticks == 1


def test_hist_percentiles():
    h = [0] * trace.N_BUCKETS
    h[5], h[6], h[7] = 10, 5, 1
    p = obs.hist_percentiles(h)
    assert p == {"p50": 32.0, "p90": 64.0, "p99": 128.0}
    assert obs.hist_percentiles([0] * trace.N_BUCKETS) \
        == {"p50": 0.0, "p90": 0.0, "p99": 0.0}


def test_hotpath_audit_covers_scrape_tick():
    from ompi_tpu.tools import hotpath_audit
    assert "Scraper.tick" in hotpath_audit.HOT_FUNCTIONS[
        "ompi_tpu/obs/__init__.py"]
    assert hotpath_audit.audit() == []


# -- idempotent registration across looped worlds (satellite 1) -------------

def test_scrape_registration_idempotent_across_worlds():
    """Two sequential worlds with scraping on: the scraper attaches in
    both, ticks at least once in both, and the obs pvar set neither
    duplicates nor grows (the pstat model)."""
    registry.set("trace_enable", "1")
    registry.set("obs_scrape_interval_ms", "1")
    import numpy as np
    from ompi_tpu.op import op as mpi_op

    def fn(comm):
        st = comm.state
        assert st.progress.obs is not None
        assert st.extra["obs_scraper"] is st.progress.obs
        sbuf = np.ones(8, np.float32)
        rbuf = np.zeros(8, np.float32)
        for _ in range(4):
            comm.Allreduce(sbuf, rbuf, mpi_op.SUM)
        comm.Barrier()
        # device collectives rendezvous without sweeping the progress
        # engine; sweep explicitly so the scrape tick provably fires
        st.progress.progress()
        return st.extra["obs_scraper"].ticks

    ticks1 = run_ranks(2, fn)
    names1 = [p.full_name for p in registry.all_pvars()
              if p.full_name.startswith("obs_")]
    assert all(t >= 1 for t in ticks1)
    assert len(set(names1)) == len(names1)
    ticks2 = run_ranks(2, fn)
    assert all(t >= 1 for t in ticks2)
    names2 = [p.full_name for p in registry.all_pvars()
              if p.full_name.startswith("obs_")]
    assert names2 == names1


def test_scrape_disabled_costs_one_check():
    """interval 0 (or trace off): the progress engine's obs slot stays
    None — the same single-attribute-check contract as the tracer."""
    registry.set("obs_scrape_interval_ms", "0")
    registry.set("trace_enable", "1")

    def fn(comm):
        assert comm.state.progress.obs is None
        comm.Barrier()
        return True

    assert all(run_ranks(2, fn))


# -- local metrics document -------------------------------------------------

def test_local_metrics_document():
    m = obs.local_metrics(events=4)
    assert set(m) >= {"ts", "pvars", "hists", "percentiles",
                      "scoped", "events"}
    assert isinstance(m["pvars"], dict) and m["pvars"]
    assert "obs_events_recorded" in m["pvars"]


def test_prometheus_text_exposition():
    sp = obs.scoped_pvar("test", "obs", "prom_counter")
    sp.add(2, band=3)
    m = obs.local_metrics(events=0)
    text = obs.prometheus_text(m)
    assert "# TYPE ompi_tpu_test_obs_prom_counter counter" in text
    assert 'ompi_tpu_test_obs_prom_counter{session="3"}' in text
    for ln in text.strip().splitlines():
        assert ln.startswith("#") or " " in ln


# -- the DVM metrics RPC: attribution under 4 concurrent sessions -----------

def test_metrics_rpc_attribution_four_sessions(pool):
    """Four concurrent sessions serve jobs; a LIVE metrics scrape
    returns per-session counters whose sum over all bands equals the
    global pvar — for every scoped counter — plus aggregated latency
    percentiles and the flight-recorder tail."""
    from ompi_tpu.tools.dvm import DvmClient
    srv, uri = pool

    def worker(tag):
        with DvmClient(uri) as c:
            sid = c.attach(2)["sid"]
            resp = c.run(sid, PROG, [tag], timeout=120)
            c.detach(sid)
        assert resp.get("code") == 0, resp.get("stderr", "")[-2000:]

    threads = [threading.Thread(target=worker, args=(f"s{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    with DvmClient(uri) as c:
        m = c.metrics(events=32)
    assert m["ok"] and m["jobs"] >= 4
    # attribution: global == sum(bands) for EVERY scoped counter
    for name, ent in m["scoped"].items():
        assert ent["global"] == sum(int(v)
                                    for v in ent["bands"].values()), name
    # dvm_jobs attributes one job to each of the four session bands
    jobs = m["scoped"]["dvm_jobs"]["bands"]
    active = [b for b, v in jobs.items() if b != "0" and v]
    assert len(active) >= 4
    # the aggregated histograms produced percentiles
    assert m["percentiles"]["serve_attach"]["p50"] > 0
    # the flight recorder saw the attaches and runs
    names = [e["name"] for e in m["events"]]
    assert "dvm_attach" in names and "dvm_run" in names
    assert m["events_recorded"] >= 8
    # prometheus exposition rides along by default
    assert "# TYPE" in m.get("prometheus", "")
    assert 'session="' in m["prometheus"]


def test_metrics_rpc_sessions_live_rows(pool):
    """While a session is RESIDENT, its row carries np and per-band
    counters; dead/detached sessions drop out."""
    from ompi_tpu.tools.dvm import DvmClient
    srv, uri = pool
    with DvmClient(uri) as c:
        sid = c.attach(2)["sid"]
        # bands are process-lifetime: a previous pool's session may
        # have used this sid's band, so assert deltas
        base = c.metrics()["sessions"][str(sid)]
        resp = c.run(sid, PROG, ["live"], timeout=120)
        assert resp.get("code") == 0
        m = c.metrics()
        row = m["sessions"][str(sid)]
        assert row["np"] == 2 and not row["dead"]
        assert row["dvm_jobs"] - base["dvm_jobs"] == 1
        assert row["dvm_job_wall_us"] > base["dvm_job_wall_us"]
        c.detach(sid)
        m2 = c.metrics()
        assert str(sid) not in m2["sessions"]


# -- operator tools ---------------------------------------------------------

def test_attach_events_live_then_persisted(pool, capsys, tmp_path):
    """attach --events: live over the metrics RPC while the pool
    answers; after halt, from the persisted <uri>.events.json ring."""
    from ompi_tpu.tools import attach
    from ompi_tpu.tools.dvm import DvmClient
    srv, uri = pool
    with DvmClient(uri) as c:
        sid = c.attach(2)["sid"]
        assert c.run(sid, PROG, ["ev"], timeout=120).get("code") == 0
        c.detach(sid)
    assert attach.main([uri, "--events"]) == 0
    out = capsys.readouterr().out
    assert "flight recorder (live)" in out
    assert "dvm_attach" in out and "dvm_run" in out

    with DvmClient(uri) as c:
        c.halt()
    srv.stop()
    persisted = f"{uri}.events.json"
    assert os.path.isfile(persisted)
    assert attach.main([uri, "--events", "8"]) == 0
    out = capsys.readouterr().out
    assert f"flight recorder ({persisted})" in out
    assert "dvm_halt" in out
    # and the persisted ring merges onto the traceview timeline
    dumps = traceview.load_dumps([persisted])
    assert dumps[0]["flight"] and dumps[0]["rank"] == -1
    doc = traceview.chrome_trace(dumps, [])
    assert any(e.get("cat") == "flight" for e in doc["traceEvents"])


def test_top_render_and_once(pool, capsys):
    from ompi_tpu.tools import top
    from ompi_tpu.tools.dvm import DvmClient
    srv, uri = pool
    with DvmClient(uri) as c:
        sid = c.attach(2)["sid"]
        assert c.run(sid, PROG, ["top"], timeout=120).get("code") == 0
        m = c.metrics()
        frame = top.render(m)
        assert f"s{sid:>3}" in frame and "jobs" in frame
        assert "flight recorder" in frame
        assert top.main([uri, "--once"]) == 0
        out = capsys.readouterr().out
        assert "tpu-dvm pid" in out and "sessions" in out
        assert top.main([uri, "--prometheus"]) == 0
        out = capsys.readouterr().out
        assert "# TYPE" in out
        c.detach(sid)


def test_top_render_empty_pool():
    from ompi_tpu.tools import top
    frame = top.render({"pid": 1, "capacity": 8, "active_ranks": 0,
                        "sessions": {}, "events": []})
    assert "(no resident sessions)" in frame


# -- traceview histogram-gauge summaries (satellite 2) ----------------------

def test_traceview_summary_ingests_metrics_snapshot(tmp_path):
    """A decimated dump (spans sampled away, no hists) still gets
    truthful percentile lines when a metrics snapshot is supplied."""
    h = [0] * trace.N_BUCKETS
    h[5], h[6], h[7] = 10, 5, 1
    metrics = {"hists": {"coll_dispatch": h}}
    dump = {"rank": 0, "recorded": 0, "dropped": 4096, "events": []}
    text = traceview.summary([dump], [], metrics=metrics)
    assert "metrics snapshot" in text
    assert "coll_dispatch" in text
    assert "p50        32 us" in text and "p99       128 us" in text


def test_traceview_summary_sums_dump_hists():
    h0 = [0] * trace.N_BUCKETS
    h1 = [0] * trace.N_BUCKETS
    h0[4] = 6
    h1[4] = 6
    dumps = [{"rank": 0, "events": [], "hists": {"p2p_complete": h0}},
             {"rank": 1, "events": [], "hists": {"p2p_complete": h1}}]
    lines = traceview.hist_gauge_summary(dumps)
    assert any("p2p_complete" in ln and "(n=12)" in ln
               for ln in lines)
    assert traceview.hist_gauge_summary([{"rank": 0, "events": []}]) \
        == ["  (no histogram gauges in dumps or snapshot)"]


def test_traceview_cli_metrics_flag(tmp_path, capsys):
    h = [0] * trace.N_BUCKETS
    h[8] = 3
    mpath = str(tmp_path / "metrics.json")
    with open(mpath, "w") as fh:
        json.dump({"hists": {"progress_tick": h}}, fh)
    dpath = str(tmp_path / "trace-r0.json")
    with open(dpath, "w") as fh:
        json.dump({"rank": 0, "events": []}, fh)
    assert traceview.main([dpath, "--metrics", mpath]) == 0
    out = capsys.readouterr().out
    assert "progress_tick" in out and "p50       256 us" in out


# -- classic observability surface (merged from test_observability.py) ------

@pytest.fixture(autouse=True)
def _clean_peruse():
    yield
    peruse.unsubscribe_all()
    registry.set("opal_memchecker_enable", False)


def test_peruse_request_lifecycle_events():
    events = []
    for ev in peruse.EVENTS:
        peruse.subscribe(ev, lambda e, **kw: events.append((e, kw)))

    def fn(comm):
        x = np.array([comm.rank], np.int64)
        y = np.empty(1, np.int64)
        nxt = (comm.rank + 1) % comm.size
        prv = (comm.rank - 1) % comm.size
        rq = comm.Irecv(y, prv, tag=5)
        comm.Send(x, nxt, tag=5)
        rq.wait()

    run_ranks(2, fn)
    kinds = {e for e, _ in events}
    assert "req_activate" in kinds
    assert "req_complete" in kinds
    # both send and recv activations observed, with byte counts
    acts = [kw for e, kw in events if e == "req_activate"]
    assert {a["kind"] for a in acts} == {"send", "recv"}
    assert all(a["bytes"] == 8 for a in acts)
    # a message arriving before its recv is posted queues unexpected
    assert any(e == "req_match_unex" for e, _ in events) or \
        any(e == "req_match" for e, _ in events)


def test_peruse_disabled_costs_nothing():
    assert not peruse.enabled
    fired = []
    peruse.subscribe("req_complete", lambda e, **kw: fired.append(1))
    peruse.unsubscribe_all()
    assert not peruse.enabled


def test_memchecker_poisons_recv_buffer():
    registry.set("opal_memchecker_enable", True)

    def fn(comm):
        if comm.rank == 0:
            y = np.zeros(4, np.uint8)
            rq = comm.Irecv(y, 1, tag=9)
            # posted but unmatched: buffer must hold the poison
            # pattern, not stale zeros
            poisoned = bytes(y) == bytes([memchecker.POISON] * 4)
            comm.Send(np.zeros(1, np.uint8), 1, tag=8)  # release peer
            rq.wait()
            assert bytes(y) == b"\x07\x07\x07\x07"
            return poisoned
        comm.Recv(np.empty(1, np.uint8), 0, tag=8)
        comm.Send(np.full(4, 7, np.uint8), 0, tag=9)
        return True

    assert all(run_ranks(2, fn))


def test_memchecker_catches_modified_send_buffer():
    registry.set("opal_memchecker_enable", True)
    big = 1024 * 1024  # above inproc eager limit: rendezvous

    def fn(comm):
        if comm.rank == 0:
            x = np.zeros(big, np.uint8)
            rq = comm.state.pml.isend(
                x, big, _u8(), 1, 11, comm)
            x[0] = 99  # illegal: buffer owned by an active request
            try:
                while not rq.complete:
                    comm.state.progress.progress()
                return False  # memchecker should have raised
            except RuntimeError as e:
                return "modified" in str(e)
        y = np.empty(big, np.uint8)
        comm.Recv(y, 0, tag=11)
        return True

    def _u8():
        from ompi_tpu.datatype import engine as dt
        return dt.BYTE

    assert all(run_ranks(2, fn))


def test_proctable_and_stack_attach():
    """mpirun publishes the MPIR-analog proctable; attach --stacks
    makes a hung rank dump its threads."""
    import tempfile
    import textwrap
    import time

    with tempfile.TemporaryDirectory() as d:
        prog = os.path.join(d, "hang.py")
        with open(prog, "w") as f:
            f.write(textwrap.dedent("""
                import os, sys, time
                import ompi_tpu
                comm = ompi_tpu.init()
                print("SESSION", os.environ["TPUMPI_SESSION_DIR"],
                      flush=True)
                time.sleep(30)
                ompi_tpu.finalize()
            """))
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
             "--timeout", "25", prog],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)
        try:
            session = None
            for _ in range(200):
                line = p.stdout.readline()
                if line.startswith("SESSION"):
                    session = line.split()[1]
                    break
            assert session, "ranks never reported their session dir"
            table_path = os.path.join(session, "proctable.json")
            for _ in range(100):
                if os.path.exists(table_path):
                    break
                time.sleep(0.05)
            table = json.load(open(table_path))
            assert len(table) == 2
            assert all("pid" in e and "tag" in e for e in table)
            # attach --stacks: every rank dumps its stacks to stderr
            r = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.attach",
                 session, "--stacks"],
                capture_output=True, text=True, timeout=30, env=env,
                cwd=REPO)
            assert r.returncode == 0, r.stderr
            assert "signalled 2/2" in r.stdout
        finally:
            p.terminate()
            out, err = p.communicate(timeout=30)
        # the SIGUSR1 faulthandler wrote tracebacks into job stderr
        assert "Traceback" in err or "Current thread" in err, err


def test_mpisync_reports_offsets():
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "3",
         "--timeout", "90",
         os.path.join(REPO, "ompi_tpu", "tools", "mpisync.py"),
         "--rounds", "10"],
        capture_output=True, text=True, timeout=150,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        cwd=REPO)
    assert r.returncode == 0, r.stderr
    last = r.stdout.strip().splitlines()[-1]
    data = json.loads(last)
    assert len(data["offsets_us"]) == 3
    assert data["rtts_us"][1] > 0 and data["rtts_us"][2] > 0
    # same-host clocks: offsets bounded by a loose sanity envelope
    assert all(abs(o) < 5e6 for o in data["offsets_us"])


@pytest.mark.skipif(sys.platform != "linux",
                    reason="pstat scrapes Linux /proc")
def test_pstat_snapshot_and_pvars():
    """opal/mca/pstat analog: /proc stats + live MPI_T pvars."""
    from ompi_tpu.runtime import pstat

    st = pstat.snapshot()
    assert st, "Linux /proc scrape failed"
    assert st["rss_mb"] > 0 and st["threads"] >= 1
    assert st["utime_s"] >= 0

    def fn(comm):
        pv = next(p for p in registry.all_pvars()
                  if p.full_name == f"opal_pstat_rss_mb_r{comm.rank}")
        return pv.read() > 0

    assert all(run_ranks(2, fn))


def test_notifier_file_sink(tmp_path):
    """orte/mca/notifier analog: events route to configured sinks;
    default is off."""
    from ompi_tpu.runtime import notifier

    log = tmp_path / "events.log"
    registry.set("orte_notifier_sinks", f"file:{log}")
    try:
        notifier.notify("error", "job-x", "rank 3 exploded")
        notifier.notify("bogus-severity", "job-x", "still logged")
    finally:
        registry.set("orte_notifier_sinks", "")
    lines = log.read_text().splitlines()
    assert len(lines) == 2
    assert "error job=job-x rank 3 exploded" in lines[0]
    assert "notice" in lines[1]  # unknown severity mapped to notice
    # default (empty) sinks: no-op, never raises
    notifier.notify("error", "job-x", "dropped")
