"""dfs exerciser: ranks on (simulated) compute nodes read a file
that only the launch host is meant to own, via file://hnp/ through
the KV control plane (dfs/app analog)."""
import os
import sys

import ompi_tpu
from ompi_tpu.runtime import dfs

path = sys.argv[1]
comm = ompi_tpu.init()

# remote-host route: explicit hnp uri forces the control plane
with dfs.open(f"file://hnp/{path.lstrip('/')}", comm.state.rte) as f:
    assert f.size() == 3000, f.size()
    head = f.read(100)
    assert head == bytes(range(100)), head[:8]
    f.seek(2900)
    tail = f.read()
    assert len(tail) == 100 and tail[-1] == (2999 % 256)
    try:
        f.seek(5000)
        raise SystemExit("seek past EOF must fail")
    except OSError:
        pass
    # pread does not disturb the pointer
    assert f.pread(0, 4) == bytes(range(4))

# local route: plain path bypasses the control plane
with dfs.open(path) as f:
    assert f.read(10) == bytes(range(10))

comm.Barrier()
if comm.rank == 0:
    print("dfs ok", flush=True)
ompi_tpu.finalize()
