"""Communicator/group tests (ref: ompi/communicator/comm.c,
comm_cid.c agreement; intercomm_create/loop_spawn analogs deferred
to dynamic-process support)."""

import numpy as np
import pytest

from ompi_tpu.comm.communicator import Group, UNDEFINED
from ompi_tpu.op import op as mpi_op
from ompi_tpu.testing import run_ranks


def test_group_operations():
    g = Group([4, 2, 7, 9])
    assert g.size == 4
    assert g.rank_of(7) == 2
    assert g.rank_of(5) == UNDEFINED
    assert g.incl([2, 0]).ranks == [7, 4]
    assert g.excl([0, 3]).ranks == [2, 7]
    assert g.union(Group([1, 2])).ranks == [4, 2, 7, 9, 1]
    assert g.intersection(Group([9, 4, 5])).ranks == [4, 9]
    assert g.difference(Group([2, 9])).ranks == [4, 7]


def test_comm_dup_independent_traffic():
    def fn(comm):
        dup = comm.dup()
        assert dup.cid != comm.cid
        assert dup.size == comm.size and dup.rank == comm.rank
        # same tag on both comms must not cross
        if comm.rank == 0:
            comm.Send(np.array([1], np.int32), dest=1, tag=5)
            dup.Send(np.array([2], np.int32), dest=1, tag=5)
        elif comm.rank == 1:
            a = np.zeros(1, np.int32)
            b = np.zeros(1, np.int32)
            dup.Recv(b, source=0, tag=5)
            comm.Recv(a, source=0, tag=5)
            assert a[0] == 1 and b[0] == 2
        dup.Free()
        return dup.cid

    res = run_ranks(3, fn)
    assert len(set(res)) == 1  # same cid agreed everywhere


def test_comm_split_colors_and_keys():
    def fn(comm):
        color = comm.rank % 2
        key = -comm.rank  # reverse order within each split
        sub = comm.split(color, key)
        return (sub.cid, sub.rank, sub.size, tuple(sub.group))

    res = run_ranks(6, fn)
    evens = [r for k, r in enumerate(res) if k % 2 == 0]
    odds = [r for k, r in enumerate(res) if k % 2 == 1]
    # reverse key ordering: global rank 4 is rank 0 of the even comm
    assert evens[0][3] == (4, 2, 0)
    assert odds[0][3] == (5, 3, 1)
    assert {r[2] for r in evens} == {3}
    # cids of the two disjoint groups may be equal; both must differ
    # from world cid 0
    assert all(r[0] != 0 for r in res)


def test_comm_split_undefined():
    def fn(comm):
        sub = comm.split(UNDEFINED if comm.rank == 1 else 0)
        if comm.rank == 1:
            assert sub is None
            return None
        return tuple(sub.group)

    res = run_ranks(4, fn)
    assert res[0] == (0, 2, 3)
    assert res[1] is None


def test_comm_create_subgroup():
    def fn(comm):
        g = comm.group_obj().incl([0, 2])
        sub = comm.create(g)
        if comm.rank in (0, 2):
            assert sub is not None
            x = np.array([comm.rank], np.int64)
            r = np.zeros(1, np.int64)
            sub.Allreduce(x, r, mpi_op.SUM)
            return int(r[0])
        assert sub is None
        return None

    res = run_ranks(4, fn)
    assert res[0] == 2 and res[2] == 2
    assert res[1] is None and res[3] is None


def test_nested_splits_cid_uniqueness():
    def fn(comm):
        cids = {comm.cid}
        c1 = comm.split(comm.rank % 2)
        cids.add(c1.cid)
        c2 = c1.split(0)
        cids.add(c2.cid)
        c3 = comm.dup()
        cids.add(c3.cid)
        # all live comms on this rank have distinct cids
        assert len(cids) == 4
        # collectives on the nested comm still work
        x = np.array([1], np.int64)
        r = np.zeros(1, np.int64)
        c2.Allreduce(x, r, mpi_op.SUM)
        return int(r[0])

    res = run_ranks(6, fn)
    assert res == [3, 3, 3, 3, 3, 3]


def test_split_type_shared():
    from ompi_tpu.comm.communicator import COMM_TYPE_SHARED

    def fn(comm):
        sub = comm.split_type(COMM_TYPE_SHARED)
        return sub.size  # thread-ranks all share the host

    res = run_ranks(4, fn)
    assert res == [4, 4, 4, 4]


def test_sendrecv_rank_translation_on_subcomm():
    def fn(comm):
        sub = comm.split(comm.rank // 2)  # pairs
        peer = 1 - sub.rank
        me = np.array([comm.rank], np.int32)
        other = np.zeros(1, np.int32)
        sub.Sendrecv(me, peer, 0, other, peer, 0)
        return int(other[0])

    res = run_ranks(4, fn)
    assert res == [1, 0, 3, 2]
