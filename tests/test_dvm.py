"""Service-plane tests for the multiplexed DVM pool (tools/dvm):
concurrent sessions must be byte-identical to sequential ones and
ride the shared warm compiled-executable cache; admission control
must queue and reject deterministically; halt must drain in-flight
runs; and a client dying mid-run must never poison the pool or a
peer session (ft_inject dvm_disconnect class)."""

import json
import os
import socket
import threading
import time

import pytest

from ompi_tpu.mca.params import registry

jax = pytest.importorskip("jax")

from ompi_tpu.tools.dvm import (DVMServer, DvmBusy,  # noqa: E402
                                DvmClient, DvmError)

HERE = os.path.dirname(__file__)
PROG = os.path.join(HERE, "_dvm_session_prog.py")
SLOW_PROG = os.path.join(HERE, "_dvm_slow_prog.py")


@pytest.fixture
def pool(tmp_path):
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(8, devices=jax.devices(), uri_file=uri).start()
    yield srv, uri
    srv.stop()


def _set(vals):
    saved = {k: registry.get(k) for k in vals}
    for k, v in vals.items():
        registry.set(k, v)
    return saved


def _restore(saved):
    for k, v in saved.items():
        registry.set(k, v)


def _run_once(uri, tag, np_=4):
    with DvmClient(uri) as c:
        sid = c.attach(np_)["sid"]
        resp = c.run(sid, PROG, [tag], timeout=120)
        c.detach(sid)
    assert resp.get("code") == 0, resp.get("stderr", "")[-2000:]
    return resp["stdout"]


def test_concurrent_sessions_byte_identical_and_warm(pool):
    """Two concurrent sessions == two sequential sessions, byte for
    byte — and after the sequential warm-up, the concurrent pair
    compiles NOTHING (device-id-keyed CompiledLRU shared pool-wide;
    hit pvars prove the reuse)."""
    from ompi_tpu.coll.device import compile_cache

    srv, uri = pool
    seq = [_run_once(uri, "x") for _ in range(2)]
    assert seq[0] == seq[1]
    assert "DIGEST x " in seq[0]
    builds0 = compile_cache.builds
    hits0 = compile_cache.pv_hits.read()
    outs = [None, None]

    def worker(i):
        outs[i] = _run_once(uri, "x")

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert outs[0] == seq[0] and outs[1] == seq[0], (outs, seq)
    assert compile_cache.builds == builds0, \
        "a concurrent session recompiled executables the sequential " \
        "runs already cached"
    assert compile_cache.pv_hits.read() > hits0


def test_session_argv_isolation(pool):
    """Two concurrent sessions with DIFFERENT argv each see their
    own (thread-local sys.argv proxy, not a process-global swap)."""
    srv, uri = pool
    outs = {}

    def worker(tag):
        outs[tag] = _run_once(uri, tag, np_=2)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in ("alpha", "beta")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert "DIGEST alpha " in outs["alpha"]
    assert "DIGEST beta " in outs["beta"]
    assert "beta" not in outs["alpha"]


def test_cross_session_batching_byte_identical(pool):
    """With the cross-session window open, fused batches from two
    concurrently-resident sessions coalesce into combined dispatches
    — results still byte-identical to the solo run, and the
    dvm_xsession pvars prove at least one combined dispatch."""
    from ompi_tpu.coll import fusion

    srv, uri = pool
    baseline = _run_once(uri, "w")
    saved = _set({"dvm_batch_window_us": 800000})
    xb0 = fusion._pv_xbatches.read()
    xc0 = fusion._pv_xcolls.read()
    try:
        # attach both sessions FIRST so the pool reports 2 resident
        # sessions before either program dispatches
        ca, cb = DvmClient(uri), DvmClient(uri)
        sa = ca.attach(4)["sid"]
        sb = cb.attach(4)["sid"]
        res = {}

        def runner(c, sid, key):
            res[key] = c.run(sid, PROG, ["w"], timeout=120)

        threads = [threading.Thread(target=runner, args=args)
                   for args in ((ca, sa, "a"), (cb, sb, "b"))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for key in ("a", "b"):
            assert res[key]["code"] == 0, res[key]["stderr"][-2000:]
            assert res[key]["stdout"] == baseline
        ca.detach(sa)
        cb.detach(sb)
        ca.close()
        cb.close()
    finally:
        _restore(saved)
    assert fusion._pv_xbatches.read() > xb0, \
        "no combined cross-session dispatch happened inside the window"
    assert fusion._pv_xcolls.read() >= xc0 + 2


def test_admission_queue_and_reject(tmp_path):
    """Rank-capacity admission: wait=False rejects immediately when
    full; one waiter queues; a second is rejected by the queue bound;
    detach admits the queued waiter FIFO."""
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    saved = _set({"dvm_queue_max": 1})
    try:
        c1 = DvmClient(uri)
        s1 = c1.attach(4)["sid"]
        c2 = DvmClient(uri)
        with pytest.raises(DvmBusy):
            c2.attach(2, wait=False)
        got = {}

        def waiter():
            try:
                with DvmClient(uri) as c3:
                    r = c3.attach(2, timeout=60)
                    got.update(r)
                    c3.detach(r["sid"])
            except DvmError as e:  # surfaced by the assert below
                got["err"] = str(e)

        th = threading.Thread(target=waiter)
        th.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with srv.lock:
                if len(srv._waiters) == 1:
                    break
            time.sleep(0.02)
        with srv.lock:
            assert len(srv._waiters) == 1, "waiter never queued"
        # queue is at its bound: the next attach bounces immediately
        c4 = DvmClient(uri)
        with pytest.raises(DvmBusy, match="queue full"):
            c4.attach(2, timeout=30)
        c4.close()
        c1.detach(s1)  # frees capacity -> the queued waiter admits
        th.join(timeout=60)
        assert "sid" in got, got
        assert got["queued_us"] > 0
        c1.close()
        c2.close()
    finally:
        _restore(saved)
        srv.stop()


def test_halt_drains_inflight_runs(tmp_path):
    """Halt while a run is executing: the drain lets the run finish
    (code 0, output delivered) before the pool stops."""
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    c = DvmClient(uri)
    sid = c.attach(4)["sid"]
    res = {}

    def runner():
        res["resp"] = c.run(sid, SLOW_PROG, timeout=120)

    th = threading.Thread(target=runner)
    th.start()
    time.sleep(0.4)  # the run is inside its 1.5s sleep now
    h = DvmClient(uri)
    hr = h.halt()
    assert hr.get("ok"), hr
    h.close()
    th.join(timeout=60)
    assert res["resp"]["code"] == 0, res["resp"]
    assert "DONE" in res["resp"]["stdout"]
    with srv.lock:
        assert not srv.sessions, "halt left sessions resident"
    c.close()
    srv.stop()


def test_client_disconnect_mid_run_never_poisons_pool(pool):
    """ft_inject dvm_disconnect: a client that dies right after
    sending a run request leaves its job executing with no client.
    The pool must complete it, reap the orphaned session, and leave
    the pool and a concurrently-resident peer session untouched."""
    srv, uri = pool
    cb = DvmClient(uri)
    sb = cb.attach(4)["sid"]
    saved = _set({"ft_inject_plan": "dvm_disconnect:1",
                  "ft_inject_skip": 0})
    try:
        ca = DvmClient(uri)  # injector armed at construction
        sa = ca.attach(2)["sid"]
        with pytest.raises(DvmError, match="dvm_disconnect"):
            ca.run(sa, PROG, ["doomed"])
    finally:
        _restore(saved)
    # the peer session keeps working while the orphan unwinds
    rb = cb.run(sb, PROG, ["peer"], timeout=120)
    assert rb["code"] == 0, rb["stderr"][-2000:]
    assert "DIGEST peer " in rb["stdout"]
    # the pool notices the dead client and detaches its session
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        with srv.lock:
            n = len(srv.sessions)
        if n == 1:
            break
        time.sleep(0.05)
    assert n == 1, f"orphaned session never reaped ({n} resident)"
    cb.detach(sb)
    cb.close()


def test_failing_session_isolated(pool):
    """A program that raises poisons ONLY its own session: the run
    reports nonzero, the session is dead to further runs, and a peer
    session attached to the same pool keeps working."""
    srv, uri = pool
    bad = os.path.join(str(srv), "")  # not used; build a bad prog
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as f:
        f.write("import ompi_tpu\n"
                "comm = ompi_tpu.init()\n"
                "raise RuntimeError('boom rank %d' % comm.rank)\n")
        bad = f.name
    try:
        cb = DvmClient(uri)
        sb = cb.attach(4)["sid"]
        ca = DvmClient(uri)
        sa = ca.attach(2)["sid"]
        ra = ca.run(sa, bad, timeout=120)
        assert ra["code"] != 0
        assert "boom" in ra["stderr"]
        with pytest.raises(DvmError, match="dead"):
            ca.run(sa, PROG, ["again"])
        rb = cb.run(sb, PROG, ["peer"], timeout=120)
        assert rb["code"] == 0, rb["stderr"][-2000:]
        ca.detach(sa)
        cb.detach(sb)
        ca.close()
        cb.close()
    finally:
        os.unlink(bad)


def test_proctable_published_and_pruned(pool):
    """Resident sessions publish {uri}.proctable.json entries mapping
    rank -> pool pid + thread (ompi_tpu-attach --stacks target);
    detach prunes them."""
    srv, uri = pool
    c = DvmClient(uri)
    r = c.attach(3)
    with open(uri + ".proctable.json") as f:
        table = json.load(f)
    tags = {e["tag"] for e in table}
    assert "pool" in tags
    assert {f"s{r['sid']}:r{i}" for i in range(3)} <= tags
    assert all(e["pid"] == os.getpid() for e in table)
    assert all("thread" in e for e in table)
    from ompi_tpu.tools.attach import load_proctable
    assert {e["tag"] for e in load_proctable(
        uri + ".proctable.json")} == tags
    c.detach(r["sid"])
    c.close()
    with open(uri + ".proctable.json") as f:
        table2 = json.load(f)
    assert not any(e["tag"].startswith(f"s{r['sid']}:")
                   for e in table2)


def test_client_diagnostics(tmp_path):
    """The client must fail fast and friendly: missing uri-file, and
    the classic stale-uri-file (pool exited, file left behind) that
    used to hang forever on settimeout(None)."""
    with pytest.raises(DvmError, match="not found"):
        DvmClient(str(tmp_path / "nope.uri"))
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()  # nothing listens here any more
    stale = str(tmp_path / "stale.uri")
    with open(stale, "w") as f:
        f.write(f"127.0.0.1:{port}\n")
    with pytest.raises(DvmError, match="stale uri-file"):
        DvmClient(stale, connect_timeout=5)


def test_attach_pvars_and_jobid_counter(pool):
    """Attach latency feeds the dvm pvars, and inproc jobids ride a
    process-monotonic counter (the old ms-truncated time collided for
    jobs started within the same millisecond)."""
    from ompi_tpu.tools.dvm import _jobid_counter
    srv, uri = pool
    before = registry._pvars["dvm_attaches"].read()
    _run_once(uri, "pv", np_=2)
    assert registry._pvars["dvm_attaches"].read() == before + 1
    assert registry._pvars["dvm_attach_us_max"].read() > 0
    assert sum(registry._pvars["dvm_attach_hist"].read()) >= 1
    assert registry._pvars["dvm_sessions_peak"].read() >= 1
    ids = {f"dvm-{os.getpid()}-j{next(_jobid_counter)}"
           for _ in range(100)}
    assert len(ids) == 100  # same-millisecond jobs can never collide


def test_detach_requires_ownership(pool):
    """A connection may only detach sessions IT attached: a stranger
    guessing a small monotonic sid bounces, and the victim session
    keeps working."""
    srv, uri = pool
    ca = DvmClient(uri)
    sa = ca.attach(2)["sid"]
    cb = DvmClient(uri)
    with pytest.raises(DvmError, match="not attached"):
        cb.detach(sa)
    with srv.lock:
        assert sa in srv.sessions, "cross-client detach destroyed it"
    r = ca.run(sa, PROG, ["own"], timeout=120)
    assert r["code"] == 0, r["stderr"][-2000:]
    ca.detach(sa)
    ca.close()
    cb.close()


def test_detach_refused_while_running(pool):
    """_detach must not finalize/scrub a world whose rank-threads are
    mid-run (only drain and owner-death cleanup force through)."""
    srv, uri = pool
    c = DvmClient(uri)
    sid = c.attach(4)["sid"]
    res = {}

    def runner():
        res["r"] = c.run(sid, SLOW_PROG, timeout=120)

    th = threading.Thread(target=runner)
    th.start()
    time.sleep(0.4)  # the run is inside its sleep now
    with pytest.raises(DvmError, match="run in progress"):
        srv._detach(sid)
    th.join(timeout=60)
    assert res["r"]["code"] == 0, res["r"]
    assert "DONE" in res["r"]["stdout"]
    c.detach(sid)
    c.close()


def test_early_rank_exit_releases_run_boundary_fence(pool):
    """One rank exits nonzero EARLY; its peers finish the program
    later and only then reach the run-boundary fence.  The session's
    namespace abort must fail that late fence immediately — the abort
    sweep released nobody (no one was parked yet), and before the fix
    the fence re-registered and wedged the rank-threads, the
    session's capacity, and the client's run RPC forever."""
    srv, uri = pool
    import tempfile
    with tempfile.NamedTemporaryFile(
            "w", suffix=".py", delete=False) as f:
        f.write("import sys\nimport time\n"
                "import ompi_tpu\n"
                "comm = ompi_tpu.init()\n"
                "if comm.rank == 0:\n"
                "    sys.exit(3)\n"
                "time.sleep(1.0)\n")
        prog = f.name
    try:
        c = DvmClient(uri)
        sid = c.attach(2)["sid"]
        r = c.run(sid, prog, timeout=60)
        assert r["code"] == 3, r
        with pytest.raises(DvmError, match="dead"):
            c.run(sid, prog)
        c.detach(sid)
        c.close()
        with srv.lock:
            assert not srv.sessions, "session never released"
    finally:
        os.unlink(prog)


def test_same_jobid_submitted_twice_runs_exactly_once(pool):
    """The reconnect-with-replay idempotency contract (DESIGN.md
    §20): a resubmitted jobid whose run already completed is
    acknowledged from the session's replay memory — same exit code,
    replayed=True, and the program does NOT execute a second time
    (the cached reply carries no stdout; a re-run would)."""
    srv, uri = pool
    with DvmClient(uri) as c:
        sid = c.attach(2)["sid"]
        msg = {"op": "run", "sid": sid,
               "prog": os.path.abspath(PROG), "args": ["dedup"],
               "jobid": "t-dedup-1"}
        r1 = c._rpc(dict(msg))
        assert r1["code"] == 0 and not r1.get("replayed"), r1
        assert "DIGEST dedup " in r1["stdout"]
        r2 = c._rpc(dict(msg))
        assert r2.get("replayed") is True, r2
        assert r2["code"] == 0
        assert r2["stdout"] == ""
        c.detach(sid)


def test_journal_rehydration_reattach_and_run(tmp_path):
    """Crash recovery end to end in-process: a journal left behind by
    a dead incarnation (simulated by resurrecting the file a clean
    stop deleted) makes the next server rehydrate the session PARKED;
    the client reattaches by token on the NEW incarnation and runs —
    the session's identity survived the crash."""
    uri = str(tmp_path / "dvm.uri")
    srv = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    jpath = uri + ".journal.jsonl"
    c = DvmClient(uri)
    sid = c.attach(2)["sid"]
    token = c._tokens[sid]
    inc1 = c.incarnation
    assert inc1
    with open(jpath, "rb") as f:
        journal = f.read()   # open + quota + attach records
    c.close()                # NOT detach: the session was live
    srv.stop()               # clean stop deletes the journal...
    assert not os.path.exists(jpath)
    with open(jpath, "wb") as f:
        f.write(journal)     # ...resurrect it: a crash left this
    srv2 = DVMServer(4, devices=jax.devices(), uri_file=uri).start()
    try:
        assert srv2.rehydrated == 1
        c2 = DvmClient(uri)
        assert c2.incarnation and c2.incarnation != inc1
        r = c2.reattach(sid, token)
        assert r["ok"] and r["parked"], r
        resp = c2.run(sid, PROG, ["rehyd"], timeout=120)
        assert resp["code"] == 0, resp.get("stderr", "")[-2000:]
        assert "DIGEST rehyd " in resp["stdout"]
        c2.detach(sid)
        c2.close()
    finally:
        srv2.stop()


def test_reattach_bad_token_refused(pool):
    """A token mismatch is a FINAL verdict (the session belongs to
    someone else) — never a silent takeover."""
    srv, uri = pool
    with DvmClient(uri) as c:
        sid = c.attach(1)["sid"]
        with DvmClient(uri) as thief:
            with pytest.raises(DvmError, match="token"):
                thief.reattach(sid, "not-the-token")
        c.detach(sid)
