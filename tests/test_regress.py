"""Perf-regression sentry (bench.py --regress / benchmarks/regress):
headline parsing with the stdout-tail fallback, the noise-aware
tolerance model (flat history trips on a 20% drop, a history whose own
scatter dwarfs the drop does not), trajectory append semantics
(--dry appends nothing), exit codes, and the tier-1 smoke over the
repo's REAL BENCH_r* history — which must stay green."""

import json
import os
import subprocess
import sys

from benchmarks import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _round_doc(v):
    # read_const_us marks a measurement-valid round (headline_valid):
    # synthetic history models honest chained-methodology sweeps
    return {"parsed": {"value": v, "unit": "GB/s",
                       "read_const_us": 25.0}, "tail": ""}


def _write_rounds(d, values):
    for i, v in enumerate(values, start=1):
        with open(os.path.join(d, f"BENCH_r{i:02d}.json"), "w") as fh:
            json.dump(_round_doc(v), fh)


# -- parsing ----------------------------------------------------------------

def test_round_headline_tail_fallback():
    """A round whose driver-side parse failed (the r2 failure mode)
    still yields its headline from the captured stdout tail."""
    doc = {"parsed": {"value": None},
           "tail": 'noise\n{"metric": "x", "value": 42.5, '
                   '"unit": "GB/s"}\ntrailer'}
    assert regress.round_headline(doc) == 42.5
    assert regress.round_headline({"parsed": {}, "tail": ""}) is None
    # nonpositive values are a failed sweep, not a headline
    assert regress.round_headline(_round_doc(0.0)) is None


def test_load_rounds_sorted(tmp_path):
    _write_rounds(str(tmp_path), [10.0, 20.0, 30.0])
    rounds = regress.load_rounds(str(tmp_path))
    assert [n for n, _ in rounds] == [1, 2, 3]


# -- the noise model --------------------------------------------------------

def test_flat_history_trips_on_20pct_drop():
    f = regress.check_metric("headline_busbw_gbs", 59.5,
                             [74.4, 74.5, 74.3, 74.4])
    assert f is not None
    assert f["current"] == 59.5
    assert f["tolerance"] == 0.1           # the tight base band held


def test_flat_history_passes_small_wobble():
    assert regress.check_metric("headline_busbw_gbs", 71.0,
                                [74.4, 74.5, 74.3, 74.4]) is None


def test_noisy_history_widens_band():
    """Scatter like the repo's real history (74 -> 10 -> 12) must
    widen the tolerance: flagging a 'regression' smaller than the
    noise floor would be a lie."""
    assert regress.check_metric("headline_busbw_gbs", 11.0,
                                [74.4, 10.5, 12.3]) is None


def test_single_prior_sample_never_judges():
    assert regress.check_metric("headline_busbw_gbs", 1.0,
                                [74.4]) is None


def test_lower_is_better_absolute_band():
    # overhead pct: rising beyond median + band regresses
    f = regress.check_metric("trace_overhead_pct", 9.0, [1.0, 1.2, 0.8])
    assert f is not None and "ceiling" in f
    assert regress.check_metric("trace_overhead_pct", 2.5,
                                [1.0, 1.2, 0.8]) is None


# -- evaluate + trajectory --------------------------------------------------

def test_synthetic_regression_exits_nonzero(tmp_path):
    """The ISSUE acceptance case: flat history, newest round 20% down
    -> exit 1 with a finding on stderr-facing JSON."""
    _write_rounds(str(tmp_path), [74.4, 74.5, 74.3, 74.4, 59.5])
    detail = str(tmp_path / "BENCH_DETAIL.json")
    rc = regress.run_regress(str(tmp_path), detail, dry=True)
    assert rc == 1


def test_contaminated_rounds_excluded_from_headline_baseline(tmp_path):
    """Rounds whose d2h read constant shows the quiet gate failed
    (or that predate the chained methodology) fabricate busbw and must
    not anchor the baseline: with every history round invalid, a low
    but honestly-measured current round is NOT a regression."""
    import os as _os
    _write_rounds(str(tmp_path), [74.4, 74.5, 74.3, 74.4, 1.0])
    for i, rc_us in ((1, None), (2, 98766.7), (3, 90965.2), (4, None)):
        p = _os.path.join(str(tmp_path), f"BENCH_r{i:02d}.json")
        doc = json.load(open(p))
        if rc_us is None:
            del doc["parsed"]["read_const_us"]  # pre-methodology
        else:
            doc["parsed"]["read_const_us"] = rc_us  # contaminated
        json.dump(doc, open(p, "w"))
        assert not regress.headline_valid(doc)
    assert regress.headline_valid(_round_doc(1.0))
    detail = str(tmp_path / "BENCH_DETAIL.json")
    rc = regress.run_regress(str(tmp_path), detail, dry=True)
    assert rc == 0


def test_green_history_exits_zero_and_appends(tmp_path):
    _write_rounds(str(tmp_path), [74.4, 74.5, 74.3, 74.2])
    detail = str(tmp_path / "BENCH_DETAIL.json")
    with open(detail, "w") as fh:
        json.dump({"trace_overhead": {"overhead_pct": 1.0}}, fh)
    rc = regress.run_regress(str(tmp_path), detail, dry=False)
    assert rc == 0
    doc = json.loads(open(detail).read())
    traj = doc["regress_trajectory"]
    assert len(traj) == 1
    assert traj[0]["round"] == 4
    assert traj[0]["metrics"]["headline_busbw_gbs"] == 74.2
    assert traj[0]["metrics"]["trace_overhead_pct"] == 1.0
    # other sections survive the read-modify-write
    assert doc["trace_overhead"]["overhead_pct"] == 1.0


def test_dry_appends_nothing(tmp_path):
    _write_rounds(str(tmp_path), [74.4, 74.5, 74.3])
    detail = str(tmp_path / "BENCH_DETAIL.json")
    with open(detail, "w") as fh:
        json.dump({}, fh)
    assert regress.run_regress(str(tmp_path), detail, dry=True) == 0
    assert "regress_trajectory" not in json.loads(open(detail).read())


def test_probe_metric_regression_via_trajectory(tmp_path):
    """Probe metrics compare against the recorded trajectory, not the
    BENCH_r files: a segring busbw collapse trips the sentry."""
    _write_rounds(str(tmp_path), [74.4, 74.5, 74.3])
    detail = str(tmp_path / "BENCH_DETAIL.json")
    traj = [{"round": i, "metrics":
             {"pipeline_segring_busbw_gbs": 10.0 + 0.1 * i}}
            for i in range(3)]
    with open(detail, "w") as fh:
        json.dump({"regress_trajectory": traj,
                   "probe_pipeline": {"busbw_gbs": {
                       "segring": {"65536": 2.0, "262144": 3.0}}}}, fh)
    rc = regress.run_regress(str(tmp_path), detail, dry=True)
    assert rc == 1                          # 3.0 << 10.x median


def test_no_history_is_config_error(tmp_path):
    assert regress.run_regress(
        str(tmp_path), str(tmp_path / "BENCH_DETAIL.json"),
        dry=True) == 2


def test_trajectory_capped(tmp_path):
    detail = str(tmp_path / "BENCH_DETAIL.json")
    with open(detail, "w") as fh:
        json.dump({"regress_trajectory":
                   [{"round": i, "metrics": {}}
                    for i in range(regress.TRAJECTORY_CAP)]}, fh)
    regress.append_trajectory(detail, {"round": 999, "metrics": {}})
    traj = json.loads(open(detail).read())["regress_trajectory"]
    assert len(traj) == regress.TRAJECTORY_CAP
    assert traj[-1]["round"] == 999


# -- the tier-1 smoke over the real repo history ----------------------------

def test_bench_regress_dry_smoke_real_history():
    """``bench.py --regress --dry`` over the repo's own BENCH_r*
    history: parses, judges, appends nothing, and stays GREEN — the
    real history's scatter is noise, not a regression (the ISSUE
    acceptance bar)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--regress", "--dry"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    line = json.loads(p.stdout.strip().splitlines()[-1])
    assert line["unit"] == "regressions"
    assert line["value"] == 0
    assert line["dry"] is True
