"""Launcher + process-rank integration tests (ref: the reference's
orte/test/mpi programs run under mpirun: hello, ring, connectivity,
abort/exit-code propagation)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mpirun(np, prog, *args, mca=(), timeout=90):
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", str(np)]
    for k, v in mca:
        cmd += ["--mca", k, v]
    cmd += [os.path.join(REPO, "examples", prog), *args]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # keep subprocess JAX off the TPU: examples never touch devices
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(cmd, capture_output=True, timeout=timeout,
                          env=env, cwd=REPO)


def test_hello():
    r = mpirun(3, "hello.py")
    assert r.returncode == 0, r.stderr.decode()
    out = r.stdout.decode()
    for k in range(3):
        assert f"I am {k} of 3" in out


def test_ring():
    r = mpirun(4, "ring.py")
    assert r.returncode == 0, r.stderr.decode()
    assert "received token 7 from 3" in r.stdout.decode()


def test_connectivity_shm():
    r = mpirun(3, "connectivity.py")
    assert r.returncode == 0, r.stderr.decode()
    assert "PASSED" in r.stdout.decode()


def test_connectivity_tcp_only():
    r = mpirun(3, "connectivity.py", mca=(("btl", "self,tcp"),))
    assert r.returncode == 0, r.stderr.decode()
    assert "PASSED" in r.stdout.decode()


def test_abort_propagates_exit_code():
    r = mpirun(3, "abort_test.py")
    assert r.returncode == 42
    assert "MPI_Abort" in r.stderr.decode()
    assert "should not reach here" not in r.stdout.decode()


def test_osu_allreduce_runs():
    r = mpirun(2, "osu_allreduce.py", "4,65536")
    assert r.returncode == 0, r.stderr.decode()
    assert "bytes" in r.stdout.decode()


def test_mca_param_flows_to_children():
    # ring still works when forced into tiny rendezvous segments
    r = mpirun(2, "osu_allreduce.py", "65536",
               mca=(("btl_shm_eager_limit", "1024"),
                    ("btl_shm_max_send_size", "4096")))
    assert r.returncode == 0, r.stderr.decode()


def test_singleton_init():
    """ompi_tpu.init() without a launcher = 1-rank world."""
    code = ("import ompi_tpu, numpy as np\n"
            "from ompi_tpu.op import op\n"
            "c = ompi_tpu.init()\n"
            "assert c.size == 1 and c.rank == 0\n"
            "x = np.ones(4, np.float32); r = np.empty_like(x)\n"
            "c.Allreduce(x, r, op.SUM)\n"
            "assert r[0] == 1.0\n"
            "c.Barrier()\n"
            "ompi_tpu.finalize()\n"
            "print('singleton ok')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       timeout=60, env=env)
    assert r.returncode == 0, r.stderr.decode()
    assert b"singleton ok" in r.stdout


def test_job_timeout():
    """--timeout kills a hung job with exit 124."""
    code = "import time; time.sleep(60)"
    import tempfile
    with tempfile.NamedTemporaryFile("w", suffix=".py", dir="/tmp",
                                     delete=False) as f:
        f.write(code)
        path = f.name
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "ompi_tpu.tools.mpirun", "-np", "2",
         "--timeout", "3", path],
        capture_output=True, timeout=60, env=env, cwd=REPO)
    os.unlink(path)
    assert r.returncode == 124


def test_kv_server_refuses_unauthenticated_connection():
    """sec/basic analog (VERDICT r3 #9): the per-job secret gates the
    KV control plane — a connection without (or with a wrong) secret
    is refused, one with the right secret proceeds."""
    import os
    import socket as sk

    from ompi_tpu.runtime import kvstore

    old = os.environ.get("TPUMPI_JOB_SECRET")
    os.environ["TPUMPI_JOB_SECRET"] = "s3cr3t-for-test"
    try:
        server = kvstore.KVServer(1)
        host, port = server.addr.rsplit(":", 1)

        # no hello at all: first op is rejected
        s = sk.create_connection((host, int(port)), timeout=10)
        kvstore._send_msg(s, {"op": "put", "key": "k", "value": 1})
        resp = kvstore._recv_msg(s)
        assert resp == {"error": "unauthenticated"}, resp
        s.close()

        # wrong secret
        s = sk.create_connection((host, int(port)), timeout=10)
        kvstore._send_msg(s, {"op": "hello", "secret": "wrong"})
        resp = kvstore._recv_msg(s)
        assert resp == {"error": "unauthenticated"}, resp
        s.close()

        # the real client authenticates from the env and works
        c = kvstore.KVClient(server.addr)
        c.put("k", 42)
        assert c.get("k") == 42

        # server data was never touched by the rejected writes
        assert server.data.get("k") == 42
    finally:
        if old is None:
            os.environ.pop("TPUMPI_JOB_SECRET", None)
        else:
            os.environ["TPUMPI_JOB_SECRET"] = old


def test_dvm_warm_pool_second_job_faster(tmp_path):
    """Persistent DVM (orte-dvm analog, VERDICT r4 missing #3): start
    the pool once, submit the same job twice via mpirun --dvm.  The
    second job rides the warm jax runtime + compiled-collective cache
    and its time-to-first-collective must be >=5x faster."""
    import re
    import subprocess
    import time as _time

    uri = str(tmp_path / "dvm.uri")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=4")
    srv = subprocess.Popen(
        [sys.executable, "-m", "ompi_tpu.tools.dvm", "--np", "4",
         "--uri-file", uri], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        deadline = _time.monotonic() + 60
        while not os.path.exists(uri):
            assert _time.monotonic() < deadline, "DVM never came up"
            assert srv.poll() is None, "DVM died during startup"
            _time.sleep(0.1)

        prog = os.path.join(REPO, "tests", "_dvm_prog.py")

        def submit():
            r = subprocess.run(
                [sys.executable, "-m", "ompi_tpu.tools.mpirun",
                 "--dvm", uri, "-np", "4", prog],
                capture_output=True, env=env, timeout=180)
            assert r.returncode == 0, r.stderr.decode()[-1500:]
            m = re.search(rb"first_coll_s=([0-9.]+)", r.stdout)
            assert m, r.stdout.decode()[-500:]
            return float(m.group(1))

        t1 = submit()
        t2 = submit()
        assert t2 <= t1 / 5, \
            f"warm job not faster: cold={t1:.3f}s warm={t2:.3f}s"
    finally:
        subprocess.run([sys.executable, "-m", "ompi_tpu.tools.dvm",
                        "--halt", uri], env=env, timeout=30)
        try:
            srv.wait(timeout=10)
        except subprocess.TimeoutExpired:
            srv.kill()


def test_kv_fence_after_ns_abort_fails_fast():
    """A rank arriving at a fence AFTER its namespace was aborted must
    get the abort error immediately: the abort sweep only releases
    waiters ALREADY parked, and a late arrival that re-registered the
    fence would hang its client forever (KVClient sockets have no read
    timeout).  Scoping — a peer namespace stays live, and a global
    abort poisons late fences in every namespace."""
    import time

    from ompi_tpu.runtime import kvstore

    server = kvstore.KVServer(2)
    try:
        a = kvstore.KVClient(server.addr, ns="sA")
        b = kvstore.KVClient(server.addr, ns="sB")
        a.abort(0, 3, "early exit")
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="aborted by rank 0"):
            a.fence("f1", n=2)
        assert time.monotonic() - t0 < 5, "late fence was parked"
        # untagged late arrival: the scope is recovered from the
        # ns-prefixed fence id
        raw = kvstore.KVClient(server.addr)
        with pytest.raises(RuntimeError, match="aborted"):
            raw.fence("sA/f2", n=2)
        # the peer namespace is unaffected: its 1-deep fence completes
        b.fence("g1", n=1)
        # a global abort fails late fences of EVERY namespace
        raw.abort(0, 1, "global down")
        with pytest.raises(RuntimeError, match="aborted"):
            b.fence("g2", n=2)
        for c in (a, b, raw):
            c.close()
    finally:
        server.close()
