"""Benchmark entry: the full BASELINE.md suite.

Device path (coll/tpu on a multi-chip mesh, coll/hbm stacked on the
single CI chip) versus the software baseline (coll/tuned over the
self,shm,tcp btl stack on process-ranks under mpirun — shm
participates so the baseline is the strongest local software path,
per the r2 verdict) across:

  * OSU allreduce, power-of-2 sweep 4 B – 256 MiB (BASELINE config 3)
  * OSU bcast (config 2), OSU alltoall (config 4)
  * Reduce_scatter_block MPI_MAX / MPI_DOUBLE via derived vector
    datatype (config 5; device side reduces float32, noted in table)

Prints the comparison table + the north-star verdict ("beat
tuned-over-TCP latency at all sizes >= 4 KiB") on stderr, ONE small
(<=1 KB) JSON line on stdout for the driver, and the full sweeps to
BENCH_DETAIL.json next to this file (the r2 failure mode was the
full-sweep stdout line outgrowing the driver's tail capture —
"parsed": null).  Soft wall-clock budgets truncate the largest sizes
rather than blowing a driver timeout; truncation is reported, never
silent.  Device timings use the forced-completion methodology of
benchmarks/device_sweep.py (block_until_ready is a no-op on the
tunneled backend) and pass a bandwidth<=HBM-peak sanity gate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

MIB = 1024 * 1024
NRANKS = 8
HEADLINE_BYTES = 8 * MIB  # keep the r1 headline metric comparable


def busbw_gbs(nbytes: int, us: float) -> float:
    """OSU allreduce bus bandwidth: 2(P-1)/P * n / t."""
    return 2 * (NRANKS - 1) / NRANKS * nbytes / (us * 1e-6) / 1e9


def run_software_sweep(caps: dict, budget_s: float,
                       mca: tuple = (("btl", "self,shm,tcp"),),
                       start: int = 4) -> dict:
    """A software sweep under mpirun.  The default MCA set is the
    STRONGEST software path (seg segments + shm rings); the
    tuned-over-TCP configuration of BASELINE.md's north star is a
    second call with seg/sm disabled and tcp only."""
    repo = os.path.dirname(os.path.abspath(__file__))
    cmd = [sys.executable, "-m", "ompi_tpu.tools.mpirun",
           "-np", str(NRANKS)]
    for k, v in mca:
        cmd += ["--mca", k, v]
    cmd += [os.path.join(repo, "benchmarks", "osu_sweep.py"),
            "--max-ar", str(caps["ar"]), "--max-bcast", str(caps["bcast"]),
            "--max-a2a", str(caps["a2a"]), "--max-rsb", str(caps["rsb"]),
            "--start", str(start),
            "--budget", str(budget_s)]
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(cmd, capture_output=True, env=env,
                       timeout=budget_s * 2 + 300)
    if r.returncode != 0:
        raise RuntimeError(
            f"software sweep failed rc={r.returncode}: "
            f"{r.stderr.decode()[-400:]}")
    for line in reversed(r.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError("software sweep produced no JSON")


def fmt_table(dev: dict, sw: dict) -> str:
    """Side-by-side latency table + north-star verdict, per coll."""
    lines = []
    pairs = [("allreduce", "allreduce"), ("bcast", "bcast"),
             ("alltoall", "alltoall"),
             ("reduce_scatter", "reduce_scatter_block_vector")]
    for dkey, skey in pairs:
        d = {k: v for k, v in dev.get(dkey, {}).items()
             if k != "truncated"}
        s = {k: v for k, v in sw.get(skey, {}).items()
             if k != "truncated"}
        lines.append(f"--- {dkey} (device)  vs  {skey} (sw shm+tcp) ---")
        lines.append(f"{'bytes':>12} {'dev_us':>12} {'sw_us':>12} "
                     f"{'speedup':>9} {'dev_busbw':>12}")
        for k in sorted(set(d) | set(s), key=int):
            nbytes = int(k)
            du = d.get(k)
            su = s.get(k)
            ratio = f"{su / du:8.2f}x" if du and su else "        -"
            if du and dkey == "allreduce":
                bb = f"{busbw_gbs(nbytes, du):9.2f} GB/s"
            else:
                bb = "          -"
            lines.append(
                f"{nbytes:>12} "
                f"{du if du is not None else '-':>12} "
                f"{su if su is not None else '-':>12} {ratio} {bb}")
    return "\n".join(lines)


def northstar(dev_ar: dict, sw_ar: dict):
    """Per-size >=4KiB latency verdict vs the software path."""
    verdict = {}
    for k in sorted(set(dev_ar) & set(sw_ar), key=lambda x: int(x)
                    if x != "truncated" else 0):
        if k == "truncated" or int(k) < 4096:
            continue
        if dev_ar[k] is None or sw_ar[k] is None:
            continue  # unmeasurable point (deadline-hit): no verdict
        verdict[k] = bool(dev_ar[k] <= sw_ar[k])
    return verdict, bool(verdict) and all(verdict.values())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="Tiny sizes for development runs")
    ap.add_argument("--dev-budget", type=float, default=480.0)
    ap.add_argument("--sw-budget", type=float, default=300.0)
    ap.add_argument("--probe-dispatch", action="store_true",
                    help="Measure the per-op dispatch constant, the "
                         "device-vs-host crossover per collective, and "
                         "the fusion amortization ratio; persist under "
                         "'probe_dispatch' in BENCH_DETAIL.json and "
                         "refresh the coll/calibrate profile")
    ap.add_argument("--trace-overhead", action="store_true",
                    help="Measure small-message latency with span "
                         "tracing off vs on (interleaved reps), "
                         "snapshot the latency-histogram pvars, "
                         "persist under 'trace_overhead' in "
                         "BENCH_DETAIL.json, and FAIL (exit 1) if the "
                         "traced path costs more than 5%%")
    ap.add_argument("--probe-pipeline", action="store_true",
                    help="Measure the large-message busbw curve per "
                         "device algorithm (fused / segmented ring / "
                         "recursive doubling / hierarchical); persist "
                         "under 'probe_pipeline' in BENCH_DETAIL.json "
                         "and refresh the coll/calibrate profile's "
                         "segmented/hierarchical crossovers")
    ap.add_argument("--pipeline-max-bytes", type=int, default=None,
                    help="Cap the --probe-pipeline size ladder (the "
                         "full 256 MiB curve needs real accelerator "
                         "memory; the default fits a CI box)")
    ap.add_argument("--probe-recovery", action="store_true",
                    help="Measure the ULFM forward-recovery pipeline "
                         "(kill -> ERR_PROC_FAILED detect -> shrink -> "
                         "first survivor collective) and the healthy-"
                         "path cost of the ULFM entry checks on vs "
                         "off; persist under 'probe_recovery' in "
                         "BENCH_DETAIL.json, and FAIL (exit 1) if the "
                         "on path costs more than 5%%")
    ap.add_argument("--probe-respawn", action="store_true",
                    help="Measure the self-healing respawn MTTR (kill "
                         "-> detect -> respawn/rejoin -> buddy restore "
                         "-> first full-size collective) and the "
                         "degree-0 cost of the buddy.checkpoint call; "
                         "persist under 'probe_respawn' in "
                         "BENCH_DETAIL.json, and FAIL (exit 1) if the "
                         "off-call costs more than 5%%")
    ap.add_argument("--probe-ckpt", action="store_true",
                    help="Measure the tiered checkpoint engine: "
                         "checkpoint stall, steady-state overhead of "
                         "the checkpointing loop, fs restore "
                         "bandwidth, and buddy-vs-filesystem MTTR at "
                         "two state sizes; persist under 'probe_ckpt' "
                         "in BENCH_DETAIL.json, and FAIL (exit 1) if "
                         "the steady-state overhead exceeds 5%%")
    ap.add_argument("--probe-serve", action="store_true",
                    help="Measure the multiplexed DVM service plane: "
                         "warm session-attach latency vs a cold "
                         "mpirun launch, and sustained jobs/sec with "
                         "p50/p99 under concurrent submitters; "
                         "persist under 'probe_serve' in "
                         "BENCH_DETAIL.json, and FAIL (exit 1) if a "
                         "warm attach is not at least 10x faster "
                         "than the cold launch")
    ap.add_argument("--probe-fleet", action="store_true",
                    help="Measure the overload-robust serving control "
                         "plane: high-priority p99 under 2x overload "
                         "vs unloaded (preemption + deadline "
                         "shedding), checkpoint-resume byte-identity "
                         "of a preempted run, and live pool resize "
                         "under traffic with zero failed jobs and "
                         "exact per-band pvar sums, plus the N-host "
                         "mode: a 2-host fleet of real tpud agents "
                         "survives a whole-host SIGKILL mid-collective "
                         "(host_kill_mttr_ms, zero failed jobs under "
                         "host-granularity resize); persist under "
                         "'probe_fleet' in BENCH_DETAIL.json, and "
                         "FAIL (exit 1) if any invariant breaks")
    ap.add_argument("--probe-rma", action="store_true",
                    help="Measure one-sided RMA for BOTH osc "
                         "components (device vs pt2pt host-AM): "
                         "OSU-style put/get busbw ladders, accumulate "
                         "rate and fetch_and_op latency; persist "
                         "under 'probe_rma' in BENCH_DETAIL.json, "
                         "and FAIL (exit 1) if device put/get busbw "
                         "is not >=5x pt2pt at the 1 MiB tier")
    ap.add_argument("--probe-ctrlplane", action="store_true",
                    help="Chaos-close the control plane: kill the KV "
                         "primary mid-fence (standby promotion must "
                         "complete the fence) and hard-kill the DVM "
                         "server mid-run (journal rehydration + "
                         "jobid-idempotent replay), both under a "
                         "4-session concurrent workload; persist "
                         "under 'probe_ctrlplane' in "
                         "BENCH_DETAIL.json, and FAIL (exit 1) on "
                         "any failed job or hung worker")
    ap.add_argument("--probe-grayfail", action="store_true",
                    help="Chaos-close the gray-failure plane: a "
                         "2-host pool with one slow-but-alive host "
                         "(slow beats + 10x-stalled resident ranks) "
                         "must detect, quarantine and migrate around "
                         "it — mitigated goodput >= 2x unmitigated, "
                         "MTTM <= 4x the health tick, zero false "
                         "quarantines on a healthy fleet, zero "
                         "failed jobs; persist under 'probe_grayfail' "
                         "in BENCH_DETAIL.json, and FAIL (exit 1) if "
                         "any gate breaks")
    ap.add_argument("--probe-sdc", action="store_true",
                    help="Chaos-close the silent-data-corruption "
                         "plane: a fully-checked device mesh with a "
                         "flip-every-op corrupting rank (detection "
                         "rate must be 1.0, conviction pinned to the "
                         "victim chip, every retried result "
                         "byte-exact), a clean armed arm (zero false "
                         "positives), and a live 2-host pool where "
                         "one conviction must quarantine the "
                         "corrupting host within the MTTQ budget "
                         "with zero failed jobs; persist under "
                         "'probe_sdc' in BENCH_DETAIL.json, and FAIL "
                         "(exit 1) if any gate breaks")
    ap.add_argument("--rma-max-bytes", type=int, default=None,
                    help="Cap the --probe-rma size ladder (the full "
                         "64 MiB curve wants real accelerator "
                         "memory; the default fits a CI box)")
    ap.add_argument("--regress", action="store_true",
                    help="Perf-regression sentry: pure file analysis "
                         "of the BENCH_r*/BENCH_DETAIL history (no "
                         "probes run) with noise-aware tolerances; "
                         "appends a trajectory row to "
                         "BENCH_DETAIL.json and exits 1 on a "
                         "regression, 2 on unusable history")
    ap.add_argument("--dry", action="store_true",
                    help="With --regress: evaluate and report but "
                         "append nothing (the tier-1 history-parsing "
                         "smoke)")
    ap.add_argument("--bench-dir", default=None,
                    help="With --regress: directory holding the "
                         "BENCH_r*.json history (default: this "
                         "file's directory)")
    ap.add_argument("--probe-obs", action="store_true",
                    help="Measure the telemetry plane: scrape-tick "
                         "overhead on the progress sweep (interleaved "
                         "on/off blocks at a 1 ms interval), exact "
                         "per-session attribution under 4 concurrent "
                         "DVM sessions, and the flight-recorder "
                         "round-trip through attach --events and a "
                         "traceview merge; persist under 'probe_obs' "
                         "in BENCH_DETAIL.json, and FAIL (exit 1) if "
                         "the median overhead exceeds 5%% or either "
                         "truth check breaks")
    ap.add_argument("--probe-reqtrace", action="store_true",
                    help="Measure request-scoped tracing + the hang "
                         "doctor: a 4-session Poisson workload on a "
                         "2-host pool whose traceview --job waterfalls "
                         "must match the client-paid wall within 10%%, "
                         "a rdv_sever-wedged job the doctor must "
                         "diagnose (absent rank + rendezvous) within "
                         "2x obs_watchdog_ms, and the per-op req_mark "
                         "overhead arm (5%% budget); persist under "
                         "'probe_reqtrace' in BENCH_DETAIL.json, FAIL "
                         "(exit 1) if any gate breaks")
    opts = ap.parse_args()

    detail_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json")

    if opts.regress:
        from benchmarks.regress import run_regress

        bench_dir = opts.bench_dir or os.path.dirname(
            os.path.abspath(__file__))
        if opts.bench_dir:
            detail_path = os.path.join(bench_dir, "BENCH_DETAIL.json")
        sys.exit(run_regress(bench_dir, detail_path, dry=opts.dry))

    if opts.probe_dispatch:
        from benchmarks.probe_dispatch import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        fused = probe.get("fused", {})
        line = {
            "metric": "probe_dispatch fused batch of "
                      f"{fused.get('batch_ops', 0)} x "
                      f"{fused.get('payload_bytes', 0)} B allreduce "
                      "vs single-op dispatch constant",
            "value": fused.get("ratio_vs_single"),
            "unit": "x_single_op",
            "meets_3x_target": fused.get("meets_3x_target"),
            "dispatch_us": probe["dispatch_us"],
            "crossover_bytes": probe["crossover_bytes"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        out = json.dumps(line)
        if len(out) > 1024:
            line.pop("crossover_bytes", None)
            out = json.dumps(line)
        print(out)
        return

    if opts.trace_overhead:
        from benchmarks.trace_overhead import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        line = {
            "metric": f"trace overhead, {probe['nranks']} ranks x "
                      f"{probe['payload_bytes']} B allreduce "
                      f"(median-of-{probe['blocks_per_side']} "
                      f"interleaved in-world blocks)",
            "value": probe["overhead_pct"],
            "unit": "pct_vs_untraced",
            "overhead_pct_best": probe["overhead_pct_best"],
            "off_us_median": probe["off_us_median"],
            "on_us_median": probe["on_us_median"],
            "off_us_per_op": probe["off_us_per_op"],
            "on_us_per_op": probe["on_us_per_op"],
            "host_cores": probe["host_cores"],
            "gil_enabled": probe["gil_enabled"],
            "phase_overhead_pct": probe["phase_overhead_pct"],
            "phase_within_budget": probe["phase_within_budget"],
            "reqtrace_overhead_pct": probe["reqtrace_overhead_pct"],
            "reqtrace_within_budget": probe["reqtrace_within_budget"],
            "integrity_overhead_pct": probe["integrity_overhead_pct"],
            "integrity_within_budget": probe["integrity_within_budget"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"] or \
                not probe["phase_within_budget"] or \
                not probe["reqtrace_within_budget"] or \
                not probe["integrity_within_budget"]:
            # the acceptance contract: >5% MEDIAN tracing overhead is
            # a regression, and it fails LOUDLY, never as a footnote
            # (best-of is reported for context but never gates); the
            # phase profiler, per-op request tagging and the armed
            # sdc-integrity plane ride the SAME budget
            sys.stderr.write(
                f"FAIL: median tracing overhead "
                f"{probe['overhead_pct']}% / phase overhead "
                f"{probe['phase_overhead_pct']}% / reqtrace overhead "
                f"{probe['reqtrace_overhead_pct']}% / integrity "
                f"overhead {probe['integrity_overhead_pct']}% exceeds "
                f"the {probe['budget_pct']}% budget\n")
            sys.exit(1)
        return

    if opts.probe_pipeline:
        from benchmarks.probe_pipeline import (DEFAULT_MAX_BYTES,
                                               persist, run_probe)

        probe = run_probe(
            max_bytes=opts.pipeline_max_bytes or DEFAULT_MAX_BYTES)
        notes = persist(probe, detail_path)
        top = str(probe["sizes"][-1])
        line = {
            "metric": f"probe_pipeline allreduce busbw, "
                      f"{probe['nranks']} ranks, {top} B top size",
            "value": {a: probe["busbw_gbs"][a].get(top)
                      for a in probe["busbw_gbs"]},
            "unit": "GB/s_busbw",
            "seg_crossover_bytes": probe["seg_crossover_bytes"],
            "hier_min_bytes": probe["hier_min_bytes"],
            "segments_rank0": probe["segments_rank0"],
            "plan_builds": sum(
                c.get("builds", 0)
                for alg in (probe.get("plan_cache") or {}).values()
                for c in alg.values()),
            "plan_hits": sum(
                c.get("hits", 0)
                for alg in (probe.get("plan_cache") or {}).values()
                for c in alg.values()),
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        return

    if opts.probe_rma:
        from benchmarks.probe_rma import (DEFAULT_MAX_BYTES, persist,
                                          run_probe)

        probe = run_probe(
            max_bytes=opts.rma_max_bytes or DEFAULT_MAX_BYTES)
        notes = persist(probe, detail_path)
        mib = str(1 << 20)
        comps = probe["components"]
        line = {
            "metric": f"osc put/get busbw at 1 MiB, "
                      f"{probe['nranks']} ranks, device vs pt2pt",
            "value": {c: {"put": comps[c]["put_busbw_gbs"].get(mib),
                          "get": comps[c]["get_busbw_gbs"].get(mib)}
                      for c in comps},
            "unit": "GB/s_busbw",
            "put_ratio": probe["put_ratio_device_over_pt2pt"].get(mib),
            "get_ratio": probe["get_ratio_device_over_pt2pt"].get(mib),
            "device_5x_at_1mib": probe["device_5x_at_1mib"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["device_5x_at_1mib"]:
            # the ISSUE acceptance gate: a device-memory window must
            # beat the host-AM component where it claims to
            sys.stderr.write(
                "FAIL: device osc busbw is not >=5x pt2pt at the "
                "1 MiB tier\n")
            sys.exit(1)
        return

    if opts.probe_recovery:
        from benchmarks.probe_recovery import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        line = {
            "metric": f"ulfm recovery, {probe['nranks']} ranks, kill "
                      f"rank {probe['victim']} mid-allreduce "
                      f"(best-of-{probe['reps']})",
            "value": probe["total_ms"],
            "unit": "ms_kill_to_first_survivor_coll",
            "detect_ms": probe["detect_ms"],
            "shrink_ms": probe["shrink_ms"],
            "first_coll_ms": probe["first_coll_ms"],
            "entry_check_overhead_pct": probe["overhead_pct"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            # same acceptance contract as --trace-overhead: resilience
            # must be near-free when nothing fails
            sys.stderr.write(
                f"FAIL: ULFM entry-check overhead "
                f"{probe['overhead_pct']}% exceeds the "
                f"{probe['budget_pct']}% budget\n")
            sys.exit(1)
        return

    if opts.probe_respawn:
        from benchmarks.probe_respawn import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        line = {
            "metric": f"respawn MTTR, {probe['nranks']} ranks, kill "
                      f"rank {probe['victim']} mid-allreduce "
                      f"(best-of-{probe['reps']})",
            "value": probe["total_ms"],
            "unit": "ms_kill_to_first_full_size_coll",
            "detect_ms": probe["detect_ms"],
            "respawn_ms": probe["respawn_ms"],
            "restore_ms": probe["restore_ms"],
            "first_coll_ms": probe["first_coll_ms"],
            "buddy_off_overhead_pct": probe["overhead_pct"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            # same acceptance contract as the other probes: buddy
            # replication must be FREE when it is off
            sys.stderr.write(
                f"FAIL: degree-0 buddy.checkpoint overhead "
                f"{probe['overhead_pct']}% exceeds the "
                f"{probe['budget_pct']}% budget\n")
            sys.exit(1)
        return

    if opts.probe_ckpt:
        from benchmarks.probe_ckpt import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        small = probe["sizes"]["64KiB"]
        big = probe["sizes"]["2MiB"]
        line = {
            "metric": f"tiered ckpt, {probe['nranks']} ranks, "
                      f"async fs tier (best-of-{probe['reps']})",
            "value": probe["worst_steady_overhead_pct"],
            "unit": "pct_steady_state_overhead",
            "stall_small_ms": small["stall_max_ms"],
            "stall_big_ms": big["stall_max_ms"],
            "fs_restore_MBps_big": big["fs_restore_MBps"],
            "mttr_buddy_ms": small["mttr_buddy"]["total_ms"],
            "mttr_fs_ms": small["mttr_fs"]["total_ms"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            # the async tier's contract: the drain hides behind the
            # application's own collectives
            sys.stderr.write(
                f"FAIL: steady-state checkpoint overhead "
                f"{probe['worst_steady_overhead_pct']}% exceeds the "
                f"{probe['budget_pct']}% budget\n")
            sys.exit(1)
        return

    if opts.probe_serve:
        from benchmarks.probe_serve import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        line = {
            "metric": f"dvm serve plane, np {probe['np']} warm attach "
                      f"vs cold mpirun + {probe['submitters']} "
                      "concurrent submitters",
            "value": probe["attach_med_ms"],
            "unit": "ms_warm_attach_median",
            "cold_launch_s": probe["cold_launch_s"],
            "attach_speedup_vs_cold": probe["attach_speedup_vs_cold"],
            "jobs_per_s": probe["jobs_per_s"],
            "job_p50_ms": probe["job_p50_ms"],
            "job_p99_ms": probe["job_p99_ms"],
            "compiled_cache_hits": probe["compiled_cache_hits"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            # the service-plane contract: attaching a warm session
            # must be an order of magnitude below a cold launch
            sys.stderr.write(
                f"FAIL: warm attach {probe['attach_med_ms']} ms is "
                f"not {probe['cold_factor']:.0f}x below the cold "
                f"launch {probe['cold_launch_s']} s\n")
            sys.exit(1)
        return

    if opts.probe_fleet:
        from benchmarks.probe_fleet import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        ov, pr, rz = (probe["overload"], probe["preempt_resume"],
                      probe["resize"])
        ho = probe["hosts"]
        line = {
            "metric": f"dvm fleet control plane, "
                      f"{ov['low_submitters']}x np{ov['low_np']} "
                      f"overload vs np{ov['hi_np']} priority burst + "
                      f"preempt-resume + live resize + "
                      f"{ho['hosts']}-host chaos",
            "value": ov["hi_p99_vs_unloaded"],
            "unit": "hi_p99_vs_unloaded_ratio",
            "hi_p99_ms": ov["hi_p99_ms"],
            "unloaded_p99_ms": ov["unloaded_p99_ms"],
            "preemptions": ov["preemptions"],
            "sheds": ov["sheds"],
            "low_jobs_done": ov["low_jobs_done"],
            "low_jobs_shed": ov["low_jobs_shed"],
            "resume_ok": pr["resume_ok"],
            "resumed_at_step": pr["resumed_at_step"],
            "resize_ok": rz["resize_ok"],
            "band_sums_exact": rz["band_sums_exact"],
            "hosts": ho["hosts"],
            "host_kill_mttr_ms": ho["host_kill_mttr_ms"],
            "host_jobs_failed": ho["traffic_jobs_failed"],
            "hosts_ok": ho["hosts_ok"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            sys.stderr.write(
                f"FAIL: fleet probe — priority_ok="
                f"{ov['priority_ok']} (p99 ratio "
                f"{ov['hi_p99_vs_unloaded']}x vs "
                f"{ov['priority_factor']}x budget), resume_ok="
                f"{pr['resume_ok']}, resize_ok={rz['resize_ok']}, "
                f"hosts_ok={ho['hosts_ok']}\n")
            sys.exit(1)
        return

    if opts.probe_grayfail:
        from benchmarks.probe_grayfail import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        mit = probe["mitigated"]
        line = {
            "metric": f"gray-failure plane, {probe['hosts']}-host "
                      f"pool with one {probe['slow_factor']}x-slowed "
                      f"host: detect + quarantine + migrate",
            "value": probe["goodput_ratio"],
            "unit": "mitigated_vs_unmitigated_goodput",
            "mttm_ms": probe["mttm_ms"],
            "mttm_budget_ms": probe["mttm_budget_ms"],
            "mitigated_jobs": mit["goodput_jobs"],
            "unmitigated_jobs": probe["unmitigated"]["goodput_jobs"],
            "false_quarantines": probe["false_quarantines"],
            "failed_jobs": probe["failed_jobs"],
            "migrations": mit.get("migrations", 0),
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            sys.stderr.write(
                f"FAIL: grayfail probe — goodput ratio "
                f"{probe['goodput_ratio']}x (floor "
                f"{probe['ratio_floor']}x), mttm "
                f"{probe['mttm_ms']}ms (budget "
                f"{probe['mttm_budget_ms']}ms), false_quarantines="
                f"{probe['false_quarantines']}, failed_jobs="
                f"{probe['failed_jobs']}, healthy_ok="
                f"{probe['healthy']['healthy_ok']}\n")
            sys.exit(1)
        return

    if opts.probe_sdc:
        from benchmarks.probe_sdc import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        det = probe["detect"]
        pool = probe["pool"]
        line = {
            "metric": f"sdc integrity plane, {probe['nranks']}-rank "
                      f"checked mesh + {pool.get('hosts')}-host pool: "
                      f"detect + attribute + quarantine",
            "value": probe["sdc_detection_rate"],
            "unit": "detection_rate",
            "sdc_false_positives": probe["sdc_false_positives"],
            "sdc_mttq_ms": probe["sdc_mttq_ms"],
            "mttq_budget_ms": probe["mttq_budget_ms"],
            "convicted_ranks": det["convicted_ranks"],
            "retry_ops": det["retry_ops"],
            "byte_exact": det["byte_exact"],
            "failed_jobs": probe["failed_jobs"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            sys.stderr.write(
                f"FAIL: sdc probe — gates {probe['gates']} "
                f"(detection_rate={probe['sdc_detection_rate']}, "
                f"false_positives={probe['sdc_false_positives']}, "
                f"mttq {probe['sdc_mttq_ms']}ms of "
                f"{probe['mttq_budget_ms']}ms budget, failed_jobs="
                f"{probe['failed_jobs']})\n")
            sys.exit(1)
        return

    if opts.probe_ctrlplane:
        from benchmarks.probe_ctrlplane import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        line = {
            "metric": f"control-plane chaos, KV kill mid-fence + DVM "
                      f"kill mid-run, {probe['kv']['workers']} "
                      "concurrent sessions",
            "value": probe["kv_failover_mttr_ms"],
            "unit": "ms_kv_warm_failover",
            "kv_fence_complete_ms": probe["kv_fence_complete_ms"],
            "dvm_restart_mttr_ms": probe["dvm_restart_mttr_ms"],
            "failed_jobs": probe["failed_jobs"],
            "jobs_done": probe["dvm"]["jobs_done"],
            "supervisor_restarts":
                probe["dvm"]["supervisor_restarts"],
            "kv_repl_overhead_pct": probe["kv_repl_overhead_pct"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            sys.stderr.write(
                f"FAIL: ctrlplane probe — failed_jobs="
                f"{probe['failed_jobs']}, kv hung="
                f"{probe['kv']['hung_workers']}, dvm hung="
                f"{probe['dvm']['hung_sessions']}, dvm killed="
                f"{probe['dvm']['killed']}, jobs_done="
                f"{probe['dvm']['jobs_done']}\n")
            sys.exit(1)
        return

    if opts.probe_obs:
        from benchmarks.probe_obs import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        line = {
            "metric": f"obs telemetry plane, scrape tick at "
                      f"{probe['scrape_interval_ms']} ms on "
                      f"{probe['nranks']} ranks + "
                      f"{probe['sessions']} attributed DVM sessions",
            "value": probe["overhead_pct"],
            "unit": "pct_overhead_median",
            "off_us_median": probe["off_us_median"],
            "on_us_median": probe["on_us_median"],
            "scrapes_on_side": probe["scrapes_on_side"],
            "attribution_ok": probe["attribution_ok"],
            "sessions_attributed": probe["sessions_attributed"],
            "events_roundtrip_ok": probe["events_roundtrip_ok"],
            "events_recorded": probe["events_recorded"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            sys.stderr.write(
                f"FAIL: obs probe — overhead "
                f"{probe['overhead_pct']}% (budget "
                f"{probe['budget_pct']}%), attribution_ok="
                f"{probe['attribution_ok']}, events_roundtrip_ok="
                f"{probe['events_roundtrip_ok']}\n")
            sys.exit(1)
        return

    if opts.probe_reqtrace:
        from benchmarks.probe_reqtrace import persist, run_probe

        probe = run_probe()
        notes = persist(probe, detail_path)
        wf = probe["waterfall"]
        doc = probe["doctor"]
        line = {
            "metric": f"reqtrace waterfalls, {wf['sessions']} Poisson "
                      f"sessions x {wf['runs_per_session']} runs on "
                      f"{wf['hosts']} hosts + rdv_sever hang doctor",
            "value": wf["worst_err_pct"],
            "unit": "pct_worst_span_vs_client_wall",
            "fidelity_ok": wf["fidelity_ok"],
            "queue_wait_p99_us": probe["queue_wait_p99_us"],
            "doctor_mttd_ms": probe["doctor_mttd_ms"],
            "mttd_budget_ms": doc["mttd_budget_ms"],
            "absent_rank_named": doc["absent_rank_named"],
            "doctor_ok": doc["doctor_ok"],
            "reqtrace_overhead_pct":
                probe["overhead"]["reqtrace_overhead_pct"],
            "within_budget": probe["within_budget"],
        }
        line.update({k: v for k, v in notes.items() if "error" in k})
        sys.stderr.write(json.dumps(probe, indent=1) + "\n")
        print(json.dumps(line))
        if not probe["within_budget"]:
            sys.stderr.write(
                f"FAIL: reqtrace probe — fidelity_ok="
                f"{wf['fidelity_ok']} (worst {wf['worst_err_pct']}%), "
                f"doctor_ok={doc['doctor_ok']} (mttd "
                f"{probe['doctor_mttd_ms']}ms of "
                f"{doc['mttd_budget_ms']}ms budget), reqtrace "
                f"overhead {probe['overhead']['reqtrace_overhead_pct']}"
                f"% (budget {probe['overhead']['budget_pct']}%)\n")
            sys.exit(1)
        return

    if opts.quick:
        caps = {"ar": 64 * 1024, "bcast": 16 * 1024, "a2a": 4 * 1024,
                "rsb": 16 * 1024}
    else:
        caps = {"ar": 256 * MIB, "bcast": 64 * MIB, "a2a": 4 * MIB,
                "rsb": 16 * MIB}

    result = {
        "metric": f"osu_allreduce busbw {NRANKS} ranks x "
                  f"{HEADLINE_BYTES // MIB} MiB float32",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
    }
    dev = {}
    sw = {}
    # ORDER MATTERS on the 1-core bench box: the software sweeps are
    # subprocess jobs and run FIRST, before the device sweep imports
    # jax into this process — r4 ran them after, and the resident
    # tunnel client's threads stole enough CPU to inflate software
    # numbers 4-22x (the "tcp large-payload cliff" of VERDICT r4 #4
    # reproduced at 9.6 s/op under that contamination vs 2.7 s idle,
    # perfectly linear; the seg path measured 810 ms at 8 MiB vs
    # 35 ms idle).  Idle-box software numbers are the honest
    # baseline for both north-star comparisons.
    try:
        sw = run_software_sweep(caps, opts.sw_budget)
    except Exception as e:  # noqa: BLE001
        result["sw_error"] = f"software sweep: {str(e)[:200]}"
    # BASELINE.md's literal north star: coll/tuned over the TCP btl
    # (no segment/sm fast paths).  allreduce >= 4 KiB only — the
    # strong-path sweep above remains the honest best-software record.
    sw_tcp = {}
    try:
        sw_tcp = run_software_sweep(
            {"ar": caps["ar"], "bcast": 0, "a2a": 0, "rsb": 0},
            min(opts.sw_budget, 150.0),
            mca=(("btl", "self,tcp"), ("coll_seg_priority", "0"),
                 ("coll_sm_priority", "0")),
            start=4096)
    except Exception as e:  # noqa: BLE001
        result["sw_tcp_error"] = f"tuned-tcp sweep: {str(e)[:160]}"
    try:
        from benchmarks.device_sweep import run_device_sweep

        dev = run_device_sweep(NRANKS, caps["ar"], caps["bcast"],
                               caps["a2a"], caps["rsb"],
                               budget_s=opts.dev_budget)
    except Exception as e:  # noqa: BLE001
        result["error"] = f"device sweep: {str(e)[:200]}"

    hk = str(HEADLINE_BYTES)
    dev_ar = dev.get("allreduce", {})
    sw_ar = sw.get("allreduce", {})
    if dev_ar.get(hk) is not None:
        result["value"] = round(busbw_gbs(HEADLINE_BYTES, dev_ar[hk]), 3)
        if sw_ar.get(hk) is not None:
            result["vs_baseline"] = round(sw_ar[hk] / dev_ar[hk], 3)
    elif opts.quick and dev_ar:
        # quick mode never reaches 8 MiB; report the largest size
        big = max((k for k in dev_ar
                   if k != "truncated" and dev_ar[k] is not None),
                  key=int, default=None)
        if big is None:
            print(json.dumps(result))
            return
        result["metric"] = (f"osu_allreduce busbw {NRANKS} ranks x "
                            f"{big} B float32 (quick)")
        result["value"] = round(busbw_gbs(int(big), dev_ar[big]), 3)
        if big in sw_ar:
            result["vs_baseline"] = round(sw_ar[big] / dev_ar[big], 3)

    per_size, beats = northstar(dev_ar, sw_ar)
    # None (not false) when no size was actually compared: the field
    # must encode "no data", never read as a losing perf verdict
    result["northstar_beats_sw_ge_4KiB"] = beats if per_size else None
    tcp_per_size, tcp_beats = northstar(
        dev_ar, sw_tcp.get("allreduce", {}))
    result["northstar_beats_tuned_tcp_ge_4KiB"] = \
        tcp_beats if tcp_per_size else None
    result["read_const_us"] = dev.get("read_const_us")
    # busbw-vs-size curve at a fixed size ladder: round-over-round
    # comparisons survive single-point jitter (VERDICT r4 #10)
    curve = {}
    for k in ("4096", "65536", "1048576", "8388608", "67108864",
              "268435456"):
        du = dev_ar.get(k)
        if du:
            curve[k] = round(busbw_gbs(int(k), du), 2)
    if curve:
        result["busbw_curve_GBs"] = curve
    trunc = []
    for side, d in (("device", dev), ("software", sw),
                    ("software_tuned_tcp", sw_tcp)):
        for k, v in d.items():
            if isinstance(v, dict) and v.get("truncated"):
                trunc.append(f"{side}:{k}")
        if d.get("truncated"):
            trunc.append(f"{side}:all")
    if trunc:
        result["truncated"] = trunc

    # full sweeps go to a file, never the driver-parsed stdout line.
    # preserve a prior --probe-dispatch block across full-sweep writes
    prior = {}
    try:
        with open(detail_path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = {}
    try:
        with open(detail_path, "w") as f:
            json.dump({**{k: prior[k]
                          for k in ("probe_dispatch", "trace_overhead",
                                    "probe_recovery", "probe_respawn",
                                    "probe_pipeline", "probe_ckpt",
                                    "probe_serve", "probe_obs",
                                    "probe_fleet", "probe_rma",
                                    "probe_ctrlplane", "probe_reqtrace",
                                    "probe_grayfail", "probe_sdc",
                                    "regress_trajectory")
                          if isinstance(prior, dict) and k in prior},
                       "device_us": dev, "software_us": sw,
                       "software_tuned_tcp_us": sw_tcp,
                       "northstar_per_size": per_size,
                       "northstar_tuned_tcp_per_size": tcp_per_size,
                       # also persisted here so shedding it from the
                       # 1 KiB driver line loses nothing (ADVICE r5 #4)
                       "busbw_curve_GBs": curve},
                      f, indent=1)
    except OSError as e:
        # never let the detail dump cost us the driver's headline line
        result["detail_error"] = str(e)[:120]

    if dev or sw:
        sys.stderr.write(fmt_table(dev, sw) + "\n")
        if per_size:
            yn = ", ".join(f"{k}B:{'yes' if v else 'NO'}"
                           for k, v in sorted(per_size.items(),
                                              key=lambda kv: int(kv[0])))
            sys.stderr.write(
                f"vs STRONG software (seg segments over shm): "
                f"{'YES' if beats else 'NO'} "
                f"[{yn}]\n")
        if tcp_per_size:
            yn = ", ".join(f"{k}B:{'yes' if v else 'NO'}"
                           for k, v in sorted(tcp_per_size.items(),
                                              key=lambda kv: int(kv[0])))
            sys.stderr.write(
                f"north star (BASELINE.md: beats coll/tuned over the "
                f"TCP btl at every size >= 4KiB): "
                f"{'YES' if tcp_beats else 'NO'} "
                f"[{yn}]\n")
        if trunc:
            sys.stderr.write(
                f"NOTE: sweeps truncated by budget: {trunc}\n")
    # the driver tail-captures stdout: keep the line small by
    # shedding optional fields rather than ever not printing it
    line = json.dumps(result)
    for drop in ("busbw_curve_GBs", "truncated", "sw_error", "error",
                 "detail_error"):
        if len(line) <= 1024:
            break
        result.pop(drop, None)
        line = json.dumps(result)
    print(line)


if __name__ == "__main__":
    main()
