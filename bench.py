"""Benchmark entry: OSU-style MPI_Allreduce bus bandwidth.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "GB/s", "vs_baseline": N}

Path selection mirrors the deployment reality (BASELINE.md):
  * >= 2 accelerator devices: coll/tpu — one XLA AllReduce over ICI.
  * 1 device (the CI chip): coll/hbm — 8 ranks co-located on the
    chip, allreduce as one fused HBM kernel (the coll/sm analog).
  * no accelerator: host path only.

vs_baseline compares against the software baseline the north star
names (coll/tuned's ring over a byte transport): the same 8-rank
allreduce run through our tuned p2p ring on host buffers.  Values
> 1.0 mean the device path beats the software path.

busbw uses the OSU/NCCL convention: algbw * 2*(n-1)/n with
algbw = bytes_per_rank / time.
"""

from __future__ import annotations

import json
import sys
import time

NRANKS = 8
MIB = 1024 * 1024
SIZE_BYTES = 8 * MIB  # per-rank buffer
ITERS = 20
WARMUP = 3


def _bench_device() -> float:
    """Seconds per allreduce through the device coll path."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ompi_tpu.op import op as mpi_op
    from ompi_tpu.testing import run_ranks

    ndev = len(jax.devices())
    if ndev >= NRANKS:
        device_map = None
        devices = True
    else:
        dev0 = jax.devices()[0]
        device_map = lambda r: jax.devices()[r % ndev]  # noqa: E731
        devices = False

    n_elems = SIZE_BYTES // 4

    def fn(comm):
        x = jax.device_put(
            jnp.full((n_elems,), comm.rank + 1.0, jnp.float32),
            comm.device)
        r = comm.allreduce_arr(x, mpi_op.SUM)
        r.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(ITERS):
            r = comm.allreduce_arr(x, mpi_op.SUM)
        r.block_until_ready()
        dt = (time.perf_counter() - t0) / ITERS
        # correctness guard: a fast-but-wrong bench is worthless
        assert abs(float(np.asarray(r)[0]) - sum(range(1, NRANKS + 1))) < 1e-3
        return dt

    res = run_ranks(NRANKS, fn, devices=devices, device_map=device_map,
                    timeout=600)
    return max(res)


def _bench_host() -> float:
    """Seconds per allreduce through the tuned p2p ring (the software
    baseline: coll/tuned over a byte transport)."""
    import numpy as np
    from ompi_tpu.op import op as mpi_op
    from ompi_tpu.testing import run_ranks

    n_elems = SIZE_BYTES // 4
    iters = 5

    def fn(comm):
        x = np.full(n_elems, comm.rank + 1.0, dtype=np.float32)
        r = np.empty_like(x)
        comm.Allreduce(x, r, mpi_op.SUM)
        t0 = time.perf_counter()
        for _ in range(iters):
            comm.Allreduce(x, r, mpi_op.SUM)
        dt = (time.perf_counter() - t0) / iters
        assert abs(r[0] - sum(range(1, NRANKS + 1))) < 1e-3
        return dt

    res = run_ranks(NRANKS, fn, timeout=600)
    return max(res)


def main() -> None:
    result = {
        "metric": f"osu_allreduce busbw {NRANKS} ranks x "
                  f"{SIZE_BYTES // MIB} MiB float32",
        "value": 0.0,
        "unit": "GB/s",
        "vs_baseline": 0.0,
    }
    try:
        t_dev = _bench_device()
        busbw = 2 * (NRANKS - 1) / NRANKS * SIZE_BYTES / t_dev / 1e9
        result["value"] = round(busbw, 3)
        try:
            t_host = _bench_host()
            result["vs_baseline"] = round(t_host / t_dev, 3)
        except Exception:  # noqa: BLE001
            result["vs_baseline"] = 0.0
    except Exception as e:  # noqa: BLE001
        result["error"] = str(e)[:200]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
