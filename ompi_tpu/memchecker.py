"""Memchecker-analog: buffer-access validity checking for MPI usage
errors.

Re-design of opal/mca/memchecker/valgrind (ref:
memchecker_valgrind_module.c:28-29 — annotate send/recv buffers
noaccess/defined around request lifecycles so Valgrind flags user
code touching in-flight buffers).  Python cannot mark pages, so the
same two error classes are caught differently:

  * **recv-buffer read-before-complete**: a posted receive buffer is
    POISONED (0xCB) at post time; user code consuming stale bytes
    sees loud garbage instead of silently stale data, and the poison
    pattern makes it grep-able.
  * **send-buffer modification while in flight**: a checksum of the
    send buffer is recorded at isend and verified at completion —
    a mismatch raises, naming the request (the MPI-2 erroneous
    program the reference's memchecker flags).

Enable with ``--mca opal_memchecker_enable 1`` (off by default: poisoning
costs a memset per receive, checksums a pass per send — same
price/benefit as running the reference under Valgrind).
"""

from __future__ import annotations

import zlib
from typing import Optional

import numpy as np

from ompi_tpu.mca.params import registry

_enabled_var = registry.register(
    "opal", "memchecker", "enable", False, bool,
    help="Poison posted recv buffers and checksum in-flight send "
         "buffers to catch MPI buffer-usage errors (memchecker "
         "analog)")

POISON = 0xCB


def enabled() -> bool:
    return _enabled_var.value


def poison_recv(conv) -> None:
    """Fill the receive region with the poison pattern at post time
    (the VALGRIND_MAKE_MEM_NOACCESS analog)."""
    view = getattr(conv, "_view", None)
    if view is not None:  # ContigConvertor fast path
        view[:] = POISON


def send_checksum(conv) -> Optional[int]:
    """Checksum the send region at activation (cheap crc32; the
    VALGRIND_CHECK_MEM_IS_DEFINED analog)."""
    view = getattr(conv, "_view", None)
    if view is None:
        return None
    return zlib.crc32(memoryview(np.ascontiguousarray(view)))


def verify_send(conv, crc: Optional[int], req_desc: str) -> None:
    """At completion, a changed send buffer is an MPI usage error
    (the program modified a buffer owned by an active request)."""
    if crc is None:
        return
    now = send_checksum(conv)
    if now is not None and now != crc:
        raise RuntimeError(
            f"memchecker: send buffer of {req_desc} was modified "
            f"while the request was in flight (MPI forbids touching "
            f"a buffer owned by an active request)")
