"""Sharded payload serialization shared by every checkpoint tier.

ISSUE 5's buddy tier pickled a rank's *whole* state per partner; this
module is the fix (ISSUE 8 satellite) and the substrate of the
filesystem tier (cr/ckpt.py): a payload pytree is split into

  * a **residue** — the pickled skeleton with every array leaf
    replaced by an indexed ``_ShardRef`` placeholder.  Small (shapes,
    Python scalars, dict keys), safe to materialize eagerly.
  * **shards** — one per array leaf, carrying dtype/shape metadata and
    a ``zlib.crc32`` over the raw bytes.  jax arrays are immutable, so
    ``plan`` holds a *reference* and defers the device→host copy to
    ``drain`` (the async-drain engine calls it from progress ticks);
    numpy arrays are mutable and get snapshotted at plan time — that
    copy is part of the checkpoint's enqueue cost by design.

The split is what lets the filesystem tier write shard-at-a-time with
per-shard integrity, and lets buddy ship the exact same bytes the
durable tier would, instead of a second ad-hoc pickle format.

Mirrors Open MPI's layering where crs components share one snapshot
image format with the sstore layer (ref: opal/mca/crs/crs.h,
orte/mca/sstore) — one serializer, many transports.
"""

from __future__ import annotations

import pickle
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class _ShardRef:
    """Pickle-stable placeholder for an extracted array leaf."""

    __slots__ = ("idx",)

    def __init__(self, idx: int) -> None:
        self.idx = idx


class Shard:
    """One array leaf of a checkpoint plan.

    ``kind`` records whether the leaf was a device (jax) or host
    (numpy) array so ``rebuild`` puts it back where it came from.
    ``arr`` holds the original leaf until :func:`drain` converts it to
    ``host`` (a flat uint8 view) and stamps ``crc``.
    """

    __slots__ = ("idx", "kind", "dtype", "shape", "nbytes", "arr",
                 "host", "crc")

    def __init__(self, idx: int, kind: str, dtype: str,
                 shape: Tuple[int, ...], nbytes: int) -> None:
        self.idx = idx
        self.kind = kind
        self.dtype = dtype
        self.shape = shape
        self.nbytes = nbytes
        self.arr: Any = None
        self.host: Optional[np.ndarray] = None
        self.crc = 0

    def meta(self) -> Dict[str, Any]:
        """JSON-safe manifest entry for this shard."""
        return {"idx": self.idx, "kind": self.kind, "dtype": self.dtype,
                "shape": list(self.shape), "nbytes": self.nbytes,
                "crc": self.crc}


class Plan:
    """A planned (but possibly not yet drained) rank snapshot."""

    __slots__ = ("residue", "shards")

    def __init__(self, residue: bytes, shards: List[Shard]) -> None:
        self.residue = residue
        self.shards = shards

    @property
    def shard_nbytes(self) -> int:
        return sum(s.nbytes for s in self.shards)

    @property
    def total_nbytes(self) -> int:
        return len(self.residue) + self.shard_nbytes


def _leaf_nbytes(dtype: np.dtype, shape: Tuple[int, ...]) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return int(dtype.itemsize) * n


def plan(payload: Any) -> Plan:
    """Walk the payload pytree, extracting array leaves into shards.

    jax leaves are held by reference (immutable — no tearing risk);
    numpy leaves are copied now so later mutation by the application
    cannot tear the snapshot.  Object-dtype numpy arrays cannot be
    byte-sharded and stay in the residue pickle.
    """
    import jax

    shards: List[Shard] = []

    def walk(obj):
        if isinstance(obj, jax.Array):
            dt = np.dtype(obj.dtype)
            sh = Shard(len(shards), "jax", dt.str, tuple(obj.shape),
                       _leaf_nbytes(dt, tuple(obj.shape)))
            sh.arr = obj
            shards.append(sh)
            return _ShardRef(sh.idx)
        if isinstance(obj, np.ndarray) and not obj.dtype.hasobject:
            dt = obj.dtype
            sh = Shard(len(shards), "np", dt.str, tuple(obj.shape),
                       _leaf_nbytes(dt, tuple(obj.shape)))
            sh.arr = np.array(obj, copy=True)  # snapshot: enqueue cost
            shards.append(sh)
            return _ShardRef(sh.idx)
        if isinstance(obj, dict):
            return {k: walk(v) for k, v in obj.items()}
        if isinstance(obj, tuple):
            return tuple(walk(v) for v in obj)
        if isinstance(obj, list):
            return [walk(v) for v in obj]
        return obj

    skeleton = walk(payload)
    residue = pickle.dumps(skeleton, protocol=pickle.HIGHEST_PROTOCOL)
    return Plan(residue, shards)


def drain(sh: Shard) -> int:
    """Materialize a shard's host bytes (device→host for jax leaves)
    and stamp its CRC.  Idempotent; returns the shard's byte count.
    This is the unit of work the async drain engine meters with
    ``cr_drain_depth``."""
    if sh.host is None:
        a = np.ascontiguousarray(np.asarray(sh.arr))
        sh.arr = None
        sh.host = a.reshape(-1).view(np.uint8)
        sh.crc = zlib.crc32(sh.host)
    return sh.nbytes


def _revive(obj, leaves):
    if isinstance(obj, _ShardRef):
        return leaves[obj.idx]
    if isinstance(obj, dict):
        return {k: _revive(v, leaves) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_revive(v, leaves) for v in obj)
    if isinstance(obj, list):
        return [_revive(v, leaves) for v in obj]
    return obj


def make_leaf(meta: Dict[str, Any], raw: np.ndarray, device):
    """Rebuild one array leaf from its raw bytes + manifest meta.

    ``raw`` is a flat uint8 array (any backing — a file-read buffer
    slice works).  jax leaves go back to the rank's device; numpy
    leaves come back as a private writable copy.
    """
    import jax

    a = np.frombuffer(raw.tobytes(), dtype=np.dtype(meta["dtype"]))
    a = a.reshape(tuple(meta["shape"]))
    if meta["kind"] == "jax":
        return (jax.device_put(a, device) if device is not None
                else jax.numpy.asarray(a))
    return np.array(a, copy=True)


def rebuild(residue: bytes, metas: List[Dict[str, Any]],
            fetch: Callable[[int], np.ndarray], device) -> Any:
    """Reassemble a payload: unpickle the residue skeleton and splice
    the array leaves back in.  ``fetch(idx)`` returns shard ``idx``'s
    raw uint8 bytes (already CRC-verified by the caller)."""
    leaves = [None] * len(metas)
    for m in metas:
        leaves[m["idx"]] = make_leaf(m, fetch(m["idx"]), device)
    return _revive(pickle.loads(residue), leaves)


# ---------------------------------------------------------------------
# self-describing one-buffer image (the buddy tier's wire format)
# ---------------------------------------------------------------------

_MAGIC = b"TPSH"  # shard image v1


def dumps(payload: Any) -> bytes:
    """Serialize a payload eagerly into one self-describing buffer:
    ``TPSH | u64 header_len | header pickle | shard bytes...``.
    Same residue/shard split and CRCs the filesystem tier writes, in
    one contiguous image the buddy ring can ship."""
    p = plan(payload)
    for sh in p.shards:
        drain(sh)
    header = pickle.dumps(
        {"residue": p.residue, "shards": [sh.meta() for sh in p.shards]},
        protocol=pickle.HIGHEST_PROTOCOL)
    parts = [_MAGIC, struct.pack("<Q", len(header)), header]
    for sh in p.shards:
        parts.append(sh.host.tobytes())
    return b"".join(parts)


def loads(data: bytes, device) -> Any:
    """Inverse of :func:`dumps`; verifies every shard CRC (a buddy
    replica that rotted in transit or in a partner's memory is caught
    here, the same way a torn file shard is caught at restore)."""
    if data[:4] != _MAGIC:
        raise ValueError("shard.loads: bad magic (not a TPSH image)")
    (hlen,) = struct.unpack_from("<Q", data, 4)
    header = pickle.loads(data[12:12 + hlen])
    metas = header["shards"]
    base = 12 + hlen
    offs: List[int] = []
    o = base
    for m in metas:
        offs.append(o)
        o += m["nbytes"]
    raws: List[np.ndarray] = []
    for i, m in enumerate(metas):
        raw = np.frombuffer(data, np.uint8, m["nbytes"], offs[i])
        crc = zlib.crc32(raw)
        if crc != m["crc"]:
            raise ValueError(
                f"shard.loads: CRC mismatch on shard {m['idx']} "
                f"(stored {m['crc']:#010x}, computed {crc:#010x})")
        raws.append(raw)
    return rebuild(header["residue"], metas, lambda i: raws[i], device)
