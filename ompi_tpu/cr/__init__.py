"""Checkpoint/restart: the C/R stack re-designed for the TPU-host
execution model.

Reference architecture this collapses (SURVEY §5 checkpoint row):
  * opal/mca/crs  — single-process snapshot engines.  The `self`
    component (application-assisted callbacks, ref:
    opal/mca/crs/crs.h) is the model here: the app hands us its
    state; transparent process-image dumps (BLCR/CRIU) are replaced
    by device-array capture, which a process image could never carry
    anyway (HBM is not in the address space).
  * ompi/mca/crcp/bkmrk — in-flight message quiesce by bookmark
    exchange (ref: crcp_bkmrk_pml.c): per-peer sent/arrived envelope
    counters drained until they match globally; buffered eager
    messages ride the snapshot.
  * orte/mca/snapc/full — distributed coordination (ref:
    snapc_full_global.c): here a fence + rank-0 "complete" marker
    make the snapshot atomic — a sequence directory missing meta.json
    is ignored at restart.
  * orte/mca/sstore — image storage layout: sequence directories
    ckpt_NNNNNN/ under one store root, latest-complete wins.
  * orte-checkpoint / orte-restart tools — ompi_tpu.tools.restart
    relaunches from the store's job.json (written by mpirun
    --ckpt-dir).

API (collective over COMM_WORLD):

    state = cr.restore(comm)            # None on a fresh start
    ...
    cr.checkpoint(comm, state)          # store dir from mpirun

mpirun --ckpt-dir DIR exports the store; mpirun --restart DIR (or
``python -m ompi_tpu.tools.restart DIR``) relaunches into it.
Device arrays anywhere in the payload are captured to host and
restored onto each rank's device; a shmem context's symmetric heap
can be snapshotted via the ``shmem_ctx`` argument.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
from typing import Any, List, Optional, Tuple

import numpy as np

ENV_DIR = "TPUMPI_CKPT_DIR"
ENV_RESTART = "TPUMPI_RESTART"

from ompi_tpu.mca.params import registry as _registry  # noqa: E402

_CKPT_GZ_MAGIC = b"TPGZ"  # pickle streams start 0x80: no collision

_compress_var = _registry.register(
    "cr", "base", "compress", True, bool,
    help="gzip rank checkpoint images (compress/gzip analog); "
         "raw images remain readable either way (format marker)")
_compress_level_var = _registry.register(
    "cr", "base", "compress_level", 1, int,
    help="gzip level for checkpoint images: 1 favors speed — the "
         "win is mostly zero pages and repeated weights")

_quiesce_timeout_var = _registry.register(
    "cr", "base", "quiesce_timeout", 60.0, float,
    help="Seconds the checkpoint quiesce may stall without counter "
         "progress before raising (bounds a hang on a lost peer)")

_keep_var = _registry.register(
    "cr", "", "keep", 0, int,
    help="Job-wide default for checkpoint(..., keep=): prune the "
         "store to the newest N complete snapshots after each commit "
         "(0 = keep all).  mpirun --ckpt-keep exports it so long "
         "chaos runs don't fill the disk")



# ---------------------------------------------------------------------
# payload encoding: device arrays <-> host
# ---------------------------------------------------------------------

class _JaxLeaf:
    """Pickle-stable marker for a captured device array."""

    __slots__ = ("arr",)

    def __init__(self, arr: np.ndarray) -> None:
        self.arr = arr


def _encode(obj):
    import jax

    if isinstance(obj, jax.Array):
        return _JaxLeaf(np.asarray(obj))
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_encode(v) for v in obj)
    if isinstance(obj, list):
        return [_encode(v) for v in obj]
    return obj


def _decode(obj, device):
    import jax

    if isinstance(obj, _JaxLeaf):
        return (jax.device_put(obj.arr, device) if device is not None
                else jax.numpy.asarray(obj.arr))
    if isinstance(obj, dict):
        return {k: _decode(v, device) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_decode(v, device) for v in obj)
    if isinstance(obj, list):
        return [_decode(v, device) for v in obj]
    return obj


# ---------------------------------------------------------------------
# sstore analog: sequence directories under one root
# ---------------------------------------------------------------------

class Store:
    """ckpt_NNNNNN/ sequence dirs; a dir without meta.json is
    incomplete and ignored (snapc/full global-coordination analog:
    rank 0 writes meta only after every rank's file is fenced)."""

    def __init__(self, root: str) -> None:
        self.root = root

    def _seq_dirs(self) -> List[int]:
        if not os.path.isdir(self.root):
            return []
        out = []
        for name in os.listdir(self.root):
            if name.startswith("ckpt_"):
                try:
                    out.append(int(name[5:]))
                except ValueError:
                    pass
        return sorted(out)

    def seq_path(self, seq: int) -> str:
        return os.path.join(self.root, f"ckpt_{seq:06d}")

    def next_seq(self) -> int:
        dirs = self._seq_dirs()
        return dirs[-1] + 1 if dirs else 0

    def latest_complete(self) -> Optional[int]:
        for seq in reversed(self._seq_dirs()):
            if os.path.exists(os.path.join(self.seq_path(seq),
                                           "meta.json")):
                return seq
        return None

    def write_rank(self, seq: int, rank: int, blob: dict) -> None:
        """Compressed (gzip) rank image with a format marker, raw
        when compression is off (ref: opal/mca/compress/gzip/
        compress_gzip.c — at model scale the HBM-array payload is
        the difference between a usable and unusable store).  The
        4-byte magic keeps old raw images readable: pickle streams
        begin with 0x80, never with the marker."""
        d = self.seq_path(seq)
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(d, f".rank_{rank}.tmp")
        with open(tmp, "wb") as f:
            if _compress_var.value:
                import gzip
                f.write(_CKPT_GZ_MAGIC)
                # stream: never hold raw + compressed images in
                # memory at once (model-scale payloads, co-resident
                # ranks checkpointing together)
                with gzip.GzipFile(
                        fileobj=f, mode="wb",
                        compresslevel=int(
                            _compress_level_var.value)) as gz:
                    pickle.dump(blob, gz,
                                protocol=pickle.HIGHEST_PROTOCOL)
            else:
                pickle.dump(blob, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, os.path.join(d, f"rank_{rank}.ckpt"))

    def read_rank(self, seq: int, rank: int) -> dict:
        with open(os.path.join(self.seq_path(seq),
                               f"rank_{rank}.ckpt"), "rb") as f:
            magic = f.read(4)
            if magic == _CKPT_GZ_MAGIC:
                import gzip
                # stream-decompress: never hold compressed + raw
                # images at once (mirror of the write path)
                with gzip.GzipFile(fileobj=f, mode="rb") as gz:
                    return pickle.load(gz)
            f.seek(0)
            return pickle.load(f)

    def mark_complete(self, seq: int, meta: dict) -> None:
        d = self.seq_path(seq)
        tmp = os.path.join(d, ".meta.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, os.path.join(d, "meta.json"))

    def read_meta(self, seq: int) -> dict:
        with open(os.path.join(self.seq_path(seq), "meta.json")) as f:
            return json.load(f)

    def prune(self, keep: int) -> None:
        done = [s for s in self._seq_dirs()
                if os.path.exists(os.path.join(self.seq_path(s),
                                               "meta.json"))]
        for seq in done[:-keep] if keep > 0 else []:
            shutil.rmtree(self.seq_path(seq), ignore_errors=True)


# ---------------------------------------------------------------------
# crcp/bkmrk analog: quiesce the pml
# ---------------------------------------------------------------------

def quiesce(comm, timeout: Optional[float] = None) -> None:
    """Drain in-flight user traffic: loop until every pair's
    sent/arrived envelope counts match globally and no rank holds a
    partially-transferred send.  Collective over COMM_WORLD — the
    counters are per GLOBAL rank, so a sub-communicator cannot speak
    for traffic outside itself.  Bounded: a drain that makes no
    progress within the timeout raises, naming the mismatched pairs
    (same discipline as the kv/rendezvous stall guards)."""
    import time

    if len(comm.group) != comm.state.size:
        raise ValueError("cr.quiesce must run on COMM_WORLD")
    if timeout is None:
        timeout = _quiesce_timeout_var.value
    pml = comm.state.pml
    n = comm.size
    me = np.empty(2 * n + 1, dtype=np.int64)
    table = np.empty((n, 2 * n + 1), dtype=np.int64)
    deadline = time.monotonic() + timeout
    last = None
    while True:
        comm.state.progress.progress()
        for j in range(n):
            me[j] = pml.cr_sent.get(comm.group[j], 0)
            me[n + j] = pml.cr_arrived.get(comm.group[j], 0)
        me[2 * n] = pml.cr_pending_sends()
        comm.Allgather(me, table)
        sent = table[:, :n]
        arrived = table[:, n:2 * n]
        if not table[:, 2 * n].any() and (sent == arrived.T).all():
            return
        snap = table.tobytes()
        if snap != last:
            last = snap  # progress: reset the stall clock
            deadline = time.monotonic() + timeout
        elif time.monotonic() > deadline:
            bad = [(i, j, int(sent[i][j]), int(arrived[j][i]))
                   for i in range(n) for j in range(n)
                   if sent[i][j] != arrived[j][i]]
            pend = [i for i in range(n) if table[i, 2 * n]]
            raise RuntimeError(
                f"cr.quiesce stalled >{timeout}s without progress: "
                f"mismatched (sender, receiver, sent, arrived) = "
                f"{bad[:8]}; ranks with partial sends: {pend} "
                f"(tune cr_base_quiesce_timeout)")


# ---------------------------------------------------------------------
# the collective checkpoint / restore API
# ---------------------------------------------------------------------

def _store_for(root: Optional[str]) -> Store:
    root = root or os.environ.get(ENV_DIR)
    if not root:
        raise RuntimeError(
            "no checkpoint store: pass store_dir= or launch with "
            "mpirun --ckpt-dir DIR")
    return Store(root)


def checkpoint(comm, payload: Any, store_dir: Optional[str] = None,
               shmem_ctx=None, keep: Optional[int] = None) -> int:
    """Collective snapshot; returns the sequence number.  ``keep``
    prunes to the newest N complete snapshots (0 = keep all; None =
    the job-wide cr_keep MCA default)."""
    if keep is None:
        keep = int(_keep_var.value)
    store = _store_for(store_dir)
    quiesce(comm)
    # quiesce stays interruptible (a recovery signal there means the
    # snapshot can't form anyway); the capture+write phases below must
    # not be torn by an armed ft interrupt — hold it until the
    # snapshot is durably complete (ADVICE r5 #5)
    with comm.state.progress.deferred_interrupts():
        from ompi_tpu.pml.vprotocol import find as _vfind
        _v = _vfind(comm.state.pml)
        if _v is not None:
            # quiesce proved every logged message consumed: the
            # coordinated checkpoint is the pessimist log's GC point
            _v.clear_log()
        msgs = comm.state.pml.cr_capture()
        blob = {
            "payload": _encode(payload),
            "pml_msgs": msgs,
            "rank": comm.rank,
        }
        eng = getattr(comm.state, "_tpu_rndv", None)
        if eng is not None and eng.pending:
            # sender halves of in-flight chunked device transfers (the
            # receiver halves are the xferhdr entries in pml_msgs)
            blob["tpu_xfers"] = eng.cr_capture()
        if shmem_ctx is not None:
            blob["shmem_heap"] = shmem_ctx.heap.copy()
            blob["shmem_alloc"] = shmem_ctx.memheap.state()

        seq = np.array([store.next_seq() if comm.rank == 0 else 0],
                       dtype=np.int64)
        comm.Bcast(seq, root=0)
        store.write_rank(int(seq[0]), comm.rank, blob)
        comm.Barrier()  # every rank's file durably in place...
        if comm.rank == 0:
            store.mark_complete(int(seq[0]), {
                "nprocs": comm.size,
                "seq": int(seq[0]),
                "jobid": os.environ.get("TPUMPI_JOBID", ""),
            })
            if keep:
                store.prune(keep)
        comm.Barrier()  # ...before anyone trusts the snapshot exists
    return int(seq[0])


def _vlayer(comm):
    from ompi_tpu.pml.vprotocol import find
    v = find(comm.state.pml)
    if v is None:
        raise RuntimeError(
            "uncoordinated checkpoint requires sender-based message "
            "logging: launch with --mca pml_vprotocol pessimist")
    return v


def checkpoint_local(comm, payload: Any,
                     store_dir: Optional[str] = None,
                     keep: Optional[int] = None) -> int:
    """UNCOORDINATED snapshot (vprotocol/pessimist): no quiesce, no
    collective, no drain — each rank snapshots at its own moment and
    writes its own sequence under ``local_r<rank>/``.  Messages
    mid-wire or arrived-but-unconsumed at the cut are NOT captured;
    the sender's log redelivers them at restore (replay), and the
    snapshotted sequence maps make redelivery exactly-once.  The
    only local contract: wait your own requests first (same as MPI
    C/R semantics)."""
    if keep is None:
        keep = int(_keep_var.value)
    store = _store_for(store_dir)
    v = _vlayer(comm)
    base = v._base
    # capture+write must not be torn by an armed ft interrupt
    # (ADVICE r5 #5); held, not discarded — it fires right after
    with comm.state.progress.deferred_interrupts():
        blob = {
            "payload": _encode(payload),
            "vlog": v.cr_capture_vlog(),
            "replay_want": base.cr_capture_lenient(),
            "rank": comm.rank,
        }
        eng = getattr(comm.state, "_tpu_rndv", None)
        if eng is not None and eng.pending:
            # parked sender halves of chunked device transfers: without
            # them a replayed _XferHdr's pulls find nothing and the
            # receiver blocks forever (ADVICE r4).  lenient: no quiesce
            # here, so a peer mid-pull is normal — capture the full
            # array; a restarted receiver re-pulls from chunk 0.
            blob["tpu_xfers"] = eng.cr_capture(lenient=True)
        sub = Store(os.path.join(store.root, f"local_r{comm.rank}"))
        seq = sub.next_seq()
        sub.write_rank(seq, comm.rank, blob)
        sub.mark_complete(seq, {"rank": comm.rank, "seq": seq})
        if keep:
            sub.prune(keep)
        # everything this snapshot covers is now durable HERE: senders
        # may trim their logs up to these watermarks (receiver-ack GC)
        v.mark_durable(blob["vlog"]["next_seq"], blob["replay_want"])
    return seq


def restore_local(comm, store_dir: Optional[str] = None
                  ) -> Optional[Any]:
    """Restore from MY latest local (uncoordinated) snapshot, then
    replay the sender logs so every in-flight message of the cut
    line is redelivered.  Collective only in the sense that every
    rank must pass through here before user traffic resumes (the
    internal barrier orders replay against restored counters)."""
    root = store_dir or os.environ.get(ENV_DIR)
    if not root or not os.environ.get(ENV_RESTART):
        return None
    sub = Store(os.path.join(root, f"local_r{comm.rank}"))
    seq = sub.latest_complete()
    if seq is None:
        return None
    blob = sub.read_rank(seq, comm.rank)
    v = _vlayer(comm)
    v.cr_restore_vlog(blob["vlog"])
    if blob.get("tpu_xfers"):
        from ompi_tpu.btl.tpu import _engine
        _engine(comm.state).cr_restore(blob["tpu_xfers"])
    v._base._replay_want = {tuple(w) for w in blob["replay_want"]}
    # every rank's counters restored BEFORE any replay frag can
    # arrive.  The rendezvous must NOT ride the pml: a pml barrier's
    # own fragments would queue BEHIND the unreplayed sequence holes
    # (symmetric in-flight cuts deadlock).  The control-plane fence
    # (KV server) is hole-free.
    comm.state.rte.fence()
    v.replay()
    out = _decode(blob["payload"], comm.state.device)
    return out


def restore(comm, store_dir: Optional[str] = None, shmem_ctx=None
            ) -> Optional[Any]:
    """Returns the latest complete snapshot's payload, or None when
    starting fresh (no --restart, or an empty store)."""
    root = store_dir or os.environ.get(ENV_DIR)
    if not root or not os.environ.get(ENV_RESTART):
        return None
    store = Store(root)
    seq = store.latest_complete()
    if seq is None:
        return None
    meta = store.read_meta(seq)
    if meta["nprocs"] != comm.size:
        raise RuntimeError(
            f"restart topology mismatch: snapshot has "
            f"{meta['nprocs']} ranks, job has {comm.size}")
    blob = store.read_rank(seq, comm.rank)
    comm.state.pml.cr_restore(blob["pml_msgs"])
    if blob.get("tpu_xfers"):
        from ompi_tpu.btl.tpu import _engine
        _engine(comm.state).cr_restore(blob["tpu_xfers"])
    if shmem_ctx is not None and "shmem_heap" in blob:
        shmem_ctx.heap[:] = blob["shmem_heap"]
        if "shmem_alloc" in blob:
            from ompi_tpu.shmem import memheap as _mh
            shmem_ctx.memheap = _mh.restore(blob["shmem_alloc"],
                                            shmem_ctx.heap_size)
        else:
            # pre-framework snapshot: hole list of the old first-fit.
            # Live regions are the holes' complement; boundaries of
            # adjacent allocations inside one live run are lost
            # (legacy format limitation) — each run frees as a unit.
            from ompi_tpu.shmem.memheap import FirstFit as _FF
            ff = _FF(shmem_ctx.heap_size)
            ff._holes = [tuple(h) for h in blob["shmem_holes"]]
            ff._live = {}
            pos = 0
            for off, sz in sorted(ff._holes):
                if off > pos:
                    ff._live[pos] = off - pos
                pos = off + sz
            if pos < shmem_ctx.heap_size:
                ff._live[pos] = shmem_ctx.heap_size - pos
            shmem_ctx.memheap = ff
    out = _decode(blob["payload"], comm.state.device)
    comm.Barrier()
    return out
