"""Tiered checkpointing: async device-drain + collective-I/O durable
tier under the in-memory buddy tier (DESIGN.md §14).

The recovery ladder (DESIGN.md §11) gains its third rung here.  A
single ``ckpt.checkpoint`` call services both tiers:

  tier 1  buddy replicas (cr/buddy.py)  — every call, in-memory,
          fast MTTR (~ms restore over p2p)
  tier 2  filesystem epoch (this file)  — every ``cr_fs_interval``-th
          call, written through io.file into ``cr_fs_dir``, survives
          the loss of a rank AND all its buddy partners

The filesystem tier is **asynchronous**: ``checkpoint`` only *plans*
the epoch (pickle the pytree skeleton, snapshot mutable numpy leaves,
agree on the epoch number and file offsets, open the file) — the
app-visible stall is that enqueue cost.  The device→host shard copies
and the pwrites happen afterwards, ``cr_drain_depth`` shards at a
time, from a low-priority progress callback that runs while the
application is back inside its own collectives.  jax arrays are
immutable, so holding a reference instead of copying is tear-free by
construction; numpy leaves are copied at enqueue (shard.plan).

Two-phase commit makes torn epochs harmless:

  phase 1  every rank writes its region of ``ep_NNNNNN/data.bin``
           (async drain or, with ``cr_drain_depth 0``, one fcoll
           two-phase collective write), then fsyncs;
  phase 2  ranks send their shard manifests to rank 0, which writes
           ``manifest.json`` atomically (tmp + rename) and publishes a
           put-once commit record in the ULFM KV plane.

``manifest.json`` *is* the commit marker: a crash anywhere in phase 1
leaves a directory restore will never select.  Commit is deferred to
the *next* ``checkpoint`` call (the drain had the whole window to
finish) or an explicit ``flush``.  No phase runs under deferred
interrupts — a rank death mid-commit surfaces as ERR_PROC_FAILED from
the collectives, the rejoin path drops the torn epoch (``ft_abort``),
and the previous committed epoch still restores.

Restore ladder (``ckpt.restore``), in order:

  1. live buddy replica        — unchanged 4.4 ms path
  2. filesystem epoch replay   — newest committed epoch whose every
     rank's shards pass CRC; a corrupt epoch falls back to the
     previous committed one (never a torn one)
  3. ``None``                  — caller escalates to job restart

A filesystem restore re-seeds the buddy tier so the *next* failure is
fast again.

Reference architecture: ompi/mca/io + fcoll for the collective write
path, orte/mca/sstore for epoch/manifest layout, SCR's multi-level
scheme for the tier composition (Moody et al.).
"""

from __future__ import annotations

import errno
import json
import os
import pickle
import time
import zlib
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu import obs as _obs
from ompi_tpu.cr import _keep_var as _cr_keep_var
from ompi_tpu.cr import buddy as _buddy
from ompi_tpu.cr import shard as _shard
from ompi_tpu.mca.params import registry as _registry

_drain_depth_var = _registry.register(
    "cr", "", "drain_depth", 2, int,
    help="Device shards drained to host and written per progress "
         "tick for the async filesystem checkpoint tier.  Bounds the "
         "per-tick stall so the drain hides behind application "
         "collectives; 0 switches to synchronous mode (one fcoll "
         "collective write inside the checkpoint call)")
_fs_dir_var = _registry.register(
    "cr", "", "fs_dir", "", str,
    help="Root directory of the durable filesystem checkpoint tier "
         "(epoch directories ep_NNNNNN/ with data.bin + "
         "manifest.json).  Empty disables the tier; buddy replication "
         "alone then covers single failures only")
_fs_interval_var = _registry.register(
    "cr", "", "fs_interval", 1, int,
    help="Write a filesystem epoch every Nth ckpt.checkpoint call "
         "(buddy replicas are refreshed every call).  The decision is "
         "taken on rank 0 and broadcast so respawned replacements "
         "never diverge on the phase")

_pv_epochs = _registry.register_pvar(
    "cr", "ckpt", "epochs_committed",
    help="Filesystem checkpoint epochs this rank committed "
         "(manifest published)")
_pv_shards = _registry.register_pvar(
    "cr", "ckpt", "shards_written",
    help="Array shards this rank wrote to the filesystem tier")
_pv_bytes = _registry.register_pvar(
    "cr", "ckpt", "bytes_written",
    help="Bytes this rank wrote to the filesystem tier (residue + "
         "shards, pre-injection)")
_pv_ticks = _registry.register_pvar(
    "cr", "ckpt", "drain_ticks",
    help="Progress ticks that drained at least one pending shard")
_pv_stall = _registry.register_pvar(
    "cr", "ckpt", "stall_us", var_class="highwatermark",
    help="Worst app-visible pause of one ckpt.checkpoint call "
         "(buddy + epoch enqueue + deferred commit), microseconds")
_pv_rest_buddy = _registry.register_pvar(
    "cr", "ckpt", "restore_buddy",
    help="Restores served by the buddy tier (fast path)")
_pv_rest_fs = _registry.register_pvar(
    "cr", "ckpt", "restore_fs",
    help="Restores served by the filesystem tier (buddy replicas "
         "dead or absent)")
_pv_crc_fb = _registry.register_pvar(
    "cr", "ckpt", "crc_fallbacks",
    help="Committed epochs rejected at restore by a shard CRC "
         "mismatch, falling back to the previous epoch")
_pv_aborted = _registry.register_pvar(
    "cr", "ckpt", "epochs_aborted",
    help="In-flight epochs dropped torn (rank failure or I/O error "
         "before commit)")

# manifest entries ride the pml on an internal tag, like fcoll's
# aggregator traffic (T_META/T_DATA at -141/-142)
T_MANIFEST = -151

_MAX_CANDIDATES = 16  # committed epochs considered at restore


def _epoch_name(epoch: int) -> str:
    return "ep_%06d" % epoch


def _epoch_dir(root: str, epoch: int) -> str:
    return os.path.join(root, _epoch_name(epoch))


def _scan_epochs(root: str) -> List[int]:
    out: List[int] = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for n in names:
        if n.startswith("ep_") and len(n) == 9 and n[3:].isdigit():
            out.append(int(n[3:]))
    return sorted(out)


def _committed_epochs(root: str) -> List[int]:
    """Committed epochs, newest first (manifest.json is the marker)."""
    out = [e for e in _scan_epochs(root)
           if os.path.exists(os.path.join(_epoch_dir(root, e),
                                          "manifest.json"))]
    out.reverse()
    return out


def _next_epoch(root: str) -> int:
    """Next unused epoch number.  Torn directories count: a number is
    never reused, so a half-written ep_N from a previous incarnation
    can never shadow a fresh commit."""
    es = _scan_epochs(root)
    return (es[-1] + 1) if es else 0


def _root(store_dir: Optional[str]) -> str:
    return store_dir or str(_fs_dir_var.value or "")


def keep_epochs() -> int:
    """Filesystem epochs retained after a commit, from the same
    ``cr_keep`` knob the cr store and buddy tier honor.  Floor of 2:
    the previous committed epoch is the CRC-fallback target and must
    survive pruning.  0 = keep all."""
    k = int(_cr_keep_var.value)
    return max(2, k) if k > 0 else 0


class _Handle:
    """One in-flight (begun, not yet committed) filesystem epoch."""

    __slots__ = ("epoch", "comm", "file", "dir", "my_off", "residue",
                 "shards", "queue", "inj", "failed", "nbytes", "t0_ns")

    def __init__(self) -> None:
        self.epoch = -1
        self.comm = None
        self.file = None
        self.dir = ""
        self.my_off = 0
        self.residue = b""
        self.shards: List[_shard.Shard] = []
        self.queue: Deque[Tuple[_shard.Shard, int]] = deque()
        self.inj = None
        self.failed: Optional[str] = None
        self.nbytes = 0
        self.t0_ns = 0  # enqueue time: the req_drain window anchor


class Engine:
    """Per-rank coordinator living in ``ProcState.extra['cr_ckpt']``.

    ``tick`` is a declared hot function (hotpath_audit): the idle path
    — no epoch in flight, or its queue already drained — must not
    allocate, because it runs on every 8th progress sweep for the rest
    of the job once a single checkpoint has been taken.
    """

    def __init__(self, state) -> None:
        self.state = state
        self.pending: Optional[_Handle] = None
        self.calls = 0
        state.progress.register(self.tick, low_priority=True)
        state.progress.register_finalize_hook(self._finalize)

    # -- async drain ----------------------------------------------------

    def tick(self) -> int:
        h = self.pending
        if h is None or not h.queue:
            return 0
        return self._drain_some(h)

    def _drain_some(self, h: _Handle) -> int:
        depth = max(1, int(_drain_depth_var.value))
        done = 0
        while done < depth and h.queue and h.failed is None:
            sh, off = h.queue.popleft()
            try:
                _shard.drain(sh)
                self._write_shard(h, sh, off)
            except OSError as exc:
                # surfaces collectively at commit; never propagate out
                # of a progress sweep
                h.failed = str(exc)
                h.queue.clear()
                break
            sh.host = None  # bytes are on disk; drop the host copy
            done += 1
        if done:
            _pv_ticks.add(1)
            _pv_shards.add(done)
            if not h.queue and h.failed is None and h.t0_ns:
                # the epoch's async drain just finished: one req_drain
                # flight event per epoch (not per tick) so a request
                # waterfall (DESIGN.md §23) can place the drain-stall
                # window against the run it shadowed
                _obs.record_event(
                    _obs.EV_REQ_DRAIN, _obs.current_band(), h.epoch,
                    (time.perf_counter_ns() - h.t0_ns) // 1000)
                h.t0_ns = 0
        return done

    def _write_shard(self, h: _Handle, sh: _shard.Shard,
                     off: int) -> None:
        from ompi_tpu.datatype import engine as dtmod
        cls = h.inj.pick() if h.inj is not None else None
        if cls == "io_stall":
            time.sleep(h.inj.delay_s)
        elif cls == "io_enospc":
            raise OSError(errno.ENOSPC,
                          "injected ENOSPC (ft_inject io_enospc)")
        nb = sh.nbytes
        host = sh.host
        if cls == "io_partial" and nb > 1:
            nb //= 2  # truncated write: the manifest CRC is over the
            host = host[:nb]  # full shard, so restore detects it
        if nb:
            h.file.write_at(off, (host, nb, dtmod.BYTE))
        _pv_bytes.add(sh.nbytes)

    # -- epoch lifecycle ------------------------------------------------

    def begin(self, comm, payload: Any, root: str) -> int:
        """Collective: plan the epoch and enqueue its shard writes.
        The app-visible cost is plan (residue pickle + numpy
        snapshots) plus the epoch agreement and collective file open
        — not the device drain or the writes."""
        from ompi_tpu import ft_inject
        from ompi_tpu.datatype import engine as dtmod
        from ompi_tpu.io import file as iof
        from ompi_tpu.op.op import SUM

        if self.pending is not None:
            raise RuntimeError("ckpt.begin: an epoch is already in "
                               "flight; commit or abort it first")
        p = _shard.plan(payload)
        h = _Handle()
        h.comm = comm
        h.residue = p.residue
        h.shards = p.shards
        h.nbytes = p.total_nbytes

        # epoch number: rank 0 scans the store, everyone follows
        e = np.array([_next_epoch(root) if comm.rank == 0 else 0],
                     dtype=np.int64)
        comm.Bcast(e, root=0)
        h.epoch = int(e[0])
        h.dir = _epoch_dir(root, h.epoch)
        os.makedirs(h.dir, exist_ok=True)

        # byte offsets: exclusive prefix sum of region sizes
        mine = np.array([h.nbytes], dtype=np.int64)
        off = np.zeros(1, dtype=np.int64)
        comm.Exscan(mine, off, SUM)
        if comm.rank == 0:
            off[0] = 0  # MPI leaves rank 0's Exscan recvbuf undefined
        h.my_off = int(off[0])

        # sharedfp=false: the engine only uses explicit offsets, so
        # the file carries no shared-pointer window — nothing polls
        # progress for the epoch's whole (possibly long) drain life
        h.file = iof.open(comm, os.path.join(h.dir, "data.bin"),
                          iof.MODE_CREATE | iof.MODE_RDWR,
                          info={"sharedfp": "false"})
        h.inj = ft_inject.io_injector(comm.rank)

        if int(_drain_depth_var.value) <= 0:
            self._write_sync(h)
        else:
            # the residue is host bytes already — write it inline (it
            # is part of the enqueue cost, like the numpy snapshots)
            if h.residue:
                h.file.write_at(
                    h.my_off,
                    (np.frombuffer(h.residue, dtype=np.uint8),
                     len(h.residue), dtmod.BYTE))
            _pv_bytes.add(len(h.residue))
            o = h.my_off + len(h.residue)
            for sh in p.shards:
                h.queue.append((sh, o))
                o += sh.nbytes
            h.t0_ns = time.perf_counter_ns()
        self.pending = h
        return h.epoch

    def _write_sync(self, h: _Handle) -> None:
        """cr_drain_depth 0: drain everything now and push the whole
        region through one fcoll two-phase collective write.  Injected
        ENOSPC is agreed *before* the collective so no rank enters it
        alone (a lone raise would strand peers in fcoll's barrier)."""
        from ompi_tpu.datatype import engine as dtmod
        from ompi_tpu.op.op import SUM

        comm = h.comm
        img = np.empty(h.nbytes, dtype=np.uint8)
        img[:len(h.residue)] = np.frombuffer(h.residue, dtype=np.uint8)
        o = len(h.residue)
        for sh in h.shards:
            _shard.drain(sh)
            cls = h.inj.pick() if h.inj is not None else None
            if cls == "io_stall":
                time.sleep(h.inj.delay_s)
            elif cls == "io_enospc":
                h.failed = "injected ENOSPC (ft_inject io_enospc)"
            view = img[o:o + sh.nbytes]
            view[:] = sh.host
            if cls == "io_partial" and sh.nbytes > 1:
                view[sh.nbytes // 2:] = 0  # truncation: CRC catches it
            sh.host = None
            o += sh.nbytes
        err = np.array([1 if h.failed is not None else 0],
                       dtype=np.int64)
        tot = np.zeros(1, dtype=np.int64)
        comm.Allreduce(err, tot, SUM)
        if int(tot[0]):
            return  # all ranks skip the write; commit raises together
        h.file.write_at_all(h.my_off, (img, h.nbytes, dtmod.BYTE))
        _pv_shards.add(len(h.shards))
        _pv_bytes.add(h.nbytes)

    def commit(self) -> int:
        """Collective: finish the drain, agree no rank hit an I/O
        error, fsync, gather per-rank manifests to rank 0, publish.
        Returns the committed epoch.  On an agreed I/O error the epoch
        directory is left uncommitted (restore ignores it) and OSError
        raises on every rank."""
        from ompi_tpu.op.op import SUM

        h = self.pending
        if h is None:
            return -1
        comm = h.comm
        while h.queue and h.failed is None:
            self._drain_some(h)
        err = np.array([1 if h.failed is not None else 0],
                       dtype=np.int64)
        tot = np.zeros(1, dtype=np.int64)
        comm.Allreduce(err, tot, SUM)
        if int(tot[0]):
            self.pending = None
            _pv_aborted.add(1)
            _obs.record_event(_obs.EV_CKPT_ABORT, h.epoch,
                              rank=comm.rank)
            h.file.close()  # collective; every rank is in this branch
            raise OSError(
                errno.EIO,
                f"ckpt: epoch {h.epoch} aborted — I/O error on "
                f"{int(tot[0])} rank(s)"
                + (f" (local: {h.failed})" if h.failed else ""))
        h.file.sync()  # phase 1 done: my region is durable

        # phase 2: rank 0 collects every rank's manifest entry (sent
        # only after that rank's fsync) and publishes atomically
        entry = {
            "rank": comm.rank,
            "off": h.my_off,
            "nbytes": h.nbytes,
            "residue": {"off": 0, "nbytes": len(h.residue),
                        "crc": zlib.crc32(h.residue)},
            "shards": [],
        }
        o = len(h.residue)
        for sh in h.shards:
            m = sh.meta()
            m["off"] = o
            entry["shards"].append(m)
            o += sh.nbytes
        pml = comm.state.pml
        from ompi_tpu.datatype import engine as dtmod
        blob = np.frombuffer(pickle.dumps(entry), dtype=np.uint8)
        req = pml.isend(blob, blob.size, dtmod.BYTE, 0, T_MANIFEST,
                        comm)
        if comm.rank == 0:
            ranks: Dict[str, Any] = {}
            for src in range(comm.size):
                st = pml.probe(src, T_MANIFEST, comm)
                data = np.empty(st.count, dtype=np.uint8)
                pml.recv(data, st.count, dtmod.BYTE, src, T_MANIFEST,
                         comm)
                ent = pickle.loads(data.tobytes())
                ranks[str(ent["rank"])] = ent
            man = {"epoch": h.epoch, "nprocs": comm.size,
                   "ranks": ranks}
            tmp = os.path.join(h.dir, "manifest.json.tmp")
            with open(tmp, "w") as fh:
                json.dump(man, fh)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, os.path.join(h.dir, "manifest.json"))
            self._publish(comm, h.epoch)
            self._prune(os.path.dirname(h.dir), h.epoch)
        req.wait()
        h.file.close()  # internal barrier: commit is global on return
        self.pending = None
        _pv_epochs.add(1)
        _obs.record_event(_obs.EV_CKPT_COMMIT, h.epoch,
                          rank=comm.rank)
        return h.epoch

    def _publish(self, comm, epoch: int) -> None:
        """Put-once commit record in the ULFM KV plane: the in-job
        half of the two-phase commit (restore candidates come from the
        store scan; the KV record lets tooling and tests observe the
        commit without touching the filesystem)."""
        from ompi_tpu.ft import ulfm as _ulfm
        try:
            _ulfm._store(comm.state).put_once(
                ("cr_ckpt", "commit", epoch),
                {"epoch": epoch, "nprocs": comm.size})
        except Exception:
            pass  # the manifest rename is authoritative

    def _prune(self, root: str, epoch: int) -> None:
        import shutil
        keep = keep_epochs()
        committed = _committed_epochs(root)
        drop = committed[keep:] if keep else []
        # torn directories older than this commit are garbage: no
        # in-flight epoch can predate a committed one
        drop += [e for e in _scan_epochs(root)
                 if e < epoch and e not in committed]
        for e in drop:
            shutil.rmtree(_epoch_dir(root, e), ignore_errors=True)

    # -- teardown -------------------------------------------------------

    def abort(self) -> None:
        """Drop the in-flight epoch torn (local, non-collective: the
        job just lost ranks, so File.close's barrier is not an
        option).  The epoch directory stays on disk without a
        manifest; restore never selects it and the next commit's prune
        removes it."""
        h = self.pending
        if h is None:
            return
        self.pending = None
        h.queue.clear()
        _pv_aborted.add(1)
        _obs.record_event(_obs.EV_CKPT_ABORT, h.epoch)
        if h.file is not None:
            h.file.ft_abandon()

    def _finalize(self) -> None:
        try:
            if self.pending is not None:
                self.commit()
        finally:
            self.state.progress.unregister(self.tick)
            self.state.extra.pop("cr_ckpt", None)


def _engine(state) -> Engine:
    eng = state.extra.get("cr_ckpt")
    if eng is None:
        eng = Engine(state)
        state.extra["cr_ckpt"] = eng
    return eng


def pending_epoch(state) -> int:
    """Epoch currently in flight on this rank (-1 = none)."""
    eng = state.extra.get("cr_ckpt")
    return eng.pending.epoch if eng is not None and eng.pending else -1


# ---------------------------------------------------------------------
# public collective API
# ---------------------------------------------------------------------

def checkpoint(comm, payload: Any, store_dir: Optional[str] = None,
               fs: Optional[bool] = None) -> Tuple[int, int]:
    """Tiered collective checkpoint.  Buddy replicas refresh every
    call; a filesystem epoch is begun every ``cr_fs_interval``-th call
    (``fs=True``/``False`` overrides).  Returns ``(buddy_seq,
    fs_epoch)``, either -1 when that tier did not run.

    The previous epoch's commit is folded into this call — its drain
    had the whole inter-checkpoint window to complete, so the commit
    is normally just fsync + manifest exchange.  With no filesystem
    root configured this is a straight buddy passthrough (zero cost
    when both tiers are off)."""
    state = comm.state
    root = _root(store_dir)
    fs_epoch = -1
    if root:
        t0 = time.perf_counter()
        eng = _engine(state)
        if eng.pending is not None:
            eng.commit()
        if fs is None:
            iv = max(1, int(_fs_interval_var.value))
            d = np.array([1 if eng.calls % iv == 0 else 0],
                         dtype=np.int64)
            comm.Bcast(d, root=0)  # replacements must not diverge
            do_fs = bool(int(d[0]))
        else:
            do_fs = bool(fs)
        eng.calls += 1
        bseq = _buddy.checkpoint(comm, payload)
        if do_fs:
            fs_epoch = eng.begin(comm, payload, root)
            if int(_drain_depth_var.value) <= 0:
                eng.commit()
        _pv_stall.update_max((time.perf_counter() - t0) * 1e6)
        return bseq, fs_epoch
    return _buddy.checkpoint(comm, payload), fs_epoch


def committed_epochs(store_dir: Optional[str] = None) -> List[int]:
    """Committed filesystem epochs (manifest present), newest first.
    Local, non-collective: the DVM preemption path and tests use it
    to ask "would a restore here find durable state?" without
    touching any communicator — a preempted session's world is
    already torn down when the question matters."""
    root = _root(store_dir)
    if not root:
        return []
    return _committed_epochs(root)


def flush(comm) -> int:
    """Collective: commit the in-flight epoch now (tests, clean
    shutdown before a planned stop).  Returns the epoch, -1 if none
    was pending."""
    eng = comm.state.extra.get("cr_ckpt")
    if eng is None:
        return -1
    return eng.commit()


def ft_abort(state) -> None:
    """Drop any in-flight epoch torn after a rank failure.  Called by
    ``respawn.rejoin`` on every survivor before the world is rewired —
    an epoch begun with dead ranks can never commit (the manifest
    gather would hang), and the previous committed epoch is intact by
    two-phase construction."""
    eng = state.extra.get("cr_ckpt")
    if eng is not None:
        eng.abort()


def restore(comm, store_dir: Optional[str] = None) -> Optional[Any]:
    """Collective restore down the ladder: buddy replica first (fast
    path), filesystem epoch replay second, ``None`` when neither tier
    has a restorable snapshot (caller escalates to job restart).

    An in-flight epoch is committed first on a *healthy* world (so the
    newest state is restorable); after a failure ``rejoin`` has
    already dropped it.  A successful filesystem restore re-seeds the
    buddy tier so the next failure takes the fast path again."""
    state = comm.state
    eng = state.extra.get("cr_ckpt")
    if eng is not None and eng.pending is not None:
        eng.commit()
    try:
        out = _buddy.restore(comm)
    except RuntimeError:
        # rank + all its partners gone: the buddy tier is lost for at
        # least one rank — the collective raise is deterministic, so
        # every rank arrives here together
        out = None
    if out is not None:
        _pv_rest_buddy.add(1)
        return out
    root = _root(store_dir)
    if not root:
        return None
    out = _fs_restore(comm, root)
    if out is None:
        return None
    _pv_rest_fs.add(1)
    _buddy.checkpoint(comm, out)  # rebuild replicas on the new world
    return out


def _fs_restore(comm, root: str) -> Optional[Any]:
    from ompi_tpu.datatype import engine as dtmod
    from ompi_tpu.io import file as iof
    from ompi_tpu.op.op import MIN

    cand = np.full(_MAX_CANDIDATES, -1, dtype=np.int64)
    if comm.rank == 0:
        es = _committed_epochs(root)[:_MAX_CANDIDATES]
        cand[:len(es)] = es
    comm.Bcast(cand, root=0)
    for e in cand:
        epoch = int(e)
        if epoch < 0:
            continue
        man = _bcast_manifest(comm, root, epoch)
        if man is None:
            continue
        entry = man["ranks"][str(comm.rank)]
        data = np.empty(int(entry["nbytes"]), dtype=np.uint8)
        try:
            f = iof.open(comm,
                         os.path.join(_epoch_dir(root, epoch),
                                      "data.bin"),
                         iof.MODE_RDONLY,
                         info={"sharedfp": "false"})
        except OSError:
            continue  # open errors are agreed: symmetric on all ranks
        if data.size:
            f.read_at_all(int(entry["off"]),
                          (data, data.size, dtmod.BYTE))
        f.close()
        ok = 1
        r = entry["residue"]
        if zlib.crc32(data[r["off"]:r["off"] + r["nbytes"]]) != r["crc"]:
            ok = 0
        for m in entry["shards"]:
            raw = data[m["off"]:m["off"] + m["nbytes"]]
            if zlib.crc32(raw) != m["crc"]:
                ok = 0
        good = np.array([ok], dtype=np.int64)
        tot = np.ones(1, dtype=np.int64)
        comm.Allreduce(good, tot, MIN)
        if not int(tot[0]):
            # a shard somewhere in the epoch is torn or corrupt: never
            # restore a damaged epoch — fall back to the previous one
            _pv_crc_fb.add(1)
            _obs.record_event(_obs.EV_CKPT_CRC_FALLBACK, epoch,
                              rank=comm.rank)
            continue
        residue = data[r["off"]:r["off"] + r["nbytes"]].tobytes()
        metas = entry["shards"]

        def fetch(i: int, _d=data, _m=metas) -> np.ndarray:
            mm = _m[i]
            return _d[mm["off"]:mm["off"] + mm["nbytes"]]

        return _shard.rebuild(residue, metas, fetch, comm.state.device)
    return None


def _bcast_manifest(comm, root: str,
                    epoch: int) -> Optional[Dict[str, Any]]:
    """Rank 0 reads + validates manifest.json, broadcasts it pickled.
    Returns None (on every rank) when it is unreadable or was written
    for a different world size."""
    blob = b""
    if comm.rank == 0:
        try:
            with open(os.path.join(_epoch_dir(root, epoch),
                                   "manifest.json")) as fh:
                man = json.load(fh)
            if int(man.get("nprocs", -1)) != comm.size:
                man = None
        except (OSError, ValueError):
            man = None
        blob = pickle.dumps(man)
    n = np.array([len(blob)], dtype=np.int64)
    comm.Bcast(n, root=0)
    buf = np.empty(int(n[0]), dtype=np.uint8)
    if comm.rank == 0:
        buf[:] = np.frombuffer(blob, dtype=np.uint8)
    comm.Bcast(buf, root=0)
    return pickle.loads(buf.tobytes())
