"""Buddy checkpointing: in-memory partner-replicated snapshots.

SCR-style multi-level C/R (Moody et al., "Design, Modeling, and
Evaluation of a Scalable Multi-level Checkpointing System") applied to
the TPU-host model: each rank's checkpoint blob — the same sharded
image (cr/shard.py: pickled residue + CRC-stamped array shards) the
filesystem tier writes — is serialized once and replicated over the
wire to ``cr_buddy_degree`` partner ranks, who hold it in process memory
(``ProcState.extra["cr_buddy"]``).  Nothing touches a filesystem: the
copies live exactly where a respawned replacement can reach them over
MPI p2p, which is what makes kill -> respawn -> restore work without a
shared store (ISSUE 5 acceptance: the replacement restores "without
reading the filesystem checkpoint store").

Placement is a failure-domain-aware ring: copy k of rank r lives on
``(r + o_k) % size`` where the offsets ``o_k`` are chosen (from the
node_id each rank published into the modex at init) so that every
rank's partner lives on a DIFFERENT host whenever the job spans more
than one — a whole host dying then never takes a rank and all its
replicas together.  On a single host the offsets degrade to the
classic ring ``o_k = k`` (SCR partner placement).  A single failure
between two checkpoints is always recoverable with degree >= 1;
simultaneous loss of a rank AND all its partners is not (that is the
filesystem store's job — the two layers compose, ``cr.checkpoint``
for cold durability, buddy for fast in-job recovery).

Commit protocol (tolerates a rank dying mid-checkpoint): every rank
stores its own blob AND its partners' blobs *before* the barrier;
``committed`` advances only after.  At restore the target sequence is
``max(committed)`` over the group — if any rank committed S, every
rank reached the barrier for S, so every rank (including a dead one's
partner) stored S first.  The last ``KEEP_SEQS`` sequences are
retained so the pre-barrier window never discards the only restorable
snapshot.

API (collective over a full-world-size communicator, same contract as
``cr.quiesce``):

    buddy.checkpoint(comm, payload)   # -> seq, or -1 when degree == 0
    payload = buddy.restore(comm)     # -> None when nothing committed

Zero-cost-when-off: with ``cr_buddy_degree`` 0 (the default),
``checkpoint`` returns after a single int check — no quiesce, no
pickle, no traffic (the --probe-respawn budget check measures this).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ompi_tpu.cr import _keep_var as _cr_keep_var
from ompi_tpu.cr import quiesce
from ompi_tpu.cr import shard as _shard
from ompi_tpu.mca.params import registry as _registry

_degree_var = _registry.register(
    "cr", "buddy", "degree", 0, int,
    help="In-memory buddy-checkpoint replicas per rank (SCR-style "
         "partner ring, offsets skipping same-host partners when the "
         "job spans multiple node_ids).  0 disables buddy "
         "replication entirely; 1 survives any single rank failure "
         "between checkpoints — including a whole-host failure when "
         "placement found an off-host offset")

_pv_ckpts = _registry.register_pvar(
    "cr", "buddy", "checkpoints",
    help="Buddy checkpoints committed by this rank")
_pv_bytes = _registry.register_pvar(
    "cr", "buddy", "bytes_replicated",
    help="Checkpoint bytes this rank shipped to partner ranks")
_pv_partner = _registry.register_pvar(
    "cr", "buddy", "partner_restores",
    help="Times this rank served a held partner copy to a restoring "
         "(typically respawned) rank")
_pv_crc_fallback = _registry.register_pvar(
    "cr", "buddy", "restore_crc_fallbacks",
    help="Buddy restores abandoned because a rank's replica failed "
         "its CRC (memory corruption in the in-memory tier): the "
         "whole world falls one ladder rung to the fs epoch")
_pv_us = _registry.register_pvar(
    "cr", "buddy", "replicate_us", var_class="highwatermark",
    help="Worst-case wall time of one buddy checkpoint (quiesce + "
         "pickle + ring exchange + commit barrier), microseconds")

# user tags must be >= 0; park buddy traffic far above anything an
# application plausibly uses (one tag pair per ring distance k, one
# pair for restore pulls)
_TAG_BASE = 998_000_000
_TAG_RESTORE = 998_500_000

# self + held sequences retained.  2, not 1: a rank can die after
# storing seq S but before committing it — survivors may then agree on
# S-1, which a keep-1 policy would already have dropped.
KEEP_SEQS = 2


def ring_offsets(nodes: Sequence[int], deg: int) -> List[int]:
    """Partner ring offsets for ``deg`` replicas given each comm
    rank's host (``nodes[r]`` = node_id of comm rank r).

    An offset ``o`` is *host-safe* when EVERY rank's partner at
    ``(r + o) % size`` lives on a different node — so one dead host
    can never hold both a rank's state and its replica.  Host-safe
    offsets are preferred in ascending order; if the topology yields
    fewer than ``deg`` of them (or the job is single-host), the
    remaining slots fall back to the smallest unused plain-ring
    offsets.  Every rank computes the same list from the same modex
    data, which is what keeps the Sendrecv pairing collective."""
    size = len(nodes)
    plain = list(range(1, min(deg, size - 1) + 1))
    if size < 2 or len(set(nodes)) < 2:
        return plain
    out = [o for o in range(1, size)
           if all(nodes[(r + o) % size] != nodes[r]
                  for r in range(size))][:deg]
    if len(out) < deg:
        for o in range(1, size):
            if o not in out:
                out.append(o)
                if len(out) == deg:
                    break
    return out[:deg]


def _rank_nodes(comm) -> List[int]:
    """node_id of every comm rank, from the modex (the value each
    rank published at init).  Missing keys (pre-modex bootstrap
    comms, stub RTEs) deterministically collapse to one host — every
    member reaches the same answer, never a split placement."""
    n = len(comm.group)
    rte = getattr(comm.state, "rte", None)
    if rte is None or not hasattr(rte, "modex_get"):
        return [0] * n
    nodes = [0] * n
    try:
        for i, g in enumerate(comm.group):
            nodes[i] = int(rte.modex_get(g, "node_id"))
    except (KeyError, LookupError, AttributeError, TypeError,
            ValueError):
        return [0] * n
    return nodes


def _buddy_state(state) -> Dict[str, Any]:
    """Per-rank replica store, private to this rank's ProcState (NOT
    world-shared: partners hold copies the way a remote node's RAM
    would, so a thread-world test exercises the same reachability a
    process job has)."""
    bs = state.extra.get("cr_buddy")
    if bs is None:
        bs = {
            "self": {},       # seq -> my pickled blob
            "held": {},       # (owner comm-rank, seq) -> their blob
            "committed": -1,  # newest barrier-committed seq
        }
        state.extra["cr_buddy"] = bs
    return bs


def committed_seq(state) -> int:
    """Newest committed sequence on this rank (-1 = none)."""
    return _buddy_state(state)["committed"]


def _keep_seqs() -> int:
    """Sequences retained per rank: the job-wide ``cr_keep`` knob,
    floored at KEEP_SEQS so the pre-barrier commit window can never
    discard the only restorable snapshot (the same knob prunes the
    filesystem tier's epoch directories — one retention policy across
    tiers).  cr_keep 0 means keep-all there, but buddy copies live in
    partner RAM, so the KEEP_SEQS default applies instead."""
    k = int(_cr_keep_var.value)
    return max(KEEP_SEQS, k) if k > 0 else KEEP_SEQS


def _prune(bs: Dict[str, Any], seq: int) -> None:
    floor = seq - _keep_seqs()  # keep (seq, seq-1, ...)
    for s in [s for s in bs["self"] if s <= floor]:
        del bs["self"][s]
    for k in [k for k in bs["held"] if k[1] <= floor]:
        del bs["held"][k]


def checkpoint(comm, payload: Any, degree: Optional[int] = None) -> int:
    """Collective in-memory snapshot; returns the committed sequence
    number, or -1 when buddy replication is off.  ``degree`` overrides
    the ``cr_buddy_degree`` MCA default for this call."""
    deg = int(_degree_var.value) if degree is None else int(degree)
    if deg <= 0:
        return -1  # zero-cost-when-off: one int check, nothing else
    state = comm.state
    if len(comm.group) != state.size:
        raise ValueError(
            "buddy.checkpoint must run on a full-world-size "
            "communicator (partner placement is defined over the "
            "whole job, like cr.quiesce)")
    size = comm.size
    deg = min(deg, size - 1)
    if deg <= 0:
        return -1
    quiesce(comm)
    # quiesce stays interruptible; the capture/replicate/commit phases
    # must not be torn by an armed ft interrupt (same discipline as
    # cr.checkpoint)
    with state.progress.deferred_interrupts():
        from ompi_tpu.op.op import MAX
        t0 = time.perf_counter()
        bs = _buddy_state(state)
        # agree on the sequence number: a replacement rank that was
        # re-seeded from the filesystem tier (or joined before its
        # first restore) has a stale local counter — max(committed)+1
        # keeps the ring's blob keys aligned on every rank
        me = np.array([bs["committed"]], dtype=np.int64)
        mx = np.empty(1, dtype=np.int64)
        comm.Allreduce(me, mx, MAX)
        seq = int(mx[0]) + 1
        # the exact shard image the filesystem tier writes (residue +
        # CRC-stamped shards), not a second ad-hoc whole-state pickle
        blob = _shard.dumps(payload)
        mine = np.frombuffer(blob, dtype=np.uint8)
        nbytes = np.array([len(blob)], dtype=np.int64)
        peer_n = np.zeros(1, dtype=np.int64)
        # failure-domain-aware placement: offsets chosen so partners
        # sit on a different host whenever the job spans more than one
        offs = ring_offsets(_rank_nodes(comm), deg)
        for k, o in enumerate(offs, start=1):
            dst = (comm.rank + o) % size
            src = (comm.rank - o) % size
            comm.Sendrecv(nbytes, dst, _TAG_BASE + 2 * k,
                          peer_n, src, _TAG_BASE + 2 * k)
            rbuf = np.empty(int(peer_n[0]), dtype=np.uint8)
            comm.Sendrecv(mine, dst, _TAG_BASE + 2 * k + 1,
                          rbuf, src, _TAG_BASE + 2 * k + 1)
            bs["held"][(src, seq)] = rbuf.tobytes()
            _pv_bytes.add(len(blob))
        bs["self"][seq] = blob
        _prune(bs, seq)
        # every rank stored seq (own + held copies) before anyone
        # commits: max(committed) at restore is therefore always a
        # sequence every surviving partner still holds
        comm.Barrier()
        bs["committed"] = seq
        _pv_ckpts.add(1)
        _pv_us.update_max((time.perf_counter() - t0) * 1e6)
    return seq


def restore(comm) -> Optional[Any]:
    """Collective restore from the newest committed buddy snapshot.
    Ranks missing their own copy (a respawned replacement) pull it
    from the lowest-distance surviving partner; every rank then rolls
    back to the same sequence.  Returns the payload, or None when no
    sequence has ever committed."""
    state = comm.state
    if len(comm.group) != state.size:
        raise ValueError(
            "buddy.restore must run on a full-world-size communicator")
    size = comm.size
    bs = _buddy_state(state)
    me = np.array([max(bs["self"], default=-1), bs["committed"]],
                  dtype=np.int64)
    table = np.empty((size, 2), dtype=np.int64)
    comm.Allgather(me, table)
    restore_seq = int(table[:, 1].max())
    if restore_seq < 0:
        comm.Barrier()
        return None
    missing = {r for r in range(size) if table[r, 0] < restore_seq}
    # who holds whose copy at restore_seq (the degree at checkpoint
    # time is not assumed — a copy either survived or it didn't)
    holds = np.zeros(size, dtype=np.uint8)
    for r in range(size):
        if r == comm.rank:
            holds[r] = 1 if restore_seq in bs["self"] else 0
        elif (r, restore_seq) in bs["held"]:
            holds[r] = 1
    htab = np.empty((size, size), dtype=np.uint8)
    comm.Allgather(holds, htab)
    for m in sorted(missing):
        supplier = None
        for k in range(1, size):
            s = (m + k) % size
            if s not in missing and htab[s][m]:
                supplier = s
                break
        if supplier is None:
            raise RuntimeError(
                f"buddy restore: no surviving replica of rank {m}'s "
                f"checkpoint seq {restore_seq} — every partner holding "
                f"it is gone (raise cr_buddy_degree, or checkpoint "
                f"again between failures)")
        if comm.rank == supplier:
            blob = bs["held"][(m, restore_seq)]
            n = np.array([len(blob)], dtype=np.int64)
            comm.Send(n, m, _TAG_RESTORE)
            comm.Send(np.frombuffer(blob, dtype=np.uint8),
                      m, _TAG_RESTORE + 1)
            _pv_partner.add(1)
        elif comm.rank == m:
            n = np.zeros(1, dtype=np.int64)
            comm.Recv(n, supplier, _TAG_RESTORE)
            rbuf = np.empty(int(n[0]), dtype=np.uint8)
            comm.Recv(rbuf, supplier, _TAG_RESTORE + 1)
            bs["self"][restore_seq] = rbuf.tobytes()
    # CRC-verify before trusting the in-memory replica (DESIGN.md
    # §25 rode this in: a corrupting host flips bits in parked blobs
    # too).  The verdict is AGREED — a single corrupt rank sends the
    # whole world one ladder rung down to the fs epoch together,
    # never a world split across checkpoint sequences.
    from ompi_tpu.op.op import MIN
    try:
        out = _shard.loads(bs["self"][restore_seq], state.device)
        ok = 1
    except Exception:
        # shard CRC mismatch (ValueError), or a decode blown up on
        # corrupt metadata the per-shard CRCs don't cover — either
        # way the replica is untrustworthy
        out = None
        ok = 0
    good = np.array([ok], dtype=np.int64)
    tot = np.empty(1, dtype=np.int64)
    comm.Allreduce(good, tot, MIN)
    if int(tot[0]) == 0:
        if comm.rank == 0:
            _pv_crc_fallback.add(1)
        from ompi_tpu import obs as _obs
        _obs.record_event(_obs.EV_CKPT_CRC_FALLBACK,
                          restore_seq, rank=comm.rank)
        raise RuntimeError(
            f"buddy restore: replica CRC mismatch at seq "
            f"{restore_seq} (in-memory tier corrupt) — falling back "
            f"to the filesystem epoch")
    bs["committed"] = restore_seq
    comm.Barrier()
    return out
