"""MPI_T tool-information interface over the variable registry.

Re-design of ompi/mpi/tool (ref: ompi/mpi/tool/mpit-internal.h; the
MPI_T chapter's object model: control variables = the MCA var
registry, performance variables = the pvar registry, categories =
frameworks).  Usable before/after MPI init, like MPI_T itself — the
registry is process-global.

    import ompi_tpu.mpit as mpit
    mpit.init_thread()
    n = mpit.cvar_get_num()
    h = mpit.cvar_handle_alloc("coll_tuned_use_device")
    mpit.cvar_write(h, 0)
    s = mpit.pvar_session_create()
    ph = mpit.pvar_handle_alloc(s, "pml_monitoring_messages_size")
    mpit.pvar_read(ph)
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Dict, List, Optional

from ompi_tpu.mca.params import (PVar, Var, registry, SOURCE_DEFAULT,
                                 SOURCE_ENV, SOURCE_FILE, SOURCE_OVERRIDE)

ERR_INVALID_INDEX = "MPI_T_ERR_INVALID_INDEX"
ERR_INVALID_NAME = "MPI_T_ERR_INVALID_NAME"
ERR_NOT_INITIALIZED = "MPI_T_ERR_NOT_INITIALIZED"

SCOPE_READONLY = "readonly"
SCOPE_ALL = "all"

_lock = threading.Lock()
_init_count = 0


class MpitError(RuntimeError):
    def __init__(self, code: str, msg: str = "") -> None:
        super().__init__(f"{code}: {msg}" if msg else code)
        self.code = code


def init_thread() -> None:
    """MPI_T_init_thread: reference-counted (mpit-internal.h model)."""
    global _init_count
    with _lock:
        _init_count += 1


def finalize() -> None:
    global _init_count
    with _lock:
        if _init_count == 0:
            raise MpitError(ERR_NOT_INITIALIZED)
        _init_count -= 1


def _check_init() -> None:
    if _init_count == 0:
        raise MpitError(ERR_NOT_INITIALIZED, "call mpit.init_thread() first")


# -- control variables ------------------------------------------------------

def cvar_get_num() -> int:
    _check_init()
    return len(registry.vars_in_registration_order())


def _cvar_at(index: int) -> Var:
    # registration order: MPI_T indices must never change once
    # returned, and new registrations only append in this order
    vars_ = registry.vars_in_registration_order()
    if not 0 <= index < len(vars_):
        raise MpitError(ERR_INVALID_INDEX, str(index))
    return vars_[index]


def cvar_get_info(index: int) -> Dict[str, Any]:
    """Name/help/type/level/scope of the index-th variable
    (registration-order enumeration — stable across new
    registrations, as MPI_T requires of indices)."""
    _check_init()
    v = _cvar_at(index)
    return {
        "name": v.full_name,
        "help": v.help,
        "type": v.typ.__name__,
        "level": v.level,
        "scope": SCOPE_READONLY if v.read_only else SCOPE_ALL,
        "default": v.default,
    }


def cvar_get_index(name: str) -> int:
    _check_init()
    for i, v in enumerate(registry.vars_in_registration_order()):
        if v.full_name == name:
            return i
    raise MpitError(ERR_INVALID_NAME, name)


class CvarHandle:
    def __init__(self, var: Var) -> None:
        self.var = var


def cvar_handle_alloc(name_or_index) -> CvarHandle:
    _check_init()
    if isinstance(name_or_index, str):
        return CvarHandle(_cvar_at(cvar_get_index(name_or_index)))
    return CvarHandle(_cvar_at(name_or_index))


def cvar_read(handle: CvarHandle) -> Any:
    _check_init()
    return handle.var.value


def cvar_write(handle: CvarHandle, value: Any) -> None:
    _check_init()
    if handle.var.read_only:
        raise MpitError("MPI_T_ERR_CVAR_SET_NEVER", handle.var.full_name)
    registry.set(handle.var.full_name, value)


# -- performance variables --------------------------------------------------

class PvarSession:
    """MPI_T_pvar_session: isolates handle start/stop/reset baselines
    so concurrent tools don't clobber each other."""

    def __init__(self) -> None:
        self.handles: List["PvarHandle"] = []


class PvarHandle:
    def __init__(self, session: PvarSession, pvar: PVar) -> None:
        self.session = session
        self.pvar = pvar
        self.started = True    # continuous pvars start started
        self._baseline = None  # raw reads until the first reset
        self._frozen = None    # value snapshot while stopped


def pvar_get_num() -> int:
    _check_init()
    return len(registry.pvars_in_registration_order())


def pvar_get_info(index: int) -> Dict[str, Any]:
    _check_init()
    pvars = registry.pvars_in_registration_order()
    if not 0 <= index < len(pvars):
        raise MpitError(ERR_INVALID_INDEX, str(index))
    p = pvars[index]
    return {"name": p.full_name, "help": p.help, "class": p.var_class}


def pvar_get_index(name: str) -> int:
    _check_init()
    for i, p in enumerate(registry.pvars_in_registration_order()):
        if p.full_name == name:
            return i
    raise MpitError(ERR_INVALID_NAME, name)


def pvar_session_create() -> PvarSession:
    _check_init()
    return PvarSession()


def pvar_session_free(session: PvarSession) -> None:
    _check_init()
    session.handles.clear()


def pvar_handle_alloc(session: PvarSession, name_or_index) -> PvarHandle:
    _check_init()
    pvars = registry.pvars_in_registration_order()
    if isinstance(name_or_index, str):
        idx = pvar_get_index(name_or_index)
    else:
        idx = name_or_index
        if not 0 <= idx < len(pvars):
            raise MpitError(ERR_INVALID_INDEX, str(idx))
    h = PvarHandle(session, pvars[idx])
    session.handles.append(h)
    return h


def pvar_start(handle: PvarHandle) -> None:
    _check_init()
    handle.started = True
    handle._frozen = None


def pvar_stop(handle: PvarHandle) -> None:
    """Freeze the handle: reads return the value at stop time."""
    _check_init()
    handle._frozen = copy.deepcopy(handle.pvar.read())
    handle.started = False


def pvar_read(handle: PvarHandle) -> Any:
    """Value relative to the handle's last reset (lists element-wise);
    frozen at the stop-time snapshot while the handle is stopped."""
    _check_init()
    val = handle.pvar.read() if handle.started else handle._frozen
    base = handle._baseline
    if base is None:
        return val
    if isinstance(val, list):
        if isinstance(base, list) and len(base) == len(val):
            return [a - b for a, b in zip(val, base)]
        return list(val)
    if isinstance(val, (int, float)) and isinstance(base, (int, float)):
        return val - base
    return val


def pvar_reset(handle: PvarHandle) -> None:
    _check_init()
    val = handle.pvar.read()
    handle._baseline = copy.deepcopy(val) if isinstance(val, list) else val


# -- categories (frameworks as the category tree) ---------------------------

def category_get_num() -> int:
    _check_init()
    from ompi_tpu.mca.base import frameworks
    return len(frameworks.all())


def category_get_info(index: int) -> Dict[str, Any]:
    _check_init()
    from ompi_tpu.mca.base import frameworks
    fws = frameworks.all()
    if not 0 <= index < len(fws):
        raise MpitError(ERR_INVALID_INDEX, str(index))
    fw = fws[index]
    prefix = fw.name + "_"
    cvars = [i for i, v in enumerate(registry.vars_in_registration_order())
             if v.full_name.startswith(prefix) or v.full_name == fw.name]
    pvars = [i for i, p in enumerate(registry.pvars_in_registration_order())
             if p.full_name.startswith(prefix)]
    return {"name": fw.name, "project": fw.project,
            "num_cvars": len(cvars), "cvar_indices": cvars,
            "num_pvars": len(pvars), "pvar_indices": pvars}


# -- whole-registry snapshot (telemetry plane) ------------------------------

def pvar_snapshot(prefix: Optional[str] = None) -> Dict[str, Any]:
    """Every pvar's current value keyed by full name, in registration
    order.  A tool-facing convenience for the obs scrape path (the DVM
    ``metrics`` RPC and the tpud OOB op): read-only against the
    process-global registry, so — like MPI_T itself — it needs no
    init_thread and never perturbs handle baselines.  Getter errors
    surface as None rather than aborting the scrape.  ``prefix``
    filters by full-name prefix (e.g. ``"dvm_"`` or ``"ctrl_"``) so a
    fleet scraper polling one subsystem does not pay for — or ship —
    the whole registry every tick."""
    out: Dict[str, Any] = {}
    for p in registry.pvars_in_registration_order():
        if prefix is not None and not p.full_name.startswith(prefix):
            continue
        try:
            out[p.full_name] = p.read()
        except Exception:
            out[p.full_name] = None
    return out
