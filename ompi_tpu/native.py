"""Loader for the native C++ data plane (ctypes; no pybind11).

Builds native/libtpumpi_native.so with make on first use when the
toolchain is present; every consumer has a pure-Python fallback, so
a missing compiler only costs performance, never correctness.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libtpumpi_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", _NATIVE_DIR, "-j2"],
                           capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, or None (pure-Python fallback)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        stale = True
        if os.path.exists(_LIB_PATH):
            lib_mtime = os.path.getmtime(_LIB_PATH)
            stale = any(
                os.path.getmtime(os.path.join(_NATIVE_DIR, f)) > lib_mtime
                for f in os.listdir(_NATIVE_DIR) if f.endswith(".cpp"))
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.tpumpi_ring_push.argtypes = [u8p, ctypes.c_uint64, u8p,
                                         ctypes.c_uint64]
        lib.tpumpi_ring_push.restype = ctypes.c_int
        lib.tpumpi_ring_push2.argtypes = [u8p, ctypes.c_uint64, u8p,
                                          ctypes.c_uint64, u8p,
                                          ctypes.c_uint64]
        lib.tpumpi_ring_push2.restype = ctypes.c_int
        lib.tpumpi_ring_peek.argtypes = [u8p, ctypes.c_uint64]
        lib.tpumpi_ring_peek.restype = ctypes.c_int64
        lib.tpumpi_ring_pop.argtypes = [u8p, ctypes.c_uint64, u8p,
                                        ctypes.c_uint64]
        lib.tpumpi_ring_pop.restype = ctypes.c_int
        lib.tpumpi_ring_readable.argtypes = [u8p]
        lib.tpumpi_ring_readable.restype = ctypes.c_uint64
        lib.tpumpi_pack_strided.argtypes = [u8p, u8p, ctypes.c_uint64,
                                            ctypes.c_int64, ctypes.c_uint64]
        lib.tpumpi_pack_strided.restype = None
        lib.tpumpi_unpack_strided.argtypes = [u8p, u8p, ctypes.c_uint64,
                                              ctypes.c_int64,
                                              ctypes.c_uint64]
        lib.tpumpi_unpack_strided.restype = None
        lib.tpumpi_seg_coll.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int64]
        lib.tpumpi_seg_coll.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None
