"""Dynamic process management: spawn / connect / accept / ports.

Re-design of ompi/dpm (ref: ompi/dpm/dpm.c — connect_accept builds
the bridge and calls add_procs; spawn goes through the runtime's
PMIx server).  Here the launcher's KV server is the universe
authority: it allocates universe-rank blocks for spawned jobs and
carries the port rendezvous records; mpirun drains spawn requests and
fork/execs the new job with TPUMPI_WORLD_BASE/TPUMPI_UNIVERSE env
identity (tools/mpirun.py).

The cross-job handshake needs p2p before any shared communicator
exists, so leaders meet on a **bridge**: a comm-shaped shim whose cid
is derived from the accept/spawn record (universe-unique, negative so
it can never collide with agreed cids) and whose 2-entry group is
[side-A leader, side-B leader].  intercomm_create() then runs its
normal leader exchange + bridged CID agreement over that bridge.
"""

from __future__ import annotations

import uuid
from typing import List, Optional

from .communicator import Communicator, Group
from .intercomm import Intercommunicator, intercomm_create

# bridge cids live far below user/agreed cids and are derived from
# universe-unique integers (a spawn's rank base; an accept's sequence)
_SPAWN_CID_BASE = -1_000_000
_PORT_CID_BASE = -2_000_000


class _BridgeComm:
    """Comm-shaped shim for leader-to-leader p2p before a real
    communicator exists.  group = [leaderA_global, leaderB_global];
    my rank is my index in it."""

    def __init__(self, state, cid: int, leaders: List[int]) -> None:
        self.state = state
        self.cid = cid
        self.group = list(leaders)
        self.rank = self.group.index(state.rank)
        self.size = len(self.group)

    def _bridge_peer(self) -> int:
        return 1 - self.rank


def _kv(state):
    kv = getattr(state.rte, "kv", None)
    if kv is None:
        raise RuntimeError(
            "dynamic process management needs the launcher's KV "
            "server (run under mpirun)")
    return kv


# ---------------------------------------------------------------------
# ports + name service (ref: ompi/mpi/c/open_port.c, publish_name.c)
# ---------------------------------------------------------------------

def open_port(state) -> str:
    return f"tpumpi-port-{state.rank}-{uuid.uuid4().hex[:12]}"


def publish_name(state, service: str, port: str) -> None:
    _kv(state).put(f"svc:{service}", port)


def lookup_name(state, service: str) -> str:
    return _kv(state).get(f"svc:{service}")


def unpublish_name(state, service: str) -> None:
    _kv(state).put(f"svc:{service}", None)


# ---------------------------------------------------------------------
# connect / accept (ref: dpm.c ompi_dpm_connect_accept)
# ---------------------------------------------------------------------

def comm_accept(comm: Communicator, port: str, root: int = 0
                ) -> Intercommunicator:
    """Collective over `comm`; the root posts the accept record and
    waits for a connector."""
    state = comm.state
    import numpy as np
    meta = np.empty(2, dtype=np.int64)
    if comm.rank == root:
        kv = _kv(state)
        # pair acceptor i with connector i on this port (sequence
        # counters), and draw the bridge cid from a universe-global
        # counter so concurrent handshakes can never collide
        aseq = kv.incr(f"port:{port}:aseq")
        cid = _PORT_CID_BASE - kv.incr("dpm:bridge_cid")
        kv.put(f"port:{port}:accept:{aseq}",
               {"leader": state.rank, "cid": cid})
        try:
            peer = kv.take(f"port:{port}:connect:{aseq}", timeout=300.0)
        except TimeoutError:
            # No connector: withdraw the offer so the port counters
            # stay in sync for later pairs.  If the record is already
            # gone a connector consumed it while we timed out — the
            # rendezvous actually succeeded, so finish it.
            try:
                kv.take(f"port:{port}:accept:{aseq}", timeout=0.05)
                withdrawn = True
            except TimeoutError:
                withdrawn = False
            if withdrawn:
                kv.uncr(f"port:{port}:aseq", aseq)
                raise
            peer = kv.take(f"port:{port}:connect:{aseq}", timeout=30.0)
        meta[0] = cid
        meta[1] = peer["leader"]
    comm.Bcast(meta, root=root)
    cid, remote_leader = int(meta[0]), int(meta[1])
    return _bridged_create(comm, root, cid, remote_leader,
                           accept_side=True)


def comm_connect(comm: Communicator, port: str, root: int = 0
                 ) -> Intercommunicator:
    state = comm.state
    import numpy as np
    meta = np.empty(2, dtype=np.int64)
    if comm.rank == root:
        kv = _kv(state)
        cseq = kv.incr(f"port:{port}:cseq")
        try:
            acc = kv.take(f"port:{port}:accept:{cseq}", timeout=300.0)
        except TimeoutError:
            # No acceptor: return the ticket so the next well-matched
            # pair on this port still lines up (counter-desync guard)
            kv.uncr(f"port:{port}:cseq", cseq)
            raise
        kv.put(f"port:{port}:connect:{cseq}", {"leader": state.rank})
        meta[0] = acc["cid"]
        meta[1] = acc["leader"]
    comm.Bcast(meta, root=root)
    cid, remote_leader = int(meta[0]), int(meta[1])
    return _bridged_create(comm, root, cid, remote_leader,
                           accept_side=False)


def _bridged_create(comm: Communicator, root: int, bridge_cid: int,
                    remote_leader: int, accept_side: bool
                    ) -> Intercommunicator:
    """Common tail: make dynamic peers addressable, build the bridge,
    run the intercomm creation handshake over it."""
    from ompi_tpu.runtime.init import extend_universe

    state = comm.state
    # make the remote LEADER addressable first (the handshake is
    # leader-to-leader); the full remote group is learned during
    # creation and covered right after
    extend_universe(state, remote_leader + 1)
    if comm.rank == root:
        leaders = ([state.rank, remote_leader] if accept_side
                   else [remote_leader, state.rank])
        bridge = _BridgeComm(state, bridge_cid, leaders)
        inter = intercomm_create(comm, root, bridge,
                                 bridge._bridge_peer(), tag=0)
    else:
        inter = intercomm_create(comm, root, None, 0, tag=0)
    # now every remote member is known: cover the whole remote group
    extend_universe(state, max(inter.group) + 1)
    return inter


# ---------------------------------------------------------------------
# spawn (ref: dpm.c ompi_dpm_spawn + MPI_Comm_spawn)
# ---------------------------------------------------------------------

def comm_spawn(comm: Communicator, cmd: str, args: List[str],
               maxprocs: int, root: int = 0) -> Intercommunicator:
    """Collective over `comm`: launch `maxprocs` new universe ranks
    running `cmd` and return the parent-side intercomm."""
    return comm_spawn_multiple(
        comm, [(cmd, list(args), maxprocs)], root)


def comm_spawn_multiple(comm: Communicator, specs, root: int = 0
                        ) -> Intercommunicator:
    """MPI_Comm_spawn_multiple: specs = [(cmd, args, maxprocs), ...],
    all children in ONE world (per-segment MPI_APPNUM set)."""
    from ompi_tpu.runtime.init import extend_universe

    state = comm.state
    import numpy as np
    maxprocs = sum(int(n) for _c, _a, n in specs)
    meta = np.empty(1, dtype=np.int64)
    if comm.rank == root:
        base = _kv(state).spawn_multiple(
            [{"cmd": c, "args": list(a), "n": int(n)}
             for c, a, n in specs], state.rank)
        meta[0] = base
    comm.Bcast(meta, root=root)
    base = int(meta[0])
    extend_universe(state, base + maxprocs)
    bridge_cid = _SPAWN_CID_BASE - base
    if comm.rank == root:
        bridge = _BridgeComm(state, bridge_cid, [state.rank, base])
        return intercomm_create(comm, root, bridge, 1, tag=0)
    return intercomm_create(comm, root, None, 1, tag=0)


def get_parent(comm_world: Communicator) -> Optional[Intercommunicator]:
    """MPI_Comm_get_parent analog: in a spawned job, the intercomm to
    the spawning communicator (collective over comm_world on first
    call)."""
    state = comm_world.state
    parent_root = getattr(state.rte, "parent_root", None)
    if parent_root is None:
        return None
    cached = state.extra.get("parent_intercomm")
    if cached is not None:
        return cached
    parent_root = int(parent_root)
    base = getattr(state.rte, "world_base", 0)
    bridge_cid = _SPAWN_CID_BASE - base
    if comm_world.rank == 0:
        bridge = _BridgeComm(state, bridge_cid,
                             [parent_root, state.rank])
        inter = intercomm_create(comm_world, 0, bridge, 0, tag=0)
    else:
        inter = intercomm_create(comm_world, 0, None, 0, tag=0)
    state.extra["parent_intercomm"] = inter
    return inter


# ---------------------------------------------------------------------
# join (ref: ompi/mpi/c/comm_join.c — two processes holding the ends
# of a connected socket build a 1-1 intercommunicator by exchanging
# port names over the fd, then running connect/accept)
# ---------------------------------------------------------------------

def comm_join(comm_self: Communicator, fd: int) -> Intercommunicator:
    """MPI_Comm_join: ``fd`` is a connected, bidirectional socket
    shared with exactly one peer process of the same universe.  Each
    side opens a port and sends it over the fd; the side with the
    lexicographically smaller port string accepts on its own port,
    the other connects to the received one (the reference decides
    send_first by the same kind of total order)."""
    import os
    import struct as _struct

    state = comm_self.state
    my_port = open_port(state)

    def _write_all(data: bytes) -> None:
        off = 0
        while off < len(data):
            off += os.write(fd, data[off:])

    def _read_exact(n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = os.read(fd, n - len(out))
            if not chunk:
                raise ConnectionError(
                    "MPI_Comm_join: peer closed the socket during "
                    "the port exchange")
            out += chunk
        return out

    enc = my_port.encode()
    _write_all(_struct.pack(">I", len(enc)) + enc)
    (n,) = _struct.unpack(">I", _read_exact(4))
    peer_port = _read_exact(n).decode()
    if my_port == peer_port:
        raise ValueError("MPI_Comm_join: both ends exchanged the "
                         "same port name (fd looped back to self?)")
    if my_port < peer_port:
        return comm_accept(comm_self, my_port, root=0)
    return comm_connect(comm_self, peer_port, root=0)
