"""Intercommunicators: two disjoint groups bridged for p2p and
two-group collectives.

Re-design of the reference's intercomm paths
(ref: ompi/communicator/comm.c:1100+ ompi_intercomm_create;
ompi/mpi/c/intercomm_create.c / intercomm_merge.c; coll/inter
semantics in ompi/mca/coll/inter).

Data model: an Intercommunicator carries BOTH groups.  ``rank`` and
``size`` refer to the LOCAL group (MPI_Comm_rank/size semantics);
p2p destination/source indices address the REMOTE group — which is
exactly what the pml's ``comm.group[dst]`` translation needs, so the
``group`` property exposes the remote ranks and the matching engine
works unchanged (the sender's local rank IS the receiver's remote
index, because each side's remote group is the other's local group in
the same order).

Construction runs the reference's two-level agreement: group lists
exchanged leader-to-leader over a bridge, broadcast locally, then a
CID agreed over the UNION by iterating (local max-allreduce ->
leader exchange -> local bcast) until the cid is free on every member
of both groups (the comm_cid.c multi-round idea stretched over the
bridge).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from .communicator import Communicator, Group, TAG_CID

# MPI_ROOT sentinel for rooted intercomm collectives (the root-group
# rank that sources/sinks the data passes ROOT; its peers PROC_NULL)
ROOT = -4

TAG_IBRIDGE = -26
TAG_IMERGE = -27
TAG_ISPLIT = -28


def _icreate_wire_tag(tag: int) -> int:
    """Fold the user's intercomm_create tag into the dedicated
    [-1500, -1999] block so it can never land on another internal
    protocol's tag (small negatives, create_group's [-400,-1399],
    nbc's <=-2000) or in non-negative user tag space.  Like
    create_group's fold, CONCURRENT creations between the same leader
    pair with tags 500 apart would alias — the (peer_comm, tag) pair
    disambiguates real uses; sequential creations are always safe
    (matching is ordered per (cid, src, tag))."""
    return -1500 - (tag % 500)


class Intercommunicator(Communicator):
    def __init__(self, state, cid: int, local_group: Group,
                 remote_group: Group, local_comm: Communicator,
                 name: str = "") -> None:
        self._remote_group = remote_group
        # base ctor computes rank/size from the LOCAL group and stacks
        # the coll modules (comm_select special-cases intercomms)
        super().__init__(state, cid, local_group,
                         name or f"intercomm-{cid}")
        self.local_comm = local_comm  # private dup for local phases

    # -- identity ------------------------------------------------------
    @property
    def is_inter(self) -> bool:
        return True

    @property
    def group(self) -> List[int]:
        """p2p rank translation table = the REMOTE group."""
        return self._remote_group.ranks

    def local_group_obj(self) -> Group:
        return Group(self._group.ranks)

    def remote_group_obj(self) -> Group:
        return Group(self._remote_group.ranks)

    @property
    def remote_size(self) -> int:
        return self._remote_group.size

    # -- capabilities ---------------------------------------------------
    def mesh(self):
        return None  # never device-offloadable as one mesh

    def split(self, color: int, key: int = 0):
        """MPI_Comm_split on an intercommunicator (ref:
        ompi/mpi/c/comm_split.c -> ompi_comm_split inter branch):
        members of the SAME color on both sides form a new
        intercommunicator; a color with members on only one side gets
        MPI_COMM_NULL (None), per MPI-3.1 §6.4.2.

        Both sides order each color group by (key, old local rank),
        computed identically from the exchanged (color, key) tables.
        """
        from .communicator import UNDEFINED
        _init_dt()
        lc = self.local_comm
        pml = self._pml()

        # 1. allgather (color, key) within each local group, in old
        # local-rank order
        mine = np.array([color, key], dtype=np.int64)
        local_tbl = np.empty((lc.size, 2), dtype=np.int64)
        lc.Allgather(mine, local_tbl)

        # 2. leaders exchange the full tables across the bridge
        # (local rank 0 <-> remote rank 0 over the intercomm), then
        # bcast locally
        if lc.rank == 0:
            sreq = pml.isend(local_tbl, local_tbl.size, _I64, 0,
                             TAG_ISPLIT, self)
            remote_tbl = np.empty((self.remote_size, 2),
                                  dtype=np.int64)
            pml.recv(remote_tbl, remote_tbl.size, _I64, 0,
                     TAG_ISPLIT, self)
            sreq.wait()
        else:
            remote_tbl = np.empty((self.remote_size, 2),
                                  dtype=np.int64)
        lc.Bcast(remote_tbl, root=0)

        # 3. my color's ordered subgroups on both sides (global ranks)
        def members(tbl, group):
            out = [(int(tbl[i][1]), i, group[i])
                   for i in range(len(group))
                   if int(tbl[i][0]) == color]
            out.sort()
            return [g for (_k, _i, g) in out]

        # 4. split the private local comm (handles UNDEFINED and the
        # local cid agreement); every member of the old intercomm
        # participates (comm_split is collective over both groups)
        local_split = lc.split(color, key)
        if color == UNDEFINED or local_split is None:
            return None
        my_local = members(local_tbl, self._group.ranks)
        my_remote = members(remote_tbl, self._remote_group.ranks)
        if not my_remote:
            # my color exists only on this side -> MPI_COMM_NULL
            local_split.free()
            return None

        # 5. cid agreement between the two color groups: the color
        # leaders bridge over the OLD intercomm (distinct leader
        # pairs per color -> per-(src) matching keeps them apart)
        am_leader = my_local[0] == self.state.rank
        if am_leader:
            # remote color leader's index in the old REMOTE group
            r_leader = self._remote_group.ranks.index(my_remote[0])
            bridge = _SplitBridge(self, r_leader)
            cid = _bridge_cid_agree_leader(self.state, local_split,
                                           bridge, 0)
        else:
            cid = _bridge_cid_agree_leader(self.state, local_split,
                                           None, 0)
        out = Intercommunicator(self.state, cid, Group(my_local),
                                Group(my_remote), local_split,
                                name=f"{self.name}-split")
        out.errhandler = self.errhandler  # MPI: children inherit
        return out

    def free(self) -> None:
        self.local_comm.free()
        super().free()

    # -- merge ----------------------------------------------------------
    def merge(self, high: bool = False) -> Communicator:
        """MPI_Intercomm_merge (ref: intercomm_merge.c): one intracomm
        over the union; the 'low' group's ranks come first.  Ties on
        `high` break by smallest global rank so both sides compute the
        same order."""
        lc = self.local_comm
        # leaders exchange (high, min_global_rank)
        mine = np.array([1 if high else 0, min(self._group.ranks)],
                        dtype=np.int64)
        if lc.rank == 0:
            sreq = self._pml().isend(mine, 2, _I64, 0, TAG_IMERGE, self)
            theirs = np.empty(2, dtype=np.int64)
            self._pml().recv(theirs, 2, _I64, 0, TAG_IMERGE, self)
            sreq.wait()
        else:
            theirs = np.empty(2, dtype=np.int64)
        lc.Bcast(theirs, root=0)
        r_high, r_min = int(theirs[0]), int(theirs[1])
        my_high = 1 if high else 0
        if my_high != r_high:
            we_low = my_high == 0
        else:
            we_low = min(self._group.ranks) < r_min
        merged = (self._group.ranks + self._remote_group.ranks
                  if we_low else
                  self._remote_group.ranks + self._group.ranks)
        cid = _bridge_cid_agree_leader(
            self.state, lc, self if lc.rank == 0 else None, 0)
        out = Communicator(self.state, cid, Group(merged),
                           name=f"{self.name}-merged")
        out.errhandler = self.errhandler  # MPI: children inherit
        return out


_I64 = None


def _init_dt():
    global _I64
    if _I64 is None:
        from ompi_tpu.datatype import engine as dtmod
        _I64 = dtmod.INT64_T
    return _I64


class _SplitBridge:
    """Adapter bridging a color-group leader to its remote color
    leader over the OLD intercomm during intercomm split."""

    def __init__(self, inter: "Intercommunicator",
                 remote_leader: int) -> None:
        self.inter = inter
        self.remote_leader = remote_leader

    def _bridge_peer(self) -> int:
        return self.remote_leader

    def __getattr__(self, name):
        return getattr(self.inter, name)


class _PeerBridge:
    """Adapter giving _bridge_cid_agree a rank-0-to-remote-leader
    path over the peer communicator during intercomm creation."""

    def __init__(self, peer_comm: Communicator, remote_leader: int) -> None:
        self.peer_comm = peer_comm
        self.remote_leader = remote_leader
        self.cid = peer_comm.cid
        self.state = peer_comm.state

    def _bridge_peer(self) -> int:
        return self.remote_leader

    # quacks like a communicator for the pml (cid + group translation)
    @property
    def group(self):
        return self.peer_comm.group

    def __getattr__(self, name):
        return getattr(self.peer_comm, name)


def intercomm_create(local_comm: Communicator, local_leader: int,
                     peer_comm: Optional[Communicator],
                     remote_leader: int, tag: int = 0
                     ) -> Intercommunicator:
    """MPI_Intercomm_create (ref: comm.c:1100+): collective over both
    local comms; the two leaders must share ``peer_comm``."""
    _init_dt()
    state = local_comm.state
    am_leader = local_comm.rank == local_leader
    wire_tag = _icreate_wire_tag(tag)
    if am_leader and peer_comm is None:
        raise ValueError("leader needs a peer communicator")
    pml = state.pml

    # 1. leaders exchange local group rank lists over the peer comm
    if am_leader:
        mine = np.asarray(local_comm.group_obj().ranks, dtype=np.int64)
        szs = np.array([mine.size], dtype=np.int64)
        s1 = pml.isend(szs, 1, _I64, remote_leader, wire_tag,
                       peer_comm)
        their_n = np.empty(1, dtype=np.int64)
        pml.recv(their_n, 1, _I64, remote_leader, wire_tag,
                 peer_comm)
        s1.wait()
        s2 = pml.isend(mine, mine.size, _I64, remote_leader,
                       wire_tag, peer_comm)
        theirs = np.empty(int(their_n[0]), dtype=np.int64)
        pml.recv(theirs, theirs.size, _I64, remote_leader,
                 wire_tag, peer_comm)
        s2.wait()
        meta = np.array([theirs.size], dtype=np.int64)
    else:
        meta = np.empty(1, dtype=np.int64)
        theirs = None

    # 2. broadcast the remote group within the local comm
    # (root must be the local leader, who owns the data)
    local_comm.Bcast(meta, root=local_leader)
    if theirs is None:
        theirs = np.empty(int(meta[0]), dtype=np.int64)
    local_comm.Bcast(theirs, root=local_leader)
    remote_group = Group([int(x) for x in theirs])

    if set(remote_group.ranks) & set(local_comm.group_obj().ranks):
        raise ValueError("intercomm groups must be disjoint")

    # 3. cid agreement over the union, bridged leader-to-leader.
    # The bridge rides the peer comm, so run it through an adapter;
    # non-leaders only see the local phases.
    lc = local_comm.dup(name="intercomm-local")
    if am_leader:
        bridge = _PeerBridge(peer_comm, remote_leader)
        cid = _bridge_cid_agree_leader(state, local_comm, bridge,
                                       local_leader)
    else:
        cid = _bridge_cid_agree_leader(state, local_comm, None,
                                       local_leader)
    inter = Intercommunicator(state, cid, local_comm.group_obj(),
                              remote_group, lc)
    inter.errhandler = local_comm.errhandler  # MPI: children inherit
    return inter


def _bridge_cid_agree_leader(state, local_comm: Communicator,
                             bridge: Optional[_PeerBridge],
                             local_leader: int) -> int:
    """CID agreement where only ``local_leader`` talks across the
    bridge (creation-time variant of _bridge_cid_agree, which assumes
    leader == local rank 0)."""
    _init_dt()
    pml = state.pml
    while True:
        proposal = state.next_cid_local()
        agreed = local_comm._allreduce_max_int(proposal, TAG_CID)
        buf = np.array([agreed], dtype=np.int64)
        if bridge is not None:
            sreq = pml.isend(buf, 1, _I64, bridge._bridge_peer(),
                             TAG_IBRIDGE, bridge)
            theirs = np.empty(1, dtype=np.int64)
            pml.recv(theirs, 1, _I64, bridge._bridge_peer(),
                     TAG_IBRIDGE, bridge)
            sreq.wait()
            buf[0] = max(agreed, int(theirs[0]))
        local_comm.Bcast(buf, root=local_leader)
        agreed = int(buf[0])
        ok = 1 if agreed not in state.comms else 0
        all_ok = -local_comm._allreduce_max_int(-ok, TAG_CID)
        buf[0] = all_ok
        if bridge is not None:
            sreq = pml.isend(buf, 1, _I64, bridge._bridge_peer(),
                             TAG_IBRIDGE, bridge)
            theirs = np.empty(1, dtype=np.int64)
            pml.recv(theirs, 1, _I64, bridge._bridge_peer(),
                     TAG_IBRIDGE, bridge)
            sreq.wait()
            buf[0] = min(all_ok, int(theirs[0]))
        local_comm.Bcast(buf, root=local_leader)
        if int(buf[0]) == 1:
            return agreed
        state.comms.setdefault(agreed, None)


# give Intercommunicator._bridge_peer for the merge-time bridge (the
# intercomm itself: remote leader is remote rank 0)
def _intercomm_bridge_peer(self) -> int:
    return 0


Intercommunicator._bridge_peer = _intercomm_bridge_peer
