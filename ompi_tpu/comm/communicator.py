"""Groups and communicators: the substrate of every parallelism axis.

Re-design of ompi/communicator (ref: comm.c:406 ompi_comm_split,
split_type :650-749; comm_cid.c:47-86 — CID allocation as an
agreement over the parent communicator; ompi/group dense groups).

A communicator is (cid, ordered list of global ranks, my position).
CID agreement runs as a max-allreduce of each member's smallest free
cid over the *parent* communicator using reserved internal tags,
repeated until the agreed cid is free everywhere — the same
multi-round idea as the reference, built on p2p so it works before
any collective module exists.

TPU mapping: a communicator whose member ranks own devices caches a
1-D jax Mesh over those devices (comm ↔ sub-mesh), which coll/tpu
uses to lower collectives onto the ICI axis (SURVEY.md §2.8).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ompi_tpu.datatype import engine as dtmod
from ompi_tpu.pml.request import ANY_TAG, PROC_NULL, Status

# internal tags (user tags must be >= 0)
TAG_CID = -17
TAG_SPLIT = -18
TAG_BCAST = -19
TAG_GATHER = -20

UNDEFINED = -32766

COMM_TYPE_SHARED = 1

# respawn recovery epochs partition the cid space into disjoint bands
# (epoch E allocates from [E*STRIDE, (E+1)*STRIDE)): a fragment or
# cached plan addressed to a pre-failure cid can never alias a
# communicator built after an in-job rank replacement.  Far above both
# next_cid_local's dense counting and the ULFM store's 4096+ range.
EPOCH_CID_STRIDE = 65536

# DVM-resident sessions band the same space along a DISJOINT outer
# dimension: session b owns [b*SESSION_CID_STRIDE,
# (b+1)*SESSION_CID_STRIDE), subdivided into its own respawn-epoch
# bands.  The dimensions must not be additive — (band+epoch)*STRIDE
# would alias session k at epoch e with session k+e at epoch 0, so a
# ULFM respawn recovery inside one session could collide with a peer
# session's cids (trace spans, pvar labels, rendezvous keys).  A
# session that survives MAX_RESPAWN_EPOCHS in-job replacements would
# spill into the next band; respawn.rejoin guards against that.
MAX_RESPAWN_EPOCHS = 1024
SESSION_CID_STRIDE = MAX_RESPAWN_EPOCHS * EPOCH_CID_STRIDE


class Group:
    """Dense ordered set of global ranks (ref: ompi/group)."""

    def __init__(self, ranks: Sequence[int]) -> None:
        self.ranks = list(ranks)

    @property
    def size(self) -> int:
        return len(self.ranks)

    def rank_of(self, global_rank: int) -> int:
        try:
            return self.ranks.index(global_rank)
        except ValueError:
            return UNDEFINED

    def translate(self, other: "Group", rank: int) -> int:
        return other.rank_of(self.ranks[rank])

    def incl(self, ranks: Sequence[int]) -> "Group":
        return Group([self.ranks[r] for r in ranks])

    def excl(self, ranks: Sequence[int]) -> "Group":
        drop = set(ranks)
        return Group([g for i, g in enumerate(self.ranks) if i not in drop])

    def union(self, other: "Group") -> "Group":
        out = list(self.ranks)
        out += [r for r in other.ranks if r not in set(self.ranks)]
        return Group(out)

    def intersection(self, other: "Group") -> "Group":
        oset = set(other.ranks)
        return Group([r for r in self.ranks if r in oset])

    def difference(self, other: "Group") -> "Group":
        oset = set(other.ranks)
        return Group([r for r in self.ranks if r not in oset])


class Communicator:
    # per-comm monotone span-correlation counters (ompi_tpu/trace).
    # Class-level defaults so the hot paths read/write them as plain
    # attributes — no dict.get() call — while the ULFM epoch purge can
    # still pop the instance entries and fall back to zero.
    _coll_seq = 0
    _dev_seq = 0

    def __init__(self, state, cid: int, group: Group, name: str = "") -> None:
        self.state = state
        self.cid = cid
        self._group = group
        self.name = name or f"comm-{cid}"
        self.rank = group.rank_of(state.rank)
        self.size = group.size
        self.coll: Any = None       # collective module stack (coll framework)
        # Python surface default is ERRORS_RETURN (raising IS the
        # error return; install ERRORS_ARE_FATAL for C semantics —
        # see ompi_tpu/errhandler.py, ref: ompi/errhandler)
        from ompi_tpu import errhandler as _eh
        self.errhandler = _eh.ERRORS_RETURN
        self.attrs: Dict[int, Any] = {}
        self.info = None  # MPI_Info hints (Set_info/Get_info)
        self.topo = None
        self._mesh = None
        state.comms[cid] = self
        # stack collective modules (coll_base_comm_select analog);
        # local-only, so safe even mid-split on a subset of ranks
        from ompi_tpu.coll import framework as _coll_fw
        _coll_fw.comm_select(self)

    # group is exposed as the raw rank list for hot-path translation
    @property
    def group(self) -> List[int]:
        return self._group.ranks

    def group_obj(self) -> Group:
        return Group(self._group.ranks)

    # -- p2p shorthands used by comm management + coll/base --------------
    def _pml(self):
        return self.state.pml

    def psend(self, obj: Any, dst: int, tag: int) -> None:
        """Internal typed-object send (numpy int64 vectors)."""
        arr = np.atleast_1d(np.asarray(obj, dtype=np.int64))
        self._pml().send(arr, arr.size, dtmod.INT64_T, dst, tag, self)

    def precv(self, n: int, src: int, tag: int) -> np.ndarray:
        arr = np.empty(n, dtype=np.int64)
        self._pml().recv(arr, n, dtmod.INT64_T, src, tag, self)
        return arr

    # -- cid agreement ---------------------------------------------------
    def _allreduce_max_int(self, value: int, tag: int) -> int:
        """Recursive-doubling-free simple max: gather to comm rank 0,
        bcast back (used only for management traffic)."""
        if self.size == 1:
            return value
        if self.rank == 0:
            best = value
            for r in range(1, self.size):
                best = max(best, int(self.precv(1, r, tag)[0]))
            for r in range(1, self.size):
                self.psend(best, r, tag)
            return best
        self.psend(value, 0, tag)
        return int(self.precv(1, 0, tag)[0])

    def next_cid(self) -> int:
        """Agree on a cid free on every member of *this* comm
        (ref: ompi_comm_nextcid multi-round agreement).  After a
        respawn recovery the proposal is floored into the current
        epoch's cid band — see EPOCH_CID_STRIDE.  A DVM-resident
        session owns a disjoint OUTER band (state.cid_band *
        SESSION_CID_STRIDE) subdivided into epoch bands, so derived
        comms of concurrent sessions can never alias — even after a
        respawn recovery bumps one session's epoch."""
        floor = (self.state.cid_band * SESSION_CID_STRIDE
                 + self.state.respawn_epoch * EPOCH_CID_STRIDE)
        while True:
            proposal = self.state.next_cid_local()
            if proposal < floor:
                proposal = floor
                while proposal in self.state.comms:
                    proposal += 1
            agreed = self._allreduce_max_int(proposal, TAG_CID)
            ok = 1 if agreed not in self.state.comms else 0
            all_ok = self._allreduce_max_int(-ok, TAG_CID)  # max(-ok)=0 iff any not ok
            if all_ok == -1:
                return agreed
            # else: someone had it taken; reserve and retry
            self.state.comms.setdefault(agreed, None)

    # -- management operations ------------------------------------------
    def dup(self, name: str = "") -> "Communicator":
        from ompi_tpu import attrs as _attrs
        cid = self.next_cid()
        new = Communicator(self.state, cid, Group(self.group),
                           name or f"{self.name}-dup")
        new.topo = self.topo  # MPI_Comm_dup carries the topology over
        new.errhandler = self.errhandler
        if self.info is not None:
            new.info = self.info.dup()
        _attrs.copy_all(self, new)  # attribute copy callbacks
        return new

    def idup(self, name: str = ""):
        """MPI_Comm_idup (ref: ompi/mpi/c/comm_idup.c): returns
        (newcomm, request).  The CID agreement runs eagerly — every
        member is inside idup anyway (it is collective), so the
        request is born complete; the value of the nonblocking form
        is API fidelity, not overlap, at this altitude."""
        from ompi_tpu.pml.request import CompletedRequest
        new = self.dup(name)
        return new, CompletedRequest(self.state.progress)

    def create_group(self, group: Group, tag: int = 0
                     ) -> Optional["Communicator"]:
        """MPI_Comm_create_group (ref: ompi/mpi/c/comm_create_group.c):
        collective only over `group`'s members — the agreement rides a
        shim translating group ranks over the parent's cid with a
        dedicated tag, so non-members never participate."""
        my_pos = group.rank_of(self.state.rank)
        if my_pos == UNDEFINED:
            return None

        parent = self
        grp_ranks = list(group.ranks)

        class _GroupShim:
            """Comm-shaped view of `group` over the parent's cid."""
            cid = parent.cid
            state = parent.state
            size = len(grp_ranks)
            rank = my_pos
            group = grp_ranks  # the p2p translation table

            psend = Communicator.psend
            precv = Communicator.precv
            _pml = Communicator._pml
            _allreduce_max_int = Communicator._allreduce_max_int

        shim = _GroupShim()
        # multi-round agreement among group members only; the wire tag
        # lives in a dedicated [-400, -1399] block so no user tag can
        # land it on another internal protocol's tag (concurrent
        # create_group calls with tags 1000 apart would collide — the
        # comm/tag pair disambiguates real uses)
        wire_tag = -400 - (tag % 1000)
        while True:
            proposal = self.state.next_cid_local()
            agreed = shim._allreduce_max_int(proposal, wire_tag)
            ok = 1 if agreed not in self.state.comms else 0
            all_ok = shim._allreduce_max_int(-ok, wire_tag)
            if all_ok == -1:
                new = Communicator(self.state, agreed, group)
                new.errhandler = self.errhandler  # MPI: children inherit
                return new
            self.state.comms.setdefault(agreed, None)

    def create(self, group: Group) -> Optional["Communicator"]:
        """MPI_Comm_create: collective over the parent; ranks outside
        `group` get None (MPI_COMM_NULL)."""
        cid = self.next_cid()
        if group.rank_of(self.state.rank) == UNDEFINED:
            self.state.comms.setdefault(cid, None)  # keep cid reserved
            return None
        new = Communicator(self.state, cid, group)
        new.errhandler = self.errhandler  # MPI: children inherit
        return new

    def split(self, color: int, key: int = 0) -> Optional["Communicator"]:
        """MPI_Comm_split (ref: comm.c:406): gather (color,key) on
        rank 0, compute ordered subgroups, scatter memberships, then a
        single parent-wide cid round per resulting group."""
        me = [color, key, self.state.rank]
        if self.rank == 0:
            table = [me] + [list(self.precv(3, r, TAG_SPLIT))
                            for r in range(1, self.size)]
            groups: Dict[int, List] = {}
            for i, (c, k, g) in enumerate(table):
                if c == UNDEFINED:
                    continue
                groups.setdefault(c, []).append((k, i, g))
            for c in groups:
                groups[c].sort()
            # send each rank its group's global-rank list (or empty);
            # fixed-size messages: [n, pad...] then payload
            mine: List[int] = []
            for r in range(self.size):
                c = table[r][0]
                payload = [] if c == UNDEFINED else \
                    [g for (_, _, g) in groups[c]]
                if r == 0:
                    mine = payload
                else:
                    self.psend([len(payload)], r, TAG_SPLIT)
                    if payload:
                        self.psend(payload, r, TAG_SPLIT)
        else:
            self.psend(me, 0, TAG_SPLIT)
            n = int(self.precv(1, 0, TAG_SPLIT)[0])
            mine = [int(x) for x in self.precv(n, 0, TAG_SPLIT)] if n else []
        # every parent rank participates in ONE cid agreement so the
        # cid is globally fresh even across disjoint split groups
        cid = self.next_cid()
        if not mine:
            self.state.comms.setdefault(cid, None)
            return None
        new = Communicator(self.state, cid, Group(mine))
        new.errhandler = self.errhandler  # MPI: children inherit
        return new

    def split_type(self, split_type: int, key: int = 0
                   ) -> Optional["Communicator"]:
        """MPI_Comm_split_type (ref: comm.c:650-749).  On the TPU-host
        model every thread-rank shares the node, so SHARED groups all
        co-located ranks (locality via the rte)."""
        node = getattr(self.state.rte, "node_id", 0)
        if split_type == COMM_TYPE_SHARED:
            return self.split(node, key)
        return self.split(UNDEFINED, key)

    def free(self) -> None:
        from ompi_tpu import attrs as _attrs
        _attrs.delete_all(self)  # attribute delete callbacks
        self.state.comms.pop(self.cid, None)
        # keep the cid burned so in-flight traffic can't alias it
        self.state.comms.setdefault(self.cid, None)
        # drop this comm's device-collective rendezvous (one entry per
        # (cid, group) in the world's shared dict)
        world = getattr(self.state.rte, "world", None)
        if world is not None:
            with world.shared_lock:
                world.shared.pop(("coll_rv", self.cid, tuple(self.group)),
                                 None)

    # -- TPU mesh mapping (SURVEY.md §2.8) -------------------------------
    def mesh(self):
        """1-D jax Mesh over member devices, or None when members
        don't own distinct devices (then coll/tpu is not eligible).
        Both verdicts are cached: device ownership is fixed for a
        comm's members, and the walk over peer states costs more than
        a small collective at the 4-byte floor."""
        if self._mesh is not None:
            return self._mesh
        if self.__dict__.get("_mesh_none"):
            return None
        devs = []
        for g in self.group:
            st = self._peer_state(g)
            if st is None or st.device is None:
                self.__dict__["_mesh_none"] = True
                return None
            devs.append(st.device)
        if len({d.id for d in devs}) != len(devs):
            self.__dict__["_mesh_none"] = True
            return None
        import numpy as _np
        from jax.sharding import Mesh
        self._mesh = Mesh(_np.array(devs), ("r",))
        return self._mesh

    def _peer_state(self, global_rank: int):
        world = getattr(self.state.rte, "world", None)
        if world is None:
            return self.state if global_rank == self.state.rank else None
        return world.states[global_rank]

    def abort(self, errorcode: int = 1) -> None:
        self.state.rte.abort(errorcode, f"abort on {self.name}")

    # -- ULFM fault tolerance (ompi_tpu/ft/ulfm; the MPIX_Comm_*
    # surface of the MPI-4 FT proposal) ---------------------------------
    def revoke(self) -> None:
        """MPIX_Comm_revoke: poison this communicator on every member
        (NOT collective — any member may call it; typically the first
        rank that catches ERR_PROC_FAILED mid-algorithm).  In-flight
        and future operations drain with ERR_REVOKED; agree/shrink
        keep working — they are the escape hatch."""
        from ompi_tpu.ft import ulfm as _ulfm
        _ulfm.publish_revoke(self)

    def is_revoked(self) -> bool:
        u = self.state.ulfm
        if u is None:
            return False
        u.poll()
        return (self.cid, tuple(self.group)) in u.revoked

    def get_failed(self) -> List[int]:
        """MPIX_Comm_get_failed analog: comm ranks known failed."""
        u = self.state.ulfm
        if u is None:
            return []
        u.poll()
        return [r for r, g in enumerate(self.group) if g in u.failed]

    def ack_failed(self) -> int:
        """MPIX_Comm_ack_failed: acknowledge the current failure set
        (re-arms ANY_SOURCE receives); returns how many are acked."""
        u = self.state.ulfm
        if u is None:
            return 0
        u.poll()
        u.acked |= u.failed.intersection(self.group)
        return sum(1 for g in self.group if g in u.acked)

    def agree(self, flag=True) -> bool:
        """MPIX_Comm_agree: fault-tolerant agreement — every survivor
        returns the same AND of the contributed flags, no matter when
        members die (see ompi_tpu/ft/ulfm.agree)."""
        from ompi_tpu.ft import ulfm as _ulfm
        return _ulfm.agree(self, flag)

    def shrink(self, name: str = "") -> "Communicator":
        """MPIX_Comm_shrink: a new communicator of the survivors, with
        the device mesh rebuilt and stale compiled collectives
        dropped.  Collective over the survivors."""
        from ompi_tpu.ft import ulfm as _ulfm
        return _ulfm.shrink(self, name)

    # -- error handlers (ref: ompi/errhandler) --------------------------
    def Set_errhandler(self, handler) -> None:
        self.errhandler = handler

    def Get_errhandler(self):
        return self.errhandler

    def Call_errhandler(self, errorcode: int) -> None:
        from ompi_tpu import errhandler as _eh
        _eh.dispatch(self, _eh.MPIException(errorcode))

    # -- attributes (ref: ompi/attribute/attribute.c) -------------------
    def Set_attr(self, keyval: int, value: Any) -> None:
        from ompi_tpu import attrs as _attrs
        _attrs.set_attr(self, keyval, value)

    def Get_attr(self, keyval: int):
        from ompi_tpu import attrs as _attrs
        return _attrs.get_attr(self, keyval)

    def Delete_attr(self, keyval: int) -> None:
        from ompi_tpu import attrs as _attrs
        _attrs.delete_attr(self, keyval)

    # -- info hints (ref: ompi/info/info.c) -----------------------------
    def Set_info(self, info) -> None:
        self.info = info

    def Get_info(self):
        from ompi_tpu.info import Info
        return self.info.dup() if self.info is not None else Info()

    # -- intercommunicators + dynamic process management ----------------
    @property
    def is_inter(self) -> bool:
        return False

    def create_intercomm(self, local_leader: int, peer_comm,
                         remote_leader: int, tag: int = 0):
        """MPI_Intercomm_create (ref: ompi/mpi/c/intercomm_create.c)."""
        from .intercomm import intercomm_create
        return intercomm_create(self, local_leader, peer_comm,
                                remote_leader, tag)

    def spawn(self, cmd: str, args=(), maxprocs: int = 1,
              root: int = 0):
        """MPI_Comm_spawn (ref: ompi/dpm/dpm.c)."""
        from .dpm import comm_spawn
        return comm_spawn(self, cmd, list(args), maxprocs, root)

    def spawn_multiple(self, specs, root: int = 0):
        """MPI_Comm_spawn_multiple: specs = [(cmd, args, n), ...]."""
        from .dpm import comm_spawn_multiple
        return comm_spawn_multiple(self, specs, root)

    def disconnect(self) -> None:
        """MPI_Comm_disconnect (ref: ompi/mpi/c/comm_disconnect.c):
        barrier (pending communication must drain) then free."""
        self.Barrier()
        self.free()

    def accept(self, port: str, root: int = 0):
        from .dpm import comm_accept
        return comm_accept(self, port, root)

    def connect(self, port: str, root: int = 0):
        from .dpm import comm_connect
        return comm_connect(self, port, root)

    # ------------------------------------------------------------------
    # Public MPI API (mpi4py-flavored buffer methods).  Buffer specs:
    # a numpy array (count/datatype inferred), or (buf, datatype), or
    # (buf, count, datatype).  Mirrors the 385-binding C surface
    # (ref: ompi/mpi/c/*.c) at Python altitude; the flat MPI_* names
    # live in ompi_tpu.mpi.
    # ------------------------------------------------------------------

    @staticmethod
    def _spec(spec):
        from ompi_tpu.coll.buffers import IN_PLACE
        if spec is IN_PLACE:
            return IN_PLACE, 0, None
        if isinstance(spec, tuple):
            if len(spec) == 3:
                return spec
            if len(spec) == 2:
                buf, dt = spec
                n = np.asarray(buf).nbytes // dt.size if dt.size else 0
                return buf, n, dt
        arr = spec
        dt = dtmod.from_numpy_dtype(arr.dtype)
        return arr, arr.size, dt

    @staticmethod
    def _check_tag(tag: int, recv: bool = False) -> None:
        """User tags must be >= 0 (negative space is reserved for comm
        management/collective traffic); ANY_TAG legal on receives."""
        if tag < 0 and not (recv and tag == -1):
            raise ValueError(
                f"invalid tag {tag}: user tags must be >= 0 (MPI_ERR_TAG)")

    # -- p2p ------------------------------------------------------------
    def Send(self, spec, dest: int, tag: int = 0) -> None:
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        self.state.pml.send(buf, count, dt, dest, tag, self)

    def Ssend(self, spec, dest: int, tag: int = 0) -> None:
        from ompi_tpu.pml.ob1 import MODE_SYNC
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        self.state.pml.send(buf, count, dt, dest, tag, self, MODE_SYNC)

    def Recv(self, spec, source: int = -1, tag: int = -1) -> Status:
        self._check_tag(tag, recv=True)
        buf, count, dt = self._spec(spec)
        return self.state.pml.recv(buf, count, dt, source, tag, self)

    def Isend(self, spec, dest: int, tag: int = 0):
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        return self.state.pml.isend(buf, count, dt, dest, tag, self)

    def Issend(self, spec, dest: int, tag: int = 0):
        from ompi_tpu.pml.ob1 import MODE_SYNC
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        return self.state.pml.isend(buf, count, dt, dest, tag, self,
                                    MODE_SYNC)

    def Irecv(self, spec, source: int = -1, tag: int = -1):
        self._check_tag(tag, recv=True)
        buf, count, dt = self._spec(spec)
        return self.state.pml.irecv(buf, count, dt, source, tag, self)

    # -- buffered / ready sends (ref: ompi/mpi/c/bsend.c, rsend.c) ------
    def Bsend(self, spec, dest: int, tag: int = 0) -> None:
        from ompi_tpu.pml import persistent as pers
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        pers.bsend(self, buf, count, dt, dest, tag)

    def Ibsend(self, spec, dest: int, tag: int = 0):
        from ompi_tpu.pml import persistent as pers
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        return pers.ibsend(self, buf, count, dt, dest, tag)

    # a ready send is correct whenever a standard send is; the
    # reference's rsend is likewise standard-send under ob1.  This
    # silently legalizes erroneous programs (no matching-recv check),
    # so the behavior is declared in the registry
    # (pml_ob1_rsend_is_standard) for ompi_info discoverability.
    def Rsend(self, spec, dest: int, tag: int = 0) -> None:
        self.Send(spec, dest, tag)

    def Irsend(self, spec, dest: int, tag: int = 0):
        return self.Isend(spec, dest, tag)

    # -- persistent requests (ref: ompi/mpi/c/send_init.c et al.) -------
    def Send_init(self, spec, dest: int, tag: int = 0):
        from ompi_tpu.pml.persistent import PersistentRequest
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        return PersistentRequest(self, PersistentRequest.KIND_SEND,
                                 buf, count, dt, dest, tag)

    def Ssend_init(self, spec, dest: int, tag: int = 0):
        from ompi_tpu.pml.ob1 import MODE_SYNC
        from ompi_tpu.pml.persistent import PersistentRequest
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        return PersistentRequest(self, PersistentRequest.KIND_SEND,
                                 buf, count, dt, dest, tag, MODE_SYNC)

    def Bsend_init(self, spec, dest: int, tag: int = 0):
        from ompi_tpu.pml.persistent import PersistentRequest
        self._check_tag(tag)
        buf, count, dt = self._spec(spec)
        return PersistentRequest(self, PersistentRequest.KIND_SEND,
                                 buf, count, dt, dest, tag, "buffered")

    def Recv_init(self, spec, source: int = -1, tag: int = -1):
        from ompi_tpu.pml.persistent import PersistentRequest
        self._check_tag(tag, recv=True)
        buf, count, dt = self._spec(spec)
        return PersistentRequest(self, PersistentRequest.KIND_RECV,
                                 buf, count, dt, source, tag)

    def Sendrecv(self, sspec, dest: int, stag: int, rspec, source: int,
                 rtag: int = -1) -> Status:
        rreq = self.Irecv(rspec, source, rtag)
        self.Send(sspec, dest, stag)
        return rreq.wait()

    def Sendrecv_replace(self, spec, dest: int, stag: int, source: int,
                         rtag: int = -1) -> Status:
        """MPI_Sendrecv_replace (ref: ompi/mpi/c/sendrecv_replace.c —
        the send side snapshots the buffer through the convertor
        before the receive overwrites it)."""
        buf, count, dt = self._spec(spec)
        from ompi_tpu.datatype.convertor import Convertor
        snapshot = bytearray(Convertor(dt, count, buf).pack())
        rreq = self.Irecv(spec, source, rtag)
        self.Send((np.frombuffer(snapshot, dtype=np.uint8),
                   count * dt.size if dt.size else 0,
                   dtmod.BYTE), dest, stag)
        return rreq.wait()

    # -- names ----------------------------------------------------------
    def Set_name(self, name: str) -> None:
        self.name = name

    def Get_name(self) -> str:
        return self.name

    def Probe(self, source: int = -1, tag: int = -1) -> Status:
        return self.state.pml.probe(source, tag, self)

    def Iprobe(self, source: int = -1, tag: int = -1) -> Optional[Status]:
        return self.state.pml.iprobe(source, tag, self)

    def Mprobe(self, source: int = -1, tag: int = -1):
        while True:
            m = self.state.pml.improbe(source, tag, self)
            if m is not None:
                return m

    def Mrecv(self, spec, message) -> Status:
        buf, count, dt = self._spec(spec)
        return self.state.pml.mrecv(buf, count, dt, message, self)

    # -- collectives ----------------------------------------------------
    def Barrier(self) -> None:
        self.coll.barrier(self)

    barrier = Barrier

    def Bcast(self, spec, root: int = 0) -> None:
        buf, count, dt = self._spec(spec)
        self.coll.bcast(self, buf, count, dt, root)

    def Reduce(self, sspec, rspec, op, root: int = 0) -> None:
        from ompi_tpu.coll.buffers import IN_PLACE
        sbuf, scount, sdt = self._spec(sspec)
        if rspec is None:
            self.coll.reduce(self, sbuf, None, scount, sdt, op, root)
            return
        rbuf, rcount, rdt = self._spec(rspec)
        if sbuf is IN_PLACE:
            scount, sdt = rcount, rdt
        self.coll.reduce(self, sbuf, rbuf, rcount if rcount else scount,
                         rdt or sdt, op, root)

    def Allreduce(self, sspec, rspec, op) -> None:
        from ompi_tpu.coll.buffers import IN_PLACE
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        self.coll.allreduce(self, sbuf, rbuf, rcount, rdt, op)

    def Allgather(self, sspec, rspec) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        self.coll.allgather(self, sbuf, scount, sdt, rbuf,
                            rcount // self.size, rdt)

    def Allgatherv(self, sspec, rspec, rcounts, displs) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        self.coll.allgatherv(self, sbuf, scount, sdt, rbuf, rcounts,
                             displs, rdt)

    def Gather(self, sspec, rspec, root: int = 0) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        if self.rank == root:
            rbuf, rcount, rdt = self._spec(rspec)
            self.coll.gather(self, sbuf, scount, sdt, rbuf,
                             rcount // self.size, rdt, root)
        else:
            self.coll.gather(self, sbuf, scount, sdt, None, 0, sdt, root)

    def Gatherv(self, sspec, rspec, rcounts, displs, root: int = 0) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        if self.rank == root:
            rbuf, _, rdt = self._spec(rspec)
        else:
            rbuf, rdt = None, sdt
        self.coll.gatherv(self, sbuf, scount, sdt, rbuf, rcounts, displs,
                          rdt, root)

    def Scatter(self, sspec, rspec, root: int = 0) -> None:
        rbuf, rcount, rdt = self._spec(rspec)
        if self.rank == root:
            sbuf, scount, sdt = self._spec(sspec)
            self.coll.scatter(self, sbuf, scount // self.size, sdt, rbuf,
                              rcount, rdt, root)
        else:
            self.coll.scatter(self, None, 0, rdt, rbuf, rcount, rdt, root)

    def Scatterv(self, sspec, scounts, displs, rspec, root: int = 0) -> None:
        rbuf, rcount, rdt = self._spec(rspec)
        if self.rank == root:
            sbuf, _, sdt = self._spec(sspec)
        else:
            sbuf, sdt = None, rdt
        self.coll.scatterv(self, sbuf, scounts, displs, sdt, rbuf, rcount,
                           rdt, root)

    def Alltoall(self, sspec, rspec) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        self.coll.alltoall(self, sbuf, scount // self.size, sdt, rbuf,
                           rcount // self.size, rdt)

    def Alltoallv(self, sspec, scounts, sdispls, rspec, rcounts,
                  rdispls) -> None:
        sbuf, _, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        self.coll.alltoallv(self, sbuf, scounts, sdispls, sdt, rbuf,
                            rcounts, rdispls, rdt)

    def Reduce_scatter(self, sspec, rspec, rcounts, op) -> None:
        sbuf, _, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        self.coll.reduce_scatter(self, sbuf, rbuf, rcounts, rdt, op,
                                 sdtype=sdt)

    def Reduce_scatter_block(self, sspec, rspec, op) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        self.coll.reduce_scatter_block(self, sbuf, rbuf, rcount, rdt, op)

    def Scan(self, sspec, rspec, op) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        self.coll.scan(self, sbuf, rbuf, rcount, rdt, op)

    def Exscan(self, sspec, rspec, op) -> None:
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        self.coll.exscan(self, sbuf, rbuf, rcount, rdt, op)

    # -- nonblocking collectives (coll/nbc schedules) -------------------
    def Ibarrier(self):
        return self.coll.ibarrier(self)

    def Ibcast(self, spec, root: int = 0):
        buf, count, dt = self._spec(spec)
        return self.coll.ibcast(self, buf, count, dt, root)

    def Ireduce(self, sspec, rspec, op, root: int = 0):
        sbuf, scount, sdt = self._spec(sspec)
        if rspec is None:
            return self.coll.ireduce(self, sbuf, None, scount, sdt, op, root)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.ireduce(self, sbuf, rbuf, rcount or scount,
                                 rdt or sdt, op, root)

    def Iallreduce(self, sspec, rspec, op):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.iallreduce(self, sbuf, rbuf, rcount, rdt, op)

    def Iallgather(self, sspec, rspec):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.iallgather(self, sbuf, scount, sdt, rbuf,
                                    rcount // self.size, rdt)

    def Iallgatherv(self, sspec, rspec, rcounts, displs):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        return self.coll.iallgatherv(self, sbuf, scount, sdt, rbuf,
                                     rcounts, displs, rdt)

    def Igather(self, sspec, rspec, root: int = 0):
        sbuf, scount, sdt = self._spec(sspec)
        if self.rank == root:
            rbuf, rcount, rdt = self._spec(rspec)
            return self.coll.igather(self, sbuf, scount, sdt, rbuf,
                                     rcount // self.size, rdt, root)
        return self.coll.igather(self, sbuf, scount, sdt, None, 0, sdt,
                                 root)

    def Iscatter(self, sspec, rspec, root: int = 0):
        rbuf, rcount, rdt = self._spec(rspec)
        if self.rank == root:
            sbuf, scount, sdt = self._spec(sspec)
            return self.coll.iscatter(self, sbuf, scount // self.size, sdt,
                                      rbuf, rcount, rdt, root)
        return self.coll.iscatter(self, None, 0, rdt, rbuf, rcount, rdt,
                                  root)

    def Igatherv(self, sspec, rspec, rcounts, displs, root: int = 0):
        sbuf, scount, sdt = self._spec(sspec)
        if self.rank == root:
            rbuf, _, rdt = self._spec(rspec)
        else:
            rbuf, rdt = None, sdt
        return self.coll.igatherv(self, sbuf, scount, sdt, rbuf,
                                  rcounts, displs, rdt, root)

    def Iscatterv(self, sspec, scounts, displs, rspec, root: int = 0):
        rbuf, rcount, rdt = self._spec(rspec)
        if self.rank == root:
            sbuf, _, sdt = self._spec(sspec)
        else:
            sbuf, sdt = None, rdt
        return self.coll.iscatterv(self, sbuf, scounts, displs, sdt,
                                   rbuf, rcount, rdt, root)

    def Ialltoall(self, sspec, rspec):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.ialltoall(self, sbuf, scount // self.size, sdt,
                                   rbuf, rcount // self.size, rdt)

    def Ialltoallv(self, sspec, scounts, sdispls, rspec, rcounts, rdispls):
        sbuf, _, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        return self.coll.ialltoallv(self, sbuf, scounts, sdispls, sdt,
                                    rbuf, rcounts, rdispls, rdt)

    def Ireduce_scatter(self, sspec, rspec, rcounts, op):
        sbuf, _, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        return self.coll.ireduce_scatter(self, sbuf, rbuf, rcounts, rdt,
                                         op, sdtype=sdt)

    def Ireduce_scatter_block(self, sspec, rspec, op):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.ireduce_scatter_block(self, sbuf, rbuf, rcount,
                                               rdt, op)

    def Iscan(self, sspec, rspec, op):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.iscan(self, sbuf, rbuf, rcount, rdt, op)

    def Iexscan(self, sspec, rspec, op):
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        return self.coll.iexscan(self, sbuf, rbuf, rcount, rdt, op)

    @property
    def device(self):
        """The jax device this rank owns (None in host-only worlds)."""
        return self.state.device

    # -- device-array collectives (jax in, jax out) ---------------------
    # The coll/tpu surface: collectives on TPU-resident buffers return
    # new arrays (jax arrays are immutable); lowered to XLA collectives
    # on the comm's mesh when eligible, host-staged otherwise.

    def allreduce_arr(self, x, op):
        return self.coll.allreduce_arr(self, x, op)

    def bcast_arr(self, x, root: int = 0):
        return self.coll.bcast_arr(self, x, root)

    def reduce_arr(self, x, op, root: int = 0):
        return self.coll.reduce_arr(self, x, op, root)

    def allgather_arr(self, x):
        return self.coll.allgather_arr(self, x)

    def alltoall_arr(self, x):
        return self.coll.alltoall_arr(self, x)

    def reduce_scatter_arr(self, x, op):
        return self.coll.reduce_scatter_block_arr(self, x, op)

    def ppermute_arr(self, x, perm):
        """perm: [(src_rank, dst_rank), ...] — mesh-neighbor shift."""
        return self.coll.ppermute_arr(self, x, perm)

    # -- nonblocking device-array collectives (the fusion surface) ------
    # Small payloads coalesce into one fused XLA dispatch (coll/fusion);
    # the returned request's .result holds the output after .wait().

    def iallreduce_arr(self, x, op):
        return self.coll.iallreduce_arr(self, x, op)

    def ibcast_arr(self, x, root: int = 0):
        return self.coll.ibcast_arr(self, x, root)

    def flush_arr(self) -> None:
        """Dispatch this comm's pending fused collectives now
        (collective: every member must flush — wait()/finalize also
        flush implicitly)."""
        from ompi_tpu.coll import fusion
        fusion.flush_comm(self)

    # -- device point-to-point (btl/tpu shim; see ompi_tpu/btl/tpu) ----
    def send_arr(self, x, dst, tag: int = 0) -> None:
        from ompi_tpu.btl import tpu as _tpu
        _tpu.send_arr(self, x, dst, tag)

    def recv_arr(self, src, tag: int = 0):
        from ompi_tpu.btl import tpu as _tpu
        return _tpu.recv_arr(self, src, tag)

    def sendrecv_arr(self, x, dst, src, tag: int = 0):
        from ompi_tpu.btl import tpu as _tpu
        return _tpu.sendrecv_arr(self, x, dst, src, tag)

    # -- topologies (ompi/mca/topo analog; ompi_tpu.topo) ---------------
    def Create_cart(self, dims, periods=None, reorder: bool = False):
        from ompi_tpu.topo import cart_create
        return cart_create(self, dims, periods, reorder)

    def Create_graph(self, index, edges, reorder: bool = False):
        from ompi_tpu.topo import graph_create
        return graph_create(self, index, edges, reorder)

    def Create_dist_graph_adjacent(self, sources, destinations,
                                   sourceweights=None, destweights=None,
                                   reorder: bool = False):
        from ompi_tpu.topo import dist_graph_create_adjacent
        return dist_graph_create_adjacent(self, sources, destinations,
                                          sourceweights, destweights,
                                          reorder)

    def Topo_test(self) -> int:
        from ompi_tpu.topo import UNDEFINED_TOPO
        return self.topo.kind if self.topo is not None else UNDEFINED_TOPO

    def _require_topo(self, kind: Optional[int] = None):
        """MPI_ERR_TOPOLOGY guard (cart-only accessors pass kind=CART)."""
        t = self.topo
        if t is None or (kind is not None and t.kind != kind):
            raise ValueError(
                f"{self.name} has no {'cartesian ' if kind == 1 else ''}"
                f"topology (MPI_ERR_TOPOLOGY)")
        return t

    def Get_coords(self, rank: Optional[int] = None):
        return self._require_topo(1).rank_to_coords(
            self.rank if rank is None else rank)

    def Get_cart_rank(self, coords) -> int:
        return self._require_topo(1).coords_to_rank(coords)

    def Shift(self, dim: int, disp: int = 1):
        """MPI_Cart_shift → (rank_source, rank_dest)."""
        return self._require_topo(1).shift(dim, disp, self.rank)

    def Sub(self, remain_dims):
        from ompi_tpu.topo import cart_sub
        return cart_sub(self, remain_dims)

    def Get_topo(self):
        t = self.topo
        if t is None:
            return None
        if t.kind == 1:   # CART
            return (t.dims, t.periods, t.coords)
        if t.kind == 2:   # GRAPH
            return (t.index, t.edges)
        return (t.sources, t.destinations)

    # -- neighbor collectives (MPI-3 §7.6) ------------------------------
    @staticmethod
    def _nbr_block(total: int, nbrs: int, what: str) -> int:
        """Per-neighbor block count; a buffer that doesn't divide
        evenly is a count mismatch, not a silent truncation."""
        if nbrs == 0:
            return 0
        if total % nbrs:
            raise ValueError(
                f"{what} buffer of {total} elements not divisible by "
                f"{nbrs} neighbors (MPI_ERR_COUNT)")
        return total // nbrs

    def Neighbor_allgather(self, sspec, rspec) -> None:
        from ompi_tpu.topo import neighbor as nb
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        topo = self._require_topo()
        nin = len(topo.in_neighbors(self.rank))
        nb.neighbor_allgather(self, sbuf, scount, sdt, rbuf,
                              self._nbr_block(rcount, nin, "recv"), rdt)

    def Neighbor_allgatherv(self, sspec, rspec, rcounts, displs) -> None:
        from ompi_tpu.topo import neighbor as nb
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        nb.neighbor_allgatherv(self, sbuf, scount, sdt, rbuf, rcounts,
                               displs, rdt)

    def Neighbor_alltoall(self, sspec, rspec) -> None:
        from ompi_tpu.topo import neighbor as nb
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        topo = self._require_topo()
        nout = len(topo.out_neighbors(self.rank))
        nin = len(topo.in_neighbors(self.rank))
        nb.neighbor_alltoall(self, sbuf,
                             self._nbr_block(scount, nout, "send"), sdt,
                             rbuf, self._nbr_block(rcount, nin, "recv"),
                             rdt)

    def Neighbor_alltoallv(self, sspec, scounts, sdispls, rspec, rcounts,
                           rdispls) -> None:
        from ompi_tpu.topo import neighbor as nb
        sbuf, _, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        nb.neighbor_alltoallv(self, sbuf, scounts, sdispls, sdt, rbuf,
                              rcounts, rdispls, rdt)

    def Ineighbor_allgather(self, sspec, rspec):
        from ompi_tpu.topo import neighbor as nb
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        nin = len(self._require_topo().in_neighbors(self.rank))
        return nb.ineighbor_allgather(
            self, sbuf, scount, sdt, rbuf,
            self._nbr_block(rcount, nin, "recv"), rdt)

    def Ineighbor_allgatherv(self, sspec, rspec, rcounts, displs):
        from ompi_tpu.topo import neighbor as nb
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        self._require_topo()
        return nb.ineighbor_allgatherv(self, sbuf, scount, sdt, rbuf,
                                       rcounts, displs, rdt)

    def Ineighbor_alltoall(self, sspec, rspec):
        from ompi_tpu.topo import neighbor as nb
        sbuf, scount, sdt = self._spec(sspec)
        rbuf, rcount, rdt = self._spec(rspec)
        topo = self._require_topo()
        nout = len(topo.out_neighbors(self.rank))
        nin = len(topo.in_neighbors(self.rank))
        return nb.ineighbor_alltoall(
            self, sbuf, self._nbr_block(scount, nout, "send"), sdt,
            rbuf, self._nbr_block(rcount, nin, "recv"), rdt)

    def Ineighbor_alltoallv(self, sspec, scounts, sdispls, rspec, rcounts,
                            rdispls):
        from ompi_tpu.topo import neighbor as nb
        sbuf, _, sdt = self._spec(sspec)
        rbuf, _, rdt = self._spec(rspec)
        return nb.ineighbor_alltoallv(self, sbuf, scounts, sdispls, sdt,
                                      rbuf, rcounts, rdispls, rdt)

    def shift_arr(self, x, dim: int, disp: int = 1):
        """Cartesian whole-grid shift of a device array along `dim` —
        lax.ppermute over the comm mesh (the TPU halo-exchange path).
        Ranks with no source neighbor (non-periodic edge) get zeros."""
        return self.coll.ppermute_arr(
            self, x, self._require_topo(1).shift_perm(dim, disp, self.size))

    def neighbor_allgather_arr(self, x):
        """Device-tier halo gather: per-dim ppermute shifts in MPI
        neighbor order (see topo.neighbor.neighbor_allgather_arr)."""
        from ompi_tpu.topo import neighbor as nb
        return nb.neighbor_allgather_arr(self, x)

    # -- management shorthands -----------------------------------------
    def Get_rank(self) -> int:
        return self.rank

    def Get_size(self) -> int:
        return self.size

    def Dup(self) -> "Communicator":
        return self.dup()

    def Split(self, color: int, key: int = 0):
        return self.split(color, key)

    def Free(self) -> None:
        self.free()

    def __repr__(self) -> str:
        return (f"Communicator({self.name}, cid={self.cid}, "
                f"rank={self.rank}/{self.size})")


# ---------------------------------------------------------------------------
# errhandler-guarded dispatch: every public operation routes raised
# errors through the communicator's installed handler
# (ref: OMPI_ERRHANDLER_INVOKE wrapping each ompi/mpi/c binding).
# With the default ERRORS_RETURN this re-raises unchanged; with
# ERRORS_ARE_FATAL the job aborts; user handlers run first.
# ---------------------------------------------------------------------------

def _guard(method):
    import functools

    @functools.wraps(method)
    def wrapped(self, *args, **kwargs):
        try:
            return method(self, *args, **kwargs)
        except (SystemExit, KeyboardInterrupt):
            raise
        except BaseException as exc:  # noqa: BLE001
            from ompi_tpu import errhandler as _eh
            _eh.dispatch(self, exc)

    return wrapped


_GUARDED = (
    "Send", "Recv", "Isend", "Irecv", "Ssend", "Rsend", "Bsend",
    "Sendrecv", "Probe", "Iprobe", "Mprobe", "Mrecv",
    "Barrier", "Bcast", "Reduce", "Allreduce", "Allgather",
    "Allgatherv", "Gather", "Gatherv", "Scatter", "Scatterv",
    "Alltoall", "Alltoallv", "Reduce_scatter", "Reduce_scatter_block",
    "Scan", "Exscan",
)
for _name in _GUARDED:
    _m = getattr(Communicator, _name, None)
    if _m is not None:
        setattr(Communicator, _name, _guard(_m))
del _name, _m
