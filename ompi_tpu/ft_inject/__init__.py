"""MCA-selectable fault-injection framework.

The robustness analog of the reference's fault tooling around
orte/mca/errmgr: a deterministic, seed-driven interposer that mangles
traffic at well-defined choke points so every recovery path in the
stack can be exercised on demand — never by hoping production
misbehaves first.  Everything is driven by MCA params, so a chaos run
is just ``mpirun --mca ft_inject_plan drop,sever --mca ft_inject_seed
7 ...`` with zero code changes.

Injection points (the framework stays passive unless a plan names it):

  * btl/tcp ``send()``   — frame-level faults: ``drop``, ``delay``,
    ``dup``, ``reorder``, ``corrupt`` (header CRC-detectable),
    ``sever`` (connection shutdown mid-stream).  All absorbed by the
    reliable sublayer (btl_tcp_reliable).
  * tools/tpud           — node-level scenarios on the victim node:
    ``daemon_kill`` (hard exit, exercising heartbeat/errmgr) and
    ``oob_sever`` (drop the daemon↔HNP channel, exercising OOB
    reconnect).
  * runtime/kvstore      — ``kv_partition``: force-close the client
    socket before ops, exercising the KV retry/backoff path.

Determinism: every injector owns a ``random.Random`` seeded from
``(ft_inject_seed, scope, rank)``, so a failing chaos run replays
bit-for-bit from its seed.  ``ft_inject_max`` bounds total injections
per scope so an injected job always converges to a clean stream.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional

from ompi_tpu.mca.params import registry

_seed_var = registry.register(
    "ft", "inject", "seed", 0, int,
    help="Deterministic seed for every injector's RNG (replay a "
         "failing chaos run bit-for-bit)")
_plan_var = registry.register(
    "ft", "inject", "plan", "", str,
    help="Comma list of fault classes to arm, each optionally "
         "class:rate — e.g. 'drop:0.05,sever:0.01'.  Classes: drop, "
         "delay, dup, reorder, corrupt, sever, daemon_kill, "
         "oob_sever, kv_partition, rank_kill, io_stall, io_partial, "
         "io_enospc, dvm_disconnect, rma_delay, kv_kill, dvm_kill, "
         "host_kill, rdv_sever, host_slow, net_jitter, device_sdc, "
         "corrupt_payload (for the kill "
         "classes the number is the armed OP COUNT the control-plane "
         "process dies at, not a rate; host_kill severs "
         "ft_inject_victim_host's whole failure domain — daemon plus "
         "every resident rank; rdv_sever wedges "
         "ft_inject_victim_rank at its Nth device-collective "
         "rendezvous — the hang-doctor test target; host_slow is the "
         "GRAY failure: ft_inject_victim_host stays alive but every "
         "resident rank and its heartbeat run "
         "ft_inject_host_slow_factor times slow; net_jitter shapes "
         "seeded latency/loss onto the tcp + KV client paths; "
         "device_sdc is the SILENT failure — ft_inject_victim_rank's "
         "chip bit-flips its collective operand at the armed op "
         "count, visible only to the integrity plane; "
         "corrupt_payload flips tcp frame bytes BEYOND the header-CRC "
         "span, exercising the payload digest above CRC).  "
         "Empty = framework disabled")
_rate_var = registry.register(
    "ft", "inject", "rate", 0.02, float,
    help="Default per-event injection probability for plan entries "
         "without an explicit rate")
_max_var = registry.register(
    "ft", "inject", "max", 64, int,
    help="Cap on injections per scope (0 = unlimited); a capped "
         "injected stream always converges to a clean one")
_skip_var = registry.register(
    "ft", "inject", "skip", 8, int,
    help="Skip the first N eligible events per scope so bring-up "
         "traffic (modex, fences) establishes the job before chaos")
_after_var = registry.register(
    "ft", "inject", "after", 1.0, float,
    help="Node-level scenarios (daemon_kill/oob_sever) fire this many "
         "seconds after daemon start")
_victim_var = registry.register(
    "ft", "inject", "victim_node", 1, int,
    help="Node id that hosts the daemon_kill/oob_sever scenarios")
_victim_host_var = registry.register(
    "ft", "inject", "victim_host", 1, int,
    help="Host (failure-domain) id severed by the host_kill scenario "
         "— the victim daemon dies and every rank resident on that "
         "host fails as ONE atomic domain record")
_victim_rank_var = registry.register(
    "ft", "inject", "victim_rank", "1", str,
    help="Global rank(s) killed by the rank_kill scenario (permanent "
         "death: the ULFM detect/revoke/shrink/agree test target).  A "
         "single rank, a comma list ('1,3'), or 'random' for a "
         "seed-deterministic pick — chaos runs sweep victims without "
         "editing the plan")
_delay_ms_var = registry.register(
    "ft", "inject", "delay_ms", 20, int,
    help="How long a 'delay'-class frame is held before hitting the "
         "wire")
_slow_factor_var = registry.register(
    "ft", "inject", "host_slow_factor", 10, int,
    help="Slowdown multiplier the host_slow gray-failure scenario "
         "applies to ft_inject_victim_host: resident ranks stall "
         "delay_ms*(factor-1) at every device-collective deposit and "
         "the host agent beats factor times slower — alive, never "
         "silent")
_jitter_ms_var = registry.register(
    "ft", "inject", "net_jitter_ms", 5, int,
    help="Mean added latency (milliseconds) of the net_jitter class; "
         "each hit sleeps a seeded uniform draw in [0, 2*mean]")
_jitter_loss_var = registry.register(
    "ft", "inject", "net_jitter_loss", 0.0, float,
    help="Per-event probability a net_jitter hit also DROPS the "
         "frame (tcp path only — the reliable sublayer retransmits; "
         "KV ops are never dropped, only delayed)")

_sdc_period_var = registry.register(
    "ft", "inject", "sdc_period", 0, int,
    help="device_sdc repeat period after the first armed flip (every "
         "Nth subsequent collective on the victim also flips); 0 = "
         "one-shot — probes measuring detection RATE arm a period so "
         "one run carries many independent flips")

# corrupt_payload flips frame bytes OUTSIDE the header-CRC span (the
# header CRC stays valid by construction — equivalent to recomputing
# it after the flip), so only the reliable layer's payload digest
# (btl_tcp_payload_digest) can catch it
BTL_CLASSES = ("drop", "delay", "dup", "reorder", "corrupt", "sever",
               "corrupt_payload")
NODE_CLASSES = ("daemon_kill", "oob_sever")
# checkpoint-I/O faults, consumed by the cr/ckpt shard-write path:
#   io_stall   — the write is held delay_ms before hitting the disk
#   io_partial — the shard is silently truncated (manifest CRC is
#                over the full shard, so restore detects the tear)
#   io_enospc  — the write raises ENOSPC; the epoch aborts on every
#                rank through the commit error agreement
IO_CLASSES = ("io_stall", "io_partial", "io_enospc")
# permanent per-RANK scenarios: unlike the transient classes these
# fire exactly once (there is no rate — death is not probabilistic)
RANK_CLASSES = ("rank_kill",)
# DVM service-plane client faults (tools/dvm): dvm_disconnect drops
# the client's pool connection right after a run request is sent —
# the session's program is already executing collectives inside the
# pool, so this exercises the client-death-mid-collective cleanup
# (the pool must finish or poison ONLY that session, never peers)
DVM_CLASSES = ("dvm_disconnect",)
# one-sided RMA faults (osc window AM handler): rma_delay holds the
# target's active-message apply — lock grants, unlock acks and pt2pt
# payload application all slow down, surfacing in osc_lock_wait_us
RMA_CLASSES = ("rma_delay",)
# control-plane process-death scenarios: like rank_kill these fire
# exactly once and deterministically — the plan number is the armed
# OP COUNT (the victim dies serving its Nth op), not a probability,
# so a chaos run kills the primary at a reproducible traffic point
# (e.g. mid-fence).  kv_kill crashes the KV primary (standby
# failover path); dvm_kill hard-exits the DVM server process
# (journal rehydration path, subprocess runs only).
KILL_CLASSES = ("kv_kill", "dvm_kill")
# whole-HOST death: at the armed op count the victim host's daemon
# (host agent) is severed and every rank resident on it fails as one
# atomic failure-domain record — the fleet-level analog of kv_kill/
# dvm_kill.  Consumed by tools/dvm (DVMServer.kill_host).
HOST_CLASSES = ("host_kill",)
# rendezvous sever: the victim rank silently stops arriving at its
# Nth device-collective rendezvous (the plan number is the armed meet
# count, deterministic like the kill classes) — every peer wedges in
# Rendezvous._wait_for, which is exactly the stall the hang doctor
# (DESIGN.md §23) must diagnose: "rank R absent from cid C gen G".
# The hold is abort-aware, so the doctor's poison unwinds it cleanly.
RDV_CLASSES = ("rdv_sever",)
# GRAY failure (DESIGN.md §24): the host stays alive — heartbeats
# keep flowing, just slow — while every resident rank crawls.  No
# liveness plane ever fires; only the health plane's scoring can see
# it.  Deterministic (a factor, not a rate): the victim is
# ft_inject_victim_host, reusing the host_kill victim knob.
SLOW_CLASSES = ("host_slow",)
# seeded latency/loss shaping on the tcp + KV client paths — the
# network-flakiness half of gray failure (jitter feeds the health
# plane's beat-jitter signal instead of tripping any death path)
NET_CLASSES = ("net_jitter",)


def plan() -> Dict[str, float]:
    """Parse ft_inject_plan into {class: rate}."""
    out: Dict[str, float] = {}
    s = _plan_var.value.strip()
    if not s:
        return out
    for item in s.split(","):
        item = item.strip()
        if not item:
            continue
        if ":" in item:
            cls, r = item.split(":", 1)
            out[cls.strip()] = float(r)
        else:
            out[item] = _rate_var.value
    return out


def enabled() -> bool:
    return bool(plan())


class _Scoped:
    """Shared per-scope bookkeeping: deterministic rng, warm-up skip,
    total-injection cap."""

    def __init__(self, scope: str, rank: int,
                 classes: Dict[str, float]) -> None:
        self.scope = scope
        self.classes = classes
        self._rng = random.Random(f"{_seed_var.value}:{scope}:{rank}")
        self._count = 0
        self._injected = 0

    def _roll(self) -> Optional[str]:
        self._count += 1
        if self._count <= max(0, _skip_var.value):
            return None
        cap = _max_var.value
        if cap > 0 and self._injected >= cap:
            return None
        for cls, rate in self.classes.items():
            if self._rng.random() < rate:
                self._injected += 1
                # annotate the span timeline: a fault firing explains
                # the latency spike around it (trace is a leaf module;
                # import here keeps injection import-light when off)
                from ompi_tpu import obs as _obs
                from ompi_tpu import trace
                tr = trace.current_tracer()
                if tr is not None:
                    tr.instant("ft_inject", "fault", cls=cls,
                               scope=self.scope)
                _obs.record_event(_obs.EV_FT_INJECT,
                                  _obs.intern(cls),
                                  _obs.intern(str(self.scope)))
                return cls
        return None


class BtlInjector(_Scoped):
    @property
    def delay_s(self) -> float:
        return max(0, _delay_ms_var.value) / 1000.0

    def pick(self, rail: int, peer: int) -> Optional[str]:
        """One frame is about to be sent; return a fault class to
        apply to it, or None to let it through clean."""
        return self._roll()


def btl_injector(rank: int) -> Optional[BtlInjector]:
    p = {c: r for c, r in plan().items() if c in BTL_CLASSES}
    if not p:
        return None
    return BtlInjector("btl", rank, p)


class KvInjector(_Scoped):
    def sever(self) -> bool:
        """About to issue a KV op: True = partition first (close the
        socket under the client's feet)."""
        return self._roll() == "kv_partition"


def kv_injector(rank: int) -> Optional[KvInjector]:
    p = {c: r for c, r in plan().items() if c == "kv_partition"}
    if not p:
        return None
    return KvInjector("kv", rank, p)


class CollInjector(_Scoped):
    """Straggler simulation at the device-collective rendezvous: a
    'delay' roll holds the depositing rank-thread before it arrives,
    so fused batches are exercised with arbitrary arrival orders."""

    def maybe_delay(self) -> float:
        """Returns seconds to sleep before depositing (0 = clean)."""
        if self._roll() == "delay":
            return max(0, _delay_ms_var.value) / 1000.0
        return 0.0


def coll_injector(rank: int) -> Optional[CollInjector]:
    p = {c: r for c, r in plan().items() if c == "delay"}
    if not p:
        return None
    return CollInjector("coll", rank, p)


class RdvSeverInjector:
    """One-shot deterministic rendezvous sever: ``should_sever()``
    counts the victim rank's meets and returns True exactly once, at
    the armed count — no RNG, so the wedge replays bit-for-bit (the
    KillInjector model, applied to a rank instead of a process).  The
    caller then holds the rank BEFORE it deposits, in small
    abort-checked sleeps, until the session is poisoned — peers wedge
    at the rendezvous and the hang doctor gets a live crime scene."""

    def __init__(self, rank: int, after_ops: float) -> None:
        self.rank = rank
        # a rate below 1 (including the bare-class default) means "no
        # explicit count": arm a post-bring-up default
        self.after_ops = int(after_ops) if after_ops >= 1 else 8
        self._count = 0
        self._fired = False

    def should_sever(self) -> bool:
        if self._fired:
            return False
        self._count += 1
        if self._count < self.after_ops:
            return False
        self._fired = True
        from ompi_tpu import obs as _obs
        from ompi_tpu import trace
        tr = trace.current_tracer()
        if tr is not None:
            tr.instant("ft_inject", "fault", cls="rdv_sever",
                       scope="coll", rank=self.rank)
        _obs.record_event(_obs.EV_FT_INJECT,
                          _obs.intern("rdv_sever"),
                          _obs.intern("coll"), rank=self.rank)
        return True


def rdv_sever_injector(rank: int,
                       size: Optional[int] = None
                       ) -> Optional[RdvSeverInjector]:
    p = plan()
    if "rdv_sever" not in p or rank not in victim_ranks(size):
        return None
    return RdvSeverInjector(rank, p["rdv_sever"])


# silent data corruption (DESIGN.md §25): the victim rank's chip
# bit-flips its own collective operand AFTER the integrity gate
# digests it — no error, no slowdown, no heartbeat change; only the
# integrity plane's sampled cross-check can see it
SDC_CLASSES = ("device_sdc",)


class SdcInjector:
    """Deterministic operand bit-flip at the device-collective meet:
    fires at the armed op count (the RdvSeverInjector model — no RNG,
    replays bit-for-bit) and then, when ft_inject_sdc_period > 0,
    every period-th collective after that, so one chaos run carries
    many independent flips for detection-RATE measurement."""

    def __init__(self, rank: int, after_ops: float, period: int = 0) -> None:
        self.rank = rank
        # a rate below 1 (the bare-class default) means "no explicit
        # count": arm a post-bring-up default
        self.after_ops = int(after_ops) if after_ops >= 1 else 8
        self.period = max(0, int(period))
        self._count = 0
        self.flips = 0
        self.last_flip_ns = 0

    def should_flip(self) -> bool:
        self._count += 1
        n = self._count
        if n < self.after_ops:
            return False
        if n > self.after_ops:
            if self.period <= 0 or (n - self.after_ops) % self.period:
                return False
        self.flips += 1
        import time as _time
        self.last_flip_ns = _time.monotonic_ns()
        from ompi_tpu import obs as _obs
        from ompi_tpu import trace
        tr = trace.current_tracer()
        if tr is not None:
            tr.instant("ft_inject", "fault", cls="device_sdc",
                       scope="coll", rank=self.rank)
        _obs.record_event(_obs.EV_FT_INJECT,
                          _obs.intern("device_sdc"),
                          _obs.intern("coll"), rank=self.rank)
        return True


def sdc_injector(rank: int,
                 size: Optional[int] = None) -> Optional[SdcInjector]:
    p = plan()
    if "device_sdc" not in p or rank not in victim_ranks(size):
        return None
    return SdcInjector(rank, p["device_sdc"], _sdc_period_var.value)


class RmaInjector(_Scoped):
    """AM-handler delay for one-sided windows: a 'rma_delay' roll
    holds the target's apply loop, so passive-target lock waits and
    pt2pt op application see slow targets (the osc analog of the
    coll rendezvous straggler)."""

    def maybe_delay(self) -> float:
        """Returns seconds the AM apply sleeps (0 = clean)."""
        if self._roll() == "rma_delay":
            return max(0, _delay_ms_var.value) / 1000.0
        return 0.0


def rma_injector(rank: int) -> Optional[RmaInjector]:
    p = {c: r for c, r in plan().items() if c == "rma_delay"}
    if not p:
        return None
    return RmaInjector("rma", rank, p)


class IoInjector(_Scoped):
    """Faults at the checkpoint shard-write choke point (cr/ckpt).
    Deliberately NOT wired into io.file itself: a raise inside an
    fcoll aggregator would strand peer ranks in the collective's
    barrier, whereas the ckpt layer agrees on errors before anything
    collective happens."""

    @property
    def delay_s(self) -> float:
        return max(0, _delay_ms_var.value) / 1000.0

    def pick(self) -> Optional[str]:
        """One shard is about to be written; return a fault class to
        apply, or None to write it clean."""
        return self._roll()


def io_injector(rank: int) -> Optional[IoInjector]:
    p = {c: r for c, r in plan().items() if c in IO_CLASSES}
    if not p:
        return None
    return IoInjector("io", rank, p)


class DvmInjector(_Scoped):
    def disconnect(self) -> bool:
        """A DVM run request was just sent: True = drop the pool
        connection now, leaving the job executing with no client."""
        return self._roll() == "dvm_disconnect"


def dvm_injector(rank: int = 0) -> Optional[DvmInjector]:
    p = {c: r for c, r in plan().items() if c in DVM_CLASSES}
    if not p:
        return None
    return DvmInjector("dvm", rank, p)


class KillInjector:
    """One-shot deterministic control-plane death: ``op()`` counts the
    victim's served ops and returns True exactly once, when the armed
    count is reached.  No RNG — death at op N replays bit-for-bit."""

    def __init__(self, scope: str, after_ops: float) -> None:
        self.scope = scope
        # plan rates below 1 (including the 0.02 default applied to a
        # bare class name) mean "no explicit count": arm a mid-run
        # default instead of dying on the first op
        self.after_ops = int(after_ops) if after_ops >= 1 else 64
        self._count = 0
        self._fired = False

    def op(self) -> bool:
        if self._fired:
            return False
        self._count += 1
        if self._count < self.after_ops:
            return False
        self._fired = True
        from ompi_tpu import obs as _obs
        from ompi_tpu import trace
        tr = trace.current_tracer()
        if tr is not None:
            tr.instant("ft_inject", "fault", cls=self.scope + "_kill",
                       scope=self.scope)
        _obs.record_event(_obs.EV_FT_INJECT,
                          _obs.intern(self.scope + "_kill"),
                          _obs.intern(self.scope))
        return True


def kv_kill_injector() -> Optional[KillInjector]:
    p = plan()
    if "kv_kill" not in p:
        return None
    return KillInjector("kv", p["kv_kill"])


def dvm_kill_injector() -> Optional[KillInjector]:
    p = plan()
    if "dvm_kill" not in p:
        return None
    return KillInjector("dvm", p["dvm_kill"])


def host_kill_injector() -> Optional[KillInjector]:
    p = plan()
    if "host_kill" not in p:
        return None
    return KillInjector("host", p["host_kill"])


def host_kill_victim() -> int:
    """Host id the host_kill scenario severs."""
    return _victim_host_var.value


def node_faults(node_id: int) -> List[str]:
    """Node-level scenario classes armed on THIS node (the daemon
    consults this once at startup and arms timers)."""
    if node_id != _victim_var.value:
        return []
    p = plan()
    return [c for c in NODE_CLASSES if c in p]


def victim_ranks(size: Optional[int] = None) -> List[int]:
    """Parse ft_inject_victim_rank into the concrete victim list.

    Accepts a single rank, a comma list, or ``random`` (one victim,
    chosen seed-deterministically so a chaos run replays from its
    seed).  ``random`` needs the world size — pass it, or export
    TPUMPI_SIZE; without either the random pick degrades to rank 1.
    """
    s = str(_victim_rank_var.value).strip()
    if not s:
        return []
    if s.lower() == "random":
        if size is None:
            import os
            size = int(os.environ.get("TPUMPI_SIZE", "0")) or None
        if not size:
            return [1]
        rng = random.Random(f"{_seed_var.value}:victim_rank")
        return [rng.randrange(size)]
    out: List[int] = []
    for item in s.split(","):
        item = item.strip()
        if item:
            out.append(int(item))
    return out


def rank_faults(rank: int, size: Optional[int] = None) -> List[str]:
    """Permanent rank-level scenario classes armed on THIS global
    rank (mpi_init consults this once and arms a one-shot timer;
    tpud consults it to kill the victim's child process for real)."""
    if rank not in victim_ranks(size):
        return []
    p = plan()
    return [c for c in RANK_CLASSES if c in p]


def rank_kill_victim() -> int:
    """First armed victim (compat shim for single-victim callers)."""
    v = victim_ranks()
    return v[0] if v else -1


class HostSlowInjector:
    """Deterministic gray-failure slowdown for one host's residents.
    No RNG, no op counting: the victim is simply SLOW, everywhere,
    from the first op — ``delay_s()`` is the stall a resident rank
    adds at every device-collective deposit, ``beat_interval_s(iv)``
    is the inflated heartbeat pacing of the host agent.  Both derive
    from delay_ms and host_slow_factor, so a 10x-slow chaos run
    replays bit-for-bit with zero seeds involved."""

    def __init__(self, host: int) -> None:
        self.host = host
        self._announced = False

    @property
    def factor(self) -> int:
        return max(2, _slow_factor_var.value)

    def delay_s(self) -> float:
        """Per-deposit stall of a resident rank: delay_ms scaled so
        the victim runs ~factor times slower than a clean rank whose
        per-op cost is about one delay_ms."""
        self._announce()
        return max(0, _delay_ms_var.value) * (self.factor - 1) / 1000.0

    def beat_interval_s(self, iv: float, grace: float = 0.0) -> float:
        """The host agent's inflated beat pacing: alive, never silent
        — the beat EWMA drifts up instead of the grace tripping.
        Capped at 3/4 of the liveness grace when the caller knows it:
        a gray host delays its heartbeats, it does not stop them —
        uncapped inflation (factor*iv > grace) would be host_kill in
        disguise and fire the WRONG plane."""
        self._announce()
        slow = iv * self.factor
        if grace > 0:
            cap = grace * 0.75
            if slow > cap:
                slow = max(iv, cap)
        return slow

    def _announce(self) -> None:
        if self._announced:
            return
        self._announced = True
        from ompi_tpu import obs as _obs
        _obs.record_event(_obs.EV_FT_INJECT,
                          _obs.intern("host_slow"),
                          _obs.intern("host"))


def host_slow_injector(host: int) -> Optional[HostSlowInjector]:
    """Armed only on ft_inject_victim_host's residents (rank-threads
    consult with their node_id, the tpud agent with its host id)."""
    if "host_slow" not in plan() or host != _victim_host_var.value:
        return None
    return HostSlowInjector(host)


class NetJitterInjector(_Scoped):
    """Seeded latency/loss shaping on the network client paths (btl
    tcp frames, KV ops).  A 'net_jitter' roll sleeps a uniform draw
    in [0, 2*net_jitter_ms]; on the tcp path it may also drop the
    frame with net_jitter_loss probability (the reliable sublayer
    retransmits — KV callers never see a drop, only added RTT, which
    is exactly what the health plane's kv_rtt signal scores)."""

    def maybe_delay_s(self) -> float:
        """Returns seconds to hold the op/frame (0 = clean)."""
        if self._roll() == "net_jitter":
            ms = max(0, _jitter_ms_var.value)
            return self._rng.uniform(0.0, 2.0 * ms) / 1000.0
        return 0.0

    def should_drop(self) -> bool:
        """tcp frames only: a seeded loss decision taken AFTER a
        jitter hit (callers pair it with maybe_delay_s)."""
        loss = _jitter_loss_var.value
        return loss > 0 and self._rng.random() < loss


def net_jitter_injector(rank: int,
                        scope: str = "net") -> Optional[NetJitterInjector]:
    p = {c: r for c, r in plan().items() if c in NET_CLASSES}
    if not p:
        return None
    return NetJitterInjector(scope, rank, p)


def after_s() -> float:
    return max(0.0, _after_var.value)
