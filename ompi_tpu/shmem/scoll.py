"""scoll: the SHMEM collectives framework.

Re-design of oshmem/mca/scoll (ref: oshmem/mca/scoll/basic — PE
collectives as their own component family; scoll/mpi delegates to
the MPI coll stack).  Here the `mpi` component is the default and
the point: the per-communicator coll stack already holds the best
available path for this topology (coll/sm object rendezvous for
thread ranks, coll/seg shared segments for same-node processes,
coll/tpu on devices, tuned p2p otherwise), so SHMEM collectives
inherit every one of those wins by riding ``comm.coll`` — the
scoll-over-coll reuse the architecture promises."""

from __future__ import annotations

import numpy as np

from ompi_tpu.mca.base import Component, frameworks
from ompi_tpu.op.op import BAND, BOR, BXOR, MAX, MIN, PROD, SUM

scoll_framework = frameworks.create("shmem", "scoll")


class MpiScollModule:
    """PE collectives delegated to the context comm's merged coll
    vtable (scoll/mpi analog).  Symmetric blocks are staged through
    the ctx accessors rather than touched as live views: a device
    heap has no writable host alias, so results land via
    ``ctx._write_sym`` (a self-put on the window) and sources come
    from ``ctx._read_sym`` (a heap view or a jitted local slice)."""

    name = "mpi"

    def __init__(self, ctx) -> None:
        self.ctx = ctx

    def barrier_all(self) -> None:
        self.ctx.comm.Barrier()

    def broadcast(self, dest, src, root: int) -> None:
        comm = self.ctx.comm
        buf = np.array(self.ctx._read_sym(src)) if comm.rank == root \
            else np.empty(src.shape, dtype=src.dtype)
        comm.Bcast(buf, root=root)
        self.ctx._write_sym(dest, buf)

    def collect(self, dest, src) -> None:
        """fcollect: concatenation of every PE's src block."""
        out = np.empty(dest.shape, dtype=dest.dtype).reshape(-1)
        self.ctx.comm.Allgather(
            np.ascontiguousarray(self.ctx._read_sym(src).reshape(-1)),
            out)
        self.ctx._write_sym(dest, out)

    def alltoall(self, dest, src) -> None:
        out = np.empty(dest.shape, dtype=dest.dtype).reshape(-1)
        self.ctx.comm.Alltoall(
            np.ascontiguousarray(self.ctx._read_sym(src).reshape(-1)),
            out)
        self.ctx._write_sym(dest, out)

    def to_all(self, dest, src, op) -> None:
        out = np.empty(dest.shape, dtype=dest.dtype).reshape(-1)
        self.ctx.comm.Allreduce(
            np.ascontiguousarray(self.ctx._read_sym(src).reshape(-1)),
            out, op)
        self.ctx._write_sym(dest, out)


class MpiScollComponent(Component):
    name = "mpi"
    priority = 50

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, None)
        return (self.priority, MpiScollModule(ctx))


scoll_framework.add_component(MpiScollComponent())

OPS = {"sum": SUM, "max": MAX, "min": MIN, "prod": PROD,
       "and": BAND, "or": BOR, "xor": BXOR}


def select(ctx) -> MpiScollModule:
    best = None
    for comp in scoll_framework.components():
        got = comp.query(ctx)
        if got and got[1] is not None and (
                best is None or got[0] > best[0]):
            best = got
    if best is None:
        raise RuntimeError("no scoll component available")
    return best[1]
