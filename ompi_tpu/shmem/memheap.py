"""memheap: the symmetric-heap allocator framework.

Re-design of oshmem/mca/memheap (ref: memheap_buddy.c — power-of-two
buddy allocator over the symmetric segment; memheap_ptmalloc as the
general-purpose alternative).  Components register with the MCA
framework and are selected per context by ``shmem_memheap_allocator``;
both are DETERMINISTIC: shmem_malloc is collective and symmetry
requires every PE to compute the same offset from the same call
sequence.

State is capturable (checkpoint/restart snapshots the allocator
alongside the heap bytes).

The allocator is storage-agnostic: it deals in OFFSETS only, so the
same component serves a host heap (numpy segment behind the pt2pt
window) and a device heap (HBM shard behind osc/device, where the
offsets feed ``Window.put/get`` displacements and ``read_local``
slices instead of pointer math)."""

from __future__ import annotations

from typing import Dict, List, Tuple

from ompi_tpu.mca.base import Component, frameworks
from ompi_tpu.mca.params import registry

memheap_framework = frameworks.create("shmem", "memheap")

_alloc_var = registry.register(
    "shmem", "memheap", "allocator", "buddy", str,
    help="Symmetric-heap allocator component: 'buddy' (power-of-two "
         "blocks, O(log n) malloc/free, bounded fragmentation — the "
         "memheap/buddy analog) or 'firstfit' (hole list, tight "
         "packing for long-lived regular allocations)")

_ALIGN = 64
_MIN_ORDER = 6  # 64-byte blocks


class Allocator:
    """Deterministic offset allocator over ``size`` heap bytes."""

    name = "base"

    def __init__(self, size: int) -> None:
        self.size = size

    def malloc(self, nbytes: int) -> int:
        raise NotImplementedError

    def free(self, offset: int) -> None:
        raise NotImplementedError

    def state(self) -> tuple:
        raise NotImplementedError

    def restore(self, state: tuple) -> None:
        raise NotImplementedError


class FirstFit(Allocator):
    """Hole-list first fit with coalescing (the ptmalloc-role
    component: tight packing, no internal fragmentation beyond
    alignment)."""

    name = "firstfit"

    def __init__(self, size: int) -> None:
        super().__init__(size)
        self._holes: List[Tuple[int, int]] = [(0, size)]
        self._live: Dict[int, int] = {}

    def malloc(self, nbytes: int) -> int:
        # zero-size allocations still get a distinct slot, else they
        # alias the next malloc and free() releases live memory
        want = max((nbytes + _ALIGN - 1) // _ALIGN * _ALIGN, _ALIGN)
        for i, (off, size) in enumerate(self._holes):
            if size >= want:
                self._holes[i] = (off + want, size - want)
                if self._holes[i][1] == 0:
                    del self._holes[i]
                self._live[off] = want
                return off
        raise MemoryError(nbytes)

    def free(self, offset: int) -> None:
        size = self._live.pop(offset, None)
        if size is None:
            return
        self._holes.append((offset, size))
        self._holes.sort()
        merged: List[Tuple[int, int]] = []
        for off, sz in self._holes:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + sz)
            else:
                merged.append((off, sz))
        self._holes = merged

    def state(self) -> tuple:
        return ("firstfit", list(self._holes), dict(self._live))

    def restore(self, state: tuple) -> None:
        _, holes, live = state
        self._holes = [tuple(h) for h in holes]
        self._live = {int(k): int(v) for k, v in live.items()}


class Buddy(Allocator):
    """Power-of-two buddy system (ref: memheap_buddy.c): the heap is
    covered by maximal power-of-two top blocks; malloc splits the
    smallest free block of sufficient order down to the fit, free
    coalesces with the buddy (offset XOR size) as far as it goes.
    Free blocks per order are kept sorted and the LOWEST offset wins,
    so the allocation pattern is identical on every PE."""

    name = "buddy"

    def __init__(self, size: int) -> None:
        super().__init__(size)
        self._free: Dict[int, List[int]] = {}   # order -> sorted offsets
        self._live: Dict[int, int] = {}         # offset -> order
        self._tops: set = set()                 # (offset, order) roots
        off = 0
        while size - off >= (1 << _MIN_ORDER):
            order = (size - off).bit_length() - 1
            # a top block must be naturally aligned for buddy math
            while off & ((1 << order) - 1):
                order -= 1
            self._free.setdefault(order, []).append(off)
            self._tops.add((off, order))
            off += 1 << order

    def malloc(self, nbytes: int) -> int:
        want = max(nbytes, 1)
        order = max(_MIN_ORDER, (want - 1).bit_length())
        o = order
        while o not in self._free or not self._free[o]:
            o += 1
            if o > 64:
                raise MemoryError(nbytes)
        off = self._free[o].pop(0)
        while o > order:   # split down, keep the low half
            o -= 1
            lst = self._free.setdefault(o, [])
            lst.append(off + (1 << o))
            lst.sort()
        self._live[off] = order
        return off

    def free(self, offset: int) -> None:
        order = self._live.pop(offset, None)
        if order is None:
            return
        while (offset, order) not in self._tops:
            buddy = offset ^ (1 << order)
            lst = self._free.get(order, [])
            if buddy in lst:
                lst.remove(buddy)
                offset = min(offset, buddy)
                order += 1
            else:
                break
        lst = self._free.setdefault(order, [])
        lst.append(offset)
        lst.sort()

    def state(self) -> tuple:
        return ("buddy",
                {k: list(v) for k, v in self._free.items()},
                dict(self._live))

    def restore(self, state: tuple) -> None:
        _, free, live = state
        self._free = {int(k): sorted(v) for k, v in free.items()}
        self._live = {int(k): int(v) for k, v in live.items()}


class _MemheapComponent(Component):
    def __init__(self, name: str, cls, priority: int) -> None:
        super().__init__()
        self.name = name
        self._cls = cls
        self.priority = priority

    def query(self, size=None):
        return (self.priority, self._cls)


memheap_framework.add_component(_MemheapComponent("buddy", Buddy, 50))
memheap_framework.add_component(
    _MemheapComponent("firstfit", FirstFit, 40))


def select(size: int) -> Allocator:
    """The MCA-selected allocator for a ``size``-byte heap."""
    name = _alloc_var.value
    for comp in memheap_framework.components():
        if comp.name == name:
            return comp.query()[1](size)
    raise ValueError(
        f"unknown shmem_memheap_allocator {name!r} "
        "(components: buddy, firstfit)")


def restore(state: tuple, size: int) -> Allocator:
    """Rebuild the allocator a snapshot carried (its own component,
    regardless of the current MCA selection)."""
    cls = {"firstfit": FirstFit, "buddy": Buddy}[state[0]]
    alloc = cls(size)
    alloc.restore(state)
    return alloc
