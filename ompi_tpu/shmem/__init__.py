"""OpenSHMEM-analog PGAS layer.

Re-design of the oshmem project (ref: oshmem/runtime/
oshmem_shmem_init.c:142,233,272-328 — init opens spml → scoll →
sshmem → memheap; §2.7): a symmetric heap + one-sided put/get/
atomics + PE collectives.  The tpu-native collapse: the **spml data
plane is the osc window machinery** (active messages over the pml,
every transport the btl framework has), the **sshmem backing segment
is the window's memory**, scoll reuses the per-communicator coll
stack, and remote atomics are window fetch-ops (applied serially in
the target's progress loop — the atomic/basic contract).

The backing window comes from real osc component selection: on a
mesh-capable comm ``osc.allocate`` mints a device-committed shard, so
the symmetric heap lives in HBM and puts/gets/atomics lower to the
device component's one-sided kernels (``ctx.device`` is True, the
heap has no host alias, and ``SymArray.local`` is a read-only
snapshot); otherwise it is the host AM window over a numpy heap.

Symmetry: every PE performs the same allocation sequence
(shmem_malloc is collective in OpenSHMEM), so a deterministic
first-fit allocator yields identical offsets everywhere — a remote
address is (pe, my_offset), exactly the memheap model
(ref: oshmem/mca/memheap).

    from ompi_tpu import shmem
    shmem.init()
    x = shmem.malloc(8, np.int64)
    shmem.put(x, np.arange(8), pe=(shmem.my_pe() + 1) % shmem.n_pes())
    shmem.barrier_all()
    print(x.local)
    shmem.finalize()
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ompi_tpu.mca.params import registry
from ompi_tpu.op.op import (BAND, BOR, BXOR, MAX, MIN, PROD, SUM)
from ompi_tpu.shmem import memheap as memheap_mod
from ompi_tpu.shmem import scoll as scoll_mod

_heap_var = registry.register(
    "shmem", "memheap", "size", 1 << 22, int,
    help="Symmetric heap size in bytes (memheap analog)")


class SymArray:
    """A symmetric allocation: same offset on every PE."""

    __slots__ = ("ctx", "offset", "shape", "dtype")

    def __init__(self, ctx: "ShmemCtx", offset: int, shape, dtype) -> None:
        self.ctx = ctx
        self.offset = offset
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape)) * self.dtype.itemsize

    @property
    def local(self) -> np.ndarray:
        """My PE's backing memory: a writable view into the host heap,
        or a read-only snapshot of the device shard (a device heap has
        no live host alias — stores go through put/atomics)."""
        return self.ctx._read_sym(self)

    def _disp(self, index: int = 0) -> int:
        return self.offset + index * self.dtype.itemsize


class ShmemCtx:
    """One PE's shmem state (the oshmem_group_all-rooted world)."""

    def __init__(self, comm=None, heap_size: Optional[int] = None) -> None:
        import ompi_tpu
        from ompi_tpu import osc as oscmod

        self.comm = comm if comm is not None else ompi_tpu.init()
        self.heap_size = heap_size or _heap_var.value
        # sshmem backing segment through real osc selection: a
        # mesh-capable comm mints a device-committed shard, so the
        # symmetric heap LIVES in device memory and every put/get/
        # atomic below lowers to the device component's kernels;
        # otherwise the host AM window over a numpy heap, as before
        self.win = oscmod.allocate(self.comm, self.heap_size,
                                   disp_unit=1, name="shmem-heap")
        self.device = hasattr(self.win, "read_local")
        self.heap = None if self.device else self.win.memory
        self.win.lock_all()  # passive epoch for the life of the ctx
        # MCA-selected components: the memheap allocator (buddy by
        # default, ref oshmem/mca/memheap/buddy) and the scoll module
        # (scoll/mpi: PE collectives ride the comm's coll stack)
        self.memheap = memheap_mod.select(self.heap_size)
        self.scoll = scoll_mod.select(self)
        self._finalized = False
        # shmem_ptr: co-resident thread-rank PEs can address each
        # other's heaps directly — publish mine where peers look
        world = getattr(self.comm.state.rte, "world", None)
        if world is not None and hasattr(world, "shared"):
            with world.shared_lock:
                # keyed by (comm cid, global rank): a second ctx over
                # a sub-communicator must not shadow the world ctx —
                # a peer resolving offsets against the wrong heap
                # would read real-looking garbage
                world.shared[("shmem_ctx", self.comm.cid,
                              self.comm.state.rank)] = self

    # -- memheap allocator (ref: oshmem/mca/memheap) --------------------
    def malloc(self, shape, dtype=np.uint8) -> SymArray:
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        try:
            off = self.memheap.malloc(nbytes)
        except MemoryError:
            raise MemoryError(
                f"symmetric heap exhausted ({nbytes} wanted; raise "
                f"--mca shmem_memheap_size)") from None
        return SymArray(self, off, shape, dtype)

    def free(self, arr: SymArray) -> None:
        self.memheap.free(arr.offset)

    # -- local symmetric-memory access ----------------------------------
    # Host heap: the block is a live writable numpy view.  Device heap:
    # the block is rank-local HBM behind the window — reads are jitted
    # local slices (Window.read_local) and writes are self-puts, so
    # they serialize with remote ops under the same window machinery.
    def _read_sym(self, arr: SymArray) -> np.ndarray:
        if self.heap is not None:
            raw = self.heap[arr.offset: arr.offset + arr.nbytes]
            return raw.view(arr.dtype).reshape(arr.shape)
        raw = self.win.read_local(arr.offset, arr.nbytes)
        out = raw.view(arr.dtype).reshape(arr.shape)
        out.flags.writeable = False
        return out

    def _write_sym(self, arr: SymArray, values) -> None:
        a = np.ascontiguousarray(
            np.asarray(values, dtype=arr.dtype)).reshape(-1)
        self._check_fit(arr, a.nbytes)
        if self.heap is not None:
            self._read_sym(arr).reshape(-1)[: a.size] = a
            return
        self.win.put(a, self.comm.rank, disp=arr.offset)
        self.win.flush_local(self.comm.rank)

    # -- spml data plane (ref: oshmem/mca/spml) -------------------------
    @staticmethod
    def _check_fit(dest: SymArray, nbytes: int, index: int = 0) -> None:
        room = dest.nbytes - index * dest.dtype.itemsize
        if nbytes > room:
            raise ValueError(
                f"put of {nbytes} bytes overruns the {room}-byte "
                f"symmetric allocation (would corrupt the target's "
                f"heap)")

    def put(self, dest: SymArray, value, pe: int) -> None:
        a = np.ascontiguousarray(np.asarray(value, dtype=dest.dtype))
        self._check_fit(dest, a.nbytes)
        self.win.put(a, pe, disp=dest._disp())
        self.win.flush_local(pe)

    def get(self, src: SymArray, pe: int) -> np.ndarray:
        out = np.empty(src.shape, dtype=src.dtype)
        self.win.get(out.reshape(-1), pe, disp=src._disp())
        return out

    def p(self, dest: SymArray, index: int, value, pe: int) -> None:
        """Single-element put (shmem_p)."""
        a = np.array([value], dtype=dest.dtype)
        self._check_fit(dest, a.nbytes, index)
        self.win.put(a, pe, disp=dest._disp(index))
        self.win.flush_local(pe)

    def g(self, src: SymArray, index: int, pe: int):
        """Single-element get (shmem_g)."""
        out = np.empty(1, dtype=src.dtype)
        self.win.get(out, pe, disp=src._disp(index))
        return out[0]

    @staticmethod
    def _check_strides(tst: int, sst: int) -> None:
        # the OpenSHMEM precondition: strides are >= 1.  Zero or
        # negative strides would address BELOW the allocation (and a
        # negative heap index wraps to the END of the numpy slice) —
        # silent corruption of neighboring symmetric allocations.
        if tst < 1 or sst < 1:
            raise ValueError(
                f"shmem_iput/iget strides must be >= 1 "
                f"(got tst={tst}, sst={sst})")

    def iput(self, dest: SymArray, source, tst: int, sst: int,
             nelems: int, pe: int) -> None:
        """Strided put (shmem_iput, ref: oshmem/shmem/c/shmem_iput.c:1):
        element i of the LOCAL ``source`` stream (stride ``sst``)
        lands at remote index i*``tst`` of ``dest``."""
        self._check_strides(tst, sst)
        src = np.asarray(source, dtype=dest.dtype).reshape(-1)
        if nelems:
            self._check_fit(dest, dest.dtype.itemsize,
                            (nelems - 1) * tst)
        for i in range(nelems):
            a = np.array([src[i * sst]], dtype=dest.dtype)
            self.win.put(a, pe, disp=dest._disp(i * tst))
        self.win.flush_local(pe)

    def iget(self, target, src: SymArray, tst: int, sst: int,
             nelems: int, pe: int) -> None:
        """Strided get (shmem_iget): remote index i*``sst`` of ``src``
        lands at index i*``tst`` of the LOCAL ``target`` array.
        Issues every fetch, then waits once (nelems serial RTTs would
        scale wall-clock by latency)."""
        self._check_strides(tst, sst)
        if not (isinstance(target, np.ndarray)
                and target.flags.c_contiguous
                and target.flags.writeable):
            # np.asarray would hand the stores to a silently-dropped
            # COPY for lists / non-contiguous views (same contract as
            # Window.rget)
            raise ValueError(
                "iget target must be a writable contiguous ndarray")
        if nelems:
            self._check_fit(src, src.dtype.itemsize,
                            (nelems - 1) * sst)
        t = target.reshape(-1)
        stage = np.empty((nelems, 1), dtype=src.dtype)
        reqs = [self.win.rget(stage[i], pe, disp=src._disp(i * sst))
                for i in range(nelems)]
        for r in reqs:
            r.wait()
        for i in range(nelems):
            t[i * tst] = stage[i, 0]

    # -- distributed locks (ref: oshmem/shmem/c/shmem_lock.c:37+) -------
    # The lock is ONE symmetric integer cell, interpreted as a ticket
    # lock packed into 64 bits: low 32 = now-serving, high 32 = next
    # ticket.  Acquisition queues FIFO (the fairness the reference's
    # MCS-style server queue provides) through osc fetch ops on the
    # cell's HOME PE (PE 0 — every PE must agree, and the spec makes
    # the lock symmetric so any deterministic home works).

    _LOCK_HOME = 0

    def set_lock(self, lock: SymArray, timeout: float = 120.0) -> None:
        old = self.atomic_fetch_add(lock, 0, np.int64(1) << 32,
                                    self._LOCK_HOME)
        my_ticket = int(old) >> 32
        deadline = time.monotonic() + timeout
        progress = self.comm.state.progress
        spins = 0
        while True:
            cur = int(self.atomic_fetch(lock, 0, self._LOCK_HOME))
            if (cur & 0xFFFFFFFF) == my_ticket:
                return
            spins += 1
            if progress.progress() == 0:
                # back off: the holder needs the core to release
                time.sleep(min(0.002, 50e-6 * spins))
            if time.monotonic() > deadline:
                self._retire_ticket(lock, my_ticket)
                raise TimeoutError(
                    f"shmem_set_lock: ticket {my_ticket} never served "
                    f"(holder dead?)")

    def _retire_ticket(self, lock: SymArray, my_ticket: int) -> None:
        """A timed-out waiter must not leave its ticket in the queue:
        once now-serving reaches it nobody would ever bump past it and
        every later PE wedges forever (ADVICE r5 #3).  Two retirement
        paths: (a) no later ticket was issued — CAS the allocation
        back so our number is never served; (b) our ticket is already
        (or just became) the one being served — pass the grant
        straight to the next waiter, exactly like clear_lock."""
        cur = int(self.atomic_fetch(lock, 0, self._LOCK_HOME))
        if (cur >> 32) == my_ticket + 1 \
                and (cur & 0xFFFFFFFF) <= my_ticket:
            got = int(self.atomic_compare_swap(
                lock, 0, cur, cur - (np.int64(1) << 32),
                self._LOCK_HOME))
            if got == cur:
                return  # allocation rolled back; nobody will serve us
            cur = int(self.atomic_fetch(lock, 0, self._LOCK_HOME))
        if (cur & 0xFFFFFFFF) == my_ticket:
            # we were granted while abandoning: release immediately
            self.atomic_add(lock, 0, 1, self._LOCK_HOME)
            self.win.flush(self._LOCK_HOME)

    def clear_lock(self, lock: SymArray) -> None:
        # quiet FIRST: every put/atomic issued inside the critical
        # section must be remotely complete EVERYWHERE before the
        # next holder can observe the lock free — releasing first
        # would let it read pre-critical-section values on third
        # PEs (the reference quiets before release too)
        self.quiet()
        # increment now-serving: hands the lock to the next ticket
        self.atomic_add(lock, 0, 1, self._LOCK_HOME)
        self.win.flush(self._LOCK_HOME)

    def test_lock(self, lock: SymArray) -> bool:
        """True = lock acquired (the OpenSHMEM return convention is
        0 on success; the Python surface speaks bool).  Acquires only
        when nobody holds or waits — a queued test would block."""
        cur = int(self.atomic_fetch(lock, 0, self._LOCK_HOME))
        if (cur >> 32) != (cur & 0xFFFFFFFF):
            return False  # held or contended
        got = int(self.atomic_compare_swap(
            lock, 0, cur, cur + (np.int64(1) << 32), self._LOCK_HOME))
        return got == cur

    # -- shmem_ptr (ref: oshmem/shmem/c/shmem_ptr.c) --------------------
    def ptr(self, arr: SymArray, pe: int) -> Optional[np.ndarray]:
        """Direct load/store access to PE ``pe``'s symmetric memory,
        or None when the peer's heap is not addressable from here.
        Thread-rank PEs share one address space, so the peer's heap
        view is real; process ranks get None (their heaps are private
        — the reference likewise returns NULL without a mapped
        sm/xpmem segment)."""
        if pe == self.comm.rank:
            return arr.local
        world = getattr(self.comm.state.rte, "world", None)
        if world is None:
            return None
        peer_ctx = getattr(world, "shared", {}).get(
            ("shmem_ctx", self.comm.cid, self.comm.group[pe]))
        if peer_ctx is None or peer_ctx.heap is None:
            # device heaps have no host alias to hand out (the
            # reference likewise returns NULL without a mapped
            # segment); use put/get
            return None
        raw = peer_ctx.heap[arr.offset: arr.offset + arr.nbytes]
        return raw.view(arr.dtype).reshape(arr.shape)

    # -- ordering (ref: oshmem quiet/fence semantics) -------------------
    def quiet(self) -> None:
        """Remote completion of all my puts/atomics everywhere."""
        self.win.flush_all()

    def fence(self) -> None:
        """Ordering between my puts to each PE.  The osc AM rides the
        pml's per-(src,dst) FIFO, so delivery order already matches
        issue order; fence is a no-op kept for API fidelity."""

    def barrier_all(self) -> None:
        self.quiet()
        self.comm.Barrier()

    # -- atomics (ref: oshmem/mca/atomic) -------------------------------
    def atomic_add(self, dest: SymArray, index: int, value, pe: int) -> None:
        a = np.array([value], dtype=dest.dtype)
        self.win.accumulate(a, pe, disp=dest._disp(index), op=SUM)
        self.win.flush_local(pe)

    def atomic_fetch_add(self, dest: SymArray, index: int, value,
                         pe: int):
        old = np.empty(1, dtype=dest.dtype)
        self.win.fetch_and_op(np.array([value], dtype=dest.dtype), old,
                              pe, disp=dest._disp(index), op=SUM)
        return old[0]

    def atomic_inc(self, dest: SymArray, index: int, pe: int) -> None:
        self.atomic_add(dest, index, 1, pe)

    def atomic_fetch_inc(self, dest: SymArray, index: int, pe: int):
        return self.atomic_fetch_add(dest, index, 1, pe)

    def atomic_fetch(self, dest: SymArray, index: int, pe: int):
        return self.g(dest, index, pe)

    def atomic_set(self, dest: SymArray, index: int, value, pe: int) -> None:
        self.p(dest, index, value, pe)
        self.win.flush(pe)  # remote completion at the one target only

    def atomic_swap(self, dest: SymArray, index: int, value, pe: int):
        from ompi_tpu.op.op import REPLACE
        old = np.empty(1, dtype=dest.dtype)
        self.win.fetch_and_op(np.array([value], dtype=dest.dtype), old,
                              pe, disp=dest._disp(index), op=REPLACE)
        return old[0]

    def atomic_compare_swap(self, dest: SymArray, index: int, cond,
                            value, pe: int):
        old = np.empty(1, dtype=dest.dtype)
        self.win.compare_and_swap(
            np.array([cond], dtype=dest.dtype),
            np.array([value], dtype=dest.dtype), old, pe,
            disp=dest._disp(index))
        return old[0]

    # -- wait (ref: shmem_wait_until) -----------------------------------
    def wait_until(self, arr: SymArray, index: int, cmp: str, value,
                   timeout: float = 60.0) -> None:
        ops = {"eq": np.equal, "ne": np.not_equal, "gt": np.greater,
               "ge": np.greater_equal, "lt": np.less,
               "le": np.less_equal}[cmp]
        deadline = time.monotonic() + timeout
        progress = self.comm.state.progress
        while not bool(ops(arr.local.reshape(-1)[index], value)):
            if progress.progress() == 0:
                progress.idle_tick()
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"shmem_wait_until({cmp}, {value}) timed out")

    # -- scoll (oshmem/mca/scoll framework; see shmem/scoll.py) ---------
    def broadcast(self, dest: SymArray, src: SymArray, root: int) -> None:
        self.scoll.broadcast(dest, src, root)

    def collect(self, dest: SymArray, src: SymArray) -> None:
        """fcollect: concatenation of every PE's src block."""
        self.scoll.collect(dest, src)

    def alltoall(self, dest: SymArray, src: SymArray) -> None:
        self.scoll.alltoall(dest, src)

    def _to_all(self, dest: SymArray, src: SymArray, op) -> None:
        self.scoll.to_all(dest, src, op)

    def sum_to_all(self, dest, src):
        self._to_all(dest, src, SUM)

    def max_to_all(self, dest, src):
        self._to_all(dest, src, MAX)

    def min_to_all(self, dest, src):
        self._to_all(dest, src, MIN)

    def prod_to_all(self, dest, src):
        self._to_all(dest, src, PROD)

    def and_to_all(self, dest, src):
        self._to_all(dest, src, BAND)

    def or_to_all(self, dest, src):
        self._to_all(dest, src, BOR)

    def xor_to_all(self, dest, src):
        self._to_all(dest, src, BXOR)

    # -- teardown --------------------------------------------------------
    def finalize(self) -> None:
        if self._finalized:
            return
        self.barrier_all()
        world = getattr(self.comm.state.rte, "world", None)
        if world is not None and hasattr(world, "shared"):
            with world.shared_lock:
                world.shared.pop(
                    ("shmem_ctx", self.comm.cid,
                     self.comm.state.rank), None)
        self.win.unlock_all()
        self.win.free()
        self._finalized = True


# -- module-level API (the flat shmem_* C surface) ---------------------------

_tls = threading.local()


def _ctx() -> ShmemCtx:
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        raise RuntimeError("shmem is not initialized (call shmem.init())")
    return ctx


def init(comm=None, heap_size: Optional[int] = None) -> ShmemCtx:
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None and not ctx._finalized:
        # explicit arguments that conflict with the live ctx must not
        # be silently ignored
        if (comm is not None and comm is not ctx.comm) or \
                (heap_size is not None and heap_size != ctx.heap_size):
            raise RuntimeError(
                "shmem is already initialized with a different "
                "comm/heap_size; finalize() first")
        return ctx
    ctx = ShmemCtx(comm, heap_size)
    _tls.ctx = ctx
    return ctx


def finalize() -> None:
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.finalize()
        _tls.ctx = None


def my_pe() -> int:
    return _ctx().comm.rank


def n_pes() -> int:
    return _ctx().comm.size


def malloc(shape, dtype=np.uint8) -> SymArray:
    return _ctx().malloc(shape, dtype)


def free(arr: SymArray) -> None:
    _ctx().free(arr)


def put(dest, value, pe):
    _ctx().put(dest, value, pe)


def get(src, pe):
    return _ctx().get(src, pe)


def p(dest, index, value, pe):
    _ctx().p(dest, index, value, pe)


def g(src, index, pe):
    return _ctx().g(src, index, pe)


def quiet():
    _ctx().quiet()


def fence():
    _ctx().fence()


def barrier_all():
    _ctx().barrier_all()


def atomic_add(dest, index, value, pe):
    _ctx().atomic_add(dest, index, value, pe)


def atomic_fetch_add(dest, index, value, pe):
    return _ctx().atomic_fetch_add(dest, index, value, pe)


def atomic_inc(dest, index, pe):
    _ctx().atomic_inc(dest, index, pe)


def atomic_fetch_inc(dest, index, pe):
    return _ctx().atomic_fetch_inc(dest, index, pe)


def atomic_fetch(dest, index, pe):
    return _ctx().atomic_fetch(dest, index, pe)


def atomic_set(dest, index, value, pe):
    _ctx().atomic_set(dest, index, value, pe)


def atomic_swap(dest, index, value, pe):
    return _ctx().atomic_swap(dest, index, value, pe)


def atomic_compare_swap(dest, index, cond, value, pe):
    return _ctx().atomic_compare_swap(dest, index, cond, value, pe)


def wait_until(arr, index, cmp, value, timeout: float = 60.0):
    _ctx().wait_until(arr, index, cmp, value, timeout)


def broadcast(dest, src, root):
    _ctx().broadcast(dest, src, root)


def collect(dest, src):
    _ctx().collect(dest, src)


def alltoall(dest, src):
    _ctx().alltoall(dest, src)


def sum_to_all(dest, src):
    _ctx().sum_to_all(dest, src)


def max_to_all(dest, src):
    _ctx().max_to_all(dest, src)


def min_to_all(dest, src):
    _ctx().min_to_all(dest, src)


def prod_to_all(dest, src):
    _ctx().prod_to_all(dest, src)


def and_to_all(dest, src):
    _ctx().and_to_all(dest, src)


def or_to_all(dest, src):
    _ctx().or_to_all(dest, src)


def xor_to_all(dest, src):
    _ctx().xor_to_all(dest, src)


def iput(dest, source, tst, sst, nelems, pe):
    _ctx().iput(dest, source, tst, sst, nelems, pe)


def iget(target, src, tst, sst, nelems, pe):
    _ctx().iget(target, src, tst, sst, nelems, pe)


def set_lock(lock, timeout: float = 120.0):
    _ctx().set_lock(lock, timeout)


def clear_lock(lock):
    _ctx().clear_lock(lock)


def test_lock(lock):
    return _ctx().test_lock(lock)


def ptr(arr, pe):
    return _ctx().ptr(arr, pe)
