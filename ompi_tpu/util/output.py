"""Verbosity-stream logging + keyed user diagnostics.

Re-design of opal_output (ref: opal/util/output.c) and show_help
(ref: opal/util/show_help.c).  Streams carry a per-framework verbosity
level controlled through the variable registry
(``<framework>_base_verbose``); show_help renders keyed, de-duplicated
user-facing diagnostics the way the reference's HNP aggregates them.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, Optional, Set, TextIO

from ompi_tpu.mca.params import registry

_lock = threading.Lock()
_seen_help: Set[str] = set()


class OutputStream:
    def __init__(self, tag: str, verbose_key: Optional[str] = None,
                 file: Optional[TextIO] = None) -> None:
        self.tag = tag
        self.verbose_key = verbose_key or f"{tag}_base_verbose"
        self.file = file or sys.stderr

    @property
    def level(self) -> int:
        return int(registry.get(self.verbose_key, 0) or 0)

    def verbose(self, level: int, msg: str, *args) -> None:
        if self.level >= level:
            self.output(msg, *args)

    def output(self, msg: str, *args) -> None:
        if args:
            msg = msg % args
        rank = os.environ.get("TPUMPI_RANK", "?")
        with _lock:
            self.file.write(f"[{self.tag}:{rank}] {msg}\n")
            self.file.flush()


_streams: Dict[str, OutputStream] = {}


def get_stream(tag: str) -> OutputStream:
    st = _streams.get(tag)
    if st is None:
        st = OutputStream(tag)
        _streams[tag] = st
    return st


def verbose(tag: str, level: int, msg: str, *args) -> None:
    get_stream(tag).verbose(level, msg, *args)


# Keyed help topics: the analog of the reference's help-text ini files
# (opal/util/show_help.c keyed *.txt files).  Kept inline as a dict —
# a TPU-native framework has no install-tree to scan.
_HELP_TOPICS: Dict[str, str] = {
    "no-component": (
        "No usable component was found for framework '%(framework)s'.\n"
        "Check your --mca %(framework)s selection."),
    "abort": (
        "Rank %(rank)s aborted the job (error code %(code)s) in "
        "communicator %(comm)s."),
    "truncate": (
        "A message was truncated: posted receive of %(recv)s bytes, "
        "incoming message of %(send)s bytes."),
    "launch-failed": (
        "Failed to launch process %(rank)s: %(reason)s"),
    "proc-died": (
        "Process %(rank)s (pid %(pid)s) terminated unexpectedly with "
        "status %(status)s; aborting the remaining processes."),
}


def show_help(topic: str, dedup: bool = True, **fields) -> None:
    """Render a keyed diagnostic once (de-duplicated per process)."""
    key = topic + repr(sorted(fields.items()))
    with _lock:
        if dedup and key in _seen_help:
            return
        _seen_help.add(key)
    text = _HELP_TOPICS.get(topic, topic)
    try:
        text = text % fields
    except (KeyError, ValueError):
        pass
    bar = "-" * 70
    sys.stderr.write(f"{bar}\n{text}\n{bar}\n")
    sys.stderr.flush()
