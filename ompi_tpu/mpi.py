"""Flat MPI_* surface: C-binding-shaped names over the object API.

The analog of ompi/mpi/c's 385 one-function files (ref:
ompi/mpi/c/send.c:78, allreduce.c:110 — arg checking + handle
translation + dispatch): each MPI_* function here translates to the
corresponding Communicator/Window method.  Predefined handles
(datatypes, ops, constants) are re-exported under their MPI names so
a reference user can port code token-for-token:

    from ompi_tpu import mpi as MPI
    MPI.MPI_Init()
    rank = MPI.MPI_Comm_rank(MPI.MPI_COMM_WORLD())
    MPI.MPI_Send(buf, 4, MPI.MPI_DOUBLE, 1, 0, comm)

Like PMPI in the reference (ompi/mpi/c/init.c:35-37 weak symbols),
every MPI_* name has a PMPI_* alias created at import time, so
profiling interposers can wrap MPI_* while calling through PMPI_*.
"""

from __future__ import annotations

import sys as _sys
from typing import List, Optional

import ompi_tpu as _top
from ompi_tpu.datatype.engine import (  # noqa: F401
    BYTE as MPI_BYTE, PACKED as MPI_PACKED, CHAR as MPI_CHAR,
    SHORT as MPI_SHORT, INT as MPI_INT, LONG as MPI_LONG,
    LONG_LONG as MPI_LONG_LONG, UNSIGNED as MPI_UNSIGNED,
    UNSIGNED_LONG as MPI_UNSIGNED_LONG, INT8_T as MPI_INT8_T,
    INT16_T as MPI_INT16_T, INT32_T as MPI_INT32_T,
    INT64_T as MPI_INT64_T, UINT8_T as MPI_UINT8_T,
    UINT16_T as MPI_UINT16_T, UINT32_T as MPI_UINT32_T,
    UINT64_T as MPI_UINT64_T, FLOAT as MPI_FLOAT, DOUBLE as MPI_DOUBLE,
    C_BOOL as MPI_C_BOOL, C_FLOAT_COMPLEX as MPI_C_FLOAT_COMPLEX,
    C_DOUBLE_COMPLEX as MPI_C_DOUBLE_COMPLEX, AINT as MPI_AINT,
    OFFSET as MPI_OFFSET, COUNT as MPI_COUNT,
    FLOAT_INT as MPI_FLOAT_INT, DOUBLE_INT as MPI_DOUBLE_INT,
    LONG_INT as MPI_LONG_INT,
    contiguous as MPI_Type_contiguous, vector as MPI_Type_vector,
    indexed as MPI_Type_indexed, struct as MPI_Type_create_struct,
)
from ompi_tpu.op.op import (  # noqa: F401
    MAX as MPI_MAX, MIN as MPI_MIN, SUM as MPI_SUM, PROD as MPI_PROD,
    LAND as MPI_LAND, BAND as MPI_BAND, LOR as MPI_LOR, BOR as MPI_BOR,
    LXOR as MPI_LXOR, BXOR as MPI_BXOR, MAXLOC as MPI_MAXLOC,
    MINLOC as MPI_MINLOC, REPLACE as MPI_REPLACE, NO_OP as MPI_NO_OP,
)
from ompi_tpu.coll.buffers import IN_PLACE as MPI_IN_PLACE  # noqa: F401
from ompi_tpu.pml.request import (  # noqa: F401
    ANY_SOURCE as MPI_ANY_SOURCE, ANY_TAG as MPI_ANY_TAG,
    PROC_NULL as MPI_PROC_NULL, SUCCESS as MPI_SUCCESS,
    Status, wait_all, wait_any, wait_some, test_all,
)
from ompi_tpu.comm.communicator import (  # noqa: F401
    COMM_TYPE_SHARED as MPI_COMM_TYPE_SHARED, UNDEFINED as MPI_UNDEFINED,
    Communicator, Group,
)

MPI_COMM_NULL = None
MPI_STATUS_IGNORE = None


# -- environment ------------------------------------------------------------

def MPI_Init(args=None):
    return _top.init()


def MPI_Finalize():
    _top.finalize()


def MPI_Initialized() -> bool:
    return _top.initialized()


def MPI_Finalized() -> bool:
    return _top.finalized()


def MPI_COMM_WORLD() -> Communicator:
    from ompi_tpu.runtime import state as _st
    return _st.current().comm_world


def MPI_COMM_SELF() -> Communicator:
    from ompi_tpu.runtime import state as _st
    return _st.current().comm_self


def MPI_Abort(comm, errorcode: int = 1):
    comm.abort(errorcode)


def MPI_Wtime() -> float:
    import time
    return time.monotonic()


def MPI_Get_processor_name() -> str:
    import socket
    return socket.gethostname()


# -- communicator management ------------------------------------------------

def MPI_Comm_rank(comm) -> int:
    return comm.rank


def MPI_Comm_size(comm) -> int:
    return comm.size


def MPI_Comm_dup(comm):
    return comm.dup()


def MPI_Comm_split(comm, color, key=0):
    return comm.split(color, key)


def MPI_Comm_split_type(comm, split_type, key=0):
    return comm.split_type(split_type, key)


def MPI_Comm_create(comm, group):
    return comm.create(group)


def MPI_Comm_free(comm):
    comm.free()


def MPI_Comm_group(comm):
    return comm.group_obj()


def MPI_Comm_compare(a, b) -> str:
    if a is b:
        return "ident"
    if a.group == b.group:      # same members, same order
        return "congruent"
    if sorted(a.group) == sorted(b.group):  # same members, reordered
        return "similar"
    return "unequal"


def MPI_Group_size(group) -> int:
    return group.size


def MPI_Group_rank(group) -> int:
    from ompi_tpu.runtime import state as _st
    return group.rank_of(_st.current().rank)


def MPI_Group_incl(group, ranks):
    return group.incl(ranks)


def MPI_Group_excl(group, ranks):
    return group.excl(ranks)


def MPI_Group_union(a, b):
    return a.union(b)


def MPI_Group_intersection(a, b):
    return a.intersection(b)


def MPI_Group_difference(a, b):
    return a.difference(b)


def MPI_Group_translate_ranks(a, ranks, b) -> List[int]:
    return [a.translate(b, r) for r in ranks]


# -- point-to-point ---------------------------------------------------------

def MPI_Send(buf, count, datatype, dest, tag, comm):
    comm.Send((buf, count, datatype), dest, tag)


def MPI_Ssend(buf, count, datatype, dest, tag, comm):
    comm.Ssend((buf, count, datatype), dest, tag)


def MPI_Bsend(buf, count, datatype, dest, tag, comm):
    comm.Bsend((buf, count, datatype), dest, tag)


def MPI_Rsend(buf, count, datatype, dest, tag, comm):
    comm.Rsend((buf, count, datatype), dest, tag)


def MPI_Recv(buf, count, datatype, source, tag, comm) -> Status:
    return comm.Recv((buf, count, datatype), source, tag)


def MPI_Isend(buf, count, datatype, dest, tag, comm):
    return comm.Isend((buf, count, datatype), dest, tag)


def MPI_Issend(buf, count, datatype, dest, tag, comm):
    return comm.Issend((buf, count, datatype), dest, tag)


def MPI_Ibsend(buf, count, datatype, dest, tag, comm):
    return comm.Ibsend((buf, count, datatype), dest, tag)


def MPI_Irsend(buf, count, datatype, dest, tag, comm):
    return comm.Irsend((buf, count, datatype), dest, tag)


def MPI_Irecv(buf, count, datatype, source, tag, comm):
    return comm.Irecv((buf, count, datatype), source, tag)


def MPI_Sendrecv(sbuf, scount, sdt, dest, stag,
                 rbuf, rcount, rdt, source, rtag, comm) -> Status:
    return comm.Sendrecv((sbuf, scount, sdt), dest, stag,
                         (rbuf, rcount, rdt), source, rtag)


def MPI_Probe(source, tag, comm) -> Status:
    return comm.Probe(source, tag)


def MPI_Iprobe(source, tag, comm) -> Optional[Status]:
    return comm.Iprobe(source, tag)


def MPI_Mprobe(source, tag, comm):
    return comm.Mprobe(source, tag)


def MPI_Mrecv(buf, count, datatype, message, comm) -> Status:
    return comm.Mrecv((buf, count, datatype), message)


def MPI_Wait(request, status=None) -> Status:
    return request.wait()


def MPI_Test(request) -> bool:
    return request.test()


def MPI_Waitall(requests, statuses=None) -> List[Status]:
    return wait_all(requests)


def MPI_Waitany(requests) -> int:
    return wait_any(requests)


def MPI_Waitsome(requests) -> List[int]:
    return wait_some(requests)


def MPI_Testall(requests) -> bool:
    return test_all(requests)


def MPI_Cancel(request):
    request.cancel()


def MPI_Get_count(status, datatype) -> int:
    return status.get_count(datatype)


# -- persistent + buffered --------------------------------------------------

def MPI_Send_init(buf, count, datatype, dest, tag, comm):
    return comm.Send_init((buf, count, datatype), dest, tag)


def MPI_Bsend_init(buf, count, datatype, dest, tag, comm):
    return comm.Bsend_init((buf, count, datatype), dest, tag)


def MPI_Ssend_init(buf, count, datatype, dest, tag, comm):
    return comm.Ssend_init((buf, count, datatype), dest, tag)


def MPI_Recv_init(buf, count, datatype, source, tag, comm):
    return comm.Recv_init((buf, count, datatype), source, tag)


def MPI_Start(request):
    request.start()


def MPI_Startall(requests):
    from ompi_tpu.pml.persistent import start_all
    start_all(requests)


def MPI_Request_free(request):
    request.free()


def MPI_Buffer_attach(size_or_buf):
    _top.attach_buffer(size_or_buf)


def MPI_Buffer_detach() -> int:
    return _top.detach_buffer()


# -- collectives ------------------------------------------------------------

def MPI_Barrier(comm):
    comm.Barrier()


def MPI_Bcast(buf, count, datatype, root, comm):
    comm.Bcast((buf, count, datatype), root)


def MPI_Reduce(sbuf, rbuf, count, datatype, op, root, comm):
    comm.Reduce((sbuf, count, datatype),
                None if rbuf is None else (rbuf, count, datatype),
                op, root)


def MPI_Allreduce(sbuf, rbuf, count, datatype, op, comm):
    comm.Allreduce((sbuf, count, datatype), (rbuf, count, datatype), op)


def MPI_Allgather(sbuf, scount, sdt, rbuf, rcount, rdt, comm):
    comm.Allgather((sbuf, scount, sdt), (rbuf, rcount * comm.size, rdt))


def MPI_Allgatherv(sbuf, scount, sdt, rbuf, rcounts, displs, rdt, comm):
    comm.Allgatherv((sbuf, scount, sdt), (rbuf, sum(rcounts), rdt),
                    rcounts, displs)


def MPI_Gather(sbuf, scount, sdt, rbuf, rcount, rdt, root, comm):
    comm.Gather((sbuf, scount, sdt),
                None if comm.rank != root else
                (rbuf, rcount * comm.size, rdt), root)


def MPI_Scatter(sbuf, scount, sdt, rbuf, rcount, rdt, root, comm):
    comm.Scatter(None if comm.rank != root else
                 (sbuf, scount * comm.size, sdt),
                 (rbuf, rcount, rdt), root)


def MPI_Alltoall(sbuf, scount, sdt, rbuf, rcount, rdt, comm):
    comm.Alltoall((sbuf, scount * comm.size, sdt),
                  (rbuf, rcount * comm.size, rdt))


def MPI_Alltoallv(sbuf, scounts, sdispls, sdt, rbuf, rcounts, rdispls,
                  rdt, comm):
    comm.Alltoallv((sbuf, 0, sdt), scounts, sdispls, (rbuf, 0, rdt),
                   rcounts, rdispls)


def MPI_Reduce_scatter(sbuf, rbuf, rcounts, datatype, op, comm):
    comm.Reduce_scatter((sbuf, sum(rcounts), datatype),
                        (rbuf, rcounts[comm.rank], datatype), rcounts, op)


def MPI_Reduce_scatter_block(sbuf, rbuf, rcount, datatype, op, comm):
    comm.Reduce_scatter_block((sbuf, rcount * comm.size, datatype),
                              (rbuf, rcount, datatype), op)


def MPI_Scan(sbuf, rbuf, count, datatype, op, comm):
    comm.Scan((sbuf, count, datatype), (rbuf, count, datatype), op)


def MPI_Exscan(sbuf, rbuf, count, datatype, op, comm):
    comm.Exscan((sbuf, count, datatype), (rbuf, count, datatype), op)


def MPI_Ibarrier(comm):
    return comm.Ibarrier()


def MPI_Ibcast(buf, count, datatype, root, comm):
    return comm.Ibcast((buf, count, datatype), root)


def MPI_Iallreduce(sbuf, rbuf, count, datatype, op, comm):
    return comm.Iallreduce((sbuf, count, datatype),
                           (rbuf, count, datatype), op)


def MPI_Ialltoall(sbuf, scount, sdt, rbuf, rcount, rdt, comm):
    return comm.Ialltoall((sbuf, scount * comm.size, sdt),
                          (rbuf, rcount * comm.size, rdt))


# -- topologies -------------------------------------------------------------

def MPI_Dims_create(nnodes, ndims, dims=None) -> List[int]:
    from ompi_tpu.topo import dims_create
    return dims_create(nnodes, ndims, dims)


def MPI_Cart_create(comm, ndims, dims, periods, reorder=False):
    return comm.Create_cart(dims, periods, reorder)


def MPI_Cart_coords(comm, rank) -> List[int]:
    return comm.Get_coords(rank)


def MPI_Cart_rank(comm, coords) -> int:
    return comm.Get_cart_rank(coords)


def MPI_Cart_shift(comm, direction, disp):
    return comm.Shift(direction, disp)


def MPI_Cart_sub(comm, remain_dims):
    return comm.Sub(remain_dims)


def MPI_Topo_test(comm) -> int:
    return comm.Topo_test()


def MPI_Neighbor_allgather(sbuf, scount, sdt, rbuf, rcount, rdt, comm):
    nin = len(comm.topo.in_neighbors(comm.rank))
    comm.Neighbor_allgather((sbuf, scount, sdt),
                            (rbuf, rcount * nin, rdt))


def MPI_Neighbor_alltoall(sbuf, scount, sdt, rbuf, rcount, rdt, comm):
    nin = len(comm.topo.in_neighbors(comm.rank))
    nout = len(comm.topo.out_neighbors(comm.rank))
    comm.Neighbor_alltoall((sbuf, scount * nout, sdt),
                           (rbuf, rcount * nin, rdt))


# -- one-sided --------------------------------------------------------------

def MPI_Win_create(base, size=None, disp_unit=None, info=None, comm=None):
    from ompi_tpu.osc import window as _w
    return _w.create(comm, base, disp_unit)


def MPI_Win_fence(assert_=0, win=None):
    win.fence()


def MPI_Win_lock(lock_type, rank, assert_=0, win=None):
    win.lock(rank, lock_type)


def MPI_Win_unlock(rank, win=None):
    win.unlock(rank)


def MPI_Put(obuf, ocount, odt, target, tdisp, tcount, tdt, win):
    win.put(obuf, target, tdisp)


def MPI_Get(obuf, ocount, odt, target, tdisp, tcount, tdt, win):
    win.get(obuf, target, tdisp)


def MPI_Accumulate(obuf, ocount, odt, target, tdisp, tcount, tdt, op, win):
    win.accumulate(obuf, target, tdisp, op=op)


# -- MPI-IO -----------------------------------------------------------------

from ompi_tpu.io import (  # noqa: E402,F401
    MODE_APPEND as MPI_MODE_APPEND, MODE_CREATE as MPI_MODE_CREATE,
    MODE_DELETE_ON_CLOSE as MPI_MODE_DELETE_ON_CLOSE,
    MODE_EXCL as MPI_MODE_EXCL, MODE_RDONLY as MPI_MODE_RDONLY,
    MODE_RDWR as MPI_MODE_RDWR, MODE_SEQUENTIAL as MPI_MODE_SEQUENTIAL,
    MODE_UNIQUE_OPEN as MPI_MODE_UNIQUE_OPEN,
    MODE_WRONLY as MPI_MODE_WRONLY,
    SEEK_CUR as MPI_SEEK_CUR, SEEK_END as MPI_SEEK_END,
    SEEK_SET as MPI_SEEK_SET,
)


def MPI_File_open(comm, filename, amode, info=None):
    from ompi_tpu import io as _io
    return _io.open(comm, filename, amode, info)


def MPI_File_close(fh):
    fh.close()


def MPI_File_delete(filename, info=None):
    from ompi_tpu import io as _io
    _io.delete(filename)


def MPI_File_set_view(fh, disp, etype, filetype, datarep="native",
                      info=None):
    fh.set_view(disp, etype, filetype, datarep)


def MPI_File_seek(fh, offset, whence=MPI_SEEK_SET):
    fh.seek(offset, whence)


def MPI_File_get_position(fh) -> int:
    return fh.get_position()


def MPI_File_get_size(fh) -> int:
    return fh.get_size()


def MPI_File_set_size(fh, size):
    fh.set_size(size)


def MPI_File_sync(fh):
    fh.sync()


def MPI_File_read(fh, buf, count, datatype) -> Status:
    return fh.read((buf, count, datatype))


def MPI_File_write(fh, buf, count, datatype) -> Status:
    return fh.write((buf, count, datatype))


def MPI_File_read_at(fh, offset, buf, count, datatype) -> Status:
    return fh.read_at(offset, (buf, count, datatype))


def MPI_File_write_at(fh, offset, buf, count, datatype) -> Status:
    return fh.write_at(offset, (buf, count, datatype))


def MPI_File_read_all(fh, buf, count, datatype) -> Status:
    return fh.read_all((buf, count, datatype))


def MPI_File_write_all(fh, buf, count, datatype) -> Status:
    return fh.write_all((buf, count, datatype))


def MPI_File_read_at_all(fh, offset, buf, count, datatype) -> Status:
    return fh.read_at_all(offset, (buf, count, datatype))


def MPI_File_write_at_all(fh, offset, buf, count, datatype) -> Status:
    return fh.write_at_all(offset, (buf, count, datatype))


def MPI_File_read_shared(fh, buf, count, datatype) -> Status:
    return fh.read_shared((buf, count, datatype))


def MPI_File_write_shared(fh, buf, count, datatype) -> Status:
    return fh.write_shared((buf, count, datatype))


def MPI_File_read_ordered(fh, buf, count, datatype) -> Status:
    return fh.read_ordered((buf, count, datatype))


def MPI_File_write_ordered(fh, buf, count, datatype) -> Status:
    return fh.write_ordered((buf, count, datatype))


def MPI_File_iread(fh, buf, count, datatype):
    return fh.iread((buf, count, datatype))


def MPI_File_iwrite(fh, buf, count, datatype):
    return fh.iwrite((buf, count, datatype))


def MPI_File_iread_at(fh, offset, buf, count, datatype):
    return fh.iread_at(offset, (buf, count, datatype))


def MPI_File_iwrite_at(fh, offset, buf, count, datatype):
    return fh.iwrite_at(offset, (buf, count, datatype))


# -- error handlers (ref: ompi/errhandler, ompi/mpi/c/comm_set_errhandler.c)
from ompi_tpu.errhandler import (  # noqa: E402,F401
    ERRORS_ARE_FATAL as MPI_ERRORS_ARE_FATAL,
    ERRORS_RETURN as MPI_ERRORS_RETURN,
    ERRORS_ABORT as MPI_ERRORS_ABORT,
    Errhandler, MPIException, error_string as _error_string,
    classify as _classify,
)
from ompi_tpu import errhandler as _eh_mod  # noqa: E402

MPI_ERR_LASTCODE = _eh_mod.ERR_LASTCODE
for _k in dir(_eh_mod):
    if _k.startswith("ERR_"):
        globals()["MPI_" + _k] = getattr(_eh_mod, _k)


def MPI_Comm_create_errhandler(fn):
    return Errhandler(fn)


MPI_Win_create_errhandler = MPI_Comm_create_errhandler
MPI_File_create_errhandler = MPI_Comm_create_errhandler


def MPI_Errhandler_free(handler):
    return None


def MPI_Comm_set_errhandler(comm, handler):
    comm.Set_errhandler(handler)


def MPI_Comm_get_errhandler(comm):
    return comm.Get_errhandler()


def MPI_Comm_call_errhandler(comm, errorcode: int):
    comm.Call_errhandler(errorcode)


def MPI_Win_set_errhandler(win, handler):
    win.Set_errhandler(handler)


def MPI_Win_get_errhandler(win):
    return win.Get_errhandler()


def MPI_Win_call_errhandler(win, errorcode: int):
    win.Call_errhandler(errorcode)


def MPI_File_set_errhandler(fh, handler):
    fh.Set_errhandler(handler)


def MPI_File_get_errhandler(fh):
    return fh.Get_errhandler()


def MPI_File_call_errhandler(fh, errorcode: int):
    fh.Call_errhandler(errorcode)


def MPI_Error_class(errorcode: int) -> int:
    return errorcode  # codes ARE classes here (ref: errcode.c identity)


def MPI_Error_string(errorcode: int) -> str:
    return _error_string(errorcode)


# -- attributes (ref: ompi/attribute/attribute.c) ----------------------------
from ompi_tpu import attrs as _attrs_mod  # noqa: E402

MPI_TAG_UB = _attrs_mod.TAG_UB
MPI_WTIME_IS_GLOBAL = _attrs_mod.WTIME_IS_GLOBAL
MPI_UNIVERSE_SIZE = _attrs_mod.UNIVERSE_SIZE
MPI_APPNUM = _attrs_mod.APPNUM
MPI_KEYVAL_INVALID = -1


def MPI_Comm_create_keyval(copy_fn=None, delete_fn=None,
                           extra_state=None) -> int:
    return _attrs_mod.create_keyval(copy_fn, delete_fn, extra_state)


MPI_Win_create_keyval = MPI_Comm_create_keyval
MPI_Type_create_keyval = MPI_Comm_create_keyval


def MPI_Comm_free_keyval(keyval: int):
    _attrs_mod.free_keyval(keyval)


MPI_Win_free_keyval = MPI_Comm_free_keyval
MPI_Type_free_keyval = MPI_Comm_free_keyval


def MPI_Comm_set_attr(comm, keyval: int, value):
    _attrs_mod.set_attr(comm, keyval, value)


def MPI_Comm_get_attr(comm, keyval: int):
    return _attrs_mod.get_attr(comm, keyval)


def MPI_Comm_delete_attr(comm, keyval: int):
    _attrs_mod.delete_attr(comm, keyval)


MPI_Win_set_attr = MPI_Comm_set_attr
MPI_Win_get_attr = MPI_Comm_get_attr
MPI_Win_delete_attr = MPI_Comm_delete_attr
# deprecated MPI-1 names
MPI_Attr_put = MPI_Comm_set_attr
MPI_Attr_get = MPI_Comm_get_attr
MPI_Attr_delete = MPI_Comm_delete_attr
MPI_Keyval_create = MPI_Comm_create_keyval
MPI_Keyval_free = MPI_Comm_free_keyval


# -- info objects (ref: ompi/info/info.c) ------------------------------------
from ompi_tpu.info import Info as _Info, info_env as _info_env  # noqa: E402

MPI_INFO_NULL = None
MPI_MAX_INFO_KEY = 255
MPI_MAX_INFO_VAL = 1024


def MPI_Info_create() -> _Info:
    return _Info()


def MPI_Info_set(info: _Info, key: str, value: str):
    info.set(key, value)


def MPI_Info_get(info: _Info, key: str):
    return info.get(key)


def MPI_Info_delete(info: _Info, key: str):
    info.delete(key)


def MPI_Info_get_nkeys(info: _Info) -> int:
    return info.nkeys()


def MPI_Info_get_nthkey(info: _Info, n: int) -> str:
    return info.nthkey(n)


def MPI_Info_dup(info: _Info) -> _Info:
    return info.dup()


def MPI_Info_free(info: _Info):
    return None


def MPI_Info_env() -> _Info:
    from ompi_tpu.runtime import state as _st
    return _info_env(_st.maybe_current())


def MPI_Comm_set_info(comm, info):
    comm.Set_info(info)


def MPI_Comm_get_info(comm):
    return comm.Get_info()


# -- intercommunicators + dpm (ref: ompi/mpi/c/intercomm_create.c,
# ompi/dpm/dpm.c) -------------------------------------------------------------
from ompi_tpu.comm.intercomm import ROOT as MPI_ROOT  # noqa: E402,F401


def MPI_Intercomm_create(local_comm, local_leader, peer_comm,
                         remote_leader, tag=0):
    return local_comm.create_intercomm(local_leader, peer_comm,
                                       remote_leader, tag)


def MPI_Intercomm_merge(intercomm, high: bool = False):
    return intercomm.merge(high)


def MPI_Comm_test_inter(comm) -> bool:
    return comm.is_inter


def MPI_Comm_remote_size(comm) -> int:
    return comm.remote_size


def MPI_Comm_remote_group(comm):
    return comm.remote_group_obj()


def MPI_Comm_spawn(command, argv, maxprocs, info=None, root=0,
                   comm=None):
    comm = comm if comm is not None else MPI_COMM_WORLD()
    return comm.spawn(command, argv or (), maxprocs, root)


def MPI_Comm_get_parent():
    return _top.get_parent()


def MPI_Open_port(info=None) -> str:
    return _top.open_port()


def MPI_Close_port(port: str):
    return None


def MPI_Comm_accept(port, info=None, root=0, comm=None):
    comm = comm if comm is not None else MPI_COMM_WORLD()
    return comm.accept(port, root)


def MPI_Comm_connect(port, info=None, root=0, comm=None):
    comm = comm if comm is not None else MPI_COMM_WORLD()
    return comm.connect(port, root)


def MPI_Publish_name(service, info, port):
    _top.publish_name(service, port)


def MPI_Lookup_name(service, info=None) -> str:
    return _top.lookup_name(service)


def MPI_Unpublish_name(service, info, port):
    from ompi_tpu.comm.dpm import unpublish_name as _un
    from ompi_tpu.runtime import state as _st
    _un(_st.current(), service)


# -- PMPI aliases (profiling layer, ref: ompi/mpi/c/init.c:35-37) -----------

_mod = _sys.modules[__name__]
for _name in list(vars(_mod)):
    if _name.startswith("MPI_") and callable(getattr(_mod, _name)):
        setattr(_mod, "P" + _name, getattr(_mod, _name))
del _mod, _name
